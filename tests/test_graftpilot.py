"""graftpilot: the unattended drift-triggered retrain daemon (PR 20).

Covers the tentpole and its satellites:

- ``DaemonSpec`` / ``DaemonLedger``: fingerprint binding, byte-prefix
  atomic appends, streak/hysteresis/inflight/incumbent reconstruction.
- The trigger: one decision per poll (``no_drift`` / ``confirming`` /
  ``armed`` / ``suppressed_*`` / ``insufficient_trace`` /
  ``breaker_open`` / ``poll_error``), graded with driftview's own
  ``grade_report`` plus SLO burn, against a stub control plane.
- The live shadow promote gate: arm → collect paired verdicts →
  two-sided sign test → ALWAYS disarm (timeout, drain and chaos paths).
- The breaker's observe-only mode, resumable from the ledger alone.
- driftview ``--json``'s machine verdict line pinned equal to
  ``--check``'s grading (one ``grade_report`` derivation).
- The orchestrator's bounded per-stage transient retries
  (``kind=attempt`` records; exhaustion re-raises the original type).
- Runtime shadow plumbing: ``ShadowScorer`` win/loss/tie pairs,
  ``sum_shadow``, ``ExtenderPolicy.set_shadow`` fresh-scorer swaps.
- ``make daemon-drill`` (``test_daemon_drill_kill_matrix``): the E2E
  acceptance — a 2-worker drift-armed pool under continuous traffic, a
  mid-soak regime flip, a daemon that detects → confirms → retrains →
  shadow-confirms → hot-promotes generation 0→1 with zero failed
  requests, SIGKILLed once in EVERY daemon ledger stage and resuming
  byte-prefix-exact, while the stationary control provably never
  retrains.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import pytest

from rl_scheduler_tpu.loopback.daemon import (
    DAEMON_LEDGER_NAME,
    DAEMON_STATE_NAME,
    DECISION_OUTCOMES,
    ITERATION_STAGES,
    Daemon,
    DaemonDrained,
    DaemonLedger,
    DaemonLedgerMismatch,
    DaemonSpec,
    daemon_spec_from_json,
    serve_status,
)
from rl_scheduler_tpu.loopback.daemon import main as daemon_main
from rl_scheduler_tpu.loopback.orchestrator import (
    TRANSIENT_STAGE_ERRORS,
    LoopLedger,
    LoopRunner,
    LoopSpec,
    fault_plan_from_env,
)

REPO_ROOT = Path(__file__).resolve().parents[1]
FIXTURE_STATS = REPO_ROOT / "tests" / "fixtures" / "driftview" / "stats.json"
BUDGETS = REPO_ROOT / "tools" / "driftview" / "budgets.json"


def _dspec(tmp_path, **kw):
    kw.setdefault("trace_dir", str(tmp_path / "trace"))
    kw.setdefault("incumbent", str(tmp_path / "incumbent"))
    kw.setdefault("pool_url", "http://127.0.0.1:1")
    kw.setdefault("poll_interval_s", 0.05)
    kw.setdefault("poll_retries", 0)
    kw.setdefault("confirm_checks", 1)
    kw.setdefault("min_trace_records", 5)
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("min_spacing_s", 0.0)
    return DaemonSpec(**kw)


# ------------------------------------------------------------ spec


class TestDaemonSpec:
    def test_fingerprint_roundtrips_through_json(self, tmp_path):
        spec = _dspec(tmp_path, confirm_checks=3,
                      verdict_seeds=(1, 2, 3))
        again = daemon_spec_from_json(spec.to_json())
        assert again == spec
        assert again.fingerprint() == spec.fingerprint()
        assert isinstance(again.verdict_seeds, tuple)
        # any protocol knob moves the fingerprint
        other = _dspec(tmp_path, confirm_checks=4,
                       verdict_seeds=(1, 2, 3))
        assert other.fingerprint() != spec.fingerprint()

    def test_validation_refusals(self, tmp_path):
        with pytest.raises(ValueError, match="pool_url"):
            _dspec(tmp_path, pool_url="")
        with pytest.raises(ValueError, match="confirm_checks"):
            _dspec(tmp_path, confirm_checks=0)
        with pytest.raises(ValueError, match="shadow_alpha"):
            _dspec(tmp_path, shadow_alpha=0.0)
        with pytest.raises(ValueError, match="breaker_threshold"):
            _dspec(tmp_path, breaker_threshold=0)
        with pytest.raises(ValueError, match="cooldown_s"):
            _dspec(tmp_path, cooldown_s=-1.0)
        with pytest.raises(ValueError, match="poll_interval_s"):
            _dspec(tmp_path, poll_interval_s=0.0)

    def test_loop_spec_tracks_moving_incumbent(self, tmp_path):
        spec = _dspec(tmp_path, steps=32, mix_frac=0.5)
        loop = spec.loop_spec("promoted-gen-3")
        assert isinstance(loop, LoopSpec)
        assert loop.incumbent == "promoted-gen-3"
        assert loop.trace_dir == spec.trace_dir
        assert loop.steps == 32 and loop.mix_frac == 0.5
        assert loop.dry_run is False


# ---------------------------------------------------------- ledger


class TestDaemonLedger:
    def test_appends_preserve_prior_bytes(self, tmp_path):
        spec = _dspec(tmp_path)
        led = DaemonLedger(tmp_path / "d", spec)
        header = led.path.read_bytes()
        led.append_decision("no_drift", {"drifting": []})
        first = led.path.read_bytes()
        assert first.startswith(header)
        led.append_iteration(0, "armed", "ok", {"loop_dir": "x"})
        second = led.path.read_bytes()
        assert second.startswith(first)
        led.append_decision("armed", {"iter": 0})
        assert led.path.read_bytes().startswith(second)
        assert [r["seq"] for r in led.decisions()] == [1, 2]
        assert led.next_seq() == 3
        assert list(led.iterations()) == [0]
        assert set(led.records()[0]) >= {"kind", "seq", "ts", "outcome"}

    def test_invalid_outcome_and_stage_refused(self, tmp_path):
        led = DaemonLedger(tmp_path / "d", _dspec(tmp_path))
        with pytest.raises(ValueError, match="outcome"):
            led.append_decision("maybe", {})
        with pytest.raises(ValueError, match="stage"):
            led.append_iteration(0, "warmup", "ok", {})
        assert "maybe" not in DECISION_OUTCOMES
        assert "warmup" not in ITERATION_STAGES

    def test_changed_spec_refuses_resume(self, tmp_path):
        DaemonLedger(tmp_path / "d", _dspec(tmp_path))
        with pytest.raises(DaemonLedgerMismatch, match="cannot resume"):
            DaemonLedger(tmp_path / "d",
                         _dspec(tmp_path, confirm_checks=5))

    def test_confirm_streak_counts_trailing_only(self, tmp_path):
        led = DaemonLedger(tmp_path / "d", _dspec(tmp_path))
        assert led.confirm_streak() == 0
        led.append_decision("confirming", {})
        led.append_decision("no_drift", {})
        led.append_decision("confirming", {})
        led.append_decision("confirming", {})
        assert led.confirm_streak() == 2
        led.append_decision("armed", {})
        assert led.confirm_streak() == 0

    def test_inflight_incumbent_hysteresis_failures(self, tmp_path):
        spec = _dspec(tmp_path)
        led = DaemonLedger(tmp_path / "d", spec)
        assert led.inflight_iteration() is None
        assert led.current_incumbent() == spec.incumbent
        assert led.hysteresis() == (0.0, 0.0)
        assert led.trailing_failures() == 0

        led.append_iteration(0, "armed", "ok", {})
        led.append_iteration(0, "retrain", "ok", {"candidate": "cand0"})
        assert led.inflight_iteration() == 0
        led.append_iteration(0, "cooldown", "ok", {
            "outcome": "promoted", "cooldown_until": 100.0,
            "next_allowed_at": 50.0})
        assert led.inflight_iteration() is None
        assert led.current_incumbent() == "cand0"
        assert led.hysteresis() == (100.0, 50.0)

        for i in (1, 2):
            led.append_iteration(i, "armed", "ok", {})
            led.append_iteration(i, "cooldown", "ok", {
                "outcome": "rolled_back", "cooldown_until": 100.0 + i,
                "next_allowed_at": 50.0 + i})
        assert led.trailing_failures() == 2
        # a rolled_back iteration never moves the incumbent
        assert led.current_incumbent() == "cand0"
        # the in-flight iteration has no outcome yet: skipped, not a
        # streak breaker
        led.append_iteration(3, "armed", "ok", {})
        assert led.inflight_iteration() == 3
        assert led.trailing_failures() == 2


# ---------------------------------------------- driftview verdict pin


class TestDriftviewVerdict:
    def test_grade_report_pins_check_drift(self):
        from tools.driftview import (
            build_report,
            check_drift,
            grade_report,
            load_budgets,
            load_stats,
        )

        budgets = load_budgets(str(BUDGETS))
        report = build_report(stats=load_stats(str(FIXTURE_STATS)))
        grade = grade_report(report, budgets)
        # one derivation: --check's violations ARE the grade's
        assert check_drift(report, budgets) == grade["violations"]
        assert grade["ok"] == (not grade["violations"])
        assert grade["exit_code"] == (2 if grade["violations"] else 0)
        assert set(grade["streams"]) == set(report["drift"]["streams"])
        assert [g["gate"] for g in grade["gates"]] == [
            "drift_section", "drifting_streams", "reference_coverage",
            "reference_match", "reference_uniform", "shadow_floor"]
        # a gate that cannot see drift fails loudly, never vacuously
        blind = grade_report({}, budgets)
        assert not blind["ok"]
        assert blind["exit_reason"] == "drift_section"
        assert blind["exit_code"] == 2

    def test_json_verdict_line_equals_check_grading(self, capsys):
        from tools.driftview import (
            build_report,
            grade_report,
            load_budgets,
            load_stats,
        )
        from tools.driftview.__main__ import main as driftview_main

        rc = driftview_main(["--stats", str(FIXTURE_STATS), "--check",
                             "--json", "--budgets", str(BUDGETS)])
        line = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        grade = grade_report(
            build_report(stats=load_stats(str(FIXTURE_STATS))),
            load_budgets(str(BUDGETS)))
        verdict = line["verdict"]
        assert verdict["would_exit"] == rc == grade["exit_code"]
        assert verdict["ok"] == grade["ok"]
        assert verdict["exit_reason"] == grade["exit_reason"]
        assert verdict["streams"] == grade["streams"]
        assert verdict["gates"] == grade["gates"]
        assert line["violations"] == grade["violations"]
        # --json without --check: same verdict, exit stays 0 (the line
        # reports what --check WOULD do; only --check acts on it)
        rc2 = driftview_main(["--stats", str(FIXTURE_STATS), "--json",
                              "--budgets", str(BUDGETS)])
        line2 = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert rc2 == 0
        assert line2["verdict"] == verdict


# ------------------------------------------- orchestrator retries


class TestOrchestratorRetries:
    def _runner(self, tmp_path, name, retries):
        spec = LoopSpec(trace_dir=str(tmp_path / "trace"),
                        incumbent="run", dry_run=True)
        return LoopRunner(spec, tmp_path / name,
                          max_stage_retries=retries)

    def _attempts(self, runner):
        return [json.loads(line)
                for line in runner.ledger.path.read_text().splitlines()[1:]
                if json.loads(line).get("kind") == "attempt"]

    def test_transient_retries_land_attempt_records(self, tmp_path):
        runner = self._runner(tmp_path, "a", retries=2)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError(f"transient {len(calls)}")
            return {"records": 7}

        runner._stage_snapshot = flaky
        done = runner.run_stages(until="snapshot")
        assert len(calls) == 3
        assert done["snapshot"]["status"] == "ok"
        assert done["snapshot"]["out"] == {"records": 7}
        attempts = self._attempts(runner)
        assert [a["attempt"] for a in attempts] == [1, 2]
        assert all(a["stage"] == "snapshot" for a in attempts)
        assert all("transient" in a["error"] for a in attempts)
        # attempt records never mark a stage done
        assert set(runner.ledger.stages()) == {"snapshot"}

    def test_exhaustion_reraises_original_type(self, tmp_path):
        runner = self._runner(tmp_path, "b", retries=1)

        def always():
            raise TimeoutError("still down")

        runner._stage_snapshot = always
        assert isinstance(TimeoutError("x"), TRANSIENT_STAGE_ERRORS)
        with pytest.raises(TimeoutError, match="still down"):
            runner.run_stages(until="snapshot")
        assert len(self._attempts(runner)) == 1
        assert runner.ledger.stages() == {}

    def test_deterministic_errors_never_retry(self, tmp_path):
        runner = self._runner(tmp_path, "c", retries=2)
        calls = []

        def misconfigured():
            calls.append(1)
            raise ValueError("bad spec")

        runner._stage_snapshot = misconfigured
        with pytest.raises(ValueError, match="bad spec"):
            runner.run_stages(until="snapshot")
        assert len(calls) == 1
        assert self._attempts(runner) == []

    def test_zero_budget_is_single_shot(self, tmp_path):
        runner = self._runner(tmp_path, "d", retries=0)
        calls = []

        def failing():
            calls.append(1)
            raise OSError("down")

        runner._stage_snapshot = failing
        with pytest.raises(OSError, match="down"):
            runner.run_stages(until="snapshot")
        assert len(calls) == 1
        assert self._attempts(runner) == []

    def test_bad_until_and_negative_budget_refused(self, tmp_path):
        runner = self._runner(tmp_path, "e", retries=0)
        with pytest.raises(ValueError, match="until"):
            runner.run_stages(until="deploy")
        with pytest.raises(ValueError, match="max_stage_retries"):
            self._runner(tmp_path, "f", retries=-1)

    def test_append_attempt_preserves_prior_bytes(self, tmp_path):
        spec = LoopSpec(trace_dir="/t", incumbent="run", dry_run=True)
        ledger = LoopLedger(tmp_path / "led", spec)
        ledger.append_stage("snapshot", "ok", {"records": 1})
        before = ledger.path.read_bytes()
        ledger.append_attempt("compile", 1, "OSError('x')")
        assert ledger.path.read_bytes().startswith(before)
        assert set(ledger.stages()) == {"snapshot"}


# ------------------------------------------------ shadow plumbing


class TestShadowPlumbing:
    def test_shadow_scorer_win_loss_tie_pairs(self):
        from rl_scheduler_tpu.scheduler.drift import (
            ShadowScorer,
            sum_shadow,
        )

        scorer = ShadowScorer(lambda obs: (0, float(obs)))
        try:
            scorer.submit(0.9, 0, 0.5)  # shadow above → win
            scorer.submit(0.1, 0, 0.5)  # shadow below → loss
            scorer.submit(0.5, 1, 0.5)  # equal → tie (and disagreement)
            deadline = time.monotonic() + 5.0
            while scorer.scored_total < 3 \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            snap = scorer.snapshot()
        finally:
            scorer.close()
        assert snap["scored_total"] == 3
        assert (snap["wins_total"], snap["losses_total"],
                snap["ties_total"]) == (1, 1, 1)
        assert snap["agreements_total"] == 2
        pooled = sum_shadow([snap, snap])
        assert (pooled["wins_total"], pooled["losses_total"],
                pooled["ties_total"]) == (2, 2, 2)
        assert pooled["scored_total"] == 6

    def test_set_shadow_swaps_fresh_scorers(self, tmp_path):
        from rl_scheduler_tpu.scheduler.extender import (
            ExtenderPolicy,
            build_policy,
            build_shadow_scorer,
        )
        from rl_scheduler_tpu.scheduler.policy_backend import (
            GreedyBackend,
        )

        policy = build_policy(backend="greedy")
        try:
            assert policy.shadow is None
            assert policy.set_shadow(None)["shadow"] == "disarmed"
            out = policy.set_shadow(str(tmp_path / "cand"))
            assert out["shadow"] == "armed"
            first = policy.shadow
            assert first is not None and first.scored_total == 0
            first.submit(0.5, 0, 0.5)
            # re-arming swaps a FRESH scorer: the promote gate grades
            # exactly the window it armed, never stale counters
            policy.set_shadow(str(tmp_path / "cand2"))
            assert policy.shadow is not first
            assert policy.shadow.scored_total == 0
            policy.set_shadow(None)
            assert policy.shadow is None
            # the module seam set_shadow rides on
            scorer = build_shadow_scorer(policy, str(tmp_path / "c3"),
                                         backend="greedy")
            scorer.close()
            bare = ExtenderPolicy(GreedyBackend(), policy.telemetry)
            with pytest.raises(ValueError, match="not assembled"):
                bare.set_shadow(str(tmp_path / "cand"))
        finally:
            if policy.shadow is not None:
                policy.shadow.close()


# --------------------------------------------- daemon vs stub pool


def _stub_stats(drifting=False, records=500, generation=0, shadow=None,
                burning=()):
    names = ("cost", "action")
    body = {
        "pool": {"generation": generation, "workers": 2, "alive": 2},
        "drift": {
            "generation": generation,
            "scores": {n: {"status": "ok", "drifting": bool(drifting)}
                       for n in names},
            "streams": {n: {"lifetime": {"count": records}}
                        for n in names},
            "drifting": sorted(names) if drifting else [],
            "reference": {"fingerprint": "f" * 16,
                          "generation": generation},
        },
        "trace": {"records_total": records},
    }
    if shadow is not None:
        body["shadow"] = shadow
    if burning:
        body["slo"] = {"objectives": {n: {"burning": True}
                                      for n in burning}}
    return body


class _StubPool:
    """A /stats + /rollout + /shadow control-plane stand-in whose
    responses come from a mutable ``box`` — the daemon under test sees
    exactly the drift/shadow evidence each case scripts."""

    def __init__(self):
        box = {
            "stats": _stub_stats(),
            "stats_code": 200,
            "rollout": {"generation": 0, "active": False,
                        "promotions_total": 0, "last_error": None},
            "shadow_ack": {"status": "armed", "workers": 2},
            "shadow_posts": [],
        }
        self.box = box

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _send(self, code, body):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/stats":
                    if box["stats_code"] != 200:
                        self._send(box["stats_code"], {"error": "down"})
                    else:
                        self._send(200, box["stats"])
                elif self.path == "/rollout":
                    self._send(200, box["rollout"])
                else:
                    self._send(404, {"error": self.path})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/shadow":
                    box["shadow_posts"].append(payload)
                    if payload.get("path") is None:
                        self._send(200, {"status": "disarmed",
                                         "workers": 2})
                    else:
                        self._send(200, box["shadow_ack"])
                else:
                    self._send(404, {"error": self.path})

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.server.server_address[1]}"

    def close(self):
        self.server.shutdown()


@pytest.fixture()
def stub_pool():
    pool = _StubPool()
    yield pool
    pool.close()


class TestDaemonTrigger:
    def _daemon(self, tmp_path, stub_pool, name="d", faults=None, **kw):
        spec = _dspec(tmp_path, pool_url=stub_pool.url, **kw)
        plan = fault_plan_from_env(faults) if faults else None
        return Daemon(spec, tmp_path / name, fault_plan=plan)

    def test_one_decision_per_poll_in_priority_order(self, tmp_path,
                                                     stub_pool):
        daemon = self._daemon(tmp_path, stub_pool, confirm_checks=2,
                              min_trace_records=50)
        box = stub_pool.box

        box["stats"] = _stub_stats(drifting=False)
        assert daemon._tick_poll() is False
        box["stats"] = _stub_stats(drifting=True, records=10)
        assert daemon._tick_poll() is False
        box["stats"] = _stub_stats(drifting=True, records=500)
        assert daemon._tick_poll() is False  # confirming 1/2
        assert daemon._tick_poll() is True   # armed
        outcomes = [r["outcome"] for r in daemon.ledger.decisions()]
        assert outcomes == ["no_drift", "insufficient_trace",
                            "confirming", "armed"]
        iters = daemon.ledger.iterations()
        assert list(iters) == [0]
        armed = iters[0]["armed"]["out"]
        assert armed["incumbent"] == daemon.spec.incumbent
        assert armed["evidence"]["drifting"] == ["action", "cost"]
        assert Path(armed["loop_dir"]).name == "iter-0000"
        assert daemon.polls_total == 4

    def test_evaluate_trigger_slo_burn_arms_without_drift(
            self, tmp_path, stub_pool):
        daemon = self._daemon(tmp_path, stub_pool)
        stats = _stub_stats(drifting=False, burning=("p99_ms",))
        evidence = daemon.evaluate_trigger(stats)
        assert evidence["drifting"] == []
        assert evidence["burning"] == ["p99_ms"]
        stub_pool.box["stats"] = stats
        assert daemon._tick_poll() is True  # burn alone arms
        assert daemon.ledger.decisions()[-1]["outcome"] == "armed"

    def test_hysteresis_suppresses_cooldown_then_spacing(
            self, tmp_path, stub_pool):
        spec = _dspec(tmp_path, pool_url=stub_pool.url)
        now = time.time()
        led = DaemonLedger(tmp_path / "cool", spec)
        led.append_iteration(0, "armed", "ok", {})
        led.append_iteration(0, "retrain", "ok", {"candidate": "c0"})
        led.append_iteration(0, "cooldown", "ok", {
            "outcome": "promoted", "cooldown_until": now + 60.0,
            "next_allowed_at": now + 60.0})
        daemon = Daemon(spec, tmp_path / "cool")
        stub_pool.box["stats"] = _stub_stats(drifting=True)
        assert daemon._tick_poll() is False
        assert daemon.ledger.decisions()[-1]["outcome"] \
            == "suppressed_cooldown"

        led2 = DaemonLedger(tmp_path / "space", spec)
        led2.append_iteration(0, "armed", "ok", {})
        led2.append_iteration(0, "cooldown", "ok", {
            "outcome": "refused", "cooldown_until": now - 1.0,
            "next_allowed_at": now + 60.0})
        daemon2 = Daemon(spec, tmp_path / "space")
        assert daemon2._tick_poll() is False
        assert daemon2.ledger.decisions()[-1]["outcome"] \
            == "suppressed_spacing"
        # stationary evidence short-circuits before any suppression
        stub_pool.box["stats"] = _stub_stats(drifting=False)
        daemon2._tick_poll()
        assert daemon2.ledger.decisions()[-1]["outcome"] == "no_drift"

    def test_poll_error_after_retry_budget(self, tmp_path, stub_pool):
        daemon = self._daemon(tmp_path, stub_pool,
                              faults="daemon.poll:1,2,3",
                              poll_retries=2)
        stub_pool.box["stats"] = _stub_stats(drifting=True)
        assert daemon._tick_poll() is False
        assert daemon.ledger.decisions()[-1]["outcome"] == "poll_error"
        # the fault budget is spent: the next poll grades normally
        assert daemon._tick_poll() is True
        # HTTP 5xx rides the same transient family
        stub_pool.box["stats_code"] = 500
        daemon2 = self._daemon(tmp_path, stub_pool, name="d2",
                               poll_retries=0)
        daemon2._tick_poll()
        assert daemon2.ledger.decisions()[-1]["outcome"] == "poll_error"

    def test_trigger_fault_is_seen_but_unrecorded(self, tmp_path,
                                                  stub_pool):
        daemon = self._daemon(tmp_path, stub_pool,
                              faults="daemon.trigger:1")
        stub_pool.box["stats"] = _stub_stats(drifting=True)
        with pytest.raises(OSError):
            daemon._tick_poll()
        # nothing recorded in the crash window: no armed decision, no
        # phantom iteration
        assert all(r["outcome"] != "armed"
                   for r in daemon.ledger.decisions())
        assert daemon.ledger.iterations() == {}
        # the resume re-derives the verdict from live evidence and arms
        # exactly once
        assert daemon._tick_poll() is True
        assert list(daemon.ledger.iterations()) == [0]

    def test_breaker_seeds_from_ledger_and_observes_only(
            self, tmp_path, stub_pool):
        spec = _dspec(tmp_path, pool_url=stub_pool.url,
                      breaker_threshold=2, max_polls=3)
        led = DaemonLedger(tmp_path / "brk", spec)
        for i in (0, 1):
            led.append_iteration(i, "armed", "ok", {})
            led.append_iteration(i, "cooldown", "ok", {
                "outcome": "rolled_back", "cooldown_until": 0.0,
                "next_allowed_at": 0.0})
        led.append_iteration(2, "armed", "ok",
                             {"loop_dir": "x", "incumbent": "r",
                              "evidence": {"generation": 0}})
        daemon = Daemon(spec, tmp_path / "brk")
        assert daemon.breaker.snapshot()["state"] == "open"
        assert daemon.iteration_counts["rolled_back"] == 2
        stub_pool.box["stats"] = _stub_stats(drifting=True)
        # observe-only with work in flight: bounded by max_polls, every
        # refused resume lands a breaker_open decision
        summary = daemon.run_forever()
        outcomes = [r["outcome"] for r in daemon.ledger.decisions()]
        assert outcomes == ["breaker_open"] * 3
        assert summary["decisions"]["breaker_open"] == 3
        assert summary["inflight_iteration"] == 2
        assert summary["breaker"]["state"] == "open"
        metrics = daemon.metrics_body()
        assert "graftpilot_breaker_state 2" in metrics
        assert 'graftpilot_decisions_total{outcome="breaker_open"} 3' \
            in metrics
        assert 'graftpilot_iterations_total{outcome="rolled_back"} 2' \
            in metrics


class TestShadowGate:
    def _daemon(self, tmp_path, stub_pool, **kw):
        kw.setdefault("shadow_min_scored", 4)
        kw.setdefault("shadow_alpha", 0.2)
        kw.setdefault("shadow_timeout_s", 5.0)
        spec = _dspec(tmp_path, pool_url=stub_pool.url, **kw)
        return Daemon(spec, tmp_path / "gate")

    def test_confirms_and_always_disarms(self, tmp_path, stub_pool):
        daemon = self._daemon(tmp_path, stub_pool)
        stub_pool.box["stats"] = _stub_stats(shadow={
            "scored_total": 6, "wins_total": 6, "losses_total": 0,
            "ties_total": 0})
        gate = daemon._shadow_gate("cand-run")
        assert gate["confirmed"] is True
        assert gate["verdict"] == "confirmed_above"
        assert gate["wins"] == 6 and gate["losses"] == 0
        assert gate["pvalue"] <= 0.2
        assert stub_pool.box["shadow_posts"] == [
            {"path": "cand-run"}, {"path": None}]

    def test_rejects_without_live_wins(self, tmp_path, stub_pool):
        daemon = self._daemon(tmp_path, stub_pool)
        stub_pool.box["stats"] = _stub_stats(shadow={
            "scored_total": 8, "wins_total": 2, "losses_total": 5,
            "ties_total": 1})
        gate = daemon._shadow_gate("cand-run")
        assert gate["confirmed"] is False
        assert gate["verdict"] == "not_confirmed"
        assert stub_pool.box["shadow_posts"][-1] == {"path": None}

    def test_timeout_is_transient_and_disarms(self, tmp_path,
                                              stub_pool):
        daemon = self._daemon(tmp_path, stub_pool, shadow_timeout_s=0.4)
        stub_pool.box["stats"] = _stub_stats(shadow={
            "scored_total": 1, "wins_total": 1, "losses_total": 0,
            "ties_total": 0})
        with pytest.raises(TimeoutError, match="paired verdicts"):
            daemon._shadow_gate("cand-run")
        assert stub_pool.box["shadow_posts"][-1] == {"path": None}

    def test_drain_unwinds_mid_gate(self, tmp_path, stub_pool):
        daemon = self._daemon(tmp_path, stub_pool)
        stub_pool.box["stats"] = _stub_stats(shadow={"scored_total": 0})
        daemon.request_stop()
        with pytest.raises(DaemonDrained):
            daemon._shadow_gate("cand-run")
        assert stub_pool.box["shadow_posts"][-1] == {"path": None}

    def test_partial_arm_refuses(self, tmp_path, stub_pool):
        daemon = self._daemon(tmp_path, stub_pool)
        stub_pool.box["shadow_ack"] = {
            "status": "partial", "workers": 1,
            "errors": ["worker 1: restore failed"]}
        with pytest.raises(RuntimeError, match="partial"):
            daemon._shadow_gate("cand-run")

    def test_chaos_site_fires_before_arming(self, tmp_path, stub_pool):
        spec = _dspec(tmp_path, pool_url=stub_pool.url,
                      shadow_min_scored=4, shadow_alpha=0.2,
                      shadow_timeout_s=5.0)
        plan = fault_plan_from_env("daemon.shadow_gate:1")
        daemon = Daemon(spec, tmp_path / "chaos", fault_plan=plan)
        with pytest.raises(OSError):
            daemon._shadow_gate("cand-run")
        assert stub_pool.box["shadow_posts"] == []  # nothing armed
        stub_pool.box["stats"] = _stub_stats(shadow={
            "scored_total": 6, "wins_total": 6, "losses_total": 0,
            "ties_total": 0})
        assert daemon._shadow_gate("cand-run")["confirmed"] is True


class TestAdoptLandedPromote:
    def test_adopts_when_pool_moved_past_armed_generation(
            self, tmp_path, stub_pool):
        spec = _dspec(tmp_path, pool_url=stub_pool.url)
        daemon = Daemon(spec, tmp_path / "adopt")
        stub_pool.box["rollout"] = {"generation": 1, "active": False,
                                    "promotions_total": 1}
        out = daemon._adopt_landed_promote(0)
        assert out["adopted"] is True and out["generation"] == 1
        # the pool still serving the armed generation means the promote
        # never dispatched: run the stage normally
        assert daemon._adopt_landed_promote(1) is None

    def test_stuck_rollout_times_out(self, tmp_path, stub_pool):
        spec = _dspec(tmp_path, pool_url=stub_pool.url,
                      rollout_timeout_s=0.4)
        daemon = Daemon(spec, tmp_path / "stuck")
        stub_pool.box["rollout"] = {"generation": 0, "active": True}
        with pytest.raises(TimeoutError, match="in flight"):
            daemon._adopt_landed_promote(0)


class TestDaemonSurfaces:
    def test_status_metrics_and_http_plane(self, tmp_path, stub_pool):
        spec = _dspec(tmp_path, pool_url=stub_pool.url)
        daemon = Daemon(spec, tmp_path / "surf")
        daemon.ledger.append_decision("no_drift", {})
        daemon.decision_counts["no_drift"] += 1
        body = daemon.status_body()
        assert body["daemon"] == "graftpilot"
        assert body["spec_sha"] == spec.fingerprint()
        assert body["decisions"]["no_drift"] == 1
        assert body["iterations_completed"] == 0
        assert body["inflight_iteration"] is None
        assert body["breaker"]["state"] == "closed"
        metrics = daemon.metrics_body()
        assert "graftpilot_breaker_state 0" in metrics
        assert "graftpilot_confirm_streak 0" in metrics
        assert "graftpilot_cooldown_active 0" in metrics

        server = serve_status(daemon)
        try:
            port = server.server_address[1]

            def get(path):
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}",
                        timeout=5) as resp:
                    return resp.status, resp.read().decode()

            code, status = get("/status")
            assert code == 200
            assert json.loads(status)["daemon"] == "graftpilot"
            code, text = get("/metrics")
            assert code == 200 and "graftpilot_polls_total 0" in text
            code, health = get("/healthz")
            assert code == 200
            assert json.loads(health)["pid"] == os.getpid()
            with pytest.raises(urllib.error.HTTPError) as err:
                get("/nope")
            assert err.value.code == 404
        finally:
            server.shutdown()

    def test_cli_status_and_stop(self, tmp_path, stub_pool, capsys):
        from rl_scheduler_tpu.utils.fsio import atomic_write_json

        out_dir = tmp_path / "cli"
        out_dir.mkdir()
        with pytest.raises(SystemExit, match=DAEMON_STATE_NAME):
            daemon_main(["status", "--out", str(out_dir)])

        spec = _dspec(tmp_path, pool_url=stub_pool.url)
        daemon = Daemon(spec, out_dir)
        server = serve_status(daemon)
        try:
            atomic_write_json(out_dir / DAEMON_STATE_NAME, {
                "pid": os.getpid(),
                "status_port": server.server_address[1],
                "started_at": time.time(),
                "spec_sha": spec.fingerprint()})
            assert daemon_main(["status", "--out", str(out_dir)]) == 0
            body = json.loads(capsys.readouterr().out.strip())
            assert body["daemon"] == "graftpilot"
            assert body["spec_sha"] == spec.fingerprint()
        finally:
            server.shutdown()

        sleeper = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(120)"])
        # reap the sleeper as soon as SIGTERM lands — a zombie child
        # still answers kill(pid, 0) and would read as "running"
        threading.Thread(target=sleeper.wait, daemon=True).start()
        try:
            atomic_write_json(out_dir / DAEMON_STATE_NAME, {
                "pid": sleeper.pid, "status_port": 1,
                "started_at": time.time(), "spec_sha": "x"})
            assert daemon_main(["stop", "--out", str(out_dir),
                                "--timeout", "15"]) == 0
            stopped = json.loads(capsys.readouterr().out.strip())
            assert stopped == {"stopped": True, "pid": sleeper.pid}
            assert sleeper.wait(timeout=10) == -signal.SIGTERM
            # a second stop reports the already-dead pid, exit 0
            assert daemon_main(["stop", "--out", str(out_dir),
                                "--timeout", "5"]) == 0
            again = json.loads(capsys.readouterr().out.strip())
            assert again["stopped"] is False
            assert again["reason"] == "not running"
        finally:
            if sleeper.poll() is None:
                sleeper.kill()


@pytest.mark.slow
def test_daemon_soak_hysteresis_never_flaps(tmp_path):
    """The anti-churn soak: a promoted iteration inside its cooldown
    window sees persistently drifting evidence for many polls and the
    daemon NEVER arms a second iteration — every decision is
    ``suppressed_cooldown``, the ledger stays byte-prefix monotonic."""
    pool = _StubPool()
    try:
        spec = _dspec(tmp_path, pool_url=pool.url, cooldown_s=300.0,
                      min_spacing_s=300.0, max_polls=40,
                      poll_interval_s=0.02)
        now = time.time()
        led = DaemonLedger(tmp_path / "soak", spec)
        led.append_iteration(0, "armed", "ok", {})
        led.append_iteration(0, "retrain", "ok", {"candidate": "c0"})
        led.append_iteration(0, "cooldown", "ok", {
            "outcome": "promoted", "cooldown_until": now + 300.0,
            "next_allowed_at": now + 300.0})
        daemon = Daemon(spec, tmp_path / "soak")
        pool.box["stats"] = _stub_stats(drifting=True)
        prev = daemon.ledger.path.read_bytes()
        summary = daemon.run_forever()
        assert daemon.ledger.path.read_bytes().startswith(prev)
        assert summary["decisions"]["suppressed_cooldown"] == 40
        assert summary["decisions"]["armed"] == 0
        assert summary["iterations_completed"] == 1
        assert list(daemon.ledger.iterations()) == [0]
    finally:
        pool.close()


# ------------------------------------------------------- the drill


def _write_table(path, cost_aws, cost_azure, lat_aws, lat_azure,
                 rows=32):
    """A normalized replay table with jitter small enough to stay
    inside one drift bucket (the graftdrift drill's tables)."""
    lines = ["cost_aws,cost_azure,latency_aws,latency_azure"]
    for i in range(rows):
        j = (i % 8) * 0.001
        lines.append(f"{cost_aws + j:.4f},{cost_azure + j:.4f},"
                     f"{lat_aws + j:.4f},{lat_azure + j:.4f}")
    path.write_text("\n".join(lines) + "\n")


def _bench_payload(i, num_nodes=8):
    items = [
        {"metadata": {"name": f"node-{j}",
                      "labels": {"cloud": "aws" if j < num_nodes // 2
                                 else "azure"}}}
        for j in range(num_nodes)
    ]
    return json.dumps({
        "pod": {"metadata": {"name": f"pilot-pod-{i}"},
                "spec": {"containers": [{"resources": {
                    "requests": {"cpu": "800m"}}}]}},
        "nodes": {"items": items},
    }).encode()


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as resp:
        body = resp.read()
    if resp.headers.get("Content-Type",
                        "").startswith("application/json"):
        return json.loads(body)
    return body.decode()


def _post(port, path, payload, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


def _load_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "extender_bench",
        REPO_ROOT / "loadgen" / "extender_bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def test_daemon_drill_kill_matrix(incumbent_run, tmp_path):
    """``make daemon-drill``, the graftpilot acceptance: a 2-worker
    drift-armed pool serves bench traffic continuously; the replay
    regime flips mid-soak; the daemon detects the drift off ``/stats``,
    confirms it across consecutive polls, retrains through graftloop,
    passes the LIVE shadow sign-test gate and hot-promotes generation
    0→1 with zero failed requests — while being SIGKILLed once in
    EVERY daemon ledger stage (armed / mid-loop / retrain recorded /
    shadow-gated / promoted) and resuming byte-prefix-exact each time.
    The stationary control (before the flip) records only ``no_drift``
    decisions and provably never retrains."""
    from rl_scheduler_tpu.scheduler import drift as drift_mod

    base_csv = tmp_path / "base.csv"
    spike_csv = tmp_path / "spike.csv"
    _write_table(base_csv, 0.10, 0.30, 0.20, 0.24)
    _write_table(spike_csv, 0.95, 0.60, 0.90, 0.85)

    port, cport = 0, 0
    import socket
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        if not port:
            port = s.getsockname()[1]
        else:
            cport = s.getsockname()[1]
        s.close()

    pool_trace = tmp_path / "pool_trace"
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT) + os.pathsep \
        + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    pool_proc = subprocess.Popen(
        [sys.executable, "-m", "rl_scheduler_tpu.scheduler.extender",
         "--workers", "2", "--host", "127.0.0.1",
         "--port", str(port), "--control-port", str(cport),
         "--run", str(incumbent_run), "--backend", "cpu",
         "--trace-dir", str(pool_trace), "--trace-max-segments", "50",
         "--data", str(base_csv),
         "--drift", "--drift-threshold", "0.2",
         "--drift-fast-window", "1.0", "--drift-slow-window", "3.0",
         "--drift-min-count", "10", "--drift-bucket-s", "0.25"],
        env=env, start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    failures, served = [], []
    stop = threading.Event()

    def _traffic():
        i = 0
        while not stop.is_set():
            body = _bench_payload(i)
            for attempt in range(4):
                try:
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{port}/filter", data=body,
                        headers={"Content-Type": "application/json"})
                    with urllib.request.urlopen(req,
                                                timeout=10) as resp:
                        json.load(resp)
                    served.append(i)
                    break
                except urllib.error.HTTPError as e:
                    failures.append((i, e.code))
                    break
                except OSError:
                    if attempt == 3:
                        failures.append((i, "connect"))
                    else:
                        time.sleep(0.1)
            i += 1
            time.sleep(0.03)

    pilot_dir = tmp_path / "pilot"
    ctl_dir = tmp_path / "control"
    daemon_common = [
        sys.executable, "-m", "rl_scheduler_tpu.loopback.daemon",
        "run",
        "--trace-dir", str(pool_trace),
        "--incumbent", str(incumbent_run),
        "--pool", f"http://127.0.0.1:{cport}",
        "--poll-interval", "0.3", "--poll-retries", "2",
        "--confirm-checks", "2", "--min-trace-records", "20",
        "--cooldown", "120", "--min-spacing", "0.5",
        "--shadow-min-scored", "24", "--shadow-alpha", "0.2",
        "--shadow-timeout", "60",
        "--steps", "16", "--mix", "0.25", "--iterations", "3",
        "--eval-every", "3", "--eval-episodes", "2",
        "--verdict-seeds", "0-4", "--verdict-episodes", "4",
        "--rollout-timeout", "180", "--max-stage-retries", "2",
    ]

    def _wait_marker(path, marker, proc, what, timeout_s=300.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if path.exists() and marker in path.read_text():
                return
            if proc.poll() is not None:
                pytest.fail(f"daemon exited rc={proc.returncode} "
                            f"before {what}")
            time.sleep(0.1)
        pytest.fail(f"{what} never appeared in {path}")

    dledger = pilot_dir / DAEMON_LEDGER_NAME
    lledger = pilot_dir / "iter-0000" / "loop_ledger.jsonl"
    try:
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            try:
                if _get(cport, "/healthz")["alive"] == 2:
                    break
            except OSError:
                time.sleep(0.2)
        else:
            pytest.fail("pool never came up")

        thread = threading.Thread(target=_traffic, daemon=True)
        thread.start()
        deadline = time.monotonic() + 120.0
        while len(served) < 40 and time.monotonic() < deadline:
            time.sleep(0.2)
        assert len(served) >= 40, "traffic never ramped"

        # Freeze the base-regime reference the daemon will grade
        # against (the mandatory snapshot-after-deploy).
        stats_url = f"http://127.0.0.1:{cport}/stats"
        ref_path = tmp_path / "reference.json"
        assert drift_mod.main(["snapshot", "--stats", stats_url,
                               "--out", str(ref_path)]) == 0
        resp = _post(cport, "/drift/reference", {"path": str(ref_path)})
        assert resp["status"] == "loaded" and resp["workers"] == 2

        # The stationary control: 3 polls over the UNCHANGED regime —
        # only no_drift decisions, zero iterations, provably no retrain.
        ctl = subprocess.run(
            daemon_common + ["--out", str(ctl_dir), "--max-polls", "3"],
            env=env, capture_output=True, text=True, timeout=120)
        assert ctl.returncode == 0, ctl.stderr[-2000:]
        ctl_summary = json.loads(
            [ln for ln in ctl.stdout.splitlines()
             if ln.startswith("{")][-1])
        assert ctl_summary["decisions"]["no_drift"] == 3
        assert sum(ctl_summary["decisions"].values()) == 3
        assert ctl_summary["iterations_completed"] == 0
        ctl_records = (ctl_dir / DAEMON_LEDGER_NAME).read_text()
        assert '"kind": "iteration"' not in ctl_records

        # The regime flip: every worker swaps to the spike table; the
        # drift sketches cross the threshold in both burn windows.
        flip = _post(cport, "/telemetry/flip", {"path": str(spike_csv)})
        assert flip["status"] == "flipped" and flip["workers"] == 2
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if _get(cport, "/stats")["drift"]["drifting"]:
                break
            time.sleep(0.25)
        else:
            pytest.fail("the flip never registered as drift")

        # The kill matrix: identical argv each run (the spec
        # fingerprint binds the ledger), one SIGKILL per daemon stage,
        # byte-prefix asserted at every resume.
        markers = [
            (dledger, '"outcome": "armed"', "armed decision"),
            (lledger, '"stage": "compile"', "loop compile stage"),
            (dledger, '"stage": "retrain"', "daemon retrain record"),
            (dledger, '"stage": "shadow_gate"', "shadow gate record"),
            (dledger, '"stage": "promote"', "daemon promote record"),
        ]
        pilot_argv = daemon_common + ["--out", str(pilot_dir)]
        prev_daemon, prev_loop = b"", b""
        for i, (path, marker, what) in enumerate(markers):
            with open(tmp_path / f"pilot_run{i}.log", "wb") as log:
                proc = subprocess.Popen(pilot_argv, env=env,
                                        start_new_session=True,
                                        stdout=log,
                                        stderr=subprocess.STDOUT)
            try:
                _wait_marker(path, marker, proc, what)
            finally:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
            proc.wait(timeout=30)
            cur = dledger.read_bytes()
            assert cur.startswith(prev_daemon), \
                f"daemon ledger lost bytes after kill {i} ({what})"
            prev_daemon = cur
            if lledger.exists():
                cur_loop = lledger.read_bytes()
                assert cur_loop.startswith(prev_loop), \
                    f"loop ledger lost bytes after kill {i} ({what})"
                prev_loop = cur_loop

        # The final resume finishes the iteration (or finds it already
        # terminal) and goes back to polling; post-promote the frozen
        # reference no longer matches the serving generation, so the
        # daemon cannot double-retrain — decisions return to no_drift.
        final_log = tmp_path / "pilot_final.log"
        with open(final_log, "wb") as log:
            final = subprocess.Popen(pilot_argv, env=env,
                                     start_new_session=True,
                                     stdout=log,
                                     stderr=subprocess.STDOUT)
        # reap on exit so the stop subcommand's kill(pid, 0) liveness
        # probe sees the drain instead of a zombie child of pytest
        threading.Thread(target=final.wait, daemon=True).start()
        # The killed run may have raced a few records past its marker
        # (cooldown, even an early no_drift) before the SIGKILL landed
        # — wait for the FINAL run to own the state file before
        # trusting the status plane.
        deadline = time.monotonic() + 120.0
        state = None
        while time.monotonic() < deadline:
            try:
                state = json.loads(
                    (pilot_dir / DAEMON_STATE_NAME).read_text())
                if state["pid"] == final.pid:
                    break
            except (OSError, ValueError):
                pass
            if final.poll() is not None:
                pytest.fail(f"final daemon exited rc={final.returncode}"
                            " before writing its state file")
            time.sleep(0.1)
        assert state is not None and state["pid"] == final.pid
        _wait_marker(dledger, '"no_drift"', final,
                     "post-promote no_drift decision")
        assert dledger.read_bytes().startswith(prev_daemon)
        # The final resume may still be closing out the iteration —
        # poll the live status plane until the promote is terminal.
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline:
            status = _get(state["status_port"], "/status")
            if status["iterations"].get("promoted") == 1 \
                    and status["inflight_iteration"] is None:
                break
            if final.poll() is not None:
                pytest.fail(f"final daemon exited rc={final.returncode}"
                            " before finishing the promote")
            time.sleep(0.25)
        assert status["iterations"]["promoted"] == 1
        assert status["inflight_iteration"] is None
        assert status["breaker"]["state"] == "closed"
        assert status["cooldown_until"] > time.time()  # hysteresis on
        assert status["incumbent"] != str(incumbent_run)
        metrics = _get(state["status_port"], "/metrics")
        assert 'graftpilot_iterations_total{outcome="promoted"} 1' \
            in metrics
        assert "graftpilot_cooldown_active 1" in metrics
        assert "graftpilot_breaker_state 0" in metrics
        sub = subprocess.run(
            [sys.executable, "-m", "rl_scheduler_tpu.loopback.daemon",
             "status", "--out", str(pilot_dir)],
            env=env, capture_output=True, text=True, timeout=60)
        assert sub.returncode == 0, sub.stderr[-2000:]
        assert json.loads(sub.stdout)["iterations"]["promoted"] == 1

        # SIGTERM drain via the stop subcommand ends the final run
        # cleanly with the summary line.
        stop_cmd = subprocess.run(
            [sys.executable, "-m", "rl_scheduler_tpu.loopback.daemon",
             "stop", "--out", str(pilot_dir), "--timeout", "60"],
            env=env, capture_output=True, text=True, timeout=120)
        assert stop_cmd.returncode == 0, stop_cmd.stderr[-2000:]
        assert json.loads(stop_cmd.stdout)["stopped"] is True
        assert final.wait(timeout=60) == 0
        summary = json.loads(
            [ln for ln in final_log.read_text().splitlines()
             if '"metric": "graftpilot_summary"' in ln][-1])
        assert summary["iterations"] == {
            "promoted": 1, "refused": 0, "shadow_rejected": 0,
            "rolled_back": 0}
        assert summary["decisions"]["armed"] == 1
        assert summary["decisions"]["confirming"] >= 1
        assert summary["decisions"]["breaker_open"] == 0
        assert summary["breaker"]["state"] == "closed"
        assert summary["breaker"]["opens_total"] == 0

        # The ledger's own story: the shadow gate confirmed with live
        # wins, and the promote landed generation 1 exactly once.
        records = [json.loads(ln) for ln
                   in dledger.read_text().splitlines()[1:]]
        stages = {r["stage"]: r for r in records
                  if r["kind"] == "iteration" and r["iter"] == 0}
        gate = stages["shadow_gate"]["out"]
        assert gate["confirmed"] is True
        assert gate["scored"] >= 24
        assert gate["wins"] > gate["losses"]
        assert gate["pvalue"] <= 0.2
        assert stages["promote"]["out"]["generation"] == 1
        assert stages["cooldown"]["out"]["outcome"] == "promoted"

        # The pool really moved: one promotion, generation 1 on every
        # worker, and the bench's soak line samples it.
        rollout = _get(cport, "/rollout")
        assert rollout["generation"] == 1
        assert rollout["promotions_total"] == 1
        assert not rollout["active"]
        pool_metrics = _get(cport, "/metrics")
        assert "rl_scheduler_extender_pool_generation 1" in pool_metrics
        bench_out = _load_bench().main(
            ["--port", str(port), "--threads", "2", "--warmup", "2",
             "--duration", "0.6", "--control-port", str(cport)])
        assert bench_out["failures"] == 0
        assert bench_out["daemon_generation"] == 1
    finally:
        stop.set()
        for leftover in (pilot_dir / DAEMON_STATE_NAME,):
            if leftover.exists():
                try:
                    pid = json.loads(leftover.read_text())["pid"]
                    os.kill(pid, signal.SIGKILL)
                except (OSError, ValueError, KeyError):
                    pass
        try:
            os.killpg(pool_proc.pid, signal.SIGTERM)
            pool_proc.wait(timeout=30)
        except (ProcessLookupError, subprocess.TimeoutExpired):
            try:
                os.killpg(pool_proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            pool_proc.wait(timeout=10)

    # Zero failed requests across the whole soak — flip, shadow gate
    # and rolling promote included.
    assert failures == [], f"dropped requests: {failures[:10]}"
    assert len(served) >= 100
