"""Batch-minor set-transformer fast path (``models/set_fast.py``).

Parity contract: ``BatchMinorSetPolicy`` computes the IDENTICAL function
to ``SetTransformerPolicy(num_heads=1)`` — float32 forward AND gradients
agree with the flax module on the same parameter tree, so a checkpoint
trained on either path serves and evaluates on the other.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.models import SetTransformerPolicy
from rl_scheduler_tpu.models.set_fast import BatchMinorSetPolicy


@pytest.fixture(scope="module")
def nets_and_params():
    flax_net = SetTransformerPolicy(dim=64, depth=2, num_heads=1)
    fast_net = BatchMinorSetPolicy(dim=64, depth=2, dtype=None)
    params = flax_net.init(jax.random.PRNGKey(3), jnp.zeros((1, 8, 6)))
    return flax_net, fast_net, params


def test_forward_parity_f32(nets_and_params):
    flax_net, fast_net, params = nets_and_params
    obs = jax.random.uniform(jax.random.PRNGKey(1), (257, 8, 6))
    l0, v0 = flax_net.apply(params, obs)
    l1, v1 = jax.jit(fast_net.apply)(params, obs)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v0),
                               rtol=1e-5, atol=1e-5)


def test_matmul_attention_parity_f32(nets_and_params):
    """The fleet-N attention formulation (batched-matmul scores; auto-
    selected above CHUNKED_ATTN_MAX_N) computes the same function as the
    chunk loop and the flax module — forward and gradients — at both a
    small and a fleet node count."""
    flax_net, _, params = nets_and_params
    mm_net = BatchMinorSetPolicy(dim=64, depth=2, attn_impl="matmul")
    for n in (8, 40):
        obs = jax.random.uniform(jax.random.PRNGKey(7), (33, n, 6))
        l0, v0 = flax_net.apply(params, obs)
        l1, v1 = jax.jit(mm_net.apply)(params, obs)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(v1), np.asarray(v0),
                                   rtol=1e-5, atol=1e-5)

    def loss(apply_fn, obs, act):
        def f(p):
            logits, value = apply_fn(p, obs)
            logp = jax.nn.log_softmax(logits)
            return jnp.mean(jnp.take_along_axis(
                logp, act[:, None], axis=1)) + jnp.mean(value ** 2)
        return f

    obs = jax.random.uniform(jax.random.PRNGKey(8), (32, 24, 6))
    act = jax.random.randint(jax.random.PRNGKey(9), (32,), 0, 24)
    g0 = jax.grad(loss(flax_net.apply, obs, act))(params)
    g1 = jax.grad(loss(mm_net.apply, obs, act))(params)
    for leaf0, leaf1 in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(leaf1), np.asarray(leaf0),
                                   rtol=2e-4, atol=2e-6)


def test_gradient_parity_f32(nets_and_params):
    flax_net, fast_net, params = nets_and_params
    obs = jax.random.uniform(jax.random.PRNGKey(2), (128, 8, 6))
    act = jax.random.randint(jax.random.PRNGKey(4), (128,), 0, 8)

    def loss(apply_fn):
        def f(p):
            logits, value = apply_fn(p, obs)
            logp = jax.nn.log_softmax(logits)
            return jnp.mean(jnp.take_along_axis(
                logp, act[:, None], axis=1)) + jnp.mean(value ** 2)
        return f

    g0 = jax.grad(loss(flax_net.apply))(params)
    g1 = jax.grad(loss(fast_net.apply))(params)
    for leaf0, leaf1 in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(leaf1), np.asarray(leaf0),
                                   rtol=2e-4, atol=2e-6)


def test_bf16_close_to_f32(nets_and_params):
    flax_net, _, params = nets_and_params
    fast_bf16 = BatchMinorSetPolicy(dim=64, depth=2, dtype=jnp.bfloat16)
    obs = jax.random.uniform(jax.random.PRNGKey(5), (64, 8, 6))
    l0, v0 = flax_net.apply(params, obs)
    l1, v1 = fast_bf16.apply(params, obs)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                               rtol=0.05, atol=0.05)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v0),
                               rtol=0.05, atol=0.05)


def test_unbatched_matches_flax(nets_and_params):
    flax_net, fast_net, params = nets_and_params
    obs = jax.random.uniform(jax.random.PRNGKey(6), (8, 6))
    l0, v0 = flax_net.apply(params, obs)
    l1, v1 = fast_net.apply(params, obs)
    assert l1.shape == (8,) and v1.shape == ()
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(v1), float(v0), rtol=1e-5, atol=1e-5)


def test_permutation_equivariance(nets_and_params):
    """The batch-minor path inherits the flax module's contract: logits
    permutation-equivariant, value permutation-invariant."""
    _, fast_net, params = nets_and_params
    obs = jax.random.uniform(jax.random.PRNGKey(7), (16, 8, 6))
    perm = jax.random.permutation(jax.random.PRNGKey(8), 8)
    l0, v0 = fast_net.apply(params, obs)
    l1, v1 = fast_net.apply(params, obs[:, perm])
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0)[:, perm],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v0),
                               rtol=1e-5, atol=1e-5)


def test_multihead_tree_rejected():
    multi = SetTransformerPolicy(dim=64, depth=2, num_heads=4)
    params = multi.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 6)))
    fast = BatchMinorSetPolicy()
    with pytest.raises(ValueError, match="num_heads=4"):
        fast.apply(params, jnp.zeros((4, 8, 6)))


def test_train_cli_fused_set(tmp_path):
    """--fused-set trains cluster_set end to end, checkpoints restore on
    the flax policy (identical tree), and the run's meta records the path."""
    import json

    from rl_scheduler_tpu.agent import train_ppo as cli
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    run_dir = cli.main([
        "--preset", "quick", "--env", "cluster_set", "--fused-set",
        "--num-envs", "8", "--rollout-steps", "16", "--minibatch-size", "32",
        "--iterations", "2", "--checkpoint-every", "2",
        "--run-root", str(tmp_path), "--run-name", "fused_set",
    ])
    mgr = CheckpointManager(run_dir)
    assert mgr.latest_step() == 2
    meta = mgr.restore_meta(2)
    assert meta["fused_set"] is True
    assert meta["num_heads"] == 1
    # The tree a --fused-set run saves restores onto the FLAX policy and
    # produces the same outputs the fast path computes (f32): serving and
    # evaluation never need to know which path trained the checkpoint.
    tree, _ = mgr.restore(2)
    mgr.close()
    params = {"params": tree["params"]["params"]}
    obs = jax.random.uniform(jax.random.PRNGKey(9), (32, 8, 6))
    l_flax, v_flax = SetTransformerPolicy(
        dim=64, depth=2, num_heads=1).apply(params, obs)
    l_fast, v_fast = BatchMinorSetPolicy(dtype=None).apply(params, obs)
    np.testing.assert_allclose(np.asarray(l_fast), np.asarray(l_flax),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(v_fast), np.asarray(v_flax),
                               rtol=1e-5, atol=1e-5)
    records = [json.loads(l) for l in (run_dir / "metrics.jsonl").open()]
    assert all(np.isfinite(r["episode_reward_mean"]) for r in records
               if "episode_reward_mean" in r)


def test_fused_set_flag_validation(tmp_path):
    from rl_scheduler_tpu.agent import train_ppo as cli

    with pytest.raises(SystemExit, match="no meaning"):
        cli.main(["--env", "multi_cloud", "--fused-set",
                  "--run-root", str(tmp_path)])
    with pytest.raises(SystemExit, match="single-head"):
        cli.main(["--env", "cluster_set", "--fused-set", "--num-heads", "4",
                  "--run-root", str(tmp_path)])


def test_preset_set_fast_implies_recipe(tmp_path):
    """VERDICT r3 item 3: `--preset set_fast` alone reproduces the measured
    config-4 recipe — cluster_set env, batch-minor fast path, 1 SGD epoch,
    bf16 — with no hand-typed flags."""
    from rl_scheduler_tpu.agent import train_ppo as cli
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    preset = PPO_PRESETS["set_fast"]
    assert preset.num_epochs == 1 and preset.compute_dtype == "bfloat16"
    assert preset.num_envs == 4096  # the measured tpu4096 scale

    run_dir = cli.main([
        "--preset", "set_fast",  # no --env / --fused-set needed
        "--num-envs", "8", "--rollout-steps", "16", "--minibatch-size", "32",
        "--iterations", "2", "--checkpoint-every", "2",
        "--run-root", str(tmp_path), "--run-name", "set_fast_preset",
    ])
    mgr = CheckpointManager(run_dir)
    meta = mgr.restore_meta(2)
    mgr.close()
    assert meta["env"] == "cluster_set"
    assert meta["fused_set"] is True
    assert meta["preset"] == "set_fast"

    # Contradicting the recipe's env is refused, not silently ignored.
    with pytest.raises(SystemExit, match="set_fast"):
        cli.main(["--preset", "set_fast", "--env", "cluster_graph",
                  "--run-root", str(tmp_path)])
