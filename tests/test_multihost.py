"""True multi-process ``jax.distributed`` test (SURVEY.md §5.8).

Two OS processes, four virtual CPU devices each, form ONE global 8-device
mesh through ``maybe_initialize_distributed`` — the same code path a
multi-host TPU pod takes over DCN — and run a data-parallel PPO update
whose gradient pmean crosses the process boundary. This is the strongest
distributed check that runs without real multi-host hardware: collectives
actually cross process memory spaces, unlike the in-process 8-device tests.
"""

import os
import socket
import subprocess
import sys

import pytest

WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
# A site hook can pin a single-accelerator platform (e.g. a tunneled TPU)
# even when JAX_PLATFORMS=cpu was exported; re-assert before backend init.
jax.config.update("jax_platforms", "cpu")
from rl_scheduler_tpu.parallel import maybe_initialize_distributed

assert maybe_initialize_distributed(), "coordinates were set; init must run"
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())

from rl_scheduler_tpu.agent.ppo import PPOTrainConfig
from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.parallel import make_mesh, make_data_parallel_ppo

mesh = make_mesh({"dp": 8})
cfg = PPOTrainConfig(num_envs=16, rollout_steps=8, minibatch_size=32,
                     num_epochs=2, hidden=(16, 16))
env_params = env_core.make_params(EnvConfig())
init_fn, update_fn, _ = make_data_parallel_ppo(env_params, cfg, mesh)
runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
runner, metrics = jax.jit(update_fn)(runner)
loss = float(metrics["policy_loss"])  # replicated -> fetchable everywhere
assert loss == loss, "nan policy loss"
print(f"MULTIHOST_OK process={jax.process_index()} loss={loss.hex()}", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(tmp_path, port: int, attempt: int):
    """Start both workers with stdout->file (no pipe-buffer coupling; output
    survives timeouts). Returns ``[(proc, out_file), ...]``."""
    procs = []
    for pid in (0, 1):
        env = dict(
            os.environ,
            RL_SCHED_COORDINATOR=f"127.0.0.1:{port}",
            RL_SCHED_NUM_PROCESSES="2",
            RL_SCHED_PROCESS_ID=str(pid),
        )
        # The conftest's single-process device-count flags must not leak in.
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        out_file = tmp_path / f"worker{pid}_try{attempt}.log"
        procs.append(
            (
                subprocess.Popen(
                    [sys.executable, "-c", WORKER],
                    env=env,
                    stdout=out_file.open("w"),
                    stderr=subprocess.STDOUT,
                ),
                out_file,
            )
        )
    return procs


@pytest.mark.slow
def test_two_process_distributed_ppo_update(tmp_path):
    # _free_port is TOCTOU-racy (the port is released before the coordinator
    # rebinds it), so retry the whole launch on a fresh port if anything
    # fails to come up.
    for attempt in range(3):
        procs = _launch(tmp_path, _free_port(), attempt)
        try:
            for p, _ in procs:
                p.wait(timeout=240)
        except subprocess.TimeoutExpired:
            pass
        finally:
            for p, _ in procs:
                p.kill()
                p.wait()
        outs = [f.read_text() for _, f in procs]
        if all(p.returncode == 0 for p, _ in procs):
            break
        if attempt == 2:
            for pid, out in enumerate(outs):
                print(f"--- worker {pid} ---\n{out}")
            pytest.fail("both launch attempts failed; see worker output above")
    for pid, ((p, _), out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"MULTIHOST_OK process={pid}" in out, out
    # pmean'd metrics are replicated: both processes must report the SAME
    # bits (float.hex) — the collective really crossed the process boundary.
    loss0 = outs[0].split("loss=")[1].split()[0]
    loss1 = outs[1].split("loss=")[1].split()[0]
    assert loss0 == loss1, (loss0, loss1)
