"""True multi-process ``jax.distributed`` tests (SURVEY.md §5.8).

N OS processes, each with its own virtual CPU devices, form ONE global
8-device mesh through ``maybe_initialize_distributed`` — the same code
path a multi-host TPU pod takes over DCN — and run data-parallel PPO
TRAINING whose gradient pmean crosses process boundaries every SGD
minibatch. This is the strongest distributed check that runs without real
multi-host hardware: collectives actually cross process memory spaces,
unlike the in-process 8-device tests.

Two topologies: 2 processes x 4 devices (the minimal boundary crossing)
and 4 processes x 2 devices (growth path: more hosts than the pairwise
case, exercising coordinator barriers and cross-host reduce trees with
real fan-in).
"""

import os
import socket
import subprocess
import sys

import pytest

WORKER = r"""
import os, sys
local_devices = os.environ["RL_TEST_LOCAL_DEVICES"]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    f"--xla_force_host_platform_device_count={local_devices}"
)
import jax
# A site hook can pin a single-accelerator platform (e.g. a tunneled TPU)
# even when JAX_PLATFORMS=cpu was exported; re-assert before backend init.
jax.config.update("jax_platforms", "cpu")
from rl_scheduler_tpu.parallel import maybe_initialize_distributed

num_procs = int(os.environ["RL_SCHED_NUM_PROCESSES"])
assert maybe_initialize_distributed(), "coordinates were set; init must run"
assert jax.process_count() == num_procs, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())

import dataclasses

from rl_scheduler_tpu.agent.ppo import PPOTrainConfig
from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.parallel import (
    make_data_parallel_ppo,
    make_mesh,
    make_seq_parallel_ppo,
    make_tensor_parallel_ppo,
)

cfg = PPOTrainConfig(num_envs=16, rollout_steps=8, minibatch_size=32,
                     num_epochs=2, hidden=(16, 16))
mode = os.environ.get("RL_TEST_MODE", "dp")
if mode == "dp":
    mesh = make_mesh({"dp": 8})
    env_params = env_core.make_params(EnvConfig())
    init_fn, update_fn, _ = make_data_parallel_ppo(env_params, cfg, mesh)
elif mode == "dp_sp":
    # sp FIRST in the mesh dict: with 2 processes x 4 local devices the
    # sp partner of device i is device i+4 — the ring-attention ppermute
    # and the value-pool pmean REALLY cross the process boundary.
    from rl_scheduler_tpu.env.bundle import cluster_set_bundle
    from rl_scheduler_tpu.models import SetTransformerPolicy

    mesh = make_mesh({"sp": 2, "dp": 4})
    net = SetTransformerPolicy(dim=32, depth=1, axis_name="sp")
    init_fn, update_fn, _ = make_seq_parallel_ppo(
        cluster_set_bundle(), cfg, net, mesh
    )
elif mode == "dp_sp_fleet":
    # Fleet node count (round 5): cluster_set at N=64 with the node
    # axis sharded sp=4 (16 nodes per device), sp outermost so every
    # ring hop's ppermute partner lives across a process boundary for
    # half the devices.
    from rl_scheduler_tpu.env import cluster_set as cs
    from rl_scheduler_tpu.env.bundle import cluster_set_bundle
    from rl_scheduler_tpu.models import SetTransformerPolicy

    mesh = make_mesh({"sp": 4, "dp": 2})
    net = SetTransformerPolicy(dim=32, depth=1, axis_name="sp")
    init_fn, update_fn, _ = make_seq_parallel_ppo(
        cluster_set_bundle(cs.make_params(num_nodes=64)), cfg, net, mesh
    )
elif mode == "dp_tp":
    # tp first for the same reason: the column/row-parallel psums (and
    # the tp-aware global-norm clip) cross processes.
    from rl_scheduler_tpu.env.bundle import multi_cloud_bundle

    mesh = make_mesh({"tp": 2, "dp": 4})
    init_fn, update_fn, _ = make_tensor_parallel_ppo(
        multi_cloud_bundle(env_core.make_params(EnvConfig())),
        dataclasses.replace(cfg, max_grad_norm=0.5),
        mesh,
    )
else:
    raise SystemExit(f"unknown RL_TEST_MODE {mode!r}")
runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
update = jax.jit(update_fn, donate_argnums=0)
losses = []
for _ in range(int(os.environ["RL_TEST_ITERATIONS"])):
    runner, metrics = update(runner)
    losses.append(float(metrics["policy_loss"]))  # replicated everywhere
assert all(l == l for l in losses), ("nan policy loss", losses)
trail = ",".join(l.hex() for l in losses)
print(f"MULTIHOST_OK process={jax.process_index()} losses={trail}", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(tmp_path, port: int, attempt: int, num_procs: int,
            local_devices: int, iterations: int, mode: str = "dp"):
    """Start all workers with stdout->file (no pipe-buffer coupling; output
    survives timeouts). Returns ``[(proc, out_file), ...]``."""
    procs = []
    for pid in range(num_procs):
        env = dict(
            os.environ,
            RL_SCHED_COORDINATOR=f"127.0.0.1:{port}",
            RL_SCHED_NUM_PROCESSES=str(num_procs),
            RL_SCHED_PROCESS_ID=str(pid),
            RL_TEST_LOCAL_DEVICES=str(local_devices),
            RL_TEST_ITERATIONS=str(iterations),
            RL_TEST_MODE=mode,
        )
        # The conftest's single-process device-count flags must not leak in.
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        out_file = tmp_path / f"worker{pid}_try{attempt}.log"
        procs.append(
            (
                subprocess.Popen(
                    [sys.executable, "-c", WORKER],
                    env=env,
                    stdout=out_file.open("w"),
                    stderr=subprocess.STDOUT,
                ),
                out_file,
            )
        )
    return procs


def _run_distributed(tmp_path, num_procs: int, local_devices: int,
                     iterations: int, mode: str = "dp"):
    # _free_port is TOCTOU-racy (the port is released before the coordinator
    # rebinds it), so retry the whole launch on a fresh port if anything
    # fails to come up.
    for attempt in range(3):
        procs = _launch(tmp_path, _free_port(), attempt, num_procs,
                        local_devices, iterations, mode)
        try:
            for p, _ in procs:
                p.wait(timeout=240)
        except subprocess.TimeoutExpired:
            pass
        finally:
            for p, _ in procs:
                p.kill()
                p.wait()
        outs = [f.read_text() for _, f in procs]
        if all(p.returncode == 0 for p, _ in procs):
            break
        if attempt == 2:
            for pid, out in enumerate(outs):
                print(f"--- worker {pid} ---\n{out}")
            pytest.fail("all launch attempts failed; see worker output above")
    for pid, ((p, _), out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"MULTIHOST_OK process={pid}" in out, out
    # pmean'd metrics are replicated: every process must report the SAME
    # bits (float.hex) for every iteration — the collectives really
    # crossed the process boundaries, throughout training.
    trails = [out.split("losses=")[1].split()[0] for out in outs]
    assert len(set(trails)) == 1, trails


@pytest.mark.slow
def test_two_process_distributed_ppo_update(tmp_path):
    _run_distributed(tmp_path, num_procs=2, local_devices=4, iterations=1)


@pytest.mark.slow
def test_four_process_distributed_ppo_training(tmp_path):
    """VERDICT r2 item 7: 4 processes x 2 virtual devices, one global
    8-device mesh, multiple training iterations with cross-host gradient
    sync staying bit-identical on every host."""
    _run_distributed(tmp_path, num_procs=4, local_devices=2, iterations=3)


@pytest.mark.slow
def test_two_process_seq_parallel_training(tmp_path):
    """VERDICT r3 item 6: the sp collectives (ring-attention ppermute,
    value-pool pmean) cross OS-process boundaries. The mesh puts sp
    OUTERMOST, so each device's sp partner lives in the other process;
    losses must stay finite and bit-identical on both ranks."""
    _run_distributed(tmp_path, num_procs=2, local_devices=4, iterations=2,
                     mode="dp_sp")


@pytest.mark.slow
def test_two_process_fleet_seq_parallel_training(tmp_path):
    """Round 5: the fleet node count (N=64, set_fleet64's env) trains
    dp x sp across OS processes — sp=4 puts 16 nodes on each device
    and the ring's ppermute hops cross the process boundary; losses
    must stay finite and bit-identical on both ranks."""
    _run_distributed(tmp_path, num_procs=2, local_devices=4, iterations=2,
                     mode="dp_sp_fleet")


@pytest.mark.slow
def test_two_process_tensor_parallel_training(tmp_path):
    """VERDICT r3 item 6: the tp collectives (column/row-parallel psums +
    the tp-aware global-norm clip) cross OS-process boundaries, tp
    outermost as above."""
    _run_distributed(tmp_path, num_procs=2, local_devices=4, iterations=2,
                     mode="dp_tp")
