"""Fused Pallas GNN kernel: forward and gradient parity with GNNPolicy.

Runs in interpret mode on CPU (same auto-pick as the Pallas GAE kernel),
so the kernel code path is covered without a TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.env import cluster_graph
from rl_scheduler_tpu.models import GNNPolicy
from rl_scheduler_tpu.ops.pallas_gnn import FusedGNNPolicy, make_fused_gnn_apply


@pytest.fixture(scope="module")
def setup():
    params_env = cluster_graph.make_params()
    adj = np.asarray(params_env.adjacency, np.float32)
    ref = GNNPolicy.from_adjacency(adj, dim=16, depth=3)
    obs = jax.random.normal(
        jax.random.PRNGKey(0), (24, adj.shape[0], cluster_graph.NODE_FEAT)
    )
    params = ref.init(jax.random.PRNGKey(1), obs)
    return adj, ref, params, obs


def test_forward_parity(setup):
    adj, ref, params, obs = setup
    logits_ref, value_ref = ref.apply(params, obs)
    fused = make_fused_gnn_apply(adj, depth=3, block_b=8)
    logits_f, value_f = fused(params, obs)
    np.testing.assert_allclose(np.asarray(logits_f), np.asarray(logits_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(value_f), np.asarray(value_ref),
                               rtol=1e-5, atol=1e-5)


def test_forward_parity_unbatched_and_padded(setup):
    adj, ref, params, obs = setup
    fused = make_fused_gnn_apply(adj, depth=3, block_b=16)
    # unbatched [N, feat]
    l1, v1 = fused(params, obs[0])
    lr, vr = ref.apply(params, obs[0])
    np.testing.assert_allclose(np.asarray(l1), np.asarray(lr), rtol=1e-5,
                               atol=1e-5)
    assert np.isclose(float(v1), float(vr), rtol=1e-5, atol=1e-5)
    # batch not a multiple of block_b (24 % 16 != 0 -> padded internally)
    lb, vb = fused(params, obs)
    lrb, vrb = ref.apply(params, obs)
    np.testing.assert_allclose(np.asarray(lb), np.asarray(lrb), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(vb), np.asarray(vrb), rtol=1e-5,
                               atol=1e-5)


def test_gradient_parity(setup):
    """Every checkpoint-parameter gradient through the custom-vjp fused
    path must match autodiff through the reference module."""
    adj, ref, params, obs = setup
    fused = make_fused_gnn_apply(adj, depth=3, block_b=8)
    key = jax.random.PRNGKey(2)
    w_l = jax.random.normal(key, obs.shape[:1] + (adj.shape[0],))
    w_v = jax.random.normal(jax.random.fold_in(key, 1), obs.shape[:1])

    def loss_with(apply_fn):
        def loss(p):
            logits, value = apply_fn(p, obs)
            return jnp.sum(logits * w_l) + jnp.sum(value * w_v)

        return loss

    g_ref = jax.grad(loss_with(ref.apply))(params)
    g_fused = jax.grad(loss_with(fused))(params)
    ref_flat = jax.tree_util.tree_leaves_with_path(g_ref)
    fused_flat = jax.tree.leaves(g_fused)
    assert len(ref_flat) == len(fused_flat)
    for (path, r), f in zip(ref_flat, fused_flat):
        np.testing.assert_allclose(
            np.asarray(f), np.asarray(r), rtol=2e-4, atol=2e-4,
            err_msg=jax.tree_util.keystr(path),
        )


def test_bf16_compute_keeps_heads_f32(setup):
    """compute_dtype=bfloat16 rounds the torso matmuls only; the heads stay
    f32 (GNNPolicy's contract), so outputs track the f32 reference within
    torso-rounding error — far tighter than full-bf16 would allow."""
    adj, ref, params, obs = setup
    logits_ref, value_ref = ref.apply(params, obs)
    fused = make_fused_gnn_apply(adj, depth=3, block_b=8,
                                 compute_dtype=jnp.bfloat16)
    logits_f, value_f = fused(params, obs)
    np.testing.assert_allclose(np.asarray(logits_f), np.asarray(logits_ref),
                               rtol=0.05, atol=0.02)
    np.testing.assert_allclose(np.asarray(value_f), np.asarray(value_ref),
                               rtol=0.05, atol=0.02)


def test_depth_one(setup):
    adj, _, _, _ = setup
    ref = GNNPolicy.from_adjacency(adj, dim=16, depth=1)
    obs = jax.random.normal(
        jax.random.PRNGKey(3), (8, adj.shape[0], cluster_graph.NODE_FEAT)
    )
    params = ref.init(jax.random.PRNGKey(4), obs)
    fused = make_fused_gnn_apply(adj, depth=1, block_b=8)
    lf, vf = fused(params, obs)
    lr, vr = ref.apply(params, obs)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(lr), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(vf), np.asarray(vr), rtol=1e-5,
                               atol=1e-5)


def test_depth_validation(setup):
    adj, _, _, _ = setup
    with pytest.raises(ValueError, match="depth"):
        make_fused_gnn_apply(adj, depth=4)


def test_fused_policy_trains_ppo(setup):
    """End-to-end: one PPO update through the fused policy stays finite
    and uses the SAME checkpoint tree as the reference module."""
    from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, make_ppo_bundle
    from rl_scheduler_tpu.env.bundle import cluster_graph_bundle

    params_env = cluster_graph.make_params()
    adj = np.asarray(params_env.adjacency, np.float32)
    net = FusedGNNPolicy(adj, dim=16, depth=3, block_b=8)
    cfg = PPOTrainConfig(num_envs=8, rollout_steps=8, minibatch_size=32,
                         num_epochs=2, lr=1e-3)
    init_fn, update_fn, _ = make_ppo_bundle(
        cluster_graph_bundle(params_env), cfg, net=net
    )
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    runner, metrics = jax.jit(update_fn)(runner)
    for k in ("policy_loss", "value_loss", "entropy"):
        assert np.isfinite(float(metrics[k])), k
    # same tree structure as the reference module's params
    ref_net = GNNPolicy.from_adjacency(adj, dim=16, depth=3)
    ref_params = ref_net.init(
        jax.random.PRNGKey(1),
        jnp.zeros((1, adj.shape[0], cluster_graph.NODE_FEAT)),
    )
    assert (jax.tree_util.tree_structure(runner.params)
            == jax.tree_util.tree_structure(ref_params))


def test_train_cli_fused_gnn(tmp_path):
    from rl_scheduler_tpu.agent import train_ppo as cli

    run_dir = cli.main([
        "--env", "cluster_graph", "--preset", "quick", "--num-envs", "4",
        "--rollout-steps", "8", "--minibatch-size", "16",
        "--iterations", "1", "--checkpoint-every", "1", "--fused-gnn",
        "--run-root", str(tmp_path), "--run-name", "fused_gnn_run",
    ])
    assert run_dir.exists()
    with pytest.raises(SystemExit, match="fused-gnn"):
        cli.main(["--env", "multi_cloud", "--fused-gnn",
                  "--run-root", str(tmp_path)])


def test_preset_gnn_fast_implies_recipe(tmp_path):
    """VERDICT r3 item 3: `--preset gnn_fast` alone reproduces the measured
    config-5 recipe — cluster_graph env, Pallas kron kernel, 1 SGD epoch."""
    import pytest

    from rl_scheduler_tpu.agent import train_ppo as cli
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    preset = PPO_PRESETS["gnn_fast"]
    assert preset.num_epochs == 1
    assert preset.num_envs == 8192  # the measured tpu8192 scale

    run_dir = cli.main([
        "--preset", "gnn_fast",  # no --env / --fused-gnn needed
        "--num-envs", "8", "--rollout-steps", "16", "--minibatch-size", "32",
        "--iterations", "2", "--checkpoint-every", "2",
        "--run-root", str(tmp_path), "--run-name", "gnn_fast_preset",
    ])
    mgr = CheckpointManager(run_dir)
    meta = mgr.restore_meta(2)
    mgr.close()
    assert meta["env"] == "cluster_graph"
    assert meta["fused_gnn"] is True

    with pytest.raises(SystemExit, match="gnn_fast"):
        cli.main(["--preset", "gnn_fast", "--env", "cluster_set",
                  "--run-root", str(tmp_path)])
