"""DryRunPodPlacer against a fake kubernetes API (SURVEY.md §4: "a fake
k8s API server (or recorded responses) for the extender").

The ``kubernetes`` package is not installed in CI, which is itself the
first case to cover (slow mode must degrade to a warning no-op, never
crash the serving path). The remaining cases inject a stub ``kubernetes``
module into ``sys.modules`` and assert the wire-level facts the reference
relied on: context-name fallback (the reference's hardcoded ``kind-aws``
lookup always failed — SURVEY.md §7.0), ``dry_run="All"`` on pod
creation, bounded request timeouts, and fail-soft error reporting.
"""

import sys
import types

import pytest


def _purge_placer_modules():
    for name in list(sys.modules):
        if name == "kubernetes" or name.startswith("kubernetes."):
            del sys.modules[name]
    sys.modules.pop("rl_scheduler_tpu.scheduler.k8s_client", None)


@pytest.fixture()
def fake_kubernetes(monkeypatch):
    """A minimal stand-in for the kubernetes client package: records every
    create_namespaced_pod call; only the reference's REAL context names
    (kind-kind-*) resolve, mirroring the kind-prefix behavior."""
    calls = []

    class FakeV1Api:
        def __init__(self, api_client=None):
            self.api_client = api_client

        def create_namespaced_pod(self, namespace, body, dry_run=None,
                                  _request_timeout=None):
            if getattr(body.metadata, "explode", False):
                raise RuntimeError("simulated API failure")
            calls.append({
                "namespace": namespace,
                "pod_name": body.metadata.name,
                "dry_run": dry_run,
                "timeout": _request_timeout,
                "context": self.api_client,
            })

    class _Meta:
        def __init__(self, name):
            self.name = name
            self.explode = False

    client_mod = types.SimpleNamespace(
        CoreV1Api=FakeV1Api,
        V1Pod=lambda metadata, spec: types.SimpleNamespace(
            metadata=metadata, spec=spec),
        V1ObjectMeta=lambda name: _Meta(name),
        V1PodSpec=lambda containers: types.SimpleNamespace(
            containers=containers),
        V1Container=lambda name, image: types.SimpleNamespace(
            name=name, image=image),
    )

    def new_client_from_config(context=None):
        if context not in ("kind-kind-aws", "kind-kind-azure"):
            raise RuntimeError(f"context {context!r} not in kubeconfig")
        return context

    config_mod = types.SimpleNamespace(
        new_client_from_config=new_client_from_config)
    pkg = types.ModuleType("kubernetes")
    pkg.client = client_mod
    pkg.config = config_mod
    _purge_placer_modules()
    monkeypatch.setitem(sys.modules, "kubernetes", pkg)
    monkeypatch.setitem(sys.modules, "kubernetes.client", client_mod)
    monkeypatch.setitem(sys.modules, "kubernetes.config", config_mod)
    yield calls
    _purge_placer_modules()


def test_placer_is_noop_without_kubernetes_package(monkeypatch):
    """No kubernetes package (the CI reality): construction succeeds,
    place() returns False — slow mode degrades, serving never crashes.
    The ImportError is forced (sys.modules[name] = None makes the import
    raise) so the branch under test is deterministic even on machines
    that DO have the package + a live kubeconfig."""
    _purge_placer_modules()
    monkeypatch.setitem(sys.modules, "kubernetes", None)
    from rl_scheduler_tpu.scheduler.k8s_client import DryRunPodPlacer

    placer = DryRunPodPlacer()
    assert placer.place("aws") is False
    assert placer.place("nonsense") is False


def test_placer_dry_runs_pods_against_fake_api(fake_kubernetes):
    from rl_scheduler_tpu.scheduler.k8s_client import DryRunPodPlacer

    placer = DryRunPodPlacer(namespace="default")
    # Context fallback found the kind-prefixed names for both clouds.
    assert placer.place("aws") is True
    assert placer.place("azure") is True
    assert [c["context"] for c in fake_kubernetes] == [
        "kind-kind-aws", "kind-kind-azure",
    ]
    call = fake_kubernetes[0]
    assert call["dry_run"] == "All"          # reference parity: never
    assert call["namespace"] == "default"    # actually schedules anything
    assert call["pod_name"].startswith("rl-pod-")
    # Bounded timeouts: a stalled kube API must not wedge AsyncPlacer.
    assert call["timeout"] is not None and call["timeout"][1] > 0


def test_placer_reports_api_failure_fail_soft(fake_kubernetes):
    from rl_scheduler_tpu.scheduler import k8s_client

    placer = k8s_client.DryRunPodPlacer()

    real_meta = sys.modules["kubernetes"].client.V1ObjectMeta

    def exploding_meta(name):
        meta = real_meta(name)
        meta.explode = True
        return meta

    sys.modules["kubernetes"].client.V1ObjectMeta = exploding_meta
    try:
        assert placer.place("aws") is False  # surfaced, not raised
    finally:
        sys.modules["kubernetes"].client.V1ObjectMeta = real_meta
    assert not fake_kubernetes  # nothing recorded for the failed create
    assert placer.place("unknown-cloud") is False  # no client for cloud
