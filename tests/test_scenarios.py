"""graftscenario tests: the workload-scenario subsystem (docs/scenarios.md).

Covers the subsystem's contracts layer by layer:

- packaging: ``rl_scheduler_tpu.scenarios`` is a REAL package (the seed
  shipped a ``__pycache__``-only directory — a namespace-package trap
  where stale ``.pyc`` names looked importable and nothing was).
- per-family determinism: same ``(family, knobs, seed)`` ⇒ bitwise-
  identical compiled tables; different seed ⇒ different tables.
- vmap/jit parity: a batched ``reset_batch``/``step_batch`` scenario draw
  equals the single-env functions applied per key.
- churn-mask reward invariants: an all-ones mask is a bitwise no-op; a
  down node costs exactly ``reward_scale * churn_penalty`` extra.
- per-episode randomization: the domain-randomized fields re-draw per
  episode from the env's own keys; the legacy path keeps its values.
- CLI round-trip: a scenario trained through the REAL train_ppo CLI pins
  its scenario meta through checkpoint save → evaluate rebuild → resume
  guards.
- serving conformance: the extender serves a scenario-trained checkpoint
  end-to-end over HTTP and refuses a mismatched --scenario demand.
"""

from __future__ import annotations

import json
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.env import cluster_set as cs
from rl_scheduler_tpu.scenarios import (
    FAMILIES,
    SCENARIOS,
    Scenario,
    baseline_columns,
    cloud_table,
    cluster_set_params,
    get_scenario,
    list_scenarios,
    node_feat_for,
    raw_prices,
    scenario_bundle,
    scenario_meta,
)
from rl_scheduler_tpu.scenarios import het_env
from rl_scheduler_tpu.scenarios.families import (
    bursty_diurnal_tables,
    churn_mask,
    heterogeneous_capacities,
    price_spike_tables,
)


# ------------------------------------------------------------- packaging


def test_scenarios_is_a_real_package():
    """The seed's scenarios/ held only a __pycache__: importable as an
    empty namespace package, submodules dead. A real package has
    __file__ and its registry populated."""
    import rl_scheduler_tpu.scenarios as pkg

    assert pkg.__file__ is not None and pkg.__file__.endswith("__init__.py")
    assert set(SCENARIOS) == {"bursty", "heterogeneous", "churn",
                              "price_spike", "randomized"}
    # trace_replay (graftloop) and external_trace (graftmix) are
    # name-built (trace_replay:<snapshot> /
    # external_trace:<dir>?format=...), never registry presets —
    # FAMILIES grows, SCENARIOS does not.
    assert len(FAMILIES) == 7
    assert "trace_replay" in FAMILIES
    assert "external_trace" in FAMILIES


def test_stale_pycache_modules_do_not_import():
    # The orphaned .pyc names from the seed's stale __pycache__ must not
    # resolve (sourceless bytecode inside __pycache__ is not importable).
    for phantom in ("distribution", "gauntlet", "randomize"):
        with pytest.raises(ImportError):
            __import__(f"rl_scheduler_tpu.scenarios.{phantom}")


# ----------------------------------------------------------- determinism


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scenario_params_bitwise_deterministic(name):
    a = cluster_set_params(get_scenario(name), num_nodes=8)
    b = cluster_set_params(get_scenario(name), num_nodes=8)
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.array_equal(np.asarray(la), np.asarray(lb))


def test_generators_reseed_differently():
    t0 = bursty_diurnal_tables(steps=50, seed=0)
    t1 = bursty_diurnal_tables(steps=50, seed=1)
    assert not np.array_equal(t0["costs"], t1["costs"])
    m0 = churn_mask(steps=50, num_nodes=6, seed=0)
    m1 = churn_mask(steps=50, num_nodes=6, seed=1)
    assert m0.shape == (50, 6) and not np.array_equal(m0, m1)
    p0 = price_spike_tables(steps=50, seed=0)
    p1 = price_spike_tables(steps=50, seed=3)
    assert not np.array_equal(p0["raw_prices"], p1["raw_prices"])
    c0 = heterogeneous_capacities(8, 3, seed=0)
    c1 = heterogeneous_capacities(8, 3, seed=9)
    assert not np.array_equal(c0, c1)


def test_churn_mask_uses_faultplan_stream_and_never_goes_dark():
    mask = churn_mask(steps=99, num_nodes=8, seed=7, preempt_rate=0.2,
                      drain_steps=5)
    assert mask.min() == 0.0  # the rate actually fired
    assert (mask.sum(axis=1) >= 1.0).all()  # >= one node up per step
    # Byte-reproducible from (seed, rate): the FaultPlan stream contract.
    assert np.array_equal(
        mask, churn_mask(steps=99, num_nodes=8, seed=7, preempt_rate=0.2,
                         drain_steps=5))


def test_price_spike_raw_prices_spike_and_normalize():
    t = price_spike_tables(steps=100, seed=0, spike_prob=0.1, spike_mult=4.0)
    raw = t["raw_prices"]
    assert raw.max() > 2.0 * np.median(raw)  # regimes actually spike
    assert t["costs"].min() >= 0.0 and t["costs"].max() <= 1.0


def test_cloud_table_and_raw_prices_family_gating():
    assert cloud_table(get_scenario("bursty")).costs.shape[1] == 2
    assert raw_prices(get_scenario("price_spike")).shape[1] == 2
    with pytest.raises(ValueError):
        cloud_table(get_scenario("churn"))
    with pytest.raises(ValueError):
        raw_prices(get_scenario("bursty"))


def test_scenario_spec_validation():
    with pytest.raises(ValueError):
        Scenario(name="x", family="not_a_family")
    with pytest.raises(ValueError):
        get_scenario("nope")
    s = get_scenario("bursty", seed=11)
    assert s.seed == 11 and s.knob("period") == 24.0
    meta = scenario_meta(s)
    assert meta["scenario"] == "bursty" and meta["node_feat"] == 6
    assert node_feat_for(get_scenario("heterogeneous")) == 13
    assert baseline_columns(s) == {"cost": 0, "cpu": 2}
    assert list_scenarios() == sorted(SCENARIOS)


# ------------------------------------------------------ vmap/jit parity


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_batched_scenario_draws_match_single_env(name):
    """reset_batch/step_batch (the fleet path) == the single-env pure
    functions per key — vmap must not change any scenario draw."""
    scn = get_scenario(name)
    params = cluster_set_params(scn, num_nodes=8)
    bundle = scenario_bundle(scn, num_nodes=8)
    env = het_env if name == "heterogeneous" else cs

    key = jax.random.PRNGKey(3)
    keys = jax.random.split(key, 4)
    bstate, bobs = bundle.reset_batch(key, 4)
    actions = jnp.arange(4, dtype=jnp.int32) % 8
    bstate2, bts = bundle.step_batch(bstate, actions)
    for i in range(4):
        sstate, sobs = env.reset(params, keys[i])
        np.testing.assert_array_equal(np.asarray(bobs[i]), np.asarray(sobs))
        _, sts = env.step(params, sstate, actions[i])
        np.testing.assert_array_equal(np.asarray(bts.reward[i]),
                                      np.asarray(sts.reward))


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scenario_trains_one_ppo_update(name):
    """Every family runs through the real jitted PPO update (the fleet
    path acceptance: scenario envs are a drop-in for the CSV replay)."""
    from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, make_ppo_bundle
    from rl_scheduler_tpu.models import SetTransformerPolicy

    bundle = scenario_bundle(get_scenario(name), num_nodes=4)
    cfg = PPOTrainConfig(num_envs=4, rollout_steps=8, minibatch_size=32,
                         num_epochs=1)
    init_fn, update_fn, _ = make_ppo_bundle(
        bundle, cfg, net=SetTransformerPolicy(dim=16, depth=1))
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    runner, metrics = jax.jit(update_fn)(runner)
    assert np.isfinite(float(metrics["reward_mean"]))


# ------------------------------------------------- churn reward invariants


def test_churn_all_ones_mask_is_bitwise_noop():
    base = cs.make_params(num_nodes=6)
    ones = cs.make_params(
        num_nodes=6,
        avail_mask=np.ones((base.costs.shape[0], 6), np.float32),
        churn_penalty=5.0)
    key = jax.random.PRNGKey(0)
    s0, o0 = cs.reset(base, key)
    s1, o1 = cs.reset(ones, key)
    np.testing.assert_array_equal(np.asarray(o0), np.asarray(o1))
    for t in range(5):
        a = jnp.asarray(t % 6)
        s0, ts0 = cs.step(base, s0, a)
        s1, ts1 = cs.step(ones, s1, a)
        np.testing.assert_array_equal(np.asarray(ts0.reward),
                                      np.asarray(ts1.reward))
        np.testing.assert_array_equal(np.asarray(ts0.obs),
                                      np.asarray(ts1.obs))


def test_churn_down_node_pays_exact_penalty_and_observes_saturated():
    t_rows = cs.make_params(num_nodes=4).costs.shape[0]
    mask = np.ones((t_rows, 4), np.float32)
    mask[0, 2] = 0.0  # node 2 down at row 0
    up = cs.make_params(num_nodes=4,
                        avail_mask=np.ones((t_rows, 4), np.float32),
                        churn_penalty=3.0)
    down = cs.make_params(num_nodes=4, avail_mask=mask, churn_penalty=3.0)
    key = jax.random.PRNGKey(1)
    su, ou = cs.reset(up, key)
    sd, od = cs.reset(down, key)
    # Down node observes maximally expensive/slow/loaded...
    np.testing.assert_array_equal(np.asarray(od[2, :3]), [1.0, 1.0, 1.0])
    # ...and placing on it costs exactly reward_scale * churn_penalty more.
    _, ts_u = cs.step(up, su, jnp.asarray(2))
    _, ts_d = cs.step(down, sd, jnp.asarray(2))
    delta = float(ts_u.reward) - float(ts_d.reward)
    assert delta == pytest.approx(float(up.reward_scale) * 3.0, rel=1e-5)
    # An up node at the same row is unaffected.
    _, ts_u0 = cs.step(up, su, jnp.asarray(0))
    _, ts_d0 = cs.step(down, sd, jnp.asarray(0))
    np.testing.assert_array_equal(np.asarray(ts_u0.reward),
                                  np.asarray(ts_d0.reward))


# ------------------------------------------- per-episode randomization


def test_per_episode_randomization_redraws_and_legacy_keeps_statics():
    rand = cs.make_params(num_nodes=4, jitter_range=(0.0, 0.5),
                          drain_range=(0.5, 0.99),
                          overload_range=(1.0, 4.0), random_phase=True)
    s1, _ = cs.reset(rand, jax.random.PRNGKey(0))
    s2, _ = cs.reset(rand, jax.random.PRNGKey(1))
    assert float(s1.ep_drain) != float(s2.ep_drain)
    assert float(s1.ep_overload) != float(s2.ep_overload)
    assert int(s1.phase) != int(s2.phase)
    lo, hi = 0.5, 0.99
    assert lo <= float(s1.ep_drain) <= hi
    # Legacy params: the per-episode fields carry the static values.
    legacy = cs.make_params(num_nodes=4)
    s, _ = cs.reset(legacy, jax.random.PRNGKey(0))
    assert float(s.ep_drain) == float(legacy.drain_rate)
    assert float(s.ep_overload) == float(legacy.overload_penalty)
    assert int(s.phase) == 0


def test_random_phase_shifts_table_replay():
    rand = cs.make_params(num_nodes=4, random_phase=True)
    # Two different episode keys land on different table rows at t=0.
    obs = [np.asarray(cs.reset(rand, jax.random.PRNGKey(k))[1])
           for k in range(6)]
    costs_at_t0 = {round(float(o[:, 0].mean()), 6) for o in obs}
    assert len(costs_at_t0) > 1


def test_multi_cloud_random_start_disables_open_loop():
    from rl_scheduler_tpu.env import core as env_core
    from rl_scheduler_tpu.env.bundle import multi_cloud_bundle

    params = env_core.make_params()
    plain = multi_cloud_bundle(params)
    assert plain.horizon_fn is not None
    randomized = multi_cloud_bundle(params, random_start=True)
    assert randomized.horizon_fn is None  # falls back to the scan rollout
    # reset_random_start actually draws different starting rows — and
    # stays jit/vmap-safe with params passed as a traced ARGUMENT (the
    # regression shape: a flag leaf in the params pytree would trace).
    starts = {
        int(env_core.reset_random_start(params,
                                        jax.random.PRNGKey(k))[0].step_idx)
        for k in range(8)
    }
    assert len(starts) > 1
    state, obs = jax.jit(env_core.reset_random_start)(
        params, jax.random.PRNGKey(0))
    assert obs.shape == (env_core.OBS_DIM,)
    # The batched randomized bundle draws per-env phases.
    bstate, _ = randomized.reset_batch(jax.random.PRNGKey(0), 16)
    assert len(set(np.asarray(bstate.step_idx).tolist())) > 1


def test_bursty_pod_scale_modulates_arrivals():
    scn = get_scenario("bursty")
    params = cluster_set_params(scn, num_nodes=4)
    assert params.pod_scale is not None
    t = bursty_diurnal_tables(steps=scn.steps, seed=scn.seed)
    assert t["pod_scale"].min() < t["pod_scale"].max()
    # Pods drawn at a high-intensity row are larger than the same draw at
    # a low-intensity row (the scale multiplies the same uniform draw).
    hi_row = int(np.argmax(t["pod_scale"]))
    lo_row = int(np.argmin(t["pod_scale"]))
    key = jax.random.PRNGKey(0)
    hi = cs._draw_pod(params, key, jnp.asarray(hi_row))
    lo = cs._draw_pod(params, key, jnp.asarray(lo_row))
    assert float(hi) > float(lo)


# ------------------------------------------------------ heterogeneous env


def test_het_env_shapes_and_feature_layout():
    params = het_env.make_params(num_nodes=6, num_resources=3, seed=0)
    assert isinstance(params, het_env.HetSetParams)
    assert params.node_feat == het_env.node_feat(3) == 13
    state, obs = het_env.reset(params, jax.random.PRNGKey(0))
    assert isinstance(state, het_env.HetSetState)
    assert obs.shape == (6, 13)
    _, ts = het_env.step(params, state, jnp.asarray(0))
    assert isinstance(ts, het_env.TimeStep) and ts.obs.shape == (6, 13)
    # Columns 2+R..2+2R are the static capacities.
    np.testing.assert_allclose(np.asarray(obs[:, 5:8]),
                               np.asarray(params.capacity), rtol=1e-6)
    assert het_env.RESOURCES == ("cpu", "mem", "acc")
    b = het_env.het_bundle(params)
    assert b.obs_shape == (6, 13) and b.name == "cluster_set_het"


def test_het_accelerator_bin_packing_pressure():
    """Placing an accelerator-requesting pod on an accelerator-less node
    must be punished dramatically harder than on an accelerator node —
    the bin-packing signal this family exists to create."""
    params = het_env.make_params(num_nodes=8, num_resources=3, seed=0,
                                 acc_node_frac=0.5)
    caps = np.asarray(params.capacity)
    acc_node = int(np.argmax(caps[:, 2]))
    no_acc_node = int(np.argmin(caps[:, 2]))
    assert caps[acc_node, 2] > 0.9 and caps[no_acc_node, 2] < 0.1
    state, _ = het_env.reset(params, jax.random.PRNGKey(0))
    state = state._replace(pod_req=jnp.asarray([0.1, 0.1, 0.5], jnp.float32))
    _, ts_acc = het_env.step(params, state, jnp.asarray(acc_node))
    _, ts_no = het_env.step(params, state, jnp.asarray(no_acc_node))
    assert float(ts_no.reward) < 5 * float(ts_acc.reward)  # rewards < 0


def test_het_requests_gate_accelerator():
    params = het_env.make_params(num_nodes=4, num_resources=3, seed=0,
                                 acc_request_prob=0.3)
    reqs = np.stack([
        np.asarray(het_env._draw_req(params, jax.random.PRNGKey(k)))
        for k in range(64)
    ])
    assert (reqs[:, :2] > 0).all()          # cpu/mem always requested
    zero_acc = (reqs[:, 2] == 0).mean()
    assert 0.3 < zero_acc < 0.95            # acc mostly absent, sometimes big


def test_het_determinism_same_seed_same_capacities():
    a = het_env.make_params(num_nodes=8, seed=4)
    b = het_env.make_params(num_nodes=8, seed=4)
    np.testing.assert_array_equal(np.asarray(a.capacity),
                                  np.asarray(b.capacity))


# --------------------------------------------------------- eval matrix


def test_scenario_policy_matrix_cells_and_summary():
    from rl_scheduler_tpu.agent.evaluate import (
        matrix_summary,
        scenario_policy_matrix,
    )

    rows = scenario_policy_matrix(["csv", "churn"], num_nodes=4,
                                  episodes=2, seed=0)
    assert len(rows) == 6  # 2 scenarios x 3 baseline policies
    for r in rows:
        assert r["schema_version"] == 1
        assert r["metric"] == "scenario_matrix_cell"
        assert np.isfinite(r["reward_mean"])
    grid = matrix_summary(rows)
    assert "csv" in grid and "churn" in grid and "cheapest_node" in grid


def test_matrix_checkpoint_width_mismatch_is_reported_not_scored():
    from rl_scheduler_tpu.agent.evaluate import scenario_policy_matrix
    from rl_scheduler_tpu.models import SetTransformerPolicy

    net = SetTransformerPolicy(dim=16, depth=1)
    params = net.init(jax.random.PRNGKey(0), jnp.zeros((1, 4, 6)))
    rows = scenario_policy_matrix(
        ["heterogeneous"], num_nodes=4, episodes=2,
        checkpoint=(net, params, 6))
    cell = next(r for r in rows if r["policy"] == "checkpoint")
    assert cell["incompatible"] is True and "reward_mean" not in cell


def test_structured_baselines_column_override():
    from rl_scheduler_tpu.env.baselines import structured_baselines

    fns = structured_baselines("cluster_set", columns={"cost": 1, "cpu": 0})
    obs = jnp.asarray([[[0.9, 0.1, 0.5], [0.1, 0.9, 0.2]]])
    # cost col overridden to 1: node 0 (0.1) is "cheapest".
    assert int(fns["cheapest_node"](obs, None)[0]) == 0
    assert int(fns["load_spread"](obs, None)[0]) == 1


# --------------------------------------- CLI round-trip + serving (HTTP)


@pytest.fixture(scope="module")
def churn_run(tmp_path_factory):
    """One tiny scenario run through the REAL train_ppo CLI, shared by
    the round-trip, evaluate, and serving tests."""
    from rl_scheduler_tpu.agent import train_ppo

    root = tmp_path_factory.mktemp("scn_cli")
    run_dir = train_ppo.main([
        "--scenario", "churn", "--scenario-seed", "3",
        "--preset", "quick", "--num-envs", "4", "--rollout-steps", "8",
        "--minibatch-size", "32", "--iterations", "1",
        "--run-name", "CHURN", "--run-root", str(root),
    ])
    return run_dir


def test_cli_records_scenario_meta(churn_run):
    from rl_scheduler_tpu.utils.checkpoint import load_policy_params

    _, meta = load_policy_params(churn_run)
    assert meta["scenario"] == "churn"
    assert meta["scenario_seed"] == 3
    assert meta["scenario_family"] == "churn"
    assert meta["node_feat"] == 6
    assert meta["env"] == "cluster_set"


def test_cli_resume_guards_pin_scenario(churn_run):
    from rl_scheduler_tpu.agent import train_ppo

    base = ["--preset", "quick", "--num-envs", "4", "--rollout-steps", "8",
            "--minibatch-size", "32", "--iterations", "2",
            "--run-name", "CHURN", "--run-root", str(churn_run.parent),
            "--resume"]
    with pytest.raises(SystemExit, match="scenario"):
        train_ppo.main(base)  # CSV resume of a scenario run
    with pytest.raises(SystemExit, match="scenario"):
        train_ppo.main(base + ["--scenario", "bursty"])
    with pytest.raises(SystemExit, match="scenario-seed"):
        train_ppo.main(base + ["--scenario", "churn", "--scenario-seed", "9"])


def test_evaluate_rebuilds_scenario_from_meta(churn_run, tmp_path, capsys):
    from rl_scheduler_tpu.agent import evaluate

    report = evaluate.main(["--run", str(churn_run), "--episodes", "2",
                            "--results-dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert "Rebuilding scenario 'churn'" in out
    assert report.env == "cluster_set"
    assert np.isfinite(report.avg_episode_reward)


def test_extender_serves_scenario_checkpoint_over_http(churn_run):
    """Acceptance: a scenario-trained checkpoint serves end-to-end over
    the real HTTP extender, and the conformance demand works both ways."""
    from rl_scheduler_tpu.scheduler.extender import build_policy, make_server

    with pytest.raises(ValueError, match="scenario"):
        build_policy(backend="cpu", run=str(churn_run),
                     scenario="heterogeneous")
    policy = build_policy(backend="cpu", run=str(churn_run),
                          scenario="churn")
    assert policy.scenario == "churn" and policy.family == "set"
    server = make_server(policy, "127.0.0.1", 0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        payload = json.dumps({
            "pod": {"metadata": {"name": "p"}},
            "nodenames": ["aws-1", "aws-2", "azure-1"],
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/filter", payload,
            {"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req, timeout=10).read())
        assert len(out["nodenames"]) == 1
        assert len(out["failedNodes"]) == 2
        hz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        assert hz["scenario"] == "churn"
    finally:
        server.shutdown()


def test_extender_het_observation_and_pod_parsing():
    """The widened serving path: multi-resource pod parsing + the het
    observation builder match the training layout without a checkpoint."""
    from rl_scheduler_tpu.scheduler.extender import pod_resource_fractions
    from rl_scheduler_tpu.scheduler.telemetry import RandomCpu, TableTelemetry

    pod = {"spec": {"containers": [{"resources": {"requests": {
        "cpu": "2", "memory": "4Gi", "nvidia.com/gpu": "1"}}}]}}
    cpu, mem, acc = pod_resource_fractions(pod)
    assert cpu == pytest.approx(0.5)       # 2 cores / 4
    assert mem == pytest.approx(0.25)      # 4Gi / 16Gi
    assert acc == pytest.approx(1.0)
    # Fail-open on junk manifests: the training-distribution defaults.
    assert pod_resource_fractions({"spec": {"containers": [
        {"resources": {"requests": {"memory": "lots"}}}]}})[1] == 0.15
    tele = TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))
    rows = tele.observe_nodes_het(["aws", "azure", None], [cpu, mem, acc], 3)
    assert rows.shape == (3, 13)
    np.testing.assert_allclose(rows[:, 5:8], 1.0)        # neutral caps
    np.testing.assert_allclose(rows[0, 9:12], [0.5, 0.25, 1.0])


def test_scenario_bench_functions_exist_and_run_tiny():
    """The bench entry points compile and measure at a toy size (the
    checked-in BENCH_scenario JSON is the real container measurement)."""
    import bench

    out = bench.scenario_env_step_bench(num_nodes=4, num_envs=4, steps=5,
                                        repeats=1)
    assert out["schema_version"] == 1
    # graftmix: the mixture variant rides every scenario bench beside
    # the per-family rows (same interleaved methodology, same bar).
    assert set(out["scenarios"]) == set(SCENARIOS) | {"mixture"}
    for cell in out["scenarios"].values():
        assert cell["steps_per_sec"] > 0
