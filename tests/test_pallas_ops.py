"""Pallas kernel equivalence tests (interpret mode on the CPU test platform).

The pallas GAE kernel must match the lax.scan reference implementation
bit-for-bit in f32 — it is swapped in automatically on TPU (`impl="auto"`),
so any divergence would silently change training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.ops.gae import gae
from rl_scheduler_tpu.ops.pallas_gae import gae_pallas


def _random_rollout(rng, t, n):
    rewards = jnp.asarray(rng.randn(t, n), jnp.float32)
    values = jnp.asarray(rng.randn(t, n), jnp.float32)
    dones = jnp.asarray(rng.rand(t, n) < 0.1, jnp.float32)
    last_value = jnp.asarray(rng.randn(n), jnp.float32)
    return rewards, values, dones, last_value


@pytest.mark.parametrize(
    "t,n",
    [
        (100, 512),  # exact block multiple (bench shape per column block)
        (100, 37),   # padding path: N not a lane/block multiple
        (7, 512),    # short rollout
        (1, 4),      # degenerate single step, heavy padding
    ],
)
def test_pallas_gae_matches_scan(rng, t, n):
    args = _random_rollout(rng, t, n)
    adv_ref, tgt_ref = gae(*args, gamma=0.99, lam=0.95, impl="scan")
    adv_pl, tgt_pl = gae_pallas(*args, gamma=0.99, lam=0.95)
    np.testing.assert_allclose(adv_pl, adv_ref, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(tgt_pl, tgt_ref, rtol=1e-6, atol=1e-6)


def test_gae_impl_dispatch(rng):
    args = _random_rollout(rng, 10, 8)
    adv_scan, _ = gae(*args, gamma=0.9, lam=1.0, impl="scan")
    adv_pl, _ = gae(*args, gamma=0.9, lam=1.0, impl="pallas")
    np.testing.assert_allclose(adv_pl, adv_scan, rtol=1e-6, atol=1e-6)
    with pytest.raises(ValueError):
        gae(*args, gamma=0.9, lam=1.0, impl="nope")


def test_pallas_gae_respects_done_boundaries(rng):
    """A done at step t must cut the bootstrap: steps <= t are unaffected
    by anything after t."""
    t, n = 20, 8
    rewards, values, dones, last_value = _random_rollout(rng, t, n)
    dones = dones.at[10].set(1.0)
    adv_a, _ = gae_pallas(rewards, values, dones, last_value, 0.99, 0.95)
    # Perturb the future: everything strictly after the done row.
    adv_b, _ = gae_pallas(
        rewards.at[11:].add(100.0), values, dones, last_value + 5.0, 0.99, 0.95
    )
    np.testing.assert_allclose(adv_a[:11], adv_b[:11], rtol=1e-6, atol=1e-6)
    assert not np.allclose(adv_a[11:], adv_b[11:])


def test_ppo_update_with_pallas_gae():
    """The full fused PPO update runs with the pallas GAE path wired in and
    matches the scan path's metrics on identical seeds."""
    from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, make_ppo
    from rl_scheduler_tpu.config import EnvConfig
    from rl_scheduler_tpu.env import core as env_core

    env_params = env_core.make_params(EnvConfig())
    metrics_by_impl = {}
    for impl in ("scan", "pallas"):
        cfg = PPOTrainConfig(
            num_envs=8, rollout_steps=16, minibatch_size=32,
            num_epochs=2, hidden=(16,), gae_impl=impl,
        )
        init_fn, update_fn, _ = make_ppo(env_params, cfg)
        runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
        _, metrics = jax.jit(update_fn)(runner)
        metrics_by_impl[impl] = {k: float(v) for k, v in metrics.items()}
    for key, val in metrics_by_impl["scan"].items():
        assert np.isfinite(val)
        np.testing.assert_allclose(
            metrics_by_impl["pallas"][key], val, rtol=1e-4, atol=1e-5, err_msg=key
        )
