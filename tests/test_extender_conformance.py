"""Protocol conformance against Go-marshaled kube-scheduler payloads.

VERDICT r4 item 8: the HTTP tests elsewhere hand-build minimal payloads;
this module drives ``/filter`` and ``/prioritize`` over a fixture corpus
shaped exactly like what a real kube-scheduler marshals
(``tests/fixtures/extender/*.json``):

- ``v1_full_nodes.json`` — modern ``k8s.io/kube-scheduler/extender/v1``
  ``ExtenderArgs`` (lowercase ``pod``/``nodes`` json tags) with FULL
  ``v1.Node`` objects: status.nodeInfo, conditions, addresses, taints,
  capacity/allocatable, images — everything a ``NodeList`` carries.
- ``v1_nodecache_names.json`` — the ``nodeCacheCapable: true`` form
  (``nodenames`` list, no node objects), which
  ``k8s_manifests/scheduler-config.yaml`` enables.
- ``legacy_caps_full_nodes.json`` — the pre-1.17 in-tree extender API
  marshaled ``Pod``/``Nodes``/``NodeNames`` WITHOUT json tags
  (capitalized Go field names); includes an unknown-cloud edge node and
  the graph family's affinity annotation.
- ``v1_minimal_pod.json`` — a BestEffort pod with empty ``resources``
  over name-only candidates.

Responses are checked for Go-unmarshal compatibility: every input node
accounted for (kept + failedNodes), response form matching the request
form (node objects in, node objects out; names in, names out),
``HostPriorityList`` entries with integer 0-100 scores, and key sets
that unmarshal into ``ExtenderFilterResult``/``HostPriority`` (Go's
``encoding/json`` matches field names case-insensitively).
"""

import json
import pathlib

import jax
import jax.numpy as jnp
import pytest

from rl_scheduler_tpu.scheduler.extender import ExtenderPolicy, make_server
from rl_scheduler_tpu.scheduler.policy_backend import GreedyBackend
from rl_scheduler_tpu.scheduler.telemetry import RandomCpu, TableTelemetry

FIXTURES = sorted(
    (pathlib.Path(__file__).parent / "fixtures" / "extender").glob("*.json")
)
FILTER_RESULT_FIELDS = {"nodes", "nodenames", "failednodes",
                        "failedandunresolvablenodes", "error"}


def _load(path):
    return json.loads(path.read_text())


def _normalized(payload):
    # The HTTP layer lowercases top-level keys (be-liberal normalization);
    # mirror it here so fixtures can drive ExtenderPolicy directly too.
    return {k.lower(): v for k, v in payload.items()}


def _input_names(payload):
    args = _normalized(payload)
    if args.get("nodenames") is not None:
        return list(args["nodenames"])
    return [n["metadata"]["name"] for n in args["nodes"]["items"]]


@pytest.fixture(scope="module")
def flat_policy():
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))
    return ExtenderPolicy(GreedyBackend(), telemetry)


@pytest.fixture(scope="module")
def set_policy():
    from rl_scheduler_tpu.models.transformer import SetTransformerPolicy
    from rl_scheduler_tpu.scheduler.set_backend import NumpySetBackend

    net = SetTransformerPolicy(dim=64, depth=2)
    tree = net.init(jax.random.PRNGKey(11), jnp.zeros((8, 6), jnp.float32))
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=1))
    return ExtenderPolicy(NumpySetBackend(tree), telemetry)


def test_fixture_corpus_exists():
    assert len(FIXTURES) >= 4, [p.name for p in FIXTURES]


@pytest.mark.parametrize("fixture", FIXTURES, ids=lambda p: p.stem)
@pytest.mark.parametrize("family", ["flat", "set"])
def test_filter_conformance(fixture, family, flat_policy, set_policy,
                            request):
    policy = flat_policy if family == "flat" else set_policy
    payload = _load(fixture)
    names = _input_names(payload)
    result = policy.filter(_normalized(payload))

    # Go-unmarshal compatibility: keys map onto ExtenderFilterResult
    # fields (case-insensitive, as encoding/json matches them).
    assert {k.lower() for k in result} <= FILTER_RESULT_FIELDS
    assert result["error"] == ""  # non-empty Error = hard scheduler failure

    # Response form mirrors the request form.
    if _normalized(payload).get("nodenames") is not None:
        kept = result["nodenames"]
        assert all(isinstance(n, str) for n in kept)
    else:
        items = result["nodes"]["items"]
        kept = [n["metadata"]["name"] for n in items]
        # Node objects pass through intact (kube-scheduler reuses them).
        by_name = {n["metadata"]["name"]: n
                   for n in _normalized(payload)["nodes"]["items"]}
        for item in items:
            assert item == by_name[item["metadata"]["name"]]

    failed = result.get("failedNodes", {})
    assert all(isinstance(k, str) and isinstance(v, str)
               for k, v in failed.items())
    # Every candidate accounted for exactly once; kept is a subset of
    # the input and at least one node always survives (fail-open).
    assert set(kept) | set(failed) == set(names)
    assert not set(kept) & set(failed)
    assert len(kept) >= 1


@pytest.mark.parametrize("fixture", FIXTURES, ids=lambda p: p.stem)
@pytest.mark.parametrize("family", ["flat", "set"])
def test_prioritize_conformance(fixture, family, flat_policy, set_policy):
    policy = flat_policy if family == "flat" else set_policy
    payload = _load(fixture)
    names = _input_names(payload)
    out = policy.prioritize(_normalized(payload))

    assert [e["host"] for e in out] == names  # one entry per candidate
    for entry in out:
        # HostPriority{Host, Score}: int64 score; kube-scheduler expects
        # 0..MaxExtenderPriority (100).
        assert {k.lower() for k in entry} == {"host", "score"}
        assert isinstance(entry["score"], int)
        assert 0 <= entry["score"] <= 100
    assert max(e["score"] for e in out) > 0


def test_http_roundtrip_over_corpus(set_policy):
    """The corpus through the real HTTP server: the Go-marshaled bytes on
    the wire (capitalization included) produce protocol-valid responses."""
    import threading
    import urllib.request

    srv = make_server(set_policy, host="127.0.0.1", port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        port = srv.server_address[1]
        for fixture in FIXTURES:
            body = fixture.read_bytes()
            for path in ("/filter", "/prioritize"):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}{path}", data=body,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=10) as resp:
                    assert resp.status == 200
                    out = json.load(resp)
            assert isinstance(out, list) and len(out) == len(
                _input_names(_load(fixture)))
    finally:
        srv.shutdown()
