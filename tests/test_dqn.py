"""DQN trainer: replay buffer semantics, update mechanics, learning smoke."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.agent.dqn import (
    DQNConfig,
    buffer_add,
    buffer_init,
    buffer_sample,
    dqn_train,
    epsilon_by_step,
    make_dqn,
)
from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.env.bundle import multi_cloud_bundle, single_cluster_bundle


def _batch(n, obs_dim=3, base=0.0):
    return {
        "obs": jnp.full((n, obs_dim), base, jnp.float32),
        "action": jnp.arange(n, dtype=jnp.int32) % 2,
        "reward": base + jnp.arange(n, dtype=jnp.float32),
        "done": jnp.zeros(n, jnp.float32),
        "next_obs": jnp.full((n, obs_dim), base + 1.0, jnp.float32),
    }


class TestReplayBuffer:
    def test_add_and_size(self):
        buf = buffer_init(8, (3,))
        buf = buffer_add(buf, _batch(4))
        assert int(buf.size) == 4 and int(buf.pos) == 4
        buf = buffer_add(buf, _batch(4, base=10.0))
        assert int(buf.size) == 8 and int(buf.pos) == 0

    def test_circular_overwrite(self):
        buf = buffer_init(4, (3,))
        buf = buffer_add(buf, _batch(4, base=0.0))
        buf = buffer_add(buf, _batch(2, base=100.0))
        # Oldest two entries overwritten; size capped at capacity.
        assert int(buf.size) == 4 and int(buf.pos) == 2
        np.testing.assert_allclose(np.asarray(buf.reward), [100.0, 101.0, 2.0, 3.0])

    def test_sample_within_valid_range(self):
        buf = buffer_init(100, (3,))
        buf = buffer_add(buf, _batch(10, base=5.0))
        s = buffer_sample(buf, jax.random.PRNGKey(0), 64)
        # All sampled rewards must come from the 10 valid entries [5, 15).
        r = np.asarray(s["reward"])
        assert r.min() >= 5.0 and r.max() < 15.0


def test_epsilon_schedule():
    cfg = DQNConfig(epsilon_start=1.0, epsilon_end=0.1, epsilon_decay_steps=100)
    assert float(epsilon_by_step(cfg, jnp.asarray(0))) == pytest.approx(1.0)
    assert float(epsilon_by_step(cfg, jnp.asarray(50))) == pytest.approx(0.55)
    assert float(epsilon_by_step(cfg, jnp.asarray(1000))) == pytest.approx(0.1)


def test_update_runs_and_counts(cloud_table):
    bundle = multi_cloud_bundle(env_core.make_params(EnvConfig(), cloud_table))
    cfg = DQNConfig(num_envs=4, collect_steps=3, buffer_size=64, batch_size=8,
                    learning_starts=8, hidden=(16,))
    init_fn, update_fn, _ = make_dqn(bundle, cfg)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    update = jax.jit(update_fn)
    runner, m1 = update(runner)
    assert int(runner.env_steps) == 12
    assert int(runner.buffer.size) == 12
    runner, m2 = update(runner)
    assert int(runner.env_steps) == 24
    # Past learning_starts the loss must be live (finite, generally nonzero).
    assert np.isfinite(float(m2["loss"]))
    assert float(m2["epsilon"]) < float(m1["epsilon"]) or cfg.epsilon_decay_steps == 0


def test_target_network_soft_update(cloud_table):
    bundle = multi_cloud_bundle(env_core.make_params(EnvConfig(), cloud_table))
    cfg = DQNConfig(num_envs=2, collect_steps=2, buffer_size=32, batch_size=4,
                    learning_starts=4, target_tau=0.5, hidden=(8,))
    init_fn, update_fn, _ = make_dqn(bundle, cfg)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(1))
    leaves0 = jax.tree.leaves(runner.target_params)
    update = jax.jit(update_fn)
    runner, _ = update(runner)
    runner, _ = update(runner)
    leaves1 = jax.tree.leaves(runner.target_params)
    # After learning begins, the target must have moved toward the online net.
    assert any(not np.allclose(a, b) for a, b in zip(leaves0, leaves1))


def test_dqn_learns_cheaper_cloud(cloud_table):
    """Convergence smoke: on the corrected-reward multi-cloud env the greedy
    Q-policy should clearly beat the worst-case policy after a short run.

    Placement here is myopic (the chosen cloud only affects this step's
    reward), so a low gamma converges sharply in a smoke-test budget where
    gamma=0.99's huge value targets would need far more iterations.
    """
    params = env_core.make_params(EnvConfig(), cloud_table)
    bundle = multi_cloud_bundle(params)
    cfg = DQNConfig(
        num_envs=16, collect_steps=8, buffer_size=4096, batch_size=128,
        learning_starts=256, epsilon_decay_steps=2000, lr=3e-3, gamma=0.3,
        hidden=(32, 32),
    )
    runner, history = dqn_train(bundle, cfg, num_iterations=60, seed=0)

    net_apply = make_dqn(bundle, cfg)[2].apply

    def eval_policy(policy_fn):
        st, obs = bundle.reset_batch(jax.random.PRNGKey(99), 32)
        total = jnp.zeros(32)
        for _ in range(int(params.max_steps)):
            a = policy_fn(obs)
            st, ts = bundle.step_batch(st, a)
            total = total + ts.reward
            obs = ts.obs
        return float(jnp.mean(total))

    greedy = eval_policy(
        jax.jit(lambda o: jnp.argmax(net_apply(runner.params, o), -1).astype(jnp.int32))
    )
    # Always-worst policy: pick the higher-cost cloud every step.
    worst = eval_policy(
        jax.jit(lambda o: jnp.where(o[..., 0] > o[..., 1], 0, 1).astype(jnp.int32))
    )
    # Robust margin: the trained policy recovers a large part of the
    # worst-to-best gap (~2350 on this table), not a seed-lucky epsilon.
    assert greedy > worst + 500.0


def test_dqn_on_single_cluster_env():
    """BASELINE config 1 wiring: 1 env, 2-layer MLP, CPU."""
    bundle = single_cluster_bundle()
    cfg = DQNConfig(num_envs=1, collect_steps=4, buffer_size=512, batch_size=16,
                    learning_starts=32, hidden=(64, 64))
    runner, history = dqn_train(bundle, cfg, num_iterations=12, seed=3)
    assert int(runner.env_steps) == 12 * 4
    assert all(np.isfinite(h["loss"]) for h in history)


def test_fused_dispatch_matches_sequential():
    """lax.scan-fused iterations are the SAME math as one-by-one dispatch
    (RNG and buffer state carry in the runner), so metrics must match."""
    bundle = single_cluster_bundle()
    cfg = DQNConfig(num_envs=2, collect_steps=4, buffer_size=256,
                    batch_size=16, learning_starts=16, hidden=(8, 8))
    _, h_seq = dqn_train(bundle, cfg, num_iterations=8, seed=5)
    _, h_fused = dqn_train(bundle, cfg, num_iterations=8, seed=5,
                           updates_per_dispatch=4)
    assert len(h_fused) == 8
    for a, b in zip(h_seq, h_fused):
        assert a["loss"] == pytest.approx(b["loss"], rel=1e-5)
        assert a["epsilon"] == pytest.approx(b["epsilon"], rel=1e-6)
        assert a["buffer_size"] == b["buffer_size"]


def test_fused_dispatch_rejects_indivisible_span():
    bundle = single_cluster_bundle()
    cfg = DQNConfig(num_envs=1, collect_steps=2, buffer_size=64, batch_size=8)
    with pytest.raises(ValueError, match="not"):
        dqn_train(bundle, cfg, num_iterations=7, updates_per_dispatch=4)


def test_train_dqn_cli_fused_dispatch(tmp_path):
    import json

    from rl_scheduler_tpu.agent import train_dqn as cli

    run_dir = cli.main([
        "--preset", "config1", "--iterations", "8",
        "--run-root", str(tmp_path), "--run-name", "dqn_fused",
        "--checkpoint-every", "8", "--hidden", "8,8",
        "--updates-per-dispatch", "4", "--sync-every", "4",
    ])
    lines = [json.loads(l) for l in (run_dir / "metrics.jsonl").open()]
    assert len(lines) == 8 and lines[-1]["iteration"] == 8


def test_train_dqn_cli_writes_checkpoints_and_metrics(tmp_path):
    import json

    from rl_scheduler_tpu.agent import train_dqn as cli
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    run_dir = cli.main([
        "--preset", "config1", "--iterations", "6",
        "--run-root", str(tmp_path), "--run-name", "dqn_cli_test",
        "--checkpoint-every", "3", "--hidden", "16,16", "--log-every", "2",
    ])
    assert run_dir == tmp_path / "dqn_cli_test"
    mgr = CheckpointManager(run_dir)
    assert mgr.latest_step() == 6
    meta = mgr.restore_meta(6)
    assert meta["algo"] == "dqn" and meta["hidden"] == [16, 16]
    tree, _ = mgr.restore(6)
    assert "params" in tree and "target_params" in tree
    mgr.close()
    lines = [json.loads(l) for l in (run_dir / "metrics.jsonl").open()]
    assert len(lines) == 6 and lines[-1]["iteration"] == 6


def test_run_train_loop_wall_time_and_crash_flush():
    from rl_scheduler_tpu.agent.loop import run_train_loop

    def update(state):
        if int(state) == 3:
            raise RuntimeError("boom")
        return state + 1, {"v": jnp.asarray(float(state))}

    seen = []
    with pytest.raises(RuntimeError):
        run_train_loop(update, jnp.asarray(0.0), 0, 10, sync_every=100,
                       log_fn=lambda i, m: seen.append((i, m)))
    # iterations 0..2 completed before the crash; the finally-flush wrote them
    assert [i for i, _ in seen] == [0, 1, 2]
    walls = [m["wall_time"] for _, m in seen]
    assert walls == sorted(walls) and walls[-1] > 0


def test_tensorboard_flag_writes_event_files(tmp_path):
    pytest.importorskip("torch.utils.tensorboard")
    from rl_scheduler_tpu.agent import train_dqn as cli

    run_dir = cli.main([
        "--preset", "config1", "--iterations", "3",
        "--run-root", str(tmp_path), "--run-name", "tb_test",
        "--checkpoint-every", "3", "--hidden", "8,8", "--tensorboard",
    ])
    events = list((run_dir / "tb").glob("events.out.tfevents.*"))
    assert events and events[0].stat().st_size > 0


class TestOpenLoopCollect:
    def test_auto_uses_open_loop_on_multi_cloud(self):
        """Learning works and the buffer fills identically-shaped data —
        and the open-loop horizon is ACTUALLY selected (call-counted)."""
        calls = {"n": 0}
        bundle = multi_cloud_bundle(env_core.make_params(EnvConfig()))
        inner = bundle.horizon_fn

        def counting_horizon(*args):
            calls["n"] += 1
            return inner(*args)

        bundle = bundle._replace(horizon_fn=counting_horizon)
        cfg = DQNConfig(num_envs=8, collect_steps=5, buffer_size=512,
                        batch_size=32, learning_starts=64, hidden=(16, 16))
        runner, history = dqn_train(bundle, cfg, num_iterations=10, seed=1)
        assert calls["n"] >= 1  # traced through the open-loop path
        assert int(runner.env_steps) == 10 * 5 * 8
        assert int(runner.buffer.size) == 10 * 5 * 8
        assert all(np.isfinite(h["loss"]) for h in history)

    def test_scan_and_open_loop_learn_comparably(self):
        """Both collect paths fill equivalent-statistics buffers: after the
        same number of iterations the mean buffered reward must agree."""
        import dataclasses

        bundle = multi_cloud_bundle(env_core.make_params(EnvConfig()))
        base = DQNConfig(num_envs=32, collect_steps=25, buffer_size=8192,
                         batch_size=64, learning_starts=10**9,  # never learn
                         epsilon_start=1.0, epsilon_end=1.0, hidden=(8, 8))
        means = {}
        for impl in ("scan", "open_loop"):
            cfg = dataclasses.replace(base, collect_impl=impl)
            runner, _ = dqn_train(bundle, cfg, num_iterations=4, seed=0)
            n = int(runner.buffer.size)
            means[impl] = float(jnp.mean(runner.buffer.reward[:n]))
        assert means["scan"] == pytest.approx(means["open_loop"], rel=0.05)

    def test_open_loop_rejected_without_horizon(self):
        bundle = single_cluster_bundle()
        cfg = DQNConfig(num_envs=2, collect_steps=2, buffer_size=64,
                        batch_size=8, collect_impl="open_loop")
        with pytest.raises(ValueError, match="horizon_fn"):
            make_dqn(bundle, cfg)


def test_buffer_add_batch_larger_than_capacity():
    """One add bigger than the buffer keeps exactly the newest cap rows
    (matching what sequential adds would leave), with no index collisions."""
    buf = buffer_init(8, (3,))
    buf = buffer_add(buf, _batch(3, base=0.0))          # pos=3
    big = _batch(20, base=100.0)                        # rewards 100..119
    buf = buffer_add(buf, big)
    assert int(buf.size) == 8
    assert int(buf.pos) == (3 + 20) % 8
    # newest 8 rewards are 112..119, laid out circularly ending at pos-1
    got = np.asarray(buf.reward)
    order = [(int(buf.pos) - 8 + i) % 8 for i in range(8)]
    np.testing.assert_allclose(got[order], np.arange(112.0, 120.0))
