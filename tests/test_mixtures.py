"""graftmix tests: importer, mixture curricula, transfer grid.

Layer by layer (docs/scenarios.md graftmix sections):

- **importer**: both external-trace formats (Google ClusterData-style,
  Alibaba v2018-style) import bitwise-deterministically per (trace
  digest, seed) from the seeded synthetic fixtures; malformed/partial
  rows are COUNTED outcomes (truncated mid-row, junk fields, inverted
  intervals, duplicate machine adds, out-of-order timestamps, an empty
  usage table), never crashes; the ``external_trace:`` scenario name
  round-trips; both formats train one real PPO update.
- **curricula**: ``MixtureSpec`` refuses everything inert (weight-zero
  components, single-component mixtures, identity anneals) and every
  obs-width mismatch at construction; the canonical name round-trips
  (anneal + name-built components included); the stacked env's
  per-episode family draw follows the (annealed) weights, matches the
  single-family env slice for slice, stays vmap-uniform, and trains one
  real PPO update — ``--overlap-collect`` composed.
- **CLI/serving**: ``train_ppo --mixture`` records provenance, the
  resume guards pin it, ``evaluate --run`` rebuilds the mixture, and the
  extender's conformance demand answers with the mixture name.
- **transfer grid** (the ``make mixture-smoke`` acceptance): a
  mixture smoke checkpoint renders the full grid — every family × two
  node counts — with held-out flags, structured incompatible reasons,
  and graftstudy verdicts engaged.
"""

from __future__ import annotations

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.mixtures import (
    ImportedTrace,
    ImportReport,
    MixtureSpec,
    TraceImportError,
    get_mixture,
    import_external_trace,
    list_mixtures,
    mixture_bundle,
    mixture_meta,
    mixture_set_params,
    parse_mixture,
    trace_digest,
)
from rl_scheduler_tpu.mixtures import env as menv
from rl_scheduler_tpu.mixtures.env import (
    MixtureSetParams,
    MixtureState,
    draw_family,
    episode_params,
    weights_at,
)
from rl_scheduler_tpu.mixtures.fixtures import (
    generate_alibaba_fixture,
    generate_google_fixture,
)
from rl_scheduler_tpu.mixtures.grid import (
    cell_verdict,
    incompatible_reason,
    render_transfer_grid,
    transfer_cells,
    transfer_grid_summary,
)
from rl_scheduler_tpu.mixtures.importer import (
    external_tables,
    node_avail_mask,
)
from rl_scheduler_tpu.scenarios import FAMILIES, get_scenario
from rl_scheduler_tpu.scenarios.families import external_trace_tables


@pytest.fixture(scope="module")
def google_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("ext") / "google"
    generate_google_fixture(d, seed=0)
    return d


@pytest.fixture(scope="module")
def alibaba_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("ext") / "alibaba"
    generate_alibaba_fixture(d, seed=0)
    return d


def _dir_for(fmt, google_dir, alibaba_dir):
    return google_dir if fmt == "google" else alibaba_dir


# -------------------------------------------------------------- importer


def test_fixture_generators_deterministic(tmp_path):
    """Same seed ⇒ byte-identical fixture files (the digest IS the
    determinism key); different seed ⇒ a different trace."""
    a = tmp_path / "a"
    b = tmp_path / "b"
    c = tmp_path / "c"
    generate_google_fixture(a, seed=3)
    generate_google_fixture(b, seed=3)
    generate_google_fixture(c, seed=4)
    assert trace_digest(a, "google") == trace_digest(b, "google")
    assert trace_digest(a, "google") != trace_digest(c, "google")
    generate_alibaba_fixture(a, seed=3)
    generate_alibaba_fixture(b, seed=3)
    assert trace_digest(a, "alibaba") == trace_digest(b, "alibaba")


@pytest.mark.parametrize("fmt", ["google", "alibaba"])
def test_import_bitwise_deterministic(fmt, google_dir, alibaba_dir):
    d = _dir_for(fmt, google_dir, alibaba_dir)
    i1 = import_external_trace(d, fmt, steps=40, seed=5)
    i2 = import_external_trace(d, fmt, steps=40, seed=5)
    assert isinstance(i1, ImportedTrace)
    assert isinstance(i1.report, ImportReport)
    np.testing.assert_array_equal(i1.costs, i2.costs)
    np.testing.assert_array_equal(i1.latencies, i2.latencies)
    np.testing.assert_array_equal(i1.pod_scale, i2.pod_scale)
    np.testing.assert_array_equal(i1.machine_avail, i2.machine_avail)
    assert i1.report.digest == i2.report.digest
    # Different seed: the seeded draws (jitter, node assignment) differ.
    i3 = import_external_trace(d, fmt, steps=40, seed=6)
    assert not np.array_equal(i1.costs, i3.costs)
    # Tables land in the normalized [0, 1] space, mask reconstructs the
    # fixtures' planted lifecycle gap.
    assert i1.costs.min() >= 0.0 and i1.costs.max() <= 1.0
    assert i1.machine_avail.min() == 0.0
    assert i1.steps == 40 and i1.report.to_json()["format"] == fmt


def test_import_truncated_mid_row_is_counted_not_fatal(tmp_path,
                                                       google_dir):
    """A torn trailing line (truncated download / mid-write crash) is a
    counted reject; the surviving rows compile bitwise as before."""
    import shutil

    d = tmp_path / "trunc"
    shutil.copytree(google_dir, d)
    clean = import_external_trace(d, "google", steps=30, seed=0)
    with (d / "task_usage.csv").open("a") as fh:
        fh.write("9999,10001,42")  # cut off mid-row, no newline
    torn = import_external_trace(d, "google", steps=30, seed=0)
    assert torn.report.rejected.get("task_usage_short_row") == 1
    np.testing.assert_array_equal(clean.costs, torn.costs)
    np.testing.assert_array_equal(clean.pod_scale, torn.pod_scale)


def test_import_junk_fields_counted(tmp_path, google_dir):
    import shutil

    d = tmp_path / "junk"
    shutil.copytree(google_dir, d)
    with (d / "task_usage.csv").open("a") as fh:
        fh.write("100,200,1,0,1000,not_a_number,0.1\n")   # bad cpu_rate
        fh.write("300,100,1,0,1000,0.5,0.1\n")            # end < start
    rep = import_external_trace(d, "google", steps=30, seed=0).report
    assert rep.rejected.get("task_usage_bad_number") == 1
    assert rep.rejected.get("task_usage_inverted_interval") == 1
    assert rep.rows_total == (rep.rows_used + rep.rows_ignored
                              + sum(rep.rejected.values()))


def test_import_out_of_order_timestamps_sorted_and_counted(tmp_path):
    """File order is shard order, not time order: the importer sorts by
    timestamp (stable) and counts the inversions it saw — a reversed
    file compiles bitwise-identically to the sorted one."""
    rows_sorted = [(t, 1000 + (t // 10) % 2, 0, "p", 1.0, 1.0)
                   for t in range(10, 60, 10)]
    usage = [(t, t + 5, 1, 0, 1000, 0.2 + t / 100.0, 0.1)
             for t in range(10, 60, 7)]

    def write(d, events):
        d.mkdir()
        with (d / "machine_events.csv").open("w") as fh:
            for r in events:
                fh.write(",".join(str(x) for x in r) + "\n")
        with (d / "task_usage.csv").open("w") as fh:
            for r in usage:
                fh.write(",".join(str(x) for x in r) + "\n")

    a = tmp_path / "fwd"
    b = tmp_path / "rev"
    write(a, rows_sorted)
    write(b, list(reversed(rows_sorted)))
    fwd = import_external_trace(a, "google", steps=10, seed=0)
    rev = import_external_trace(b, "google", steps=10, seed=0)
    assert fwd.report.out_of_order_rows == 0
    assert rev.report.out_of_order_rows > 0
    np.testing.assert_array_equal(fwd.costs, rev.costs)
    np.testing.assert_array_equal(fwd.machine_avail, rev.machine_avail)


def test_import_duplicate_machine_ids_counted_idempotent(google_dir):
    """The fixture plants a duplicate ADD for an up machine: counted,
    treated idempotently (no phantom second machine, no double-up)."""
    rep = import_external_trace(google_dir, "google", steps=20,
                                seed=0).report
    assert rep.duplicate_machine_adds >= 1
    assert rep.rows_ignored >= 1          # well-formed, skipped, counted
    assert rep.machines == 8
    # The report's row invariant: every parsed row is accounted for
    # exactly once across used / ignored / rejected.
    assert rep.rows_total == (rep.rows_used + rep.rows_ignored
                              + sum(rep.rejected.values()))


def test_import_empty_usage_table_degrades_pod_scale(tmp_path, google_dir):
    import shutil

    d = tmp_path / "nousage"
    shutil.copytree(google_dir, d)
    (d / "task_usage.csv").write_text("")
    imported = import_external_trace(d, "google", steps=20, seed=0)
    assert imported.pod_scale is None
    # A non-row outcome lives on its own field, not the row counters.
    assert not imported.report.pod_from_trace
    assert "empty_usage_table" not in imported.report.rejected
    # The scenario layer still compiles (default pod draw).
    from rl_scheduler_tpu.scenarios import cluster_set_params

    p = cluster_set_params(
        get_scenario(f"external_trace:{d}?format=google&steps=20"),
        num_nodes=4)
    assert p.pod_scale is None and p.avail_mask.shape == (20, 4)


def test_import_refusals(tmp_path, google_dir):
    with pytest.raises(TraceImportError, match="missing"):
        import_external_trace(tmp_path / "nope", "google")
    with pytest.raises(TraceImportError, match="format"):
        import_external_trace(google_dir, "borg")
    with pytest.raises(TraceImportError, match="steps"):
        import_external_trace(google_dir, "google", steps=1)
    d = tmp_path / "one_machine"
    d.mkdir()
    (d / "machine_events.csv").write_text("0,1,0,p,1,1\n")
    (d / "task_usage.csv").write_text("")
    with pytest.raises(TraceImportError, match="machines"):
        import_external_trace(d, "google")


def test_node_avail_mask_mapping(google_dir):
    imported = import_external_trace(google_dir, "google", steps=30, seed=0)
    mask = node_avail_mask(imported, 8, seed=0)
    assert mask.shape == (30, 8)
    assert (mask.sum(axis=1) >= 1).all()          # never fully dark
    np.testing.assert_array_equal(mask, node_avail_mask(imported, 8,
                                                        seed=0))
    # The planted REMOVE/re-ADD cycle survives the node mapping.
    assert mask.min() == 0.0


def test_external_scenario_name_roundtrip(google_dir):
    assert "external_trace" in FAMILIES
    name = f"external_trace:{google_dir}?format=google&steps=30"
    scn = get_scenario(name, seed=4)
    assert scn.family == "external_trace" and scn.steps == 30
    assert scn.knob("format") == "google" and scn.seed == 4
    # The name IS the spec: reparsing is identity.
    assert get_scenario(scn.name, seed=4) == scn
    t = external_trace_tables(str(google_dir), "google", steps=30, seed=4)
    t2 = external_tables(google_dir, "google", steps=30, seed=4)
    np.testing.assert_array_equal(t["costs"], t2["costs"])
    # The scenario env params fuse ONE import with the node mask; the
    # compiled mask matches the standalone two-call reconstruction.
    from rl_scheduler_tpu.scenarios import cluster_set_params

    p = cluster_set_params(scn, num_nodes=6)
    mask = node_avail_mask(
        import_external_trace(google_dir, "google", steps=30, seed=4),
        6, seed=4)
    np.testing.assert_array_equal(np.asarray(p.avail_mask), mask)
    assert mask.shape == (30, 6)
    for bad in ("external_trace:", f"external_trace:{google_dir}",
                f"external_trace:{google_dir}?format=borg",
                f"external_trace:{google_dir}?format=google&steps=zz",
                f"external_trace:{google_dir}?format=google&nope=1"):
        with pytest.raises(ValueError):
            get_scenario(bad)


@pytest.mark.parametrize("fmt", ["google", "alibaba"])
def test_external_fixture_roundtrip_ppo_update(fmt, google_dir,
                                               alibaba_dir):
    """The satellite pin: import → compile → one REAL jitted PPO update
    per format (the same drop-in acceptance every scenario family
    carries)."""
    from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, make_ppo_bundle
    from rl_scheduler_tpu.models import SetTransformerPolicy
    from rl_scheduler_tpu.scenarios import scenario_bundle

    d = _dir_for(fmt, google_dir, alibaba_dir)
    scn = get_scenario(f"external_trace:{d}?format={fmt}&steps=30")
    bundle = scenario_bundle(scn, num_nodes=4)
    cfg = PPOTrainConfig(num_envs=4, rollout_steps=8, minibatch_size=32,
                         num_epochs=1)
    init_fn, update_fn, _ = make_ppo_bundle(
        bundle, cfg, net=SetTransformerPolicy(dim=16, depth=1))
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    runner, metrics = jax.jit(update_fn)(runner)
    assert np.isfinite(float(metrics["reward_mean"]))


# ------------------------------------------------------------- curricula


def test_mixture_spec_refuses_inert_and_mismatched():
    with pytest.raises(ValueError, match="weight-zero"):
        parse_mixture("mixture:bursty*1+churn*0")
    with pytest.raises(ValueError, match="single-family"):
        parse_mixture("mixture:bursty*1")
    with pytest.raises(ValueError, match="duplicate"):
        parse_mixture("mixture:bursty*1+bursty*2")
    with pytest.raises(ValueError, match="13 features"):
        parse_mixture("mixture:bursty*1+heterogeneous*1")
    with pytest.raises(ValueError, match="unknown scenario"):
        parse_mixture("mixture:bursty*1+nope*1")
    with pytest.raises(ValueError, match="needs <scenario>"):
        parse_mixture("mixture:bursty+churn*1")
    with pytest.raises(ValueError, match="inert"):
        # Identity anneal: from == final weights.
        parse_mixture("mixture:bursty*1+churn*1@anneal=10"
                      "&from=bursty*1+churn*1")
    with pytest.raises(ValueError, match="from="):
        parse_mixture("mixture:bursty*1+churn*1@anneal=10")
    with pytest.raises(ValueError, match="inert"):
        MixtureSpec(components=(("bursty", 1.0), ("churn", 1.0)),
                    start=(("bursty", 1.0),))
    with pytest.raises(ValueError, match="not in the mixture"):
        parse_mixture("mixture:bursty*1+churn*1@anneal=10&from=nope*1")
    with pytest.raises(ValueError, match="unknown mixture"):
        get_mixture("nope")
    assert list_mixtures() == ["generalist", "generalist_anneal"]


def test_mixture_canonical_name_roundtrips(google_dir):
    for preset in list_mixtures():
        spec = get_mixture(preset)
        assert parse_mixture(spec.canonical_name()) == spec
    # Name-built components with ?/& in their own query parse unchanged.
    ext = f"external_trace:{google_dir}?format=google&steps=100"
    spec = parse_mixture(f"mixture:bursty*0.5+{ext}*1.5")
    assert spec.names() == ("bursty", ext)
    assert parse_mixture(spec.canonical_name()) == spec
    assert spec.weights() == (0.25, 0.75)
    # Anneal spec: start aligned to components, zero = anneals in.
    a = get_mixture("generalist_anneal")
    assert a.anneal_episodes == 200
    assert a.start_weights()[a.names().index("churn")] == 0.0
    meta = mixture_meta(spec, scenario_seed=7)
    assert meta["mixture"] == spec.canonical_name()
    assert meta["scenario_seed"] == 7 and meta["node_feat"] == 6
    assert "external_trace" in meta["mixture_families"]


# ------------------------------------------------------------ mixture env


@pytest.fixture(scope="module")
def gen_params():
    return mixture_set_params(get_mixture("generalist"), num_nodes=6,
                              seed=0)


def test_mixture_params_stack_bitwise_deterministic(gen_params):
    again = mixture_set_params(get_mixture("generalist"), num_nodes=6,
                               seed=0)
    assert isinstance(gen_params, MixtureSetParams)
    for a, b in zip(jax.tree.leaves(gen_params), jax.tree.leaves(again)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert gen_params.costs.shape == (4, 100, 2)
    assert gen_params.avail_mask.shape == (4, 100, 6)
    # A different table seed recompiles different component tables.
    other = mixture_set_params(get_mixture("generalist"), num_nodes=6,
                               seed=1)
    assert not np.array_equal(np.asarray(gen_params.costs),
                              np.asarray(other.costs))


def test_mixture_refuses_mismatched_table_lengths(google_dir):
    ext = f"external_trace:{google_dir}?format=google&steps=64"
    spec = parse_mixture(f"mixture:bursty*1+{ext}*1")
    with pytest.raises(ValueError, match="different lengths"):
        mixture_set_params(spec, num_nodes=4)
    # Pinned to the registry length it stacks fine — an external trace
    # joins a mixture by naming steps=100.
    ok = parse_mixture(
        f"mixture:bursty*1+external_trace:{google_dir}"
        "?format=google&steps=100*1")
    p = mixture_set_params(ok, num_nodes=4)
    assert p.costs.shape == (2, 100, 2)


def test_mixture_episode_params_match_single_family(gen_params):
    """Slice k of the stack IS component k's densified params: same
    tables, identity leaves where the family has none — the all-ones /
    degenerate-range no-ops the scenario suite pins."""
    from rl_scheduler_tpu.scenarios import cluster_set_params

    spec = get_mixture("generalist")
    for k, name in enumerate(spec.names()):
        ep = episode_params(gen_params, jnp.asarray(k))
        single = cluster_set_params(get_scenario(name, seed=0), 6)
        np.testing.assert_array_equal(np.asarray(ep.costs),
                                      np.asarray(single.costs))
        if single.avail_mask is not None:
            np.testing.assert_array_equal(np.asarray(ep.avail_mask),
                                          np.asarray(single.avail_mask))
        else:
            np.testing.assert_array_equal(np.asarray(ep.avail_mask), 1.0)
        if single.pod_scale is not None:
            np.testing.assert_array_equal(np.asarray(ep.pod_scale),
                                          np.asarray(single.pod_scale))
        # Degenerate ranges reproduce the component's static values.
        if single.drain_range is None:
            np.testing.assert_allclose(np.asarray(ep.drain_range),
                                       float(single.drain_rate))


def test_mixture_family_draw_follows_weights():
    spec = parse_mixture("mixture:bursty*3+churn*1")
    params = mixture_set_params(spec, num_nodes=4)
    draws = [int(draw_family(params, jax.random.PRNGKey(k),
                             jnp.asarray(0))) for k in range(300)]
    frac = sum(1 for d in draws if d == 0) / len(draws)
    assert 0.65 < frac < 0.85          # ~0.75 expected
    # Deterministic per key; annealed weights interpolate start->final.
    assert draws[:20] == [int(draw_family(params, jax.random.PRNGKey(k),
                                          jnp.asarray(0)))
                          for k in range(20)]
    a = mixture_set_params(get_mixture("generalist_anneal"), num_nodes=4)
    w0 = np.asarray(weights_at(a, jnp.asarray(0)))
    w_mid = np.asarray(weights_at(a, jnp.asarray(100)))
    w_end = np.asarray(weights_at(a, jnp.asarray(10_000)))
    np.testing.assert_allclose(w0, np.asarray(a.start_weights), atol=1e-6)
    np.testing.assert_allclose(w_end, np.asarray(a.weights), atol=1e-6)
    assert not np.allclose(w0, w_mid) and not np.allclose(w_mid, w_end)
    np.testing.assert_allclose(w_mid.sum(), 1.0, atol=1e-6)


def test_mixture_vmap_matches_single_env(gen_params):
    """reset_batch/step_batch (the fleet path) == the single-env pure
    functions per key — the same vmap-parity contract every scenario
    family pins."""
    bundle = mixture_bundle(gen_params)
    key = jax.random.PRNGKey(3)
    keys = jax.random.split(key, 4)
    bstate, bobs = bundle.reset_batch(key, 4)
    actions = jnp.arange(4, dtype=jnp.int32) % 6
    bstate2, bts = bundle.step_batch(bstate, actions)
    for i in range(4):
        sstate, sobs = menv.reset(gen_params, keys[i])
        np.testing.assert_array_equal(np.asarray(bobs[i]),
                                      np.asarray(sobs))
        assert int(bstate.family[i]) == int(sstate.family)
        _, sts = menv.step(gen_params, sstate, actions[i])
        np.testing.assert_array_equal(np.asarray(bts.reward[i]),
                                      np.asarray(sts.reward))


def test_mixture_autoreset_counts_episodes_and_redraws(gen_params):
    """The custom auto-reset threads the anneal clock: ep_count
    increments exactly on done, and the replacement episode re-draws its
    family from the lane's own key stream."""
    bundle = mixture_bundle(gen_params)
    state, obs = bundle.reset_batch(jax.random.PRNGKey(0), 8)
    assert isinstance(jax.tree.leaves(state)[0], jnp.ndarray)
    assert isinstance(state, MixtureState)
    np.testing.assert_array_equal(np.asarray(state.ep_count), 0)
    fams0 = np.asarray(state.family).copy()
    for _ in range(bundle.episode_steps):
        state, ts = bundle.step_batch(state, jnp.zeros(8, jnp.int32))
    # The last step wrapped every lane into episode 1.
    np.testing.assert_array_equal(np.asarray(state.ep_count), 1)
    del fams0  # family MAY re-draw the same index; nothing to pin there
    # Mid-episode steps must NOT advance the counter.
    state, ts = bundle.step_batch(state, jnp.zeros(8, jnp.int32))
    np.testing.assert_array_equal(np.asarray(state.ep_count), 1)


def test_mixture_trains_one_ppo_update_and_composes_overlap(gen_params):
    """The fleet-path acceptance: a real jitted PPO update on the
    mixture bundle — and the graftpipe composition (--overlap-collect)
    the ISSUE names, at one update."""
    from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, make_ppo_bundle
    from rl_scheduler_tpu.models import SetTransformerPolicy

    bundle = mixture_bundle(gen_params)
    for overlap in (False, True):
        cfg = PPOTrainConfig(num_envs=4, rollout_steps=8,
                             minibatch_size=32, num_epochs=1,
                             overlap_collect=overlap)
        init_fn, update_fn, _ = make_ppo_bundle(
            bundle, cfg, net=SetTransformerPolicy(dim=16, depth=1))
        runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
        runner, metrics = jax.jit(update_fn)(runner)
        assert np.isfinite(float(metrics["reward_mean"]))


# --------------------------------------------- CLI round-trip + serving


@pytest.fixture(scope="module")
def mixture_run(tmp_path_factory):
    """One tiny mixture run through the REAL train_ppo CLI, shared by
    the meta, resume-guard, evaluate, serving, and grid tests."""
    from rl_scheduler_tpu.agent import train_ppo

    root = tmp_path_factory.mktemp("mix_cli")
    run_dir = train_ppo.main([
        "--mixture", "generalist", "--scenario-seed", "2",
        "--preset", "quick", "--num-envs", "4", "--rollout-steps", "8",
        "--minibatch-size", "32", "--iterations", "1",
        "--run-name", "MIX", "--run-root", str(root),
    ])
    return run_dir


def test_cli_records_mixture_meta(mixture_run):
    from rl_scheduler_tpu.utils.checkpoint import load_policy_params

    _, meta = load_policy_params(mixture_run)
    assert meta["mixture"] == get_mixture("generalist").canonical_name()
    assert meta["scenario"] is None
    assert meta["scenario_seed"] == 2
    assert meta["node_feat"] == 6
    assert set(meta["mixture_families"]) == {
        "bursty_diurnal", "churn", "price_spike", "domain_random"}


def test_cli_mixture_flag_validation(tmp_path):
    from rl_scheduler_tpu.agent import train_ppo

    base = ["--preset", "quick", "--iterations", "1",
            "--run-root", str(tmp_path)]
    with pytest.raises(SystemExit, match="pick one flag"):
        train_ppo.main(base + ["--mixture", "generalist",
                               "--scenario", "churn"])
    with pytest.raises(SystemExit, match="cluster_set"):
        train_ppo.main(base + ["--mixture", "generalist",
                               "--env", "multi_cloud"])
    with pytest.raises(SystemExit, match="--mixture"):
        train_ppo.main(base + ["--mixture", "mixture:bursty*1+churn*0"])


def test_cli_resume_guards_pin_mixture(mixture_run):
    from rl_scheduler_tpu.agent import train_ppo

    base = ["--preset", "quick", "--num-envs", "4", "--rollout-steps", "8",
            "--minibatch-size", "32", "--iterations", "2",
            "--run-name", "MIX", "--run-root", str(mixture_run.parent),
            "--resume"]
    with pytest.raises(SystemExit, match="mixture"):
        train_ppo.main(base)  # mixture run resumed without the flag
    with pytest.raises(SystemExit, match="training distribution"):
        train_ppo.main(base + ["--mixture", "mixture:bursty*1+churn*1",
                               "--scenario-seed", "2"])
    with pytest.raises(SystemExit, match="scenario-seed"):
        train_ppo.main(base + ["--mixture", "generalist",
                               "--scenario-seed", "9"])


def test_evaluate_rebuilds_mixture_from_meta(mixture_run, tmp_path,
                                             capsys):
    from rl_scheduler_tpu.agent import evaluate

    report = evaluate.main(["--run", str(mixture_run), "--episodes", "2",
                            "--results-dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert "Rebuilding mixture" in out
    assert report.env == "cluster_set"
    assert np.isfinite(report.avg_episode_reward)


def test_extender_mixture_serving_conformance(mixture_run):
    """Serving answers the conformance demand with the canonical mixture
    name (the trace_replay one-string convention): matching demand
    builds, mismatched demand refuses."""
    from rl_scheduler_tpu.scheduler.extender import build_policy

    canonical = get_mixture("generalist").canonical_name()
    with pytest.raises(ValueError, match="scenario"):
        build_policy(backend="cpu", run=str(mixture_run),
                     scenario="churn")
    policy = build_policy(backend="cpu", run=str(mixture_run),
                          scenario=canonical)
    assert policy.scenario == canonical and policy.family == "set"


# ----------------------------------------------------- transfer grid


def test_cell_verdict_grading():
    assert cell_verdict(5, 0, 0)["verdict"] == "confirmed_above"
    assert cell_verdict(0, 5, 0)["verdict"] == "confirmed_below"
    assert cell_verdict(3, 2, 0)["verdict"] == "point_above"
    assert cell_verdict(2, 3, 0)["verdict"] == "point_below"
    tie = cell_verdict(0, 0, 5)
    assert tie["verdict"] == "tied" and tie["sign_test_p"] == 1.0
    assert tie["win_rate"] is None        # zero evidence, no side claimed
    v = cell_verdict(4, 1, 0)
    assert v["wilson95"][0] < 0.5 < v["wilson95"][1]  # n=5 cannot confirm
    assert v["verdict"] == "point_above"


def test_incompatible_reason_codes():
    assert incompatible_reason(6, 13)["reason"] == "obs_width"
    assert incompatible_reason(6, 6, "cluster_graph")["reason"] == \
        "env_family"
    assert incompatible_reason(6, 6)["reason"] == "scenario_meta"


def test_matrix_incompatible_cells_carry_reason_and_held_out():
    """Satellite: the eval matrix's incompatible cells now say WHY, and
    a trained-families record flags the zero-shot columns."""
    from rl_scheduler_tpu.agent.evaluate import (
        matrix_summary,
        scenario_policy_matrix,
    )
    from rl_scheduler_tpu.models import SetTransformerPolicy

    net = SetTransformerPolicy(dim=16, depth=1)
    params = net.init(jax.random.PRNGKey(0), jnp.zeros((1, 4, 6)))
    rows = scenario_policy_matrix(
        ["heterogeneous", "churn"], num_nodes=4, episodes=2,
        checkpoint=(net, params, 6),
        trained_families=("bursty_diurnal", "churn"))
    het = next(r for r in rows if r["policy"] == "checkpoint"
               and r["scenario"] == "heterogeneous")
    assert het["incompatible"] is True and het["reason"] == "obs_width"
    assert het["held_out"] is True
    churn = next(r for r in rows if r["policy"] == "checkpoint"
                 and r["scenario"] == "churn")
    assert churn["held_out"] is False and "reward_mean" in churn
    grid = matrix_summary(rows)
    assert "heterogeneous*" in grid and "held-out" in grid


def test_transfer_cells_unit(gen_params):
    """Direct unit of the grid engine: a tiny net vs baselines over two
    scenarios × one node count, verdicts attached, csv row included."""
    from rl_scheduler_tpu.models import SetTransformerPolicy

    net = SetTransformerPolicy(dim=16, depth=1)
    params = net.init(jax.random.PRNGKey(0), jnp.zeros((1, 4, 6)))
    cells = transfer_cells(
        (net, params, 6), ["csv", "churn"], node_counts=(4,),
        seeds=(0, 1, 2), episodes=2,
        specialists={"churn": (net, params, 6)},
        trained_families=("bursty_diurnal",))
    assert len(cells) == 2
    for c in cells:
        assert c["metric"] == "transfer_grid_cell"
        assert c["verdict"] in ("confirmed_above", "point_above", "tied",
                                "point_below", "confirmed_below")
        assert np.isfinite(c["margin_pct"])
    # csv maps to the domain_random workload shape — the SHARED row
    # definition both tools key their held-out mapping on: a
    # bursty-only curriculum never trained it -> held out.
    from rl_scheduler_tpu.scenarios import csv_reference_row

    bundle_fn, _cols, feat, fam = csv_reference_row()
    assert fam == "domain_random" and feat == 6
    assert bundle_fn(4).num_actions == 4
    assert cells[0]["held_out"] is True
    assert cells[0]["opponent"].startswith("baseline:")
    assert cells[1]["scenario"] == "churn" and cells[1]["held_out"]
    # A named specialist swaps that column's opponent; same net here
    # means every seed ties -> the zero-evidence grading path.
    assert cells[1]["opponent"] == "specialist"
    assert cells[1]["ties"] == 3 and cells[1]["verdict"] == "tied"
    # A width-mismatched specialist is NOT silently a baseline row:
    # the cell says the named specialist was ignored and why.
    mm = transfer_cells(
        (net, params, 6), ["churn"], node_counts=(4,), seeds=(0,),
        episodes=2, specialists={"churn": (net, params, 13)})
    assert mm[0]["specialist_ignored"] == "obs_width"
    assert mm[0]["opponent"].startswith("baseline:")
    summary = transfer_grid_summary(cells, run="unit", mixture=None,
                                    trained_families=("bursty_diurnal",))
    assert summary["held_out_cells"] >= 1
    assert "TRANSFER GRID" in render_transfer_grid(summary)


@pytest.fixture(scope="module")
def churn_specialist_run(tmp_path_factory):
    """A tiny REAL churn specialist for the grid's margin row (the
    specialist guard refuses mixture/wrong-scenario runs, so the smoke
    needs an honest one)."""
    from rl_scheduler_tpu.agent import train_ppo

    root = tmp_path_factory.mktemp("spec_cli")
    return train_ppo.main([
        "--scenario", "churn", "--preset", "quick", "--num-envs", "4",
        "--rollout-steps", "8", "--minibatch-size", "32",
        "--iterations", "1", "--run-name", "SPEC_churn",
        "--run-root", str(root),
    ])


def test_transfer_grid_specialist_guard(mixture_run, churn_specialist_run,
                                        tmp_path):
    """--specialist refuses a generalist (it would compare the
    generalist against itself) and a wrong-scenario run."""
    from rl_scheduler_tpu.agent import evaluate

    base = ["--transfer-grid", "--run", str(mixture_run),
            "--scenarios", "churn", "--grid-nodes", "4",
            "--grid-seeds", "2", "--grid-episodes", "2",
            "--results-dir", str(tmp_path)]
    with pytest.raises(SystemExit, match="generalist"):
        evaluate.main(base + ["--specialist", f"churn={mixture_run}"])
    with pytest.raises(SystemExit, match="real specialist"):
        evaluate.main(base + ["--specialist",
                              f"bursty={churn_specialist_run}"])


@pytest.mark.parametrize("flavor", ["grid"])
def test_mixture_smoke_transfer_grid(flavor, mixture_run, google_dir,
                                     churn_specialist_run,
                                     tmp_path, capsys):
    """`make mixture-smoke` — the container acceptance: the mixture
    smoke checkpoint renders the FULL transfer grid (every family
    including the imported external trace × 2 node counts) with the
    verdict machinery engaged, held-out and incompatible cells flagged,
    and one schema-tagged transfer_grid JSON line + artifacts written."""
    from rl_scheduler_tpu.agent import evaluate

    ext = f"external_trace:{google_dir}?format=google&steps=100"
    summary = evaluate.main([
        "--transfer-grid", "--run", str(mixture_run),
        "--scenarios", f"csv,bursty,churn,price_spike,randomized,"
                       f"heterogeneous,{ext}",
        "--grid-nodes", "4,8", "--grid-seeds", "3", "--grid-episodes", "2",
        "--specialist", f"churn={churn_specialist_run}",
        "--results-dir", str(tmp_path)])
    assert summary["schema_version"] == 1
    assert summary["metric"] == "transfer_grid"
    assert summary["mixture"] == get_mixture("generalist").canonical_name()
    assert len(summary["cells"]) == 7 * 2
    assert summary["node_counts"] == [4, 8]
    het = [c for c in summary["cells"]
           if c["scenario"] == "heterogeneous"]
    assert all(c["incompatible"] and c["reason"] == "obs_width"
               for c in het)
    ext_cells = [c for c in summary["cells"] if c["scenario"] == ext]
    assert all(c["held_out"] for c in ext_cells)     # zero-shot column
    churn = [c for c in summary["cells"] if c["scenario"] == "churn"]
    assert all(c["opponent"] == "specialist" for c in churn)
    graded = [c for c in summary["cells"] if not c.get("incompatible")]
    assert graded and all("verdict" in c and "wilson95" in c
                          for c in graded)
    # One JSON line on stdout + the artifact pair on disk.
    out = capsys.readouterr().out
    line = next(l for l in out.splitlines()
                if '"metric": "transfer_grid"' in l)
    assert json.loads(line)["metric"] == "transfer_grid"
    assert (tmp_path / "transfer_grid.jsonl").exists()
    assert (tmp_path / "transfer_grid.json").exists()
    assert "ZERO-SHOT TRANSFER GRID" in \
        (tmp_path / "transfer_grid.txt").read_text()
