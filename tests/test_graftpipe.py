"""graftpipe: pipelined collect/learn + fused update prologue (agent/ppo.py).

The contract under test (ISSUE 10 / docs/roofline.md):

- ``overlap_collect`` OFF is byte-identical to the unpipelined update —
  same RNG draw order and values, same runner pytree leaves (the
  ``collect_params`` slot is ``None``, an empty node).
- ON, iteration k's rollout samples with the 1-iteration-stale
  ``collect_params`` slot, the recorded behavior log-probs come from that
  stale policy, and the loss's ratio is computed against them — exact PPO
  on the recorded behavior policy (the ratio/approx_kl pin below).
- The fused prologue's argsort-permutation + per-minibatch gather
  produces the same minibatch content as the materialized shuffle for the
  same permutation, and GAE at fleet env counts routes through the Pallas
  kernel with the CPU interpret fallback agreeing with the scan.
- Both compose with dp and dp x sp (trajectory equivalence + replicated
  param sync, sharded via the version-compat helper so the numerics run
  on the container's JAX too), ride the full-state checkpoint, and are
  resume-guard-pinned through the real CLI.
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.agent.ppo import (
    PPOTrainConfig,
    RunnerState,
    make_ppo_bundle,
    ppo_train,
    resolve_prologue_gae_impl,
)
from rl_scheduler_tpu.env.bundle import multi_cloud_bundle
from rl_scheduler_tpu.ops.indexing import (
    gather_shuffled_minibatch,
    shuffle_block_perm,
)
from rl_scheduler_tpu.ops.losses import categorical_log_prob

SMALL = PPOTrainConfig(
    num_envs=4, rollout_steps=8, minibatch_size=16, num_epochs=2,
    hidden=(16, 16), rollout_impl="scan",
)


def _leaves_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


def _snapshot(tree):
    """Host copy that survives buffer donation: on the CPU backend
    ``device_get`` can be zero-copy, so a donated update would mutate the
    fetched arrays in place under the comparison."""
    return jax.tree.map(lambda x: np.array(x, copy=True),
                        jax.device_get(tree))


def _run(bundle, cfg, n, seed=0):
    init_fn, update_fn, net = make_ppo_bundle(bundle, cfg)
    update = jax.jit(update_fn, donate_argnums=0)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(seed))
    history = []
    for _ in range(n):
        runner, metrics = update(runner)
        history.append(jax.device_get(metrics))
    return runner, history, net


# ------------------------------------------------- byte-identity pins


def test_off_leaves_runner_layout_and_update_byte_identical():
    """overlap off: the collect slot is an EMPTY pytree node (leaf count
    unchanged from the pre-graftpipe layout — old checkpoints and the
    sharded specs see the same tree), and the default config IS the off
    config."""
    bundle = multi_cloud_bundle()
    assert not SMALL.overlap_collect and not SMALL.prologue_enabled
    init_fn, _, _ = make_ppo_bundle(bundle, SMALL)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    assert runner.collect_params is None
    # None is an empty node: flattening must see exactly the historical
    # leaves, nothing for the slot.
    without = RunnerState(*runner[:7])
    assert len(jax.tree.leaves(runner)) == len(jax.tree.leaves(without))


@pytest.mark.parametrize("rollout_impl", ["scan", "open_loop"])
def test_first_update_bitwise_matches_off_then_diverges(rollout_impl):
    """Pipeline warm-up: iteration 0 collects with collect_params ==
    params (on-policy), so ONE update is bitwise identical to the
    unpipelined path — same RNG draw order and values. From iteration 1
    the behavior policy is one update stale and params diverge."""
    bundle = multi_cloud_bundle()
    base = dataclasses.replace(SMALL, rollout_impl=rollout_impl)
    on = dataclasses.replace(base, overlap_collect=True,
                             fused_prologue="off")
    r_off1, _, _ = _run(bundle, base, 1)
    r_on1, _, _ = _run(bundle, on, 1)
    assert _leaves_equal(r_off1.params, r_on1.params)
    assert _leaves_equal(r_off1.opt_state, r_on1.opt_state)
    assert _leaves_equal(r_off1.key, r_on1.key)

    r_off2, _, _ = _run(bundle, base, 2)
    r_on2, _, _ = _run(bundle, on, 2)
    assert not _leaves_equal(r_off2.params, r_on2.params), (
        "two pipelined updates matched the on-policy path bitwise — the "
        "rollout is not using the stale slot"
    )


def test_collect_slot_carries_entry_params():
    """The pipeline advance: after update k the slot holds update k's
    ENTRY params — available before SGD k completes, which is the broken
    dependency the overlap exists for."""
    bundle = multi_cloud_bundle()
    cfg = dataclasses.replace(SMALL, overlap_collect=True)
    init_fn, update_fn, _ = make_ppo_bundle(bundle, cfg)
    update = jax.jit(update_fn, donate_argnums=0)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(3))
    p0 = _snapshot(runner.params)
    assert _leaves_equal(runner.params, runner.collect_params)  # warm-up
    runner1, _ = update(runner)
    assert _leaves_equal(runner1.collect_params, p0)
    p1 = _snapshot(runner1.params)
    runner2, _ = update(runner1)
    assert _leaves_equal(runner2.collect_params, p1)


# --------------------------------------- exact-PPO-on-behavior pins


def test_behavior_logprobs_recorded_from_stale_params():
    """The recorded log-probs ARE the stale policy's: recomputing them
    under collect_params reproduces the trajectory's log_prob field, and
    recomputing under the fresh params does NOT (the staleness is real)."""
    bundle = multi_cloud_bundle()
    cfg = dataclasses.replace(SMALL, overlap_collect=True,
                              fused_prologue="off")
    init_fn, update_fn, net = make_ppo_bundle(bundle, cfg)
    update = jax.jit(update_fn, donate_argnums=0)
    runner1, _ = update(jax.jit(init_fn)(jax.random.PRNGKey(1)))
    # The collect seam is deterministic in (runner, behavior_params):
    # this re-runs exactly the rollout update 2 will consume.
    _, _, _, _, traj, _ = update_fn.collect(runner1, runner1.collect_params)
    obs = traj["obs"].reshape(-1, *bundle.obs_shape)
    act = traj["action"].reshape(-1)
    stale_logits, _ = net.apply(runner1.collect_params, obs)
    fresh_logits, _ = net.apply(runner1.params, obs)
    stale_lp = categorical_log_prob(stale_logits, act)
    fresh_lp = categorical_log_prob(fresh_logits, act)
    np.testing.assert_allclose(np.asarray(traj["log_prob"]).reshape(-1),
                               np.asarray(stale_lp), rtol=1e-5, atol=1e-6)
    assert not np.allclose(np.asarray(traj["log_prob"]).reshape(-1),
                           np.asarray(fresh_lp), rtol=1e-5, atol=1e-6)


def test_ratio_is_exact_ppo_on_recorded_behavior():
    """The acceptance ratio pin: with one epoch and one whole-batch
    minibatch, the update's approx_kl equals mean(recorded behavior
    log-prob - fresh-params log-prob) computed independently — i.e. the
    loss's ratio is exp(log pi_current - log pi_behavior) on the RECORDED
    behavior policy, nothing resampled or recomputed."""
    bundle = multi_cloud_bundle()
    cfg = dataclasses.replace(
        SMALL, overlap_collect=True, fused_prologue="off",
        num_epochs=1, minibatch_size=SMALL.num_envs * SMALL.rollout_steps,
    )
    init_fn, update_fn, net = make_ppo_bundle(bundle, cfg)
    update = jax.jit(update_fn, donate_argnums=0)
    runner1, _ = update(jax.jit(init_fn)(jax.random.PRNGKey(5)))
    _, _, _, _, traj, _ = update_fn.collect(runner1, runner1.collect_params)
    obs = traj["obs"].reshape(-1, *bundle.obs_shape)
    act = traj["action"].reshape(-1)
    fresh_logits, _ = net.apply(runner1.params, obs)
    expected_kl = float(jnp.mean(
        traj["log_prob"].reshape(-1)
        - categorical_log_prob(fresh_logits, act)))
    _, metrics = update(runner1)
    assert float(metrics["approx_kl"]) == pytest.approx(expected_kl,
                                                        rel=1e-4, abs=1e-6)


def test_overlap_composes_with_sample_temp_anneal():
    """tau comes from the collecting iteration's index and is applied to
    the STALE params consistently (sampling, stored log-probs, loss) —
    the first update stays bitwise identical to the unpipelined tempered
    path, and the stale recompute must use the same tau."""
    bundle = multi_cloud_bundle()
    tempered = dataclasses.replace(SMALL, sample_temp_end=0.5,
                                   sample_temp_iters=4)
    on = dataclasses.replace(tempered, overlap_collect=True,
                             fused_prologue="off")
    r_off1, _, _ = _run(bundle, tempered, 1, seed=9)
    r_on1, _, _ = _run(bundle, on, 1, seed=9)
    assert _leaves_equal(r_off1.params, r_on1.params)

    from rl_scheduler_tpu.agent.ppo import sample_temperature

    init_fn, update_fn, net = make_ppo_bundle(bundle, on)
    runner1, _ = jax.jit(update_fn, donate_argnums=0)(
        jax.jit(init_fn)(jax.random.PRNGKey(9)))
    _, _, _, _, traj, _ = update_fn.collect(runner1, runner1.collect_params)
    obs = traj["obs"].reshape(-1, *bundle.obs_shape)
    act = traj["action"].reshape(-1)
    tau = sample_temperature(on, runner1.update_idx)
    logits, _ = net.apply(runner1.collect_params, obs)
    np.testing.assert_allclose(
        np.asarray(traj["log_prob"]).reshape(-1),
        np.asarray(categorical_log_prob(logits / tau, act)),
        rtol=1e-5, atol=1e-6)


# ------------------------------------------------- fused prologue


def test_shuffle_block_perm_is_a_deterministic_permutation():
    key = jax.random.PRNGKey(0)
    perm = shuffle_block_perm(key, 257)
    assert np.array_equal(np.sort(np.asarray(perm)), np.arange(257))
    assert np.array_equal(np.asarray(shuffle_block_perm(key, 257)),
                          np.asarray(perm))
    assert not np.array_equal(
        np.asarray(shuffle_block_perm(jax.random.PRNGKey(1), 257)),
        np.asarray(perm))


def test_gather_shuffled_minibatch_matches_materialized_shuffle():
    """The fused shuffle-gather equivalence: for the same permutation,
    per-minibatch gathers from the unshuffled batch reproduce the
    materialized ``packed[perm]`` minibatches exactly."""
    num_blocks, row_width, mb_blocks = 24, 6, 4
    packed_blocks = jnp.arange(num_blocks * row_width, dtype=jnp.float32)
    packed_blocks = packed_blocks.reshape(num_blocks, row_width)
    perm = shuffle_block_perm(jax.random.PRNGKey(7), num_blocks)
    materialized = np.asarray(packed_blocks)[np.asarray(perm)]
    for i in range(num_blocks // mb_blocks):
        fused = gather_shuffled_minibatch(packed_blocks, perm,
                                          jnp.int32(i), mb_blocks)
        np.testing.assert_array_equal(
            np.asarray(fused), materialized[i * mb_blocks:(i + 1) * mb_blocks])


def test_prologue_update_matches_unfused_on_single_minibatch():
    """With one whole-batch minibatch the permutation only reorders rows
    inside the same normalization/reduction set, so the fused prologue
    must reproduce the unfused update up to summation order."""
    bundle = multi_cloud_bundle()
    base = dataclasses.replace(
        SMALL, num_epochs=1,
        minibatch_size=SMALL.num_envs * SMALL.rollout_steps)
    fused = dataclasses.replace(base, fused_prologue="on")
    r_a, h_a, _ = _run(bundle, base, 2, seed=11)
    r_b, h_b, _ = _run(bundle, fused, 2, seed=11)
    for a, b in zip(jax.tree.leaves(r_a.params), jax.tree.leaves(r_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
    assert h_a[-1]["reward_mean"] == pytest.approx(h_b[-1]["reward_mean"],
                                                   rel=1e-5)


def test_prologue_gae_routing_and_interpret_parity():
    """Fleet env counts route an "auto" GAE through the Pallas kernel
    (CPU: interpret fallback); small counts keep the scan; an explicit
    impl is respected. The interpret kernel agrees with the scan across
    a block boundary."""
    from rl_scheduler_tpu.ops.gae import gae
    from rl_scheduler_tpu.ops.pallas_gae import gae_pallas

    small = dataclasses.replace(SMALL, fused_prologue="on")
    fleet = dataclasses.replace(small, num_envs=512)
    pinned = dataclasses.replace(fleet, gae_impl="scan")
    assert resolve_prologue_gae_impl(fleet) == "pallas"
    assert resolve_prologue_gae_impl(pinned) == "scan"
    if jax.default_backend() != "tpu":
        assert resolve_prologue_gae_impl(small) == "scan"

    t, n = 7, 600  # crosses the kernel's 512-lane column block boundary
    key = jax.random.PRNGKey(0)
    kr, kv, kd, kl = jax.random.split(key, 4)
    rewards = jax.random.normal(kr, (t, n))
    values = jax.random.normal(kv, (t, n))
    dones = (jax.random.uniform(kd, (t, n)) < 0.1).astype(jnp.float32)
    last = jax.random.normal(kl, (n,))
    adv_s, tgt_s = gae(rewards, values, dones, last, 0.99, 0.95, impl="scan")
    adv_p, tgt_p = gae_pallas(rewards, values, dones, last, 0.99, 0.95,
                              interpret=True)
    np.testing.assert_allclose(np.asarray(adv_p), np.asarray(adv_s),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(tgt_p), np.asarray(tgt_s),
                               rtol=1e-6, atol=1e-6)


# ------------------------------------------- learning + dispatch


def test_fused_dispatch_overlap_matches_sequential():
    """updates_per_dispatch over the pipelined update is pure dispatch
    plumbing — the scan-over-updates program (the overlap substrate) must
    reproduce the one-by-one pipelined metrics."""
    bundle = multi_cloud_bundle()
    cfg = dataclasses.replace(SMALL, overlap_collect=True)
    _, h_seq = ppo_train(bundle, cfg, 4, seed=7)
    _, h_fused = ppo_train(bundle, cfg, 4, seed=7, updates_per_dispatch=2)
    assert len(h_fused) == 4
    for a, b in zip(h_seq, h_fused):
        assert a["policy_loss"] == pytest.approx(b["policy_loss"], rel=1e-5)
        assert a["reward_mean"] == pytest.approx(b["reward_mean"], rel=1e-6)


def test_overlap_learning_progress():
    """The 1-iteration-stale behavior policy still learns the flagship
    table. Measured honestly: at this smoke recipe's aggressive lr
    (3e-3, 4 epochs) staleness costs a little sample efficiency — 30
    iterations reach 0.81-0.91 greedy row accuracy across seeds where the
    on-policy run reaches 0.95 (tests/test_ppo.py) — so the bar here is
    substantial learning (far above the 0.5 chance level) plus a large
    reward gain, and the sample-efficiency note lives in docs/scaling.md
    §1b next to the staleness semantics."""
    from rl_scheduler_tpu.config import EnvConfig
    from rl_scheduler_tpu.env import core as env_core
    from tests.test_ppo import SMOKE_CFG, greedy_row_accuracy

    env_params = env_core.make_params(EnvConfig())
    cfg = dataclasses.replace(SMOKE_CFG, rollout_impl="scan",
                              overlap_collect=True)
    runner, history = ppo_train(env_params, cfg, 30, seed=0)
    accuracy = greedy_row_accuracy(runner, env_params, SMOKE_CFG.hidden)
    assert accuracy >= 0.75, (
        f"pipelined greedy policy only matches the optimum on "
        f"{accuracy:.0%} of rows — staleness should cost a little sample "
        "efficiency, not learning")
    first, last = (history[0]["episode_reward_mean"],
                   history[-1]["episode_reward_mean"])
    assert last - first > 0.15 * abs(first), (
        f"no learning progress under overlap: {first:.1f} -> {last:.1f}")


# --------------------------------------------------- dp / dp x sp


def _compat_sharded(bundle, cfg, mesh, net=None, axes=("dp",)):
    """The LIBRARY's per-member wrappers (parallel/sharding.py
    make_local_ppo), sharded through the version-compat helper so the
    numerics run on the container's JAX too (the library call sites keep
    jax.shard_map — tests/test_sharding.py covers them where it
    exists)."""
    from jax.sharding import PartitionSpec as P

    from rl_scheduler_tpu.parallel.mesh import shard_map_compat
    from rl_scheduler_tpu.parallel.sharding import make_local_ppo

    dp = mesh.shape["dp"]
    local_cfg = dataclasses.replace(
        cfg, num_envs=cfg.num_envs // dp,
        minibatch_size=cfg.minibatch_size // dp)
    sp_axis = "sp" if "sp" in axes else None
    local_init, local_update, specs, net = make_local_ppo(
        bundle, local_cfg, "dp", net=net, sp_axis=sp_axis)
    sharded_init = jax.jit(shard_map_compat(
        local_init, mesh, in_specs=P(), out_specs=specs))
    sharded_update = jax.jit(shard_map_compat(
        local_update, mesh, in_specs=(specs,), out_specs=(specs, P())))
    return sharded_init, sharded_update, local_cfg, net


def test_dp_overlap_trajectory_equivalence_and_sync():
    """dp-sharded pipelined update: each shard's env trajectory equals the
    single-device pipelined run with that shard's folded key, bitwise,
    across TWO updates (the second consumes the stale slot — both runs
    share it because the warm-up slot is the replicated init params), and
    params stay replicated bit-identical (pmean sync)."""
    from rl_scheduler_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    bundle = multi_cloud_bundle()
    cfg = dataclasses.replace(SMALL, num_envs=8, minibatch_size=16,
                              overlap_collect=True)
    mesh = make_mesh({"dp": 2})
    sh_init, sh_update, local_cfg, _ = _compat_sharded(bundle, cfg, mesh)
    rs = sh_init(jax.random.PRNGKey(0))
    rs, _ = sh_update(rs)
    rs, _ = sh_update(rs)

    # Per-shard reference: the single-device pipelined update, seeded the
    # way the library's local_init does — env/rollout streams from the
    # dp-folded key, the replicated leaves (params, optimizer state, the
    # stale slot) from the unfolded one.
    init_l, update_l, _ = make_ppo_bundle(bundle, local_cfg)
    shared = jax.jit(init_l)(jax.random.PRNGKey(0))
    for d in range(2):
        key = jax.random.fold_in(jax.random.PRNGKey(0), d)
        r = jax.jit(init_l)(key)
        r = r._replace(params=shared.params, opt_state=shared.opt_state,
                       collect_params=shared.collect_params)
        r, _ = jax.jit(update_l)(r)
        r, _ = jax.jit(update_l)(r)
        sharded_obs = np.asarray(
            jax.device_get(rs.obs))[d * local_cfg.num_envs:(d + 1)
                                    * local_cfg.num_envs]
        np.testing.assert_array_equal(sharded_obs,
                                      np.asarray(jax.device_get(r.obs)))
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(rs.ep_return))[
                d * local_cfg.num_envs:(d + 1) * local_cfg.num_envs],
            np.asarray(jax.device_get(r.ep_return)))

    for leaf in jax.tree.leaves(rs.params) + jax.tree.leaves(
            rs.collect_params):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        assert all(np.array_equal(shards[0], s) for s in shards[1:]), (
            "replicated leaves diverged across dp shards")


def test_dp_sp_overlap_update_finite_and_synced():
    """dp x sp composition at a fleet node count: the pipelined update
    through the node-axis-sharded flax policy (SeqParallelNet ring
    machinery) stays finite, keeps params AND the stale slot replicated,
    and advances the slot to the entry params."""
    from rl_scheduler_tpu.env import cluster_set as cs
    from rl_scheduler_tpu.env.bundle import cluster_set_bundle
    from rl_scheduler_tpu.models import SetTransformerPolicy
    from rl_scheduler_tpu.parallel.mesh import make_mesh
    from rl_scheduler_tpu.parallel.sharding import SeqParallelNet

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    num_nodes = 32
    bundle = cluster_set_bundle(cs.make_params(num_nodes=num_nodes))
    cfg = PPOTrainConfig(num_envs=4, rollout_steps=8, minibatch_size=8,
                         num_epochs=2, overlap_collect=True)
    mesh = make_mesh({"dp": 2, "sp": 2})
    net = SeqParallelNet(
        SetTransformerPolicy(dim=16, depth=1, axis_name="sp"), "sp", 2)
    sh_init, sh_update, _, _ = _compat_sharded(
        bundle, cfg, mesh, net=net, axes=("dp", "sp"))
    rs = sh_init(jax.random.PRNGKey(2))
    p0 = jax.device_get(rs.params)
    rs, metrics = sh_update(rs)
    assert np.isfinite(float(metrics["policy_loss"]))
    assert np.isfinite(float(metrics["value_loss"]))
    assert _leaves_equal(rs.collect_params, p0)
    for leaf in jax.tree.leaves(rs.params):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        assert all(np.array_equal(shards[0], s) for s in shards[1:])


# ------------------------------------------------------ CLI + resume


def _cli_args(root, name, extra=()):
    return ["--preset", "quick", "--env", "multi_cloud", "--num-envs", "4",
            "--rollout-steps", "8", "--minibatch-size", "16",
            "--num-epochs", "2", "--hidden", "8,8", "--run-root", str(root),
            "--run-name", name, "--checkpoint-every", "2", *extra]


def test_cli_overlap_meta_resume_guard_and_legacy(tmp_path):
    """--overlap-collect is meta-recorded; --resume refuses a flag flip in
    BOTH directions (a run without the key — legacy — counts as off)."""
    from rl_scheduler_tpu.agent import train_ppo as cli
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    on = _cli_args(tmp_path, "on_run", ("--overlap-collect",))
    run_dir = cli.main(on + ["--iterations", "2"])
    meta = CheckpointManager(run_dir).restore_meta(2)
    assert meta["overlap_collect"] is True
    assert meta["full_state"] is True

    with pytest.raises(SystemExit, match="overlap-collect"):
        cli.main(_cli_args(tmp_path, "on_run") + ["--iterations", "4",
                                                  "--resume"])

    off = _cli_args(tmp_path, "off_run")
    run_dir = cli.main(off + ["--iterations", "2"])
    assert CheckpointManager(run_dir).restore_meta(2)[
        "overlap_collect"] is False
    with pytest.raises(SystemExit, match="unpipelined"):
        cli.main(_cli_args(tmp_path, "off_run",
                           ("--overlap-collect",)) + ["--iterations", "4",
                                                      "--resume"])


def test_cli_overlap_interrupt_resume_bitwise(tmp_path):
    """The graftguard deterministic-resume guarantee extends to the
    pipelined runner: a 2+2 resumed run replays iterations 3-4 of the
    straight 4-iteration run exactly (the in-flight collect_params slot
    rides the full-state checkpoint; without it the resumed pipeline
    would restart warm and diverge)."""
    from rl_scheduler_tpu.agent import train_ppo as cli

    def rewards(run_dir):
        out = {}
        for line in (run_dir / "metrics.jsonl").read_text().splitlines():
            row = json.loads(line)
            if "iteration" in row and "reward_mean" in row:
                out[row["iteration"]] = row["reward_mean"]
        return out

    straight = cli.main(_cli_args(tmp_path, "straight",
                                  ("--overlap-collect",))
                        + ["--iterations", "4"])
    cli.main(_cli_args(tmp_path, "resumed", ("--overlap-collect",))
             + ["--iterations", "2"])
    resumed = cli.main(_cli_args(tmp_path, "resumed", ("--overlap-collect",))
                       + ["--iterations", "4", "--resume"])
    a, b = rewards(straight), rewards(resumed)
    for i in (3, 4):
        assert a[i] == b[i], (
            f"iteration {i} diverged after resume: {a[i]} != {b[i]} — the "
            "stale-params slot did not survive the checkpoint round-trip")


def test_learning_state_only_resume_restarts_pipeline_warm():
    """A params-only restore (sharded paths, changed env shape, legacy
    trees) must seed the slot with the RESTORED params — not leave the
    fresh init's random weights collecting one rollout."""
    from rl_scheduler_tpu.config import EnvConfig
    from rl_scheduler_tpu.env import core as env_core

    env_params = env_core.make_params(EnvConfig())
    cfg = dataclasses.replace(SMALL, hidden=(8, 8), overlap_collect=True)
    runner_a, _ = ppo_train(env_params, cfg, 2, seed=7)
    tree = {"params": _snapshot(runner_a.params),
            "opt_state": _snapshot(runner_a.opt_state)}
    runner_b, history = ppo_train(env_params, cfg, 3, seed=7,
                                  restore=(dict(tree), 2))
    assert len(history) == 1
    # After ONE continued update the slot holds that update's entry
    # params == the restored params (warm restart).
    assert _leaves_equal(runner_b.collect_params, tree["params"])


def test_full_state_overlap_tree_restored_with_overlap_off_drops_slot():
    """API callers bypass the CLI's resume guard: restoring an
    overlap-trained FULL-STATE tree with overlap off must drop the slot
    (collect_params stays None) instead of installing a carry the
    unpipelined update cannot return — which crashed the fused-dispatch
    scan with a pytree-structure mismatch before the guard here."""
    from rl_scheduler_tpu.config import EnvConfig
    from rl_scheduler_tpu.env import core as env_core

    env_params = env_core.make_params(EnvConfig())
    on_cfg = dataclasses.replace(SMALL, hidden=(8, 8), overlap_collect=True)
    runner_a, _ = ppo_train(env_params, on_cfg, 2, seed=3)
    tree = {"params": _snapshot(runner_a.params),
            "opt_state": _snapshot(runner_a.opt_state),
            "loop": {"env_state": _snapshot(runner_a.env_state),
                     "obs": _snapshot(runner_a.obs),
                     "key": _snapshot(runner_a.key),
                     "ep_return": _snapshot(runner_a.ep_return),
                     "update_idx": _snapshot(runner_a.update_idx),
                     "collect_params": _snapshot(runner_a.collect_params)}}
    off_cfg = dataclasses.replace(SMALL, hidden=(8, 8))
    runner_b, history = ppo_train(env_params, off_cfg, 4, seed=3,
                                  restore=(tree, 2), updates_per_dispatch=2)
    assert runner_b.collect_params is None
    assert len(history) == 2
    assert np.isfinite(history[-1]["policy_loss"])


def test_cli_overlap_refused_with_tp():
    from rl_scheduler_tpu.agent import train_ppo as cli

    with pytest.raises(SystemExit, match="tensor-parallel"):
        cli.main(["--preset", "quick", "--iterations", "1", "--hidden",
                  "8,8", "--overlap-collect", "--tp", "2"])


def test_ppo_train_refuses_overlap_with_tp_mesh():
    """The library-level guard (API callers, not just the CLI)."""
    from rl_scheduler_tpu.config import EnvConfig
    from rl_scheduler_tpu.env import core as env_core
    from rl_scheduler_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    cfg = dataclasses.replace(SMALL, hidden=(8, 8), overlap_collect=True)
    with pytest.raises(ValueError, match="tensor-parallel"):
        ppo_train(env_core.make_params(EnvConfig()), cfg, 1,
                  mesh=make_mesh({"tp": 2}))
