"""Checkify debug mode: clean runs pass; injected NaNs raise with context."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import checkify

from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, ppo_train
from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.utils.debug import checkified_update

CFG = PPOTrainConfig(num_envs=4, rollout_steps=8, minibatch_size=16,
                     num_epochs=1, hidden=(8, 8))


def test_clean_training_passes_checks():
    env_params = env_core.make_params(EnvConfig())
    _, history = ppo_train(env_params, CFG, 2, seed=0, debug_checks=True)
    assert np.isfinite(history[-1]["policy_loss"])


def test_injected_nan_raises():
    def bad_update(state):
        x = state["x"]
        y = jnp.log(x)  # NaN for the negative entry
        return {"x": x + 1.0}, {"out": y.sum()}

    update = checkified_update(bad_update, donate=False)
    with pytest.raises(checkify.JaxRuntimeError, match="nan"):
        update({"x": jnp.array([1.0, -1.0])})


def test_division_by_zero_raises():
    def bad_div(state):
        return state, {"v": state["a"] // state["b"]}

    update = checkified_update(bad_div, donate=False)
    with pytest.raises(checkify.JaxRuntimeError):
        update({"a": jnp.asarray(4), "b": jnp.asarray(0)})


def test_out_of_bounds_gather_raises():
    def bad_gather(state):
        table = state["t"]
        # index 10 is out of bounds for a length-4 table
        return state, {"v": table[jnp.asarray(10)]}

    update = checkified_update(bad_gather, donate=False)
    with pytest.raises(checkify.JaxRuntimeError):
        update({"t": jnp.arange(4.0)})


def test_dqn_clean_training_passes_checks():
    """--debug-checks parity for the DQN path (VERDICT r1 weak #4)."""
    from rl_scheduler_tpu.agent.dqn import DQNConfig, dqn_train
    from rl_scheduler_tpu.env.bundle import multi_cloud_bundle

    cfg = DQNConfig(num_envs=4, collect_steps=4, buffer_size=256,
                    batch_size=16, learning_starts=16, hidden=(8, 8))
    _, history = dqn_train(multi_cloud_bundle(), cfg, 8, seed=0,
                           debug_checks=True)
    assert len(history) == 8
    assert np.isfinite(history[-1]["loss"])


def test_dqn_debug_checks_reject_fused_dispatch():
    from rl_scheduler_tpu.agent.dqn import DQNConfig, dqn_train
    from rl_scheduler_tpu.env.bundle import multi_cloud_bundle

    with pytest.raises(ValueError, match="updates_per_dispatch"):
        dqn_train(multi_cloud_bundle(), DQNConfig(), 4,
                  debug_checks=True, updates_per_dispatch=2)
