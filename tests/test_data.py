"""Data pipeline tests: determinism, golden values, validation, loaders."""

import numpy as np
import pandas as pd
import pytest

from rl_scheduler_tpu.data.generate import (
    AWS_COST_BASE,
    AZURE_COST_BASE,
    generate_all,
    generate_load_history,
)
from rl_scheduler_tpu.data.loader import (
    default_data_dir,
    ensure_dataset,
    load_single_cluster_trace,
    load_table,
)
from rl_scheduler_tpu.data.normalize import normalize


def test_generate_deterministic(tmp_path):
    a = generate_all(tmp_path / "a")
    b = generate_all(tmp_path / "b")
    pd.testing.assert_frame_equal(a, b)


def test_generate_anchors(tmp_path):
    df = generate_all(tmp_path)
    assert len(df) == 100
    assert np.allclose(df["cost_aws"].mean(), AWS_COST_BASE, atol=2e-4)
    assert np.allclose(df["cost_azure"].mean(), AZURE_COST_BASE, atol=2e-4)
    assert (df["cost_aws"] - AWS_COST_BASE).abs().max() <= 0.001
    assert df["latency_aws"].between(60, 80).all()
    assert df["latency_azure"].between(50, 70).all()


def test_normalize_range_and_no_nan(reference_table):
    t = reference_table
    assert len(t) == 100
    cols = ["cost_aws", "cost_azure", "latency_aws", "latency_azure", "cpu_aws", "cpu_azure"]
    assert not t[cols].isna().any().any()
    assert (t[cols].min() >= -1e-9).all()
    assert (t[cols].max() <= 1 + 1e-9).all()
    # cost/latency columns hit both ends of the MinMax range
    for c in cols[:4]:
        assert t[c].min() == pytest.approx(0.0, abs=1e-12)
        assert t[c].max() == pytest.approx(1.0, abs=1e-12)


def test_normalize_golden_row0(reference_table):
    """Golden values: row 0 of the normalized table must match the
    reference's shipped data/processed/normalized_rl_data.csv."""
    row = reference_table.iloc[0]
    assert row["cost_aws"] == pytest.approx(0.37602530109083077, rel=1e-9)
    assert row["cost_azure"] == pytest.approx(0.025009805949220976, rel=1e-9)
    assert row["latency_aws"] == pytest.approx(0.6466751913980993, rel=1e-9)
    assert row["latency_azure"] == pytest.approx(0.03820078616014877, rel=1e-9)


def test_legacy_nan_cpu_mode(tmp_path):
    raw = generate_all(tmp_path)
    legacy = normalize(raw, legacy_nan_cpu=True)
    assert legacy["cpu_aws"].isna().sum() == 99  # reference bug reproduced
    fixed = normalize(raw, legacy_nan_cpu=False)
    assert fixed["cpu_aws"].isna().sum() == 0


def test_ensure_dataset_bootstraps(tmp_path):
    processed = ensure_dataset(tmp_path)
    assert processed.exists()
    df = pd.read_csv(processed)
    assert len(df) == 100


def test_load_table_shapes():
    table = load_table()
    assert table.costs.shape == (100, 2)
    assert table.latencies.shape == (100, 2)
    assert table.num_steps == 100
    assert table.num_clouds == 2
    assert table.costs.dtype.name == "float32"


def test_load_table_rejects_nan(tmp_path):
    bad = pd.DataFrame(
        {
            "cost_aws": [0.1, np.nan],
            "cost_azure": [0.2, 0.3],
            "latency_aws": [0.1, 0.2],
            "latency_azure": [0.1, 0.2],
        }
    )
    p = tmp_path / "bad.csv"
    bad.to_csv(p, index=False)
    with pytest.raises(ValueError, match="NaN"):
        load_table(p)


def test_single_cluster_trace(tmp_path):
    p = tmp_path / "history.csv"
    generate_load_history(p)
    trace = load_single_cluster_trace(p)
    assert trace.shape == (297, 3)
    assert float(trace.min()) >= 0.0 and float(trace.max()) <= 1.0


def test_default_data_dir_in_repo():
    assert default_data_dir().name == "data"
    assert default_data_dir().parent.name == "repo"


# Every loose data file the reference ships (reference data/ listing); the
# repo's data dir must round-trip the full schema family.
REFERENCE_DATA_FILES = (
    "real_prices.csv",
    "real_latencies.csv",
    "local_aws_load_stats.csv",
    "local_azure_load_stats.csv",
    "local_aws_load_failures.csv",
    "local_azure_load_failures.csv",
    "local_aws_load_stats_history.csv",
    "local_azure_load_stats_history.csv",
    "local_aws_load_exceptions.csv",
    "local_azure_load_exceptions.csv",
)


def test_data_dir_has_full_reference_schema():
    missing = [f for f in REFERENCE_DATA_FILES if not (default_data_dir() / f).exists()]
    assert not missing, f"data/ lacks reference files: {missing}"


def test_generate_load_histories_full_locust_schema(tmp_path):
    from rl_scheduler_tpu.data.generate import (
        LOCUST_HISTORY_COLUMNS,
        generate_load_histories,
    )

    written = generate_load_histories(tmp_path)
    assert len(written) == 2
    aws = pd.read_csv(tmp_path / "local_aws_load_stats_history.csv")
    azure = pd.read_csv(tmp_path / "local_azure_load_stats_history.csv")
    for df in (aws, azure):
        assert tuple(df.columns) == LOCUST_HISTORY_COLUMNS
        assert len(df) == 297  # reference history length
        assert (df["Total Request Count"].diff().dropna() >= 0).all()
    # per-cloud seeds differ: the two clouds are not identical copies
    assert not aws["Requests/s"].equals(azure["Requests/s"])
    # loader accepts the full-schema export
    trace = load_single_cluster_trace(tmp_path / "local_azure_load_stats_history.csv")
    assert trace.shape == (297, 3)
    # existing exports are preserved without overwrite
    assert generate_load_histories(tmp_path) == []


def test_generate_load_exceptions_header_only(tmp_path):
    from rl_scheduler_tpu.data.loadtest import (
        LOCUST_EXCEPTIONS_COLUMNS,
        generate_load_exceptions,
    )

    written = generate_load_exceptions(tmp_path)
    assert len(written) == 2
    for cloud in ("aws", "azure"):
        df = pd.read_csv(tmp_path / f"local_{cloud}_load_exceptions.csv")
        assert tuple(df.columns) == LOCUST_EXCEPTIONS_COLUMNS
        assert df.empty  # clean run: header only, like the reference's
    assert generate_load_exceptions(tmp_path) == []


class TestLoadtestCalibration:
    def test_generate_and_failure_rate_roundtrip(self, tmp_path):
        from rl_scheduler_tpu.data.loadtest import (
            SYNTH_REQUESTS,
            failure_rate,
            generate_load_stats,
        )

        counts = generate_load_stats(tmp_path, seed=7)
        rate = failure_rate(tmp_path)
        expect = sum(counts.values()) / (2 * SYNTH_REQUESTS)
        assert rate == pytest.approx(expect)
        assert 0.0 < rate < 0.1
        # deterministic given seed (overwrite needed: existing exports
        # are never clobbered by default)
        assert generate_load_stats(tmp_path, seed=7, overwrite=True) == counts
        # without overwrite, existing exports are preserved untouched
        before = (tmp_path / "local_aws_load_stats.csv").read_text()
        assert generate_load_stats(tmp_path, seed=99) == {}
        assert (tmp_path / "local_aws_load_stats.csv").read_text() == before

    def test_failure_rate_none_without_exports(self, tmp_path):
        from rl_scheduler_tpu.data.loadtest import failure_rate

        assert failure_rate(tmp_path) is None

    def test_failure_rate_skips_header_only_export(self, tmp_path):
        from rl_scheduler_tpu.data.loadtest import failure_rate

        (tmp_path / "local_aws_load_stats.csv").write_text(
            "Type,Name,Request Count,Failure Count\n"
        )
        assert failure_rate(tmp_path) is None

    def test_reference_schema_parses(self, tmp_path):
        """The reference's recorded run (100% failures) parses to rate 1.0."""
        from rl_scheduler_tpu.data.loadtest import failure_rate

        header = ("Type,Name,Request Count,Failure Count,Median Response Time,"
                  "Average Response Time,Min Response Time,Max Response Time,"
                  "Average Content Size,Requests/s,Failures/s,50%,66%,75%,80%,"
                  "90%,95%,98%,99%,99.9%,99.99%,100%")
        row = "GET,/,2980,2980,2,2.82,0.55,595.8,0.0,9.94,9.94," + ",".join(["3"] * 11)
        agg = ",Aggregated,2980,2980,2,2.82,0.55,595.8,0.0,9.94,9.94," + ",".join(["3"] * 11)
        (tmp_path / "local_aws_load_stats.csv").write_text(f"{header}\n{row}\n{agg}\n")
        assert failure_rate(tmp_path) == pytest.approx(1.0)

    def test_train_cli_fault_from_loadtest(self, tmp_path):
        from rl_scheduler_tpu.agent import train_ppo as cli

        run_dir = cli.main([
            "--preset", "quick", "--num-envs", "4", "--rollout-steps", "8",
            "--minibatch-size", "16", "--hidden", "8,8", "--iterations", "1",
            "--run-root", str(tmp_path), "--run-name", "fault_test",
            "--fault-from-loadtest",
        ])
        assert run_dir.exists()
