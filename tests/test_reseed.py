"""The --reseed-on-stall bad-seed guard (docs/scaling.md §1b).

At fleet N the structured policies' greedy eval is seed-fragile: a bad
seed's in-training eval never crosses the node-baseline threshold while
its stochastic training reward looks healthy. The guard automates the
measured detection recipe — eval by iteration ~16, reseed if below the
best hand-coded node baseline. These tests pin the CLI contract and the
restart mechanics on tiny CPU configs (the threshold is monkeypatched;
the measured thresholds themselves live in the docs).
"""

import json

import pytest

from rl_scheduler_tpu.agent import train_ppo as cli


TINY = [
    "--env", "cluster_set", "--num-nodes", "4", "--num-envs", "4",
    "--rollout-steps", "8", "--minibatch-size", "16", "--num-epochs", "1",
]


def _run(tmp_path, name, extra, monkeypatch=None, threshold=None):
    if monkeypatch is not None:
        monkeypatch.setattr(
            "rl_scheduler_tpu.agent.evaluate.best_node_baseline_reward",
            lambda *a, **k: threshold,
        )
    return cli.main(TINY + ["--run-root", str(tmp_path),
                            "--run-name", name] + extra)


def _metrics_lines(tmp_path, name):
    path = tmp_path / name / "metrics.jsonl"
    return [json.loads(l) for l in path.read_text().splitlines()]


class TestValidation:
    def test_flat_env_refused(self, tmp_path):
        with pytest.raises(SystemExit, match="node baselines"):
            cli.main(["--env", "multi_cloud", "--reseed-on-stall", "1",
                      "--eval-every", "1", "--run-root", str(tmp_path)])

    def test_needs_eval_signal(self, tmp_path):
        with pytest.raises(SystemExit, match="--eval-every"):
            _run(tmp_path, "x", ["--reseed-on-stall", "1",
                                 "--iterations", "30"])

    def test_eval_after_deadline_refused(self, tmp_path):
        with pytest.raises(SystemExit, match="never trigger"):
            _run(tmp_path, "x", ["--reseed-on-stall", "1",
                                 "--eval-every", "20",
                                 "--stall-deadline", "16",
                                 "--iterations", "30"])

    def test_deadline_past_end_refused(self, tmp_path):
        with pytest.raises(SystemExit, match="end of training"):
            _run(tmp_path, "x", ["--reseed-on-stall", "1",
                                 "--eval-every", "1",
                                 "--stall-deadline", "16",
                                 "--iterations", "10"])

    def test_negative_count_refused(self, tmp_path):
        with pytest.raises(SystemExit, match="reseed count"):
            _run(tmp_path, "x", ["--reseed-on-stall", "-1"])

    def test_resume_contradiction_refused(self, tmp_path):
        with pytest.raises(SystemExit, match="--resume"):
            _run(tmp_path, "x", ["--reseed-on-stall", "1",
                                 "--eval-every", "1",
                                 "--stall-deadline", "1",
                                 "--iterations", "3", "--resume"])


class TestReseedMechanics:
    def test_stall_reseeds_then_finishes(self, tmp_path, monkeypatch):
        """An unreachable threshold exhausts the reseed budget: each
        abandoned attempt leaves a marker line + cleared checkpoints,
        and the FINAL attempt still runs to completion (warn, don't
        abort: the run must always produce a usable checkpoint)."""
        _run(tmp_path, "stall", ["--reseed-on-stall", "2",
                                 "--eval-every", "1",
                                 "--stall-deadline", "1",
                                 "--iterations", "3",
                                 "--checkpoint-every", "1",
                                 "--seed", "7"],
             monkeypatch=monkeypatch, threshold=float("inf"))
        markers = [l for l in _metrics_lines(tmp_path, "stall")
                   if "reseed" in l]
        assert [m["reseed"] for m in markers] == [1, 2]
        assert markers[0]["from_seed"] == 7
        assert markers[1]["to_seed"] == 9
        assert all(m["threshold"] == float("inf") for m in markers)

        from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

        mgr = CheckpointManager(tmp_path / "stall")
        # Only the final attempt's checkpoints survive; its meta carries
        # the seed that actually trained the surviving weights.
        assert mgr.latest_step() == 3
        assert mgr.restore_meta(3)["seed"] == 9
        mgr.close()

    def test_healthy_run_never_reseeds(self, tmp_path, monkeypatch):
        """A crossable threshold (-inf) leaves the run untouched: no
        marker lines, original seed in meta."""
        _run(tmp_path, "ok", ["--reseed-on-stall", "2",
                              "--eval-every", "1",
                              "--stall-deadline", "1",
                              "--iterations", "2",
                              "--checkpoint-every", "1",
                              "--seed", "5"],
             monkeypatch=monkeypatch, threshold=float("-inf"))
        assert not [l for l in _metrics_lines(tmp_path, "ok")
                    if "reseed" in l]

        from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

        mgr = CheckpointManager(tmp_path / "ok")
        assert mgr.restore_meta(mgr.latest_step())["seed"] == 5
        mgr.close()

    def test_resume_preserves_init_seed(self, tmp_path):
        """--resume under a different --seed must not overwrite the meta
        seed: the recorded seed attributes the weights' INITIALIZATION,
        not the latest invocation's RNG stream."""
        _run(tmp_path, "res", ["--iterations", "1",
                               "--checkpoint-every", "1", "--seed", "7"])
        _run(tmp_path, "res", ["--iterations", "2",
                               "--checkpoint-every", "1", "--resume"])
        from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

        mgr = CheckpointManager(tmp_path / "res")
        assert mgr.restore_meta(2)["seed"] == 7
        mgr.close()

    def test_resume_legacy_checkpoint_records_null_seed(self, tmp_path):
        """Resuming a pre-seed-key checkpoint must record an explicit
        null, not misattribute the weights to this invocation's --seed."""
        _run(tmp_path, "leg", ["--iterations", "1",
                               "--checkpoint-every", "1", "--seed", "7"])
        # Strip the seed key in place: the on-disk shape of a checkpoint
        # written before the key existed.
        meta_file = (tmp_path / "leg" / "checkpoints" / "1" / "meta"
                     / "metadata")
        meta = json.loads(meta_file.read_text())
        del meta["seed"]
        meta_file.write_text(json.dumps(meta))
        # A pre-seed-key run also predates integrity manifests; without
        # this the edit above reads as a tampered file and graftguard
        # (correctly) quarantines the step instead of restoring it.
        (tmp_path / "leg" / "checkpoint_manifests" / "1.json").unlink()

        _run(tmp_path, "leg", ["--iterations", "2",
                               "--checkpoint-every", "1", "--resume"])
        from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

        mgr = CheckpointManager(tmp_path / "leg")
        meta2 = mgr.restore_meta(2)
        assert "seed" in meta2 and meta2["seed"] is None
        mgr.close()

    def test_guard_off_by_default(self, tmp_path):
        """Without the flag nothing changes: no threshold computation,
        no seed key surprises for old meta consumers (seed is recorded
        regardless — additive, never breaking)."""
        _run(tmp_path, "plain", ["--iterations", "1",
                                 "--checkpoint-every", "1"])
        from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

        mgr = CheckpointManager(tmp_path / "plain")
        assert mgr.restore_meta(1)["seed"] == 0
        mgr.close()


class TestStallGuardUnit:
    """make_stall_guard's two checkpoints, driven with synthetic eval
    streams (the 9-seed study showed BOTH are needed: never-converge
    fails the deadline, late-degrade passes it and fails the final
    acceptance — docs/scaling.md §1b)."""

    def _guard(self, **kw):
        kw.setdefault("decision_iter", 2)
        kw.setdefault("final_iter", 6)
        kw.setdefault("threshold", -100.0)
        return cli.make_stall_guard(lambda i, m: None, **kw)

    @staticmethod
    def _eval(guard, iteration, value):
        guard(iteration - 1, {"eval_episode_reward_mean": value})

    def test_never_converged_fails_deadline(self):
        g = self._guard()
        self._eval(g, 1, -500.0)
        with pytest.raises(cli.EvalStall) as e:
            self._eval(g, 2, -500.0)
        assert e.value.iteration == 2

    def test_late_degrader_fails_final_acceptance(self):
        g = self._guard()
        self._eval(g, 2, -50.0)      # healthy at the deadline
        self._eval(g, 4, -50.0)
        with pytest.raises(cli.EvalStall) as e:
            self._eval(g, 6, -500.0)  # degraded by the last eval
        assert e.value.iteration == 6

    def test_healthy_run_passes_both(self):
        g = self._guard()
        for it in (1, 2, 4, 6):
            self._eval(g, it, -50.0)  # no raise

    def test_budget_spent_warns_instead(self, capsys):
        g = self._guard(raise_on_stall=False)
        self._eval(g, 2, -500.0)
        self._eval(g, 6, -500.0)
        out = capsys.readouterr().out
        assert out.count("WARNING") == 2

    def test_on_stall_hook_fires_only_at_checkpoints(self):
        """The flight recorder's collapse hook must NOT fire on
        pre-deadline evals (expected below the bar while the policy is
        still untrained — each spurious dump would burn the recorder's
        max_dumps budget), only when the guard actually trips."""
        calls = []
        g = self._guard(on_stall=lambda it, v: calls.append((it, v)))
        self._eval(g, 1, -500.0)  # pre-deadline: no stall decision yet
        assert calls == []
        with pytest.raises(cli.EvalStall):
            self._eval(g, 2, -500.0)
        assert calls == [(2, -500.0)]

    def test_on_stall_hook_fires_in_warn_path_too(self, capsys):
        calls = []
        g = self._guard(raise_on_stall=False,
                        on_stall=lambda it, v: calls.append(it))
        self._eval(g, 2, -500.0)
        self._eval(g, 6, -500.0)
        assert calls == [2, 6]


class TestPresetImpliedGuard:
    """The fleet presets imply --reseed-on-stall 2 (the preset IS the
    guarded recipe), auto-disabled for invocations that can't use it."""

    TINY_FLEET = [
        "--preset", "set_fleet64", "--num-nodes", "4", "--num-envs", "4",
        "--rollout-steps", "8", "--minibatch-size", "16",
    ]

    def test_implied_for_long_runs(self, tmp_path, monkeypatch, capsys):
        monkeypatch.setattr(
            "rl_scheduler_tpu.agent.evaluate.best_node_baseline_reward",
            lambda *a, **k: float("-inf"),   # healthy: never stalls
        )
        cli.main(self.TINY_FLEET + ["--iterations", "20",
                                    "--run-root", str(tmp_path),
                                    "--run-name", "long"])
        out = capsys.readouterr().out
        assert "implies --reseed-on-stall 2" in out
        assert "Stall guard:" in out

    def test_auto_disabled_for_smoke_runs(self, tmp_path, monkeypatch,
                                          capsys):
        def boom(*a, **k):
            raise AssertionError("threshold must not be computed")

        monkeypatch.setattr(
            "rl_scheduler_tpu.agent.evaluate.best_node_baseline_reward",
            boom)
        cli.main(self.TINY_FLEET + ["--iterations", "1",
                                    "--run-root", str(tmp_path),
                                    "--run-name", "smoke"])
        assert "implied reseed guard is disabled" in capsys.readouterr().out

    def test_incompatible_eval_cadence_auto_disables(self, tmp_path,
                                                     monkeypatch, capsys):
        """An eval cadence the guard can't use (no eval at or before the
        deadline) auto-disables the IMPLIED guard with a note — it must
        not turn into the explicit flag's hard error."""
        def boom(*a, **k):
            raise AssertionError("threshold must not be computed")

        monkeypatch.setattr(
            "rl_scheduler_tpu.agent.evaluate.best_node_baseline_reward",
            boom)
        cli.main(self.TINY_FLEET + ["--iterations", "40",
                                    "--eval-every", "32",
                                    "--run-root", str(tmp_path),
                                    "--run-name", "cadence"])
        assert "implied reseed guard is disabled" in capsys.readouterr().out

    def test_explicit_zero_respected(self, tmp_path, monkeypatch, capsys):
        def boom(*a, **k):
            raise AssertionError("threshold must not be computed")

        monkeypatch.setattr(
            "rl_scheduler_tpu.agent.evaluate.best_node_baseline_reward",
            boom)
        cli.main(self.TINY_FLEET + ["--iterations", "20",
                                    "--reseed-on-stall", "0",
                                    "--run-root", str(tmp_path),
                                    "--run-name", "off"])
        out = capsys.readouterr().out
        assert "implies --reseed-on-stall" not in out

    def test_resume_auto_disables(self, tmp_path, monkeypatch, capsys):
        cli.main(self.TINY_FLEET + ["--iterations", "1",
                                    "--checkpoint-every", "1",
                                    "--run-root", str(tmp_path),
                                    "--run-name", "res"])

        def boom(*a, **k):
            raise AssertionError("threshold must not be computed")

        monkeypatch.setattr(
            "rl_scheduler_tpu.agent.evaluate.best_node_baseline_reward",
            boom)
        cli.main(self.TINY_FLEET + ["--iterations", "20", "--resume",
                                    "--checkpoint-every", "1",
                                    "--run-root", str(tmp_path),
                                    "--run-name", "res"])
        assert "implied reseed guard is disabled" in capsys.readouterr().out


def test_best_node_baseline_reward_is_best():
    """The threshold helper returns the max over the three node
    baselines (the value the guard compares evals against)."""
    from rl_scheduler_tpu.agent.evaluate import (
        best_node_baseline_reward,
        run_bundle_episodes,
    )
    from rl_scheduler_tpu.agent.train_ppo import make_bundle_and_net
    from rl_scheduler_tpu.agent.ppo import PPOTrainConfig
    from rl_scheduler_tpu.env.baselines import structured_baselines

    bundle, _ = make_bundle_and_net("cluster_set", PPOTrainConfig(),
                                    num_nodes=4)
    best = best_node_baseline_reward("cluster_set", bundle,
                                     num_episodes=8, seed=3)
    singles = [
        float(run_bundle_episodes(bundle, fn, 8, 3)[0].mean())
        for fn in structured_baselines("cluster_set").values()
    ]
    assert best == pytest.approx(max(singles))
