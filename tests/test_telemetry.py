"""scheduler/telemetry.py: PrometheusCpu parsing, per-request fallback,
the never-block serving contract, and thread-safety of repeated samples.

No network: ``urllib.request.urlopen`` is monkeypatched with canned
Prometheus instant-query payloads (the ``/api/v1/query`` response shape).
"""

import io
import json
import threading
import urllib.error

import numpy as np
import pytest

from rl_scheduler_tpu.scheduler.telemetry import (
    PROMETHEUS_URLS,
    PrometheusCpu,
    RandomCpu,
    TableTelemetry,
)


def _payload(value: float) -> bytes:
    """A Prometheus instant-query success body for a scalar vector."""
    return json.dumps({
        "status": "success",
        "data": {"resultType": "vector",
                 "result": [{"metric": {}, "value": [1754200000.0,
                                                     str(value)]}]},
    }).encode()


class _Response(io.BytesIO):
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _urlopen_for(responses: dict):
    """Fake urlopen dispatching on URL substring; values are bytes bodies
    or exceptions to raise."""
    calls = []

    def urlopen(url, timeout=None):
        calls.append((url, timeout))
        for marker, body in responses.items():
            if marker in url:
                if isinstance(body, Exception):
                    raise body
                return _Response(body)
        raise AssertionError(f"unexpected URL {url}")

    urlopen.calls = calls
    return urlopen


# -------------------------------------------------------- success path


def test_query_one_parses_instant_query(monkeypatch):
    fake = _urlopen_for({"localhost:39090": _payload(0.42)})
    monkeypatch.setattr("urllib.request.urlopen", fake)
    cpu = PrometheusCpu()
    assert cpu._query_one(PROMETHEUS_URLS["aws"]) == pytest.approx(0.42)
    (url, timeout), = fake.calls
    assert "/api/v1/query?" in url
    assert "node_cpu_seconds_total" in url  # the query rode along, encoded
    assert timeout == cpu.timeout_s


def test_refresh_caches_both_clouds(monkeypatch):
    fake = _urlopen_for({"39090": _payload(0.3), "39091": _payload(0.7)})
    monkeypatch.setattr("urllib.request.urlopen", fake)
    cpu = PrometheusCpu()
    cpu._refresh()  # synchronous: the thread target, driven directly
    assert cpu.sample() == pytest.approx((0.3, 0.7))
    # A fresh cache (within ttl_s) serves without re-querying.
    n = len(fake.calls)
    assert cpu.sample() == pytest.approx((0.3, 0.7))
    assert len(fake.calls) == n


# ------------------------------------------------------------- fallback


def test_per_cloud_fallback_on_error(monkeypatch):
    """One cloud down does not poison the other: azure's query failing
    falls back to the random source FOR AZURE ONLY."""
    fake = _urlopen_for({
        "39090": _payload(0.25),
        "39091": urllib.error.URLError("connection refused"),
    })
    monkeypatch.setattr("urllib.request.urlopen", fake)
    cpu = PrometheusCpu()
    cpu._refresh()
    aws, azure = cpu.sample()
    assert aws == pytest.approx(0.25)
    assert 0.1 <= azure <= 0.8  # RandomCpu's default band
    assert not cpu._refreshing  # refresh completed despite the error


def test_sample_serves_fallback_until_first_refresh(monkeypatch):
    """The serving-latency contract: sample() NEVER blocks on HTTP — it
    kicks ONE background refresh and serves random until it lands."""
    started = []
    monkeypatch.setattr(
        "rl_scheduler_tpu.scheduler.telemetry.threading.Thread",
        lambda target, daemon: started.append(target) or
        type("T", (), {"start": staticmethod(lambda: None)})(),
    )
    fake = _urlopen_for({"39090": _payload(0.3), "39091": _payload(0.7)})
    monkeypatch.setattr("urllib.request.urlopen", fake)
    cpu = PrometheusCpu()
    a, b = cpu.sample()
    assert 0.1 <= a <= 0.8 and 0.1 <= b <= 0.8  # random fallback, no HTTP
    assert not fake.calls
    assert len(started) == 1
    cpu.sample()
    assert len(started) == 1, "refresh already in flight: no second kick"
    started[0]()  # the deferred refresh lands...
    assert cpu.sample() == pytest.approx((0.3, 0.7))  # ...and serves


# -------------------------------------------------------- thread-safety


def test_repeated_samples_thread_safe(monkeypatch):
    """Hammer sample() from many threads while refreshes churn (ttl 0
    forces a staleness decision on every call): no exceptions, every
    reading well-formed, and the refresh latch ends released."""
    fake = _urlopen_for({"39090": _payload(0.3), "39091": _payload(0.7)})
    monkeypatch.setattr("urllib.request.urlopen", fake)
    cpu = PrometheusCpu(ttl_s=0.0)
    errors = []
    readings = []

    def worker():
        try:
            for _ in range(50):
                pair = cpu.sample()
                assert len(pair) == 2
                assert all(0.0 <= v <= 1.0 for v in pair)
                readings.append(pair)
        except Exception as e:  # noqa: BLE001 — surfaced via the assert below
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    assert len(readings) == 8 * 50
    for t in threads:
        assert not t.is_alive()


def test_table_telemetry_concurrent_observe_steps_exactly_once():
    """The decision counter under concurrency: N observe() calls advance
    the replay index exactly N times (no lost updates), and every
    observation is the documented 6-vector."""
    table = TableTelemetry(
        costs=np.arange(10, dtype=np.float32).reshape(5, 2),
        latencies=np.ones((5, 2), np.float32),
        cpu_source=RandomCpu(seed=0),
    )
    out = []

    def worker():
        for _ in range(25):
            out.append(table.observe())

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert table._step == 8 * 25
    assert all(o.shape == (6,) and o.dtype == np.float32 for o in out)
    # Every row replays an actual table entry (cost pairs cycle mod 5).
    seen = {tuple(o[:2]) for o in out}
    assert seen <= {(0.0, 1.0), (2.0, 3.0), (4.0, 5.0), (6.0, 7.0),
                    (8.0, 9.0)}


def test_random_cpu_seeded_and_banded():
    a = RandomCpu(seed=7)
    b = RandomCpu(seed=7)
    for _ in range(5):
        pair = a.sample()
        assert pair == b.sample()
        assert all(0.1 <= v <= 0.8 for v in pair)


def test_last_replay_position_is_thread_exact():
    """graftroll provenance: last_replay_position names the RAW row the
    CALLING thread's most recent observation consumed — exact under
    concurrency (each thread sees its own consumed positions, never a
    neighbor's), None before the thread's first observation."""
    import threading

    table = TableTelemetry(
        np.arange(10, dtype=np.float32).reshape(5, 2),
        np.zeros((5, 2), np.float32), cpu_source=RandomCpu(seed=0),
    )
    assert table.last_replay_position() is None
    table.observe()
    assert table.last_replay_position() == 0
    table.observe()
    assert table.last_replay_position() == 1

    seen = {}

    def worker(name):
        table.observe()
        seen[name] = table.last_replay_position()

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    # each thread observed a DISTINCT position, and the main thread's
    # view is untouched by the others' observations
    assert sorted(seen.values()) == [2, 3, 4, 5, 6, 7]
    assert table.last_replay_position() == 1
