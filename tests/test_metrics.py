"""graftscope device-resident metrics: numpy parity, merge algebra, the
one-fetch-per-window contract, and the instrumented-update equivalence
(observability must not change the math)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, ppo_train
from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.utils import metrics as gs

SMOKE_CFG = PPOTrainConfig(
    num_envs=8, rollout_steps=16, minibatch_size=32, num_epochs=2,
    hidden=(16, 16),
)


@pytest.fixture(scope="module")
def env_params():
    return env_core.make_params(EnvConfig())


# ------------------------------------------------------- numpy parity


def test_welford_observe_matches_numpy(rng):
    x = rng.randn(1000).astype(np.float32) * 3.0 + 1.5
    s = jax.device_get(gs.stats_observe(jnp.asarray(x)))
    assert float(s.count) == 1000
    np.testing.assert_allclose(float(s.mean), x.mean(), rtol=1e-5)
    np.testing.assert_allclose(float(s.m2) / 1000, x.var(), rtol=1e-4)
    assert float(s.min) == pytest.approx(x.min())
    assert float(s.max) == pytest.approx(x.max())


def test_welford_merge_matches_whole_stream(rng):
    """Chunked observe+merge == one-shot observe of the concatenation,
    for unequal chunk sizes (the merge algebra, not just the mean)."""
    chunks = [rng.randn(n).astype(np.float32) * (i + 1)
              for i, n in enumerate((7, 400, 1, 93))]
    acc = gs.stats_observe(jnp.asarray(chunks[0]))
    for c in chunks[1:]:
        acc = gs.stats_merge(acc, gs.stats_observe(jnp.asarray(c)))
    whole = np.concatenate(chunks)
    acc = jax.device_get(acc)
    assert float(acc.count) == whole.size
    np.testing.assert_allclose(float(acc.mean), whole.mean(), rtol=1e-5)
    np.testing.assert_allclose(float(acc.m2) / whole.size, whole.var(),
                               rtol=1e-4)
    assert float(acc.min) == pytest.approx(whole.min())
    assert float(acc.max) == pytest.approx(whole.max())


def test_stats_reduce_matches_pairwise_merge(rng):
    """The closed-form stacked reduction (fused-dispatch path) equals
    folding stats_merge pairwise."""
    parts = [gs.stats_observe(jnp.asarray(rng.randn(50).astype(np.float32)))
             for _ in range(4)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *parts)
    reduced = jax.device_get(gs.stats_reduce(stacked))
    folded = parts[0]
    for p in parts[1:]:
        folded = gs.stats_merge(folded, p)
    folded = jax.device_get(folded)
    for field in gs.TensorStats._fields:
        np.testing.assert_allclose(
            float(getattr(reduced, field)), float(getattr(folded, field)),
            rtol=1e-5, err_msg=field)


def test_hist_observe_matches_numpy(rng):
    edges = (-2.0, -0.5, 0.0, 0.5, 2.0)
    x = rng.randn(2000).astype(np.float32)
    counts = np.asarray(gs.hist_observe(jnp.asarray(x), edges))
    expected = np.bincount(
        np.searchsorted(np.asarray(edges), x, side="right"),
        minlength=len(edges) + 1,
    )
    np.testing.assert_array_equal(counts, expected)
    assert counts.sum() == 2000


def test_categorical_observe_counts_and_clips():
    ids = jnp.asarray([0, 1, 1, 2, 2, 2, 7, -3])
    counts = np.asarray(gs.categorical_observe(ids, 4))
    # 7 clips into the top bin, -3 into bin 0 — piled up, not dropped.
    np.testing.assert_array_equal(counts, [2, 2, 3, 1])


def test_hist_spec_validation():
    with pytest.raises(ValueError, match="exactly one"):
        gs.HistSpec("x")
    with pytest.raises(ValueError, match="exactly one"):
        gs.HistSpec("x", edges=(0.0,), bins=2)
    with pytest.raises(ValueError, match=">= 1"):
        gs.ScopeSession(gs.MetricsSpec(), 0, lambda i, s: None)


def test_scope_observe_merge_summary_roundtrip(rng):
    spec = gs.MetricsSpec(
        stats=("loss",),
        hists=(gs.HistSpec("loss", edges=(0.0, 1.0)),
               gs.HistSpec("action", bins=3)),
    )
    a = rng.rand(64).astype(np.float32)
    b = rng.rand(64).astype(np.float32)
    s1 = gs.scope_observe(spec, {"loss": jnp.asarray(a),
                                 "action": jnp.zeros(64, jnp.int32)})
    s2 = gs.scope_observe(spec, {"loss": jnp.asarray(b),
                                 "action": jnp.ones(64, jnp.int32)})
    merged = jax.device_get(gs.scope_merge(s1, s2))
    out = gs.scope_summary(merged, spec)
    whole = np.concatenate([a, b])
    assert out["loss/count"] == 128
    np.testing.assert_allclose(out["loss/mean"], whole.mean(), rtol=1e-5)
    np.testing.assert_allclose(out["loss/std"], whole.std(), rtol=1e-4)
    assert sum(out["hist/loss"]["counts"]) == 128
    assert out["hist/loss"]["edges"] == [0.0, 1.0]
    assert out["hist/action"]["counts"] == [64, 64, 0]


# ------------------------------------------- the one-fetch-per-window gate


def _run_scoped(env_params, iterations, window, k=1, monkeypatch=None):
    spec = gs.ppo_scope_spec(2)
    summaries = []
    session = gs.ScopeSession(
        spec, window, lambda i, s: summaries.append((i, s)))
    observer = gs.TrainObserver(session)
    fetches = []
    if monkeypatch is not None:
        real = gs._device_get
        monkeypatch.setattr(
            gs, "_device_get",
            lambda tree: (fetches.append(1), real(tree))[1])
    _, history = ppo_train(env_params, SMOKE_CFG, iterations, seed=0,
                           scope=spec, observer=observer,
                           updates_per_dispatch=k)
    return session, summaries, fetches, history


def test_exactly_one_host_fetch_per_logging_window(env_params, monkeypatch):
    """THE graftscope invariant (GL008/GL009 by construction): 10
    iterations at window 5 cost exactly 2 scope fetches — counted at the
    module's single device_get seam — and every per-update accumulate is
    fetch-free."""
    session, summaries, fetches, history = _run_scoped(
        env_params, 10, 5, monkeypatch=monkeypatch)
    assert session.fetch_count == 2
    assert len(fetches) == 2, "scope performed a host fetch outside flush"
    assert [i for i, _ in summaries] == [4, 9]
    assert len(history) == 10  # scalar logging unchanged
    # Each window summary covers exactly window * batch samples.
    for _, s in summaries:
        assert s["advantage/count"] == 5 * SMOKE_CFG.batch_size


def test_window_with_fused_dispatch(env_params):
    """updates_per_dispatch=2 stacks the per-iteration states; the
    stacked closed-form reduction keeps window accounting exact."""
    session, summaries, _, _ = _run_scoped(env_params, 8, 4, k=2)
    assert session.fetch_count == 2
    assert [i for i, _ in summaries] == [3, 7]
    for _, s in summaries:
        assert s["advantage/count"] == 4 * SMOKE_CFG.batch_size
        assert sum(s["hist/action"]["counts"]) == 4 * SMOKE_CFG.batch_size


def test_partial_window_flushes_at_close(env_params):
    session, summaries, _, _ = _run_scoped(env_params, 5, 4)
    assert session.fetch_count == 2  # one full window + the remainder
    assert [i for i, _ in summaries] == [3, 4]
    assert summaries[-1][1]["advantage/count"] == 1 * SMOKE_CFG.batch_size


def test_instrumentation_does_not_change_training(env_params):
    """Observability is free in MATH, not just time: the instrumented
    update consumes no extra RNG and computes the same function — to
    float tolerance, since the added metric ops shift XLA's fusion/
    reassociation choices by a few ulps."""
    _, plain = ppo_train(env_params, SMOKE_CFG, 3, seed=7)
    spec = gs.ppo_scope_spec(2)
    session = gs.ScopeSession(spec, 3, lambda i, s: None)
    _, scoped = ppo_train(env_params, SMOKE_CFG, 3, seed=7, scope=spec,
                          observer=gs.TrainObserver(session))
    for a, b in zip(plain, scoped):
        for key in a:
            if key == "wall_time":
                continue
            assert a[key] == pytest.approx(b[key], rel=1e-3, abs=1e-6), key


def test_scope_refused_on_sharded_path(env_params):
    import jax.sharding as shd

    mesh = shd.Mesh(np.array(jax.devices()[:2]), ("dp",))
    with pytest.raises(ValueError, match="single-chip"):
        ppo_train(env_params, SMOKE_CFG, 1, mesh=mesh,
                  scope=gs.ppo_scope_spec(2))


def test_custom_spec_without_ratio_hist_trains(env_params):
    """The scope contract is any validating MetricsSpec, not only
    ppo_scope_spec: a trimmed spec with no ratio hist skips the in-scan
    bucketization entirely and still summarizes per window."""
    spec = gs.MetricsSpec(stats=("reward",),
                          hists=(gs.HistSpec("action", bins=2),))
    summaries = []
    session = gs.ScopeSession(spec, 2, lambda i, s: summaries.append((i, s)))
    ppo_train(env_params, SMOKE_CFG, 2, seed=0, scope=spec,
              observer=gs.TrainObserver(session))
    assert [i for i, _ in summaries] == [1]
    assert set(summaries[0][1]) == {"reward/count", "reward/mean",
                                    "reward/std", "reward/min", "reward/max",
                                    "hist/action"}


def test_unknown_stream_rejected_at_build_time(env_params):
    """A spec naming a stream the trainer does not produce fails before
    any tracing, with the available names spelled out."""
    with pytest.raises(ValueError, match="advantage"):
        ppo_train(env_params, SMOKE_CFG, 1, scope=gs.MetricsSpec(
            stats=("loss",)))


def test_validate_spec_lists_unknown_and_available():
    spec = gs.MetricsSpec(stats=("loss",),
                          hists=(gs.HistSpec("ratio", edges=(1.0,)),))
    with pytest.raises(ValueError) as err:
        gs.validate_spec(spec, values=("reward",), context="ctx")
    msg = str(err.value)
    assert "ctx" in msg and "loss" in msg and "ratio" in msg \
        and "reward" in msg
    # Histogram-only streams delivered via counts= validate cleanly.
    gs.validate_spec(spec, values=("reward", "loss"), counts=("ratio",))


def test_validate_spec_rejects_bins_for_counts_stream():
    """An in-scan stream is bucketized by the trainer against the spec's
    static edges; a bins-typed HistSpec has none, so scope_observe would
    KeyError from inside the first traced update — the guard must catch
    it at build time instead."""
    spec = gs.MetricsSpec(hists=(gs.HistSpec("ratio", bins=8),))
    with pytest.raises(ValueError, match="edges"):
        gs.validate_spec(spec, values=(), counts=("ratio",))
    # The same bins spec is fine when a raw value stream exists.
    gs.validate_spec(spec, values=("ratio",), counts=("ratio",))


# ----------------------------------------------------------- CLI plumbing


def test_train_ppo_cli_metrics_window(tmp_path):
    from rl_scheduler_tpu.agent import train_ppo as cli

    run_dir = cli.main([
        "--preset", "quick", "--num-envs", "8", "--rollout-steps", "16",
        "--minibatch-size", "32", "--num-epochs", "2", "--iterations", "4",
        "--metrics-window", "2", "--run-root", str(tmp_path),
        "--run-name", "scoped",
    ])
    lines = [json.loads(ln) for ln in
             (run_dir / "metrics.jsonl").read_text().splitlines()]
    scoped = [ln for ln in lines if ln.get("graftscope")]
    assert [ln["iteration"] for ln in scoped] == [2, 4]
    for ln in scoped:
        assert {"advantage/mean", "grad_norm/max", "hist/ratio",
                "hist/action"} <= set(ln)
        assert len(ln["hist/action"]["counts"]) == 2  # per-cloud
    # Per-iteration scalar lines also gained the grad_norm stream.
    scalar = [ln for ln in lines if "env_steps_per_sec" in ln]
    assert all("grad_norm" in ln for ln in scalar)


def test_train_dqn_cli_metrics_window(tmp_path):
    from rl_scheduler_tpu.agent import train_dqn as cli

    run_dir = cli.main([
        "--preset", "config1", "--iterations", "6", "--metrics-window", "3",
        "--sync-every", "2", "--checkpoint-every", "6",
        "--run-root", str(tmp_path), "--run-name", "scoped",
    ])
    lines = [json.loads(ln) for ln in
             (run_dir / "metrics.jsonl").read_text().splitlines()]
    scoped = [ln for ln in lines if ln.get("graftscope")]
    assert [ln["iteration"] for ln in scoped] == [3, 6]
    assert all("reward/mean" in ln and "hist/action" in ln for ln in scoped)


def test_cli_metrics_window_validation(tmp_path):
    from rl_scheduler_tpu.agent import train_ppo as cli

    with pytest.raises(SystemExit, match="multiple"):
        cli.main(["--metrics-window", "3", "--updates-per-dispatch", "2",
                  "--iterations", "4", "--run-root", str(tmp_path)])
    with pytest.raises(SystemExit, match="single-chip"):
        cli.main(["--metrics-window", "2", "--dp", "2",
                  "--run-root", str(tmp_path)])
    with pytest.raises(SystemExit, match="positive"):
        cli.main(["--metrics-window", "-1", "--run-root", str(tmp_path)])
