"""Native C++ inference core: build, numerical parity with numpy,
thread-safety under concurrent decide(), and graceful degradation."""

import concurrent.futures
import shutil

import numpy as np
import pytest

from rl_scheduler_tpu.native import NativeMLP, ensure_built, pack_mlp
from rl_scheduler_tpu.scheduler.policy_backend import (
    NumpyMLPBackend,
    make_backend,
)

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain"
)


def random_layers(rng, dims=(6, 32, 16, 2)):
    return [
        (rng.standard_normal((i, o)).astype(np.float32),
         rng.standard_normal(o).astype(np.float32))
        for i, o in zip(dims[:-1], dims[1:])
    ]


def numpy_forward(layers, obs):
    x = obs.astype(np.float32)
    for kernel, bias in layers[:-1]:
        x = np.tanh(x @ kernel + bias)
    kernel, bias = layers[-1]
    return x @ kernel + bias


@pytest.fixture(scope="module")
def lib_path():
    path = ensure_built()
    assert path is not None and path.exists()
    return path


def test_native_matches_numpy(lib_path):
    rng = np.random.default_rng(0)
    layers = random_layers(rng)
    mlp = NativeMLP(layers, lib_path)
    for _ in range(50):
        obs = rng.standard_normal(6).astype(np.float32)
        ref = numpy_forward(layers, obs)
        action, logits = mlp.decide(obs)
        np.testing.assert_allclose(logits, ref, rtol=1e-4, atol=1e-5)
        assert action == int(np.argmax(ref))


def test_native_thread_safe_on_shared_handle(lib_path):
    rng = np.random.default_rng(1)
    layers = random_layers(rng)
    mlp = NativeMLP(layers, lib_path)
    observations = rng.standard_normal((256, 6)).astype(np.float32)
    expected = [int(np.argmax(numpy_forward(layers, o))) for o in observations]

    with concurrent.futures.ThreadPoolExecutor(max_workers=8) as pool:
        got = list(pool.map(lambda o: mlp.decide(o)[0], observations))
    assert got == expected


def test_pack_mlp_rejects_inconsistent_shapes():
    rng = np.random.default_rng(2)
    layers = random_layers(rng)
    layers[1] = (rng.standard_normal((99, 16)).astype(np.float32),
                 layers[1][1])
    with pytest.raises(ValueError):
        pack_mlp(layers)


def test_native_rejects_bad_obs_shape(lib_path):
    rng = np.random.default_rng(3)
    mlp = NativeMLP(random_layers(rng), lib_path)
    with pytest.raises(ValueError):
        mlp.decide(np.zeros(5, np.float32))


@pytest.fixture(scope="module")
def params_tree():
    import jax
    import jax.numpy as jnp

    from rl_scheduler_tpu.env import core as env_core
    from rl_scheduler_tpu.models import ActorCritic

    net = ActorCritic(num_actions=env_core.NUM_ACTIONS, hidden=(32, 32))
    return net.init(
        jax.random.PRNGKey(7), jnp.zeros((1, env_core.OBS_DIM), jnp.float32)
    )


def test_native_backend_parity_with_cpu_backend(params_tree):
    native, fell_back = make_backend("native", params_tree)
    assert not fell_back
    cpu = NumpyMLPBackend(params_tree)
    rng = np.random.default_rng(4)
    for _ in range(20):
        obs = rng.uniform(0, 1, 6).astype(np.float32)
        a_n, l_n = native.decide(obs)
        a_c, l_c = cpu.decide(obs)
        assert a_n == a_c
        np.testing.assert_allclose(l_n, l_c, rtol=1e-4, atol=1e-5)


def test_native_degrades_to_cpu_when_lib_missing(monkeypatch, params_tree):
    import rl_scheduler_tpu.native.build as build_mod

    monkeypatch.setattr(build_mod, "ensure_built", lambda force=False: None)
    backend, fell_back = make_backend("native", params_tree)
    assert backend.name == "cpu"
    assert not fell_back


def test_native_relu_matches_numpy(lib_path):
    """ABI v2 activation selector: relu hidden layers match numpy exactly."""
    rng = np.random.default_rng(5)
    layers = random_layers(rng)
    mlp = NativeMLP(layers, lib_path=lib_path, activation="relu")
    for _ in range(10):
        obs = rng.uniform(-1, 1, 6).astype(np.float32)
        x = obs.copy()
        for kernel, bias in layers[:-1]:
            x = np.maximum(x @ kernel + bias, 0.0)
        kernel, bias = layers[-1]
        expect = x @ kernel + bias
        action, logits = mlp.decide(obs)
        # C++ accumulates in a different order than numpy's BLAS; tolerance
        # matches the tanh parity tests.
        np.testing.assert_allclose(logits, expect, rtol=1e-4, atol=1e-5)
        assert action == int(np.argmax(expect))


def test_native_unknown_activation_rejected(lib_path):
    rng = np.random.default_rng(6)
    with pytest.raises(ValueError, match="activation"):
        NativeMLP(random_layers(rng), lib_path=lib_path, activation="gelu")
