"""graftfront: the asyncio data-plane front behind ``--front``.

``AsyncFrontServer`` replaces ``ThreadingHTTPServer`` behind
``make_server(..., front="asyncio")`` and must be a drop-in under the
pool supervisor's facade contract — ``server_address`` readable after
construction, blocking ``serve_forever``, thread-safe ``shutdown``,
idempotent ``server_close`` — while serving the EXACT decision/stats/
metrics semantics of the threading front (the graftlens suites are the
spec; ``test_graftlens``/``test_pool`` run parameterized over both
fronts). Here: the facade contract, front parity on the observable
stats surface, keep-alive connection reuse, and loop health under
concurrent load."""

from __future__ import annotations

import http.client
import json
import threading
import urllib.request

import pytest

from rl_scheduler_tpu.scheduler.extender import (
    PHASES,
    ExtenderPolicy,
    make_server,
)
from rl_scheduler_tpu.scheduler.front import AsyncFrontServer
from rl_scheduler_tpu.scheduler.policy_backend import GreedyBackend
from rl_scheduler_tpu.scheduler.telemetry import RandomCpu, TableTelemetry

FRONT_PARAMS = ["threading", "asyncio"]


def _policy(seed=0):
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=seed))
    return ExtenderPolicy(GreedyBackend(), telemetry)


def _args(i=0, n=4):
    return {"nodenames": [f"{'aws' if j % 2 else 'azure'}-n{i}-{j}"
                          for j in range(n)], "pod": {}}


class _Server:
    """Start/serve/stop helper for one front."""

    def __init__(self, front, policy=None):
        self.policy = policy or _policy()
        self.srv = make_server(self.policy, host="127.0.0.1", port=0,
                               front=front)
        self.port = self.srv.server_address[1]
        self.thread = threading.Thread(target=self.srv.serve_forever,
                                       daemon=True)
        self.thread.start()

    def post(self, path, body, ctype="application/json"):
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.port}{path}", data=body,
            headers={"Content-Type": ctype})
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status, resp.read()

    def get_json(self, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{self.port}{path}", timeout=5) as resp:
            return json.loads(resp.read())

    def stop(self):
        self.srv.shutdown()
        self.srv.server_close()
        self.thread.join(timeout=10)


# ------------------------------------------------------- facade contract


def test_make_server_refuses_unknown_front():
    with pytest.raises(ValueError):
        make_server(_policy(), host="127.0.0.1", port=0, front="gevent")


def test_async_front_satisfies_the_pool_facade():
    """The supervisor's contract: address before serve_forever, blocking
    serve loop, thread-safe shutdown, idempotent close."""
    srv = AsyncFrontServer(_policy(), "127.0.0.1", 0)
    host, port = srv.server_address[:2]
    assert host == "127.0.0.1" and port > 0
    srv.daemon_threads = True  # writable, like ThreadingHTTPServer's

    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                                timeout=5) as resp:
        assert json.loads(resp.read())["status"] == "ok"
    srv.shutdown()           # from another thread, like the control loop
    thread.join(timeout=10)
    assert not thread.is_alive()
    srv.server_close()
    srv.server_close()       # idempotent
    with pytest.raises(OSError):
        http.client.HTTPConnection("127.0.0.1", port, timeout=1).connect()


def test_shutdown_before_serve_forever_is_clean():
    """The SIGTERM race: a drain signal can land between construction
    and serve_forever; the serve loop must exit immediately."""
    srv = AsyncFrontServer(_policy(), "127.0.0.1", 0)
    srv.shutdown()
    srv.serve_forever()      # returns at once instead of serving
    srv.server_close()


# ----------------------------------------------------------- front parity


def test_stats_surface_identical_across_fronts():
    """The agreement suite's core claim: the same request stream
    produces the SAME decision counters, per-phase sample counts and
    fail-open counts on both fronts (latencies differ; counts and
    structure may not)."""
    snaps = {}
    for front in FRONT_PARAMS:
        server = _Server(front)
        try:
            for i in range(6):
                path = "/filter" if i % 2 == 0 else "/prioritize"
                status, _ = server.post(path, json.dumps(_args(i)).encode())
                assert status == 200
            snaps[front] = server.get_json("/stats")
        finally:
            server.stop()
    a, b = snaps["threading"], snaps["asyncio"]
    assert a["decisions"] == b["decisions"]  # per-cloud choice counts
    assert a["fail_open_total"] == b["fail_open_total"] == 0
    assert a["choice_fractions"] == b["choice_fractions"]
    assert set(a["phases"]) == set(b["phases"]) == set(PHASES)
    for phase in PHASES:
        assert a["phases"][phase]["lifetime_count"] \
            == b["phases"][phase]["lifetime_count"], phase


def test_phase_count_uniformity_on_asyncio():
    """graftlens count-uniformity: one sample per phase per served
    decision on the asyncio front — probes and /stats traffic add
    nothing."""
    server = _Server("asyncio")
    try:
        for i in range(5):
            server.post("/filter", json.dumps(_args(i)).encode())
        server.get_json("/healthz")
        server.get_json("/stats")
        stats = server.get_json("/stats")
        counts = {phase: stats["phases"][phase]["lifetime_count"]
                  for phase in PHASES}
        assert set(counts.values()) == {5}, counts
    finally:
        server.stop()


def test_reset_never_rewinds_lifetime_counters_on_asyncio():
    server = _Server("asyncio")
    try:
        for i in range(4):
            server.post("/filter", json.dumps(_args(i)).encode())
        before = server.get_json("/stats")
        status, _ = server.post("/stats/reset", b"{}")
        assert status == 200
        after = server.get_json("/stats")
        assert after["decisions"] == before["decisions"]
        for phase in PHASES:
            assert after["phases"][phase]["lifetime_count"] \
                == before["phases"][phase]["lifetime_count"]
        assert after["latency"]["count"] == 0  # the ring DID clear
    finally:
        server.stop()


@pytest.mark.parametrize("front", FRONT_PARAMS)
def test_error_semantics_match(front):
    """404 on unknown paths, 400 on undecodable JSON — identical status
    codes and JSON error bodies on both fronts."""
    server = _Server(front)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=5)
        conn.request("GET", "/nope")
        resp = conn.getresponse()
        assert resp.status == 404 and b"error" in resp.read()
        conn.close()

        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=5)
        conn.request("POST", "/filter", b"{not json",
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 400 and b"error" in resp.read()
        conn.close()

        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=5)
        conn.request("POST", "/nope", b"{}",
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 404
        resp.read()
        conn.close()
    finally:
        server.stop()


# ------------------------------------------------------------- keep-alive


def test_asyncio_front_keeps_connections_alive():
    """HTTP/1.1 keep-alive end to end: many requests ride ONE
    connection (the threading front is HTTP/1.0 and closes per
    request — exactly the setup cost the asyncio front removes)."""
    server = _Server("asyncio")
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=5)
        for i in range(10):
            conn.request("POST", "/filter",
                         json.dumps(_args(i)).encode(),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = resp.read()
            assert resp.status == 200
            assert json.loads(body)["nodenames"]
            assert not resp.will_close, "server dropped keep-alive"
        stats = server.get_json("/stats")
        assert sum(stats["decisions"].values()) == 10
        conn.close()
    finally:
        server.stop()


def test_connection_close_header_is_honored():
    server = _Server("asyncio")
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=5)
        conn.request("POST", "/filter", json.dumps(_args()).encode(),
                     {"Content-Type": "application/json",
                      "Connection": "close"})
        resp = conn.getresponse()
        resp.read()
        assert resp.status == 200 and resp.will_close
        conn.close()
    finally:
        server.stop()


# -------------------------------------------------------- load / drain


def test_concurrent_keepalive_load_zero_failures():
    """8 keep-alive clients x 25 requests on one event loop: every
    request answers 200 and the stats account for all of them."""
    server = _Server("asyncio")
    errors = []

    def client(tid):
        try:
            conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                              timeout=10)
            for i in range(25):
                path = "/filter" if i % 2 == 0 else "/prioritize"
                conn.request("POST", path,
                             json.dumps(_args(tid * 100 + i)).encode(),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                if resp.status != 200:
                    errors.append((tid, i, resp.status))
            conn.close()
        except Exception as exc:  # noqa: BLE001 - collected for assert
            errors.append((tid, repr(exc)))

    try:
        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors[:5]
        stats = server.get_json("/stats")
        assert sum(stats["decisions"].values()) == 8 * 25
    finally:
        server.stop()


def test_shutdown_drains_inflight_requests():
    """A shutdown issued mid-request lets the in-flight decision finish
    (the SIGTERM drain contract) instead of resetting the client."""
    import time as _time

    class _SlowBackend:
        name = "slow"

        def decide(self, obs):
            _time.sleep(0.3)
            import numpy as np

            return 0, np.zeros(2, "float32")

    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))
    server = _Server("asyncio",
                     policy=ExtenderPolicy(_SlowBackend(), telemetry))
    result = {}

    def slow_request():
        try:
            result["status"], result["body"] = server.post(
                "/filter", json.dumps(_args()).encode())
        except Exception as exc:  # noqa: BLE001 - asserted below
            result["error"] = repr(exc)

    t = threading.Thread(target=slow_request)
    t.start()
    _time.sleep(0.1)           # let the request reach the executor
    server.srv.shutdown()      # drain: must NOT cut the in-flight reply
    t.join(timeout=15)
    server.srv.server_close()
    server.thread.join(timeout=10)
    assert result.get("status") == 200, result
    assert json.loads(result["body"])["nodenames"]
