"""graftlint: the repo lint gate + per-rule fixture self-tests.

Two layers:

- **The gate** (tier-1): run the analyzer over the whole configured repo
  (``[tool.graftlint]`` paths) and fail on ANY unsuppressed finding. This
  makes the lint part of ``pytest`` — no new CI machinery — so a future
  PR cannot quietly reintroduce a host sync in a jitted body, reuse a
  PRNG key, or ship a misaligned Pallas tile.
- **Self-tests**: every rule has a minimal positive and negative fixture
  under ``tests/graftlint_fixtures/`` (never imported, only parsed); the
  parametrized cases pin each rule's detection surface so engine changes
  cannot silently blunt a rule.
"""

from __future__ import annotations

import dataclasses
import json
import subprocess
import sys
from pathlib import Path

import pytest

from tools.graftlint import LintConfig, lint_paths, load_config, load_rules

REPO_ROOT = Path(__file__).resolve().parents[1]
FIXTURES = REPO_ROOT / "tests" / "graftlint_fixtures"


def fixture_config() -> LintConfig:
    """Config for linting fixture files in isolation: no excludes, the
    fixture corpus as the GL007 reference test set."""
    return LintConfig(
        exclude=(),
        test_paths=(str(FIXTURES / "corpus"),),
        per_path_ignore={},
    )


# ------------------------------------------------------------------ gate


@pytest.fixture(scope="module")
def repo_lint():
    """ONE repo-wide lint (all rules, flow layer, audit) shared by every
    gate test below — the run is identical for all of them, and at ~7 s
    per 190-file pass, repeating it per-test is real tier-1 wall-clock.
    Returns (result, elapsed_seconds)."""
    import time

    config = load_config(REPO_ROOT / "pyproject.toml")
    config = dataclasses.replace(
        config, test_paths=tuple(str(REPO_ROOT / p) for p in config.test_paths)
    )
    t0 = time.perf_counter()
    result = lint_paths(
        [REPO_ROOT / p for p in config.paths], config, root=REPO_ROOT
    )
    return result, time.perf_counter() - t0


def test_repo_gate_zero_unsuppressed_findings(repo_lint):
    """The tentpole invariant: the analyzer over the WHOLE repo (same
    paths as `python -m tools.graftlint`) reports nothing unsuppressed."""
    result, _ = repo_lint
    assert result.files_checked > 50, "lint set collapsed — check config"
    pretty = "\n".join(f.format() for f in result.unsuppressed)
    assert not result.unsuppressed, f"unsuppressed graftlint findings:\n{pretty}"


def test_repo_gate_no_stale_suppressions(repo_lint):
    """The suppression audit, tier-1-wired: a justified suppression whose
    rule no longer fires on its line is a silenced alarm nobody will
    re-arm — delete the disable comment when the code it excused heals."""
    result, _ = repo_lint
    pretty = "\n".join(f.format() for f in result.stale_suppressions)
    assert not result.stale_suppressions, (
        f"stale graftlint suppressions (justification outlived the code "
        f"it excused — remove the disable comment):\n{pretty}"
    )


def test_repo_gate_suppressions_all_justified(repo_lint):
    """Every suppression that exists in the repo parses with a
    justification (GL000 would fire otherwise — covered by the gate — but
    assert the count explicitly so drive-by suppressions stay visible)."""
    result, _ = repo_lint
    assert not [f for f in result.findings if f.rule == "GL000"]
    # The documented boundary cases (docs/static_analysis.md): two
    # shape-driven GL003 branches, the flight recorder's dict-key GL003
    # branch, quick_eval's per-step-walkthrough GL009 fetch, and the kube
    # placer's GL010 (_warn_once logging indirection). Update this count
    # when adding one.
    assert len(result.suppressed) == 5


# ------------------------------------------------------- fixture self-tests

CASES = [
    ("gl001_bad.py", "GL001", 3),
    ("gl001_good.py", "GL001", 0),
    ("gl002_bad.py", "GL002", 2),
    ("gl002_good.py", "GL002", 0),
    ("gl003_bad.py", "GL003", 2),
    ("gl003_good.py", "GL003", 0),
    ("gl004_bad.py", "GL004", 2),
    ("gl004_good.py", "GL004", 0),
    ("gl005_bad_pallas.py", "GL005", 4),
    ("gl005_good_pallas.py", "GL005", 0),
    ("gl006_bad.py", "GL006", 2),
    ("gl006_good.py", "GL006", 0),
    ("ops/gl007_bad.py", "GL007", 1),
    ("ops/gl007_good.py", "GL007", 0),
    ("gl008_bad.py", "GL008", 1),
    ("gl008_good.py", "GL008", 0),
    ("gl009_bad.py", "GL009", 3),
    ("gl009_good.py", "GL009", 0),
    ("scheduler/gl010_bad.py", "GL010", 4),
    ("scheduler/gl010_good.py", "GL010", 0),
    ("scheduler/gl011_bad.py", "GL011", 3),
    ("scheduler/gl011_good.py", "GL011", 0),
    ("scheduler/gl012_bad.py", "GL012", 5),
    ("scheduler/gl012_good.py", "GL012", 0),
    ("scheduler/gl013_bad.py", "GL013", 3),
    ("scheduler/gl013_good.py", "GL013", 0),
    ("scheduler/gl014_bad.py", "GL014", 3),
    ("scheduler/gl014_good.py", "GL014", 0),
    ("scheduler/gl015_bad.py", "GL015", 1),
    ("scheduler/gl015_good.py", "GL015", 0),
    ("gl016_bad.py", "GL016", 2),
    ("gl016_good.py", "GL016", 0),
    ("scheduler/gl017_bad.py", "GL017", 2),
    ("scheduler/gl017_good.py", "GL017", 0),
]


@pytest.mark.parametrize("fixture,rule,expected", CASES,
                         ids=[c[0].replace("/", "_") for c in CASES])
def test_rule_fixture(fixture, rule, expected):
    result = lint_paths([FIXTURES / fixture], fixture_config(), root=REPO_ROOT)
    got = [f for f in result.unsuppressed if f.rule == rule]
    pretty = "\n".join(f.format() for f in result.unsuppressed)
    assert len(got) == expected, (
        f"{fixture}: expected {expected} {rule} finding(s), got "
        f"{len(got)}:\n{pretty}"
    )
    # Fixtures are single-rule by construction: nothing ELSE may fire.
    others = [f for f in result.unsuppressed if f.rule != rule]
    assert not others, f"{fixture}: unexpected cross-rule findings:\n{pretty}"


def test_suppression_semantics():
    """Justified suppressions suppress; unjustified or unknown-rule ones
    become GL000 findings and do NOT suppress."""
    result = lint_paths(
        [FIXTURES / "gl000_suppressions.py"], fixture_config(), root=REPO_ROOT
    )
    gl000 = [f for f in result.unsuppressed if f.rule == "GL000"]
    assert len(gl000) == 2  # missing justification + unknown rule
    gl002_open = [f for f in result.unsuppressed if f.rule == "GL002"]
    gl002_closed = [f for f in result.suppressed if f.rule == "GL002"]
    assert len(gl002_open) == 1   # the unjustified comment did not suppress
    assert len(gl002_closed) == 1  # the justified one did


# ------------------------------------------------------------ engine units


def test_traced_scope_resolution_one_level():
    """Decorator, transform-argument, lexical nesting, and one-hop calls
    all mark traced; a function nobody traces stays unmarked."""
    from tools.graftlint.engine import Module

    src = (
        "import jax\n"
        "@jax.jit\n"
        "def direct(x):\n"
        "    def nested(y):\n"
        "        return helper(y)\n"
        "    return nested(x)\n"
        "def helper(z):\n"
        "    return z\n"
        "def scanned(c, _):\n"
        "    return c, c\n"
        "def run(c):\n"
        "    return jax.lax.scan(scanned, c, None, length=2)\n"
        "def untouched(w):\n"
        "    return w\n"
    )
    mod = Module(Path("synthetic.py"), "synthetic.py", src, known_rules=())
    verdict = {r.qualname: r.traced for r in mod.functions}
    assert verdict["direct"] and verdict["direct.nested"]
    assert verdict["helper"], "one-hop call from traced body"
    assert verdict["scanned"]
    assert not verdict["untouched"]
    assert not verdict["run"]  # calling scan does not trace the CALLER


def test_static_argnames_not_tainted():
    from tools.graftlint.engine import Module

    src = (
        "import functools, jax\n"
        "@functools.partial(jax.jit, static_argnames=('block',))\n"
        "def f(x, block):\n"
        "    return x, block\n"
    )
    mod = Module(Path("s.py"), "s.py", src, known_rules=())
    (rec,) = [r for r in mod.functions if r.name == "f"]
    assert rec.traced and rec.static_params == {"block"}
    assert "block" not in rec.taint() and "x" in rec.taint()


# ----------------------------------------------------------------- the CLI


def _run_cli(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )


def test_cli_gate_exits_zero_on_repo():
    """The acceptance command: explicit paths, zero unsuppressed, exit 0."""
    proc = _run_cli("rl_scheduler_tpu", "tests", "loadgen")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 error(s)" in proc.stderr


def test_cli_json_and_exit_code_on_bad_fixture():
    rel = "tests/graftlint_fixtures/gl002_bad.py"
    # Explicit file paths bypass the config's fixture exclude on purpose.
    proc = _run_cli("--json", "--select", "GL002", rel)
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["files_checked"] == 1
    rules = {f["rule"] for f in payload["unsuppressed"]}
    assert rules == {"GL002"}
    assert all(f["path"] == rel for f in payload["unsuppressed"])


def test_cli_list_rules_covers_registry():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rid in ["GL000"] + [f"GL{i:03d}" for i in range(1, 18)]:
        assert rid in proc.stdout
    assert len(load_rules()) == 17


# --------------------------------------------------- audit / SARIF / severity


def test_stale_suppression_fixture_fails_audit():
    """The deliberately-stale fixture: a justified GL013 suppression on a
    line where GL013 no longer fires must surface as a stale-audit
    finding (and ONLY as that — the file itself lints clean)."""
    result = lint_paths(
        [FIXTURES / "scheduler" / "gl_audit_stale.py"], fixture_config(),
        root=REPO_ROOT,
    )
    assert not result.unsuppressed
    assert len(result.stale_suppressions) == 1
    stale = result.stale_suppressions[0]
    assert stale.rule == "GL000" and "GL013" in stale.message
    assert "stale suppression" in stale.message


def test_cli_audit_suppressions_fails_on_stale_fixture():
    # --select GL013: the repo config's GL007 corpus deliberately
    # excludes the fixture tree, so an unrestricted run would fail for
    # the wrong reason (untested fixture publics, not the stale comment).
    rel = "tests/graftlint_fixtures/scheduler/gl_audit_stale.py"
    proc = _run_cli("--select", "GL013", "--audit-suppressions", rel)
    assert proc.returncode == 1
    assert "stale suppression" in proc.stdout
    # Without the audit flag the same file gates clean (suppression
    # still parses and the rule genuinely does not fire).
    proc = _run_cli("--select", "GL013", rel)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_sarif_artifact_shape(tmp_path):
    """Pin the SARIF 2.1.0 surface CI annotators rely on: version/schema,
    driver rules covering the registry, one result per finding with
    ruleId/level/location, and inSource suppression marking."""
    out = tmp_path / "out.sarif"
    rel = "tests/graftlint_fixtures/gl002_bad.py"
    proc = _run_cli("--select", "GL002", "--sarif", str(out), rel)
    assert proc.returncode == 1
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-2.1.0.json")
    (run,) = doc["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "graftlint"
    rule_ids = {r["id"] for r in driver["rules"]}
    assert rule_ids == {"GL000"} | {f"GL{i:03d}" for i in range(1, 18)}
    assert all(r["shortDescription"]["text"] for r in driver["rules"])
    results = run["results"]
    assert results, "expected GL002 results from the bad fixture"
    for r in results:
        assert r["ruleId"] == "GL002"
        assert r["level"] == "error"
        assert r["message"]["text"]
        loc = r["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == rel
        assert loc["region"]["startLine"] >= 1
        assert "suppressions" not in r  # nothing suppressed in the fixture


def test_sarif_marks_suppressed_in_source(tmp_path):
    from tools.graftlint.sarif import to_sarif

    result = lint_paths(
        [FIXTURES / "gl000_suppressions.py"], fixture_config(),
        root=REPO_ROOT,
    )
    doc = to_sarif(result)
    marks = [r.get("suppressions") for r in doc["runs"][0]["results"]
             if r["ruleId"] == "GL002"]
    assert [{"kind": "inSource"}] in marks  # the justified suppression
    assert None in marks                    # the unjustified one: live


def test_severity_warn_does_not_gate():
    """[tool.graftlint.severity] demotion: a warn-severity rule's findings
    are reported as warnings and keep the errors list (the gate) empty."""
    config = dataclasses.replace(fixture_config(),
                                 severity={"GL014": "warn"})
    result = lint_paths(
        [FIXTURES / "scheduler" / "gl014_bad.py"], config, root=REPO_ROOT
    )
    assert len(result.warnings) == 3
    assert not result.errors
    assert all(f.severity == "warn" for f in result.warnings)
    assert "[warn]" in result.warnings[0].format()
    # Default severity is error: same file, no demotion.
    result = lint_paths(
        [FIXTURES / "scheduler" / "gl014_bad.py"], fixture_config(),
        root=REPO_ROOT,
    )
    assert len(result.errors) == 3 and not result.warnings


def test_repo_lint_runtime_bound(repo_lint):
    """The repo-wide gate (all 17 rules, flow layer included) must stay a
    trivial fraction of the 870 s tier-1 cap. Generous bound — CI boxes
    are slow — but a superlinear flow-layer regression still trips it."""
    result, elapsed = repo_lint
    assert result.files_checked > 50
    assert elapsed < 30.0, (
        f"repo-wide lint took {elapsed:.1f}s — the flow layer went "
        f"superlinear; profile DefUse/literal_strings before raising this"
    )
