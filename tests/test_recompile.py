"""Recompilation regression: a second same-shaped call must NOT retrace.

The runtime twin of graftlint's GL006 (weak-type cache-key churn) and
GL003 (tracer control flow baking per-value programs): if anything in the
update path keys compilation on VALUES — a weak-typed constant flipping
strength, a Python branch on a tracer leaked through static args, a
non-hashable config sneaking into the cache key — the second iteration of
training silently recompiles. On the fleet configs one extra XLA compile
is tens of seconds of chip time per occurrence, paid every iteration; the
failure is invisible on CPU tests that only check numerics.

Probes ``jit(...)._cache_size()`` (stable across the container's 0.4.x
and the driver's newer JAX — asserted here so a version bump that drops
it fails loudly rather than silently weakening the gate).
"""

import jax
import pytest

from rl_scheduler_tpu.agent.dqn import DQNConfig, make_dqn
from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, make_ppo_bundle
from rl_scheduler_tpu.env.bundle import multi_cloud_bundle, single_cluster_bundle


def _cache_size(jitted) -> int:
    assert hasattr(jitted, "_cache_size"), (
        "jit cache probe missing on this JAX version — port this test to "
        "jax.log_compiles before trusting the recompile gate"
    )
    return jitted._cache_size()


def test_ppo_update_does_not_retrace():
    bundle = multi_cloud_bundle()
    cfg = PPOTrainConfig(
        num_envs=4, rollout_steps=8, minibatch_size=16, num_epochs=2,
        rollout_impl="scan",
    )
    init_fn, update_fn, _ = make_ppo_bundle(bundle, cfg)
    update = jax.jit(update_fn, donate_argnums=0)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    runner, _ = update(runner)
    first = _cache_size(update)
    runner, _ = update(runner)
    runner, _ = update(runner)
    assert _cache_size(update) == first == 1, (
        "PPO update retraced on same-shaped inputs — something in the "
        "update keys compilation on values (weak type, host branch, or an "
        "unhashable static)"
    )


def test_ppo_open_loop_update_does_not_retrace():
    """The open-loop rollout path builds different programs (batched RNG,
    no scan) — gate it separately."""
    bundle = multi_cloud_bundle()
    cfg = PPOTrainConfig(
        num_envs=4, rollout_steps=8, minibatch_size=16, num_epochs=2,
        rollout_impl="open_loop",
    )
    init_fn, update_fn, _ = make_ppo_bundle(bundle, cfg)
    update = jax.jit(update_fn, donate_argnums=0)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(1))
    runner, _ = update(runner)
    first = _cache_size(update)
    runner, _ = update(runner)
    assert _cache_size(update) == first == 1


def test_ppo_overlap_update_does_not_retrace():
    """The graftpipe pipelined update (stale collect_params slot + fused
    prologue) must not key compilation on values either — the slot is a
    pytree of arrays, and the prologue's per-minibatch gather indexes
    with a traced scan counter, not a Python int."""
    bundle = multi_cloud_bundle()
    cfg = PPOTrainConfig(
        num_envs=4, rollout_steps=8, minibatch_size=16, num_epochs=2,
        rollout_impl="scan", overlap_collect=True,
    )
    assert cfg.prologue_enabled  # auto follows overlap_collect
    init_fn, update_fn, _ = make_ppo_bundle(bundle, cfg)
    update = jax.jit(update_fn, donate_argnums=0)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(2))
    runner, _ = update(runner)
    first = _cache_size(update)
    runner, _ = update(runner)
    runner, _ = update(runner)
    assert _cache_size(update) == first == 1, (
        "pipelined PPO update retraced on same-shaped inputs"
    )


def test_dqn_update_does_not_retrace():
    bundle = single_cluster_bundle()
    cfg = DQNConfig(
        num_envs=2, collect_steps=4, buffer_size=64, batch_size=8,
        learning_starts=4,
    )
    init_fn, update_fn, _ = make_dqn(bundle, cfg)
    update = jax.jit(update_fn, donate_argnums=0)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    runner, _ = update(runner)
    first = _cache_size(update)
    # Crossing the learning_starts threshold must not retrace either: the
    # warm/cold switch is a lax.cond INSIDE one program, not two programs.
    for _ in range(6):
        runner, _ = update(runner)
    assert _cache_size(update) == first == 1, (
        "DQN update retraced on same-shaped inputs (did the buffer-warm "
        "branch leak to Python?)"
    )
