"""Property-based invariants (hypothesis) for the simulator and ops.

Example-based tests pin specific seeds and shapes; these sweep randomized
configs, actions, and shapes, checking the invariants that every
configuration must satisfy — the SURVEY.md §4 test-pyramid tier the
reference has nothing of.
"""

import jax
import jax.numpy as jnp
import numpy as np
from hypothesis import given, settings, strategies as st

from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.ops.gae import gae

from test_ops import numpy_gae  # the single numpy GAE reference

SETTINGS = dict(deadline=None, max_examples=25)

# Module-level jit + table: a fresh jax.jit wrapper (or a make_params CSV
# re-read) per hypothesis example would repeat compile/IO every time.
_JIT_STEP = jax.jit(env_core.step)
_TABLE = None


def _make_params(cfg: EnvConfig | None = None) -> env_core.EnvParams:
    global _TABLE
    if _TABLE is None:
        from rl_scheduler_tpu.data.loader import load_table

        _TABLE = load_table()
    return env_core.make_params(cfg or EnvConfig(), table=_TABLE)


@settings(**SETTINGS)
@given(
    t=st.integers(1, 12),
    n=st.integers(1, 5),
    gamma=st.floats(0.5, 1.0),
    lam=st.floats(0.0, 1.0),
    seed=st.integers(0, 2**31 - 1),
)
def test_gae_scan_matches_reference_formula(t, n, gamma, lam, seed):
    rng = np.random.default_rng(seed)
    rewards = rng.normal(size=(t, n)).astype(np.float32)
    values = rng.normal(size=(t, n)).astype(np.float32)
    dones = (rng.random((t, n)) < 0.2).astype(np.float32)
    last_value = rng.normal(size=n).astype(np.float32)
    adv, targets = gae(
        jnp.asarray(rewards), jnp.asarray(values), jnp.asarray(dones),
        jnp.asarray(last_value), gamma, lam, impl="scan",
    )
    expect_adv, expect_targets = numpy_gae(
        rewards, values, dones, last_value, gamma, lam
    )
    np.testing.assert_allclose(np.asarray(adv), expect_adv, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(targets), expect_targets, rtol=1e-4, atol=1e-4
    )


@settings(deadline=None, max_examples=10)
@given(
    seed=st.integers(0, 2**31 - 1),
    cost_weight=st.floats(0.0, 1.0),
    fault_prob=st.sampled_from([0.0, 0.3, 1.0]),
    num_steps=st.integers(1, 120),
)
def test_env_step_invariants(seed, cost_weight, fault_prob, num_steps):
    """For ANY config: obs bounds, reward formula sign, episode wrap."""
    params = _make_params(EnvConfig(
        cost_weight=cost_weight,
        latency_weight=1.0 - cost_weight,
        fault_prob=fault_prob,
    ))
    ms = int(params.max_steps)
    key = jax.random.PRNGKey(seed)
    state, obs = env_core.reset(params, key)
    for t in range(num_steps):
        action = jnp.asarray((seed + t) % 2, jnp.int32)
        state, ts = _JIT_STEP(params, state, action)
        o = np.asarray(ts.obs)
        assert o.shape == (env_core.OBS_DIM,)
        assert (o >= 0.0).all() and (o <= 1.0).all(), o
        # corrected sign: reward is never positive (costs are non-negative)
        assert float(ts.reward) <= 0.0
        assert int(ts.step) == t + 1
        assert bool(ts.done) == (t + 1 >= ms)
        if bool(ts.done):
            break
    # state always stays inside the table
    assert 0 <= int(state.step_idx) <= ms


@settings(**SETTINGS)
@given(
    seed=st.integers(0, 2**31 - 1),
    n=st.integers(1, 6),
    t=st.integers(1, 30),
)
def test_open_loop_rewards_match_step_for_any_actions(seed, n, t):
    """Property form of the open-loop parity tests: for any env batch and
    action sequence, open_loop_rewards equals the step() formula exactly
    (fault_prob=0 so rewards are table-deterministic)."""
    from rl_scheduler_tpu.env import vector

    params = _make_params()
    state, obs = vector.reset_batch(params, jax.random.PRNGKey(seed), n)
    _, aux, new_state = env_core.open_loop_horizon(
        params, state, obs, jax.random.PRNGKey(seed + 1), t
    )
    rng = np.random.default_rng(seed)
    actions = jnp.asarray(rng.integers(0, 2, (t, n)), jnp.int32)
    rewards = np.asarray(env_core.open_loop_rewards(params, aux, actions))
    ms = int(params.max_steps)
    idx = (np.asarray(state.step_idx)[None, :] + np.arange(t)[:, None]) % ms
    a = np.asarray(actions)
    cost = np.asarray(params.costs)[idx, a]
    lat = np.asarray(params.latencies)[idx, a]
    expect = -100.0 * (0.6 * cost + 0.4 * lat)
    np.testing.assert_allclose(rewards, expect, rtol=1e-5)
    np.testing.assert_array_equal(
        np.asarray(new_state.step_idx), (np.asarray(state.step_idx) + t) % ms
    )


@settings(**SETTINGS)
@given(
    cap=st.integers(4, 64),
    adds=st.lists(st.integers(1, 16), min_size=1, max_size=8),
)
def test_replay_buffer_circular_invariants(cap, adds):
    """Size never exceeds capacity; pos always in range; newest data wins."""
    from rl_scheduler_tpu.agent.dqn import buffer_add, buffer_init

    buf = buffer_init(cap, (3,))
    total = 0
    for k, n in enumerate(adds):
        batch = {
            "obs": jnp.full((n, 3), float(k), jnp.float32),
            "action": jnp.zeros(n, jnp.int32),
            "reward": jnp.full(n, float(k), jnp.float32),
            "done": jnp.zeros(n, jnp.float32),
            "next_obs": jnp.zeros((n, 3), jnp.float32),
        }
        buf = buffer_add(buf, batch)
        total += n
        assert int(buf.size) == min(total, cap)
        assert 0 <= int(buf.pos) < cap
    # the most recent element is always retrievable at pos-1
    last = (int(buf.pos) - 1) % cap
    assert float(buf.reward[last]) == float(len(adds) - 1)
