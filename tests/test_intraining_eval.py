"""In-training periodic evaluation (reference train_final.py:19 parity:
evaluation_interval=5, evaluation_duration=20 — here --eval-every /
--eval-episodes on both train CLIs)."""

import json

import jax
import jax.numpy as jnp
import pytest

from rl_scheduler_tpu.agent.evaluate import make_greedy_eval_fn
from rl_scheduler_tpu.env.bundle import (
    cluster_set_bundle,
    multi_cloud_bundle,
    single_cluster_bundle,
)


def _read_jsonl(path):
    return [json.loads(line) for line in path.read_text().splitlines()]


class TestGreedyEvalFn:
    def test_multi_cloud_counts_and_determinism(self):
        from rl_scheduler_tpu.models import ActorCritic

        bundle = multi_cloud_bundle()
        net = ActorCritic(num_actions=bundle.num_actions, hidden=(8, 8))
        params = net.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, *bundle.obs_shape)))
        eval_fn = make_greedy_eval_fn(bundle, net, num_episodes=5)
        m = jax.device_get(eval_fn(params, jax.random.PRNGKey(1)))
        # fixed-length episodes: every lane completes exactly one episode
        assert m["eval_episodes_completed"] == 5
        assert jnp.isfinite(m["eval_episode_reward_mean"])
        # greedy policy + same key => identical metrics
        m2 = jax.device_get(eval_fn(params, jax.random.PRNGKey(1)))
        assert m2["eval_episode_reward_mean"] == m["eval_episode_reward_mean"]

    def test_works_for_q_networks(self):
        """The greedy argmax serves actor-critic AND Q-net outputs."""
        from rl_scheduler_tpu.models import QNetwork

        bundle = single_cluster_bundle()
        net = QNetwork(num_actions=bundle.num_actions, hidden=(8, 8))
        params = net.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, *bundle.obs_shape)))
        m = jax.device_get(
            make_greedy_eval_fn(bundle, net, num_episodes=3)(
                params, jax.random.PRNGKey(2)
            )
        )
        assert m["eval_episodes_completed"] == 3

    def test_structured_policy_bundle(self):
        from rl_scheduler_tpu.models import SetTransformerPolicy

        bundle = cluster_set_bundle()
        net = SetTransformerPolicy(dim=16, depth=1)
        params = net.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, *bundle.obs_shape)))
        m = jax.device_get(
            make_greedy_eval_fn(bundle, net, num_episodes=2)(
                params, jax.random.PRNGKey(3)
            )
        )
        assert m["eval_episodes_completed"] == 2

    def test_rejects_bundle_without_episode_steps(self):
        bundle = multi_cloud_bundle()._replace(episode_steps=None)
        with pytest.raises(ValueError, match="episode_steps"):
            make_greedy_eval_fn(bundle, net=None)


class TestTrainCLIEval:
    def test_ppo_cli_emits_eval_records(self, tmp_path):
        from rl_scheduler_tpu.agent import train_ppo as cli

        run_dir = cli.main([
            "--preset", "quick", "--num-envs", "4", "--rollout-steps", "100",
            "--minibatch-size", "64", "--hidden", "8,8", "--iterations", "4",
            "--run-root", str(tmp_path), "--run-name", "eval_test",
            "--eval-every", "2", "--eval-episodes", "4",
        ])
        records = _read_jsonl(run_dir / "metrics.jsonl")
        evals = [r for r in records if r.get("eval")]
        assert [r["iteration"] for r in evals] == [2, 4]
        for r in evals:
            assert r["eval_episodes_completed"] == 4.0
            assert "eval_episode_reward_mean" in r
        # ordering: the eval record lands after the training record of the
        # iteration it evaluated (the loop flushes pending metrics first)
        idx_train2 = next(i for i, r in enumerate(records)
                          if not r.get("eval") and r["iteration"] == 2)
        idx_eval2 = next(i for i, r in enumerate(records)
                         if r.get("eval") and r["iteration"] == 2)
        assert idx_eval2 > idx_train2

    def test_dqn_cli_emits_eval_records(self, tmp_path):
        from rl_scheduler_tpu.agent import train_dqn as cli

        run_dir = cli.main([
            "--env", "multi_cloud", "--preset", "config1",
            "--iterations", "6", "--hidden", "8,8",
            "--run-root", str(tmp_path), "--run-name", "dqn_eval_test",
            "--checkpoint-every", "6", "--sync-every", "2",
            "--eval-every", "3", "--eval-episodes", "2",
        ])
        evals = [r for r in _read_jsonl(run_dir / "metrics.jsonl")
                 if r.get("eval")]
        assert [r["iteration"] for r in evals] == [3, 6]
        assert all(r["eval_episodes_completed"] == 2.0 for r in evals)

    def test_final_preset_defaults_to_reference_eval_schedule(self):
        from rl_scheduler_tpu.agent.presets import PPO_PRESETS

        assert PPO_PRESETS["final"].eval_every == 5
        assert PPO_PRESETS["final"].eval_episodes == 20


def test_fused_dispatch_rejects_misaligned_checkpoint_interval():
    """ADVICE r2: a checkpoint interval that updates_per_dispatch would
    silently skip must raise up front, mirroring the eval_every check."""
    import pytest

    from rl_scheduler_tpu.agent.loop import (
        make_periodic_checkpoint_fn,
        run_train_loop,
    )

    class _Ckpt:
        def save(self, step, tree, extras=None):
            pass

    fn = make_periodic_checkpoint_fn(_Ckpt(), 3, 8, lambda r: {}, {})
    assert fn.every == 3
    with pytest.raises(ValueError, match="checkpoint interval 3"):
        run_train_loop(
            lambda r: (r, {}), runner=None, start_iteration=0,
            num_iterations=8, checkpoint_fn=fn, updates_per_dispatch=2,
        )


def test_align_checkpoint_interval():
    """Defaults auto-align up to the dispatch factor; explicit misaligned
    values are refused rather than silently rewritten."""
    import pytest

    from rl_scheduler_tpu.agent.loop import align_checkpoint_interval

    assert align_checkpoint_interval(None, 10, 1) == 10
    assert align_checkpoint_interval(None, 10, 100) == 100
    assert align_checkpoint_interval(None, 500, 300) == 600
    assert align_checkpoint_interval(200, 10, 100) == 200
    with pytest.raises(SystemExit, match="not a multiple"):
        align_checkpoint_interval(500, 10, 300)
    # Explicit <=0 cadences must be refused here, BEFORE the run dir
    # exists — not surface as ZeroDivisionError at the first boundary.
    with pytest.raises(SystemExit, match="positive"):
        align_checkpoint_interval(0, 10, 1)
    with pytest.raises(SystemExit, match="positive"):
        align_checkpoint_interval(-5, 10, 2)


def test_train_cli_rejects_nonpositive_num_epochs(tmp_path):
    """--num-epochs 0 would scan over zero SGD passes (training completes
    without ever updating params); the CLI refuses it up front — and the
    guard lives in PPOTrainConfig.__post_init__, so programmatic
    construction fails just as loudly."""
    from rl_scheduler_tpu.agent import train_ppo
    from rl_scheduler_tpu.agent.ppo import PPOTrainConfig

    with pytest.raises(ValueError, match="num_epochs"):
        PPOTrainConfig(num_epochs=0)

    with pytest.raises(SystemExit, match="num-epochs"):
        train_ppo.main(["--preset", "quick", "--num-epochs", "0",
                        "--run-root", str(tmp_path)])
    assert not list(tmp_path.iterdir())  # refused before any side effects
