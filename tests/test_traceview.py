"""traceview: fixture round-trip into the documented schema, self-time
attribution, budget checking (the obs gate), and the CLI contract.

The checked-in fixture (``tests/fixtures/traceview/fixture.trace.json.gz``)
is a hand-built Perfetto trace with exactly-known self-times: a 50 ms
``jit(update_fn)`` span containing rollout (10 compute + 2 copy), gae (3),
sgd (25 compute + 5 copy) children — so the parent's SELF time is 5 ms —
plus a 1 ms host python frame, plus a second 30 ms graftpipe
``jit(update_fn)`` span (overlap_collect 8, prologue 4 at the head + 1
nested INSIDE the sgd scan — pinning that "prologue" outranks "sgd" in
phase order — sgd 15, parent self 2). ``tools/traceview/budgets.json``
records the phase totals; this file is the pytest gate behind ``make obs``.
"""

import gzip
import json
from pathlib import Path

import pytest

from tools.traceview import (
    budgets_from_summary,
    check_budgets,
    find_trace,
    load_trace,
    summarize,
)
from tools.traceview.__main__ import main as traceview_main

REPO_ROOT = Path(__file__).resolve().parents[1]
FIXTURE = REPO_ROOT / "tests" / "fixtures" / "traceview" / "fixture.trace.json.gz"
BUDGETS = REPO_ROOT / "tools" / "traceview" / "budgets.json"


@pytest.fixture(scope="module")
def fixture_summary():
    return summarize(load_trace(FIXTURE), source=str(FIXTURE))


# ------------------------------------------------- schema round-trip


def test_fixture_roundtrips_documented_schema(fixture_summary):
    """The acceptance path: checked-in trace -> the docs/observability.md
    schema, with self-times attributed exactly once."""
    s = fixture_summary
    assert s["metric"] == "traceview-phase-breakdown"
    assert s["unit"] == "ms"
    assert s["schema_version"] == 1
    assert s["source"].endswith("fixture.trace.json.gz")
    # Self-time accounting: child durations subtracted from the enclosing
    # jit span, every microsecond attributed exactly once.
    assert s["total_ms"] == pytest.approx(81.0)
    phases = s["phases"]
    assert set(phases) == {"rollout", "gae", "sgd", "overlap", "prologue",
                           "other"}
    assert phases["rollout"]["total_ms"] == pytest.approx(12.0)
    assert phases["rollout"]["categories"]["compute"] == pytest.approx(10.0)
    assert phases["rollout"]["categories"]["transfer"] == pytest.approx(2.0)
    assert phases["gae"]["total_ms"] == pytest.approx(3.0)
    assert phases["sgd"]["total_ms"] == pytest.approx(45.0)
    assert phases["sgd"]["categories"]["transfer"] == pytest.approx(5.0)
    # graftpipe span: the pipelined rollout's own scope ("overlap_collect"
    # must not be swallowed by the generic collect/rollout markers) and
    # the fused prologue — including the gather nested INSIDE the sgd
    # scan, which classifies as prologue because its marker outranks sgd.
    assert phases["overlap"]["total_ms"] == pytest.approx(8.0)
    assert phases["prologue"]["total_ms"] == pytest.approx(5.0)
    # The jit parents' SELF times (5 + 2) plus the 1 ms host frame land
    # in "other".
    assert phases["other"]["total_ms"] == pytest.approx(8.0)
    assert phases["other"]["categories"]["host"] == pytest.approx(1.0)
    for entry in phases.values():
        assert entry["fraction"] == pytest.approx(
            entry["total_ms"] / s["total_ms"], abs=1e-5)
        assert entry["total_ms"] == pytest.approx(
            sum(entry["categories"].values()))
    # JSON-serializable end to end (the bench.py-style output line).
    json.dumps(s)


def test_self_time_nesting_and_thread_isolation():
    """Unit check on the stack pass: siblings, grandchildren, and an
    identical-ts event on ANOTHER thread must not steal self-time."""
    events = [
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 100,
         "name": "parent"},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 30, "name": "c1"},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 10, "dur": 5, "name": "g1"},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 40, "dur": 20, "name": "c2"},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 0, "dur": 100,
         "name": "othread"},
    ]
    s = summarize({"traceEvents": events})
    # parent self = 100 - 30 - 20; c1 self = 30 - 5; all in phase "other".
    assert s["total_ms"] == pytest.approx(0.2)  # 100 + 100 us per thread
    assert s["phases"]["other"]["total_ms"] == pytest.approx(0.2)


def test_phase_markers_from_long_name_and_thread_name():
    events = [
        {"ph": "M", "pid": 1, "tid": 9, "name": "thread_name",
         "args": {"name": "rollout worker"}},
        {"ph": "X", "pid": 1, "tid": 9, "ts": 0, "dur": 10, "name": "op"},
        {"ph": "X", "pid": 1, "tid": 3, "ts": 0, "dur": 7, "name": "f.1",
         "args": {"long_name": "jit(update_fn)/sgd/while/f.1"}},
        {"ph": "X", "pid": 1, "tid": 3, "ts": 10, "dur": 4,
         "name": "all-reduce.2",
         "args": {"long_name": "jit(update_fn)/sgd/all-reduce.2"}},
    ]
    s = summarize({"traceEvents": events})
    assert s["phases"]["rollout"]["total_ms"] == pytest.approx(0.01)
    assert s["phases"]["sgd"]["total_ms"] == pytest.approx(0.011)
    assert s["phases"]["sgd"]["categories"]["transfer"] == pytest.approx(0.004)


# ------------------------------------------------------- budget checks


def test_checked_in_budgets_pass_on_fixture(fixture_summary):
    """The make-obs invariant: the committed budgets accept the committed
    fixture."""
    budgets = json.loads(BUDGETS.read_text())
    assert check_budgets(fixture_summary, budgets) == []


def test_injected_25pct_regression_fails_budgets(fixture_summary):
    """A 25% across-the-board slowdown must trip the 20% tolerance for
    every budgeted phase."""
    budgets = json.loads(BUDGETS.read_text())
    slowed = json.loads(json.dumps(fixture_summary))
    for entry in slowed["phases"].values():
        entry["total_ms"] *= 1.25
    violations = check_budgets(slowed, budgets)
    assert len(violations) == len(budgets["phases"])
    assert all("exceeds budget" in v for v in violations)


def test_within_tolerance_regression_passes(fixture_summary):
    budgets = json.loads(BUDGETS.read_text())
    slowed = json.loads(json.dumps(fixture_summary))
    for entry in slowed["phases"].values():
        entry["total_ms"] *= 1.15
    assert check_budgets(slowed, budgets) == []


def test_absent_budgeted_phase_is_a_violation(fixture_summary):
    """A renamed named_scope zeroes its phase — that must FAIL, not pass
    with 0 ms < budget."""
    stripped = json.loads(json.dumps(fixture_summary))
    del stripped["phases"]["sgd"]
    violations = check_budgets(stripped,
                               json.loads(BUDGETS.read_text()))
    assert len(violations) == 1
    assert "absent" in violations[0] and "'sgd'" in violations[0]


def test_budgets_from_summary_excludes_other(fixture_summary):
    budgets = budgets_from_summary(fixture_summary, tolerance_pct=20.0)
    assert budgets["tolerance_pct"] == 20.0
    assert set(budgets["phases"]) == {"rollout", "gae", "sgd", "overlap",
                                      "prologue"}
    assert budgets["phases"]["sgd"] == pytest.approx(45.0)
    # And the freshly-recorded baseline accepts the trace it came from.
    assert check_budgets(fixture_summary, budgets) == []


# ------------------------------------------------------------ find_trace


def test_find_trace_resolves_newest_in_profiler_dir(tmp_path):
    layout = tmp_path / "plugins" / "profile"
    for i, ts in enumerate(("2026_01_01", "2026_01_02")):
        d = layout / ts
        d.mkdir(parents=True)
        p = d / f"host.trace.json.gz"
        with gzip.open(p, "wt") as fh:
            json.dump({"traceEvents": []}, fh)
        # Ensure distinct mtimes regardless of filesystem resolution.
        import os
        os.utime(p, (1000 + i, 1000 + i))
    assert find_trace(tmp_path).parent.name == "2026_01_02"


def test_find_trace_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="no trace"):
        find_trace(tmp_path / "nope")
    with pytest.raises(FileNotFoundError):
        find_trace(tmp_path)  # empty dir


def test_load_trace_reads_plain_json(tmp_path):
    p = tmp_path / "t.trace.json"
    p.write_text(json.dumps({"traceEvents": []}))
    assert load_trace(p) == {"traceEvents": []}


# ------------------------------------------------------------------ CLI


def test_cli_prints_one_summary_line_and_checks_budgets(capsys):
    rc = traceview_main(["--check", "--budgets", str(BUDGETS), str(FIXTURE)])
    out = capsys.readouterr()
    assert rc == 0
    lines = out.out.strip().splitlines()
    assert len(lines) == 1  # ONE bench.py-style JSON line on stdout
    summary = json.loads(lines[0])
    assert summary["metric"] == "traceview-phase-breakdown"
    assert "OK" in out.err


def test_cli_exits_2_on_budget_violation(tmp_path, capsys):
    """The fail-the-build contract: an injected 25% regression on the
    trace side exits nonzero under --check."""
    data = load_trace(FIXTURE)
    for e in data["traceEvents"]:
        if e.get("ph") == "X":
            e["dur"] = int(e["dur"] * 1.25)
    slowed = tmp_path / "slow.trace.json"
    slowed.write_text(json.dumps(data))
    rc = traceview_main(["--check", "--budgets", str(BUDGETS), str(slowed)])
    err = capsys.readouterr().err
    assert rc == 2
    assert "BUDGET VIOLATION" in err


def test_cli_write_budgets_round_trip(tmp_path, capsys):
    out_path = tmp_path / "budgets.json"
    rc = traceview_main(["--write-budgets", str(out_path),
                         "--tolerance-pct", "10", str(FIXTURE)])
    capsys.readouterr()
    assert rc == 0
    written = json.loads(out_path.read_text())
    assert written["tolerance_pct"] == 10.0
    assert written["phases"]["rollout"] == pytest.approx(12.0)
    # The recorded baseline gates itself: same trace passes, --check works.
    rc = traceview_main(["--check", "--budgets", str(out_path),
                         str(FIXTURE)])
    capsys.readouterr()
    assert rc == 0


def test_cli_missing_trace_and_missing_budgets(tmp_path, capsys):
    assert traceview_main([str(tmp_path / "absent")]) == 1
    assert "traceview:" in capsys.readouterr().err
    assert traceview_main(["--check", str(FIXTURE)]) == 1
    assert "--check needs --budgets" in capsys.readouterr().err
