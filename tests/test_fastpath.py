"""graftfwd (PR 13): the serving fast path — exact-agreement suites per
lever (telemetry-epoch score cache, cross-request micro-batching, the
int8 native fleet forward), span-uniformity under batching, the
flush-on-promote verify hook, and the bench's lever matrix. The
fastpath.agree chaos test lives with the other rollout chaos tests in
tests/test_graftguard.py; pool-wide fastpath aggregation is unit-tested
here against worker-snapshot dicts (the pool suite's discipline)."""

import json
import threading
import time
import types

import numpy as np
import pytest

from rl_scheduler_tpu.scheduler.extender import (
    PHASES,
    ExtenderPolicy,
    build_policy,
    fastpath_metric_lines,
)
from rl_scheduler_tpu.scheduler.fastpath import (
    INT8_AGREEMENT_MIN,
    MicroBatcher,
    ScoreCache,
    agreement_corpus,
    check_int8_agreement,
)
from rl_scheduler_tpu.scheduler.set_backend import (
    Int8NativeSetBackend,
    JaxSetAOTBackend,
    NumpySetBackend,
    make_set_backend,
)
from rl_scheduler_tpu.utils.faults import FaultPlan


@pytest.fixture(scope="module")
def set_tree():
    import jax
    import jax.numpy as jnp

    from rl_scheduler_tpu.models.transformer import SetTransformerPolicy

    net = SetTransformerPolicy(dim=64, depth=2)
    return net.init(jax.random.PRNGKey(3), jnp.zeros((8, 6), jnp.float32))


class FrozenTelemetry:
    """Telemetry stub whose observation never changes — the setting the
    score cache's exact-agreement contract is judged in (between scrapes
    the real telemetry is constant too)."""

    def __init__(self, n=8, feat=6, seed=0):
        rng = np.random.default_rng(seed)
        self.obs = rng.uniform(0, 1, (n, feat)).astype(np.float32)
        self.observes = 0
        self.noted = None
        from rl_scheduler_tpu.scheduler.telemetry import RandomCpu

        self.cpu = RandomCpu(seed=seed)

    def observe_nodes(self, clouds, pod_cpu):
        self.observes += 1
        return self.obs[: len(clouds)].copy()

    def last_replay_position(self):
        return 42

    def note_replay_position(self, raw):
        self.noted = raw


def _clouds(n=8):
    return ["aws" if i % 2 == 0 else "azure" for i in range(n)]


# ------------------------------------------------------------- score cache


def test_cache_hit_is_bitwise_and_skips_observe(set_tree):
    """Lever (iii) exact agreement: with telemetry frozen inside the
    epoch, a cache hit returns the SAME decision a recompute would —
    bitwise — while skipping the observe and forward phases entirely."""
    telemetry = FrozenTelemetry()
    policy = ExtenderPolicy(NumpySetBackend(set_tree), telemetry)
    policy.score_cache = ScoreCache(epoch_s=3600.0)
    clouds = _clouds()
    a1, p1, o1 = policy.decide_set(clouds, 0.25)
    observes_after_miss = telemetry.observes
    a2, p2, o2 = policy.decide_set(clouds, 0.25)
    assert telemetry.observes == observes_after_miss  # observe skipped
    assert a2 == a1
    assert np.array_equal(p2, p1)                     # bitwise
    assert np.array_equal(o2, o1)                     # stored provenance
    # The recompute (cache off) is bitwise-identical too: same obs,
    # deterministic forward.
    a3, logits3 = policy.backend.decide_nodes(o2)
    assert a3 == a1
    snap = policy.score_cache.snapshot()
    assert snap["hits_total"] == 1 and snap["misses_total"] == 1
    # The hit's trace provenance names the ORIGINAL replay position.
    assert telemetry.noted == 42
    stats = policy.statistics()
    assert stats["fastpath"]["cache"]["hit_rate"] == 0.5


def test_cache_hit_keeps_phase_count_uniformity(set_tree):
    """A hit still records one sample per phase (the request-level span
    accumulator closes out through the handlers), with forward charged
    its true zero."""
    telemetry = FrozenTelemetry()
    policy = ExtenderPolicy(NumpySetBackend(set_tree), telemetry)
    policy.score_cache = ScoreCache(epoch_s=3600.0)
    args = {"nodenames": [f"{'aws' if i % 2 else 'azure'}-n{i}"
                          for i in range(8)], "pod": {}}
    for _ in range(4):
        policy.filter(dict(args))
    assert policy.score_cache.snapshot()["hits_total"] == 3
    for phase in PHASES:
        assert policy.phase_stats[phase].histogram()[2] == 4
    # 3 hits charged 0 forward: the forward phase's lifetime sum is the
    # single miss's forward alone, well under the e2e sum.
    fwd_sum = policy.phase_stats["forward"].histogram()[1]
    e2e_sum = policy.stats.histogram()[1]
    assert fwd_sum < e2e_sum


def test_cache_keys_generation_pod_and_nodeset():
    key = ScoreCache.make_key(0, ["aws", None], 0.25, None)
    assert ScoreCache.make_key(1, ["aws", None], 0.25, None) != key
    assert ScoreCache.make_key(0, ["aws", "azure"], 0.25, None) != key
    assert ScoreCache.make_key(0, ["aws", None], 0.5, None) != key
    assert ScoreCache.make_key(0, ["aws", None], 0.25, [0.1, 0.2]) != key
    assert ScoreCache.make_key(0, ["aws", None], 0.25, None) == key


def test_cache_epoch_rollover_invalidates_like_price_replay():
    """Epoch semantics pinned like --price-replay wallclock: the epoch
    is int(now / epoch_s); crossing the boundary drops every entry and
    counts ONE invalidation."""
    now = [0.0]
    cache = ScoreCache(epoch_s=15.0, clock=lambda: now[0])
    key = cache.make_key(0, ["aws"], 0.25, None)
    cache.put(key, 1, np.ones(1), np.ones((1, 6)), 7)
    assert cache.get(key) is not None
    now[0] = 14.9
    assert cache.get(key) is not None          # same epoch: still live
    now[0] = 15.1
    assert cache.get(key) is None              # rolled: invalidated
    snap = cache.snapshot()
    assert snap["invalidations_total"] == 1
    assert snap["entries"] == 0
    assert snap["epoch"] == 1


def test_cache_lru_bound_and_flush():
    cache = ScoreCache(epoch_s=3600.0, max_entries=2)
    for i in range(3):
        cache.put((0, (f"n{i}",), 0.25, None), i, np.ones(1),
                  np.ones((1, 6)), i)
    assert cache.snapshot()["entries"] == 2
    assert cache.get((0, ("n0",), 0.25, None)) is None  # LRU-evicted
    assert cache.flush("test") == 2
    snap = cache.snapshot()
    assert snap["entries"] == 0
    # two invalidations: none from LRU (bound, not epoch), one flush,
    # plus the epoch init... flush counts exactly one.
    assert snap["invalidations_total"] == 1


def test_cache_validation():
    with pytest.raises(ValueError):
        ScoreCache(epoch_s=0)
    with pytest.raises(ValueError):
        ScoreCache(max_entries=0)


def test_fastpath_verify_flushes_cache(set_tree):
    """Flush-on-promote: the rollout gate's fastpath command must drop
    every entry — a stale-generation hit after a rollout is a
    correctness bug even with the generation in the key."""
    policy = ExtenderPolicy(NumpySetBackend(set_tree), FrozenTelemetry())
    policy.score_cache = ScoreCache(epoch_s=3600.0)
    policy.decide_set(_clouds(), 0.25)
    assert policy.score_cache.snapshot()["entries"] == 1
    out = policy.fastpath_verify()
    assert out["ok"] and out["cache_flushed"] == 1
    assert policy.score_cache.snapshot()["entries"] == 0


def test_probe_bypasses_cache(set_tree):
    """A rollout warm-up probe must exercise the REAL decide path (a
    cached answer is not a gate signal) and must not seed the cache."""
    telemetry = FrozenTelemetry()
    policy = ExtenderPolicy(NumpySetBackend(set_tree), telemetry)
    policy.score_cache = ScoreCache(epoch_s=3600.0)
    assert policy.warmup_probe()["decided"]
    assert policy.warmup_probe()["decided"]
    snap = policy.score_cache.snapshot()
    assert snap["hits_total"] == 0 and snap["misses_total"] == 0
    assert snap["entries"] == 0


# ----------------------------------------------------------- micro-batcher


def test_batcher_coalesces_and_agrees_with_sequential(set_tree):
    """Lever (i): k concurrent same-shape submits share ONE [k, N, F]
    forward, and every row's decision agrees with its own sequential
    forward (tolerance on the numpy host batch; the bitwise guarantee
    is the AOT test below)."""
    backend = NumpySetBackend(set_tree)
    batcher = MicroBatcher(backend, window_s=0.25, max_batch=4)
    rng = np.random.default_rng(0)
    obs = [rng.uniform(0, 1, (16, 6)).astype(np.float32) for _ in range(4)]
    results = [None] * 4

    def submit(i):
        results[i] = batcher.submit(obs[i], generation=0)

    threads = [threading.Thread(target=submit, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(4):
        action, logits, forward_s = results[i]
        ref_action, ref_logits = backend.decide_nodes(obs[i])
        assert action == ref_action
        np.testing.assert_allclose(logits, ref_logits, atol=1e-5)
        assert forward_s > 0
    snap = batcher.snapshot()
    assert snap["requests_total"] == 4
    assert snap["batches_total"] < 4          # at least one coalesce
    assert snap["coalesced_total"] >= 2
    assert snap["max_occupancy"] >= 2


def test_batcher_keys_on_shape_and_generation(set_tree):
    """Different obs specs (and generations) never share a forward —
    the AOT executable and the checkpoint must match every row."""
    backend = NumpySetBackend(set_tree)
    batcher = MicroBatcher(backend, window_s=0.15, max_batch=4)
    results = {}

    def submit(name, obs, gen):
        results[name] = batcher.submit(obs, generation=gen)

    rng = np.random.default_rng(1)
    o8 = rng.uniform(0, 1, (8, 6)).astype(np.float32)
    o16 = rng.uniform(0, 1, (16, 6)).astype(np.float32)
    threads = [threading.Thread(target=submit, args=(n, o, g))
               for n, o, g in (("a", o8, 0), ("b", o16, 0), ("c", o8, 1))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert batcher.snapshot()["batches_total"] == 3  # nothing coalesced
    for name, obs in (("a", o8), ("b", o16), ("c", o8)):
        ref_action, _ = backend.decide_nodes(obs)
        assert results[name][0] == ref_action


def test_batcher_error_fans_out_to_every_member():
    class Poisoned:
        def decide_nodes_batch(self, batch):
            raise RuntimeError("poisoned batch")

    batcher = MicroBatcher(Poisoned(), window_s=0.15, max_batch=2)
    obs = np.zeros((4, 6), np.float32)
    errors = []

    def submit():
        try:
            batcher.submit(obs, generation=0)
        except RuntimeError as e:
            errors.append(str(e))

    threads = [threading.Thread(target=submit) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == ["poisoned batch", "poisoned batch"]


def test_batcher_validation(set_tree):
    backend = NumpySetBackend(set_tree)
    with pytest.raises(ValueError):
        MicroBatcher(backend, window_s=0.0)
    with pytest.raises(ValueError):
        MicroBatcher(backend, window_s=0.01, max_batch=1)
    with pytest.raises(ValueError):
        MicroBatcher(object(), window_s=0.01)  # no decide_nodes_batch


def test_span_uniformity_under_batching(set_tree):
    """graftlens invariant under lever (i): k coalesced requests each
    still record exactly one sample per phase — batch_wait included —
    and the batch_wait phase carries real window time while the shared
    forward is charged once per member."""
    policy = ExtenderPolicy(NumpySetBackend(set_tree), FrozenTelemetry())
    policy.batcher = MicroBatcher(policy.backend, window_s=0.1,
                                  max_batch=4)
    args = {"nodenames": [f"{'aws' if i % 2 else 'azure'}-n{i}"
                          for i in range(8)], "pod": {}}
    k = 4
    threads = [threading.Thread(target=policy.filter, args=(dict(args),))
               for _ in range(k)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stats = policy.statistics()
    assert set(stats["phases"]) == set(PHASES)
    for phase in PHASES:
        assert stats["phases"][phase]["lifetime_count"] == k
    # Everyone waited some window time; the forward phase carries the
    # shared batch forward, not k full windows.
    assert stats["phases"]["batch_wait"]["lifetime_mean_ms"] > 0
    assert stats["fastpath"]["batch"]["coalesced_total"] >= 2


def test_batch_wait_records_zero_without_batching(set_tree):
    """Count-uniformity with the lever OFF: batch_wait still records one
    (zero-cost) sample per decision, so decisionview's reconciliation
    row closes on pre-batching serve configs too."""
    policy = ExtenderPolicy(NumpySetBackend(set_tree), FrozenTelemetry())
    args = {"nodenames": ["aws-0", "azure-1"], "pod": {}}
    for _ in range(3):
        policy.filter(dict(args))
    assert policy.phase_stats["batch_wait"].histogram()[2] == 3
    assert policy.phase_stats["batch_wait"].histogram()[1] == 0.0


def test_batched_aot_forward_is_bitwise(set_tree):
    """THE lever-(i) exact-agreement bar: the batched AOT executable
    (jax.vmap of the single-request apply) returns per-row logits
    BITWISE-identical to the single-request AOT executable."""
    backend = JaxSetAOTBackend(set_tree, warm_counts=(16,),
                               warm_batches=((3, 16),))
    rng = np.random.default_rng(2)
    batch = rng.uniform(0, 1, (3, 16, 6)).astype(np.float32)
    assert backend.has_batch_executable(3, 16)
    actions, logits = backend.decide_nodes_batch(batch)
    for i in range(3):
        a_ref, l_ref = backend.decide_nodes(batch[i])
        assert int(actions[i]) == a_ref
        assert np.array_equal(logits[i], l_ref)  # bitwise


def test_batched_aot_uncompiled_shape_serves_host_then_compiles(set_tree):
    backend = JaxSetAOTBackend(set_tree, warm_counts=(8,))
    rng = np.random.default_rng(3)
    batch = rng.uniform(0, 1, (2, 8, 6)).astype(np.float32)
    assert not backend.has_batch_executable(2, 8)
    actions, logits = backend.decide_nodes_batch(batch)  # host fallback
    for i in range(2):
        a_ref, l_ref = backend._fallback.decide_nodes(batch[i])
        assert int(actions[i]) == a_ref
        np.testing.assert_allclose(logits[i], l_ref, atol=1e-5)
    deadline = time.monotonic() + 60.0
    while (not backend.has_batch_executable(2, 8)
           and time.monotonic() < deadline):
        time.sleep(0.05)
    assert backend.has_batch_executable(2, 8)  # background compile landed


def test_torch_batch_agrees_with_sequential(set_tree):
    torch = pytest.importorskip("torch")
    del torch
    from rl_scheduler_tpu.scheduler.set_backend import TorchSetBackend

    backend = TorchSetBackend(set_tree)
    rng = np.random.default_rng(4)
    batch = rng.uniform(0, 1, (3, 12, 6)).astype(np.float32)
    actions, logits = backend.decide_nodes_batch(batch)
    for i in range(3):
        a_ref, l_ref = backend.decide_nodes(batch[i])
        assert int(actions[i]) == a_ref
        np.testing.assert_allclose(logits[i], l_ref, atol=1e-5)


# ------------------------------------------------------------- int8 native


def _int8_backend(set_tree):
    try:
        return Int8NativeSetBackend(set_tree)
    except Exception as e:  # noqa: BLE001 - no toolchain in this env
        pytest.skip(f"native toolchain unavailable: {e}")


def test_int8_agreement_corpus_clears_the_gate(set_tree):
    """Lever (ii) exact-agreement bar: >= 99.5% top-1 agreement vs fp32
    on the seeded candidate corpus (serving-size AND fleet-size Ns)."""
    q8 = _int8_backend(set_tree)
    reference = NumpySetBackend(set_tree)
    agreement, ok = check_int8_agreement(q8, reference, node_feat=6,
                                         node_counts=(8, 64, 256))
    assert ok and agreement >= INT8_AGREEMENT_MIN


def test_int8_scales_recorded_per_tensor(set_tree):
    """Quantize-at-load contract: one recorded scale per dense tensor
    (embed + 6 per block x depth 2 = 13), all positive."""
    q8 = _int8_backend(set_tree)
    assert len(q8.quantization_scales) == 13
    assert all(s > 0 for s in q8.quantization_scales)


def test_make_set_backend_int8_gates_and_stamps(set_tree):
    try:
        backend, fell_back = make_set_backend("native-int8", set_tree)
    except ValueError as e:
        pytest.skip(f"int8 backend unavailable: {e}")
    assert not fell_back
    assert backend.name == "native-int8"
    assert backend.agreement >= INT8_AGREEMENT_MIN
    assert backend.reference is not None and backend.node_feat == 6


def test_make_set_backend_int8_refuses_low_agreement(set_tree, monkeypatch):
    _int8_backend(set_tree)  # skip when no toolchain
    import rl_scheduler_tpu.scheduler.fastpath as fastpath_mod

    monkeypatch.setattr(fastpath_mod, "check_int8_agreement",
                        lambda *a, **k: (0.5, False))
    with pytest.raises(ValueError, match="below"):
        make_set_backend("native-int8", set_tree)


def test_fastpath_verify_reruns_int8_agreement(set_tree, monkeypatch):
    """Flush-on-promote satellite: the gate RE-RUNS the agreement check
    on the (possibly new) checkpoint; a failing re-check returns
    ok=False — the rollout refuses the promote rather than silently
    serving."""
    try:
        backend, _ = make_set_backend("native-int8", set_tree)
    except ValueError as e:
        pytest.skip(f"int8 backend unavailable: {e}")
    policy = ExtenderPolicy(backend, FrozenTelemetry())
    out = policy.fastpath_verify()
    assert out["ok"] and out["agreement"] >= INT8_AGREEMENT_MIN
    import rl_scheduler_tpu.scheduler.fastpath as fastpath_mod

    monkeypatch.setattr(fastpath_mod, "check_int8_agreement",
                        lambda *a, **k: (0.4, False))
    out = policy.fastpath_verify()
    assert not out["ok"] and out["agreement"] == 0.4


def test_check_int8_agreement_fault_site():
    """The fastpath.agree chaos seam fires INSIDE the check — a caller
    that cannot verify must refuse, never default to passing."""
    plan = FaultPlan(schedule={"fastpath.agree": (1,)})
    with pytest.raises(RuntimeError):
        check_int8_agreement(None, None, 6, fault_plan=plan)
    assert plan.fired["fastpath.agree"] == 1


def test_agreement_corpus_is_deterministic():
    a = agreement_corpus(6, node_counts=(8, 64), samples=6, seed=3)
    b = agreement_corpus(6, node_counts=(8, 64), samples=6, seed=3)
    assert len(a) == 6 and [o.shape[0] for o in a] == [8, 64, 8, 64, 8, 64]
    assert all(np.array_equal(x, y) for x, y in zip(a, b))
    c = agreement_corpus(6, node_counts=(8, 64), samples=6, seed=4)
    assert not np.array_equal(a[0], c[0])


# --------------------------------------------------- build_policy / stats


def test_build_policy_refuses_levers_on_wrong_family(tmp_path):
    with pytest.raises(ValueError, match="micro-batching"):
        build_policy(backend="greedy", run_root=str(tmp_path),
                     batch_window_ms=2.0)
    with pytest.raises(ValueError, match="score cache"):
        build_policy(backend="greedy", run_root=str(tmp_path),
                     score_cache_epoch_s=15.0)


def test_fastpath_metric_lines_exposition(set_tree):
    policy = ExtenderPolicy(NumpySetBackend(set_tree), FrozenTelemetry())
    policy.score_cache = ScoreCache(epoch_s=3600.0)
    policy.batcher = MicroBatcher(policy.backend, window_s=0.002)
    policy.decide_set(_clouds(), 0.25)
    policy.decide_set(_clouds(), 0.25)
    lines = fastpath_metric_lines("rl_scheduler_extender",
                                  policy.fastpath_snapshot())
    text = "\n".join(lines)
    assert "rl_scheduler_extender_score_cache_hits_total 1" in text
    assert "rl_scheduler_extender_score_cache_misses_total 1" in text
    assert "rl_scheduler_extender_batch_requests_total 1" in text
    # Levers off -> no lines at all (byte-identical scrape).
    bare = ExtenderPolicy(NumpySetBackend(set_tree), FrozenTelemetry())
    assert fastpath_metric_lines("p", bare.fastpath_snapshot()) == []
    assert "_score_cache_" not in bare.metrics_text()


def test_pool_sum_fastpath_merges_counters():
    from rl_scheduler_tpu.scheduler.pool import sum_fastpath

    def snap(hits, misses, batches, occupancy, agreement):
        return {"stats": {"fastpath": {
            "cache": {"hits_total": hits, "misses_total": misses,
                      "invalidations_total": 1, "entries": 2},
            "batch": {"requests_total": batches * 2,
                      "batches_total": batches, "coalesced_total": 2,
                      "max_occupancy": 3, "mean_occupancy": occupancy},
            "int8": {"agreement": agreement, "scales_recorded": 13},
        }}}

    merged = sum_fastpath([snap(8, 2, 4, 2.0, 0.999),
                           snap(2, 8, 1, 1.0, 0.996)])
    assert merged["cache"]["hits_total"] == 10
    assert merged["cache"]["misses_total"] == 10
    assert merged["cache"]["hit_rate"] == 0.5
    assert merged["batch"]["batches_total"] == 5
    assert merged["batch"]["mean_occupancy"] == pytest.approx(1.8)
    assert merged["int8"]["agreement"] == 0.996  # pool shows the WORST
    assert sum_fastpath([{"stats": {}}]) is None


# ------------------------------------------------------------------- bench


def test_bench_soak_emits_retries_unconditionally(set_tree):
    """Round-13 small fix: the soak's JSON line carries the retry
    counter with or without --promote-at, so lever A/B lines are
    field-comparable with rollout-drill lines."""
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                           / "loadgen"))
    import extender_bench

    from rl_scheduler_tpu.scheduler.extender import make_server

    policy = ExtenderPolicy(NumpySetBackend(set_tree), FrozenTelemetry())
    server = make_server(policy, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        port = server.server_address[1]
        out = extender_bench.main(["--port", str(port), "--duration",
                                   "0.4", "--threads", "2", "--nodes",
                                   "4", "--warmup", "2"])
        assert out["retries"] == 0 and "phases" not in out
        out = extender_bench.main(["--port", str(port), "--requests",
                                   "4", "--threads", "2", "--nodes",
                                   "4", "--warmup", "1"])
        assert out["retries"] == 0
    finally:
        server.shutdown()


def test_levers_matrix_smoke(tmp_path):
    """The --levers matrix: interleaved per-lever pools, one ledger line
    per lever with the `lever` shape key, cache lever actually hitting."""
    import os

    if not hasattr(os, "fork"):
        pytest.skip("graftserve pools require fork")
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                           / "loadgen"))
    import extender_bench

    history = tmp_path / "hist.jsonl"
    args = types.SimpleNamespace(
        levers="off,cache", nodes=8, threads=2, workers=1, rounds=1,
        duration=1.0, batch_window_ms=1.5, cache_epoch_s=3600.0,
        history=str(history))
    lines = extender_bench.run_levers_matrix(args)
    assert [ln["lever"] for ln in lines] == ["off", "cache"]
    for line in lines:
        assert line["mode"] == "levers"
        assert line["failures"] == 0 and line["retries"] == 0
        assert line["req_per_sec"] > 0
    cache_line = lines[1]
    assert cache_line["fastpath"]["cache"]["hits_total"] > 0
    ledger = [json.loads(ln) for ln in
              history.read_text().splitlines() if ln.strip()]
    assert [ln["lever"] for ln in ledger] == ["off", "cache"]
    # check-history gates per lever: a fast cache row is never the
    # baseline an off row is judged against.
    from tools.decisionview import check_history

    assert check_history(ledger) == []
