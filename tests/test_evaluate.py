"""Evaluation + comparison harness (reference final_evaluation / compare parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.agent.evaluate import (
    BASELINE_POLICIES,
    EvalReport,
    baseline_episode_cost,
    evaluate,
    greedy_policy_fn,
    quick_eval,
    run_episodes,
)
from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.models import ActorCritic


@pytest.fixture(scope="module")
def env_params():
    return env_core.make_params(EnvConfig())


def test_baseline_cost_matches_manual_computation(env_params):
    """Cost-greedy baseline cost equals a hand-rolled numpy computation."""
    costs = np.asarray(env_params.costs)[:99]
    lats = np.asarray(env_params.latencies)[:99]
    acts = np.where(costs[:, 0] <= costs[:, 1], 0, 1)
    expected = (
        100.0
        * (0.6 * costs[np.arange(99), acts] + 0.4 * lats[np.arange(99), acts])
    ).sum()
    assert baseline_episode_cost(env_params, "greedy") == pytest.approx(
        expected, rel=1e-5
    )


def test_round_robin_cost_differs_from_greedy(env_params):
    rr = baseline_episode_cost(env_params, "round_robin")
    g = baseline_episode_cost(env_params, "greedy")
    assert rr != pytest.approx(g, rel=1e-3)


def test_run_episodes_shapes_and_determinism(env_params):
    policy = BASELINE_POLICIES["greedy"]
    r1, c1, l1 = run_episodes(env_params, policy, num_episodes=8, seed=0)
    r2, c2, l2 = run_episodes(env_params, policy, num_episodes=8, seed=0)
    assert r1.shape == (8,)
    assert c1.shape == (8, env_core.NUM_ACTIONS)
    np.testing.assert_allclose(np.asarray(r1), np.asarray(r2))
    # greedy baseline is data-deterministic: all episodes identical reward
    assert float(r1.std()) == pytest.approx(0.0, abs=1e-2)
    # every step takes exactly one action
    assert int(c1.sum()) == 8 * int(env_params.max_steps)
    assert int(l1[0]) == int(env_params.max_steps)


def test_evaluate_greedy_baseline_zero_improvement(env_params):
    """Evaluating the cost-greedy policy must report ~0% improvement over the
    cost-greedy baseline (self-comparison sanity)."""
    report = evaluate(env_params, BASELINE_POLICIES["greedy"], num_episodes=4)
    assert isinstance(report, EvalReport)
    assert report.improvement_pct == pytest.approx(0.0, abs=0.1)
    assert sum(report.choice_fractions) == pytest.approx(1.0)
    # corrected reward sign: reward = -cost
    assert report.avg_episode_reward == pytest.approx(-report.avg_episode_cost, rel=1e-5)


def test_evaluate_legacy_sign_cost_still_positive():
    params = env_core.make_params(EnvConfig(legacy_reward_sign=True))
    report = evaluate(params, BASELINE_POLICIES["greedy"], num_episodes=4)
    assert report.avg_episode_cost > 0
    assert report.avg_episode_reward == pytest.approx(report.avg_episode_cost, rel=1e-5)


def test_evaluate_with_fault_injection_uses_matched_baseline():
    """With fault_prob>0 the baseline must come from the same faulted env, so
    greedy-vs-greedy improvement stays near zero (not wildly skewed)."""
    params = env_core.make_params(EnvConfig(fault_prob=0.2))
    report = evaluate(params, BASELINE_POLICIES["greedy"], num_episodes=16)
    assert abs(report.improvement_pct) < 5.0


def test_evaluate_untrained_policy_and_quick_eval(env_params):
    net = ActorCritic(num_actions=env_core.NUM_ACTIONS, hidden=(32, 32))
    params = net.init(
        jax.random.PRNGKey(0), jnp.zeros((1, env_core.OBS_DIM), jnp.float32)
    )
    report = evaluate(env_params, greedy_policy_fn(net, params), num_episodes=4)
    assert np.isfinite(report.avg_episode_cost)
    lines = []
    total = quick_eval(env_params, net, params, num_steps=5, print_fn=lines.append)
    assert len(lines) == 6  # 5 steps + total line
    assert "Total reward" in lines[-1]
    assert np.isfinite(total)


def test_report_summary_contains_key_fields(env_params):
    report = evaluate(env_params, BASELINE_POLICIES["greedy"], num_episodes=2)
    text = report.summary()
    assert "FINAL EVALUATION SUMMARY" in text
    assert "Improvement vs baseline" in text
    assert "AWS" in text and "Azure" in text
    js = report.to_json()
    assert js["num_episodes"] == 2


def test_compare_harness_end_to_end(env_params, tmp_path):
    """Short compare run: table formats, results serialize, PPO entry present."""
    from rl_scheduler_tpu.agent.compare import compare, format_table, save_plot

    results, _ = compare(
        EnvConfig(), preset="quick", iterations=1, episodes=2, log_fn=lambda *_: None
    )
    for k in ("ppo", "cost_greedy", "round_robin", "random", "reward_curve"):
        assert k in results
    table = format_table(results)
    assert "PPO (trained, greedy)" in table and "best" in table
    assert len(results["reward_curve"]) == 1
    # plot is optional (matplotlib may be absent); must not raise either way
    save_plot(results, tmp_path / "plot.png")


def test_evaluate_dqn_checkpoint_end_to_end(tmp_path):
    """A multi-cloud DQN run's checkpoint is discovered and evaluated with
    a greedy-Q policy (the algo meta key selects QNetwork)."""
    from rl_scheduler_tpu.agent import evaluate as eval_cli
    from rl_scheduler_tpu.agent import train_dqn as dqn_cli

    run_dir = dqn_cli.main([
        "--env", "multi_cloud", "--preset", "config1", "--iterations", "8",
        "--run-root", str(tmp_path), "--run-name", "dqn_eval_test",
        "--checkpoint-every", "8", "--hidden", "16,16",
    ])
    report = eval_cli.main([
        "--run", str(run_dir), "--episodes", "4",
        "--results-dir", str(tmp_path / "results"),
    ])
    assert np.isfinite(report.avg_episode_cost)
    assert (tmp_path / "results" / "final_evaluation_summary.txt").exists()


def test_evaluate_greedy_q_policy_via_qnetwork(env_params):
    from rl_scheduler_tpu.models import QNetwork

    net = QNetwork(num_actions=env_core.NUM_ACTIONS, hidden=(16, 16))
    params = net.init(
        jax.random.PRNGKey(0), jnp.zeros((1, env_core.OBS_DIM), jnp.float32)
    )
    report = evaluate(env_params, greedy_policy_fn(net, params), num_episodes=4)
    assert np.isfinite(report.avg_episode_cost)


def test_structured_baselines_policies():
    """cheapest-node/load-spread argmin the right feature column per env
    family; random stays within the node range."""
    from rl_scheduler_tpu.env.baselines import structured_baselines

    obs = jnp.zeros((3, 4, 6)).at[:, :, 0].set(
        jnp.asarray([[0.4, 0.1, 0.9, 0.5]] * 3)
    ).at[:, :, 2].set(jnp.asarray([[0.9, 0.8, 0.1, 0.7]] * 3))
    set_pols = structured_baselines("cluster_set")
    key = jax.random.PRNGKey(0)
    assert list(np.asarray(set_pols["cheapest_node"](obs, key))) == [1, 1, 1]
    assert list(np.asarray(set_pols["load_spread"](obs, key))) == [2, 2, 2]
    r = np.asarray(set_pols["random"](obs, key))
    assert r.shape == (3,) and (0 <= r).all() and (r < 4).all()

    # graph family: cpu lives in column 1
    gobs = jnp.zeros((2, 4, 7)).at[:, :, 1].set(
        jnp.asarray([[0.9, 0.2, 0.8, 0.6]] * 2)
    )
    graph_pols = structured_baselines("cluster_graph")
    assert list(np.asarray(graph_pols["load_spread"](gobs, key))) == [1, 1]


def test_structured_evaluate_cluster_set(tmp_path):
    """End-to-end: train a tiny cluster_set run, evaluate it with the CLI —
    per-baseline rewards reported, artifacts written (the reproducible
    form of the status-table convergence comparisons)."""
    from rl_scheduler_tpu.agent import evaluate as eval_cli
    from rl_scheduler_tpu.agent import train_ppo as ppo_cli

    run_dir = ppo_cli.main([
        "--env", "cluster_set", "--preset", "quick", "--iterations", "2",
        "--num-envs", "8", "--rollout-steps", "20", "--minibatch-size", "40",
        "--num-epochs", "2", "--run-root", str(tmp_path),
        "--run-name", "set_eval_test", "--checkpoint-every", "2",
    ])
    report = eval_cli.main([
        "--run", str(run_dir), "--episodes", "8",
        "--results-dir", str(tmp_path / "results"),
    ])
    assert report.env == "cluster_set"
    assert np.isfinite(report.avg_episode_reward)
    assert set(report.baseline_rewards) == {
        "random", "cheapest_node", "load_spread"
    }
    assert all(np.isfinite(v) for v in report.baseline_rewards.values())
    assert np.isclose(sum(report.cloud_fractions), 1.0)
    out = (tmp_path / "results" / "structured_evaluation_cluster_set.txt")
    assert "Improvement vs best baseline" in out.read_text()


def test_structured_evaluate_cluster_graph_from_fused_run(tmp_path):
    """Graph family: a --fused-gnn-trained checkpoint (same tree) evaluates
    through the flax GNN with the graph-family baselines."""
    from rl_scheduler_tpu.agent import evaluate as eval_cli
    from rl_scheduler_tpu.agent import train_ppo as ppo_cli

    run_dir = ppo_cli.main([
        "--env", "cluster_graph", "--preset", "quick", "--fused-gnn",
        "--iterations", "2", "--num-envs", "8", "--rollout-steps", "20",
        "--minibatch-size", "40", "--num-epochs", "2",
        "--run-root", str(tmp_path), "--run-name", "graph_eval_test",
        "--checkpoint-every", "2",
    ])
    report = eval_cli.main([
        "--run", str(run_dir), "--episodes", "8",
        "--results-dir", str(tmp_path / "results"),
    ])
    assert report.env == "cluster_graph"
    assert np.isfinite(report.avg_episode_reward)
    assert all(np.isfinite(v) for v in report.baseline_rewards.values())
