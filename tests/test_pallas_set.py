"""Fused Pallas set-transformer kernels: parity with SetTransformerPolicy.

These kernels are EXPERIMENTAL (see the module docstring and the config-4
note in docs/status.md): per-minibatch forward+backward measured ~55x
faster than the XLA path in isolation on TPU, but inside the full fused
PPO update the Pallas custom-call overhead in while-loop context makes
them a net loss, so the trainer does not default to them. The parity
contract is still enforced here (interpret mode on CPU).

Note on tolerances: the flat-lane formulation computes attention scores
with a different f32 summation order than flax's einsum; softmax amplifies
that last-bit noise, so comparisons use scale-relative bounds and gradient
cosine similarity rather than elementwise exactness (both programs sit at
comparable distance from the f64 ground truth).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.env import cluster_set
from rl_scheduler_tpu.models import SetTransformerPolicy
from rl_scheduler_tpu.ops.pallas_set import FusedSetPolicy, make_fused_set_apply

N, F, D = 8, cluster_set.NODE_FEAT, 64


@pytest.fixture(scope="module")
def setup():
    ref = SetTransformerPolicy(dim=D, depth=2, num_heads=1)
    obs = jax.random.normal(jax.random.PRNGKey(0), (24, N, F)) * 0.3
    params = ref.init(jax.random.PRNGKey(1), obs)
    return ref, params, obs


def test_forward_parity(setup):
    ref, params, obs = setup
    lr, vr = ref.apply(params, obs)
    fused = make_fused_set_apply(N, F, D, 2, block_b=8)
    lf, vf = fused(params, obs)
    scale_l = float(jnp.abs(lr).max()) + 1e-6
    scale_v = float(jnp.abs(vr).max()) + 1e-6
    assert float(jnp.abs(lf - lr).max()) / scale_l < 2e-3
    assert float(jnp.abs(vf - vr).max()) / scale_v < 2e-2


def test_forward_unbatched_and_padding(setup):
    ref, params, obs = setup
    fused = make_fused_set_apply(N, F, D, 2, block_b=16)
    # 24 % 16 != 0 -> padded internally; unbatched squeezes
    lf, vf = fused(params, obs)
    assert lf.shape == (24, N) and vf.shape == (24,)
    l1, v1 = fused(params, obs[0])
    assert l1.shape == (N,) and v1.shape == ()
    lr, vr = ref.apply(params, obs[0])
    np.testing.assert_allclose(np.asarray(l1), np.asarray(lr), atol=2e-3)


def test_gradient_direction_parity(setup):
    """Per-leaf gradient cosine similarity vs the reference autodiff.
    (Elementwise equality is not achievable: f32 reassociation through
    softmax; the key biases are skipped — their true gradient is zero by
    softmax shift-invariance, so both sides are pure noise there.)"""
    ref, params, obs = setup
    fused = make_fused_set_apply(N, F, D, 2, block_b=8)
    wl = jax.random.normal(jax.random.PRNGKey(2), (24, N))
    wv = jax.random.normal(jax.random.PRNGKey(3), (24,))

    def loss(apply_fn):
        def f(p):
            logits, value = apply_fn(p, obs)
            return jnp.sum(logits * wl) + jnp.sum(value * wv)

        return f

    g_ref = jax.grad(loss(ref.apply))(params)
    g_f = jax.grad(loss(fused))(params)
    for (path, a), b in zip(jax.tree_util.tree_leaves_with_path(g_ref),
                            jax.tree.leaves(g_f)):
        name = jax.tree_util.keystr(path)
        if "['key']['bias']" in name:
            continue  # true gradient is zero: softmax shift-invariance
        a = np.asarray(a).ravel(); b = np.asarray(b).ravel()
        denom = np.linalg.norm(a) * np.linalg.norm(b)
        if denom < 1e-10:
            continue
        cos = float(a @ b) / denom
        assert cos > 0.999, f"{name}: cosine {cos}"


def test_depth_one_parity():
    ref = SetTransformerPolicy(dim=D, depth=1, num_heads=1)
    obs = jax.random.normal(jax.random.PRNGKey(4), (16, N, F)) * 0.3
    params = ref.init(jax.random.PRNGKey(5), obs)
    fused = make_fused_set_apply(N, F, D, 1, block_b=8)
    lr, vr = ref.apply(params, obs)
    lf, vf = fused(params, obs)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(lr), atol=2e-3)
    np.testing.assert_allclose(np.asarray(vf), np.asarray(vr), atol=2e-2)


def test_fused_policy_dispatch_and_checkpoint_tree(setup):
    """The policy object dispatches small batches to the reference module
    (identical function) and exposes the same checkpoint tree."""
    ref, params, obs = setup
    net = FusedSetPolicy(num_nodes=N, feat=F, dim=D, depth=2, block_b=8,
                         min_fused_batch=16)
    # below threshold: exact flax path
    l_small, v_small = net.apply(params, obs[:8])
    lr, vr = ref.apply(params, obs[:8])
    np.testing.assert_array_equal(np.asarray(l_small), np.asarray(lr))
    # above threshold: fused path, same function within tolerance
    l_big, _ = net.apply(params, obs)
    lrb, _ = ref.apply(params, obs)
    np.testing.assert_allclose(np.asarray(l_big), np.asarray(lrb), atol=2e-3)
    assert (jax.tree_util.tree_structure(net.init(jax.random.PRNGKey(9), obs))
            == jax.tree_util.tree_structure(params))


def test_fused_policy_trains_ppo():
    from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, make_ppo_bundle
    from rl_scheduler_tpu.env.bundle import cluster_set_bundle

    net = FusedSetPolicy(num_nodes=N, feat=F, dim=16, depth=1, block_b=8,
                         min_fused_batch=16)
    cfg = PPOTrainConfig(num_envs=8, rollout_steps=8, minibatch_size=32,
                         num_epochs=2, lr=1e-3)
    init_fn, update_fn, _ = make_ppo_bundle(cluster_set_bundle(), cfg, net=net)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    runner, metrics = jax.jit(update_fn)(runner)
    for k in ("policy_loss", "value_loss", "entropy"):
        assert np.isfinite(float(metrics[k])), k


def test_fused_apply_rejects_multihead_tree():
    """ADVICE r2: a num_heads>1 checkpoint must fail with the constraint
    named, not as a rank error deep inside the Pallas trace."""
    import pytest

    from rl_scheduler_tpu.models import SetTransformerPolicy
    from rl_scheduler_tpu.ops.pallas_set import make_fused_set_apply

    multi = SetTransformerPolicy(dim=64, depth=2, num_heads=4)
    params = multi.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 6)))
    apply = make_fused_set_apply(interpret=True)
    with pytest.raises(ValueError, match="num_heads=1"):
        apply(params, jnp.zeros((96, 8, 6)))
