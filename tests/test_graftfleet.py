"""graftfleet (scheduler/fleet.py): the multi-host fleet control plane.

What is pinned here, and why it is the contract:

- **Discovery** — ``parse_pools`` formats, ``StaticResolver``, and the
  ``EndpointsResolver`` over the checked-in kubernetes Endpoints
  fixture (off-network by design): named-port selection, first-port
  fallback, the no-ready-addresses refusal, and ``refresh()`` picking
  up a rewritten document.
- **The merge** — fleet ``/stats``/``/metrics`` reuse the pool's OWN
  merge functions over pool pseudo-snapshots (``pool_stats_snapshot``),
  so merged-at-the-fleet == union-of-all-workers is pinned at 3 pools
  x 2 workers of REAL policy snapshots, and a version-skewed pool
  missing the ``raw`` section (or a phase) degrades under the
  optional-phase rule instead of poisoning the merge.
- **Fleet promote** — canary pool first, HOLD, the rest one at a time;
  a canary refusal ends ``refused`` with nothing rolled; ANY pool
  rollback or a pool dying mid-roll (the ``fleet.promote`` chaos site)
  aborts AND reverts every already-rolled pool; the ledger is
  graftstudy-discipline (byte-prefix appends, spec-fingerprint header,
  SIGKILL-anywhere resume that never re-runs a recorded stage) and the
  lifecycle counters derive from it, which is why ``/stats/reset``
  fan-out can never rewind them.
- **The drill** (`make fleet-drill`) — three real 2-worker pools under
  continuous multi-target bench traffic: a fleet promote canaries and
  rolls with zero failed requests in every phase and per pool, an
  injected regression aborts-and-reverts, a SIGKILLed fleet-promote
  CLI resumes its ledger byte-prefix-exact, and ``fleet_snapshot``
  unions the three trace dirs into one snapshot root that compiles and
  round-trips through the real env.

``run_fleet`` (the serve loop) installs SIGTERM/SIGINT handlers, which
only works on the main thread — the HTTP plane is exercised through
``_make_fleet_server`` instead, same handler, no signals.
"""

from __future__ import annotations

import importlib.util
import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import pytest

from rl_scheduler_tpu.loopback import (
    compile_trace,
    snapshot_trace,
    trace_scenario_name,
    verify_roundtrip,
)
from rl_scheduler_tpu.scheduler.extender import ExtenderPolicy, LatencyStats
from rl_scheduler_tpu.scheduler.fleet import (
    FLEET_LEDGER_NAME,
    EndpointsResolver,
    FleetController,
    FleetLedger,
    FleetLedgerMismatch,
    FleetSpec,
    PoolRef,
    StaticResolver,
    aggregate_fleet_metrics,
    aggregate_fleet_stats,
    fault_plan_from_env,
    fleet_snapshot,
    parse_pools,
    pool_stats_snapshot,
)
from rl_scheduler_tpu.scheduler.fleet import main as fleet_main
from rl_scheduler_tpu.scheduler.policy_backend import GreedyBackend
from rl_scheduler_tpu.scheduler.pool import (
    METRIC_PREFIX,
    PoolShared,
    ServingPool,
    aggregate_stats,
    worker_snapshot,
)
from rl_scheduler_tpu.scheduler.telemetry import RandomCpu, TableTelemetry
from rl_scheduler_tpu.scheduler.tracelog import (
    TraceLog,
    decision_record,
    iter_trace,
)
from rl_scheduler_tpu.utils.faults import FaultPlan
from rl_scheduler_tpu.utils.retry import RetryPolicy

REPO_ROOT = Path(__file__).resolve().parents[1]
FIXTURES = Path(__file__).parent / "fixtures" / "fleet"

FAST_RESTARTS = RetryPolicy(max_attempts=5, base_delay_s=0.05,
                            max_delay_s=0.2, jitter=0.0)

needs_fork = pytest.mark.skipif(
    not hasattr(os, "fork"), reason="graftserve pools require fork"
)


# ------------------------------------------------------------- helpers


def _post(port, path, payload, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


def _get(port, path, timeout=10):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=timeout) as resp:
        body = resp.read()
    if resp.headers.get("Content-Type", "").startswith("application/json"):
        return json.loads(body)
    return body.decode()


def _post_code(port, path, payload, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get_code(port, path, timeout=10):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                    timeout=timeout) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _filter_args(i=0):
    return {"nodenames": [f"aws-w{i}", f"azure-w{i}"], "pod": {}}


def _greedy_factory(worker_id, shared):
    telemetry = TableTelemetry.from_table(
        cpu_source=RandomCpu(seed=0), counter=shared.table_counter
    )
    return ExtenderPolicy(GreedyBackend(), telemetry)


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "extender_bench", REPO_ROOT / "loadgen" / "extender_bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _FakePool:
    """A pool control plane in miniature: just the four endpoints the
    fleet controller touches, with a scripted promote behavior —
    ``land`` (accept and serve the candidate), ``rollback`` (accept,
    then stay on the incumbent with ``last_error`` set: the pool's own
    canary gate rolled it back), ``refuse`` (422 at verification).
    Real network, real HTTP, no fork — the promote ENGINE's unit rig."""

    def __init__(self, behavior="land", decisions=None,
                 latencies=(0.0002, 0.002), alive=2):
        self.behavior = behavior
        self.checkpoint = "/ckpt/incumbent"
        self.generation = 1
        self.last_error = None
        self.promote_posts: list = []
        self.resets = 0
        self.decisions = dict(decisions or {"aws": 3, "gcp": 2})
        stats = LatencyStats()
        for v in latencies:
            stats.record(v)
        cum, total, count = stats.histogram()
        self.raw_histogram = {"cumulative": cum, "sum": total,
                              "count": count}
        self.alive = alive
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (stdlib API)
                if self.path == "/rollout":
                    self._send(200, {
                        "active": False,
                        "generation": fake.generation,
                        "checkpoint": fake.checkpoint,
                        "last_error": fake.last_error,
                    })
                elif self.path == "/stats":
                    self._send(200, fake.stats_body())
                else:
                    self._send(404, {"error": self.path})

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
                if self.path == "/promote":
                    fake.promote_posts.append(payload.get("checkpoint"))
                    if fake.behavior == "refuse":
                        self._send(422, {"error": "manifest verification "
                                                  "refused the candidate"})
                        return
                    target = fake.generation + 1
                    if fake.behavior == "land":
                        fake.checkpoint = payload.get("checkpoint")
                        fake.generation = target
                    else:  # rollback: the pool's own gate reverts it
                        fake.last_error = ("canary probes failed; "
                                           "rolled back")
                    self._send(202, {"status": "rolling",
                                     "target_generation": target})
                elif self.path == "/stats/reset":
                    fake.resets += 1
                    self._send(200, {"status": "reset"})
                else:
                    self._send(404, {"error": self.path})

            def log_message(self, *args):
                pass

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        threading.Thread(
            target=lambda: self.server.serve_forever(poll_interval=0.05),
            daemon=True).start()

    def stats_body(self):
        return {
            "backend": "cpu",
            "family": "set",
            "decisions": dict(self.decisions),
            "choice_fractions": {},
            "latency": {"count": self.raw_histogram["count"]},
            "breakers": {},
            "pool": {"workers": 2, "alive": self.alive,
                     "generation": self.generation,
                     "rollout": {"active": False}},
            "raw": {"histogram": dict(self.raw_histogram), "phases": {}},
        }

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture()
def fakes():
    created: list = []

    def make(count=3, behaviors=None):
        for i in range(count):
            behavior = behaviors[i] if behaviors else "land"
            created.append(_FakePool(behavior=behavior))
        return created

    yield make
    for fake in created:
        fake.close()


def _controller(tmp_path, pools, **kwargs):
    spec = ",".join(f"127.0.0.1:{f.port}" for f in pools)
    kwargs.setdefault("rollout_timeout_s", 10.0)
    return FleetController(StaticResolver(spec), tmp_path / "fleet",
                           **kwargs), spec


# ----------------------------------------------------------- discovery


def test_parse_pools_formats_and_errors():
    refs = parse_pools(" 10.0.0.5:8788, host-b:9000 ,")
    assert refs == [PoolRef("10.0.0.5:8788", "10.0.0.5", 8788),
                    PoolRef("host-b:9000", "host-b", 9000)]
    assert refs[0].url == "http://10.0.0.5:8788"
    assert StaticResolver("a:1,b:2").resolve() == parse_pools("a:1,b:2")
    with pytest.raises(ValueError, match="host:port"):
        parse_pools("no-port-here")
    with pytest.raises(ValueError, match="integer"):
        parse_pools("host:banana")
    with pytest.raises(ValueError, match="at least one"):
        parse_pools(" , ")


def test_endpoints_resolver_reads_the_k8s_fixture():
    refs = EndpointsResolver(FIXTURES / "endpoints.json").resolve()
    # Both subsets contribute; the named "control" port wins over http.
    assert [(r.host, r.port) for r in refs] == [
        ("10.0.0.5", 8788), ("10.0.0.6", 8788), ("10.0.1.9", 9788)]
    assert refs[0].name == "10.0.0.5:8788"
    # An unmatched port name falls back to the subset's first port.
    refs = EndpointsResolver(FIXTURES / "endpoints.json",
                             port_name="nope").resolve()
    assert [(r.host, r.port) for r in refs] == [
        ("10.0.0.5", 8787), ("10.0.0.6", 8787), ("10.0.1.9", 9788)]


def test_endpoints_resolver_refuses_an_empty_document(tmp_path):
    doc = tmp_path / "endpoints.json"
    doc.write_text(json.dumps({"subsets": []}))
    with pytest.raises(ValueError, match="no ready addresses"):
        EndpointsResolver(doc).resolve()


def test_controller_refresh_picks_up_endpoints_churn(tmp_path):
    doc = tmp_path / "endpoints.json"
    shutil.copy(FIXTURES / "endpoints.json", doc)
    controller = FleetController(EndpointsResolver(doc),
                                 tmp_path / "fleet")
    assert len(controller.pools) == 3
    # A pod churns away: the next refresh() sees the smaller set (the
    # resolver re-reads the document per resolve — no restart needed).
    churned = json.loads(doc.read_text())
    churned["subsets"] = churned["subsets"][:1]
    doc.write_text(json.dumps(churned))
    assert [r.name for r in controller.refresh()] == [
        "10.0.0.5:8788", "10.0.0.6:8788"]


# ----------------------------------------------------------- the ledger


def test_fleet_spec_validation_and_fingerprint():
    spec = FleetSpec(pools=("a:1", "b:2"), canary="a:1")
    assert spec.fingerprint() == FleetSpec(pools=("a:1", "b:2"),
                                           canary="a:1").fingerprint()
    assert spec.fingerprint() != FleetSpec(pools=("a:1", "b:2"),
                                           canary="b:2").fingerprint()
    with pytest.raises(ValueError, match="at least one pool"):
        FleetSpec(pools=(), canary="a:1")
    with pytest.raises(ValueError, match="not one of the fleet's pools"):
        FleetSpec(pools=("a:1",), canary="c:3")


def test_ledger_header_byte_prefix_and_topology_mismatch(tmp_path):
    spec = FleetSpec(pools=("a:1", "b:2"), canary="a:1")
    ledger = FleetLedger(tmp_path, spec)
    header = json.loads(ledger.path.read_text().splitlines()[0])
    assert header["kind"] == "header"
    assert header["spec_sha"] == spec.fingerprint()
    ledger.append({"kind": "begin", "promote": "fp0001",
                   "checkpoint": "/c", "incumbents": {}})
    before = ledger.path.read_bytes()
    ledger.append({"kind": "stage", "promote": "fp0001", "pool": "a:1",
                   "role": "canary", "status": "ok", "out": {}})
    assert ledger.path.read_bytes().startswith(before)
    # Same topology resumes; a changed one refuses the fleet dir.
    again = FleetLedger(tmp_path, spec)
    assert len(again.records()) == 2
    with pytest.raises(FleetLedgerMismatch, match="changed fleet"):
        FleetLedger(tmp_path, FleetSpec(pools=("a:1", "b:2"),
                                        canary="b:2"))


def test_ledger_counters_open_promote_and_stages(tmp_path):
    spec = FleetSpec(pools=("a:1", "b:2"), canary="a:1")
    ledger = FleetLedger(tmp_path, spec)
    assert ledger.counters() == {
        "generation": 0, "promotions_total": 0, "rollbacks_total": 0,
        "aborts_total": 0, "refusals_total": 0}
    assert ledger.open_promote() is None
    ledger.append({"kind": "begin", "promote": "fp0001",
                   "checkpoint": "/v2", "incumbents": {}})
    assert ledger.open_promote()["promote"] == "fp0001"
    ledger.append({"kind": "stage", "promote": "fp0001", "pool": "a:1",
                   "role": "canary", "status": "ok", "out": {}})
    ledger.append({"kind": "stage", "promote": "fp0001", "pool": "b:2",
                   "role": "roll", "status": "rolled_back", "out": {}})
    ledger.append({"kind": "stage", "promote": "fp0001", "pool": "a:1",
                   "role": "revert", "status": "ok", "out": {}})
    ledger.append({"kind": "end", "promote": "fp0001",
                   "status": "aborted"})
    ledger.append({"kind": "begin", "promote": "fp0002",
                   "checkpoint": "/v2", "incumbents": {}})
    ledger.append({"kind": "end", "promote": "fp0002", "status": "ok",
                   "generation": 1})
    assert ledger.open_promote() is None
    assert ledger.begun_total() == 2
    assert ledger.counters() == {
        "generation": 1, "promotions_total": 1, "rollbacks_total": 1,
        "aborts_total": 1, "refusals_total": 0}
    stages = ledger.promote_stages("fp0001")
    assert set(stages) == {("a:1", "canary"), ("b:2", "roll"),
                           ("a:1", "revert")}
    assert stages[("b:2", "roll")]["status"] == "rolled_back"


# ----------------------------------------------------- promote engine


def test_fleet_promote_all_pools_land(tmp_path, fakes):
    pools = fakes(3)
    controller, _ = _controller(tmp_path, pools)
    out = controller.promote("/ckpt/v2")
    assert out["status"] == "ok"
    assert out["generation"] == 1
    # Canary first, then the rest in topology order, one POST each.
    assert [f.checkpoint for f in pools] == ["/ckpt/v2"] * 3
    assert [len(f.promote_posts) for f in pools] == [1, 1, 1]
    counters = controller.ledger.counters()
    assert counters["promotions_total"] == 1
    assert counters["generation"] == 1
    metrics = controller.metrics()
    assert f"{METRIC_PREFIX}_fleet_generation 1" in metrics
    assert f"{METRIC_PREFIX}_fleet_promotions_total 1" in metrics
    # Idempotent re-run: every pool already serves the candidate, so
    # nothing POSTs again (the pre-check records already_serving).
    out = controller.promote("/ckpt/v2")
    assert out["status"] == "ok"
    assert [len(f.promote_posts) for f in pools] == [1, 1, 1]


def test_fleet_promote_canary_refusal_rolls_nothing(tmp_path, fakes):
    pools = fakes(3, behaviors=["refuse", "land", "land"])
    controller, _ = _controller(tmp_path, pools)
    out = controller.promote("/ckpt/v2")
    assert out["status"] == "refused"
    assert "refused the promote" in out["reason"]
    # Nothing rolled: the non-canary pools never saw a POST and every
    # pool still serves its incumbent — refusal is an outcome, not an
    # abort.
    assert [len(f.promote_posts) for f in pools] == [1, 0, 0]
    assert [f.checkpoint for f in pools] == ["/ckpt/incumbent"] * 3
    counters = controller.ledger.counters()
    assert counters == {"generation": 0, "promotions_total": 0,
                        "rollbacks_total": 0, "aborts_total": 0,
                        "refusals_total": 1}
    assert f"{METRIC_PREFIX}_fleet_refusals_total 1" \
        in controller.metrics()


def test_fleet_promote_pool_rollback_aborts_and_reverts(tmp_path, fakes):
    pools = fakes(3, behaviors=["land", "rollback", "land"])
    controller, _ = _controller(tmp_path, pools)
    out = controller.promote("/ckpt/v2")
    assert out["status"] == "aborted"
    assert out["pool"] == f"127.0.0.1:{pools[1].port}"
    assert "rolled back" in out["reason"]
    # The canary pool had landed the candidate — the abort reverted it
    # to its incumbent; the pool AFTER the failure never rolled at all.
    assert pools[0].checkpoint == "/ckpt/incumbent"
    assert pools[0].promote_posts == ["/ckpt/v2", "/ckpt/incumbent"]
    assert pools[2].promote_posts == []
    assert out["reverted"] == {f"127.0.0.1:{pools[0].port}": "ok"}
    counters = controller.ledger.counters()
    assert counters == {"generation": 0, "promotions_total": 0,
                        "rollbacks_total": 1, "aborts_total": 1,
                        "refusals_total": 0}


def test_fleet_promote_fault_pool_dies_mid_roll(tmp_path, fakes):
    """The ``fleet.promote`` chaos site: the THIRD pool-promote attempt
    (pool C, after the canary and pool B already rolled) raises a
    connection-level error before the POST — the fleet promote must
    record ``aborted`` and revert B then the canary, in reverse order,
    leaving every pool on its incumbent."""
    pools = fakes(3)
    plan = FaultPlan(schedule={"fleet.promote": (3,)})
    controller, _ = _controller(tmp_path, pools, fault_plan=plan)
    out = controller.promote("/ckpt/v2")
    assert plan.fired["fleet.promote"] == 1
    assert out["status"] == "aborted"
    assert out["pool"] == f"127.0.0.1:{pools[2].port}"
    assert "unreachable mid-roll" in out["reason"]
    assert pools[2].promote_posts == []  # died before the POST
    # Reverts ran in reverse roll order (the fault site counts calls
    # 4 and 5 without firing — the revert path stays attackable).
    assert [f.checkpoint for f in pools] == ["/ckpt/incumbent"] * 3
    assert plan.calls["fleet.promote"] == 5
    counters = controller.ledger.counters()
    assert counters["aborts_total"] == 1
    assert counters["rollbacks_total"] == 0
    assert f"{METRIC_PREFIX}_fleet_aborts_total 1" in controller.metrics()


def test_fleet_promote_resume_skips_recorded_stages(tmp_path, fakes):
    """A killed run's ledger is the resume plan: the recorded canary-ok
    stage is never re-POSTed, the remaining pools roll, and the resumed
    ledger extends the prior bytes verbatim."""
    pools = fakes(3)
    controller, _ = _controller(tmp_path, pools)
    canary_name = f"127.0.0.1:{pools[0].port}"
    incumbents = {f"127.0.0.1:{f.port}": {"generation": 1,
                                          "checkpoint": f.checkpoint}
                  for f in pools}
    controller.ledger.append({"kind": "begin", "promote": "fp0001",
                              "checkpoint": "/ckpt/v2",
                              "incumbents": incumbents})
    controller.ledger.append({"kind": "stage", "promote": "fp0001",
                              "pool": canary_name, "role": "canary",
                              "status": "ok", "out": {"generation": 2}})
    before = controller.ledger.path.read_bytes()
    out = controller.promote("/ckpt/v2")
    assert out["status"] == "ok" and out["promote"] == "fp0001"
    assert pools[0].promote_posts == []  # the recorded stage skipped
    assert [len(f.promote_posts) for f in pools[1:]] == [1, 1]
    assert controller.ledger.path.read_bytes().startswith(before)


def test_fleet_promote_refuses_to_interleave_checkpoints(tmp_path, fakes):
    pools = fakes(2)
    controller, _ = _controller(tmp_path, pools)
    controller.ledger.append({"kind": "begin", "promote": "fp0001",
                              "checkpoint": "/ckpt/v2", "incumbents": {}})
    with pytest.raises(RuntimeError, match="mid-flight"):
        controller.promote("/ckpt/OTHER")


# ------------------------------------------- scrape faults and health


def test_fleet_scrape_fault_degrades_health_without_failing_merge(
        tmp_path, fakes):
    """The ``fleet.scrape`` chaos site: scrapes 1 and 3 time out — the
    merge proceeds over the pool that answered (its counters, exactly),
    the dead pools are listed down, and the fleet is degraded, not
    down. The NEXT pass (calls 4-6) is clean again."""
    pools = fakes(3)
    plan = FaultPlan(schedule={"fleet.scrape": (1, 3)})
    controller, _ = _controller(tmp_path, pools, fault_plan=plan)
    body = controller.stats()
    assert plan.fired["fleet.scrape"] == 2
    survivor = f"127.0.0.1:{pools[1].port}"
    assert [row["pool"] for row in body["pools"]] == [survivor]
    assert body["decisions"] == pools[1].decisions
    assert body["raw"]["histogram"]["count"] \
        == pools[1].raw_histogram["count"]
    assert body["fleet"]["up"] == 1
    assert len(body["fleet"]["down"]) == 2
    # Clean pass: every pool answers, health is ok fleet-wide.
    health = controller.health()
    assert health["status"] == "ok"
    assert health["down"] == [] and health["up"] == 3


def test_fleet_health_classifies_degraded_vs_down(tmp_path, fakes):
    pools = fakes(3)
    pools[1].alive = 1  # below worker strength, no rollout in flight
    plan = FaultPlan(schedule={"fleet.scrape": (3,)})
    controller, _ = _controller(tmp_path, pools, fault_plan=plan)
    health = controller.health()
    assert health["status"] == "degraded"
    assert health["degraded"] == [f"127.0.0.1:{pools[1].port}"]
    assert health["down"] == [f"127.0.0.1:{pools[2].port}"]
    names = [f"127.0.0.1:{f.port}" for f in pools]
    assert health["pools"][names[0]]["status"] == "ok"
    assert health["pools"][names[1]]["status"] == "degraded"
    assert health["pools"][names[2]] == {"status": "down"}


def test_fleet_http_plane_reset_fanout_and_decisionview(tmp_path, fakes):
    """The served plane end to end: /stats, /metrics, /healthz over a
    live fleet server; /stats/reset fans out to every pool WITHOUT
    rewinding the ledger-derived lifecycle counters; promotes are
    deliberately NOT on HTTP (CLI only); and decisionview's
    ``load_stats`` reads the fleet URL like any pool URL (satellite:
    ``decisionview --stats http://fleet:8790/stats``)."""
    from rl_scheduler_tpu.scheduler.fleet import _make_fleet_server
    from tools.decisionview import build_report, load_stats

    pools = fakes(3)
    controller, _ = _controller(tmp_path, pools)
    assert controller.promote("/ckpt/v2")["status"] == "ok"
    server = _make_fleet_server(controller, "127.0.0.1", 0)
    port = server.socket.getsockname()[1]
    threading.Thread(
        target=lambda: server.serve_forever(poll_interval=0.05),
        daemon=True).start()
    try:
        health = _get(port, "/healthz")
        assert health["status"] == "ok" and health["generation"] == 1
        metrics = _get(port, "/metrics")
        assert f"{METRIC_PREFIX}_fleet_pools 3" in metrics
        assert f"{METRIC_PREFIX}_fleet_pools_up 3" in metrics
        assert f"{METRIC_PREFIX}_fleet_promotions_total 1" in metrics
        assert f"{METRIC_PREFIX}_decision_latency_seconds_count 6" \
            in metrics
        # The fleet body reads like a pool body to decisionview.
        stats = load_stats(f"http://127.0.0.1:{port}/stats")
        assert stats["fleet"]["generation"] == 1
        report = build_report(stats=stats)
        assert report["e2e"]["count"] == 6
        # Reset fan-out: every pool acked, the lifecycle counters and
        # the fleet generation did NOT rewind (they derive from the
        # ledger, which /stats/reset never touches).
        ack = _post(port, "/stats/reset", {})
        assert all(ack["pools"].values())
        assert [f.resets for f in pools] == [1, 1, 1]
        assert f"{METRIC_PREFIX}_fleet_promotions_total 1" \
            in _get(port, "/metrics")
        # The write plane stays off HTTP: promotes go through the CLI.
        status, _ = _post_code(port, "/promote", {"checkpoint": "/x"})
        assert status == 404
    finally:
        server.shutdown()
        server.server_close()


def test_fleet_healthz_503_only_when_every_pool_is_down(tmp_path, fakes):
    from rl_scheduler_tpu.scheduler.fleet import _make_fleet_server

    pools = fakes(2)
    controller, _ = _controller(tmp_path, pools)
    for fake in pools:
        fake.close()
    server = _make_fleet_server(controller, "127.0.0.1", 0)
    port = server.socket.getsockname()[1]
    threading.Thread(
        target=lambda: server.serve_forever(poll_interval=0.05),
        daemon=True).start()
    try:
        code, health = _get_code(port, "/healthz")
        assert code == 503
        assert health["status"] == "down"
    finally:
        server.shutdown()
        server.server_close()


# ------------------------------------------------- the merge, pinned


def _pool_bodies(pools=3, workers=2):
    """``pools`` x ``workers`` REAL policy snapshots — greedy decisions
    through the real filter path with SLO trackers armed — grouped into
    per-pool ``/stats`` bodies via the pool's own ``aggregate_stats``.
    Returns ``(bodies_by_name, all_worker_snapshots)``."""
    from rl_scheduler_tpu.scheduler.slo import SloConfig, SloTracker

    bodies = {}
    all_snaps = []
    n = 0
    for p in range(pools):
        shared = PoolShared()
        snaps = []
        for w in range(workers):
            policy = _greedy_factory(w, shared)
            policy.slo = SloTracker(SloConfig(p99_ms=1000.0))
            n += 1
            for i in range(n):  # distinct per-worker request counts
                policy.filter(_filter_args(i))
            snaps.append(worker_snapshot(policy, w))
        all_snaps.extend(snaps)
        bodies[f"pool{p}"] = aggregate_stats(
            snaps, {"workers": workers, "alive": workers,
                    "generation": 0})
    return bodies, all_snaps


def test_fleet_merge_equals_union_of_all_workers():
    """The tentpole pin: merging pool /stats bodies at the fleet level
    (pool pseudo-snapshots through the SAME ``aggregate_stats``) equals
    merging all six worker snapshots directly — bucket counts and
    lifetime counters exactly, float sums to rounding. Associativity is
    what makes 'scrape the fleet OR the pools' a free choice."""
    bodies, all_snaps = _pool_bodies(pools=3, workers=2)
    fleet_body = aggregate_fleet_stats(bodies, fleet={"generation": 0})
    union = aggregate_stats(all_snaps, pool={})

    assert fleet_body["decisions"] == union["decisions"]
    assert fleet_body["raw"]["histogram"]["cumulative"] \
        == union["raw"]["histogram"]["cumulative"]
    assert fleet_body["raw"]["histogram"]["count"] \
        == union["raw"]["histogram"]["count"]
    assert fleet_body["raw"]["histogram"]["sum"] == pytest.approx(
        union["raw"]["histogram"]["sum"])
    # Latency quantiles come from the same merged buckets — identical.
    assert fleet_body["latency"]["p50_ms"] == union["latency"]["p50_ms"]
    assert fleet_body["latency"]["p99_ms"] == union["latency"]["p99_ms"]
    assert fleet_body["latency"]["lifetime_count"] \
        == union["latency"]["lifetime_count"]
    # Per-phase histograms and the SLO section merge associatively too.
    assert set(fleet_body["phases"]) == set(union["phases"])
    for phase in union["phases"]:
        assert fleet_body["raw"]["phases"][phase]["cumulative"] \
            == union["raw"]["phases"][phase]["cumulative"]
    assert fleet_body["slo"]["lifetime"] == union["slo"]["lifetime"]
    assert fleet_body["slo"]["windows_raw"] == union["slo"]["windows_raw"]
    assert not fleet_body["slo"]["degraded"]
    # The pools rows carry per-pool provenance the way workers[] does.
    assert [row["pool"] for row in fleet_body["pools"]] \
        == ["pool0", "pool1", "pool2"]
    assert sum(row["decisions_total"] for row in fleet_body["pools"]) \
        == sum(union["decisions"].values())


def test_fleet_merge_tolerates_version_skewed_pools():
    """The optional-phase rule one level up: a pool without the ``raw``
    section (older build) contributes its counters but no buckets; a
    pool whose raw phases lack ``batch_wait`` merges the phases it has.
    Nothing raises, nothing silently double-counts."""
    bodies, _ = _pool_bodies(pools=2, workers=1)
    names = sorted(bodies)
    skewed = {k: v for k, v in bodies[names[0]].items() if k != "raw"}
    full = bodies[names[1]]
    trimmed_raw = {
        "histogram": full["raw"]["histogram"],
        "phases": {k: v for k, v in full["raw"]["phases"].items()
                   if k != "batch_wait"},
    }
    trimmed = dict(full)
    trimmed["raw"] = trimmed_raw
    fleet_body = aggregate_fleet_stats(
        {"old": skewed, "new": trimmed}, fleet={})
    # Counters from BOTH pools, buckets only from the one that has them.
    assert fleet_body["decisions"]["aws"] == (
        skewed["decisions"]["aws"] + trimmed["decisions"]["aws"])
    assert fleet_body["raw"]["histogram"]["count"] \
        == full["raw"]["histogram"]["count"]
    assert "batch_wait" not in fleet_body["raw"]["phases"]
    assert fleet_body["raw"]["phases"]["forward"]["count"] \
        == full["raw"]["phases"]["forward"]["count"]
    snap = pool_stats_snapshot("old", skewed)
    assert snap["histogram"] == {"cumulative": [], "sum": 0.0, "count": 0}


def test_fleet_metrics_exposition_names_and_series(fakes, tmp_path):
    pools = fakes(2)
    controller, _ = _controller(tmp_path, pools)
    scrapes = controller.scrape()
    scrapes[f"127.0.0.1:{pools[1].port}"] = None  # one pool down
    text = aggregate_fleet_metrics(scrapes,
                                   controller.fleet_info(scrapes))
    p = METRIC_PREFIX
    assert f"{p}_fleet_pools 2" in text
    assert f"{p}_fleet_pools_up 1" in text
    assert (f'{p}_fleet_pool_up{{pool="127.0.0.1:{pools[1].port}"}} 0'
            in text)
    assert (f'{p}_fleet_pool_generation'
            f'{{pool="127.0.0.1:{pools[0].port}"}} 1' in text)
    assert f'{p}_decisions_total{{cloud="aws"}} 3' in text
    # Same exposition names as the pool plane — one Prometheus scrape
    # config serves every level.
    assert f"{p}_decision_latency_seconds_bucket" in text


def test_fault_plan_from_env_parses_the_fleet_sites():
    assert fault_plan_from_env(None) is None
    assert fault_plan_from_env("") is None
    plan = fault_plan_from_env("fleet.promote:3;fleet.scrape:1,4")
    assert plan.schedule["fleet.promote"] == frozenset({3})
    assert plan.schedule["fleet.scrape"] == frozenset({1, 4})
    with pytest.raises(ValueError, match="call_index"):
        fault_plan_from_env("fleet.promote")
    with pytest.raises(ValueError, match="unknown fault site"):
        fault_plan_from_env("fleet.bogus:1")


# -------------------------------------------------------- trace harvest


def _trace_record(i, generation=0):
    return decision_record(
        endpoint="filter", family="set", backend="numpy",
        candidates=2, chosen="node-0", score=0.5, latency_ms=1.0,
        obs_sha="ab" * 8, telemetry_pos=i, worker_id=0,
        generation=generation, fail_open=False,
        clouds=["aws", "azure"], pod_cpu=0.2,
    )


def _write_stream(trace_dir, prefix, records, seg_records=16):
    log = TraceLog(trace_dir, prefix=prefix,
                   max_records_per_segment=seg_records)
    for r in records:
        assert log.append(r)
    log.close()


def test_fleet_snapshot_cli_unions_pool_traces(tmp_path, capsys):
    """``fleet snapshot`` through the real CLI: per-pool prefixes keep
    every segment parseable, the union manifest records per-pool
    provenance, and the union root is itself a valid trace dir — one
    graftloop iteration can snapshot/compile straight from it."""
    for p, count in enumerate((12, 30)):
        _write_stream(tmp_path / f"trace{p}", "w0-",
                      [_trace_record(i) for i in range(count)])
    out = tmp_path / "union"
    rc = fleet_main([
        "snapshot",
        "--trace-dirs", f"{tmp_path / 'trace0'},{tmp_path / 'trace1'}",
        "--names", "east,west",
        "--out", str(out),
    ])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["metric"] == "fleet_snapshot"
    assert line["records"] == 42
    assert line["pools"] == {"east": 12, "west": 30}
    meta = json.loads((out / "snapshot.json").read_text())
    assert meta["source"] == "fleet"
    assert meta["pools"]["east"]["prefix"] == "p0-"
    assert all(name.startswith(("p0-", "p1-")) for name in meta["files"])
    assert sum(1 for _ in iter_trace(out)) == 42
    # Valid snapshot root: a second-level snapshot_trace accepts it.
    resnap = snapshot_trace(out, tmp_path / "resnap")
    assert resnap["records"] == 42


def test_fleet_snapshot_validates_inputs(tmp_path):
    with pytest.raises(ValueError, match="at least one"):
        fleet_snapshot({}, tmp_path / "union")


# ------------------------------------------------------------ the drill


def _make_verified_checkpoint(root, name="ckpt-good"):
    import hashlib

    run = Path(root) / name
    step = run / "checkpoints" / "1"
    step.mkdir(parents=True)
    payload = (name.encode() + b"-weights") * 64
    (step / "state.bin").write_bytes(payload)
    mdir = run / "checkpoint_manifests"
    mdir.mkdir()
    (mdir / "1.json").write_text(json.dumps({
        "step": 1,
        "files": {"state.bin": {
            "sha256": hashlib.sha256(payload).hexdigest(),
            "size": len(payload),
        }},
    }))
    return run


class _PoisonedBackend:
    name = "poisoned"

    def decide(self, obs):
        raise RuntimeError("regressing checkpoint")


def _rollout_factory(trace_dir=None):
    def factory(worker_id, shared, spec):
        telemetry = TableTelemetry.from_table(
            cpu_source=RandomCpu(seed=0), counter=shared.table_counter
        )
        backend = (_PoisonedBackend()
                   if spec.checkpoint
                   and "regress" in Path(spec.checkpoint).name
                   else GreedyBackend())
        policy = ExtenderPolicy(backend, telemetry)
        if trace_dir is not None:
            policy.trace = TraceLog(trace_dir, prefix=f"w{worker_id}-")
        return policy

    return factory


def _make_rollout_pool(workers=2, trace_dir=None):
    pool = ServingPool(
        _rollout_factory(trace_dir), workers=workers, host="127.0.0.1",
        port=0, control_port=0, restart_policy=FAST_RESTARTS,
        stable_after_s=60.0, poll_interval_s=0.05,
        # max_latency_ratio is load-sensitive at these sub-millisecond
        # absolute latencies (a busy machine can 4x a 0.08 ms mean); the
        # regressing candidate is caught by the probe gate, not this one.
        rollout_opts={"canary_hold_s": 0.2, "probe_count": 2,
                      "ready_timeout_s": 60.0, "max_latency_ratio": 50.0},
    )
    pool.start(ready_timeout_s=60.0)
    return pool


@needs_fork
def test_fleet_drill_promote_abort_resume_union(tmp_path):
    """`make fleet-drill`, the acceptance drill: three real 2-worker
    pools serve continuous multi-target bench traffic while a fleet
    promote canaries the first pool, holds, and rolls the rest — zero
    failed requests in every phase and per pool; a regressing candidate
    is rolled back by the canary pool's own gate and the fleet promote
    aborts with nothing left divergent; a SIGKILLed fleet-promote CLI
    resumes from its ledger byte-prefix-exact without re-running the
    recorded canary; and ``fleet_snapshot`` unions the three live trace
    dirs into one root that compiles and round-trips through the real
    env (the fleet-wide retrain input; the full graftloop iteration on
    this union is the slow ``fleet-soak`` test)."""
    bench = _load_bench()
    pools = []
    try:
        for i in range(3):
            pools.append(_make_rollout_pool(
                workers=2, trace_dir=tmp_path / f"trace{i}"))
        data_targets = [f"127.0.0.1:{p.port}" for p in pools]
        pools_arg = ",".join(
            f"127.0.0.1:{p.control_address[1]}" for p in pools)
        fleet_dir = tmp_path / "fleet"
        ckpt_v2 = _make_verified_checkpoint(tmp_path, "ckpt-v2")
        ckpt_bad = _make_verified_checkpoint(tmp_path, "ckpt-regress")
        ckpt_v3 = _make_verified_checkpoint(tmp_path, "ckpt-v3")
        controller = FleetController(
            StaticResolver(pools_arg), fleet_dir,
            canary_hold_s=0.3, rollout_timeout_s=120.0)

        # Prime traffic, then pin merged == union of the pool scrapes.
        for i in range(12):
            _post(pools[i % 3].port, "/filter", _filter_args(i))
        scrapes = controller.scrape()
        body = aggregate_fleet_stats(scrapes,
                                     controller.fleet_info(scrapes))
        assert body["raw"]["histogram"]["count"] == sum(
            s["raw"]["histogram"]["count"] for s in scrapes.values())
        assert body["raw"]["histogram"]["count"] >= 12
        assert [row["pool"] for row in body["pools"]] == sorted(scrapes)
        assert sum(row["decisions_total"] for row in body["pools"]) \
            == sum(body["decisions"].values())

        # Phase 1: the good promote lands mid-soak across all pools.
        result = {}

        def _run_soak():
            result["r"] = bench._soak(
                None, 3.0, 3, 2, promote_at=1.0,
                targets=data_targets, connect_retries=3)

        soak = threading.Thread(target=_run_soak)
        soak.start()
        time.sleep(1.0)
        out = controller.promote(str(ckpt_v2))
        assert out["status"] == "ok", out
        assert out["generation"] == 1
        assert out["pools"][0] == controller.canary
        soak.join(timeout=120)
        assert "r" in result, "soak thread never finished"
        _, _, failures, phases, _, _, per_pool = result["r"]
        assert failures == 0
        for phase, counts in phases.items():
            assert counts["failures"] == 0, (phase, counts)
        assert set(per_pool) == set(data_targets)
        for target, counts in per_pool.items():
            assert counts["requests"] > 0, (target, counts)
            assert counts["failures"] == 0, (target, counts)
        for p in pools:
            status = _get(p.control_address[1], "/rollout")
            assert status["checkpoint"] == str(ckpt_v2)
            assert not status["active"]

        # Phase 2: the regressing candidate — the canary pool's own
        # gate rolls it back, the fleet promote aborts, every pool
        # stays on v2 and the ledger counters say exactly what ran.
        out = controller.promote(str(ckpt_bad))
        assert out["status"] == "aborted", out
        assert controller.ledger.counters() == {
            "generation": 1, "promotions_total": 1,
            "rollbacks_total": 1, "aborts_total": 1,
            "refusals_total": 0}
        for p in pools:
            assert _get(p.control_address[1],
                        "/rollout")["checkpoint"] == str(ckpt_v2)
        metrics = controller.metrics()
        assert f"{METRIC_PREFIX}_fleet_generation 1" in metrics
        assert f"{METRIC_PREFIX}_fleet_aborts_total 1" in metrics
        assert f"{METRIC_PREFIX}_fleet_rollbacks_total 1" in metrics

        # Phase 3: SIGKILL the fleet-promote CLI during the canary
        # hold; the in-process resume finishes the SAME promote without
        # re-running the recorded canary, extending the killed ledger's
        # bytes verbatim.
        env = dict(os.environ)
        env["PYTHONPATH"] = str(REPO_ROOT) + os.pathsep \
            + env.get("PYTHONPATH", "")
        env.pop("GRAFTFLEET_FAULTS", None)
        proc = subprocess.Popen(
            [sys.executable, "-m", "rl_scheduler_tpu.scheduler.fleet",
             "promote", "--pools", pools_arg,
             "--fleet-dir", str(fleet_dir),
             "--checkpoint", str(ckpt_v3),
             "--canary-hold", "5.0", "--rollout-timeout", "120"],
            env=env, start_new_session=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        ledger_path = fleet_dir / FLEET_LEDGER_NAME
        deadline = time.monotonic() + 120.0
        try:
            while time.monotonic() < deadline:
                # Two prior canary stages exist (v2 ok, regress
                # rolled_back); the third is THIS promote's.
                if ledger_path.read_text().count('"role": "canary"') >= 3:
                    break
                if proc.poll() is not None:
                    pytest.fail("fleet CLI exited before the canary "
                                f"stage (rc={proc.returncode})")
                time.sleep(0.1)
            else:
                pytest.fail("canary stage never recorded")
        finally:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        proc.wait(timeout=30)
        killed = ledger_path.read_bytes()
        out = controller.promote(str(ckpt_v3))
        assert out["status"] == "ok", out
        assert out["generation"] == 2
        assert ledger_path.read_bytes().startswith(killed)
        assert ledger_path.read_text().count('"role": "canary"') == 3
        for p in pools:
            assert _get(p.control_address[1],
                        "/rollout")["checkpoint"] == str(ckpt_v3)
        assert f"{METRIC_PREFIX}_fleet_generation 2" \
            in controller.metrics()

        # Phase 4: harvest the fleet — one union snapshot of all three
        # live trace dirs compiles and round-trips through the real env.
        from rl_scheduler_tpu.scenarios import get_scenario

        union = tmp_path / "union"
        meta = fleet_snapshot(
            {f"pool{i}": tmp_path / f"trace{i}" for i in range(3)},
            union)
        assert set(meta["pools"]) == {"pool0", "pool1", "pool2"}
        assert all(m["records"] > 0 for m in meta["pools"].values())
        assert meta["records"] == sum(m["records"]
                                      for m in meta["pools"].values())
        compiled = compile_trace(union, steps=8, seed=0)
        assert compiled.stats["steps"] == 8
        name = trace_scenario_name(union, steps=8)
        report = verify_roundtrip(get_scenario(name), num_nodes=8)
        assert report["steps_checked"] >= 1
    finally:
        for p in pools:
            p.shutdown()


# ------------------------------------------------------------ fleet-soak


@pytest.mark.slow
def test_fleet_soak_union_feeds_one_loop_iteration(tmp_path):
    """The closing claim: a fleet-wide trace union IS a graftloop
    input. Two pools' traces union into one snapshot root, and one
    (dry-run) loop iteration snapshots, compiles, retrains from a thin
    incumbent, and reaches the promote gate on the UNION's record
    count — fleet-wide traffic, one retrain."""
    from rl_scheduler_tpu.agent import train_ppo
    from rl_scheduler_tpu.loopback import LoopRunner, LoopSpec

    for p, count in enumerate((40, 40)):
        _write_stream(tmp_path / f"trace{p}", "w0-",
                      [_trace_record(i) for i in range(count)])
    union = tmp_path / "union"
    meta = fleet_snapshot({"east": tmp_path / "trace0",
                           "west": tmp_path / "trace1"}, union)
    assert meta["records"] == 80
    incumbent = train_ppo.main([
        "--env", "cluster_set", "--preset", "quick", "--num-envs", "4",
        "--rollout-steps", "8", "--minibatch-size", "32",
        "--iterations", "1", "--eval-every", "1", "--eval-episodes", "2",
        "--run-name", "INCUMBENT", "--run-root", str(tmp_path / "runs"),
    ])
    spec = LoopSpec(trace_dir=str(union), incumbent=str(incumbent),
                    dry_run=True, steps=16, mix_frac=0.25, iterations=2,
                    eval_every=1, eval_episodes=2,
                    verdict_seeds=(0, 1, 2), verdict_episodes=2)
    summary = LoopRunner(spec, tmp_path / "loop").run()
    assert summary["trace_records"] == 80
    # Dry-run stops at the gate either way: a winning candidate refuses
    # with would_promote, a losing one with the verdict verdict — both
    # prove the fleet union drove the full snapshot/compile/retrain/
    # evaluate chain to the promote decision.
    assert summary["promote_status"] == "refused"
    reason = summary["promote"]["reason"]
    assert "dry-run" in reason or "verdict" in reason
    if "dry-run" in reason:
        assert summary["promote"]["would_promote"] == summary["candidate"]


def test_fleet_server_handler_threads_are_joinable(tmp_path, fakes):
    """The GL017 fix pinned: ThreadingHTTPServer defaults
    ``daemon_threads = True``, which lets server_close() abandon
    in-flight scrapes mid-write on shutdown. The fleet server must keep
    handler threads non-daemon so shutdown() + server_close() DRAINS
    them (the same drain contract the pool server documents)."""
    from rl_scheduler_tpu.scheduler.fleet import _make_fleet_server

    controller, _ = _controller(tmp_path, fakes(1))
    server = _make_fleet_server(controller, "127.0.0.1", 0)
    try:
        assert server.daemon_threads is False
    finally:
        server.server_close()
