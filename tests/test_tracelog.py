"""graftroll part 1 (scheduler/tracelog.py): the durable decision trace.

Pins the writer's crash-safety story (flush-per-record parts, fsync-then-
rename seals, orphan recovery), the counted drop-oldest backpressure (the
hot path never blocks), the schema-versioned record the extender appends
per decision — success AND fail-open — and the replay order ``iter_trace``
guarantees. The ``tracelog.append`` chaos site rides in the graftguard
suite (``make chaos``); lifetime-counter monotonicity across
``/stats/reset`` is pinned here at the policy level and again pool-wide
in tests/test_pool.py.
"""

import json
import threading

import pytest

from rl_scheduler_tpu.scheduler.extender import ExtenderPolicy
from rl_scheduler_tpu.scheduler.policy_backend import (
    GreedyBackend,
    backend_info,
)
from rl_scheduler_tpu.scheduler.telemetry import RandomCpu, TableTelemetry
from rl_scheduler_tpu.scheduler.tracelog import (
    TRACE_SCHEMA,
    TraceLog,
    decision_record,
    iter_trace,
    obs_digest,
)


def _records(n, start=0):
    return [{"schema": TRACE_SCHEMA, "i": i} for i in range(start, start + n)]


def _greedy_policy(trace=None):
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))
    policy = ExtenderPolicy(GreedyBackend(), telemetry)
    policy.trace = trace
    return policy


def _filter_args(i=0):
    return {"nodenames": [f"aws-w{i}", f"azure-w{i}"], "pod": {}}


# ----------------------------------------------------------------- writer


def test_append_write_seal_and_replay(tmp_path):
    """Records flow queue -> part file -> sealed segment; iter_trace
    replays every record in write order; sealing happens at the
    configured segment size and close() seals the remainder."""
    log = TraceLog(tmp_path, max_records_per_segment=3)
    for rec in _records(7):
        assert log.append(rec)
    log.close()
    snap = log.snapshot()
    assert snap["records_total"] == 7
    assert snap["written_total"] == 7
    assert snap["dropped_total"] == 0
    assert snap["write_errors_total"] == 0
    # 3 + 3 sealed on rotation, the last 1 sealed by close()
    assert snap["segments_total"] == 3
    sealed = sorted(p.name for p in tmp_path.glob("*.jsonl"))
    assert sealed == ["seg-000001.jsonl", "seg-000002.jsonl",
                      "seg-000003.jsonl"]
    assert not list(tmp_path.glob("*.part"))
    assert [r["i"] for r in iter_trace(tmp_path)] == list(range(7))


def test_prefix_namespaces_streams_in_one_dir(tmp_path):
    """Pool workers share one trace dir: per-writer prefixes never
    collide, and iter_trace filters per stream or replays all."""
    a = TraceLog(tmp_path, prefix="w0-", max_records_per_segment=2)
    b = TraceLog(tmp_path, prefix="w1-", max_records_per_segment=2)
    for rec in _records(3):
        a.append(rec)
    for rec in _records(2, start=100):
        b.append(rec)
    a.close()
    b.close()
    assert [r["i"] for r in iter_trace(tmp_path, prefix="w0-")] == [0, 1, 2]
    assert [r["i"] for r in iter_trace(tmp_path, prefix="w1-")] == [100, 101]
    assert len(list(iter_trace(tmp_path))) == 5


def test_drop_oldest_backpressure_counted(tmp_path):
    """With the writer stalled, a full queue drops the OLDEST record and
    counts it — append never blocks and never raises (the AsyncPlacer
    policy). The survivors are the newest records."""
    log = TraceLog(tmp_path, max_queue=4, autostart=False)
    for rec in _records(10):
        log.append(rec)
    snap = log.snapshot()
    assert snap["records_total"] == 10
    assert snap["dropped_total"] == 6
    log.start()
    log.close()
    assert [r["i"] for r in iter_trace(tmp_path)] == [6, 7, 8, 9]


def test_orphaned_part_recovered_on_restart(tmp_path):
    """A .part stranded by a crash (writer never sealed it) is sealed by
    the NEXT writer over the same dir — flushed lines survive, and the
    new writer's sequence numbers continue past it."""
    part = tmp_path / "seg-000004.jsonl.part"
    part.write_text(json.dumps({"i": 40}) + "\n")
    log = TraceLog(tmp_path)
    assert (tmp_path / "seg-000004.jsonl").exists()
    assert not part.exists()
    log.append({"i": 50})
    log.close()
    assert (tmp_path / "seg-000005.jsonl").exists()
    assert [r["i"] for r in iter_trace(tmp_path)] == [40, 50]


def test_iter_trace_skips_torn_trailing_line(tmp_path):
    """A writer killed mid-write leaves a torn last line; replay yields
    every whole record and skips the tear instead of raising."""
    seg = tmp_path / "seg-000001.jsonl"
    seg.write_text(json.dumps({"i": 0}) + "\n" + '{"i": 1, "tr')
    assert [r["i"] for r in iter_trace(tmp_path)] == [0]


def test_validation_and_closed_append(tmp_path):
    with pytest.raises(ValueError, match="max_records_per_segment"):
        TraceLog(tmp_path, max_records_per_segment=0)
    with pytest.raises(ValueError, match="max_queue"):
        TraceLog(tmp_path, max_queue=0)
    log = TraceLog(tmp_path)
    log.close()
    assert log.append({"i": 0}) is False  # no-op after close, never raises
    log.close()  # idempotent


def test_concurrent_appends_all_land(tmp_path):
    """The serving threads append concurrently; every record lands
    exactly once (queue + single writer thread)."""
    log = TraceLog(tmp_path, max_records_per_segment=64, max_queue=4096)

    def worker(base):
        for rec in _records(100, start=base):
            log.append(rec)

    threads = [threading.Thread(target=worker, args=(t * 1000,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log.close()
    seen = sorted(r["i"] for r in iter_trace(tmp_path))
    assert seen == sorted(t * 1000 + i for t in range(4) for i in range(100))


# ----------------------------------------------------------------- records


def test_decision_record_schema_and_digest():
    import numpy as np

    obs = np.arange(6, dtype=np.float32)
    rec = decision_record(
        endpoint="filter", family="cloud", backend="greedy", candidates=2,
        chosen="aws", score=0.75, latency_ms=0.123456, obs=obs,
        telemetry_pos=7, worker_id=1, generation=3,
        breaker_state="closed",
    )
    assert rec["schema"] == TRACE_SCHEMA
    assert rec["obs_sha"] == obs_digest(obs) and len(rec["obs_sha"]) == 16
    assert obs_digest(obs) == obs_digest(obs.copy())  # content-stable
    assert obs_digest(None) is None
    assert rec["chosen"] == "aws" and rec["generation"] == 3
    assert rec["fail_open"] is False and rec["breaker"] == "closed"
    json.dumps(rec)  # every field is JSONL-serializable


def test_policy_traces_every_decision_and_fail_open(tmp_path):
    """The extender appends one record per decision — /filter and
    /prioritize, flat family — carrying the chosen cloud, score, obs
    digest and telemetry position; a backend failure appends a
    fail_open record and bumps the policy's fail_open_total."""
    log = TraceLog(tmp_path)
    policy = _greedy_policy(trace=log)
    policy.filter(_filter_args(0))
    policy.prioritize(_filter_args(1))

    class Boom:
        name = "boom"

        def decide(self, obs):
            raise RuntimeError("poisoned")

    healthy_backend = policy.backend
    policy.backend = Boom()
    policy.filter(_filter_args(2))  # fails open, stays answered
    policy.backend = healthy_backend
    log.close()

    records = list(iter_trace(tmp_path))
    assert len(records) == 3
    ok_filter, ok_prio, failed = records
    assert ok_filter["endpoint"] == "filter" and not ok_filter["fail_open"]
    assert ok_filter["chosen"] in ("aws", "azure")
    assert ok_filter["candidates"] == 2
    assert len(ok_filter["obs_sha"]) == 16
    # exact provenance: THIS thread's first observation consumed row 0,
    # its second row 1 (last_replay_position is thread-local)
    assert ok_filter["telemetry_pos"] == 0
    assert ok_prio["telemetry_pos"] == 1
    assert 0.0 <= ok_filter["score"] <= 1.0
    assert ok_prio["endpoint"] == "prioritize" and not ok_prio["fail_open"]
    assert failed["fail_open"] is True and failed["chosen"] is None
    assert failed["obs_sha"] is None
    assert policy.statistics()["fail_open_total"] == 1
    assert policy.statistics()["trace"]["records_total"] == 3
    info = backend_info(policy.backend)
    assert info == {"name": "greedy", "family": "cloud"}


def test_reset_stats_never_clears_trace_counters(tmp_path):
    """The small-fix satellite, single-process half: /stats/reset clears
    the percentile ring only — trace records/segments and fail-open
    counts are lifetime-monotonic, like the latency histogram."""
    log = TraceLog(tmp_path, max_records_per_segment=2)
    policy = _greedy_policy(trace=log)
    for i in range(5):
        policy.filter(_filter_args(i))
    before = policy.statistics()["trace"]
    assert before["records_total"] == 5
    policy.reset_stats()
    stats = policy.statistics()
    assert stats["latency"]["count"] == 0          # the ring cleared
    assert stats["trace"]["records_total"] == 5    # the trace did not
    assert stats["trace"]["segments_total"] >= before["segments_total"]
    metrics = policy.metrics_text()
    assert "rl_scheduler_extender_trace_records_total 5" in metrics
    assert "rl_scheduler_extender_trace_dropped_total 0" in metrics
    assert "rl_scheduler_extender_fail_open_total 0" in metrics
    log.close()


def test_close_with_wedged_writer_leaves_part_for_recovery(
        tmp_path, caplog):
    """The GL017 drain contract: close() joins the writer with a
    timeout, and when the join VERDICT says the thread is still alive
    (write(2) wedged on a dying mount), it must NOT seal the active
    segment — the writer still owns the file handle, and sealing under
    it would race its next write. The .part is left for the next
    startup's recovery, which is the crash path that already works."""
    import logging
    import time

    log = TraceLog(tmp_path, max_records_per_segment=100)
    log.append({"i": 1})
    deadline = time.monotonic() + 5.0
    while log.snapshot()["written_total"] < 1:
        assert time.monotonic() < deadline, "writer never drained"
        time.sleep(0.01)
    assert list(tmp_path.glob("*.jsonl.part"))

    class _Wedged:
        """Stands in for a writer blocked in write(2): join times out,
        is_alive stays True."""

        def join(self, timeout=None):
            pass

        def is_alive(self):
            return True

    real_thread = log._thread
    log._thread = _Wedged()
    with caplog.at_level(logging.ERROR,
                         logger="rl_scheduler_tpu.scheduler.tracelog"):
        log.close()
    assert any("still alive" in r.message for r in caplog.records)
    # Not sealed: the segment is still a .part, owned by the writer.
    assert list(tmp_path.glob("*.jsonl.part"))
    assert not list(tmp_path.glob("*.jsonl"))
    real_thread.join(timeout=5.0)  # the real writer drained the sentinel

    # Startup recovery seals it — the record is never lost.
    log2 = TraceLog(tmp_path)
    log2.close()
    assert [r["i"] for r in iter_trace(tmp_path)] == [1]
