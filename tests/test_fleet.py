"""Fleet-scale node counts for the structured envs (--num-nodes).

The domain's real scaling axis is the node set (SURVEY.md §5.7: a
production cluster has hundreds of nodes). The set/GNN policies share
per-node weights, so one checkpoint applies at any N; these tests pin
the plumbing that takes the training distribution to fleet N — env
construction, CLI validation, checkpoint meta, resume guards, and the
evaluate-at-trained-N round trip.
"""

import pytest

from rl_scheduler_tpu.agent.ppo import PPOTrainConfig
from rl_scheduler_tpu.agent.train_ppo import make_bundle_and_net


def test_structured_bundles_scale_node_count():
    cfg = PPOTrainConfig()
    bundle, net = make_bundle_and_net("cluster_set", cfg, num_nodes=16)
    assert bundle.obs_shape == (16, 6)
    assert bundle.num_actions == 16
    bundle, net = make_bundle_and_net("cluster_graph", cfg, num_nodes=12)
    assert bundle.obs_shape[0] == 12
    assert bundle.num_actions == 12


def test_set_policy_one_checkpoint_any_n():
    """Per-node weight sharing: params init'd at N=8 apply at N=64
    unchanged — the property that makes fleet serving/eval free."""
    import jax

    from rl_scheduler_tpu.models import SetTransformerPolicy

    net = SetTransformerPolicy(dim=32, depth=1)
    obs8 = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 6))
    params = net.init(jax.random.PRNGKey(1), obs8)
    obs64 = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 6))
    logits, value = net.apply(params, obs64)
    assert logits.shape == (2, 64)
    assert value.shape == (2,)


def test_set_fleet64_preset_implies_fleet_recipe(tmp_path):
    """--preset set_fleet64 is the measured N=64 recipe: implies
    cluster_set at 64 nodes (overridable --num-nodes), bf16, 1 epoch,
    1024 envs; contradictions refused like the other recipe presets."""
    from rl_scheduler_tpu.agent import train_ppo as cli
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS, PRESET_IMPLIES

    cfg = PPO_PRESETS["set_fleet64"]
    assert (cfg.num_envs, cfg.num_epochs, cfg.compute_dtype) == \
        (1024, 1, "bfloat16")
    assert PRESET_IMPLIES["set_fleet64"] == {"env": "cluster_set",
                                             "num_nodes": 64,
                                             "reseed_on_stall": 2,
                                             "fused_set_block": "tpu"}
    with pytest.raises(SystemExit, match="cluster_set"):
        cli.main(["--preset", "set_fleet64", "--env", "cluster_graph",
                  "--run-root", str(tmp_path)])


def test_set_fleet64_preset_trains(tmp_path):
    """The preset trains end-to-end (tiny overrides) and records the
    implied node count in checkpoint meta."""
    from rl_scheduler_tpu.agent import train_ppo as cli
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    cli.main([
        "--preset", "set_fleet64", "--num-nodes", "16", "--num-envs", "4",
        "--rollout-steps", "8", "--minibatch-size", "16",
        "--iterations", "1", "--checkpoint-every", "1",
        "--run-root", str(tmp_path), "--run-name", "fleet_preset",
    ])
    mgr = CheckpointManager(tmp_path / "fleet_preset")
    meta = mgr.restore_meta(1)
    assert meta["num_nodes"] == 16  # explicit flag overrides the implied 64
    assert meta["env"] == "cluster_set"
    # The preset's fused-block implication is TPU-only (off-chip the
    # kernel would run interpret mode); on the CPU suite it must resolve
    # to off — and be recorded so resumes keep the path.
    assert meta["fused_set_block"] is False
    mgr.close()


def test_flash_attn_validation(tmp_path):
    """--flash-attn guards: cluster_set only, flax policy only, no --sp,
    N a multiple of the kernel block (128) — each refused with an
    actionable message BEFORE any device work."""
    from rl_scheduler_tpu.agent import train_ppo as cli

    with pytest.raises(SystemExit, match="no meaning"):
        cli.main(["--env", "multi_cloud", "--flash-attn",
                  "--run-root", str(tmp_path)])
    with pytest.raises(SystemExit, match="batch-minor"):
        cli.main(["--env", "cluster_set", "--flash-attn", "--fused-set",
                  "--run-root", str(tmp_path)])
    with pytest.raises(SystemExit, match="multiple of 128"):
        cli.main(["--env", "cluster_set", "--flash-attn",
                  "--num-nodes", "64", "--run-root", str(tmp_path)])
    with pytest.raises(SystemExit, match="ring attention"):
        cli.main(["--env", "cluster_set", "--flash-attn",
                  "--num-nodes", "256", "--sp", "2", "--dp", "1",
                  "--run-root", str(tmp_path)])


def test_flash_attn_wires_through_bundle_and_meta():
    """make_bundle_and_net(flash_attn=True) builds the flash policy (the
    path evaluate.py takes for flash-trained checkpoint meta)."""
    from rl_scheduler_tpu.agent.ppo import PPOTrainConfig
    from rl_scheduler_tpu.agent.train_ppo import make_bundle_and_net

    _, net = make_bundle_and_net("cluster_set", PPOTrainConfig(),
                                 num_nodes=128, flash_attn=True)
    assert net.attn_impl == "flash"


def test_flash_attn_policy_field_validation():
    """The policy itself refuses bad attn_impl combinations and node
    counts at trace time (covers programmatic construction, not just
    the CLI)."""
    import jax
    import jax.numpy as jnp

    from rl_scheduler_tpu.models import SetTransformerPolicy

    with pytest.raises(ValueError, match="unknown attn_impl"):
        SetTransformerPolicy(dim=32, depth=1, attn_impl="fhash").init(
            jax.random.PRNGKey(0), jnp.zeros((1, 128, 6)))
    with pytest.raises(ValueError, match="cannot combine"):
        SetTransformerPolicy(dim=32, depth=1, attn_impl="flash",
                             axis_name="sp").init(
            jax.random.PRNGKey(0), jnp.zeros((1, 128, 6)))
    with pytest.raises(ValueError, match="multiple of 128"):
        SetTransformerPolicy(dim=32, depth=1, attn_impl="flash").init(
            jax.random.PRNGKey(0), jnp.zeros((1, 64, 6)))


def test_flash_attn_parity():
    """The flash wrapper computes the same attention as flax's dense
    attention — on EVERY platform, no skips.

    On TPU the real Pallas flash kernel runs end to end through the
    policy (chip-verified at 1.1e-5 logits). On CPU the kernel has no
    lowering in this JAX version (no interpret= plumbing in
    jax.experimental.pallas.ops.tpu.flash_attention), so the wrapper's
    own logic — the flax [..., seq, heads, head_dim] <-> kernel
    [batch, heads, seq, head_dim] fold/unfold, the scale, and the
    batch-dim flattening — is pinned against a dense reference injected
    through the kernel_fn seam. That layout logic is exactly what a
    refactor can silently break while the chip job is queued. Platform
    is checked INSIDE the body — a skipif decorator would initialize the
    JAX backend at collection time for every pytest invocation touching
    this file."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rl_scheduler_tpu.models import SetTransformerPolicy

    if jax.devices()[0].platform != "cpu":
        dense_net = SetTransformerPolicy(dim=64, depth=2)
        flash_net = SetTransformerPolicy(dim=64, depth=2, attn_impl="flash")
        obs = jax.random.uniform(jax.random.PRNGKey(1), (4, 128, 6))
        params = dense_net.init(jax.random.PRNGKey(2), obs)
        l0, v0 = jax.jit(dense_net.apply)(params, obs)
        l1, v1 = jax.jit(flash_net.apply)(params, obs)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                                   rtol=2e-2, atol=2e-2)
        np.testing.assert_allclose(np.asarray(v1), np.asarray(v0),
                                   rtol=2e-2, atol=2e-2)
        return

    from rl_scheduler_tpu.ops.flash_attention import (
        make_flax_flash_attention_fn,
    )

    def ref_kernel(q, k, v, sm_scale):
        # Dense exact attention in the KERNEL's [batch, heads, seq, dim]
        # convention — what the Pallas kernel computes blockwise.
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm_scale
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)

    attn_fn = make_flax_flash_attention_fn(kernel_fn=ref_kernel)
    key = jax.random.PRNGKey(3)
    kq, kk, kv = jax.random.split(key, 3)
    # flax layout [batch, seq, heads, head_dim], multi-head, N=128 (the
    # wrapper's block-size constraint boundary).
    q = jax.random.normal(kq, (4, 128, 2, 32))
    k = jax.random.normal(kk, (4, 128, 2, 32))
    v = jax.random.normal(kv, (4, 128, 2, 32))
    out = attn_fn(q, k, v)
    assert out.shape == q.shape
    # Reference computed directly in the flax layout.
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(32.0)
    expect = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=1e-5, atol=1e-5)

    # Leading batch dims beyond one must fold and unfold faithfully.
    q5 = q.reshape(2, 2, 128, 2, 32)
    out5 = attn_fn(q5, k.reshape(2, 2, 128, 2, 32),
                   v.reshape(2, 2, 128, 2, 32))
    np.testing.assert_allclose(np.asarray(out5.reshape(out.shape)),
                               np.asarray(out), rtol=1e-6, atol=1e-6)

    # The wrapper's refusals fire before the kernel on every platform.
    with pytest.raises(ValueError, match="multiple of 128"):
        attn_fn(q[:, :64], k[:, :64], v[:, :64])
    with pytest.raises(ValueError, match="not supported"):
        attn_fn(q, k, v, dropout_rate=0.5)


def test_fused_set_block_validation(tmp_path):
    """--fused-set-block guards: cluster_set only, fleet N only (>= 32,
    multiple of 8), single-head, exclusive with the other set fast
    paths and with --sp — each refused with an actionable message
    BEFORE any device work."""
    from rl_scheduler_tpu.agent import train_ppo as cli

    with pytest.raises(SystemExit, match="no meaning"):
        cli.main(["--env", "multi_cloud", "--fused-set-block",
                  "--run-root", str(tmp_path)])
    with pytest.raises(SystemExit, match="fleet"):
        # Default N=8 is below the fleet floor — the regime where the
        # hand-fused kernel measured 3-5x WORSE (docs/roofline.md).
        cli.main(["--env", "cluster_set", "--fused-set-block",
                  "--run-root", str(tmp_path)])
    with pytest.raises(SystemExit, match="fleet"):
        cli.main(["--env", "cluster_set", "--fused-set-block",
                  "--num-nodes", "36", "--run-root", str(tmp_path)])
    with pytest.raises(SystemExit, match="pick one"):
        cli.main(["--env", "cluster_set", "--fused-set-block",
                  "--fused-set", "--num-nodes", "64",
                  "--run-root", str(tmp_path)])
    with pytest.raises(SystemExit, match="drop one"):
        cli.main(["--env", "cluster_set", "--fused-set-block",
                  "--flash-attn", "--num-nodes", "128",
                  "--run-root", str(tmp_path)])
    with pytest.raises(SystemExit, match="single-head"):
        cli.main(["--env", "cluster_set", "--fused-set-block",
                  "--num-heads", "4", "--num-nodes", "64",
                  "--run-root", str(tmp_path)])
    with pytest.raises(SystemExit, match="ring attention"):
        cli.main(["--env", "cluster_set", "--fused-set-block",
                  "--num-nodes", "64", "--sp", "2", "--dp", "1",
                  "--run-root", str(tmp_path)])


def test_num_nodes_rejected_for_flat_envs(tmp_path):
    from rl_scheduler_tpu.agent import train_ppo as cli

    with pytest.raises(SystemExit, match="node axis"):
        cli.main(["--env", "multi_cloud", "--num-nodes", "64",
                  "--run-root", str(tmp_path)])


def test_num_nodes_floor(tmp_path):
    from rl_scheduler_tpu.agent import train_ppo as cli

    with pytest.raises(SystemExit, match="at least 2"):
        cli.main(["--env", "cluster_set", "--num-nodes", "1",
                  "--run-root", str(tmp_path)])
    with pytest.raises(SystemExit, match="at least 4"):
        cli.main(["--env", "cluster_graph", "--num-nodes", "3",
                  "--run-root", str(tmp_path)])


def test_sp_divisibility_uses_actual_node_count(tmp_path):
    from rl_scheduler_tpu.agent import train_ppo as cli

    with pytest.raises(SystemExit, match=r"node axis \(12\)"):
        cli.main(["--env", "cluster_set", "--num-nodes", "12", "--sp", "8",
                  "--dp", "1", "--run-root", str(tmp_path)])


def test_fleet_cli_roundtrip_meta_resume_evaluate(tmp_path):
    """Train at N=12, meta records it, mismatched resume refuses, and
    evaluation rebuilds the env at the trained N."""
    from rl_scheduler_tpu.agent import evaluate as eval_cli
    from rl_scheduler_tpu.agent import train_ppo as cli
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    common = [
        "--env", "cluster_set", "--preset", "quick", "--num-envs", "4",
        "--rollout-steps", "8", "--minibatch-size", "16",
        "--checkpoint-every", "1", "--run-root", str(tmp_path),
        "--run-name", "fleet12",
    ]
    cli.main(common + ["--iterations", "1", "--num-nodes", "12"])
    mgr = CheckpointManager(tmp_path / "fleet12")
    assert mgr.restore_meta(1)["num_nodes"] == 12
    mgr.close()
    with pytest.raises(SystemExit, match="num-nodes 12"):
        cli.main(common + ["--iterations", "2", "--resume"])
    report = eval_cli.main([
        "--run", str(tmp_path / "fleet12"), "--episodes", "4",
        "--results-dir", str(tmp_path / "results"),
    ])
    assert report.env == "cluster_set"
    # 12-node episodes: the cloud split covers both halves of the node set
    assert len(report.cloud_fractions) == 2
