"""graftlens (PR 12): per-phase decision-path spans, SLO wiring, and the
synthetic-traffic exclusion — at the ExtenderPolicy level and over real
HTTP. Pool-wide aggregation is pinned in tests/test_pool.py, the SLO
math in tests/test_slo.py, and the report in tests/test_decisionview.py.
"""

import json
import threading
import time
import urllib.request

import pytest

from rl_scheduler_tpu.scheduler.extender import (
    PHASES,
    ExtenderPolicy,
    LatencyStats,
    build_policy,
    make_server,
    phase_metric_lines,
    slo_metric_lines,
)
from rl_scheduler_tpu.scheduler.policy_backend import GreedyBackend
from rl_scheduler_tpu.scheduler.slo import SloConfig, SloTracker
from rl_scheduler_tpu.scheduler.telemetry import RandomCpu, TableTelemetry
from rl_scheduler_tpu.scheduler.tracelog import TraceLog, iter_trace
from rl_scheduler_tpu.utils.faults import FaultPlan


def _policy(spans=True, slo=None, trace=None, backend=None):
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))
    policy = ExtenderPolicy(backend or GreedyBackend(), telemetry,
                            spans=spans, slo=slo)
    policy.trace = trace
    return policy


def _args(i=0, n=4):
    return {"nodenames": [f"{'aws' if j % 2 else 'azure'}-n{i}-{j}"
                          for j in range(n)], "pod": {}}


class _FaultableBackend:
    """The chaos-suite idiom: a backend whose decide consults the
    backend.decide fault site (utils/faults.py)."""

    name = "faultable"

    def __init__(self, plan):
        self.plan = plan

    def decide(self, obs):
        self.plan.check("backend.decide", RuntimeError)
        return 0, __import__("numpy").zeros(2, "float32")


class _SlowBackend:
    name = "slow"

    def __init__(self, sleep_s=0.02):
        self.sleep_s = sleep_s

    def decide(self, obs):
        time.sleep(self.sleep_s)
        return 0, __import__("numpy").zeros(2, "float32")


# ------------------------------------------------------------------- spans


def test_phases_recorded_per_request_and_reconcile():
    """Every served request lands one sample in each phase's histogram,
    and observe+forward explain >=90% of the end-to-end decide mean (the
    decomposition acceptance bar)."""
    policy = _policy()
    for i in range(20):
        policy.filter(_args(i)) if i % 2 else policy.prioritize(_args(i))
    stats = policy.statistics()
    assert set(stats["phases"]) == set(PHASES)
    for phase in PHASES:
        assert stats["phases"][phase]["lifetime_count"] == 20
    e2e = stats["latency"]["lifetime_mean_ms"]
    inner = (stats["phases"]["observe"]["lifetime_mean_ms"]
             + stats["phases"]["forward"]["lifetime_mean_ms"])
    assert inner >= 0.9 * e2e
    # The full phase sum covers the decide window and the handler edges.
    total = sum(stats["phases"][p]["lifetime_mean_ms"] for p in PHASES)
    assert total >= 0.9 * e2e


def test_spans_off_records_nothing_and_omits_stats_section():
    policy = _policy(spans=False)
    for i in range(5):
        policy.filter(_args(i))
    stats = policy.statistics()
    assert "phases" not in stats
    assert all(s.histogram()[2] == 0 for s in policy.phase_stats.values())
    # The end-to-end histogram still records (spans are additive only).
    assert policy.stats.histogram()[2] == 5
    assert "_phase_latency_seconds" not in policy.metrics_text()


def test_fail_open_drops_partial_spans():
    """A failing decide keeps the phase histograms aligned with the
    end-to-end histogram: neither records the fail-open request."""
    plan = FaultPlan(rates={"backend.decide": 1.0})
    policy = _policy(backend=_FaultableBackend(plan))
    policy.filter(_args(0))
    assert plan.fired["backend.decide"] == 1
    assert policy.stats.histogram()[2] == 0
    assert all(s.histogram()[2] == 0 for s in policy.phase_stats.values())


def test_stats_reset_never_rewinds_phase_lifetime_counters():
    policy = _policy()
    for i in range(6):
        policy.filter(_args(i))
    before = {p: policy.phase_stats[p].histogram()[2] for p in PHASES}
    policy.reset_stats()
    stats = policy.statistics()
    for phase in PHASES:
        assert stats["phases"][phase]["count"] == 0  # ring cleared
        assert stats["phases"][phase]["lifetime_count"] == before[phase]


def test_trace_records_carry_span_breakdown(tmp_path):
    policy = _policy(trace=TraceLog(tmp_path))
    policy.filter(_args(0))
    policy.prioritize(_args(1))
    policy.trace.close()
    records = list(iter_trace(tmp_path))
    assert len(records) == 2
    for record in records:
        spans = record["spans"]
        assert set(spans) <= set(PHASES)
        for phase in ("parse", "observe", "forward", "marshal", "trace"):
            assert spans[phase] >= 0.0
        # The span sum is consistent with the record's own latency.
        assert sum(spans.values()) <= record["latency_ms"] + 1.0


def test_phase_metric_lines_exposition():
    policy = _policy()
    for i in range(4):
        policy.filter(_args(i))
    text = policy.metrics_text()
    assert "# TYPE rl_scheduler_extender_phase_latency_seconds histogram" \
        in text
    for phase in PHASES:
        assert (f'rl_scheduler_extender_phase_latency_seconds_count'
                f'{{phase="{phase}"}} 4') in text
    # The shared helper is what produced those lines.
    hists = {p: s.histogram() for p, s in policy.phase_stats.items()}
    for line in phase_metric_lines("rl_scheduler_extender", hists):
        assert line in text


# ------------------------------------------------------ probe exclusion


def test_warmup_probe_excluded_from_histograms_and_slo(tmp_path):
    """The satellite pin: probe decisions appear ONLY in the trace
    (endpoint=probe) — never in the end-to-end histogram, the phase
    histograms, or the SLO counters a canary gate reads."""
    slo = SloTracker(SloConfig(p99_ms=10.0, availability=0.999))
    policy = _policy(slo=slo, trace=TraceLog(tmp_path))
    for i in range(3):
        policy.filter(_args(i))
    for _ in range(5):
        out = policy.warmup_probe()
        assert out["decided"]
    assert policy.stats.histogram()[2] == 3
    for phase in PHASES:
        assert policy.phase_stats[phase].histogram()[2] == 3
    assert slo.snapshot()["lifetime"]["requests_total"] == 3
    policy.trace.close()
    records = list(iter_trace(tmp_path))
    assert sum(1 for r in records if r["endpoint"] == "probe") == 5
    assert len(records) == 8  # every decision still traced


def test_failed_probe_does_not_burn_availability():
    plan = FaultPlan(rates={"backend.decide": 1.0})
    slo = SloTracker(SloConfig(availability=0.999))
    policy = _policy(slo=slo, backend=_FaultableBackend(plan))
    out = policy.warmup_probe()
    assert not out["decided"]
    assert slo.snapshot()["lifetime"] == {
        "requests_total": 0, "latency_bad_total": 0, "fail_open_total": 0}
    # The gate still sees the fail-open through the policy counter.
    assert policy.statistics()["fail_open_total"] == 1


# --------------------------------------------------------------- SLO wiring


def test_latency_fault_burns_slo_and_degrades_health():
    """The acceptance drill: a latency fault (slow backend vs a tight
    objective) flips the burn gauge on /metrics and degrades /healthz."""
    slo = SloTracker(SloConfig(p99_ms=1.0))  # 1 ms bar, 20 ms backend
    policy = _policy(slo=slo, backend=_SlowBackend(0.02))
    assert policy.health()["status"] == "ok"
    for i in range(20):
        policy.filter(_args(i))
    health = policy.health()
    assert health["status"] == "degraded"
    assert health["slo"] == {"degraded": True, "burning": ["latency"]}
    text = policy.metrics_text()
    assert "rl_scheduler_extender_slo_degraded 1" in text
    assert 'rl_scheduler_extender_slo_burning{objective="latency"} 1' \
        in text
    assert "rl_scheduler_extender_slo_latency_bad_total 20" in text
    for line in slo_metric_lines("rl_scheduler_extender", slo.snapshot()):
        assert line in text


def test_injected_backend_fault_burns_availability():
    """The existing utils/faults.py site drives the availability burn:
    every decide fails open, the objective burns, /healthz degrades."""
    plan = FaultPlan(rates={"backend.decide": 1.0})
    slo = SloTracker(SloConfig(availability=0.999))
    policy = _policy(slo=slo, backend=_FaultableBackend(plan))
    for i in range(20):
        policy.filter(_args(i))
    assert plan.fired["backend.decide"] >= 1
    snap = slo.snapshot()
    assert snap["objectives"]["availability"]["burning"]
    assert policy.health()["status"] == "degraded"


def test_build_policy_arms_slo_and_no_spans(tmp_path):
    policy = build_policy(backend="greedy", spans=False, slo_p99_ms=5.0,
                          slo_avail=0.999)
    assert not policy.spans_enabled
    assert policy.slo is not None
    assert policy.slo.config.p99_ms == 5.0
    with pytest.raises(ValueError):
        build_policy(backend="greedy", slo_avail=2.0)  # refused pre-traffic


# ------------------------------------------------------------------ HTTP


@pytest.mark.parametrize("front", ["threading", "asyncio"])
def test_http_stats_and_metrics_carry_phases_and_slo(front):
    """Parameterized over BOTH data-plane fronts (graftfront): the
    phase/SLO surface is the agreement spec the asyncio front must
    serve bit-for-bit."""
    slo = SloTracker(SloConfig(p99_ms=1000.0, availability=0.999))
    policy = _policy(slo=slo)
    srv = make_server(policy, host="127.0.0.1", port=0, front=front)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        port = srv.server_address[1]
        for i in range(4):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/filter",
                data=json.dumps(_args(i)).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as resp:
                resp.read()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats", timeout=5) as resp:
            stats = json.loads(resp.read())
        assert set(stats["phases"]) == set(PHASES)
        assert stats["phases"]["forward"]["lifetime_count"] == 4
        assert not stats["slo"]["degraded"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as resp:
            text = resp.read().decode()
        assert 'phase_latency_seconds_count{phase="forward"} 4' in text
        assert "slo_degraded 0" in text
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5) as resp:
            assert json.loads(resp.read())["slo"] == {
                "degraded": False, "burning": []}
    finally:
        srv.shutdown()
        srv.server_close()
