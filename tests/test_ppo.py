"""PPO trainer: shapes, determinism, and convergence on the shipped table."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, make_ppo, ppo_train
from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env import core as env_core

SMOKE_CFG = PPOTrainConfig(
    num_envs=16,
    rollout_steps=99,
    minibatch_size=512,
    num_epochs=4,
    lr=3e-3,
    gamma=0.99,
    hidden=(64, 64),
    entropy_coeff=0.01,
)


@pytest.fixture(scope="module")
def env_params():
    return env_core.make_params(EnvConfig())


def test_update_shapes_and_metrics(env_params):
    init_fn, update_fn, _ = make_ppo(env_params, SMOKE_CFG)
    runner = init_fn(jax.random.PRNGKey(0))
    runner, metrics = jax.jit(update_fn)(runner)
    for k in ("episode_reward_mean", "policy_loss", "value_loss", "entropy", "approx_kl"):
        assert np.isfinite(float(metrics[k])), k
    assert int(runner.update_idx) == 1
    # one full episode per env completed during a 99-step rollout
    assert float(metrics["episodes_completed"]) == SMOKE_CFG.num_envs


def test_train_deterministic(env_params):
    cfg = SMOKE_CFG
    _, h1 = ppo_train(env_params, cfg, 2, seed=123)
    _, h2 = ppo_train(env_params, cfg, 2, seed=123)
    assert h1[-1]["episode_reward_mean"] == pytest.approx(
        h2[-1]["episode_reward_mean"], rel=1e-6
    )


def test_sgd_unroll_matches_scan(env_params):
    """sgd_unroll only changes compilation, never the math."""
    import dataclasses

    _, h1 = ppo_train(env_params, SMOKE_CFG, 2, seed=11)
    cfg_u = dataclasses.replace(SMOKE_CFG, sgd_unroll=4)
    _, h2 = ppo_train(env_params, cfg_u, 2, seed=11)
    for a, b in zip(h1, h2):
        assert a["policy_loss"] == pytest.approx(b["policy_loss"], rel=1e-4)
        assert a["reward_mean"] == pytest.approx(b["reward_mean"], rel=1e-5)


def test_fused_dispatch_matches_sequential(env_params):
    """updates_per_dispatch is pure dispatch plumbing: the scanned
    iterations must reproduce the one-by-one metrics exactly."""
    _, h_seq = ppo_train(env_params, SMOKE_CFG, 4, seed=7)
    _, h_fused = ppo_train(env_params, SMOKE_CFG, 4, seed=7,
                           updates_per_dispatch=2)
    assert len(h_fused) == 4
    for a, b in zip(h_seq, h_fused):
        assert a["policy_loss"] == pytest.approx(b["policy_loss"], rel=1e-5)
        assert a["reward_mean"] == pytest.approx(b["reward_mean"], rel=1e-6)
    with pytest.raises(ValueError, match="updates_per_dispatch"):
        ppo_train(env_params, SMOKE_CFG, 4, debug_checks=True,
                  updates_per_dispatch=2)


def greedy_row_accuracy(runner, env_params, hidden) -> float:
    """Fraction of table rows where the learned greedy action matches the
    per-row optimum (argmin of 0.6*cost + 0.4*latency)."""
    from rl_scheduler_tpu.models import ActorCritic

    net = ActorCritic(num_actions=env_core.NUM_ACTIONS, hidden=hidden)
    table = np.asarray(
        jnp.concatenate([env_params.costs, env_params.latencies], axis=1)
    )
    obs = np.concatenate([table, np.full((len(table), 2), 0.45, np.float32)], axis=1)
    logits, _ = net.apply(runner.params, jnp.asarray(obs))
    greedy = np.asarray(jnp.argmax(logits, axis=-1))
    weighted = 0.6 * table[:, :2] + 0.4 * table[:, 2:4]
    return float((greedy == np.argmin(weighted, axis=1)).mean())


def test_ppo_converges_to_optimal_policy(env_params):
    """After a short run the greedy policy must pick the per-row optimal cloud
    (argmin of 0.6*cost + 0.4*latency) on ~all rows, beating both baselines.

    This is the reference's end-to-end claim (train_and_compare.py) as a
    test: the env is exactly learnable from the observation.

    Pinned to the scan rollout — this test predates (and now anchors) the
    sequential path; tests/test_open_loop.py covers the open-loop path
    with its own convergence run.
    """
    import dataclasses

    cfg = dataclasses.replace(SMOKE_CFG, rollout_impl="scan")
    runner, history = ppo_train(env_params, cfg, 30, seed=0)

    from rl_scheduler_tpu.models import ActorCritic

    net = ActorCritic(num_actions=2, hidden=SMOKE_CFG.hidden)
    table = np.asarray(
        jnp.concatenate([env_params.costs, env_params.latencies], axis=1)
    )
    obs = np.concatenate([table, np.full((len(table), 2), 0.45, np.float32)], axis=1)
    logits, _ = net.apply(runner.params, jnp.asarray(obs))
    greedy = np.asarray(jnp.argmax(logits, axis=-1))
    weighted = 0.6 * table[:, :2] + 0.4 * table[:, 2:4]
    optimal = np.argmin(weighted, axis=1)
    accuracy = greedy_row_accuracy(runner, env_params, SMOKE_CFG.hidden)
    assert accuracy >= 0.95, f"greedy policy only matches optimum on {accuracy:.0%} of rows"

    # episode reward improved substantially over training
    first, last = history[0]["episode_reward_mean"], history[-1]["episode_reward_mean"]
    assert last > first

    # beats the cost-greedy baseline (which ignores latency): compare episode
    # cost under the corrected reward (higher reward = lower weighted cost)
    greedy_cost = weighted[np.arange(99), optimal[:99]].sum()
    baseline_cost = weighted[
        np.arange(99), np.argmin(table[:99, :2], axis=1)
    ].sum()
    learned_cost = weighted[np.arange(99), greedy[:99]].sum()
    assert learned_cost <= baseline_cost + 1e-3
    assert learned_cost <= greedy_cost * 1.05


def test_ppo_resume_continues_training(env_params, tmp_path):
    """restore=(tree, step) resumes learning state; CLI --resume round-trips
    through Orbax checkpoints (SURVEY.md §5.4 — capability the reference lacks)."""
    cfg = PPOTrainConfig(
        num_envs=8, rollout_steps=20, minibatch_size=64, num_epochs=2,
        hidden=(16, 16),
    )
    runner_a, _ = ppo_train(env_params, cfg, 2, seed=7)
    tree = {"params": runner_a.params, "opt_state": runner_a.opt_state}
    runner_b, history_b = ppo_train(env_params, cfg, 4, seed=7, restore=(tree, 2))
    assert len(history_b) == 2  # only iterations 3 and 4 ran
    assert int(runner_b.update_idx) == 4

    # Resumed run matches an uninterrupted one's learning trajectory in
    # param space (same seed => same rollout randomness after restore point
    # is NOT guaranteed, so compare against loss finiteness + progression).
    assert np.isfinite(history_b[-1]["policy_loss"])


def test_train_cli_resume_roundtrip(tmp_path):
    from rl_scheduler_tpu.agent import train_ppo as cli
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    common = [
        "--preset", "quick", "--num-envs", "8", "--rollout-steps", "20",
        "--minibatch-size", "64", "--hidden", "16,16",
        "--run-root", str(tmp_path), "--run-name", "resume_test",
        "--checkpoint-every", "1",
    ]
    cli.main(common + ["--iterations", "2"])
    mgr = CheckpointManager(tmp_path / "resume_test")
    assert mgr.latest_step() == 2
    mgr.close()

    cli.main(common + ["--iterations", "4", "--resume"])
    mgr = CheckpointManager(tmp_path / "resume_test")
    assert mgr.latest_step() == 4
    mgr.close()


def test_bfloat16_compute_dtype_close_to_f32(env_params):
    """compute_dtype='bfloat16' keeps params and heads f32; outputs track
    the f32 network within bf16 tolerance."""
    import jax.numpy as jnp

    from rl_scheduler_tpu.models import ActorCritic

    obs = jax.random.uniform(jax.random.PRNGKey(0), (64, 6))
    f32_net = ActorCritic(num_actions=2, hidden=(32, 32))
    bf_net = ActorCritic(num_actions=2, hidden=(32, 32), dtype=jnp.bfloat16)
    params = f32_net.init(jax.random.PRNGKey(1), obs)

    logits32, value32 = f32_net.apply(params, obs)
    logits16, value16 = bf_net.apply(params, obs)
    assert logits16.dtype == jnp.float32  # heads stay f32
    np.testing.assert_allclose(
        np.asarray(logits16), np.asarray(logits32), atol=0.05
    )
    np.testing.assert_allclose(
        np.asarray(value16), np.asarray(value32), atol=0.05
    )

    cfg = PPOTrainConfig(num_envs=8, rollout_steps=20, minibatch_size=64,
                         num_epochs=2, hidden=(16, 16),
                         compute_dtype="bfloat16")
    _, history = ppo_train(env_params, cfg, 2, seed=0)
    assert np.isfinite(history[-1]["policy_loss"])


def test_unknown_compute_dtype_raises(env_params):
    cfg = PPOTrainConfig(num_envs=4, rollout_steps=4, minibatch_size=16,
                         compute_dtype="bf16")
    with pytest.raises(ValueError, match="compute_dtype"):
        make_ppo(env_params, cfg)


def test_block_shuffle_active_convergence(env_params):
    """At scales where the tile-aligned block shuffle engages
    (minibatch >= 1024 blocks), training must converge exactly like the
    per-sample shuffle. 128 envs x 99 steps, minibatch 8192 -> 1024 blocks."""
    from rl_scheduler_tpu.agent.ppo import effective_shuffle_block

    cfg = PPOTrainConfig(num_envs=128, rollout_steps=99, minibatch_size=8192,
                         num_epochs=4, lr=3e-3, hidden=(64, 64),
                         entropy_coeff=0.01)
    # The exact runtime gate, not a proxy: the block path must be ON here.
    assert effective_shuffle_block(cfg) == cfg.shuffle_block_size > 1
    runner, _ = ppo_train(env_params, cfg, 25, seed=0)
    agreement = greedy_row_accuracy(runner, env_params, cfg.hidden)
    assert agreement >= 0.95, f"only {agreement:.0%} of rows optimal"


def test_block_shuffle_gate_requires_env_divisibility():
    """Blocks must not straddle timesteps: few envs -> exact shuffle."""
    from rl_scheduler_tpu.agent.ppo import effective_shuffle_block

    cfg = PPOTrainConfig(num_envs=4, rollout_steps=2048, minibatch_size=8192,
                         num_epochs=1, hidden=(8, 8))
    assert effective_shuffle_block(cfg) == 1
