"""driftview (tools/driftview): the drift report + retrain-trigger gate.

The package duplicates ``scheduler/drift.reference_fingerprint`` to
stay stdlib-only; the cross-check test here pins the two
implementations byte-equal (and the two REFERENCE_SCHEMA constants
equal) so they can never drift apart silently. The ``--check`` gates
are pinned one by one — a missing drift section fails loudly, a
drifting stream exits 2, a zero-data stream is exempt from
``require_reference``, a stale ``--reference`` file is visible as a
fingerprint mismatch — and the checked-in fixture under
``tests/fixtures/driftview/`` keeps ``make drift-report`` green and
off-network in tier-1.
"""

import copy
import json
from pathlib import Path

import pytest

import tools.driftview as driftview
from tools.driftview import (
    REFERENCE_SCHEMA,
    build_report,
    check_drift,
    format_report,
    load_budgets,
    load_reference,
    load_stats,
    reference_fingerprint,
    summarize_trace,
)
from tools.driftview.__main__ import main as driftview_main
from rl_scheduler_tpu.scheduler import drift as drift_mod
from rl_scheduler_tpu.scheduler.tracelog import TraceLog, decision_record

FIXTURES = Path(__file__).resolve().parent / "fixtures" / "driftview"
BUDGETS = Path(__file__).resolve().parents[1] / "tools" / "driftview" \
    / "budgets.json"


def _reference(observations=6):
    tracker = drift_mod.DriftTracker(drift_mod.DriftConfig(),
                                     clock=lambda: 1000.0)
    for i in range(observations):
        tracker.observe_decision("aws" if i % 2 else "azure",
                                 0.1 * (i % 5), cost=0.3, latency=0.4)
    return tracker, drift_mod.build_reference(tracker.snapshot(),
                                              source="test")


def test_fingerprint_cross_check_pins_both_implementations():
    """driftview.reference_fingerprint must equal
    scheduler/drift.reference_fingerprint on the same reference — the
    stdlib duplicate and the scheduler original share one
    canonicalization, and the schema constants agree."""
    assert REFERENCE_SCHEMA == drift_mod.REFERENCE_SCHEMA
    _, ref = _reference()
    assert reference_fingerprint(ref) == ref["fingerprint"]
    assert reference_fingerprint(ref) \
        == drift_mod.reference_fingerprint(ref)
    # provenance fields stay outside the hash in BOTH implementations
    relabeled = dict(ref, source="elsewhere")
    assert reference_fingerprint(relabeled) \
        == drift_mod.reference_fingerprint(relabeled) \
        == ref["fingerprint"]


def test_load_reference_refuses_tamper(tmp_path):
    _, ref = _reference()
    path = tmp_path / "ref.json"
    drift_mod.save_reference(str(path), ref)
    assert load_reference(path) == ref

    tampered = copy.deepcopy(ref)
    tampered["streams"]["score"]["counts"][0] += 1
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(tampered))
    with pytest.raises(ValueError, match="fingerprint"):
        load_reference(bad)
    notref = tmp_path / "notref.json"
    notref.write_text(json.dumps({"hello": 1}))
    with pytest.raises(ValueError, match="schema"):
        load_reference(notref)


def test_load_stats_file_and_checked_in_budgets(tmp_path):
    body = {"backend": "greedy", "drift": {"drifting": []}}
    path = tmp_path / "stats.json"
    path.write_text(json.dumps(body))
    assert load_stats(str(path)) == body
    budgets = load_budgets(BUDGETS)
    assert budgets["schema_version"] == 1
    assert budgets["require_reference"] is True
    assert budgets["allow_drifting"] is False
    assert 0.0 < budgets["shadow_agreement_floor"] <= 1.0
    assert budgets["shadow_floor_min_scored"] >= 1


def test_build_and_format_report_sections():
    tracker, ref = _reference(observations=10)
    tracker.set_reference(ref)
    stats = {
        "drift": tracker.snapshot(),
        "shadow": {"submitted_total": 5, "scored_total": 4,
                   "dropped_total": 1, "errors_total": 0,
                   "agreements_total": 4, "agreement_rate": 1.0,
                   "score_delta": {"mean": -0.002}},
    }
    report = build_report(
        stats=stats, reference=ref,
        trace_summary={"generations": {"0": 7}, "served_records": 7,
                       "synthetic_excluded": 2, "fail_opens_excluded": 1})
    drift = report["drift"]
    assert drift["reference_loaded"] is True
    assert drift["reference_fingerprint"] == ref["fingerprint"]
    assert drift["streams"]["score"]["status"] == "ok"
    assert drift["streams"]["score"]["lifetime_count"] == 10
    assert report["shadow"]["agreement_rate"] == 1.0
    assert report["reference_file"]["streams"] \
        == sorted(ref["streams"])

    text = format_report(report)
    assert "== drift (generation 0) ==" in text
    assert "== shadow ==" in text
    assert "== reference file ==" in text
    assert "== trace ==" in text
    assert ref["fingerprint"][:12] in text
    assert "DRIFTING" not in text
    assert check_drift(report, load_budgets(BUDGETS)) == []

    bare = build_report(stats=None, reference=None, trace_summary=None)
    assert format_report(bare) == ""


def _report(drifting=(), statuses=None, lifetime=50, ref_fp="f" * 64,
            file_fp=None, mixed=False, shadow=None):
    statuses = statuses or {}
    streams = {}
    for name in ("score", "action", "cost", "latency"):
        streams[name] = {
            "status": statuses.get(name, "ok"),
            "psi": {"fast": 0.01, "slow": 0.01},
            "ks": {"fast": 0.01, "slow": 0.01},
            "windows": {"fast": {"count": lifetime, "sufficient": True},
                        "slow": {"count": lifetime, "sufficient": True}},
            "drifting": name in drifting,
            "lifetime_count": lifetime,
        }
    report = {"schema_version": 1, "drift": {
        "generation": 0, "streams": streams,
        "drifting": sorted(drifting), "reference_loaded": bool(ref_fp),
        "reference_fingerprint": ref_fp, "reference_generation": 0,
        "reference_mixed": mixed,
    }}
    if file_fp is not None:
        report["reference_file"] = {"fingerprint": file_fp,
                                    "generation": 0, "streams": []}
    if shadow is not None:
        report["shadow"] = shadow
    return report


def test_check_drift_gates_one_by_one():
    budgets = load_budgets(BUDGETS)

    missing = check_drift({"schema_version": 1}, budgets)
    assert len(missing) == 1 and "no drift section" in missing[0]

    assert check_drift(_report(), budgets) == []

    drifting = check_drift(_report(drifting=("cost",)), budgets)
    assert len(drifting) == 1 and "`cost` is DRIFTING" in drifting[0]
    assert check_drift(_report(drifting=("cost",)),
                       dict(budgets, allow_drifting=True)) == []

    ungraded = check_drift(
        _report(statuses={"cost": "no_reference"}), budgets)
    assert len(ungraded) == 1 and "`cost`" in ungraded[0]
    skewed = check_drift(
        _report(statuses={"cost": "generation_mismatch"}), budgets)
    assert "generation_mismatch" in skewed[0]
    # a stream the deployment never feeds is NOT gradable: exempt
    assert check_drift(
        _report(statuses={"cost": "no_reference"}, lifetime=0),
        budgets) == []
    assert check_drift(
        _report(statuses={"cost": "no_reference"}),
        dict(budgets, require_reference=False)) == []

    stale = check_drift(_report(file_fp="a" * 64), budgets)
    assert len(stale) == 1 and "reference mismatch" in stale[0]
    assert check_drift(_report(file_fp="f" * 64), budgets) == []

    torn = check_drift(_report(mixed=True), budgets)
    assert len(torn) == 1 and "disagree" in torn[0]

    low = {"scored_total": 30, "agreement_rate": 0.5}
    floored = check_drift(_report(shadow=low), budgets)
    assert len(floored) == 1 and "agreement" in floored[0]
    # the floor binds only once enough was scored
    assert check_drift(
        _report(shadow={"scored_total": 3, "agreement_rate": 0.0}),
        budgets) == []
    # per-run override beats the budgets file
    assert check_drift(_report(shadow=low), budgets,
                       shadow_floor=0.25) == []


def test_summarize_trace_counts_synthetic_apart(tmp_path):
    log = TraceLog(tmp_path / "trace", prefix="w0-")

    def _rec(**kw):
        base = dict(endpoint="extender", family="cloud", backend="greedy",
                    candidates=2, chosen="aws", score=0.4, latency_ms=1.0)
        base.update(kw)
        assert log.append(decision_record(**base))

    _rec(generation=0)
    _rec(generation=0)
    _rec(generation=1)
    _rec(endpoint="probe")
    _rec(endpoint="shadow")
    _rec(fail_open=True, score=None, chosen=None)
    log.close()
    summary = summarize_trace(tmp_path / "trace")
    assert summary["generations"] == {"0": 2, "1": 1}
    assert summary["served_records"] == 3
    assert summary["synthetic_excluded"] == 2
    assert summary["fail_opens_excluded"] == 1


def test_fixture_gate_green_and_drifting_red(tmp_path, capsys):
    """``make drift-report``'s exact invocation against the checked-in
    fixture exits 0 (off-network tier-1 proof the gate plumbing works
    end to end); flipping one stream's verdict in the same body exits 2
    with the violation on stderr and in the JSON line."""
    assert driftview_main(["--stats", str(FIXTURES / "stats.json"),
                           "--reference",
                           str(FIXTURES / "reference.json"),
                           "--check", "--budgets", str(BUDGETS)]) == 0
    out, err = capsys.readouterr()
    assert "== drift" in out
    line = json.loads(out.strip().splitlines()[-1])
    assert line["report"] == "driftview"
    assert line["violations"] == []
    assert err == ""

    stats = json.loads((FIXTURES / "stats.json").read_text())
    stats["drift"]["scores"]["cost"]["drifting"] = True
    stats["drift"]["drifting"] = ["cost"]
    red = tmp_path / "drifting.json"
    red.write_text(json.dumps(stats))
    assert driftview_main(["--stats", str(red), "--reference",
                           str(FIXTURES / "reference.json"), "--check",
                           "--budgets", str(BUDGETS), "--json"]) == 2
    out, err = capsys.readouterr()
    assert "DRIFTING" in err
    line = json.loads(out.strip().splitlines()[-1])
    assert any("cost" in v for v in line["violations"])
    assert "== drift" not in out  # --json suppresses the tables

    with pytest.raises(SystemExit):
        driftview_main([])  # at least one input is required
    capsys.readouterr()
    assert driftview.SCHEMA_VERSION == 1
