"""End-to-end sequence-parallel PPO training on a dp x sp mesh.

The round-1 suite proved the ring-attention FORWARD matches dense
attention; these tests close the remaining gap: the PPO *gradient* with
the node axis sharded over ``sp`` must equal the unsharded gradient
(exercising the transposes of the logits all-gather and of the
``pool_axis_name`` pmean in ``models/heads.py``), and full sharded
training must track the unsharded run and learn.

Note on tolerances: parameters after an Adam step CANNOT be compared
tightly across the two paths — at near-zero initial gradients Adam's
update is ~``lr * sign(g)`` per component, so float-level (1e-7) forward
differences between ring and dense attention flip signs of near-zero
gradient components into O(lr) parameter differences. The gradient
comparison below is the precise equivalence check; the training-path
test asserts tight METRIC agreement instead (VERDICT r1 item 3 allows
either).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from rl_scheduler_tpu.agent.ppo import PPOTrainConfig
from rl_scheduler_tpu.env import cluster_set
from rl_scheduler_tpu.env.bundle import cluster_graph_bundle, cluster_set_bundle
from rl_scheduler_tpu.models import SetTransformerPolicy
from rl_scheduler_tpu.ops.losses import PPOLossConfig, categorical_log_prob, ppo_loss
from rl_scheduler_tpu.parallel import (
    make_data_parallel_ppo_bundle,
    make_mesh,
    make_seq_parallel_ppo,
)
from rl_scheduler_tpu.parallel.sharding import SeqParallelNet

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

CFG = PPOTrainConfig(
    num_envs=8,
    rollout_steps=8,
    minibatch_size=32,
    num_epochs=2,
    lr=1e-3,
)


def test_seq_parallel_ppo_gradients_match_unsharded():
    """The exact check: grad of the PPO loss through the node-sharded
    policy (ring attention + all-gathered logits + pmean'd value pool),
    pmean-reduced over sp, equals the unsharded gradient."""
    num_nodes, feat, batch = 8, cluster_set.NODE_FEAT, 16
    key = jax.random.PRNGKey(2)
    k_obs, k_par, k_act, k_adv, k_tgt = jax.random.split(key, 5)
    obs = jax.random.normal(k_obs, (batch, num_nodes, feat), jnp.float32)
    single = SetTransformerPolicy(dim=16, depth=2)
    params = single.init(k_par, obs)
    actions = jax.random.randint(k_act, (batch,), 0, num_nodes, jnp.int32)
    logits0, values0 = single.apply(params, obs)
    old_log_prob = categorical_log_prob(logits0, actions)
    advantages = jax.random.normal(k_adv, (batch,))
    targets = jax.random.normal(k_tgt, (batch,))
    loss_cfg = PPOLossConfig()

    def make_loss(net):
        def loss_fn(p):
            logits, values = net.apply(p, obs)
            loss, _ = ppo_loss(
                logits, values, actions, old_log_prob, values0,
                advantages, targets, loss_cfg,
            )
            return loss

        return loss_fn

    g_ref = jax.grad(make_loss(single))(params)

    mesh = make_mesh({"sp": 4})
    wrapped = SeqParallelNet(
        SetTransformerPolicy(dim=16, depth=2, axis_name="sp"), "sp", 4
    )

    def local_grad(p):
        g = jax.grad(make_loss(wrapped))(p)
        return jax.lax.pmean(g, "sp")

    g_sp = jax.jit(
        shard_map(local_grad, mesh=mesh, in_specs=(P(),), out_specs=P(),
                  check_vma=False)
    )(params)

    for ref, sp in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_sp)):
        np.testing.assert_allclose(
            np.asarray(sp), np.asarray(ref), rtol=1e-4, atol=1e-6
        )


def _run_sp(sp: int, num_updates: int = 3):
    mesh = make_mesh({"dp": 2, "sp": sp})
    net = SetTransformerPolicy(dim=16, depth=1, axis_name="sp")
    init_fn, update_fn, _ = make_seq_parallel_ppo(
        cluster_set_bundle(), CFG, net, mesh
    )
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    update = jax.jit(update_fn)
    history = []
    for _ in range(num_updates):
        runner, metrics = update(runner)
        history.append({k: float(v) for k, v in metrics.items()})
    return runner, history


def test_seq_parallel_training_metrics_track_unsharded():
    """Three full PPO updates: the sp=2 run's metrics must track sp=1
    (ring size 1 == dense, identical RNG: keys fold by dp only). Later
    updates run on parameters produced by earlier sharded updates, so
    agreement here means the gradient path stayed faithful end to end."""
    _, h1 = _run_sp(1)
    _, h2 = _run_sp(2)
    for m1, m2 in zip(h1, h2):
        assert m1["reward_mean"] == pytest.approx(m2["reward_mean"], rel=1e-3)
        assert m1["value_loss"] == pytest.approx(m2["value_loss"], rel=2e-2)
        assert m1["entropy"] == pytest.approx(m2["entropy"], rel=1e-3)


def test_seq_parallel_four_way():
    """sp=4 (2 nodes per shard) stays finite and syncs params."""
    runner, history = _run_sp(4, num_updates=1)
    assert np.isfinite(history[0]["policy_loss"])
    assert np.isfinite(history[0]["value_loss"])
    leaf = jax.tree.leaves(runner.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    assert all(np.array_equal(shards[0], s) for s in shards[1:])


def test_seq_parallel_learning_progress():
    """The dp x sp path must actually learn on the set env (hyperparams
    mirror the single-device set-policy smoke config in
    test_policy_zoo.py)."""
    mesh = make_mesh({"dp": 4, "sp": 2})
    cfg = PPOTrainConfig(
        num_envs=16, rollout_steps=64, minibatch_size=256, num_epochs=4,
        lr=3e-3, entropy_coeff=0.01,
    )
    net = SetTransformerPolicy(dim=16, depth=1, axis_name="sp")
    init_fn, update_fn, _ = make_seq_parallel_ppo(
        cluster_set_bundle(), cfg, net, mesh
    )
    runner = jax.jit(init_fn)(jax.random.PRNGKey(1))
    update = jax.jit(update_fn)
    rewards = []
    for _ in range(12):
        runner, metrics = update(runner)
        rewards.append(float(metrics["reward_mean"]))
    assert np.mean(rewards[-3:]) > np.mean(rewards[:3]), rewards


def test_dp_bundle_gnn_policy():
    """BASELINE config 5 (GNN over cluster topology) trains data-parallel
    through the bundle-generic builder."""
    from rl_scheduler_tpu.env import cluster_graph
    from rl_scheduler_tpu.models import GNNPolicy

    params = cluster_graph.make_params()
    net = GNNPolicy.from_adjacency(np.asarray(params.adjacency), dim=16, depth=2)
    mesh = make_mesh({"dp": 8})
    init_fn, update_fn, _ = make_data_parallel_ppo_bundle(
        cluster_graph_bundle(params), CFG, mesh, net=net
    )
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    runner, metrics = jax.jit(update_fn)(runner)
    assert np.isfinite(float(metrics["policy_loss"]))
    assert np.isfinite(float(metrics["value_loss"]))


def test_seq_parallel_training_large_node_set():
    """Long-context story at training time: a 64-node cluster_set (8x the
    default) trains on a dp=2 x sp=4 mesh — 16 nodes per shard, K/V
    rotating a 4-stage ring — with finite losses and params synced across
    shards. The per-node pointer logits must cover all 64 nodes."""
    params = cluster_set.make_params(num_nodes=64)
    bundle = cluster_set_bundle(params)
    assert bundle.obs_shape == (64, cluster_set.NODE_FEAT)
    mesh = make_mesh({"dp": 2, "sp": 4})
    net = SetTransformerPolicy(dim=16, depth=1, axis_name="sp")
    init_fn, update_fn, _ = make_seq_parallel_ppo(bundle, CFG, net, mesh)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    update = jax.jit(update_fn)
    for _ in range(2):
        runner, metrics = update(runner)
    for key in ("policy_loss", "value_loss", "reward_mean"):
        assert np.isfinite(float(metrics[key])), key
    for leaf in jax.tree.leaves(runner.params):
        assert bool(jnp.all(jnp.isfinite(leaf)))
    # Params really are synced: every physical shard holds the same bits
    # (a dropped pmean would leave shards divergent but finite).
    leaf = jax.tree.leaves(runner.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    assert all(np.array_equal(shards[0], s) for s in shards[1:])

    # The single-chip twin (axis_name=None) computes the same function on
    # the trained params: greedy actions over 64 nodes stay in range.
    twin = net.clone(axis_name=None)
    obs = jax.random.uniform(jax.random.PRNGKey(1),
                             (4, 64, cluster_set.NODE_FEAT))
    logits, value = twin.apply(runner.params, obs)
    assert logits.shape == (4, 64) and value.shape == (4,)
    assert bool(jnp.all(jnp.isfinite(logits)))
