"""Profiling harness: trace capture writes artifacts; StepTimer reports."""

from pathlib import Path

import jax
import jax.numpy as jnp

from rl_scheduler_tpu.utils.profiling import StepTimer, trace_iterations


def test_trace_iterations_writes_trace(tmp_path):
    @jax.jit
    def f(x):
        return x * 2.0 + 1.0

    with trace_iterations(tmp_path / "trace") as d:
        jax.block_until_ready(f(jnp.ones((128,))))
    files = list(Path(d).rglob("*"))
    assert any(p.is_file() for p in files), "profiler trace produced no files"


def test_step_timer_reports_throughput():
    @jax.jit
    def step(x):
        return x + 1.0

    timer = StepTimer(step, env_steps_per_iter=4096)
    state, report = timer.run(jnp.zeros((16,)), iters=5)
    assert report.iters == 5
    assert report.mean_s > 0
    assert report.env_steps_per_sec > 0
    assert float(state[0]) == 6.0  # warmup + 5 timed iterations
    d = report.as_dict()
    assert set(d) == {"iters", "mean_s", "p50_s", "p90_s", "env_steps_per_sec"}
