"""graftguard chaos suite (docs/robustness.md).

Attacks every host-I/O boundary under a seeded, deterministic
:class:`~rl_scheduler_tpu.utils.faults.FaultPlan` and asserts the stack
degrades the way the failure-domain design promises:

- checkpoint write failures are non-fatal; torn writes are caught by the
  integrity manifest, quarantined, and restore falls back to the newest
  VERIFIED step — the data-loss bound;
- simulated preemption stops the loop at a dispatch boundary, writes a
  final checkpoint, and interrupt-and-resume is BITWISE identical to an
  uninterrupted run (PPO via the real CLI, DQN via the API);
- Prometheus scrape timeouts and kube 5xx are retried under the unified
  ``utils/retry.py`` policy behind circuit breakers whose state the
  extender exports on ``/stats`` and ``/metrics``;
- a failing policy backend trips the extender's breaker and scheduling
  keeps answering (fail-open) without invoking the poisoned backend.

Every test asserts its fault actually FIRED (``plan.fired``): a chaos
test whose fault never triggers is a green lie. Long soak variants are
marked ``slow`` (``make chaos`` runs the fast gate; ``make chaos-soak``
includes them).
"""

from __future__ import annotations

import json
import math
from pathlib import Path

import jax
import numpy as np
import pytest

from rl_scheduler_tpu.agent.loop import make_periodic_checkpoint_fn
from rl_scheduler_tpu.utils.checkpoint import (
    CheckpointCorrupt,
    CheckpointManager,
    tree_structure_hash,
)
from rl_scheduler_tpu.utils.faults import (
    FaultInjected,
    FaultPlan,
    corrupt_checkpoint_step,
)
from rl_scheduler_tpu.utils.preemption import PreemptionGuard, guard_from_env
from rl_scheduler_tpu.utils.retry import (
    CircuitBreaker,
    CircuitOpenError,
    RetryBudgetExceeded,
    RetryPolicy,
)

SMALL_TREE = {"params": {"w": np.arange(12.0, dtype=np.float32).reshape(3, 4),
                         "b": np.zeros(4, np.float32)}}


def preempt_after(n: int) -> PreemptionGuard:
    """Simulated guard firing after exactly ``n`` dispatch boundaries."""
    state = {"polls": 0}

    def fire() -> bool:
        state["polls"] += 1
        return state["polls"] > n

    return PreemptionGuard(simulated=fire)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ------------------------------------------------------------ retry policy


def test_retry_policy_succeeds_after_transient_failures():
    calls = {"n": 0}
    sleeps = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("503")
        return "ok"

    policy = RetryPolicy(max_attempts=3, base_delay_s=0.1, jitter=0.0,
                         sleep=sleeps.append)
    assert policy.call(flaky) == "ok"
    assert calls["n"] == 3
    # Exponential backoff: 0.1, then 0.2.
    assert sleeps == [pytest.approx(0.1), pytest.approx(0.2)]


def test_retry_policy_exhausts_and_chains_cause():
    policy = RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0,
                         sleep=lambda _s: None)

    def always():
        raise TimeoutError("scrape")

    with pytest.raises(RetryBudgetExceeded) as exc:
        policy.call(always)
    assert isinstance(exc.value.__cause__, TimeoutError)


def test_retry_policy_jitter_is_seeded_deterministic():
    a = RetryPolicy(max_attempts=4, base_delay_s=0.1, jitter=0.5, seed=7)
    b = RetryPolicy(max_attempts=4, base_delay_s=0.1, jitter=0.5, seed=7)
    assert a.delays() == b.delays()


def test_retry_policy_deadline_stops_early():
    clock = FakeClock()
    sleeps = []

    def slow_sleep(s):
        sleeps.append(s)
        clock.advance(s)

    def failing():
        clock.advance(0.4)
        raise TimeoutError

    policy = RetryPolicy(max_attempts=10, base_delay_s=0.1, jitter=0.0,
                         deadline_s=1.0, sleep=slow_sleep, clock=clock)
    with pytest.raises(RetryBudgetExceeded):
        policy.call(failing)
    # Far fewer than 10 attempts fit inside the 1 s deadline.
    assert len(sleeps) <= 2


def test_retry_policy_propagates_non_retryable():
    policy = RetryPolicy(max_attempts=3, retry_on=(ConnectionError,),
                         sleep=lambda _s: None)

    def typo():
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        policy.call(typo)


# -------------------------------------------------------- circuit breaker


def test_breaker_full_cycle_closed_open_halfopen_closed():
    clock = FakeClock()
    br = CircuitBreaker(name="t", failure_threshold=2, reset_timeout_s=10.0,
                        clock=clock)
    assert br.state == "closed" and br.allow()
    br.record_failure()
    assert br.state == "closed"
    br.record_failure()
    assert br.state == "open"
    assert not br.allow()  # refused while cooling down
    clock.advance(10.1)
    assert br.state == "half_open"
    assert br.allow()          # the single probe
    assert not br.allow()      # concurrent second probe refused
    br.record_success()
    assert br.state == "closed"
    snap = br.snapshot()
    assert snap["opens_total"] == 1 and snap["failures_total"] == 2
    assert snap["refusals_total"] >= 2


def test_breaker_failed_probe_reopens():
    clock = FakeClock()
    br = CircuitBreaker(name="t", failure_threshold=1, reset_timeout_s=5.0,
                        clock=clock)
    br.record_failure()
    assert br.state == "open"
    clock.advance(5.1)
    assert br.allow()
    br.record_failure()  # probe fails
    assert br.state == "open"
    assert not br.allow()  # cool-down restarted
    assert br.snapshot()["opens_total"] == 2


def test_breaker_stuck_probe_rearms_after_cooldown():
    """A half-open probe that never reports back (wedged dependency,
    caller thread died) must not block recovery forever: the probe slot
    re-arms after another cool-down."""
    clock = FakeClock()
    br = CircuitBreaker(name="t", failure_threshold=1, reset_timeout_s=5.0,
                        clock=clock)
    br.record_failure()
    clock.advance(5.1)
    assert br.allow()       # probe admitted... and never reports back
    assert not br.allow()   # slot held
    clock.advance(5.1)
    assert br.allow()       # slot re-armed: recovery still possible
    br.record_success()
    assert br.state == "closed"


def test_breaker_call_raises_circuit_open():
    br = CircuitBreaker(name="t", failure_threshold=1, reset_timeout_s=60.0)
    with pytest.raises(RuntimeError):
        br.call(lambda: (_ for _ in ()).throw(RuntimeError("x")))
    with pytest.raises(CircuitOpenError):
        br.call(lambda: "never runs")


# ------------------------------------------------------------- fault plan


def test_fault_plan_schedule_and_counters():
    plan = FaultPlan(schedule={"checkpoint.save": (2,)})
    assert not plan.fires("checkpoint.save")
    assert plan.fires("checkpoint.save")
    assert not plan.fires("checkpoint.save")
    assert plan.calls["checkpoint.save"] == 3
    assert plan.fired["checkpoint.save"] == 1


def test_fault_plan_rates_deterministic_per_seed_and_site():
    a = FaultPlan(seed=3, rates={"telemetry.scrape": 0.5, "k8s.place": 0.5})
    b = FaultPlan(seed=3, rates={"telemetry.scrape": 0.5, "k8s.place": 0.5})
    pattern_a = [a.fires("telemetry.scrape") for _ in range(50)]
    pattern_b = [b.fires("telemetry.scrape") for _ in range(50)]
    assert pattern_a == pattern_b
    assert any(pattern_a) and not all(pattern_a)
    # Independent streams: consuming one site does not shift the other.
    c = FaultPlan(seed=3, rates={"telemetry.scrape": 0.5, "k8s.place": 0.5})
    [c.fires("k8s.place") for _ in range(17)]
    assert [c.fires("telemetry.scrape") for _ in range(50)] == pattern_a


def test_fault_plan_rejects_unknown_site():
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultPlan(schedule={"not.a.site": (1,)})
    plan = FaultPlan(schedule={"preempt": (1,)})
    with pytest.raises(FaultInjected):
        plan.check("preempt")


# ------------------------------------------------- hardened checkpointing


def test_checkpoint_manifest_written_and_verifies(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=3)
    mgr.save(1, SMALL_TREE, extras={"k": 1}, wait=True)
    mpath = tmp_path / "checkpoint_manifests" / "1.json"
    assert mpath.exists()
    manifest = json.loads(mpath.read_text())
    assert manifest["tree_hash"] == tree_structure_hash(SMALL_TREE)
    assert manifest["files"], "manifest recorded no files"
    ok, reason = mgr.verify_step(1)
    assert ok and reason == "verified"
    tree, extras = mgr.restore(1)
    assert extras == {"k": 1}
    np.testing.assert_array_equal(tree["params"]["w"],
                                  SMALL_TREE["params"]["w"])
    mgr.close()


def test_async_save_finalizes_at_close(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, SMALL_TREE)  # async: no wait
    mgr.close()              # finalize happens here
    assert (tmp_path / "checkpoint_manifests" / "1.json").exists()


def test_corrupt_step_quarantined_and_restore_falls_back(tmp_path):
    mgr = CheckpointManager(tmp_path)
    tree2 = {"params": {"w": SMALL_TREE["params"]["w"] + 1.0,
                        "b": SMALL_TREE["params"]["b"]}}
    mgr.save(1, SMALL_TREE, extras={"step": 1}, wait=True)
    mgr.save(2, tree2, extras={"step": 2}, wait=True)
    corrupt_checkpoint_step(tmp_path / "checkpoints" / "2")
    ok, reason = mgr.verify_step(2)
    assert not ok and "truncated" in reason
    tree, extras = mgr.restore()  # auto-select: falls back to step 1
    assert extras == {"step": 1}
    np.testing.assert_array_equal(tree["params"]["w"],
                                  SMALL_TREE["params"]["w"])
    assert (tmp_path / "quarantine" / "2").exists(), \
        "corrupt step must be quarantined as evidence, not deleted"
    assert mgr.latest_verified_step() == 1
    mgr.close()


def test_corrupt_garbage_detected_by_digest(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, SMALL_TREE, wait=True)
    corrupt_checkpoint_step(tmp_path / "checkpoints" / "1", mode="garbage")
    ok, reason = mgr.verify_step(1)
    assert not ok and "sha256" in reason
    mgr.close()


def test_explicit_corrupt_step_raises_not_silently_substitutes(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, SMALL_TREE, wait=True)
    mgr.save(2, SMALL_TREE, wait=True)
    corrupt_checkpoint_step(tmp_path / "checkpoints" / "2")
    with pytest.raises(CheckpointCorrupt):
        mgr.restore(2)
    mgr.close()


def test_wrong_target_on_verified_step_does_not_quarantine(tmp_path):
    """A restore failure on a step whose DIGESTS verified clean is a
    caller error (wrong net/algo/config), not disk corruption — it must
    raise without relocating the healthy checkpoint (in auto mode the
    old behavior quarantined the entire run, one fallback at a time)."""
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, SMALL_TREE, wait=True)
    # Structure mismatch (a DQN/PPO-style extra key the checkpoint lacks)
    # — Orbax raises on it; wrong SHAPES alone it silently ignores here.
    bad_target = {"params": {"w": jax.ShapeDtypeStruct((3, 4), np.float32),
                             "b": jax.ShapeDtypeStruct((4,), np.float32)},
                  "opt_state": {"m": jax.ShapeDtypeStruct((4,), np.float32)}}
    with pytest.raises(ValueError, match="key mismatch"):
        mgr.restore(1, target=bad_target)
    assert not (tmp_path / "quarantine").exists()
    assert mgr.latest_verified_step() == 1
    mgr.close()


def test_unfinalized_async_save_not_quarantined_on_fallback(tmp_path):
    """A manifest-less step in a run that HAS manifests is an in-flight
    async save (a live trainer finalizes it at its next save/close): a
    concurrent reader's failed restore must fall back WITHOUT moving the
    directory out from under the trainer's in-flight Orbax write."""
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, SMALL_TREE, extras={"step": 1}, wait=True)
    mgr.close()
    # Fabricate the on-disk shape of a dispatched-but-unfinalized save:
    # a step dir Orbax cannot yet read, with no manifest.
    step2 = tmp_path / "checkpoints" / "2"
    (step2 / "state").mkdir(parents=True)
    (step2 / "state" / "partial").write_bytes(b"\x00" * 64)
    reader = CheckpointManager(tmp_path)
    _, extras = reader.restore()
    assert extras == {"step": 1}
    assert step2.exists(), \
        "the unfinalized save must stay in place for the live trainer"
    assert not (tmp_path / "quarantine").exists()
    reader.close()


def test_ppo_cli_resume_with_changed_env_shape_degrades(tmp_path):
    """Resuming a full-state run with different env-shape knobs must not
    die inside Orbax: it degrades to the params-only resume with a note
    (scaling a run up/down is a legitimate operation)."""
    from rl_scheduler_tpu.agent import train_ppo as cli

    common = ["--preset", "quick", "--rollout-steps", "16",
              "--minibatch-size", "32", "--hidden", "8,8",
              "--checkpoint-every", "2", "--run-root", str(tmp_path),
              "--run-name", "scale"]
    cli.main(common + ["--num-envs", "8", "--iterations", "2"])
    cli.main(common + ["--num-envs", "4", "--iterations", "4", "--resume"])
    mgr = CheckpointManager(tmp_path / "scale")
    assert mgr.latest_verified_step() == 4
    mgr.close()


def test_legacy_checkpoint_without_manifest_still_restores(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, SMALL_TREE, wait=True)
    (tmp_path / "checkpoint_manifests" / "1.json").unlink()
    ok, reason = mgr.verify_step(1)
    assert ok and reason == "legacy"
    tree, _ = mgr.restore()
    np.testing.assert_array_equal(tree["params"]["w"],
                                  SMALL_TREE["params"]["w"])
    mgr.close()


def test_injected_save_failure_is_nonfatal_in_periodic_fn(tmp_path):
    plan = FaultPlan(schedule={"checkpoint.save": (2,)})
    mgr = CheckpointManager(tmp_path, fault_plan=plan)
    fn = make_periodic_checkpoint_fn(
        mgr, every=1, total_iterations=3,
        tree_fn=lambda r: SMALL_TREE, extras={})
    runner = object()
    fn(0, runner)   # step 1 saves
    fn(1, runner)   # step 2: injected OSError — logged, not raised
    fn(2, runner)   # step 3 saves
    assert plan.fired["checkpoint.save"] == 1
    assert [s for s, _ in fn.failures] == [2]
    assert mgr.latest_verified_step() == 3
    mgr.close()


def test_injected_partial_write_caught_on_restore(tmp_path):
    plan = FaultPlan(schedule={"checkpoint.partial": (2,)})
    mgr = CheckpointManager(tmp_path, fault_plan=plan)
    mgr.save(1, SMALL_TREE, extras={"step": 1}, wait=True)
    mgr.save(2, SMALL_TREE, extras={"step": 2}, wait=True)  # torn write
    mgr.close()
    assert plan.fired["checkpoint.partial"] == 1
    fresh = CheckpointManager(tmp_path)
    _, extras = fresh.restore()
    assert extras == {"step": 1}, \
        "restore must fall back past the torn step-2 write"
    fresh.close()


def test_load_policy_params_closes_manager_on_raise(tmp_path, monkeypatch):
    from rl_scheduler_tpu.utils import checkpoint as ckpt_mod

    closed = []
    monkeypatch.setattr(
        ckpt_mod.CheckpointManager, "restore",
        lambda self, step=None, target=None: (_ for _ in ()).throw(
            RuntimeError("boom")))
    monkeypatch.setattr(
        ckpt_mod.CheckpointManager, "close",
        lambda self: closed.append(True))
    with pytest.raises(RuntimeError, match="boom"):
        ckpt_mod.load_policy_params(tmp_path)
    assert closed == [True], "manager must close even when restore raises"


# --------------------------------------------------- preemption mechanics


def test_run_train_loop_stops_at_dispatch_boundary(tmp_path):
    from rl_scheduler_tpu.agent.loop import run_train_loop

    saves = []

    def update(r):
        return r + 1, {"loss": float(r)}

    def checkpoint_fn(i, r):
        if (i + 1) % 10 == 0:
            saves.append(("periodic", i + 1))

    checkpoint_fn.force = lambda i, r: saves.append(("force", i + 1))
    guard = preempt_after(3)
    runner, history = run_train_loop(
        update, 0, 0, 10, checkpoint_fn=checkpoint_fn, preemption=guard)
    assert runner == 3 and len(history) == 3
    assert guard.stopped_at == 2
    assert saves == [("force", 3)], \
        "preemption must force a final checkpoint at the last iteration"


def test_guard_from_env_validation():
    assert guard_from_env(None).simulated is None
    assert guard_from_env("").simulated is None
    with pytest.raises(SystemExit):
        guard_from_env("zero-ish")
    with pytest.raises(SystemExit):
        guard_from_env("0")
    g = guard_from_env("2")
    assert not g.should_stop() and not g.should_stop()
    assert g.should_stop()


# ------------------------------------------- interrupt-resume equivalence


PPO_COMMON = [
    "--preset", "quick", "--num-envs", "8", "--rollout-steps", "16",
    "--minibatch-size", "64", "--hidden", "8,8", "--checkpoint-every", "2",
]


def _ppo_cli_params(run_dir: Path, step: int):
    mgr = CheckpointManager(run_dir)
    tree, _ = mgr.restore(step)
    mgr.close()
    return jax.tree_util.tree_leaves(tree["params"])


def test_ppo_cli_interrupt_resume_bitwise(tmp_path, monkeypatch):
    """The acceptance criterion: interrupt at iteration 2 via simulated
    SIGTERM, resume, and the step-4 params are BITWISE identical to the
    uninterrupted run's — the full-state checkpoint carries env state,
    obs, and the RNG stream, so the continuation replays the exact same
    trajectory through the real CLI."""
    from rl_scheduler_tpu.agent import train_ppo as cli

    common = PPO_COMMON + ["--run-root", str(tmp_path)]
    cli.main(common + ["--run-name", "full", "--iterations", "4"])
    monkeypatch.setenv("GRAFTGUARD_PREEMPT_AFTER", "2")
    cli.main(common + ["--run-name", "cut", "--iterations", "4"])
    monkeypatch.delenv("GRAFTGUARD_PREEMPT_AFTER")
    # The preempted run stopped at its step-2 checkpoint...
    mgr = CheckpointManager(tmp_path / "cut")
    assert mgr.latest_verified_step() == 2
    mgr.close()
    # ...and the resumed continuation reaches 4 with identical params.
    cli.main(common + ["--run-name", "cut", "--iterations", "4", "--resume"])
    leaves_full = _ppo_cli_params(tmp_path / "full", 4)
    leaves_cut = _ppo_cli_params(tmp_path / "cut", 4)
    assert len(leaves_full) == len(leaves_cut)
    for a, b in zip(leaves_full, leaves_cut):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _dqn_setup():
    from rl_scheduler_tpu.agent.dqn import DQNConfig, make_dqn
    from rl_scheduler_tpu.env.bundle import single_cluster_bundle

    bundle = single_cluster_bundle()
    cfg = DQNConfig(num_envs=2, collect_steps=4, buffer_size=64,
                    batch_size=8, learning_starts=4)
    return bundle, cfg, make_dqn(bundle, cfg)


def _dqn_tree_fn(runner):
    return {
        "params": runner.params,
        "target_params": runner.target_params,
        "opt_state": runner.opt_state,
        "loop": {
            "buffer": runner.buffer._asdict(),
            "env_state": runner.env_state,
            "obs": runner.obs,
            "key": runner.key,
            "env_steps": runner.env_steps,
            "ep_return": runner.ep_return,
            "last_episode_return": runner.last_episode_return,
        },
    }


def test_dqn_interrupt_resume_bitwise(tmp_path):
    """Same guarantee for DQN at the API level: the full-state tree
    includes the REPLAY BUFFER, so the resumed learner samples the exact
    minibatches the uninterrupted run would have."""
    from rl_scheduler_tpu.agent.dqn import dqn_train

    bundle, cfg, (init_fn, _, _) = _dqn_setup()
    runner_full, _ = dqn_train(bundle, cfg, 6, seed=1)

    mgr = CheckpointManager(tmp_path)
    fn = make_periodic_checkpoint_fn(mgr, every=3, total_iterations=6,
                                     tree_fn=_dqn_tree_fn, extras={})
    guard = preempt_after(3)
    dqn_train(bundle, cfg, 6, seed=1, checkpoint_fn=fn, preemption=guard)
    assert guard.stopped_at == 2  # iterations 1-3 done (0-indexed last=2)
    mgr.close()

    fresh = CheckpointManager(tmp_path)
    step = fresh.latest_verified_step()
    assert step == 3
    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(1))
    target = {"params": abstract.params,
              "target_params": abstract.target_params,
              "opt_state": abstract.opt_state,
              "loop": {"buffer": abstract.buffer._asdict(),
                       "env_state": abstract.env_state,
                       "obs": abstract.obs,
                       "key": abstract.key,
                       "env_steps": abstract.env_steps,
                       "ep_return": abstract.ep_return,
                       "last_episode_return": abstract.last_episode_return}}
    tree, _ = fresh.restore(step, target=target)
    fresh.close()
    runner_resumed, _ = dqn_train(bundle, cfg, 6, seed=1,
                                  restore=(tree, step))
    for a, b in zip(jax.tree_util.tree_leaves(runner_full.params),
                    jax.tree_util.tree_leaves(runner_resumed.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------ telemetry under attack


class StubProm:
    """PrometheusCpu with the HTTP layer replaced by the fault seam +
    a constant reading — the breaker/retry/fallback logic is the code
    under test, not urllib."""

    def __new__(cls, *a, **k):
        from rl_scheduler_tpu.scheduler.telemetry import PrometheusCpu

        class _Stub(PrometheusCpu):
            def _query_one(self, base_url):
                if self.fault_plan is not None:
                    self.fault_plan.check("telemetry.scrape", TimeoutError)
                return 0.42

        return _Stub(*a, **k)


def test_scrape_timeouts_fall_back_and_trip_breaker():
    clock = FakeClock()
    # Calls 1-4: the first refresh's two clouds x two retry attempts all
    # time out; everything after (the recovery probes) succeeds.
    plan = FaultPlan(schedule={"telemetry.scrape": (1, 2, 3, 4)})
    cpu = StubProm(
        fault_plan=plan,
        retry=RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0,
                          sleep=lambda _s: None),
        breakers={c: CircuitBreaker(name=f"prometheus_{c}",
                                    failure_threshold=1,
                                    reset_timeout_s=10.0, clock=clock)
                  for c in ("aws", "azure")},
    )
    cpu._refresh()  # both endpoints fail (2 retries each) -> both open
    assert plan.fired["telemetry.scrape"] >= 2
    assert all(b.state == "open" for b in cpu.breakers.values())
    a, b = cpu.sample()
    assert 0.0 <= a <= 1.0 and 0.0 <= b <= 1.0  # fallback values, no block
    consults_before = plan.calls["telemetry.scrape"]
    cpu._refresh()  # breakers open: no HTTP attempt at all
    assert plan.calls["telemetry.scrape"] == consults_before
    # Cool-down passes; the plan's schedule is exhausted -> probes heal.
    clock.advance(10.1)
    cpu._refresh()
    assert all(b.state == "closed" for b in cpu.breakers.values())
    assert cpu._cached == (0.42, 0.42)


def test_scrape_breakers_are_per_endpoint():
    """One dead endpoint must neither have its failure streak reset by
    the healthy one (the shared-breaker bug: it would never open) nor,
    once open, refuse the healthy endpoint's scrapes."""
    clock = FakeClock()
    # Odd consults = aws (the refresh loop queries aws first): aws times
    # out every refresh, azure always succeeds.
    plan = FaultPlan(schedule={"telemetry.scrape": (1, 3, 5)})
    cpu = StubProm(
        fault_plan=plan,
        retry=RetryPolicy(max_attempts=1, sleep=lambda _s: None),
        breakers={c: CircuitBreaker(name=f"prometheus_{c}",
                                    failure_threshold=3,
                                    reset_timeout_s=10.0, clock=clock)
                  for c in ("aws", "azure")},
    )
    for _ in range(3):
        cpu._refresh()
    assert cpu.breakers["aws"].state == "open"
    assert cpu.breakers["azure"].state == "closed"
    # The healthy endpoint keeps scraping real values past the open peer.
    cpu._refresh()
    assert cpu._cached[1] == 0.42


# ------------------------------------------------- kube API under attack


class StubPlacer:
    """DryRunPodPlacer with the kube client call replaced by the fault
    seam (no kubernetes package in the container)."""

    def __new__(cls, *a, **k):
        from rl_scheduler_tpu.scheduler.k8s_client import DryRunPodPlacer

        class _Stub(DryRunPodPlacer):
            def _load_clients(self):
                self._clients = {"aws": object(), "azure": object()}

            def _create_pod(self, v1, cloud, dry_run):
                if self.fault_plan is not None:
                    self.fault_plan.check("k8s.place", ConnectionError)

        return _Stub(*a, **k)


def test_k8s_5xx_retried_then_succeeds():
    plan = FaultPlan(schedule={"k8s.place": (1,)})
    placer = StubPlacer(
        fault_plan=plan,
        retry=RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0,
                          sleep=lambda _s: None),
    )
    assert placer.place("aws") is True  # first attempt 503s, retry lands
    assert plan.fired["k8s.place"] == 1
    assert plan.calls["k8s.place"] == 2
    assert placer.breakers["aws"].state == "closed"


def test_k8s_persistent_5xx_trips_breaker_and_skips_calls():
    clock = FakeClock()
    plan = FaultPlan(rates={"k8s.place": 1.0})
    placer = StubPlacer(
        fault_plan=plan,
        retry=RetryPolicy(max_attempts=2, base_delay_s=0.0, jitter=0.0,
                          sleep=lambda _s: None),
        breakers={c: CircuitBreaker(name=f"k8s_{c}", failure_threshold=2,
                                    reset_timeout_s=30.0, clock=clock)
                  for c in ("aws", "azure")},
    )
    assert placer.place("aws") is False
    assert placer.place("aws") is False
    assert placer.breakers["aws"].state == "open"
    consults = plan.calls["k8s.place"]
    assert placer.place("aws") is False  # refused pre-call
    assert plan.calls["k8s.place"] == consults
    # Per-cloud isolation: the open aws breaker must not refuse azure —
    # and azure's single failure must not be polluted by aws's streak.
    assert placer.place("azure") is False
    assert plan.calls["k8s.place"] > consults
    assert placer.breakers["azure"].state == "closed"


# --------------------------------------------- extender backend breaker


class FaultyBackend:
    name = "chaos"
    family = "cloud"

    def __init__(self, plan):
        self.plan = plan

    def decide(self, obs):
        self.plan.check("backend.decide", RuntimeError)
        return 1, np.array([0.0, 1.0], np.float32)


def _telemetry():
    from rl_scheduler_tpu.scheduler.telemetry import RandomCpu, TableTelemetry

    return TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))


def test_backend_failures_fail_open_then_breaker_short_circuits():
    from rl_scheduler_tpu.scheduler.extender import ExtenderPolicy

    clock = FakeClock()
    plan = FaultPlan(rates={"backend.decide": 1.0})
    policy = ExtenderPolicy(FaultyBackend(plan), _telemetry())
    policy.backend_breaker = CircuitBreaker(
        name="backend", failure_threshold=2, reset_timeout_s=10.0,
        clock=clock)
    args = {"nodenames": ["aws-node-1", "azure-node-1"]}
    for _ in range(2):  # failures: fail-open passthrough, breaker counts
        out = policy.filter(dict(args))
        assert out["nodenames"] == args["nodenames"] and out["error"] == ""
    assert policy.backend_breaker.state == "open"
    consults = plan.calls["backend.decide"]
    out = policy.filter(dict(args))  # breaker open: backend NOT invoked
    assert out["nodenames"] == args["nodenames"]
    assert plan.calls["backend.decide"] == consults
    # Breaker state is a /stats read...
    stats = policy.statistics()
    assert stats["breakers"]["backend"]["state"] == "open"
    assert stats["breakers"]["backend"]["opens_total"] == 1
    # ...and a /metrics scrape (state code 2 = open).
    text = policy.metrics_text()
    assert 'circuit_state{breaker="backend"} 2' in text
    assert 'circuit_opens_total{breaker="backend"} 1' in text


def test_stats_exports_all_configured_breakers():
    from rl_scheduler_tpu.scheduler.extender import ExtenderPolicy

    plan = FaultPlan()
    cpu = StubProm(fault_plan=None)
    telemetry = _telemetry()
    telemetry.cpu = cpu
    placer = StubPlacer(fault_plan=plan)
    policy = ExtenderPolicy(FaultyBackend(plan), telemetry, placer=placer)
    names = set(policy.breakers())
    assert names == {"backend", "prometheus_aws", "prometheus_azure",
                     "k8s_aws", "k8s_azure"}
    text = policy.metrics_text()
    for name in names:
        assert f'circuit_state{{breaker="{name}"}}' in text


# ----------------------------------------------- flight recorder dumps


def test_flight_recorder_dump_is_nonfatal_on_unwritable_dir(tmp_path):
    from rl_scheduler_tpu.utils.flight_recorder import FlightRecorder

    blocker = tmp_path / "blocker"
    blocker.write_text("a file where the dump dir should be")
    rec = FlightRecorder(path=blocker / "sub" / "dump.jsonl", manifest={})
    # mkdir(parents=True) under a FILE raises; dump must swallow + log.
    assert rec.dump("nan_inf", 3, detail="test") is False
    assert rec.dump_count == 1, "failed attempts still count vs max_dumps"


def test_flight_recorder_dump_still_works_normally(tmp_path):
    from rl_scheduler_tpu.utils.flight_recorder import FlightRecorder

    rec = FlightRecorder(path=tmp_path / "fr.jsonl", manifest={"run": "x"})
    assert rec.dump("nan_inf", 0, detail="t") is True
    lines = (tmp_path / "fr.jsonl").read_text().splitlines()
    head = json.loads(lines[0])
    assert head["kind"] == "manifest" and head["run"] == "x"


# ------------------------------------------------------------ chaos soak


def test_chaos_training_survives_combined_faults(tmp_path):
    """The fast chaos gate: one PPO training run attacked with a
    checkpoint write failure AND a torn write AND a preemption, all from
    one seeded plan — training never crashes, the preempted state is
    checkpointed, and restore lands on a VERIFIED step."""
    from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, ppo_train
    from rl_scheduler_tpu.config import EnvConfig
    from rl_scheduler_tpu.env import core as env_core

    cfg = PPOTrainConfig(num_envs=4, rollout_steps=8, minibatch_size=16,
                         num_epochs=2, rollout_impl="scan")
    env_params = env_core.make_params(EnvConfig())
    # Call-index bookkeeping: checkpoint.save is consulted once per save
    # attempt (steps 1,2,3,4 -> calls 1-4); checkpoint.partial only on
    # saves that DISPATCH (step 2's save raised first), so its calls are
    # step1->1, step3->2, step4->3 — firing call 2 tears step 3.
    plan = FaultPlan(schedule={
        "checkpoint.save": (2,),      # step-2 save: write error (nonfatal)
        "checkpoint.partial": (2,),   # step-3 save: torn write
        "preempt": (5,),              # stop before the 5th dispatch
    })
    mgr = CheckpointManager(tmp_path / "run", fault_plan=plan)
    fn = make_periodic_checkpoint_fn(
        mgr, every=1, total_iterations=8,
        tree_fn=lambda r: {"params": r.params, "opt_state": r.opt_state},
        extras={})
    guard = PreemptionGuard(simulated=lambda: plan.fires("preempt"))
    runner, history = ppo_train(env_params, cfg, 8, seed=0,
                                checkpoint_fn=fn, preemption=guard)
    assert guard.stopped_at == 3, "preemption must stop after 4 iterations"
    assert len(history) == 4
    assert [s for s, _ in fn.failures] == [2], "write failure was nonfatal"
    assert plan.fired["checkpoint.partial"] == 1
    mgr.close()

    fresh = CheckpointManager(tmp_path / "run")
    step = fresh.latest_verified_step()
    # Step 4 (the pre-preemption boundary) verified; the torn step 3
    # would only surface (and quarantine) if 4 were ever damaged.
    assert step == 4
    tree, _ = fresh.restore(step)
    assert all(math.isfinite(float(np.asarray(leaf).ravel()[0]))
               for leaf in jax.tree_util.tree_leaves(tree["params"]))
    fresh.close()


@pytest.mark.slow
def test_chaos_soak_random_rates(tmp_path):
    """Soak variant (make chaos-soak): longer run, rate-based plan — the
    fault pattern is still reproducible from the seed, but not hand
    placed. Training must complete or stop cleanly, and at least one
    verified checkpoint must survive whatever fired."""
    from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, ppo_train
    from rl_scheduler_tpu.config import EnvConfig
    from rl_scheduler_tpu.env import core as env_core

    cfg = PPOTrainConfig(num_envs=4, rollout_steps=8, minibatch_size=16,
                         num_epochs=2, rollout_impl="scan")
    env_params = env_core.make_params(EnvConfig())
    plan = FaultPlan(seed=11, rates={"checkpoint.save": 0.25,
                                     "checkpoint.partial": 0.25})
    mgr = CheckpointManager(tmp_path / "soak", fault_plan=plan)
    fn = make_periodic_checkpoint_fn(
        mgr, every=1, total_iterations=24,
        tree_fn=lambda r: {"params": r.params, "opt_state": r.opt_state},
        extras={})
    ppo_train(env_params, cfg, 24, seed=0, checkpoint_fn=fn)
    mgr.close()
    assert plan.fired, "soak plan fired nothing — raise the rates"
    fresh = CheckpointManager(tmp_path / "soak")
    assert fresh.latest_verified_step() is not None
    fresh.close()


# ----------------------------------------------- best-eval checkpoint (3a)


class TestBestEvalCheckpoint:
    """ROADMAP item 3a: the best-in-training-eval keeper salvages the
    measured late-degrade failure mode — a run whose eval peaks mid-
    training and ends below it must leave its PEAK weights in best/."""

    def test_late_degrade_run_is_salvaged(self, tmp_path):
        from rl_scheduler_tpu.agent.loop import make_best_checkpoint_hook
        from rl_scheduler_tpu.utils.checkpoint import load_policy_params

        best = CheckpointManager(tmp_path / "best", keep=1)
        # Runner stand-in: a float whose value IS the weights, so the
        # restored params identify which iteration's runner was kept.
        tree_fn = lambda r: {"params": {
            "w": np.full(3, float(r), np.float32)}}
        hook = make_best_checkpoint_hook(best, tree_fn,
                                         extras={"env": "sim"})
        # The measured late-degrade shape (docs/scaling.md §1b, seeds
        # 5/8): healthy early, PEAK mid-run, final eval collapsed.
        for i, value in [(0, -80.0), (7, -12.0), (15, -65.0)]:
            hook(i, float(i), {"eval_episode_reward_mean": value})
        best.close()
        assert hook.best_value() == -12.0
        params, meta = load_policy_params(tmp_path / "best")
        assert meta["best_eval"] == -12.0
        # The PEAK iteration's weights survive — not the degraded tail's.
        np.testing.assert_array_equal(params["w"],
                                      np.full(3, 7.0, np.float32))

    def test_best_save_failure_is_nonfatal(self, tmp_path):
        from rl_scheduler_tpu.agent.loop import make_best_checkpoint_hook

        plan = FaultPlan(schedule={"checkpoint.save": (1,)})
        best = CheckpointManager(tmp_path / "best", keep=1,
                                 fault_plan=plan)
        hook = make_best_checkpoint_hook(
            best, lambda r: {"params": {"w": np.zeros(2, np.float32)}},
            extras={})
        hook(0, 0.0, {"eval_episode_reward_mean": 1.0})  # save fails
        assert plan.fired.get("checkpoint.save") == 1
        assert len(hook.failures) == 1
        # The tracker still advanced: a better eval later saves normally.
        hook(1, 1.0, {"eval_episode_reward_mean": 2.0})
        best.close()
        fresh = CheckpointManager(tmp_path / "best")
        assert fresh.latest_verified_step() == 2
        fresh.close()

    def test_cli_keeps_best_and_resume_best_continues(self, tmp_path):
        """Through the real CLI: --eval-every arms the keeper, best/
        holds the peak eval, --resume-best trains onward from it, and
        the degraded tail PAST the peak is abandoned (its step numbers
        freed — otherwise the continuation's saves at them are refused
        by Orbax and swallowed, and a stale newer step keeps winning
        --resume/evaluate selection)."""
        from rl_scheduler_tpu.agent import train_ppo
        from rl_scheduler_tpu.agent.loop import BEST_DIR
        from rl_scheduler_tpu.utils.checkpoint import (
            CheckpointManager as Mgr,
            load_policy_params,
        )

        base = ["--preset", "quick", "--num-envs", "4",
                "--rollout-steps", "8", "--minibatch-size", "32",
                "--eval-every", "1", "--eval-episodes", "2",
                "--checkpoint-every", "1",
                "--run-name", "BEST", "--run-root", str(tmp_path)]
        run_dir = train_ppo.main(base + ["--iterations", "3"])
        _, meta = load_policy_params(run_dir / BEST_DIR)
        evals = [json.loads(line)["eval_episode_reward_mean"]
                 for line in (run_dir / "metrics.jsonl").read_text().splitlines()
                 if '"eval": true' in line]
        assert meta["best_eval"] == pytest.approx(max(evals))
        best_step = 1 + evals.index(max(evals))
        # Resume from the best checkpoint, train one iteration past it.
        run_dir = train_ppo.main(base + ["--iterations", str(best_step + 1),
                                         "--resume-best"])
        lines = (run_dir / "metrics.jsonl").read_text().splitlines()
        assert any('"resume_source": "best"' in line for line in lines)
        # The continuation's save is the NEWEST step: any degraded-tail
        # step beyond it was deleted, not left to shadow the salvage.
        mgr = Mgr(run_dir)
        assert mgr.latest_verified_step() == best_step + 1
        mgr.close()


class TestStudyChaos:
    """graftstudy under SIGKILL (docs/studies.md): a killed mid-study
    run resumes from the atomic tmp-then-rename ledger — completed-trial
    entries bitwise intact and not re-run, the in-flight trial restarted
    from scratch."""

    # The study driver runs as its own process group so SIGKILL takes
    # the in-flight trial's work down with it, exactly like a lost VM.
    # The acceptance shape — 2 variants x 3 seeds — is DERIVED from the
    # registry's study_smoke preset (not hand-copied) so the trial
    # config can never silently diverge from the tier-1 smoke's, and
    # every XLA program is shared with it via the persistent cache.
    DRIVER = """
import dataclasses
import sys
sys.path.insert(0, {root!r})
from rl_scheduler_tpu.studies import StudyRunner, configure_jax_cache, get_study
configure_jax_cache()
spec = dataclasses.replace(
    get_study("study_smoke"), name="chaos", seeds=(0, 1, 2),
    target_failure_rate=0.2)
StudyRunner(spec, {study_dir!r}, jobs=0).run()
"""
    TRIAL_IDS = ["control-seed0", "control-seed1", "control-seed2",
                 "anneal-seed0", "anneal-seed1", "anneal-seed2"]

    def _launch(self, script):
        import os
        import subprocess
        import sys

        return subprocess.Popen(
            [sys.executable, "-c", script],
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
            start_new_session=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})

    def test_sigkill_mid_study_resumes_bitwise(self, tmp_path):
        import os
        import signal
        import time

        study_dir = tmp_path / "study"
        script = self.DRIVER.format(
            root=str(Path(__file__).resolve().parents[1]),
            study_dir=str(study_dir))
        ledger = study_dir / "ledger.jsonl"

        proc = self._launch(script)
        try:
            # Wait for the FIRST completed trial to land in the ledger,
            # then SIGKILL the whole group mid-trial-2.
            deadline = time.time() + 420
            while time.time() < deadline:
                if ledger.exists() and len(ledger.read_bytes().splitlines()) >= 2:
                    break
                if proc.poll() is not None:
                    raise AssertionError("study finished before the kill")
                time.sleep(0.25)
            else:
                raise AssertionError("no trial completed before deadline")
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()

        killed_bytes = ledger.read_bytes()
        killed_lines = killed_bytes.splitlines()
        assert len(killed_lines) >= 2  # header + >= 1 completed trial
        done_ids = [json.loads(l)["trial_id"] for l in killed_lines[1:]]
        # Evidence of the in-flight trial: its dir exists, result absent
        # (may be absent if the kill landed between trials — both fine).
        mtimes = {
            tid: (study_dir / "trials" / tid / "result.json").stat().st_mtime
            for tid in done_ids
        }

        # Resume: same driver, runs to completion.
        rc = self._launch(script).wait(timeout=600)
        assert rc == 0
        after_bytes = ledger.read_bytes()
        # Bitwise: the killed run's ledger is an exact PREFIX — completed
        # entries were neither rewritten nor re-run.
        assert after_bytes.startswith(killed_bytes)
        records = [json.loads(l) for l in after_bytes.splitlines()[1:]]
        assert [r["trial_id"] for r in records] == self.TRIAL_IDS
        assert all(r["status"] == "ok" for r in records)
        # Completed trials untouched on disk (result.json not rewritten).
        for tid, mtime in mtimes.items():
            assert (study_dir / "trials" / tid
                    / "result.json").stat().st_mtime == mtime
        # The resumed run restarted the in-flight trial and produced its
        # verdict (and every trial dir now holds an atomic result).
        for r in records:
            assert (study_dir / "trials" / r["trial_id"]
                    / "result.json").exists()
        # The completed ledger analyzes to per-variant Wilson-interval
        # failure rates + graded verdicts (the acceptance summary the
        # CLI emits as the driver line).
        from rl_scheduler_tpu.studies import analyze_study, load_spec

        summary = analyze_study(load_spec(study_dir), records)
        assert summary["schema_version"] == 1
        for v in ("control", "anneal"):
            cell = summary["variants"][v]
            assert cell["trials"] == 3
            lo, hi = cell["wilson95"]
            assert 0.0 <= lo <= cell["failure_rate"] <= hi <= 1.0
            assert cell["verdict"] in (
                "confirmed_below", "point_below", "point_above",
                "confirmed_above")


class TestGraftrollChaos:
    """graftroll's fault sites (utils/faults.py: `tracelog.append`,
    `rollout.spawn`, `rollout.health`), wired as plumbed seams and
    asserted to actually fire — a chaos test whose fault never triggers
    is a green lie. The rollout sites must take the ROLLBACK path: a
    fault mid-promotion leaves the pool serving the incumbent
    generation, never a mixed pool."""

    def test_tracelog_append_fault_counted_and_survived(self, tmp_path):
        """An injected disk-full on append is counted as a write error,
        drops exactly that record, and the writer keeps serving the
        queue — the decision hot path never saw any of it."""
        from rl_scheduler_tpu.scheduler.tracelog import TraceLog, iter_trace

        plan = FaultPlan(schedule={"tracelog.append": (2,)})
        log = TraceLog(tmp_path, fault_plan=plan)
        for i in range(4):
            assert log.append({"i": i})
        log.close()
        assert plan.fired["tracelog.append"] == 1
        assert plan.calls["tracelog.append"] == 4
        snap = log.snapshot()
        assert snap["write_errors_total"] == 1
        assert snap["written_total"] == 3
        assert [r["i"] for r in iter_trace(tmp_path)] == [0, 2, 3]

    @staticmethod
    def _rollout_pool_pieces(tmp_path, plan):
        """A 2-worker greedy pool + a manifest-verified candidate, built
        with the pool test-suite's own helpers (tests/test_pool.py) so
        the chaos path exercises the identical machinery."""
        import os as _os

        if not hasattr(_os, "fork"):
            pytest.skip("graftserve pools require fork")
        from tests import test_pool as tp

        pool = tp._make_rollout_pool(fault_plan=plan)
        candidate = tp._make_verified_checkpoint(tmp_path, "ckpt-good")
        return tp, pool, candidate

    def _promote_and_wait(self, tp, pool, candidate):
        cport = pool.control_address[1]
        code, _ = tp._post_code(cport, "/promote",
                                {"checkpoint": str(candidate)})
        assert code == 202
        return tp._wait_rollout_idle(cport)

    def test_rollout_spawn_fault_rolls_back(self, tmp_path):
        """`rollout.spawn` firing on the canary's respawn must leave the
        incumbent generation serving: the rollback re-spawns the slot
        the failed promote took down."""
        plan = FaultPlan(schedule={"rollout.spawn": (1,)})
        tp, pool, candidate = self._rollout_pool_pieces(tmp_path, plan)
        try:
            status = self._promote_and_wait(tp, pool, candidate)
            assert plan.fired["rollout.spawn"] == 1
            # the rollback's own replaces consulted the site again
            assert plan.calls["rollout.spawn"] >= 2
            assert status["rollbacks_total"] == 1
            assert status["promotions_total"] == 0
            assert status["generation"] == 0
            assert "spawn failed" in status["last_error"]
            snapshots = pool.scrape()
            assert len(snapshots) == 2
            assert all(s["generation"] == 0 for s in snapshots)
            assert len(tp._post(pool.port, "/filter",
                                tp._filter_args(0))["nodenames"]) == 1
        finally:
            pool.shutdown()

    def test_rollout_health_fault_rolls_back(self, tmp_path):
        """`rollout.health` firing at the canary's health gate is the
        same rollback obligation as a dead canary — the already-spawned
        new-generation worker is rolled back onto the incumbent."""
        plan = FaultPlan(schedule={"rollout.health": (1,)})
        tp, pool, candidate = self._rollout_pool_pieces(tmp_path, plan)
        try:
            status = self._promote_and_wait(tp, pool, candidate)
            assert plan.fired["rollout.health"] == 1
            assert status["rollbacks_total"] == 1
            assert status["generation"] == 0
            assert "health gate failed" in status["last_error"]
            assert all(s["generation"] == 0 for s in pool.scrape())
            assert len(tp._post(pool.port, "/filter",
                                tp._filter_args(0))["nodenames"]) == 1
        finally:
            pool.shutdown()

    def test_fastpath_agree_fault_refuses_promote(self, tmp_path):
        """graftfwd's `fastpath.agree` site: a failing int8-agreement
        re-check at the promote gate must REFUSE the promote (rollback
        to the incumbent), never fall through to serving the candidate
        — quantized or silently-fp32 (docs/serving.md)."""
        plan = FaultPlan(schedule={"fastpath.agree": (1,)})
        tp, pool, candidate = self._rollout_pool_pieces(tmp_path, plan)
        try:
            status = self._promote_and_wait(tp, pool, candidate)
            assert plan.fired["fastpath.agree"] == 1
            # Rollback replaces run gate=False: the site is consulted
            # exactly once — by the promote-path gate that failed.
            assert plan.calls["fastpath.agree"] == 1
            assert status["rollbacks_total"] == 1
            assert status["promotions_total"] == 0
            assert status["generation"] == 0
            assert "fastpath agreement check failed" in status["last_error"]
            assert all(s["generation"] == 0 for s in pool.scrape())
            assert len(tp._post(pool.port, "/filter",
                                tp._filter_args(0))["nodenames"]) == 1
        finally:
            pool.shutdown()


def test_quarantine_tolerates_concurrent_move(tmp_path):
    """The GL014 fix pinned: two restore paths can race to quarantine
    the same corrupt step. The loser's moves find the evidence already
    gone — that is success (the evidence IS preserved, by the winner),
    not a crash in the restore path."""
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, SMALL_TREE, wait=True)
    dest = mgr.quarantine(1, "race-winner")
    assert dest.exists()
    # The racing loser: step dir and manifest were already moved.
    dest2 = mgr.quarantine(1, "race-loser")
    assert not dest2.exists()  # nothing left to move — and no raise
    assert dest.exists()
    mgr.close()
