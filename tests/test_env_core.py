"""Functional env core: golden-value parity, episode semantics, determinism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env import core
from rl_scheduler_tpu.env.baselines import cost_greedy_policy, round_robin_policy


@pytest.fixture(scope="module")
def params():
    return core.make_params(EnvConfig(legacy_reward_sign=True))  # reference parity


@pytest.fixture(scope="module")
def corrected_params():
    return core.make_params(EnvConfig())


def test_reset_obs(params, reference_table):
    state, obs = core.reset(params, jax.random.PRNGKey(0))
    assert obs.shape == (6,)
    assert int(state.step_idx) == 0
    row = reference_table.iloc[0]
    np.testing.assert_allclose(obs[0], row["cost_aws"], rtol=1e-6)
    np.testing.assert_allclose(obs[1], row["cost_azure"], rtol=1e-6)
    np.testing.assert_allclose(obs[2], row["latency_aws"], rtol=1e-6)
    np.testing.assert_allclose(obs[3], row["latency_azure"], rtol=1e-6)
    assert 0.1 <= float(obs[4]) <= 0.8 and 0.1 <= float(obs[5]) <= 0.8


def test_step_reward_golden_legacy(params, reference_table):
    """Reward parity with the reference formula 100*(0.6*cost + 0.4*latency)
    computed from the shipped table, for both actions over several steps."""
    state, _ = core.reset(params, jax.random.PRNGKey(1))
    for i in range(5):
        row = reference_table.iloc[i]
        for action, cloud in ((0, "aws"), (1, "azure")):
            _, ts = core.step(params, state, jnp.asarray(action))
            expected = 100.0 * (0.6 * row[f"cost_{cloud}"] + 0.4 * row[f"latency_{cloud}"])
            np.testing.assert_allclose(float(ts.reward), expected, rtol=1e-5)
        state, ts = core.step(params, state, jnp.asarray(i % 2))
    # row-0 sanity anchors from SURVEY.md §7.0.1
    s0, _ = core.reset(params, jax.random.PRNGKey(2))
    _, ts_aws = core.step(params, s0, jnp.asarray(0))
    _, ts_az = core.step(params, s0, jnp.asarray(1))
    assert float(ts_aws.reward) == pytest.approx(48.4, abs=0.2)
    assert float(ts_az.reward) == pytest.approx(3.0, abs=0.2)


def test_corrected_reward_is_negated(params, corrected_params):
    s_l, _ = core.reset(params, jax.random.PRNGKey(3))
    s_c, _ = core.reset(corrected_params, jax.random.PRNGKey(3))
    _, ts_l = core.step(params, s_l, jnp.asarray(0))
    _, ts_c = core.step(corrected_params, s_c, jnp.asarray(0))
    np.testing.assert_allclose(float(ts_c.reward), -float(ts_l.reward), rtol=1e-6)


def test_episode_length_and_done(params):
    """done exactly at step 99 (max_steps = T-1 = 99), reference :66,139-141."""
    state, _ = core.reset(params, jax.random.PRNGKey(4))
    step_fn = jax.jit(core.step)
    for i in range(1, 100):
        state, ts = step_fn(params, state, jnp.asarray(0))
        assert int(ts.step) == i
        assert bool(ts.done) == (i >= 99)
    assert int(state.step_idx) == 99


def test_determinism_per_key(params):
    s1, o1 = core.reset(params, jax.random.PRNGKey(7))
    s2, o2 = core.reset(params, jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    _, t1 = core.step(params, s1, jnp.asarray(1))
    _, t2 = core.step(params, s2, jnp.asarray(1))
    np.testing.assert_array_equal(np.asarray(t1.obs), np.asarray(t2.obs))
    # different keys -> different cpu noise
    _, o3 = core.reset(params, jax.random.PRNGKey(8))
    assert not np.array_equal(np.asarray(o1[4:]), np.asarray(o3[4:]))


def test_obs_within_bounds(params):
    state, obs = core.reset(params, jax.random.PRNGKey(9))
    for _ in range(20):
        state, ts = core.step(params, state, jnp.asarray(0))
        obs = ts.obs
        assert float(obs.min()) >= 0.0 and float(obs.max()) <= 1.0


def test_baselines(params, reference_table):
    _, obs = core.reset(params, jax.random.PRNGKey(10))
    a = int(cost_greedy_policy(obs))
    row = reference_table.iloc[0]
    assert a == (0 if row["cost_aws"] <= row["cost_azure"] else 1)
    batch = jnp.stack([obs, obs])
    assert cost_greedy_policy(batch).shape == (2,)
    assert int(round_robin_policy(jnp.asarray(0))) == 0
    assert int(round_robin_policy(jnp.asarray(1))) == 1


def test_fault_injection():
    p = core.make_params(EnvConfig(fault_prob=1.0, fault_latency_penalty=1.0))
    state, _ = core.reset(p, jax.random.PRNGKey(11))
    _, ts = core.step(p, state, jnp.asarray(0))
    # with fault_prob=1 the latency term is pinned at the penalty
    expected = -100.0 * (0.6 * float(p.costs[0, 0]) + 0.4 * 1.0)
    np.testing.assert_allclose(float(ts.reward), expected, rtol=1e-5)


def test_max_steps_validation():
    with pytest.raises(ValueError):
        core.make_params(EnvConfig(max_steps=1000))
