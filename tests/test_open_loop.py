"""Open-loop rollout fast path: horizon parity with the scan semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, make_ppo, ppo_train
from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.env import vector
from rl_scheduler_tpu.env.bundle import multi_cloud_bundle, single_cluster_bundle

N, T = 8, 25


@pytest.fixture(scope="module")
def env_params():
    return env_core.make_params(EnvConfig())


@pytest.fixture(scope="module")
def horizon(env_params):
    state, obs = vector.reset_batch(env_params, jax.random.PRNGKey(0), N)
    obs_all, aux, new_state = env_core.open_loop_horizon(
        env_params, state, obs, jax.random.PRNGKey(1), T
    )
    return state, obs, obs_all, aux, new_state


def test_horizon_obs_match_table_and_carry(env_params, horizon):
    state, obs, obs_all, aux, new_state = horizon
    assert obs_all.shape == (T + 1, N, env_core.OBS_DIM)
    # t=0 is the caller's current obs, carried exactly (not re-drawn)
    np.testing.assert_array_equal(np.asarray(obs_all[0]), np.asarray(obs))
    ms = int(env_params.max_steps)
    for t in (1, 7, T):
        idx = (np.asarray(state.step_idx) + t) % ms
        np.testing.assert_allclose(
            np.asarray(obs_all[t, :, 0:2]), np.asarray(env_params.costs)[idx]
        )
        np.testing.assert_allclose(
            np.asarray(obs_all[t, :, 2:4]), np.asarray(env_params.latencies)[idx]
        )
    # CPU noise dims respect the configured range
    cpu = np.asarray(obs_all[1:, :, 4:6])
    assert cpu.min() >= float(env_params.cpu_low)
    assert cpu.max() <= float(env_params.cpu_high)


def test_horizon_dones_and_state_advance(env_params, horizon):
    state, _, _, aux, new_state = horizon
    ms = int(env_params.max_steps)
    s0 = np.asarray(state.step_idx)
    expect_done = ((s0[None, :] + np.arange(T)[:, None]) % ms) == ms - 1
    np.testing.assert_array_equal(np.asarray(aux["dones"]), expect_done.astype(np.float32))
    np.testing.assert_array_equal(
        np.asarray(new_state.step_idx), (s0 + T) % ms
    )
    # per-env keys advanced (fresh streams for any later scan-path step)
    assert not np.array_equal(np.asarray(new_state.key), np.asarray(state.key))


def test_horizon_rewards_match_step_formula(env_params, horizon):
    state, _, _, aux, _ = horizon
    actions = jnp.asarray(np.random.default_rng(0).integers(0, 2, (T, N)), jnp.int32)
    rewards = env_core.open_loop_rewards(env_params, aux, actions)
    ms = int(env_params.max_steps)
    idx = (np.asarray(state.step_idx)[None, :] + np.arange(T)[:, None]) % ms
    a = np.asarray(actions)
    cost = np.asarray(env_params.costs)[idx, a]
    lat = np.asarray(env_params.latencies)[idx, a]
    expect = -100.0 * (0.6 * cost + 0.4 * lat)  # fault_prob=0 by default
    np.testing.assert_allclose(np.asarray(rewards), expect, rtol=1e-6)


def test_fault_injection_parity():
    """fault_prob=1 makes faults deterministic: every step serves at the
    penalty latency in BOTH paths, so rewards must match step() exactly."""
    params = env_core.make_params(
        EnvConfig(fault_prob=1.0, fault_latency_penalty=0.9)
    )
    state, obs = vector.reset_batch(params, jax.random.PRNGKey(0), N)
    _, aux, _ = env_core.open_loop_horizon(
        params, state, obs, jax.random.PRNGKey(1), T
    )
    actions = jnp.asarray(np.random.default_rng(1).integers(0, 2, (T, N)), jnp.int32)
    rewards = env_core.open_loop_rewards(params, aux, actions)
    ms = int(params.max_steps)
    idx = (np.asarray(state.step_idx)[None, :] + np.arange(T)[:, None]) % ms
    cost = np.asarray(params.costs)[idx, np.asarray(actions)]
    expect = -100.0 * (0.6 * cost + 0.4 * 0.9)
    np.testing.assert_allclose(np.asarray(rewards), expect, rtol=1e-6)


def test_horizon_without_reward_fn_rejected(env_params):
    from rl_scheduler_tpu.agent.ppo import make_ppo_bundle

    bad = multi_cloud_bundle(env_params)._replace(horizon_reward_fn=None)
    cfg = PPOTrainConfig(num_envs=4, rollout_steps=8, minibatch_size=16,
                         num_epochs=1, hidden=(8, 8))
    with pytest.raises(ValueError, match="horizon_reward_fn"):
        make_ppo_bundle(bad, cfg)


def test_rewards_statistically_match_scan_path(env_params):
    """Same policy (uniform-random), both rollout paths: per-step reward
    mean over a long horizon must agree (different RNG streams, same
    distribution)."""
    cfg = PPOTrainConfig(num_envs=64, rollout_steps=99, minibatch_size=512,
                         num_epochs=1, hidden=(16, 16))
    means = {}
    for impl in ("scan", "open_loop"):
        import dataclasses

        c = dataclasses.replace(cfg, rollout_impl=impl)
        init_fn, update_fn, _ = make_ppo(env_params, c)
        runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
        _, metrics = jax.jit(update_fn)(runner)
        means[impl] = float(metrics["reward_mean"])
    assert means["scan"] == pytest.approx(means["open_loop"], rel=0.05)


def test_open_loop_training_converges(env_params):
    """End-to-end: open-loop rollout trains to the optimal policy exactly
    like the scan path (mirrors test_ppo_converges_to_optimal_policy)."""
    cfg = PPOTrainConfig(num_envs=16, rollout_steps=99, minibatch_size=512,
                         num_epochs=4, lr=3e-3, hidden=(64, 64),
                         entropy_coeff=0.01, rollout_impl="open_loop")
    runner, history = ppo_train(env_params, cfg, 45, seed=0)
    from rl_scheduler_tpu.models import ActorCritic

    net = ActorCritic(num_actions=env_core.NUM_ACTIONS, hidden=cfg.hidden)
    costs = np.asarray(env_params.costs)
    lats = np.asarray(env_params.latencies)
    obs = np.concatenate(
        [costs, lats, np.full((costs.shape[0], 2), 0.45, np.float32)], axis=1
    )
    logits, _ = net.apply(runner.params, jnp.asarray(obs, jnp.float32))
    learned = np.argmax(np.asarray(logits), axis=1)
    optimal = np.argmin(0.6 * costs + 0.4 * lats, axis=1)
    agreement = float(np.mean(learned == optimal))
    assert agreement >= 0.95, f"only {agreement:.0%} of rows optimal"


def test_rollout_impl_validation(env_params):
    import dataclasses

    from rl_scheduler_tpu.agent.ppo import make_ppo_bundle

    cfg = PPOTrainConfig(num_envs=4, rollout_steps=8, minibatch_size=16,
                         num_epochs=1, hidden=(8, 8))
    with pytest.raises(ValueError, match="horizon_fn"):
        make_ppo_bundle(single_cluster_bundle(),
                        dataclasses.replace(cfg, rollout_impl="open_loop"))
    with pytest.raises(ValueError, match="rollout_impl"):
        make_ppo_bundle(multi_cloud_bundle(env_params),
                        dataclasses.replace(cfg, rollout_impl="bogus"))


def test_auto_uses_scan_for_envs_without_horizon():
    """single_cluster has no horizon_fn: auto must fall back to scan and
    still train."""
    from rl_scheduler_tpu.agent.ppo import make_ppo_bundle

    cfg = PPOTrainConfig(num_envs=4, rollout_steps=16, minibatch_size=32,
                         num_epochs=1, hidden=(8, 8))
    init_fn, update_fn, _ = make_ppo_bundle(single_cluster_bundle(), cfg)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    _, metrics = jax.jit(update_fn)(runner)
    assert np.isfinite(float(metrics["policy_loss"]))
