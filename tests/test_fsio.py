"""Regression tests for utils/fsio — the canonical GL013/GL014 fixes.

``atomic_write_json`` (grown out of studies/runner.py) is now the one
write path behind comparison.json, eval reports, the transfer grid, and
every studies ledger; ``fresh_dir`` is the EAFP recreate behind
loopback's trace snapshots and fleet_snapshot. These tests pin the
crash/race semantics the GL013/GL014 lint rules exist to protect.
"""
from __future__ import annotations

import json
import os
import shutil

import pytest

from rl_scheduler_tpu.utils.fsio import atomic_write_json, fresh_dir


def test_atomic_write_json_crash_before_replace_keeps_old_file(
        tmp_path, monkeypatch):
    """The GL013 contract: a writer killed mid-write leaves either the
    OLD complete file or the NEW complete file — never a torn one."""
    path = tmp_path / "comparison.json"
    atomic_write_json(path, {"verdict": "old"})

    real_replace = os.replace

    def crash(src, dst):
        raise OSError("simulated SIGKILL before rename")

    monkeypatch.setattr(os, "replace", crash)
    with pytest.raises(OSError, match="simulated"):
        atomic_write_json(path, {"verdict": "new"})
    monkeypatch.setattr(os, "replace", real_replace)

    # The reader still sees the old COMPLETE artifact.
    assert json.loads(path.read_text()) == {"verdict": "old"}
    # The half-written attempt is a .tmp sibling, never the target.
    leftovers = list(tmp_path.glob(".comparison.json.*.tmp"))
    assert len(leftovers) == 1


def test_atomic_write_json_tmp_name_is_per_writer_unique(tmp_path,
                                                         monkeypatch):
    """Concurrent writers of the same target must each rename their OWN
    complete file — the tmp name carries the pid, so two workers racing
    on a shared threshold cache never truncate each other's tmp."""
    seen = []
    real_replace = os.replace

    def record(src, dst):
        seen.append(os.path.basename(str(src)))
        return real_replace(src, dst)

    monkeypatch.setattr(os, "replace", record)
    atomic_write_json(tmp_path / "cache.json", {"t": 1})
    assert seen == [f".cache.json.{os.getpid()}.tmp"]


def test_fresh_dir_creates_wipes_and_tolerates_concurrent_delete(
        tmp_path, monkeypatch):
    dest = tmp_path / "snap"
    # Absent: created.
    assert fresh_dir(dest) == dest and dest.is_dir()
    # Present with content: wiped and recreated empty.
    (dest / "stale.json").write_text("{}")
    fresh_dir(dest)
    assert list(dest.iterdir()) == []

    # The GL014 race this replaced `if exists(): rmtree()` to survive:
    # a concurrent deleter wins the rmtree — "already gone" is fine.
    def racing_rmtree(p, **kw):
        raise FileNotFoundError(p)

    monkeypatch.setattr(shutil, "rmtree", racing_rmtree)
    fresh_dir(tmp_path / "snap2")
    assert (tmp_path / "snap2").is_dir()


def test_fresh_dir_surfaces_concurrent_creator(tmp_path, monkeypatch):
    """A concurrent CREATOR is a real conflict (two snapshotters told to
    own the same dest) and must not be silenced by the EAFP rewrite."""
    dest = tmp_path / "snap"
    dest.mkdir()
    monkeypatch.setattr(shutil, "rmtree", lambda p, **kw: None)  # racer
    with pytest.raises(FileExistsError):
        fresh_dir(dest)


def test_studies_runner_still_reexports_atomic_write_json():
    """The implementation moved to utils/fsio when the discipline went
    repo-wide; studies/runner.py re-exports it for existing importers."""
    from rl_scheduler_tpu.studies import runner

    assert runner.atomic_write_json is atomic_write_json
