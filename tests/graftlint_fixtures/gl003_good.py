"""GL003 negative fixture: every static branch idiom the repo relies on."""

import functools

import jax
import jax.numpy as jnp


@jax.jit
def shape_driven(x):
    n = x.shape[-1]
    if n % 8:                         # static: shapes are Python ints
        x = jnp.pad(x, ((0, 0), (0, 8 - n % 8)))
    if x.ndim == 3 and len(x.shape) == 3:   # static metadata
        x = x.reshape(-1, x.shape[-1])
    return jnp.where(jnp.sum(x) > 0, x, -x)   # tracer branch done right


@jax.jit
def optional_arg(x, mask=None):
    if mask is not None:              # `is None` is a static Python test
        x = x * mask
    if isinstance(x, tuple):          # type checks are static
        x = x[0]
    return x


@functools.partial(jax.jit, static_argnames=("block_n",))
def blocked(x, block_n):
    if block_n > 8:                   # static_argnames param: a Python int
        return x.reshape(-1, block_n)
    return x
