"""GL001 negative fixture: the same calls OUTSIDE traced scopes (the
adapter-boundary pattern) plus static metadata reads inside one."""

import jax
import jax.numpy as jnp


@jax.jit
def update(state):
    # Shape/metadata reads are static under trace — not syncs.
    n = state.shape[0]
    return state / jnp.asarray(n, state.dtype)


def adapter_step(params, state, action):
    state, ts = update_step(params, state, action)
    # Boundary code: conversions AFTER the jitted call returned are fine
    # (one combined fetch, so GL008 stays quiet too).
    reward, done = jax.device_get((ts.reward, ts.done))
    return state, float(reward), bool(done)


def update_step(params, state, action):
    return state, state
