"""Good fixture: the three safe shapes around scalar pytree leaves.

Arrays-only fields on the traced-argument type; Python scalars on a
container that stays CLOSED OVER (never a traced argument — the
``ClusterSetParams.random_phase`` pattern); and a plain dataclass,
which is not a pytree at all (jit rejects it loudly, not late).
"""
import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp


class EnvP(NamedTuple):
    rates: jnp.ndarray  # arrays only on the traced-argument type
    horizon: jnp.ndarray = jnp.ones(())


class PhaseCfg(NamedTuple):
    random_phase: bool = False  # never a traced argument: closed over


@dataclasses.dataclass
class TrainCfg:
    lr: float = 3e-4  # plain dataclass: not a pytree, out of scope


@jax.jit
def apply_prices(params: EnvP, load):
    return load * params.rates


def make_step(cfg: PhaseCfg):
    # The scalar rides the CLOSURE, not the trace boundary.
    shift = 1.0 if cfg.random_phase else 0.0

    @jax.jit
    def step(load):
        return load + shift

    return step
