"""GL000 fixture: suppression hygiene (2 GL000 findings + 1 suppressed
GL002 + 1 UNsuppressed GL002 because its comment lacks a justification)."""

import jax


def justified(key):
    a = jax.random.normal(key, (4,))
    # graftlint: disable=GL002 -- fixture: deliberately correlated draws to document the suppression syntax
    b = jax.random.uniform(key, (4,))
    return a + b


def unjustified(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))  # graftlint: disable=GL002
    return a + b


def unknown_rule(x):
    return x  # graftlint: disable=GL999 -- no such rule
