"""GL009 positive fixture: per-iteration host fetches in a logging loop (3)."""

import jax


def train_loop(update, runner, steps, log_fn):
    for i in range(steps):
        runner, metrics = update(runner)
        loss = float(metrics["loss"])        # per-step concretization sync
        grad = metrics["grad_norm"].item()   # ... a second sync
        row = jax.device_get(metrics)        # ... and a third, unbatched
        log_fn(i, {"loss": loss, "grad": grad, **row})
