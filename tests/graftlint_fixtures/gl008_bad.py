"""GL008 positive fixture: per-field host conversions of one timestep (1)."""


def adapter_step(env, action):
    state, ts = env.step_fn(env.params, action)
    reward = float(ts.reward)     # one device round-trip
    done = bool(ts.done)          # ... and another, for the same timestep
    return state, reward, done
