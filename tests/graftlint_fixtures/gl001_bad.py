"""GL001 positive fixture: host syncs inside traced scopes (3 findings).

Never imported — parsed by the graftlint self-tests only.
"""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def update(state):
    loss = jnp.sum(state)
    scale = float(loss)            # GL001: concretizes a tracer
    fetched = jax.device_get(loss)  # GL001: device_get inside the trace
    return state * scale + fetched


def helper(x):
    # Traced one call-graph level deep: `body` below is scanned and calls
    # helper by name.
    return x * np.asarray(x)       # GL001: np pull on a tracer


def rollout(init):
    def body(carry, _):
        carry = helper(carry)
        return carry, carry

    return jax.lax.scan(body, init, None, length=4)
