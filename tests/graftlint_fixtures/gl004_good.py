"""GL004 negative fixture: donation present, or nothing to donate."""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class Runner(NamedTuple):
    params: dict
    opt_state: dict


@jax.jit
def metrics_only(runner: Runner):
    # Reads an argument, returns fresh scalars — no update, no donation
    # needed.
    return {"norm": jnp.sum(runner.params["w"])}


def train_step(runner: Runner):
    return Runner(params=runner.params, opt_state=runner.opt_state)


update = jax.jit(train_step, donate_argnums=0)


def init_fn(key):
    # Produces a fresh tree from a PRNG key: not an updated argument.
    params = {"w": jax.random.normal(key, (4, 4))}
    return Runner(params=params, opt_state=optax.adam(1e-3).init(params))


jit_init = jax.jit(init_fn)
