"""Deliberately-bad fixture: Python-scalar leaves on traced pytrees.

``EnvP`` crosses the trace boundary as an argument of a jitted
function; its ``bool``/``int`` defaults are pytree leaves that become
tracers under the transform — ``if params.random_start:`` then raises
TracerBoolConversionError (the PR-7 ``random_start`` near-miss).
"""
from typing import NamedTuple

import jax
import jax.numpy as jnp


class EnvP(NamedTuple):
    rates: jnp.ndarray
    random_start: bool = False  # GL016: bool leaf on a traced argument
    horizon: int = 128          # GL016: int leaf on a traced argument


@jax.jit
def apply_prices(params: EnvP, load):
    return load * params.rates
