"""GL003 positive fixture: Python control flow on tracer values (2)."""

import jax
import jax.numpy as jnp


@jax.jit
def clip_positive(x):
    total = jnp.sum(x)
    if total > 0:                 # GL003: tracer boolean
        return x
    return -x


@jax.jit
def drain(x):
    while jnp.any(x > 0):         # GL003: tracer loop condition
        x = x - 1.0
    return x
