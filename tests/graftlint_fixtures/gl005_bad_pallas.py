"""GL005 positive fixture: misaligned tiles (3) + VMEM oversubscription (1)."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def kernel(x_ref, o_ref):
    acc = jnp.zeros((8, 100), jnp.float32)   # GL005: 100 lanes -> pad to 128
    o_ref[...] = x_ref[...] + acc[:, :100]


def run(x):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec((48, 100), lambda i: (i, 0))],  # GL005
        out_specs=pl.BlockSpec((48, 100), lambda i: (i, 0)),   # GL005
        grid=(4,),
    )(x)


def run_oversubscribed(x):
    # GL005: 2 x (8192, 512) f32 blocks = 32 MiB static footprint > 16 MiB.
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec((8192, 512), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8192, 512), lambda i: (i, 0)),
        grid=(1,),
    )(x)
