"""GL002 negative fixture: the split/fold_in discipline this repo uses."""

import jax


def sample_twice(key):
    akey, bkey = jax.random.split(key)
    a = jax.random.normal(akey, (4,))
    b = jax.random.uniform(bkey, (4,))
    return a + b


def sample_in_loop(key, steps):
    total = 0.0
    for i in range(steps):
        # fold_in derives a fresh key per iteration: a derivation, not a
        # consumption — the ppo eval-hook idiom.
        total += jax.random.normal(jax.random.fold_in(key, i), ())
    return total


def reassigned_in_loop(key, steps):
    total = 0.0
    for _ in range(steps):
        key, draw = jax.random.split(key)
        total += jax.random.normal(draw, ())
    return total
