"""Stand-in test corpus for the GL007 self-tests (not a pytest module).

References the good fixture's public op and deliberately nothing from the
bad fixture. Also references every public name in the OTHER rules'
fixtures that live under GL007-covered dirs (the scheduler/ GL010 pair —
covered since graftroll extended OP_DIRS), keeping those fixtures
single-rule by construction.
"""

from fixtures.ops.gl007_good import covered_op


def check_covered_op():
    assert covered_op is not None


def check_gl010_fixture_names_are_covered():
    # scheduler/gl010_bad.py + gl010_good.py public surface: scrape_cpu,
    # place_pod, read_stats, score_node, parse_quantity, load_table,
    # restore_checkpoint — referenced here so only GL010 fires there.
    pass


def check_gl011_fixture_names_are_covered():
    # scheduler/gl011_bad.py + gl011_good.py public surface:
    # measure_decide, record_request, trial_wall_seconds,
    # measure_decide_monotonic, cache_age_seconds, stamp_record,
    # one_hour_ago — referenced here so only GL011 fires there.
    pass


def check_gl012_fixture_names_are_covered():
    # scheduler/gl012_bad.py + gl012_good.py public surface: handle,
    # fetch, probe, dispatch, with_helper, sync_path — referenced here
    # so only GL012 fires there.
    pass


def check_gl013_gl014_fixture_names_are_covered():
    # scheduler/gl013_*.py + gl014_*.py public surface: write_manifest,
    # write_cache, start, atomic_write_json, staged_write, emit_stream,
    # refresh, clear_lock, seed_default, fresh_under_lock,
    # read_if_present — referenced here so only GL013/GL014 fire there.
    pass


def check_gl015_gl017_fixture_names_are_covered():
    # scheduler/gl015_*.py + gl017_*.py + gl_audit_stale.py public
    # surface: TelemetryPush, Backend, push_aws, push_azure, push, call,
    # Recorder, Courier, close, poll_workers, make_server —
    # referenced here so only GL015/GL017 (and the stale audit) fire.
    pass
