"""Stand-in test corpus for the GL007 self-tests (not a pytest module).

References the good fixture's public op and deliberately nothing from the
bad fixture.
"""

from fixtures.ops.gl007_good import covered_op


def check_covered_op():
    assert covered_op is not None
