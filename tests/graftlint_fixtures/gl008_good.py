"""GL008 negative fixture: one batched fetch, then host-side conversion."""

import jax


def adapter_step(env, action):
    state, ts = env.step_fn(env.params, action)
    reward, done = jax.device_get((ts.reward, ts.done))
    return state, float(reward), bool(done)


def single_conversion(env, action):
    state, ts = env.step_fn(env.params, action)
    return state, float(ts.reward)    # one field, one sync: fine
