"""GL005 negative fixture: aligned literals, symbolic shapes, 1-row blocks."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def kernel(x_ref, o_ref):
    acc = jnp.zeros((8, 128), jnp.float32)       # aligned f32 tile
    row = jnp.zeros((1, 128), jnp.float32)       # 1-row blocks are legal
    o_ref[...] = x_ref[...] + acc + row


def run(x, block_rows):
    # Symbolic shapes are the author's runtime contract — lint stays out.
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec((block_rows, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((64, 128), lambda i: (i, 0)),
        grid=(4,),
        scratch_shapes=[pltpu.VMEM((256, 128), jnp.float32)],
    )(x)
