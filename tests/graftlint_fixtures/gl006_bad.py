"""GL006 positive fixture: dtype-less float-literal arrays in traced code (2)."""

import jax
import jax.numpy as jnp


@jax.jit
def loss(x):
    eps = jnp.asarray(1e-8)             # GL006: weak-typed constant
    floor = jnp.full((8,), 0.5)         # GL006: weak-typed fill
    return jnp.sum(x / (x + eps)) + jnp.sum(floor)
