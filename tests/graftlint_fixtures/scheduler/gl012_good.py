"""GL012 negative fixture: coroutines that await, delegate blocking
work to the executor, or keep sync calls inside nested sync defs."""

import asyncio
import time


async def handle(reader, writer):
    await asyncio.sleep(0.01)
    body = await reader.readexactly(4)
    writer.write(body)
    await writer.drain()


async def dispatch(loop, executor, policy, body):
    return await loop.run_in_executor(executor, policy.decide, body)


async def with_helper():
    def helper():
        # A nested sync def only defines; it runs on an executor
        # thread, not on the loop.
        time.sleep(0.0)
        return 0

    return await asyncio.get_running_loop().run_in_executor(None, helper)


def sync_path():
    # Not a coroutine: blocking here never touches an event loop.
    time.sleep(0.0)
