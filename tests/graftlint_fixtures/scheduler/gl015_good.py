"""Good fixture: per-key breaker instances, and the one-endpoint case.

The dict-comprehension construction is per-key discipline (telemetry/
k8s_client); a single breaker guarding a single dependency takes no key
at all.
"""
from rl_scheduler_tpu.scheduler.telemetry import CircuitBreaker


class TelemetryPush:
    def __init__(self, clouds):
        # Per-key construction: each endpoint owns its failure domain.
        self.breakers = {c: CircuitBreaker(threshold=5) for c in clouds}

    def push(self, cloud, payload):
        if self.breakers[cloud].allow():
            self._post(cloud, payload)

    def _post(self, cloud, payload):
        del cloud, payload


class Backend:
    def __init__(self):
        self.breaker = CircuitBreaker(threshold=3)  # one dependency: fine

    def call(self, request):
        if self.breaker.allow():
            return self._send(request)
        return None

    def _send(self, request):
        del request
