"""Good fixture: the two accepted closures of the check-act window.

EAFP (act, tolerate "already gone") and check-under-lock (the pidlock
seam makes check-then-act the LOCK's semantics, not a race).
"""
import os
import shutil

from rl_scheduler_tpu.utils.pidlock import acquire_pidfile_lock


def refresh(dest):
    try:
        shutil.rmtree(dest)
    except FileNotFoundError:
        pass  # concurrent delete won: nothing left to remove
    dest.mkdir(parents=True)


def clear_lock(lock_path):
    lock_path.unlink(missing_ok=True)


def fresh_under_lock(study_dir):
    fd = acquire_pidfile_lock(study_dir / "runner.pid")
    trials = study_dir / "trials"
    if trials.exists():  # held lock: the window is closed by design
        shutil.rmtree(trials)
    os.close(fd)


def read_if_present(path):
    # Check then READ is outside the rule: the racing acts are the
    # destructive/creating ones.
    if path.exists():
        return path.read_text()
    return None
