"""Deliberately-bad fixture: drain contracts that don't drain.

A timed join on a daemon thread with no ``is_alive()`` verdict, and a
socketserver whose ``daemon_threads = True`` voids ``server_close()``'s
handler join (the graftroll record-loss race).
"""
import threading
from http.server import ThreadingHTTPServer


class Recorder:
    def __init__(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        pass

    def close(self):
        self._thread.join(timeout=5.0)  # GL017: wedged writer unnoticed
        self._seal()

    def _seal(self):
        pass


def make_server(handler_cls):
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
    server.daemon_threads = True  # GL017: server_close() skips the join
    return server
