"""GL011 positive fixture: wall-clock deltas used as durations in a
(fixture) scheduler/ path. Expected findings: 3."""

import time
from time import time as now


def measure_decide(backend, obs):
    t0 = time.time()
    action = backend.decide(obs)
    latency_s = time.time() - t0  # finding 1: wall-clock duration
    return action, latency_s


def record_request(stats, start_ts):
    # finding 2: direct time.time() call on one side of the delta
    stats.record(time.time() - start_ts)


def trial_wall_seconds():
    t_start = now()
    run_trial = sum(range(100))
    return now() - t_start, run_trial  # finding 3: from-import variant
