"""GL011 negative fixture: monotonic clocks for durations, wall clock
only as a timestamp or epoch arithmetic. Expected findings: 0."""

import time


def measure_decide_monotonic(backend, obs):
    t0 = time.perf_counter()
    action = backend.decide(obs)
    return action, time.perf_counter() - t0  # monotonic: correct


def cache_age_seconds(cached_at):
    return time.monotonic() - cached_at  # monotonic: correct


def stamp_record(record):
    record["ts"] = round(time.time(), 6)  # timestamp, not a duration
    return record


def one_hour_ago():
    return time.time() - 3600  # epoch arithmetic: a point in time
