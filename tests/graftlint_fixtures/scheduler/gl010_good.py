"""GL010 negative fixture: every broad handler in this (fixture)
scheduler/ path observes what it swallows. Expected findings: 0."""

import logging
import warnings

logger = logging.getLogger(__name__)


def scrape_cpu(url):
    try:
        return float(open(url).read())
    except Exception:
        logger.exception("scrape failed; serving fallback")
        return 0.5


def place_pod(client, cloud):
    try:
        client.create(cloud)
        return True
    except Exception as e:
        print(f"pod placement on {cloud} failed: {e}")
        return False


def read_stats(path):
    try:
        with open(path) as fh:
            return fh.read()
    except Exception:
        warnings.warn("stats file unreadable; returning empty")
        return ""


def restore_checkpoint(mgr, step):
    try:
        return mgr.restore(step)
    except Exception as e:
        # Re-raising (translated) also satisfies the rule: the failure
        # stays observable to the caller.
        raise RuntimeError(f"checkpoint {step} failed to restore") from e


def parse_quantity(raw):
    try:
        return int(raw)
    except (ValueError, TypeError):  # narrow catches stay unflagged
        return None
