"""GL010 positive fixture: broad exception handlers in a (fixture)
scheduler/ path that swallow failures silently. Expected findings: 4."""

import logging
import math

logger = logging.getLogger(__name__)


def scrape_cpu(url):
    try:
        return float(open(url).read())
    except Exception:  # finding 1: broad catch, no log, no raise
        return 0.5


def place_pod(client, cloud):
    try:
        client.create(cloud)
        return True
    except:  # noqa: E722 — finding 2: bare except, silent fallback
        return False


def read_stats(path):
    try:
        with open(path) as fh:
            return fh.read()
    except (OSError, Exception):  # finding 3: tuple containing a broad type
        return ""


def score_node(cpu):
    try:
        return 1.0 / cpu
    except Exception:  # finding 4: math.log is not logging — the method
        # name alone must not satisfy the rule
        return math.log(2.0)


def parse_quantity(raw):
    try:
        return int(raw)
    except ValueError:  # NOT a finding: narrow catch is a deliberate pattern
        return None


def load_table(path):
    try:
        return open(path).read()
    except Exception as e:  # NOT a finding: logs what it swallowed
        logger.warning("table load failed: %s", e)
        return None
