"""Deliberately-bad fixture: check-then-act TOCTOU windows.

Every pair checks existence of a path expression and then acts on the
SAME expression with nothing closing the window — another process wins
the race between the two lines.
"""
import os
import shutil


def refresh(dest):
    if dest.exists():
        shutil.rmtree(dest)  # GL014: dest can vanish/appear in between
    dest.mkdir(parents=True)


def clear_lock(lock_path):
    if lock_path.is_file():
        os.remove(lock_path)  # GL014: a new holder can recreate it first


def seed_default(path, payload):
    if not path.exists():
        path.write_text(payload)  # GL014: two seeders both pass the check
