"""Deliberately-bad fixture: non-atomic durable JSON artifact writes.

Each write lands a ``*.json`` artifact through a plain write — a reader
overlapping the write observes a torn file (the threshold-cache race).
"""
import json
import threading


def write_manifest(dest, payload):
    (dest / "manifest.json").write_text(json.dumps(payload))  # GL013


def write_cache(path, obj):
    name = f"{path.stem}.json"
    out = path.parent / name
    with out.open("w") as fh:
        json.dump(obj, fh)  # GL013: 'w' handle resolved through def-use


def _writer(path, obj):
    # GL013, and the context model tags this as a thread target: the
    # torn window is concurrent by construction.
    path.with_suffix(".json").write_text(json.dumps(obj))


def start(path, obj):
    worker = threading.Thread(target=_writer, args=(path, obj))
    worker.start()
    return worker
