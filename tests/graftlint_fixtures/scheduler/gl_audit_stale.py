"""Deliberately-stale suppression: the audit must flag this file.

The disable comment below is justified and parses cleanly — but the
write it once excused has since been made atomic, so GL013 no longer
fires on the covered lines. A justification that outlived its code is a
silenced alarm: the suppression audit turns it into a gate failure.
"""
import json
import os


def write_manifest(dest, payload):
    # graftlint: disable=GL013 -- manifest write predates the atomic idiom
    tmp = dest / ".manifest.json.tmp"
    tmp.write_text(json.dumps(payload))
    os.replace(tmp, dest / "manifest.json")
