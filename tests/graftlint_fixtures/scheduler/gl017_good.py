"""Good fixture: honored drain contracts.

The timed join takes the is_alive() verdict (wedged branch leaves
sealing to recovery), the bare join is a guaranteed drain, fan-out
polling with join(timeout) outside a drain path is by-design, and the
server keeps non-daemon handler threads so server_close() drains.
"""
import threading
from http.server import ThreadingHTTPServer


class Recorder:
    def __init__(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        pass

    def close(self):
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():  # verdict taken: wedged branch
            return
        self._seal()

    def _seal(self):
        pass


class Courier:
    def __init__(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        pass

    def close(self):
        self._thread.join()  # bare join: guaranteed drain, never flagged


def poll_workers(jobs):
    threads = []
    for job in jobs:
        t = threading.Thread(target=job, daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=0.5)  # fan-out poll, not a drain path
    return threads


def make_server(handler_cls):
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
    server.daemon_threads = False  # server_close() joins handlers
    return server
