"""GL012 positive fixture: synchronous blocking calls inside async
defs on the serving data plane (each one parks the whole event loop)."""

import time
import urllib.request


async def handle(reader, writer):
    time.sleep(0.1)
    data = open("/tmp/fixture").read()
    writer.write(data.encode())
    await writer.drain()


async def fetch(url):
    return urllib.request.urlopen(url).read()


async def probe(sock):
    conn, _ = sock.accept()
    return conn.recv(4096)
