"""Deliberately-bad fixture: one breaker shared across endpoint keys.

Two endpoints funnel failures into a single CircuitBreaker — a flapping
``aws`` dilutes (or poisons) the ``azure`` signal and the breaker never
opens cleanly under mixed traffic (the telemetry/k8s defect, twice).
"""
from rl_scheduler_tpu.scheduler.telemetry import CircuitBreaker


class TelemetryPush:
    def __init__(self):
        self.breaker = CircuitBreaker(threshold=5)  # GL015: one for all keys

    def push_aws(self, payload):
        if self.breaker.allow("aws"):
            self._post("aws", payload)

    def push_azure(self, payload):
        if self.breaker.allow("azure"):
            self._post("azure", payload)

    def _post(self, cloud, payload):
        del cloud, payload
