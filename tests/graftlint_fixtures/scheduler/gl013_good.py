"""Good fixture: every durable JSON write rides an atomic idiom.

Covers the three accepted shapes — the pid-unique tmp sibling
(``atomic_write_json``), the unnamed-tmp write-then-rename, and the
``.jsonl`` line-stream exemption (torn tails are the recovery layer's
job, not tmp-then-rename's).
"""
import json
import os


def atomic_write_json(path, obj):
    tmp = path.parent / f".{path.name}.{os.getpid()}.tmp"
    tmp.write_text(json.dumps(obj))
    os.replace(tmp, path)


def write_manifest(dest, payload):
    atomic_write_json(dest / "manifest.json", payload)


def staged_write(path, obj):
    staging = path.parent / "staging.json"
    staging.write_text(json.dumps(obj))  # renamed below: the tmp half
    os.replace(staging, path)


def emit_stream(dest, rows):
    with (dest / "events.jsonl").open("w") as fh:  # line stream: exempt
        for row in rows:
            fh.write(json.dumps(row) + "\n")
