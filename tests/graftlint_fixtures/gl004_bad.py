"""GL004 positive fixture: train-step-shaped jits without donation (2)."""

from typing import NamedTuple

import jax
import optax


class Runner(NamedTuple):
    params: dict
    opt_state: dict


@jax.jit
def train_step(runner: Runner):          # GL004: returns updated Runner
    grads = runner.params
    return Runner(params=grads, opt_state=runner.opt_state)


def sgd(params, grads, opt_state, tx):
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state


jitted_sgd = jax.jit(sgd)                # GL004: rebinds + returns `params`
