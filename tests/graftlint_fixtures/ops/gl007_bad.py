"""GL007 positive fixture (lives under an ``ops/`` dir on purpose): one
public op with no test reference (1 finding)."""

import jax.numpy as jnp


def totally_untested_op(x):              # GL007: nothing references this
    return jnp.cumsum(x, axis=-1)


def _private_helper(x):                  # private: out of scope
    return x
