"""GL007 negative fixture: the public op IS referenced by the corpus."""

import jax.numpy as jnp


def covered_op(x):
    return jnp.cumsum(x, axis=-1)


def _private_helper(x):
    return x
