"""GL002 positive fixture: key reuse, linear and loop-carried (2 findings)."""

import jax


def sample_twice(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))   # GL002: key already consumed
    return a + b


def sample_in_loop(key, steps):
    total = 0.0
    for _ in range(steps):
        # GL002: same key every iteration — identical draws.
        total += jax.random.normal(key, ())
    return total
