"""GL009 negative fixture: the window-gated / measurement-only shapes."""

import jax


def train_loop(update, runner, steps, window, merge, log_fn):
    """Device-side accumulation, ONE batched fetch per logging window."""
    acc = None
    for i in range(steps):
        runner, metrics = update(runner)
        acc = metrics if acc is None else merge(acc, metrics)  # on device
        if (i + 1) % window == 0:
            host = jax.device_get(acc)  # the window's single fetch
            log_fn(i, {k: float(v) for k, v in host.items()})
            acc = None
    return runner


def measure(update, runner, steps):
    """Fetch-synced measurement loop: the fetch IS the measurement and
    nothing logs per step — GL009 stays silent (GL001/GL008 territory)."""
    total = 0.0
    for _ in range(steps):
        runner, metrics = update(runner)
        total += float(metrics["loss"])
    return runner, total


def convert_fetched(pending, log_fn):
    """Converting an already-fetched result is free: the batched
    ``device_get`` happened BEFORE the loop, so ``float()`` here touches
    host memory only."""
    host_rows = jax.device_get(pending)
    for i in range(len(host_rows)):
        log_fn(i, float(host_rows[i]))
