"""GL006 negative fixture: explicit dtypes, scalar-literal arithmetic, and
host-side constants."""

import jax
import jax.numpy as jnp

# Module scope is not traced: weak typing here is resolved once at import.
_TABLE = jnp.asarray(0.25)


@jax.jit
def loss(x):
    eps = jnp.asarray(1e-8, x.dtype)        # dtype pinned
    floor = jnp.full((8,), 0.5, jnp.float32)
    ints = jnp.asarray(3)                   # int literals don't promote floats
    return jnp.sum(x / (x + eps)) * 0.5 + jnp.sum(floor) + ints
