"""Units for the graftflow dataflow tier (tools/graftlint/flow).

The GL013–GL017 rule pack rides on three small analyses: intra-scope
def-use chains with a string lattice (``defuse.py``), canonical path
expressions, and execution-context tagging (``context.py``). These
tests pin each analysis in isolation — the fixture-driven tests in
test_graftlint.py only prove the composed rules, so a regression here
would otherwise surface as an opaque fixture-count mismatch.

Everything is pure-AST (ast.parse on inline sources); no JAX import,
so the suite costs milliseconds and is identical on both JAX versions.
"""
from __future__ import annotations

import ast
import textwrap
from pathlib import Path

from tools.graftlint.engine import Module
from tools.graftlint.rules import load_rules
from tools.graftlint.flow import (
    DefUse,
    flows_through,
    literal_strings,
    module_contexts,
    path_expr,
    scope_statements,
)


def _module(source: str, rel: str = "scheduler/mod.py") -> Module:
    src = textwrap.dedent(source)
    return Module(Path(rel), rel, src, known_rules=set(load_rules()))


def _fn(source: str) -> ast.FunctionDef:
    tree = ast.parse(textwrap.dedent(source))
    node = tree.body[0]
    assert isinstance(node, ast.FunctionDef)
    return node


def _expr(source: str) -> ast.AST:
    return ast.parse(source, mode="eval").body


# ---------------------------------------------------------------- DefUse


def test_defuse_reassignment_picks_newest_binding():
    fn = _fn(
        """
        def f():
            p = a
            p = b
            use(p)
        """
    )
    du = DefUse(fn)
    # Two bindings recorded in line order (the dedented source has a
    # leading blank line: def on 2, bindings on 3 and 4, use on 5);
    # value_at resolves the reaching definition for any later use line.
    assert [v.id for v in du.values("p")] == ["a", "b"]
    assert du.value_at("p", 5).id == "b"
    # A use between the bindings sees only the first one.
    assert du.value_at("p", 3).id == "a"
    # Before any binding: no reaching definition.
    assert du.value_at("p", 2) is None


def test_defuse_loop_carried_binding_resolves_to_iterable():
    fn = _fn(
        """
        def f(paths):
            for p in paths:
                touch(p)
        """
    )
    du = DefUse(fn)
    (value,) = du.values("p")
    assert isinstance(value, ast.Name) and value.id == "paths"


def test_defuse_with_tuple_and_walrus_bindings():
    fn = _fn(
        """
        def f():
            with open(src) as fh:
                a, b = pair()
        """
    )
    du = DefUse(fn)
    (with_value,) = du.values("fh")
    assert isinstance(with_value, ast.Call)  # the context expression
    # Tuple targets: each element bound to the whole right-hand side.
    assert isinstance(du.values("a")[0], ast.Call)
    assert isinstance(du.values("b")[0], ast.Call)


def test_defuse_module_scope_and_scope_statements():
    tree = ast.parse("x = 1\ny = x\n")
    du = DefUse(tree)
    assert du.value_at("y", 2).id == "x"
    assert [s.lineno for s in scope_statements(tree)] == [1, 2]


def test_defuse_skips_nested_function_bodies():
    fn = _fn(
        """
        def f():
            p = outer
            def g():
                p = inner
            return p
        """
    )
    du = DefUse(fn)
    # g's rebinding is a different scope; it must not shadow f's chain.
    assert [v.id for v in du.values("p")] == ["outer"]


# ------------------------------------------------------------- path_expr


def test_path_expr_canonical_forms():
    assert path_expr(_expr("dest")) == "dest"
    assert path_expr(_expr("self._queue")) == "self._queue"
    assert path_expr(_expr("qdir / name")) == "(qdir/name)"
    assert path_expr(_expr("cache['run']")) == "cache['run']"


def test_path_expr_unwraps_path_transparent_calls():
    # A check on `p` must match an act on `str(p)` / `Path(p)` /
    # `p.resolve()` — wrappers canonicalize to their operand.
    assert path_expr(_expr("str(p)")) == "p"
    assert path_expr(_expr("Path(p)")) == "p"
    assert path_expr(_expr("p.resolve()")) == "p"
    assert path_expr(_expr("os.fspath(p)")) == "p"


def test_path_expr_parent_is_a_different_path():
    assert path_expr(_expr("p.parent")) == "p.parent"
    assert path_expr(_expr("p.parent")) != path_expr(_expr("p"))


def test_path_expr_unstable_identity_is_none():
    # Call results have no stable identity: never-matching, not a guess.
    assert path_expr(_expr("make_path()")) is None
    assert path_expr(_expr("a @ b")) is None


# ------------------------------------------------------- literal_strings


def test_literal_strings_fstring_and_concat():
    assert literal_strings(_expr("f'{stem}.json'")) == {".json"}
    assert literal_strings(_expr("base + '.tmp'")) == {".tmp"}
    assert literal_strings(_expr("Path('out') / name")) == {"out"}


def test_literal_strings_follows_defuse_hops():
    fn = _fn(
        """
        def f(dest):
            name = f"{dest.stem}.json"
            target = dest / name
            write(target)
        """
    )
    du = DefUse(fn)
    target = du.value_at("target", 4)
    assert ".json" in literal_strings(target, du)


def test_literal_strings_lineno_resolves_reaching_definition():
    fn = _fn(
        """
        def f():
            suffix = ".tmp"
            suffix = ".json"
            use(suffix)
        """
    )
    du = DefUse(fn)
    probe = _expr("suffix")
    # At the use line only the newest binding reaches...
    assert literal_strings(probe, du, lineno=4) == {".json"}
    # ...while the un-pinned query is a may-analysis over all bindings.
    assert literal_strings(probe, du) == {".tmp", ".json"}


def test_literal_strings_hop_bound_terminates():
    fn = _fn(
        """
        def f():
            a = ".json"
            b = a
            c = b
            d = c
            use(d)
        """
    )
    du = DefUse(fn)
    # d -> c -> b -> a is 4 hops; the 3-hop bound stops at `a`'s Name.
    assert literal_strings(_expr("d"), du) == set()
    assert literal_strings(_expr("c"), du) == {".json"}


# --------------------------------------------------------- flows_through


def test_flows_through_direct_and_via_defuse():
    fn = _fn(
        """
        def f():
            fd = os.open(path, os.O_WRONLY | os.O_EXCL)
            handle = fd
            write(handle)
        """
    )
    du = DefUse(fn)
    assert flows_through(du.value_at("fd", 3), {"O_EXCL"})
    # Transitively through the def-use hop handle -> fd.
    assert flows_through(_expr("handle"), {"O_EXCL"}, du)
    assert not flows_through(_expr("handle"), {"mkstemp"}, du)


# ------------------------------------------------------- module_contexts


def test_context_handler_tags_transitive_subclasses():
    module = _module(
        """
        from http.server import BaseHTTPRequestHandler

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                pass

        class MetricsHandler(Handler):
            def do_POST(self):
                pass

        def helper():
            pass
        """
    )
    tags = module_contexts(module)
    assert "handler" in tags["Handler.do_GET"]
    # Transitive: a subclass of a local handler subclass is one too.
    assert "handler" in tags["MetricsHandler.do_POST"]
    assert tags["helper"] == frozenset({"main"})


def test_context_thread_process_and_executor_seams():
    module = _module(
        """
        import threading
        import multiprocessing

        def writer():
            pass

        def worker():
            pass

        def hop():
            pass

        def later():
            pass

        def start(pool, loop):
            t = threading.Thread(target=writer, daemon=True)
            p = multiprocessing.Process(target=worker)
            pool.submit(hop, 1)
            loop.run_in_executor(None, later)
            t.start()
        """
    )
    tags = module_contexts(module)
    assert "thread" in tags["writer"]
    assert "forked-worker" in tags["worker"]
    assert "executor" in tags["hop"]
    assert "executor" in tags["later"]
    # The constructing function owns the lifecycle: supervisor.
    assert "supervisor" in tags["start"]
    # Seam tags do not leak onto the supervisor itself.
    assert "thread" not in tags["start"]


def test_context_async_and_nested_inheritance():
    module = _module(
        """
        import threading

        async def serve():
            pass

        def run():
            t = threading.Thread(target=drain)
            t.start()

        def drain():
            def flush():
                pass
            flush()
        """
    )
    tags = module_contexts(module)
    assert "async" in tags["serve"]
    # A closure defined in a thread-target executes on that thread...
    assert "thread" in tags["drain"]
    assert "thread" in tags["drain.flush"]
    # ...but "supervisor" describes the parent's OWN body only.
    assert "supervisor" in tags["run"]
    assert "supervisor" not in tags["drain.flush"]
