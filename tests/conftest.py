"""Test configuration: force a virtual 8-device CPU platform.

Must set XLA flags before jax is imported anywhere; pytest imports conftest
first, so this is the single place that configures the test platform.
Multi-device sharding tests rely on the 8 virtual CPU devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Keep compilation deterministic and quiet in CI.
os.environ.setdefault("JAX_ENABLE_X64", "0")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def reference_table():
    """The deterministic normalized table (regenerated, not read from disk)."""
    from rl_scheduler_tpu.data.generate import generate_all
    from rl_scheduler_tpu.data.normalize import normalize

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        raw = generate_all(d)
    return normalize(raw)


@pytest.fixture(scope="session")
def cloud_table():
    from rl_scheduler_tpu.data.loader import load_table

    return load_table()


@pytest.fixture(scope="session")
def rng():
    return np.random.RandomState(0)
