"""Test configuration: force a virtual 8-device CPU platform.

Must set XLA flags before jax is imported anywhere; pytest imports conftest
first, so this is the single place that configures the test platform.
Multi-device sharding tests rely on the 8 virtual CPU devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Keep compilation deterministic and quiet in CI.
os.environ.setdefault("JAX_ENABLE_X64", "0")
# Persistent-cache env vars, not just the in-process config below: tests
# spawn real CLIs as subprocesses (train_ppo retrains, pool workers,
# study workers) which inherit os.environ — without these each
# subprocess pays every compile cold (the loop drill alone re-compiles
# ~35s of programs the suite already built).
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.2")

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import jax  # noqa: E402

# Tests are CPU-only. A site hook may have imported jax at interpreter
# startup with an accelerator platform pinned in JAX_PLATFORMS (e.g. a
# tunneled TPU plugin); the env var was read then, so setting os.environ
# above is not enough — update the config explicitly, otherwise
# xla_bridge.backends() initializes the accelerator plugin and can hang on
# a dead transport.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: compiles dominate suite runtime on CPU
# (~1.2s per jit on this box vs ~0.1ms per dispatched step). Config
# mirrors the env vars exported above (a site hook may have imported
# jax before the env was set, so update the config explicitly too).
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update(
    "jax_persistent_cache_min_compile_time_secs",
    float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))


@pytest.fixture(scope="session")
def reference_table():
    """The deterministic normalized table (regenerated, not read from disk)."""
    from rl_scheduler_tpu.data.generate import generate_all
    from rl_scheduler_tpu.data.normalize import normalize

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        raw = generate_all(d)
    return normalize(raw)


@pytest.fixture(scope="session")
def cloud_table():
    from rl_scheduler_tpu.data.loader import load_table

    return load_table()


@pytest.fixture(scope="session")
def rng():
    return np.random.RandomState(0)


@pytest.fixture(scope="session")
def incumbent_run(tmp_path_factory):
    """A deliberately thin incumbent (1 iteration): the serving
    checkpoint today's pool carries, weak enough that a fine-tune on
    the served trace reliably beats it 5/5 paired seeds. Session-scoped
    so the graftloop and graftpilot drills share ONE training run."""
    from rl_scheduler_tpu.agent import train_ppo

    root = tmp_path_factory.mktemp("loopback_cli")
    return train_ppo.main([
        "--env", "cluster_set", "--preset", "quick", "--num-envs", "4",
        "--rollout-steps", "8", "--minibatch-size", "32",
        "--iterations", "1", "--eval-every", "1", "--eval-episodes", "2",
        "--run-name", "INCUMBENT", "--run-root", str(root),
    ])
