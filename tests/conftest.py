"""Test configuration: force a virtual 8-device CPU platform.

Must set XLA flags before jax is imported anywhere; pytest imports conftest
first, so this is the single place that configures the test platform.
Multi-device sharding tests rely on the 8 virtual CPU devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Keep compilation deterministic and quiet in CI.
os.environ.setdefault("JAX_ENABLE_X64", "0")

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import jax  # noqa: E402

# Tests are CPU-only. A site hook may have imported jax at interpreter
# startup with an accelerator platform pinned in JAX_PLATFORMS (e.g. a
# tunneled TPU plugin); the env var was read then, so setting os.environ
# above is not enough — update the config explicitly, otherwise
# xla_bridge.backends() initializes the accelerator plugin and can hang on
# a dead transport.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: compiles dominate suite runtime on CPU
# (~1.2s per jit on this box vs ~0.1ms per dispatched step).
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)


@pytest.fixture(scope="session")
def reference_table():
    """The deterministic normalized table (regenerated, not read from disk)."""
    from rl_scheduler_tpu.data.generate import generate_all
    from rl_scheduler_tpu.data.normalize import normalize

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        raw = generate_all(d)
    return normalize(raw)


@pytest.fixture(scope="session")
def cloud_table():
    from rl_scheduler_tpu.data.loader import load_table

    return load_table()


@pytest.fixture(scope="session")
def rng():
    return np.random.RandomState(0)
