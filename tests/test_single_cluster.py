"""Single-cluster autoscaling env (BASELINE config 1)."""

import jax
import jax.numpy as jnp
import pytest

from rl_scheduler_tpu.config import SingleClusterConfig
from rl_scheduler_tpu.env import single_cluster as sc
from rl_scheduler_tpu.env.bundle import single_cluster_bundle


@pytest.fixture(scope="module")
def params():
    return sc.make_params(SingleClusterConfig())


def test_reset_shape_and_determinism(params):
    key = jax.random.PRNGKey(0)
    state, obs = sc.reset(params, key)
    assert obs.shape == (sc.OBS_DIM,)
    assert int(state.step_idx) == 0
    assert 1 <= int(state.replicas) <= int(params.max_replicas)
    state2, obs2 = sc.reset(params, key)
    assert jnp.array_equal(obs, obs2)


def test_step_replica_dynamics(params):
    state, _ = sc.reset(params, jax.random.PRNGKey(0))
    r0 = int(state.replicas)
    state_up, _ = sc.step(params, state, jnp.asarray(2))
    assert int(state_up.replicas) == r0 + 1
    state_dn, _ = sc.step(params, state, jnp.asarray(0))
    assert int(state_dn.replicas) == r0 - 1
    state_hold, _ = sc.step(params, state, jnp.asarray(1))
    assert int(state_hold.replicas) == r0


def test_replicas_clipped_to_bounds(params):
    state, _ = sc.reset(params, jax.random.PRNGKey(0))
    # Scale down far past the floor.
    for _ in range(int(params.max_replicas) + 3):
        state, _ = sc.step(params, state, jnp.asarray(0))
    assert int(state.replicas) == 1
    for _ in range(2 * int(params.max_replicas)):
        state, ts = sc.step(params, state, jnp.asarray(2))
    assert int(state.replicas) == int(params.max_replicas)


def test_reward_negative_and_overload_penalized(params):
    """More replicas under high load -> less latency penalty."""
    state, _ = sc.reset(params, jax.random.PRNGKey(0))
    # Find the trace row with max load (users), step to just before it.
    load = params.trace[:, 0]
    hot = int(jnp.argmax(load))
    if hot == 0:
        hot = 1
    state = state._replace(step_idx=jnp.asarray(hot, jnp.int32))

    lo = state._replace(replicas=jnp.asarray(1, jnp.int32))
    hi = state._replace(replicas=params.max_replicas - 1)
    _, ts_lo = sc.step(params, lo, jnp.asarray(1))
    _, ts_hi = sc.step(params, hi, jnp.asarray(1))
    assert float(ts_lo.reward) <= 0.0
    assert float(ts_hi.reward) <= 0.0
    # At max load, underprovisioning must hurt more than the replica cost
    # of (near-)full provisioning.
    assert float(ts_hi.reward) > float(ts_lo.reward)


def test_done_at_max_steps(params):
    state, _ = sc.reset(params, jax.random.PRNGKey(0))
    t = int(params.max_steps)
    for i in range(t):
        state, ts = sc.step(params, state, jnp.asarray(1))
    assert bool(ts.done)


def test_bundle_vmap_matches_single(params):
    bundle = single_cluster_bundle(params)
    key = jax.random.PRNGKey(7)
    n = 5
    state, obs = bundle.reset_batch(key, n)
    assert obs.shape == (n, sc.OBS_DIM)
    actions = jnp.asarray([0, 1, 2, 1, 0], jnp.int32)
    state2, ts = bundle.step_batch(state, actions)
    # Env 2 scaled up, env 0 scaled down, relative to the shared initial count.
    r0 = int(jnp.maximum(params.max_replicas // 2, 1))
    assert int(state2.replicas[0]) == r0 - 1
    assert int(state2.replicas[2]) == r0 + 1
    # Single-env step from the same per-env state gives identical results.
    single_state = jax.tree.map(lambda x: x[3], state)
    _, ts_single = sc.step(params, single_state, actions[3])
    assert jnp.allclose(ts_single.reward, ts.reward[3])


def test_autoreset_restarts_episode(params):
    bundle = single_cluster_bundle(params)
    state, obs = bundle.reset_batch(jax.random.PRNGKey(0), 2)
    t = int(params.max_steps)
    for _ in range(t):
        state, ts = bundle.step_batch(state, jnp.ones(2, jnp.int32))
    assert bool(ts.done[0])
    # After the terminal step the carried state restarted at row 0.
    assert int(state.step_idx[0]) == 0
