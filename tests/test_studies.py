"""graftstudy (rl_scheduler_tpu/studies/, docs/studies.md).

Pins the subsystem's contracts: frozen specs compiling to deterministic
trial lists, the atomic bitwise-resumable ledger, Wilson/sign-test
verdicts, the reseed x best-keeper lineage fix, the anti-latch
interventions (--sample-temp-anneal / --argmax-penalty) and their
checkpoint-meta round-trip, and the tier-1 smoke: a real 2-seed x
2-variant study through the multi-process CLI. The SIGKILL-mid-study
chaos case lives with the chaos suite (tests/test_graftguard.py).
"""

import dataclasses
import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from rl_scheduler_tpu.studies import (
    OVERLAY_KEYS,
    STUDIES,
    LedgerMismatch,
    StudyLedger,
    StudyRunner,
    StudySpec,
    TrialSpec,
    acquire_runner_lock,
    analyze_study,
    atomic_write_json,
    build_trial_config,
    configure_jax_cache,
    get_study,
    limit_blas_threads,
    list_studies,
    load_spec,
    overlay,
    parse_seeds,
    render_grid,
    run_trial,
    sign_test_pvalue,
    spec_from_json,
    summary_json_line,
    wilson_interval,
    write_result,
)

# The tier-1-affordable trial shape (shared with the chaos suite and the
# study_smoke preset so every XLA program is compiled once per session).
TINY_BASE = overlay(num_envs=8, rollout_steps=8, minibatch_size=64,
                    num_epochs=1)


def tiny_spec(**kw) -> StudySpec:
    base = dict(
        name="t", env="cluster_set", preset="quick", num_nodes=4,
        seeds=(0, 1), iterations=2, eval_every=1, eval_episodes=4,
        final_eval_episodes=8, stall_deadline=1, base_overlay=TINY_BASE,
    )
    base.update(kw)
    return StudySpec(**base)


# ----------------------------------------------------------------- spec


class TestStudySpec:
    def test_trials_deterministic_and_ordered(self):
        spec = tiny_spec(variants=(("control", ()),
                                   ("anneal", overlay(sample_temp_anneal=0.5))))
        ids = [t.trial_id for t in spec.trials()]
        assert ids == ["control-seed0", "control-seed1",
                       "anneal-seed0", "anneal-seed1"]
        assert spec.trials() == spec.trials()
        t = spec.trials()[2]
        assert isinstance(t, TrialSpec)
        assert t.variant == "anneal" and t.seed == 0
        # base + variant overlays merge, variant wins
        assert t.overlay["sample_temp_anneal"] == 0.5
        assert t.overlay["num_envs"] == 8

    def test_fingerprint_tracks_protocol(self):
        a, b = tiny_spec(), tiny_spec()
        assert a.fingerprint() == b.fingerprint()
        c = tiny_spec(seeds=(0, 1, 2))
        assert c.fingerprint() != a.fingerprint()

    def test_json_roundtrip(self):
        spec = tiny_spec(variants=(
            ("control", ()), ("rand", overlay(scenario="randomized"))),
            control="control")
        back = spec_from_json(spec.to_json())
        assert back == spec and back.fingerprint() == spec.fingerprint()

    def test_unknown_overlay_key_refused(self):
        with pytest.raises(ValueError, match="vocabulary"):
            tiny_spec(variants=(("control", ()),
                                ("bad", overlay(warp_drive=9))))
        assert "sample_temp_anneal" in OVERLAY_KEYS

    def test_validation(self):
        with pytest.raises(ValueError, match="control"):
            tiny_spec(variants=(("a", ()),), control="b")
        with pytest.raises(ValueError, match="duplicate"):
            tiny_spec(seeds=(0, 0))
        with pytest.raises(ValueError, match="structured"):
            tiny_spec(env="multi_cloud")
        with pytest.raises(ValueError, match="preset"):
            tiny_spec(preset="nope")
        with pytest.raises(ValueError, match="score_source"):
            tiny_spec(score_source="peak")
        # best-keeper scoring without evals would silently degrade every
        # verdict to final params — refused up front.
        with pytest.raises(ValueError, match="no best-eval keeper"):
            tiny_spec(score_source="best", eval_every=0)
        # The verdict defaults to the §1b final-params protocol.
        assert tiny_spec().score_source == "final"

    def test_inert_companion_keys_refused(self):
        """A spec-valid-but-inert knob would burn a chip arm on a
        variant identical to control — refused at construction."""
        with pytest.raises(ValueError, match="sample_temp_anneal"):
            tiny_spec(variants=(("control", ()),
                                ("v", overlay(sample_temp_iters=40))))
        with pytest.raises(ValueError, match="inert"):
            tiny_spec(variants=(("control", ()),
                                ("v", overlay(scenario_seed=3))))
        # Inert VALUES are the same defect class: identity temperature
        # and a zero penalty both train byte-identical to control.
        with pytest.raises(ValueError, match="identity temperature"):
            tiny_spec(variants=(("control", ()),
                                ("v", overlay(sample_temp_anneal=1.0))))
        with pytest.raises(ValueError, match="disables the penalty"):
            tiny_spec(variants=(("control", ()),
                                ("v", overlay(argmax_penalty=0.0))))
        with pytest.raises(ValueError, match="never reads the sharpness"):
            tiny_spec(variants=(
                ("control", ()),
                ("v", overlay(argmax_penalty_sharpness=32.0))))

    def test_scenario_overlay_resolved_at_construction(self):
        """A typo'd scenario name or an env-incompatible family must
        fail when the spec is built, not per-trial on the chip."""
        with pytest.raises(ValueError, match="unknown scenario"):
            tiny_spec(variants=(("control", ()),
                                ("v", overlay(scenario="randomzied"))))
        with pytest.raises(ValueError, match="does not shape env"):
            tiny_spec(env="cluster_graph",
                      variants=(("control", ()),
                                ("v", overlay(scenario="randomized"))))
        tiny_spec(variants=(("control", ()),
                            ("v", overlay(scenario="randomized"))))
        # With the companion present, both are fine.
        tiny_spec(variants=(
            ("control", ()),
            ("v", overlay(sample_temp_anneal=0.5, sample_temp_iters=40)),
            ("r", overlay(scenario="randomized", scenario_seed=3))))

    def test_reseed_guard_eligibility_validated(self):
        """A guard the eval schedule can never fire is refused (the
        runner would otherwise silently skip it — same arithmetic as
        the train CLI's refusal)."""
        with pytest.raises(ValueError, match="silently disabled"):
            tiny_spec(eval_every=8, stall_deadline=4,
                      variants=(("control", overlay(reseed_on_stall=1)),))
        with pytest.raises(ValueError, match="eval signal"):
            tiny_spec(eval_every=0, stall_deadline=4,
                      variants=(("control", overlay(reseed_on_stall=1)),))

    def test_parse_seeds(self):
        assert parse_seeds("0-3") == [0, 1, 2, 3]
        assert parse_seeds("0,2,7") == [0, 2, 7]
        assert parse_seeds("1-2,9") == [1, 2, 9]

    def test_registry(self):
        assert "fleet64_antilatch" in list_studies()
        fleet = get_study("fleet64_antilatch")
        assert set(fleet.variant_names()) == {
            "control", "anneal", "argmax_penalty", "randomized"}
        assert len(fleet.seeds) == 9
        assert fleet.target_failure_rate == 0.20
        # Every registered study compiles (spec validation runs in
        # __post_init__; trials() exercises the overlay merge).
        for name in STUDIES:
            assert get_study(name).trials()
        with pytest.raises(ValueError, match="unknown study"):
            get_study("nope")


# --------------------------------------------------------------- ledger


class TestLedger:
    def test_append_preserves_prior_bytes(self, tmp_path):
        spec = tiny_spec()
        led = StudyLedger(tmp_path, spec)
        led.append({"trial_id": "control-seed0", "variant": "control",
                    "seed": 0, "status": "ok", "failed": False,
                    "improvement_pct": 1.0})
        before = led.path.read_bytes()
        led.append({"trial_id": "control-seed1", "variant": "control",
                    "seed": 1, "status": "ok", "failed": True,
                    "improvement_pct": -2.0})
        after = led.path.read_bytes()
        assert after.startswith(before)  # bitwise: appends never rewrite
        assert led.completed_ids() == {"control-seed0", "control-seed1"}
        assert len(led.records()) == 2
        assert led.header()["spec_sha"] == spec.fingerprint()
        assert not list(tmp_path.glob("*.tmp"))  # rename completed

    def test_reopen_resumes_same_spec(self, tmp_path):
        spec = tiny_spec()
        StudyLedger(tmp_path, spec).append(
            {"trial_id": "control-seed0", "variant": "control", "seed": 0,
             "status": "ok", "failed": False, "improvement_pct": 0.0})
        led2 = StudyLedger(tmp_path, spec)
        assert led2.completed_ids() == {"control-seed0"}
        assert load_spec(tmp_path) == spec

    def test_changed_spec_refused(self, tmp_path):
        StudyLedger(tmp_path, tiny_spec())
        with pytest.raises(LedgerMismatch, match="changed protocol"):
            StudyLedger(tmp_path, tiny_spec(seeds=(0, 1, 2)))

    def test_runner_single_writer_lock(self, tmp_path):
        """A live runner.pid refuses a second runner (it would wipe the
        first's in-flight trial dirs); a stale lock (dead pid) is
        overridden and resume proceeds."""
        import os

        spec = tiny_spec(seeds=(0,))
        runner = StudyRunner(spec, tmp_path, jobs=0)
        # Pre-complete the single trial so an unblocked run() returns
        # instantly instead of training.
        runner.ledger.append(_rec("control", 0, False, 10.0))
        (tmp_path / "runner.pid").write_text(str(os.getpid()))  # alive
        with pytest.raises(RuntimeError, match="already being run"):
            runner.run(progress=None)
        with pytest.raises(RuntimeError, match="already being run"):
            # The CLI's --fresh path takes the same exclusive lock
            # before deleting anything.
            acquire_runner_lock(tmp_path)
        # Max pid on Linux is < 2^22 by default; this one is dead.
        (tmp_path / "runner.pid").write_text("4194000")
        records = runner.run(progress=None)
        assert len(records) == 1
        assert not (tmp_path / "runner.pid").exists()  # released

    def test_atomic_write_json(self, tmp_path):
        """The one atomic-JSON implementation behind result.json and
        summary.json: complete file, no .tmp left behind."""
        path = tmp_path / "summary.json"
        atomic_write_json(path, {"b": 2, "a": 1})
        assert json.loads(path.read_text()) == {"a": 1, "b": 2}
        atomic_write_json(path, {"a": 3}, indent=1)
        assert json.loads(path.read_text()) == {"a": 3}
        assert not list(tmp_path.glob("*.tmp"))
        # configure_jax_cache / limit_blas_threads are the shared
        # best-effort runtime knobs behind the worker, the in-process
        # CLI path, and the chaos driver (never-raise contract).
        configure_jax_cache()
        assert limit_blas_threads(1) in (True, False)


# ------------------------------------------------------------- analysis


def _rec(variant, seed, failed, impr, status="ok", **kw):
    base = {"trial_id": f"{variant}-seed{seed}", "variant": variant,
            "seed": seed, "status": status, "failed": failed,
            "improvement_pct": impr, "argmax_collision": 0.5 if failed
            else 0.1, "attempts": 1}
    base.update(kw)
    return base


class TestAnalysis:
    def test_wilson_interval_known_values(self):
        lo, hi = wilson_interval(4, 9)
        # 4/9 at z=1.96: the standard Wilson values.
        assert lo == pytest.approx(0.1888, abs=1e-3)
        assert hi == pytest.approx(0.7334, abs=1e-3)
        assert wilson_interval(0, 0) == (0.0, 1.0)
        lo0, hi0 = wilson_interval(0, 9)
        assert lo0 == 0.0 and 0.0 < hi0 < 0.35

    def test_sign_test(self):
        assert sign_test_pvalue(0, 0) == 1.0
        assert sign_test_pvalue(5, 0) == pytest.approx(2 * 0.5**5)
        assert sign_test_pvalue(3, 3) == 1.0

    def test_verdicts_and_paired_deltas(self):
        spec = tiny_spec(
            seeds=tuple(range(9)), target_failure_rate=0.20,
            variants=(("control", ()),
                      ("fix", overlay(argmax_penalty=0.05)),
                      ("worse", overlay(sample_temp_anneal=0.5))))
        control_failed = {2, 4, 5, 8}  # the measured 4/9 pattern
        records = []
        for s in range(9):
            records.append(_rec("control", s, s in control_failed,
                                -20.0 if s in control_failed else 20.0))
            # 'fix' converges everywhere: 4 seeds fixed, 0 broken.
            records.append(_rec("fix", s, False, 22.0))
            # 'worse' fails everything.
            records.append(_rec("worse", s, True, -30.0))
        summary = analyze_study(spec, records)
        assert summary["schema_version"] == 1
        assert summary["metric"] == "study_summary"
        v = summary["variants"]
        assert v["control"]["failures"] == 4
        # 4/9 = 0.44 over the bar, but wilson lo (0.19) is under it.
        assert v["control"]["verdict"] == "point_above"
        assert v["fix"]["failures"] == 0
        # 0/9's wilson hi is 0.30: n=9 cannot CONFIRM <0.2 — the honest
        # graded verdict (docstring arithmetic).
        assert v["fix"]["verdict"] == "point_below"
        assert v["fix"]["wilson95"][1] == pytest.approx(0.299, abs=1e-2)
        assert v["worse"]["verdict"] == "confirmed_above"
        vs = v["fix"]["vs_control"]
        assert vs["paired_seeds"] == 9
        assert vs["seeds_fixed"] == 4 and vs["seeds_broken"] == 0
        assert vs["sign_test_p"] == pytest.approx(2 * 0.5**4)
        assert vs["mean_delta_pct"] > 0
        grid = render_grid(summary)
        assert "point_below" in grid and "control (ctrl)" in grid
        line = summary_json_line(summary)
        assert json.loads(line)["study"] == spec.name

    def test_errors_excluded_from_rates(self):
        spec = tiny_spec(variants=(("control", ()),))
        records = [_rec("control", 0, False, 10.0),
                   _rec("control", 1, None, None, status="error")]
        v = analyze_study(spec, records)["variants"]["control"]
        assert v["trials"] == 1 and v["errors"] == 1
        assert v["failure_rate"] == 0.0


# -------------------------------------------------- trial config overlay


class TestBuildTrialConfig:
    def test_intervention_and_scenario_overlays(self):
        spec = tiny_spec(variants=(
            ("control", ()),
            ("anneal", overlay(sample_temp_anneal=0.5)),
            ("pen", overlay(argmax_penalty=0.05)),
            ("rand", overlay(scenario="randomized", scenario_seed=3)),
            ("guard", overlay(reseed_on_stall=2))))
        trials = {t.variant: t for t in spec.trials() if t.seed == 0}
        cfg, bk, budget = build_trial_config(spec, trials["control"])
        assert cfg.num_envs == 8 and cfg.eval_every == 1
        assert cfg.sample_temp_end == 1.0 and budget == 0
        assert bk == {"num_nodes": 4}
        cfg, _, _ = build_trial_config(spec, trials["anneal"])
        assert cfg.sample_temp_end == 0.5
        assert cfg.sample_temp_iters == spec.iterations  # CLI default
        cfg, _, _ = build_trial_config(spec, trials["pen"])
        assert cfg.argmax_penalty_coeff == 0.05
        _, bk, _ = build_trial_config(spec, trials["rand"])
        assert bk["scenario"].name == "randomized"
        assert bk["scenario"].seed == 3
        _, _, budget = build_trial_config(spec, trials["guard"])
        assert budget == 2


# ------------------------------------------- reseed x best-keeper lineage


class TestReseedBestLineage:
    def test_each_attempt_keeps_its_own_best(self, tmp_path):
        """Satellite fix (ISSUE 9): with the reseed guard tripping, the
        abandoned attempt's best_attempt0/ lineage SURVIVES (the train
        CLI clears best/ on reseed; a study keeps the evidence) and the
        ledger record names the attempt the verdict was scored from."""
        # stall_deadline=2 with eval_every=1: attempt 0's eval@1 SAVES a
        # best checkpoint before the guard trips at the deadline eval@2 —
        # the lineage under test needs an abandoned attempt that got far
        # enough to have a peak. score_source="best" opts the verdict
        # into the keeper (the default is the §1b final-params protocol).
        spec = tiny_spec(variants=(
            ("guard", overlay(reseed_on_stall=1)),), control="guard",
            stall_deadline=2, score_source="best")
        trial = spec.trials()[0]
        # An unreachable bar forces exactly one reseed (budget 1: the
        # final attempt runs to completion with the warn-only guard).
        record = run_trial(spec, trial, tmp_path / "trial",
                           baseline_threshold=float("inf"))
        assert record["status"] == "ok"
        assert record["attempts"] == 2
        assert record["scored_attempt"] == 1
        assert record["scored_seed"] == trial.seed + 1
        assert record["scored_source"] == "best"
        assert record["scored_step"] is not None
        # BOTH lineages on disk, each with a saved best checkpoint.
        for attempt in (0, 1):
            d = tmp_path / "trial" / f"best_attempt{attempt}"
            assert d.is_dir(), d
            from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

            mgr = CheckpointManager(d, keep=1)
            step = mgr.latest_verified_step()
            assert step is not None
            assert mgr.restore_meta(step)["attempt"] == attempt
            mgr.close()
        assert record["attempt_log"][0]["attempt"] == 0
        assert record["attempt_log"][0]["seed"] == trial.seed
        # result.json is the atomic worker handoff.
        on_disk = json.loads((tmp_path / "trial" / "result.json").read_text())
        assert on_disk == record
        write_result(tmp_path / "trial", record)  # idempotent rewrite


# ----------------------------------------------------- tier-1 study smoke


class TestStudySmoke:
    def test_smoke_study_through_multiprocess_cli(self, tmp_path):
        """The satellite tier-1 smoke: 2 seeds x 2 variants on the tiny
        preset, through the REAL CLI with 2 worker subprocesses — spec
        -> ledger -> workers -> verdict grid -> driver JSON line."""
        out = subprocess.run(
            [sys.executable, "-m", "rl_scheduler_tpu.studies",
             "--study", "study_smoke", "--study-root", str(tmp_path),
             "--jobs", "2"],
            capture_output=True, text=True, timeout=540,
            cwd=Path(__file__).resolve().parents[1])
        assert out.returncode == 0, out.stdout + out.stderr
        study_dir = tmp_path / "study_smoke"
        led = StudyLedger(study_dir, get_study("study_smoke"))
        records = led.records()
        assert len(records) == 4
        assert all(r["status"] == "ok" for r in records), records
        # Driver line: last stdout line is the schema-tagged summary.
        line = json.loads(out.stdout.strip().splitlines()[-1])
        assert line["schema_version"] == 1
        assert line["metric"] == "study_summary"
        assert set(line["variants"]) == {"control", "anneal"}
        for v in line["variants"].values():
            assert v["trials"] == 2
            assert v["wilson95"][0] <= (v["failure_rate"] or 0)
        assert (study_dir / "summary.json").exists()
        # Idempotent resume: a second run re-runs nothing and leaves the
        # ledger byte-identical.
        before = led.path.read_bytes()
        again = subprocess.run(
            [sys.executable, "-m", "rl_scheduler_tpu.studies",
             "--study", "study_smoke", "--study-root", str(tmp_path),
             "--jobs", "2"],
            capture_output=True, text=True, timeout=120,
            cwd=Path(__file__).resolve().parents[1])
        assert again.returncode == 0, again.stdout + again.stderr
        assert "already in the ledger" in again.stdout
        assert led.path.read_bytes() == before


# -------------------------------------------------- seed_study migration


class TestSeedStudyCompat:
    def test_same_cli_compiles_to_study(self):
        """loadgen/seed_study.py keeps its CLI but compiles to a
        graftstudy spec (the docs/scaling.md §1b protocol cannot drift
        from the subsystem)."""
        sys.path.insert(0, str(Path(__file__).resolve().parents[1]
                              / "loadgen"))
        import seed_study

        spec = seed_study.build_spec("cluster_set", 64, [0, 1, 2], 80,
                                     100, 16)
        assert spec.preset == "set_fleet64"
        assert spec.seeds == (0, 1, 2)
        assert spec.final_eval_episodes == 100
        assert spec.variant_names() == ["control"]
        assert spec.stall_deadline == 16
        big = seed_study.build_spec("cluster_set", 256, [0], 80, 100, 16)
        assert big.preset == "set_fleet256"
        # Final-params scoring: the docs/scaling.md §1b protocol the
        # recorded 4/9 baseline was measured under.
        assert spec.score_source == "final"
        # cluster_graph historically used set_fleet64's scale knobs at
        # ANY node count ("same scale knobs", the original script).
        graph = seed_study.build_spec("cluster_graph", 256, [0], 80, 100, 16)
        assert graph.preset == "set_fleet64"

    def test_dry_run_cli_and_row_format(self, capsys):
        import seed_study

        rows = seed_study.main(["--seeds", "0-2", "--dry-run"])
        assert rows == []
        out = capsys.readouterr().out
        assert out.count("trial_id") == 3
        # The historical row/verdict printer from ledger records.
        records = [
            {"status": "ok", "seed": 0, "eval_at_deadline": -5.0,
             "eval_final": -4.0, "flagged_early": True,
             "flagged_final": False, "improvement_pct": -9.7,
             "failed": True, "wall_s": 1.0},
            {"status": "ok", "seed": 1, "eval_at_deadline": -1.0,
             "eval_final": -1.0, "flagged_early": False,
             "flagged_final": False, "improvement_pct": 20.0,
             "failed": False, "wall_s": 1.0},
        ]
        rows = seed_study.print_rows(records, 16)
        out = capsys.readouterr().out
        assert "NO false negatives" in out
        assert rows[0]["failed_final"] is True
        assert rows[0]["flagged_early"] is True


# ------------------------------------------------- interventions (3b)


class TestSampleTemperature:
    def test_schedule(self):
        import jax.numpy as jnp

        from rl_scheduler_tpu.agent.ppo import (
            PPOTrainConfig,
            sample_temperature,
        )

        assert sample_temperature(PPOTrainConfig(), jnp.int32(5)) is None
        cfg = PPOTrainConfig(sample_temp_end=0.5, sample_temp_iters=10)
        assert float(sample_temperature(cfg, jnp.int32(0))) == 1.0
        assert float(sample_temperature(cfg, jnp.int32(5))) == pytest.approx(0.75)
        assert float(sample_temperature(cfg, jnp.int32(10))) == 0.5
        assert float(sample_temperature(cfg, jnp.int32(99))) == 0.5  # held
        hold = PPOTrainConfig(sample_temp_end=0.7, sample_temp_iters=0)
        assert float(sample_temperature(hold, jnp.int32(0))) == pytest.approx(0.7)

    def test_config_validation(self):
        from rl_scheduler_tpu.agent.ppo import PPOTrainConfig

        with pytest.raises(ValueError, match="temperature"):
            PPOTrainConfig(sample_temp_end=0.0)
        with pytest.raises(ValueError, match="anneal span"):
            PPOTrainConfig(sample_temp_end=0.5, sample_temp_iters=-1)
        with pytest.raises(ValueError, match="penalty"):
            PPOTrainConfig(argmax_penalty_coeff=-0.1)


class TestArgmaxPenalty:
    def test_concentration_bounds_and_latch_signature(self):
        import jax.numpy as jnp

        from rl_scheduler_tpu.ops.losses import argmax_concentration

        # A latched policy (every state's argmax = node 3) scores ~1
        # even though each state's distribution is near-uniform.
        latched = 0.1 * np.random.RandomState(0).randn(64, 16)
        latched[:, 3] += 0.5
        c_latched = float(argmax_concentration(jnp.asarray(latched)))
        # A rotating argmax spreads the pooled mass.
        rotating = 0.1 * np.random.RandomState(1).randn(64, 16)
        rotating[np.arange(64), np.arange(64) % 16] += 0.5
        c_rotating = float(argmax_concentration(jnp.asarray(rotating)))
        assert c_latched > 0.5
        assert c_rotating < 0.2
        assert 1.0 / 16 <= c_rotating <= c_latched <= 1.0

    def test_penalty_gradient_lowers_concentration(self):
        """The satellite pin: optimizing the penalty term measurably
        lowers the policy-concentration metric — gradient descent on a
        latched logit table de-latches it."""
        import jax
        import jax.numpy as jnp

        from rl_scheduler_tpu.ops.losses import argmax_concentration

        logits = 0.05 * np.random.RandomState(0).randn(64, 16)
        logits[:, 3] += 0.3  # the static-premium latch
        logits = jnp.asarray(logits, jnp.float32)
        before = float(argmax_concentration(logits))
        grad_fn = jax.jit(jax.grad(argmax_concentration))
        for _ in range(50):
            logits = logits - 0.5 * grad_fn(logits)
        after = float(argmax_concentration(logits))
        assert before > 0.5
        assert after < before * 0.5, (before, after)

    def test_ppo_loss_carries_penalty_and_metric(self):
        import jax.numpy as jnp

        from rl_scheduler_tpu.ops.losses import PPOLossConfig, ppo_loss

        rng = np.random.RandomState(0)
        b, a = 32, 8
        logits = jnp.asarray(rng.randn(b, a), jnp.float32)
        args = (logits, jnp.zeros(b), jnp.zeros(b, jnp.int32),
                jnp.asarray(rng.randn(b) * 0.01, jnp.float32),
                jnp.zeros(b), jnp.asarray(rng.randn(b), jnp.float32),
                jnp.zeros(b))
        base, m0 = ppo_loss(*args, PPOLossConfig())
        pen, m1 = ppo_loss(*args, PPOLossConfig(argmax_penalty_coeff=1.0))
        assert "argmax_concentration" not in m0
        conc = float(m1["argmax_concentration"])
        assert 1.0 / a <= conc <= 1.0
        # total = base + coeff * concentration, exactly.
        assert float(pen) == pytest.approx(float(base) + conc, rel=1e-5)


class TestInterventionCLIRoundTrip:
    """Satellite pin: penalty/temperature flags round-trip through
    checkpoint meta and the --resume guards."""

    TINY = ["--env", "cluster_set", "--num-nodes", "4", "--num-envs", "4",
            "--rollout-steps", "8", "--minibatch-size", "16",
            "--num-epochs", "1"]

    def _run(self, tmp_path, extra):
        from rl_scheduler_tpu.agent import train_ppo as cli

        return cli.main(self.TINY + ["--run-root", str(tmp_path),
                                     "--run-name", "r"] + extra)

    def test_meta_roundtrip_and_resume_guard(self, tmp_path):
        from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

        self._run(tmp_path, ["--iterations", "1", "--checkpoint-every", "1",
                             "--sample-temp-anneal", "0.5",
                             "--sample-temp-iters", "4",
                             "--argmax-penalty", "0.05"])
        mgr = CheckpointManager(tmp_path / "r")
        meta = mgr.restore_meta(1)
        mgr.close()
        assert meta["sample_temp_end"] == 0.5
        assert meta["sample_temp_iters"] == 4
        assert meta["argmax_penalty"] == 0.05
        # Resume WITHOUT the flags: the guard refuses (objective switch).
        with pytest.raises(SystemExit, match="sample_temp_end"):
            self._run(tmp_path, ["--iterations", "2",
                                 "--checkpoint-every", "1", "--resume"])
        # Mismatched penalty: refused with the recorded value named.
        with pytest.raises(SystemExit, match="argmax_penalty=0.05"):
            self._run(tmp_path, ["--iterations", "2",
                                 "--checkpoint-every", "1", "--resume",
                                 "--sample-temp-anneal", "0.5",
                                 "--sample-temp-iters", "4",
                                 "--argmax-penalty", "0.1"])
        # Matching flags: resumes and carries the meta forward.
        self._run(tmp_path, ["--iterations", "2", "--checkpoint-every", "1",
                             "--resume", "--sample-temp-anneal", "0.5",
                             "--sample-temp-iters", "4",
                             "--argmax-penalty", "0.05"])
        mgr = CheckpointManager(tmp_path / "r")
        meta = mgr.restore_meta(2)
        mgr.close()
        assert meta["sample_temp_end"] == 0.5
        assert meta["argmax_penalty"] == 0.05

    def test_legacy_checkpoint_resumes_with_flags_off(self, tmp_path):
        """Pre-intervention checkpoints (no keys) resume fine without
        flags — and refuse a resume that tries to TURN THEM ON."""
        self._run(tmp_path, ["--iterations", "1", "--checkpoint-every", "1"])
        with pytest.raises(SystemExit, match="sample_temp_end"):
            self._run(tmp_path, ["--iterations", "2",
                                 "--checkpoint-every", "1", "--resume",
                                 "--sample-temp-anneal", "0.5"])
        self._run(tmp_path, ["--iterations", "2", "--checkpoint-every", "1",
                             "--resume"])

    def test_flag_validation(self, tmp_path):
        with pytest.raises(SystemExit, match="positive"):
            self._run(tmp_path, ["--iterations", "1",
                                 "--sample-temp-anneal", "0"])
        with pytest.raises(SystemExit, match="pass both"):
            self._run(tmp_path, ["--iterations", "1",
                                 "--sample-temp-iters", "4"])
        with pytest.raises(SystemExit, match=">= 0"):
            self._run(tmp_path, ["--iterations", "1",
                                 "--argmax-penalty", "-1"])

    def test_domain_random_scenario_trains_and_records_meta(self, tmp_path):
        """The randomization variant's substrate: the 'randomized'
        scenario (family domain_random) keeps the CSV workload, adds
        per-episode randomization, and rides the normal scenario meta."""
        from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

        self._run(tmp_path, ["--iterations", "1", "--checkpoint-every", "1",
                             "--scenario", "randomized"])
        mgr = CheckpointManager(tmp_path / "r")
        meta = mgr.restore_meta(1)
        mgr.close()
        assert meta["scenario"] == "randomized"
        assert meta["scenario_family"] == "domain_random"
        assert meta["node_feat"] == 6  # classic layout: same policy/serving
