"""Version-split-safe units of ``parallel/``: the helpers every sharded
path leans on but no sharded test exercised directly (GL007).

Unlike ``test_tensor_parallel.py`` / ``test_sharding.py`` (which need
``jax.shard_map`` and 8 virtual devices, so they only run on the driver's
newer JAX), everything here is single-device semantics — the parts of the
parallel stack whose contracts must hold on BOTH sides of the
container-vs-driver JAX version split.
"""

import jax
import jax.numpy as jnp
import numpy as np

from rl_scheduler_tpu.parallel.mesh import device_count
from rl_scheduler_tpu.parallel.tensor_parallel import (
    copy_to_tp,
    reduce_from_tp,
    untp_checkpoint_tree,
)


def test_device_count_matches_jax():
    n = device_count()
    assert isinstance(n, int) and n >= 1
    assert n == len(jax.devices())


def test_copy_and_reduce_identity_off_mesh():
    """With ``axis_name=None`` (the unsharded twin modules) both Megatron
    markers must be exact identities in forward AND backward — that is
    what makes the tp=1 twin the parity reference."""
    x = jnp.arange(6.0).reshape(2, 3)
    np.testing.assert_array_equal(np.asarray(copy_to_tp(x, None)), np.asarray(x))
    np.testing.assert_array_equal(
        np.asarray(reduce_from_tp(x, None)), np.asarray(x)
    )

    g_copy = jax.grad(lambda v: copy_to_tp(v, None).sum())(x)
    g_red = jax.grad(lambda v: reduce_from_tp(v, None).sum())(x)
    np.testing.assert_array_equal(np.asarray(g_copy), np.ones_like(x))
    np.testing.assert_array_equal(np.asarray(g_red), np.ones_like(x))


def _tp_params():
    """Minimal TPActorCritic-layout torso: one (col, row, row_bias) pair."""
    return {
        "actor_torso": {
            "col0": {"kernel": jnp.ones((4, 8)), "bias": jnp.zeros(8)},
            "row0": {"kernel": jnp.ones((8, 4)), "bias": jnp.zeros(4)},
            "row_bias0": jnp.full(4, 0.5),
        },
        "logits_head": {"kernel": jnp.ones((4, 2)), "bias": jnp.zeros(2)},
    }


def test_untp_checkpoint_tree_passthrough_and_convert():
    tree = {"params": _tp_params()}
    # Non-tp runs (tp absent or 1) pass through untouched.
    assert untp_checkpoint_tree({}, tree) is tree
    assert untp_checkpoint_tree({"tp": 1}, tree) is tree
    # tp>1 meta converts the torso to ActorCritic Dense_{2i}/Dense_{2i+1}
    # layout, with row_bias{i} (the true bias of the row-parallel matmul)
    # replacing the sharded row bias; heads are layout-identical.
    out = untp_checkpoint_tree({"tp": 2}, tree)["params"]
    torso = out["actor_torso"]
    assert set(torso) == {"Dense_0", "Dense_1"}
    np.testing.assert_array_equal(
        np.asarray(torso["Dense_0"]["kernel"]), np.ones((4, 8))
    )
    np.testing.assert_array_equal(
        np.asarray(torso["Dense_1"]["bias"]), np.full(4, 0.5)
    )
    assert out["logits_head"] is tree["params"]["logits_head"]
