"""graftloop (rl_scheduler_tpu/loopback/): close the decision loop.

What is pinned here, and why it is the contract:

- **Trace merge + edge cases** — ``iter_trace_merged`` interleaves
  per-worker streams deterministically (equal timestamps break by
  prefix then stream order), and the compiler survives what a crashed
  pool leaves behind: torn trailing lines in sealed segments, orphaned
  ``.part`` files, generation boundaries mid-segment.
- **Retention** — ``max_segments`` prunes oldest sealed segments of ONE
  writer's stream only, counted on ``segments_pruned_total``.
- **Compile determinism + round trip** — same (snapshot, steps, seed,
  mix) ⇒ bitwise-identical tables, and the compiled scenario replays
  the trace's cost/latency/pod columns bit-exactly through the REAL
  env (``verify_roundtrip``) — the fidelity claim training stands on.
- **Verdict grading** — Wilson/sign-test arithmetic of ``grade_pairs``
  at the known small-n values, and the spec validations that keep a
  mis-protocoled loop from silently training.
- **Ledger resume** — completed stage records survive appends bitwise;
  a changed spec refuses to resume; a SIGKILLed CLI re-enters exactly
  the interrupted stage (``loop_drill`` tests).
- **The drill** (`make loop-drill`) — a live pool serves bench traffic
  continuously while one loop iteration compiles the trace, retrains,
  wins the paired-seed verdict, and hot-promotes through the canary
  gates with zero failed requests; a failing verdict and a
  ``loopback.promote`` fault each provably refuse with the pool
  untouched, and a regressing candidate rolls back.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from rl_scheduler_tpu.loopback import (
    CompiledTrace,
    FinetuneSpec,
    LoopLedger,
    LoopLedgerMismatch,
    LoopRunner,
    LoopSpec,
    RoundTripError,
    TraceCompileError,
    VERDICTS,
    compile_trace,
    compiled_tables,
    fault_plan_from_env,
    finetune_spec_from_json,
    grade_pairs,
    incumbent_meta,
    loop_spec_from_json,
    run_finetune,
    score_candidate,
    snapshot_digest,
    snapshot_trace,
    trace_scenario_name,
    usable_records,
    verdict_rank,
    verify_roundtrip,
)
from rl_scheduler_tpu.scheduler.tracelog import (
    TraceLog,
    clouds_from_token,
    clouds_token,
    decision_record,
    iter_trace,
    iter_trace_merged,
    trace_prefixes,
)

REPO_ROOT = Path(__file__).resolve().parents[1]


# ------------------------------------------------------------- helpers


def _record(i, *, ts=None, prefix_pos=None, endpoint="filter",
            generation=0, fail_open=False, clouds=("aws", "azure"),
            pod_cpu=0.2, telemetry_pos=None):
    """One hand-built trace record; ``ts`` overrides the wallclock stamp
    so merge-order tests are deterministic."""
    r = decision_record(
        endpoint=endpoint, family="set", backend="numpy",
        candidates=len(clouds), chosen=None if fail_open else "node-0",
        score=None if fail_open else 0.5, latency_ms=1.0,
        obs_sha="ab" * 8,
        telemetry_pos=i if telemetry_pos is None else telemetry_pos,
        worker_id=0, generation=generation, fail_open=fail_open,
        clouds=None if fail_open else list(clouds), pod_cpu=pod_cpu,
    )
    if ts is not None:
        r["ts"] = ts
    if prefix_pos is not None:
        r["telemetry_pos"] = prefix_pos
    return r


def _write_stream(trace_dir, prefix, records, seg_records=1024):
    log = TraceLog(trace_dir, prefix=prefix,
                   max_records_per_segment=seg_records)
    for r in records:
        assert log.append(r)
    log.close()


@pytest.fixture()
def trace_dir(tmp_path):
    """Two worker streams, 30 records each, distinct telemetry
    positions, schema-2 fields throughout."""
    d = tmp_path / "trace"
    for w in range(2):
        _write_stream(d, f"w{w}-",
                      [_record(w * 100 + i, pod_cpu=0.1 + 0.01 * i)
                       for i in range(30)], seg_records=8)
    return d


# ------------------------------------------------- merged trace iterator


class TestIterTraceMerged:
    def test_merges_streams_by_timestamp(self, tmp_path):
        d = tmp_path / "t"
        _write_stream(d, "w0-", [_record(i, ts=float(2 * i))
                                 for i in range(5)])
        _write_stream(d, "w1-", [_record(100 + i, ts=float(2 * i + 1))
                                 for i in range(5)])
        merged = list(iter_trace_merged(d))
        assert [r["ts"] for r in merged] == sorted(
            float(t) for t in range(10))
        # Alternating by construction: w0 even stamps, w1 odd.
        assert [r["telemetry_pos"] < 100 for r in merged] \
            == [True, False] * 5

    def test_equal_timestamps_interleave_stably(self, tmp_path):
        """The satellite pin: under EQUAL timestamps the merge breaks
        ties by prefix then per-stream order — deterministic across
        runs, so two consumers see the same sequence."""
        d = tmp_path / "t"
        _write_stream(d, "w0-", [_record(i, ts=1.0) for i in range(3)])
        _write_stream(d, "w1-", [_record(100 + i, ts=1.0)
                                 for i in range(3)])
        first = [r["telemetry_pos"] for r in iter_trace_merged(d)]
        assert first == [0, 1, 2, 100, 101, 102]  # w0- sorts before w1-
        assert first == [r["telemetry_pos"] for r in iter_trace_merged(d)]

    def test_prefixes_listed_sorted(self, trace_dir):
        assert trace_prefixes(trace_dir) == ["w0-", "w1-"]
        assert trace_prefixes(trace_dir / "missing") == []

    def test_single_stream_equals_iter_trace(self, tmp_path):
        d = tmp_path / "t"
        _write_stream(d, "", [_record(i) for i in range(7)])
        assert list(iter_trace_merged(d)) == list(iter_trace(d))

    def test_clock_step_back_clamps_not_misorders(self, tmp_path, caplog):
        """heapq.merge silently misorders unsorted inputs, so a
        wallclock step-back (NTP) within one stream clamps to the
        stream's running max — stream order survives and the merge
        stays correct, with one warning per stream."""
        d = tmp_path / "t"
        _write_stream(d, "w0-", [_record(0, ts=5.0), _record(1, ts=2.0),
                                 _record(2, ts=6.0)])
        _write_stream(d, "w1-", [_record(100, ts=5.5)])
        with caplog.at_level("WARNING"):
            merged = [r["telemetry_pos"] for r in iter_trace_merged(d)]
        # The clamped record (ts 2->5.0) stays in its stream slot before
        # w1's 5.5 instead of jumping to the front of the merge.
        assert merged == [0, 1, 100, 2]
        assert sum("step backwards" in r.message
                   for r in caplog.records) == 1

    def test_clouds_token_round_trip(self):
        assert clouds_from_token(clouds_token(["aws", "azure", None])) \
            == ["aws", "azure", None]
        assert clouds_token(None) is None
        assert clouds_from_token(None) is None
        assert clouds_token(["aws", "gcp"]) == "a?"


# ------------------------------------------------- trace-log edge cases


class TestTraceEdgeCases:
    def test_truncated_final_record_in_sealed_segment(self, tmp_path):
        """A sealed segment whose final line is torn (copied mid-write
        by the snapshotter) yields every whole record and skips the
        tail — and the compiler's usable_records sees the same."""
        d = tmp_path / "t"
        _write_stream(d, "", [_record(i) for i in range(4)])
        seg = sorted(d.glob("seg-*.jsonl"))[0]
        with open(seg, "ab") as f:
            f.write(b'{"schema": 2, "ts": 99.0, "telemetry')  # torn
        records = list(iter_trace(d))
        assert len(records) == 4
        used, stats = usable_records(d)
        assert len(used) == 4 and stats["records_total"] == 4

    def test_orphaned_part_sealed_at_startup(self, tmp_path):
        """A ``.part`` orphaned by a crashed writer is sealed when the
        next writer starts, mid-iteration-safe: the records it held are
        replayed, none duplicated."""
        d = tmp_path / "t"
        d.mkdir()
        orphan = d / "w0-seg-000000.jsonl.part"
        with open(orphan, "w") as f:
            for i in range(3):
                f.write(json.dumps(_record(i, ts=float(i))) + "\n")
        log = TraceLog(d, prefix="w0-")  # startup seals the orphan
        assert not orphan.exists()
        assert (d / "w0-seg-000000.jsonl").exists()
        assert log.append(_record(10, ts=10.0))
        log.close()
        positions = [r["telemetry_pos"] for r in iter_trace_merged(d)]
        assert positions == [0, 1, 2, 10]

    def test_generation_boundary_mid_segment(self, tmp_path):
        """Records from two policy generations inside ONE segment (a
        promote landing mid-file): the compiler keeps both and reports
        the generation set."""
        d = tmp_path / "t"
        recs = [_record(i, generation=0 if i < 3 else 1)
                for i in range(6)]
        _write_stream(d, "", recs, seg_records=1024)  # one segment
        assert len(list(d.glob("*.jsonl*"))) == 1
        used, stats = usable_records(d)
        assert len(used) == 6
        assert stats["generations"] == [0, 1]

    def test_probe_failopen_and_schema1_records_excluded(self, tmp_path):
        d = tmp_path / "t"
        recs = [_record(i) for i in range(4)]
        recs.append(_record(50, endpoint="probe"))
        recs.append(_record(51, fail_open=True))
        no_pos = _record(52)
        no_pos["telemetry_pos"] = None
        recs.append(no_pos)
        _write_stream(d, "", recs)
        used, stats = usable_records(d)
        assert len(used) == 4
        assert stats["probes_excluded"] == 1
        assert stats["fail_open_excluded"] == 1
        assert stats["missing_pos_excluded"] == 1


# ------------------------------------------------------------ retention


class TestTraceRetention:
    def test_prunes_oldest_sealed_segments_counted(self, tmp_path):
        d = tmp_path / "t"
        log = TraceLog(d, prefix="w0-", max_records_per_segment=2,
                       max_segments=2)
        for i in range(11):  # seals 5 segments + 1 active record
            assert log.append(_record(i, ts=float(i)))
        log.close()  # close seals the active part too (6 sealed total)
        sealed = sorted(p.name for p in d.glob("w0-seg-*.jsonl"))
        assert len(sealed) == 2
        snap = log.snapshot()
        assert snap["segments_pruned_total"] == 4
        assert snap["segments_total"] == 6
        # Replay only carries the retained window.
        assert [r["telemetry_pos"] for r in iter_trace(d)] == [8, 9, 10]

    def test_prune_leaves_other_streams_alone(self, tmp_path):
        d = tmp_path / "t"
        _write_stream(d, "w1-", [_record(i) for i in range(6)],
                      seg_records=2)
        other = sorted(p.name for p in d.glob("w1-*.jsonl"))
        log = TraceLog(d, prefix="w0-", max_records_per_segment=2,
                       max_segments=1)
        for i in range(8):
            log.append(_record(i))
        log.close()
        assert sorted(p.name for p in d.glob("w1-*.jsonl")) == other
        assert len(list(d.glob("w0-seg-*.jsonl"))) == 1

    def test_max_segments_validation(self, tmp_path):
        with pytest.raises(ValueError, match="max_segments"):
            TraceLog(tmp_path, max_segments=-1)

    def test_cli_flag_validation(self):
        from rl_scheduler_tpu.scheduler import extender

        with pytest.raises(SystemExit, match="trace-max-segments"):
            extender.main(["--backend", "greedy",
                           "--trace-max-segments", "-3"])
        with pytest.raises(SystemExit, match="trace-dir"):
            extender.main(["--backend", "greedy",
                           "--trace-max-segments", "4"])


# ------------------------------------------------------------- snapshot


class TestSnapshot:
    def test_snapshot_seals_parts_and_digests(self, trace_dir, tmp_path):
        # Leave an active .part behind (a live writer mid-segment).
        log = TraceLog(trace_dir, prefix="w2-", max_records_per_segment=100)
        log.append(_record(500))
        deadline = time.monotonic() + 10.0
        while (log.snapshot()["written_total"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.01)  # flushed to the .part, not sealed
        meta = snapshot_trace(trace_dir, tmp_path / "snap")
        log.close()
        assert meta["records"] == 61
        names = set(meta["files"])
        assert not any(n.endswith(".part") for n in names)
        assert any(n.startswith("w2-") for n in names)
        assert meta["digest"] == snapshot_digest(tmp_path / "snap")
        assert (tmp_path / "snap" / "snapshot.json").exists()

    def test_snapshot_missing_dir_refused(self, tmp_path):
        with pytest.raises(TraceCompileError, match="does not exist"):
            snapshot_trace(tmp_path / "nope", tmp_path / "snap")

    def test_compile_fault_site_fires(self, trace_dir, tmp_path):
        from rl_scheduler_tpu.utils.faults import FaultPlan

        plan = FaultPlan(schedule={"loopback.compile": (1,)})
        with pytest.raises(OSError, match="loopback.compile"):
            snapshot_trace(trace_dir, tmp_path / "snap", fault_plan=plan)
        assert plan.fired["loopback.compile"] == 1


# -------------------------------------------------------------- compile


class TestCompile:
    def test_bitwise_deterministic_per_seed(self, trace_dir):
        a = compile_trace(trace_dir, steps=16, seed=3, mix_frac=0.25)
        b = compile_trace(trace_dir, steps=16, seed=3, mix_frac=0.25)
        assert a.costs.tobytes() == b.costs.tobytes()
        assert a.latencies.tobytes() == b.latencies.tobytes()
        assert a.pod_scale.tobytes() == b.pod_scale.tobytes()
        assert a.stats == b.stats
        # A different seed draws a different window/mixture.
        c = compile_trace(trace_dir, steps=16, seed=4, mix_frac=0.25)
        assert (a.costs.tobytes() != c.costs.tobytes()
                or a.pod_scale.tobytes() != c.pod_scale.tobytes())

    def test_compiled_shape_and_pod_provenance(self, trace_dir):
        compiled = compile_trace(trace_dir, steps=16, seed=0)
        assert isinstance(compiled, CompiledTrace)
        assert compiled.steps == 16
        assert compiled.costs.shape == (16, 2)
        assert compiled.pod_from_trace
        assert compiled.stats["usable_records"] == 60
        assert compiled.stats["mixed_rows"] == 0
        tables = compiled_tables(trace_dir, steps=16, seed=0)
        assert tables["costs"].tobytes() == compiled.costs.tobytes()

    def test_schema1_records_degrade_pod(self, tmp_path):
        d = tmp_path / "t"
        recs = [_record(i) for i in range(4)]
        recs[2]["pod_cpu"] = None  # one legacy record poisons the column
        _write_stream(d, "", recs)
        compiled = compile_trace(d, steps=4)
        assert not compiled.pod_from_trace
        assert compiled.pod_scale is None
        assert compiled.stats["records_without_pod"] == 1

    def test_too_few_records_refused(self, tmp_path):
        d = tmp_path / "t"
        _write_stream(d, "", [_record(0, endpoint="probe")] * 5)
        with pytest.raises(TraceCompileError, match="usable decision"):
            compile_trace(d)
        with pytest.raises(TraceCompileError, match="steps"):
            compile_trace(d, steps=1)

    def test_scenario_name_round_trips(self, trace_dir):
        from rl_scheduler_tpu.scenarios import get_scenario

        name = trace_scenario_name(trace_dir, steps=16, mix_frac=0.25)
        scn = get_scenario(name, seed=5)
        assert scn.family == "trace_replay"
        assert scn.steps == 16 and scn.seed == 5
        assert scn.knob("mix_frac") == 0.25
        assert scn.knob("trace_dir") == str(trace_dir)
        # Mix-free name carries no query params beyond steps.
        assert "mix" not in trace_scenario_name(trace_dir, steps=16)

    def test_scenario_name_validation(self):
        from rl_scheduler_tpu.scenarios import get_scenario

        with pytest.raises(ValueError, match="unknown trace_replay"):
            get_scenario("trace_replay:/x?foo=1")
        with pytest.raises(ValueError, match="bad value"):
            get_scenario("trace_replay:/x?steps=abc")
        with pytest.raises(ValueError, match="snapshot directory"):
            get_scenario("trace_replay:")
        with pytest.raises(ValueError, match="mix_frac"):
            get_scenario("trace_replay:/x?mix=1.0")

    def test_families_registry_gained_trace_replay(self):
        from rl_scheduler_tpu.scenarios.families import trace_replay_tables
        from rl_scheduler_tpu.scenarios.spec import FAMILIES

        assert "trace_replay" in FAMILIES
        # 7 since graftmix added external_trace (tests/test_mixtures.py
        # owns that family's registry pin).
        assert len(FAMILIES) == 7
        assert callable(trace_replay_tables)

    def test_roundtrip_pin_through_real_env(self, trace_dir, tmp_path):
        """The compile contract: env reset/step over the compiled
        scenario reproduces the trace-derived cost/latency/pod columns
        bit-exactly (documented digest semantics — the live-CPU column
        is out of scope)."""
        from rl_scheduler_tpu.scenarios import get_scenario

        snapshot_trace(trace_dir, tmp_path / "snap")
        name = trace_scenario_name(tmp_path / "snap", steps=16)
        report = verify_roundtrip(get_scenario(name), num_nodes=8)
        assert report["steps_checked"] == 15
        assert report["pod_checked"]

    def test_roundtrip_detects_a_wrong_compile(self, trace_dir, tmp_path,
                                               monkeypatch):
        from rl_scheduler_tpu.scenarios import families
        from rl_scheduler_tpu.scenarios import get_scenario

        snapshot_trace(trace_dir, tmp_path / "snap")
        name = trace_scenario_name(tmp_path / "snap", steps=16)
        real = families.trace_replay_tables

        def poisoned(*a, **kw):
            t = dict(real(*a, **kw))
            t["costs"] = t["costs"] + 0.125  # a wrong reconstruction
            return t

        monkeypatch.setattr(families, "trace_replay_tables", poisoned)
        with pytest.raises(RoundTripError, match="compiled trace rows"):
            verify_roundtrip(get_scenario(name), num_nodes=4)


# ------------------------------------------------------ verdict grading


class TestVerdict:
    def test_grade_pairs_known_values(self):
        win = [(1.0, 0.0)] * 5
        g = grade_pairs(win)
        assert g["verdict"] == "confirmed_above"
        assert g["wins"] == 5 and g["losses"] == 0
        assert g["win_rate_wilson95"][0] > 0.5
        assert grade_pairs([(0.0, 1.0)] * 5)["verdict"] == "confirmed_below"
        # 3/5: the interval straddles 0.5 — a point lead only.
        assert grade_pairs(win[:3] + [(0.0, 1.0)] * 2)["verdict"] \
            == "point_above"
        assert grade_pairs(win[:2] + [(0.0, 1.0)] * 3)["verdict"] \
            == "point_below"
        # All ties demonstrate nothing.
        g = grade_pairs([(1.0, 1.0)] * 4)
        assert g["verdict"] == "point_below" and g["ties"] == 4
        # 3 wins of 3 cannot confirm (Wilson lower 0.438 < 0.5).
        assert grade_pairs(win[:3])["verdict"] == "point_above"

    def test_verdict_rank_scale(self):
        assert [verdict_rank(v) for v in VERDICTS] == [0, 1, 2, 3]
        assert verdict_rank("confirmed_above") > verdict_rank("point_above")
        with pytest.raises(ValueError, match="unknown verdict"):
            verdict_rank("amazing")

    def test_finetune_spec_validation(self):
        ok = FinetuneSpec(incumbent="run", scenario="trace_replay:/x")
        assert finetune_spec_from_json(ok.to_json()) == ok
        assert ok.fingerprint() == finetune_spec_from_json(
            ok.to_json()).fingerprint()
        with pytest.raises(ValueError, match="trace_replay"):
            FinetuneSpec(incumbent="run", scenario="bursty")
        with pytest.raises(ValueError, match="double-count"):
            FinetuneSpec(incumbent="run", scenario="trace_replay:/x",
                         verdict_seeds=(0, 0))
        with pytest.raises(ValueError, match="eval_every"):
            FinetuneSpec(incumbent="run", scenario="trace_replay:/x",
                         eval_every=0)
        with pytest.raises(ValueError, match="unknown verdict"):
            FinetuneSpec(incumbent="run", scenario="trace_replay:/x",
                         required_verdict="sideways")

    def test_loop_spec_validation(self):
        ok = LoopSpec(trace_dir="/t", incumbent="run", dry_run=True)
        assert loop_spec_from_json(ok.to_json()) == ok
        with pytest.raises(ValueError, match="pool_url"):
            LoopSpec(trace_dir="/t", incumbent="run")
        with pytest.raises(ValueError, match="mix_frac"):
            LoopSpec(trace_dir="/t", incumbent="run", dry_run=True,
                     mix_frac=1.0)
        with pytest.raises(ValueError, match="trace_dir"):
            LoopSpec(trace_dir="", incumbent="run", dry_run=True)

    def test_fault_plan_from_env(self):
        assert fault_plan_from_env(None) is None
        assert fault_plan_from_env("") is None
        plan = fault_plan_from_env(
            "loopback.compile:1,3; loopback.promote:2")
        assert set(plan.schedule["loopback.compile"]) == {1, 3}
        assert set(plan.schedule["loopback.promote"]) == {2}
        with pytest.raises(ValueError, match="site:call_index"):
            fault_plan_from_env("loopback.promote")
        with pytest.raises(ValueError, match="integers"):
            fault_plan_from_env("loopback.promote:x")


# ------------------------------------------------------------- ledger


def _spec(tmp_path, **kw):
    kw.setdefault("trace_dir", str(tmp_path / "trace"))
    kw.setdefault("incumbent", str(tmp_path / "incumbent"))
    kw.setdefault("dry_run", True)
    return LoopSpec(**kw)


class TestLoopLedger:
    def test_appends_preserve_prior_bytes(self, tmp_path):
        spec = _spec(tmp_path)
        ledger = LoopLedger(tmp_path / "loop", spec)
        ledger.append_stage("snapshot", "ok", {"records": 3})
        before = ledger.path.read_bytes()
        ledger.append_stage("compile", "ok", {"scenario": "x"})
        after = ledger.path.read_bytes()
        assert after.startswith(before)
        assert ledger.stages()["snapshot"]["out"] == {"records": 3}
        # Reopening under the same spec resumes the same records.
        again = LoopLedger(tmp_path / "loop", spec)
        assert set(again.stages()) == {"snapshot", "compile"}

    def test_changed_spec_refuses_resume(self, tmp_path):
        LoopLedger(tmp_path / "loop", _spec(tmp_path))
        with pytest.raises(LoopLedgerMismatch, match="changed loop"):
            LoopLedger(tmp_path / "loop", _spec(tmp_path, steps=64))


# ------------------------------------------------- orchestrator (stubbed)


def _stub_outs():
    """Stage outputs shaped like the real ones — enough for run()'s
    summary extraction."""
    return {
        "snapshot": {"snapshot": "/snap", "digest": "d", "records": 9,
                     "segments": 1},
        "compile": {"scenario": "trace_replay:/snap?steps=16",
                    "train_scenario": "trace_replay:/snap?steps=16&mix=0.25",
                    "stats": {"steps": 16}, "roundtrip": {"steps_checked": 15}},
        "retrain": {"candidate": "/cand"},
    }


def _verdict_out(promote):
    return {"matrix": {}, "candidate": "/cand", "incumbent": "/inc",
            "verdict": "confirmed_above" if promote else "point_below",
            "required_verdict": "confirmed_above", "promote": promote}


class TestLoopRunnerResume:
    def test_resume_skips_completed_stages(self, tmp_path, monkeypatch):
        """Recorded stages are never re-entered: stub every stage to
        count calls, pre-record the first two, run — only the last
        three execute."""
        spec = _spec(tmp_path)
        runner = LoopRunner(spec, tmp_path / "loop")
        outs = _stub_outs()
        runner.ledger.append_stage("snapshot", "ok", outs["snapshot"])
        runner.ledger.append_stage("compile", "ok", outs["compile"])
        calls = []
        monkeypatch.setattr(LoopRunner, "_stage_snapshot",
                            lambda self: calls.append("snapshot"))
        monkeypatch.setattr(LoopRunner, "_stage_compile",
                            lambda self, s: calls.append("compile"))
        monkeypatch.setattr(LoopRunner, "_stage_retrain",
                            lambda self, s: (calls.append("retrain"),
                                             outs["retrain"])[1])
        monkeypatch.setattr(
            LoopRunner, "_stage_evaluate",
            lambda self, c, s: (calls.append("evaluate"),
                                _verdict_out(False))[1])
        summary = runner.run()
        assert calls == ["retrain", "evaluate"]
        assert summary["promote_status"] == "refused"
        assert not summary["promoted"]
        # A re-run now skips EVERYTHING, bitwise-identical summary.
        calls.clear()
        assert LoopRunner(spec, tmp_path / "loop").run() == summary
        assert calls == []

    def test_failing_verdict_refuses_without_pool_contact(self, tmp_path):
        """promote:false short-circuits BEFORE any pool I/O — a refused
        candidate must leave the pool untouched (no pool_url needed at
        all on this path, dry_run aside)."""
        spec = _spec(tmp_path, dry_run=False, pool_url="http://127.0.0.1:1")
        runner = LoopRunner(spec, tmp_path / "loop")
        status, out = runner._stage_promote("/cand", _verdict_out(False))
        assert status == "refused"
        assert "below required" in out["reason"]

    def test_dry_run_stops_before_promote(self, tmp_path):
        runner = LoopRunner(_spec(tmp_path), tmp_path / "loop")
        status, out = runner._stage_promote("/cand", _verdict_out(True))
        assert status == "refused"
        assert out["would_promote"] == "/cand"

    def test_promote_fault_leaves_no_record(self, tmp_path):
        """The loopback.promote chaos seam fires BEFORE the POST: the
        stage raises, nothing is recorded, and a resumed run re-enters
        exactly the promote stage."""
        from rl_scheduler_tpu.utils.faults import FaultPlan

        spec = _spec(tmp_path, dry_run=False, pool_url="http://127.0.0.1:1")
        plan = FaultPlan(schedule={"loopback.promote": (1,)})
        runner = LoopRunner(spec, tmp_path / "loop", fault_plan=plan)
        outs = _stub_outs()
        for stage in ("snapshot", "compile", "retrain"):
            runner.ledger.append_stage(stage, "ok", outs[stage])
        runner.ledger.append_stage("evaluate", "ok", _verdict_out(True))
        with pytest.raises(OSError, match="loopback.promote"):
            runner.run()
        assert plan.fired["loopback.promote"] == 1
        assert "promote" not in runner.ledger.stages()
        before = runner.ledger.path.read_bytes()
        # Disarmed resume re-enters promote only; the unreachable pool
        # is a TRANSIENT failure (URLError) — still no record, so yet
        # another resume would retry the promote.
        resumed = LoopRunner(spec, tmp_path / "loop")
        with pytest.raises(urllib.error.URLError):
            resumed.run()
        assert resumed.ledger.path.read_bytes() == before

    def test_pool_409_and_5xx_are_transient_not_refusals(self, tmp_path,
                                                         monkeypatch):
        """A 409 (rollout already in flight — possibly OUR interrupted
        promote) or a 5xx must RAISE so the stage stays unrecorded and a
        resume retries; only candidate-judging 4xx (e.g. 422 verify
        failure) records the permanent ``refused``."""
        import io

        from rl_scheduler_tpu.loopback import orchestrator as orch

        spec = _spec(tmp_path, dry_run=False, pool_url="http://127.0.0.1:1")
        runner = LoopRunner(spec, tmp_path / "loop")

        def _http_error(code):
            def _raise(req, timeout=None):
                raise urllib.error.HTTPError(
                    req.full_url, code, "err", {},
                    io.BytesIO(b'{"error": "detail"}'))
            return _raise

        for code in (409, 500, 503):
            monkeypatch.setattr(orch.urllib.request, "urlopen",
                                _http_error(code))
            with pytest.raises(RuntimeError, match=f"{code}.*transient"):
                runner._stage_promote("/cand", _verdict_out(True))
        monkeypatch.setattr(orch.urllib.request, "urlopen",
                            _http_error(422))
        status, out = runner._stage_promote("/cand", _verdict_out(True))
        assert status == "refused" and "422" in out["reason"]


# ------------------------------------------------------ warm start (ppo)


class TestWarmStart:
    def test_cli_warm_start_exclusive_with_resume(self):
        from rl_scheduler_tpu.agent import train_ppo

        with pytest.raises(SystemExit, match="pick one"):
            train_ppo.main(["--warm-start", "/x", "--resume",
                            "--preset", "quick"])
        with pytest.raises(SystemExit, match="single-chip"):
            train_ppo.main(["--warm-start", "/x", "--dp", "2",
                            "--preset", "quick"])

    def test_warm_start_params_installed_and_guarded(self):
        """ppo_train(warm_start_params=): same warm source + seed ⇒
        bitwise-identical training; a fresh init differs; restore and
        shape mismatches are refused."""
        import jax

        from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, ppo_train
        from rl_scheduler_tpu.env.core import make_params

        env = make_params()
        cfg = PPOTrainConfig(num_envs=2, rollout_steps=4,
                             minibatch_size=8, num_epochs=1,
                             hidden=(16,))
        fresh, _ = ppo_train(env, cfg, num_iterations=1, seed=0)
        warm_a, _ = ppo_train(env, cfg, num_iterations=1, seed=0,
                              warm_start_params=fresh.params)
        warm_b, _ = ppo_train(env, cfg, num_iterations=1, seed=0,
                              warm_start_params=fresh.params)
        la = jax.tree_util.tree_leaves(warm_a.params)
        lb = jax.tree_util.tree_leaves(warm_b.params)
        assert all(np.array_equal(a, b) for a, b in zip(la, lb))
        lf = jax.tree_util.tree_leaves(fresh.params)
        assert any(not np.array_equal(a, f) for a, f in zip(la, lf))
        with pytest.raises(ValueError, match="pick one"):
            ppo_train(env, cfg, num_iterations=1,
                      warm_start_params=fresh.params,
                      restore=(fresh.params, 1))
        wide = PPOTrainConfig(num_envs=2, rollout_steps=4,
                              minibatch_size=8, num_epochs=1,
                              hidden=(24,))
        with pytest.raises(ValueError, match="shapes do not match"):
            ppo_train(env, wide, num_iterations=1,
                      warm_start_params=fresh.params)


# ------------------------------------------------------- bench replay


class TestBenchReplay:
    def _bench(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "extender_bench",
            REPO_ROOT / "loadgen" / "extender_bench.py")
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        return bench

    def test_load_replay_payloads(self, tmp_path):
        d = tmp_path / "t"
        recs = [_record(i, clouds=("aws", "azure", "aws"), pod_cpu=0.25)
                for i in range(5)]
        recs.append(_record(50, endpoint="probe"))
        legacy = _record(51)
        legacy["clouds"] = None
        recs.append(legacy)
        _write_stream(d, "", recs)
        bench = self._bench()
        payloads, report = bench.load_replay_payloads(str(d))
        assert report == {"trace_records": 5, "skipped": 1,
                          "probes_excluded": 1, "nodes": 3,
                          "capacity_cores": 4.0}
        # --replay-limit pass-through bounds how much is prebuilt (a
        # long-serving trace dir must not be materialized whole).
        capped, capped_report = bench.load_replay_payloads(str(d), limit=2)
        assert len(capped) == 2 and capped_report["trace_records"] == 2
        # A non-default server capacity rescales the re-issued quantity:
        # 0.25 of 8 cores = 2000m (must match --node-capacity-cores).
        wide, _ = bench.load_replay_payloads(str(d),
                                             node_capacity_cores=8.0)
        assert json.loads(wide[0])["pod"]["spec"]["containers"][0][
            "resources"]["requests"]["cpu"] == "2000m"
        body = json.loads(payloads[0])
        items = body["nodes"]["items"]
        assert [n["metadata"]["labels"]["cloud"] for n in items] \
            == ["aws", "azure", "aws"]
        # 0.25 of the 4-core default capacity = 1000 millicores.
        cpu = body["pod"]["spec"]["containers"][0]["resources"][
            "requests"]["cpu"]
        assert cpu == "1000m"

    def test_replay_refuses_empty_trace(self, tmp_path):
        d = tmp_path / "t"
        _write_stream(d, "", [_record(0, endpoint="probe")])
        with pytest.raises(SystemExit, match="no replayable"):
            self._bench().load_replay_payloads(str(d))


# ----------------------------------------------------- the loop drill


def _post(port, path, payload, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as resp:
        body = resp.read()
    return json.loads(body) if path != "/metrics" else body.decode()


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# `incumbent_run` is session-scoped in conftest.py: the graftpilot
# daemon drill shares the same one-iteration incumbent training run.


def test_incumbent_meta_reads_newest_verified(incumbent_run):
    meta = incumbent_meta(incumbent_run)
    assert meta["env"] == "cluster_set"
    assert meta.get("algo", "ppo") == "ppo"  # absent = ppo (graftguard)


def test_loop_drill_serving_promote(incumbent_run, tmp_path):
    """`make loop-drill`, the ROADMAP item-1 acceptance: a 2-worker
    pool serves bench traffic CONTINUOUSLY while one loop iteration
    snapshots its live trace, compiles the trace_replay scenario
    (round-trip pinned inside the compile stage), retrains from the
    incumbent, wins the paired-seed verdict, and hot-promotes through
    graftroll's canary gates — zero failed requests throughout, and a
    SIGKILLed loop resumes from its ledger without rerunning completed
    stages."""
    port, cport = _free_port(), _free_port()
    pool_trace = tmp_path / "pool_trace"
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT) + os.pathsep \
        + env.get("PYTHONPATH", "")
    # The pool runs as the REAL CLI in a fresh process (the production
    # entry; a pool forked from a jax-initialized pytest process would
    # hit the multithreaded-fork deadlock the supervisor design avoids).
    proc = subprocess.Popen(
        [sys.executable, "-m", "rl_scheduler_tpu.scheduler.extender",
         "--workers", "2", "--host", "127.0.0.1",
         "--port", str(port), "--control-port", str(cport),
         "--run", str(incumbent_run), "--backend", "cpu",
         "--trace-dir", str(pool_trace), "--trace-max-segments", "50"],
        env=env, start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    failures, served = [], []
    stop = threading.Event()

    def _traffic():
        """Continuous bench-payload traffic; connection errors during a
        rolling restart retry like the bench's soak mode (3x), HTTP
        errors count as failures."""
        i = 0
        while not stop.is_set():
            body = _bench_payload(i)
            for attempt in range(4):
                try:
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{port}/filter", data=body,
                        headers={"Content-Type": "application/json"})
                    with urllib.request.urlopen(req, timeout=10) as resp:
                        json.load(resp)
                    served.append(i)
                    break
                except urllib.error.HTTPError as e:
                    failures.append((i, e.code))
                    break
                except OSError:
                    if attempt == 3:
                        failures.append((i, "connect"))
                    else:
                        time.sleep(0.1)
            i += 1
            time.sleep(0.03)

    loop_dir = tmp_path / "loop"
    killed_ledger = None
    try:
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            try:
                if _get(cport, "/healthz")["alive"] == 2:
                    break
            except OSError:
                time.sleep(0.2)
        else:
            pytest.fail("pool never came up")

        thread = threading.Thread(target=_traffic, daemon=True)
        thread.start()
        # Let the pool log enough decisions to compile from.
        deadline = time.monotonic() + 120.0
        while len(served) < 40 and time.monotonic() < deadline:
            time.sleep(0.2)
        assert len(served) >= 40, "traffic never ramped"

        argv = [
            sys.executable, "-m", "rl_scheduler_tpu.loopback",
            "--trace-dir", str(pool_trace),
            "--incumbent", str(incumbent_run),
            "--out", str(loop_dir),
            "--pool", f"http://127.0.0.1:{cport}",
            "--steps", "16", "--mix", "0.25", "--iterations", "3",
            "--eval-every", "1", "--eval-episodes", "2",
            "--verdict-seeds", "0-4", "--verdict-episodes", "4",
            "--rollout-timeout", "180",
        ]
        env = dict(os.environ)
        env["PYTHONPATH"] = str(REPO_ROOT) + os.pathsep \
            + env.get("PYTHONPATH", "")

        # First run: SIGKILL the whole process group once the compile
        # stage is recorded (mid-retrain) — the honest interrupt.
        first = subprocess.Popen(argv, env=env, start_new_session=True,
                                 stdout=subprocess.DEVNULL,
                                 stderr=subprocess.DEVNULL)
        ledger_path = loop_dir / "loop_ledger.jsonl"
        deadline = time.monotonic() + 240.0
        try:
            while time.monotonic() < deadline:
                if ledger_path.exists() \
                        and '"stage": "compile"' in ledger_path.read_text():
                    break
                if first.poll() is not None:
                    pytest.fail("loop CLI exited before compile stage "
                                f"(rc={first.returncode})")
                time.sleep(0.2)
            else:
                pytest.fail("compile stage never recorded")
        finally:
            try:
                os.killpg(first.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        first.wait(timeout=30)
        killed_ledger = ledger_path.read_bytes()
        snapshot_mtime = (loop_dir / "trace_snapshot"
                          / "snapshot.json").stat().st_mtime_ns

        # Resume: completed stages skip (snapshot bytes + ledger prefix
        # prove it), the loop retrains, wins the verdict, promotes.
        out = subprocess.run(argv, env=env, capture_output=True,
                             text=True, timeout=900)
        assert out.returncode == 0, out.stderr[-2000:]
        summary = json.loads(
            [ln for ln in out.stdout.splitlines()
             if ln.startswith("{")][-1])
        assert ledger_path.read_bytes().startswith(killed_ledger)
        assert (loop_dir / "trace_snapshot"
                / "snapshot.json").stat().st_mtime_ns == snapshot_mtime
        assert summary["promoted"], summary
        assert summary["verdict"] == "confirmed_above"
        assert summary["roundtrip"]["steps_checked"] >= 8
        assert summary["compile"]["probes_excluded"] >= 0
        assert summary["promote"]["generation"] == 1

        # The pool landed the candidate generation on every worker and
        # kept serving: zero failed requests, trace counters monotonic.
        status = _get(cport, "/rollout")
        assert status["generation"] == 1 and not status["active"]
        assert status["promotions_total"] == 1
        metrics = _get(cport, "/metrics")
        assert "rl_scheduler_extender_pool_generation 1" in metrics
        assert "rl_scheduler_extender_trace_segments_pruned_total" \
            in metrics

        # The promoted candidate records its warm-start provenance.
        from rl_scheduler_tpu.utils.checkpoint import load_policy_params

        _, cand_meta = load_policy_params(summary["candidate"])
        assert cand_meta["warm_start"] == str(incumbent_run)
        assert cand_meta["scenario"].startswith("trace_replay:")

        # Traffic kept flowing mid-promote.
        before_stop = len(served)
        time.sleep(1.0)
        assert len(served) > before_stop
    finally:
        stop.set()
        try:
            os.killpg(proc.pid, signal.SIGTERM)
            proc.wait(timeout=30)
        except (ProcessLookupError, subprocess.TimeoutExpired):
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            proc.wait(timeout=10)
    assert failures == [], f"dropped requests: {failures[:10]}"
    assert len(served) >= 60


def _bench_payload(i, num_nodes=8):
    items = [
        {"metadata": {"name": f"node-{j}",
                      "labels": {"cloud": "aws" if j < num_nodes // 2
                                 else "azure"}}}
        for j in range(num_nodes)
    ]
    return json.dumps({
        "pod": {"metadata": {"name": f"drill-pod-{i}"},
                "spec": {"containers": [{"resources": {
                    "requests": {"cpu": "800m"}}}]}},
        "nodes": {"items": items},
    }).encode()


def test_loop_drill_rollback_on_regressing_candidate(tmp_path):
    """A verdict can pass while the pool's own gates still refuse: a
    verifies-clean-but-regressing candidate fails the canary's warm-up
    probes and _stage_promote records ``rolled_back`` — graftroll's
    machinery unchanged under graftloop."""
    import hashlib

    from rl_scheduler_tpu.scheduler.extender import ExtenderPolicy
    from rl_scheduler_tpu.scheduler.policy_backend import GreedyBackend
    from rl_scheduler_tpu.scheduler.pool import ServingPool
    from rl_scheduler_tpu.scheduler.telemetry import (
        RandomCpu,
        TableTelemetry,
    )
    from rl_scheduler_tpu.utils.retry import RetryPolicy

    def _verified_checkpoint(root, name):
        run = Path(root) / name
        step = run / "checkpoints" / "1"
        step.mkdir(parents=True)
        payload = (name.encode() + b"-weights") * 64
        (step / "state.bin").write_bytes(payload)
        mdir = run / "checkpoint_manifests"
        mdir.mkdir()
        (mdir / "1.json").write_text(json.dumps({
            "step": 1,
            "files": {"state.bin": {
                "sha256": hashlib.sha256(payload).hexdigest(),
                "size": len(payload)}},
        }))
        return run

    class _Poisoned:
        name = "poisoned"

        def decide(self, obs):
            raise RuntimeError("regressing checkpoint")

    def factory(worker_id, shared, spec):
        telemetry = TableTelemetry.from_table(
            cpu_source=RandomCpu(seed=0), counter=shared.table_counter)
        backend = (_Poisoned() if spec.checkpoint
                   and "regress" in Path(spec.checkpoint).name
                   else GreedyBackend())
        return ExtenderPolicy(backend, telemetry)

    regress = _verified_checkpoint(tmp_path, "ckpt-regress")
    good = _verified_checkpoint(tmp_path, "ckpt-good")
    pool = ServingPool(
        factory, workers=2, host="127.0.0.1", port=0, control_port=0,
        restart_policy=RetryPolicy(max_attempts=4, base_delay_s=0.05,
                                   max_delay_s=0.2, deadline_s=30.0),
        stable_after_s=60.0, poll_interval_s=0.05,
        rollout_opts={"canary_hold_s": 0.2, "probe_count": 2,
                      "ready_timeout_s": 60.0})
    pool.start(ready_timeout_s=60.0)
    try:
        cport = pool.control_address[1]
        spec = LoopSpec(trace_dir=str(tmp_path), incumbent=str(tmp_path),
                        pool_url=f"http://127.0.0.1:{cport}",
                        dry_run=False)
        runner = LoopRunner(spec, tmp_path / "loop",
                            rollout_timeout_s=120.0)

        # (a) regressing candidate: pool verifies it clean, the canary
        # probes fail, the pool rolls back — recorded, not raised.
        status, out = runner._stage_promote(str(regress),
                                            _verdict_out(True))
        assert status == "rolled_back", out
        assert _get(cport, "/rollout")["generation"] == 0
        assert _get(cport, "/rollout")["rollbacks_total"] == 1

        # (b) a good candidate through the same seam lands.
        status, out = runner._stage_promote(str(good), _verdict_out(True))
        assert status == "ok", out
        assert out["generation"] == 1
        assert _get(cport, "/rollout")["generation"] == 1

        # (c) pool-side refusal (corrupt candidate) is a recorded
        # refusal, not an exception.
        bad = _verified_checkpoint(tmp_path, "ckpt-bad")
        (bad / "checkpoints" / "1" / "state.bin").write_bytes(b"JUNK")
        status, out = runner._stage_promote(str(bad), _verdict_out(True))
        assert status == "refused"
        assert "422" in out["reason"]
        assert _get(cport, "/rollout")["generation"] == 1
    finally:
        pool.shutdown()


@pytest.mark.slow
def test_loop_soak_score_candidate_end_to_end(incumbent_run, tmp_path):
    """The in-process retrain+verdict path (`make loop-soak` rides the
    full drill plus this): run_finetune trains a real candidate from
    the incumbent on a compiled trace and score_candidate grades the
    paired matrix with the anti-forgetting gate attached."""
    d = tmp_path / "trace"
    _write_stream(d, "w0-", [_record(i, pod_cpu=0.2) for i in range(40)])
    snap = tmp_path / "snap"
    snapshot_trace(d, snap)
    spec = FinetuneSpec(
        incumbent=str(incumbent_run),
        scenario=trace_scenario_name(snap, steps=16, mix_frac=0.25),
        iterations=2, eval_every=1, eval_episodes=2,
        verdict_seeds=(0, 1, 2), verdict_episodes=2)
    cand = run_finetune(spec, tmp_path / "retrain",
                        log_path=tmp_path / "retrain.log")
    assert (cand / "checkpoints").is_dir()
    verdict = score_candidate(cand, incumbent_run, spec)
    assert verdict["verdict"] in VERDICTS
    trace_grade = verdict["matrix"]["trace_scenario"]
    assert trace_grade["pairs"] == 3
    # random_phase verdict protocol: per-seed deltas must differ — a
    # deterministic replay would grade one sample n times.
    assert len(set(trace_grade["per_seed_delta"])) > 1
    orig = verdict["matrix"]["original_workload"]
    assert "regression_pct" in orig and "forgot" in orig
