"""Vectorized env: vmap equivalence, auto-reset semantics, scan rollouts."""

import jax
import jax.numpy as jnp
import numpy as np

from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env import core, vector
from rl_scheduler_tpu.env.baselines import cost_greedy_policy


def make_params(**kw):
    return core.make_params(EnvConfig(**kw))


def test_vmap_matches_single():
    """Env 0 of a batch must evolve exactly like a single env with the same
    key (vmap is a pure batching transform over the state pytree)."""
    params = make_params()
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    bstate, bobs = vector.reset_batch(params, jax.random.PRNGKey(0), 4)
    sstate, sobs = core.reset(params, keys[0])
    np.testing.assert_array_equal(np.asarray(bobs[0]), np.asarray(sobs))
    actions = jnp.zeros((4,), jnp.int32)
    bstate, bts = vector.step_autoreset_batch(params, bstate, actions)
    sstate, sts = vector.step_autoreset(params, sstate, jnp.asarray(0))
    np.testing.assert_array_equal(np.asarray(bts.obs[0]), np.asarray(sts.obs))
    np.testing.assert_allclose(float(bts.reward[0]), float(sts.reward), rtol=1e-6)


def test_autoreset_cycles():
    """A short-episode env must restart at row 0 after done and keep going."""
    params = make_params(max_steps=3)
    state, obs = core.reset(params, jax.random.PRNGKey(1))
    step = jax.jit(vector.step_autoreset)
    dones = []
    for i in range(10):
        state, ts = step(params, state, jnp.asarray(0))
        dones.append(bool(ts.done))
        expected_idx = (i + 1) % 3
        assert int(state.step_idx) == expected_idx
        # after a done, obs must be the row-0 observation
        if ts.done:
            np.testing.assert_allclose(
                np.asarray(ts.obs[:4]),
                np.asarray(jnp.concatenate([params.costs[0], params.latencies[0]])),
                rtol=1e-6,
            )
    assert dones == [False, False, True] * 3 + [False]


def test_rollout_scan_shapes_and_rewards():
    params = make_params()
    num_envs, num_steps = 8, 50
    state, obs = vector.reset_batch(params, jax.random.PRNGKey(2), num_envs)

    def policy(ob, key):
        return cost_greedy_policy(ob)

    final_state, final_obs, _, traj = jax.jit(
        vector.rollout_from, static_argnums=(4, 5)
    )(params, state, obs, jax.random.PRNGKey(3), policy, num_steps)
    assert traj["obs"].shape == (num_steps, num_envs, core.OBS_DIM)
    assert traj["action"].shape == (num_steps, num_envs)
    assert traj["reward"].shape == (num_steps, num_envs)
    # cost-greedy under corrected sign: all rewards negative
    assert float(traj["reward"].max()) < 0.0
    assert final_obs.shape == (num_envs, core.OBS_DIM)
    # greedy actions must equal argmin of cost columns in the obs
    expected = np.where(np.asarray(traj["obs"][..., 0]) <= np.asarray(traj["obs"][..., 1]), 0, 1)
    np.testing.assert_array_equal(np.asarray(traj["action"]), expected)


def test_rollout_episode_boundaries():
    """done flags appear every max_steps steps for every env (all envs start
    at row 0 and the table replay is synchronized)."""
    params = make_params(max_steps=5)
    state, obs = vector.reset_batch(params, jax.random.PRNGKey(4), 3)
    _, _, _, traj = vector.rollout_from(
        params, state, obs, jax.random.PRNGKey(5), lambda o, k: cost_greedy_policy(o), 17
    )
    done = np.asarray(traj["done"])
    for e in range(3):
        assert list(np.where(done[:, e])[0]) == [4, 9, 14]


def test_large_vmap_smoke():
    params = make_params()
    n = 2048
    state, obs = vector.reset_batch(params, jax.random.PRNGKey(6), n)
    state, ts = jax.jit(vector.step_autoreset_batch)(
        params, state, jnp.zeros((n,), jnp.int32)
    )
    assert ts.obs.shape == (n, core.OBS_DIM)
    assert bool(jnp.all(ts.step == 1))
