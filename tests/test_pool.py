"""graftserve (scheduler/pool.py): the multi-worker serving plane.

Aggregation semantics are pinned at two levels: pure-function tests feed
synthetic per-worker snapshots to ``aggregate_stats``/``aggregate_metrics``
(breaker max-merge, request-weighted fractions, merged-histogram
quantiles), and end-to-end tests fork a real pool — SO_REUSEPORT workers
plus the inherit fallback — and check the supervisor's ``/stats``,
``/metrics``, ``/stats/reset`` fan-out, dead-worker restart, and the
shared price-replay/table counters against single-process ground truth.
Multi-process tests keep worker counts small and backoffs short so they
stay inside the tier-1 budget; the bench-driven soak is marked ``slow``
(``make serve-soak``).
"""

import hashlib
import json
import os
import shutil
import signal
import socket
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from rl_scheduler_tpu.scheduler.extender import (
    ExtenderPolicy,
    LatencyStats,
    make_server,
)
from rl_scheduler_tpu.scheduler.policy_backend import GreedyBackend
from rl_scheduler_tpu.scheduler.pool import (
    PoolShared,
    ServingPool,
    SharedCounter,
    _HistogramView,
    aggregate_metrics,
    aggregate_stats,
    merge_worker_histograms,
    quantiles_from_histogram,
    run_pool,
    worker_snapshot,
)
from rl_scheduler_tpu.scheduler.rollout import (
    RolloutController,
    WorkerSpec,
    verify_candidate,
)
from rl_scheduler_tpu.scheduler.telemetry import RandomCpu, TableTelemetry
from rl_scheduler_tpu.scheduler.tracelog import iter_trace
from rl_scheduler_tpu.utils.retry import CircuitBreaker, RetryPolicy

pytestmark = pytest.mark.skipif(
    not hasattr(os, "fork"), reason="graftserve pools require fork"
)

FAST_RESTARTS = RetryPolicy(max_attempts=5, base_delay_s=0.05,
                            max_delay_s=0.2, jitter=0.0)


def _greedy_factory(worker_id, shared):
    """The cheapest real policy: no checkpoint, no jax — safe to build
    inside a forked test worker."""
    telemetry = TableTelemetry.from_table(
        cpu_source=RandomCpu(seed=0), counter=shared.table_counter
    )
    return ExtenderPolicy(GreedyBackend(), telemetry)


def _post(port, path, payload, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


def _get(port, path, timeout=10):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=timeout) as resp:
        body = resp.read()
    if resp.headers.get("Content-Type", "").startswith("application/json"):
        return json.loads(body)
    return body.decode()


def _filter_args(i=0):
    return {"nodenames": [f"aws-w{i}", f"azure-w{i}"], "pod": {}}


def _make_pool(workers, **kwargs):
    kwargs.setdefault("restart_policy", FAST_RESTARTS)
    kwargs.setdefault("stable_after_s", 60.0)
    kwargs.setdefault("poll_interval_s", 0.05)
    pool = ServingPool(_greedy_factory, workers=workers, host="127.0.0.1",
                       port=0, control_port=0, **kwargs)
    pool.start(ready_timeout_s=60.0)
    return pool


# ------------------------------------------------------------ pure helpers


def test_quantiles_from_histogram_bucket_semantics():
    """histogram_quantile-style estimates: monotone, inside the winning
    bucket's bounds, +Inf reports the highest finite bound, empty is
    count 0."""
    stats = LatencyStats()
    for _ in range(100):
        stats.record(0.0003)  # lands in the (0.25 ms, 0.5 ms] bucket
    cumulative, _, _ = stats.histogram()
    q = quantiles_from_histogram(cumulative)
    assert q["count"] == 100
    for key in ("p50_ms", "p90_ms", "p99_ms"):
        assert 0.25 <= q[key] <= 0.5

    stats = LatencyStats()
    for v in (0.0002,) * 50 + (0.002,) * 40 + (5.0,) * 10:
        stats.record(v)
    cumulative, _, _ = stats.histogram()
    q = quantiles_from_histogram(cumulative)
    assert q["p50_ms"] <= q["p90_ms"] <= q["p99_ms"]
    # 5 s sits beyond the last finite bound (1 s): the histogram carries
    # no information above it, so p99 caps there — exactly
    # histogram_quantile's behavior.
    assert q["p99_ms"] == pytest.approx(1000.0)

    assert quantiles_from_histogram([0] * (len(LatencyStats.BUCKETS) + 1)) \
        == {"count": 0}


def test_breaker_merge_snapshots_max_state_summed_counters():
    """'A dependency is down ANYWHERE' is one gauge: merged state is the
    max by STATE_CODES; lifetime counters sum; the dict keeps
    snapshot()'s exact shape."""
    healthy = CircuitBreaker(name="backend", failure_threshold=2)
    healthy.record_success()
    tripped = CircuitBreaker(name="backend", failure_threshold=2)
    tripped.record_failure()
    tripped.record_failure()  # trips open
    assert tripped.state == CircuitBreaker.OPEN

    merged = CircuitBreaker.merge_snapshots(
        [healthy.snapshot(), tripped.snapshot()]
    )
    assert merged["state"] == CircuitBreaker.OPEN
    assert merged["failures_total"] == 2
    assert merged["opens_total"] == 1
    assert set(merged) == set(healthy.snapshot())

    # half_open outranks closed but not open
    assert CircuitBreaker.merge_snapshots(
        [{"state": "closed", "consecutive_failures": 0, "failures_total": 0,
          "refusals_total": 0, "opens_total": 0},
         {"state": "half_open", "consecutive_failures": 1,
          "failures_total": 3, "refusals_total": 2, "opens_total": 1}]
    )["state"] == "half_open"

    assert CircuitBreaker.merge_snapshots([])["state"] == "closed"


def _synthetic_snapshot(worker_id, decisions, latencies_s, shed=None,
                        breakers=None):
    stats = LatencyStats()
    for v in latencies_s:
        stats.record(v)
    cumulative, total_sum, count = stats.histogram()
    body = {
        "backend": "cpu", "family": "set", "decisions": decisions,
        "choice_fractions": {}, "latency": stats.percentiles_ms(),
        "breakers": breakers or {},
    }
    if shed is not None:
        body["shed_fraction"] = shed
    return {
        "schema": 1, "worker_id": worker_id, "pid": 1000 + worker_id,
        "stats": body,
        "histogram": {"cumulative": cumulative, "sum": total_sum,
                      "count": count},
    }, stats


def test_aggregate_stats_merges_three_workers():
    """Pool /stats over a 3-worker pool: decision counts sum, the latency
    histogram equals ``LatencyStats.merged_histogram`` of the per-worker
    records, shed fractions are request-weighted, and one worker's open
    breaker dominates the pool view."""
    open_breaker = {"state": "open", "consecutive_failures": 0,
                    "failures_total": 5, "refusals_total": 7,
                    "opens_total": 1}
    closed_breaker = {"state": "closed", "consecutive_failures": 1,
                      "failures_total": 1, "refusals_total": 0,
                      "opens_total": 0}
    snap_a, stats_a = _synthetic_snapshot(
        0, {"aws": 8, "azure": 2}, [0.0002] * 10, shed=0.5,
        breakers={"backend": closed_breaker})
    snap_b, stats_b = _synthetic_snapshot(
        1, {"aws": 5, "azure": 25}, [0.002] * 30, shed=0.0,
        breakers={"backend": open_breaker})
    snap_c, stats_c = _synthetic_snapshot(
        2, {"aws": 0, "azure": 0}, [], breakers={"backend": closed_breaker})

    out = aggregate_stats([snap_a, snap_b, snap_c],
                          {"workers": 3, "alive": 3, "restarts_total": 0})
    assert out["decisions"] == {"aws": 13, "azure": 27}
    assert out["choice_fractions"]["aws"] == pytest.approx(13 / 40)

    # merged histogram == union of the per-worker records (ground truth
    # from the same per-worker scrapes, merged by the pinned method)
    ref_cum, ref_sum, ref_count = LatencyStats.merged_histogram(
        [stats_a, stats_b, stats_c])
    assert out["latency"]["count"] == ref_count == 40
    assert out["latency"]["source"] == "merged_histogram"
    assert out["latency"]["sum_seconds"] == pytest.approx(ref_sum)

    # request-weighted shed: (0.5*10 + 0.0*30) / 40
    assert out["shed_fraction"] == pytest.approx(0.125)

    # breaker max-merge: open anywhere -> open pool-wide, counters summed
    assert out["breakers"]["backend"]["state"] == "open"
    assert out["breakers"]["backend"]["failures_total"] == 7
    assert out["breakers"]["backend"]["refusals_total"] == 7

    assert [w["worker_id"] for w in out["workers"]] == [0, 1, 2]
    assert out["backend"] == "cpu" and out["family"] == "set"


def test_aggregate_metrics_exposition():
    """Pool /metrics: ONE histogram whose buckets are the bucket-wise
    sums of the per-worker cumulative counts, summed decision counters,
    max-merged breaker gauge, and per-worker liveness/decision labels."""
    snap_a, stats_a = _synthetic_snapshot(0, {"aws": 3}, [0.0002] * 3)
    snap_b, stats_b = _synthetic_snapshot(1, {"azure": 4}, [0.02] * 4)
    pool = {"workers": 3, "alive": 2, "restarts_total": 1}
    text = aggregate_metrics([snap_a, snap_b], pool)

    ref_cum, ref_sum, ref_count = LatencyStats.merged_histogram(
        [stats_a, stats_b])
    got_buckets = [
        int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
        if line.startswith("rl_scheduler_extender_decision_latency_seconds_bucket")
    ]
    assert got_buckets == ref_cum
    assert f"rl_scheduler_extender_decision_latency_seconds_count {ref_count}" in text
    assert 'rl_scheduler_extender_decisions_total{cloud="aws"} 3' in text
    assert 'rl_scheduler_extender_decisions_total{cloud="azure"} 4' in text
    assert "rl_scheduler_extender_pool_workers 3" in text
    assert "rl_scheduler_extender_pool_workers_alive 2" in text
    assert "rl_scheduler_extender_pool_restarts_total 1" in text
    # worker 2 never answered the scrape: visible, not silently absent
    assert 'rl_scheduler_extender_pool_worker_up{worker="0"} 1' in text
    assert 'rl_scheduler_extender_pool_worker_up{worker="2"} 0' in text
    assert 'rl_scheduler_extender_pool_worker_decisions_total{worker="1"} 4' in text


def test_merge_worker_histograms_is_the_pinned_method():
    """merge_worker_histograms — the ONE place /stats and /metrics
    derive the pool histogram from — is exactly
    LatencyStats.merged_histogram over the snapshot dicts."""
    snap_a, stats_a = _synthetic_snapshot(0, {"aws": 3}, [0.0002] * 3)
    snap_b, stats_b = _synthetic_snapshot(1, {"azure": 2}, [0.02] * 2)
    assert merge_worker_histograms([snap_a, snap_b]) == \
        LatencyStats.merged_histogram([stats_a, stats_b])


def test_aggregate_stats_raw_section_carries_the_merged_buckets():
    """The ``raw`` section on the /stats body IS the merged bucket
    state — ``merge_worker_histograms`` and ``merge_phase_histograms``
    verbatim, ints throughout — so a fleet controller can re-merge
    pool scrapes with the same machinery the pool applies to workers
    (graftfleet's pool_stats_snapshot reads exactly these keys)."""
    from rl_scheduler_tpu.scheduler.extender import PHASES
    from rl_scheduler_tpu.scheduler.pool import merge_phase_histograms

    shared = PoolShared()
    snapshots = []
    for worker_id, n in enumerate((3, 5)):
        policy = _greedy_factory(worker_id, shared)
        for i in range(n):
            policy.filter(_filter_args(i))
        snapshots.append(worker_snapshot(policy, worker_id))
    out = aggregate_stats(snapshots, {"workers": 2, "alive": 2})
    ref_cum, ref_sum, ref_count = merge_worker_histograms(snapshots)
    raw = out["raw"]
    assert raw["histogram"]["cumulative"] == [int(c) for c in ref_cum]
    assert raw["histogram"]["sum"] == ref_sum
    assert raw["histogram"]["count"] == ref_count == 8
    assert all(isinstance(c, int) for c in raw["histogram"]["cumulative"])
    ref_phases = merge_phase_histograms(snapshots)
    assert set(raw["phases"]) == set(ref_phases) == set(PHASES)
    for phase, (cum, p_sum, p_count) in ref_phases.items():
        assert raw["phases"][phase]["cumulative"] == [int(c) for c in cum]
        assert raw["phases"][phase]["sum"] == p_sum
        assert raw["phases"][phase]["count"] == int(p_count)


def test_worker_snapshot_round_trips_histogram():
    """The control-plane snapshot carries exactly the worker's lifetime
    histogram, and _HistogramView feeds it back to merged_histogram
    unchanged — the pool aggregation literally reuses the pinned
    method."""
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))
    policy = ExtenderPolicy(GreedyBackend(), telemetry)
    for i in range(7):
        policy.filter(_filter_args(i))
    snap = worker_snapshot(policy, worker_id=4)
    assert snap["worker_id"] == 4 and snap["pid"] == os.getpid()
    assert _HistogramView(snap["histogram"]).histogram() == \
        policy.stats.histogram()
    merged = LatencyStats.merged_histogram(
        [_HistogramView(snap["histogram"]), policy.stats])
    assert merged[2] == 2 * snap["histogram"]["count"]


# ----------------------------------------------------------- shared state


def test_shared_counter_is_cross_process_atomic():
    """Every index is handed out exactly once across processes."""
    import multiprocessing

    ctx = multiprocessing.get_context("fork")
    counter = SharedCounter(ctx)
    queue = ctx.Queue()

    def worker():
        queue.put([counter.next_index() for _ in range(200)])

    procs = [ctx.Process(target=worker) for _ in range(3)]
    for p in procs:
        p.start()
    seen = [i for _ in procs for i in queue.get(timeout=30)]
    for p in procs:
        p.join(timeout=30)
    assert sorted(seen) == list(range(600))
    assert counter.value == 600


def _constant_cpu():
    return RandomCpu(low=0.4, high=0.4, seed=0)  # uniform(0.4, 0.4) == 0.4


def test_pool_price_counter_score_parity_graph_family():
    """Satellite: all workers of one pool walk the SAME price trajectory
    under ``--price-replay counter``. Two policies sharing the pool's
    counter, serving an identical request stream interleaved, produce
    exactly the score sequence one single-process policy produces —
    request k scores identically no matter which worker serves it."""
    import jax
    import jax.numpy as jnp

    from rl_scheduler_tpu.env.cluster_graph import build_topology
    from rl_scheduler_tpu.models import GNNPolicy
    from rl_scheduler_tpu.scheduler.graph_backend import NumpyGNNBackend

    _, adj, _ = build_topology(8)
    net = GNNPolicy.from_adjacency(adj, dim=64, depth=3)
    tree = net.init(jax.random.PRNGKey(4), jnp.zeros((8, 7), jnp.float32))

    shared = PoolShared()
    clouds = ["aws", "aws", "azure", "azure"]
    display = ["aws-a", "aws-b", "azure-a", "azure-b"]

    def graph_policy(counter):
        return ExtenderPolicy(
            NumpyGNNBackend(tree),
            TableTelemetry.from_table(cpu_source=_constant_cpu()),
            price_replay="counter", price_counter=counter,
        )

    worker_a, worker_b = (graph_policy(shared.price_counter)
                          for _ in range(2))
    reference = graph_policy(None)  # process-local counter, same stream

    pool_probs = [
        (worker_a if k % 2 == 0 else worker_b)
        .decide_graph(clouds, display, None, 0.25)[1]
        for k in range(12)
    ]
    ref_probs = [reference.decide_graph(clouds, display, None, 0.25)[1]
                 for _ in range(12)]
    for pooled, ref in zip(pool_probs, ref_probs):
        np.testing.assert_array_equal(pooled, ref)
    # The trajectory genuinely advanced — the pool consumed one shared
    # position per request, and the price rows moved the distribution
    # (otherwise the parity above would be vacuous).
    assert shared.price_counter.value == 12
    assert any(not np.array_equal(ref_probs[0], p) for p in ref_probs[1:])


def test_pool_table_counter_score_parity_set_family():
    """The normalized-table replay has the same pool seam: set-family
    workers sharing the table counter reproduce the single-process
    score sequence for an identical request stream."""
    import jax
    import jax.numpy as jnp

    from rl_scheduler_tpu.models.transformer import SetTransformerPolicy
    from rl_scheduler_tpu.scheduler.set_backend import NumpySetBackend

    net = SetTransformerPolicy(dim=64, depth=2)
    tree = net.init(jax.random.PRNGKey(3), jnp.zeros((8, 6), jnp.float32))

    shared = PoolShared()
    clouds = ["aws", "aws", "azure"]

    def set_policy(counter):
        return ExtenderPolicy(
            NumpySetBackend(tree),
            TableTelemetry.from_table(cpu_source=_constant_cpu(),
                                      counter=counter),
        )

    worker_a = set_policy(shared.table_counter)
    worker_b = set_policy(shared.table_counter)
    reference = set_policy(None)

    pool_probs = [
        (worker_a if k % 2 == 0 else worker_b).decide_set(clouds, 0.25)[1]
        for k in range(12)
    ]
    ref_probs = [reference.decide_set(clouds, 0.25)[1] for _ in range(12)]
    for pooled, ref in zip(pool_probs, ref_probs):
        np.testing.assert_array_equal(pooled, ref)
    assert shared.table_counter.value == 12
    assert any(not np.array_equal(ref_probs[0], p) for p in ref_probs[1:])


def test_raw_price_replay_refuses_counter_with_wallclock():
    from rl_scheduler_tpu.scheduler.graph_backend import RawPriceReplay

    with pytest.raises(ValueError, match="counter"):
        RawPriceReplay(np.ones((4, 2), np.float32), mode="wallclock",
                       counter=SharedCounter())


# ------------------------------------------------------------- end to end


def test_pool_end_to_end_aggregation_reset_and_health():
    """A real 3-worker pool: traffic through the shared data port, then
    the supervisor's aggregated endpoints against per-worker-scrape
    ground truth, /stats/reset fan-out (rings clear everywhere, lifetime
    histograms don't), and /healthz live-worker reporting."""
    pool = _make_pool(workers=3)
    try:
        cport = pool.control_address[1]
        n_requests = 45
        for i in range(n_requests):
            result = _post(pool.port, "/filter", _filter_args(i))
            assert len(result["nodenames"]) == 1

        health = _get(cport, "/healthz")
        assert health["status"] == "ok"
        assert health["workers"] == 3 and health["alive"] == 3

        # a pool worker's own /healthz names its pool membership
        worker_health = _get(pool.port, "/healthz")
        assert worker_health["workers"] == 3
        assert worker_health["worker_id"] in (0, 1, 2)

        # ground truth: per-worker scrapes, merged by the pinned method
        snapshots = pool.scrape()
        assert len(snapshots) == 3
        ref_cum, ref_sum, ref_count = LatencyStats.merged_histogram(
            [_HistogramView(s["histogram"]) for s in snapshots])
        assert ref_count == n_requests

        stats = _get(cport, "/stats")
        assert sum(stats["decisions"].values()) == n_requests
        assert stats["latency"]["count"] == n_requests
        assert stats["latency"]["source"] == "merged_histogram"
        assert stats["backend"] == "greedy" and stats["family"] == "cloud"
        assert sum(w["decisions_total"] for w in stats["workers"]) \
            == n_requests
        assert "backend" in stats["breakers"]

        metrics = _get(cport, "/metrics")
        got_buckets = [
            int(line.rsplit(" ", 1)[1]) for line in metrics.splitlines()
            if line.startswith(
                "rl_scheduler_extender_decision_latency_seconds_bucket")
        ]
        assert got_buckets == ref_cum
        assert (f"rl_scheduler_extender_decision_latency_seconds_count "
                f"{n_requests}") in metrics
        assert 'rl_scheduler_extender_circuit_state{breaker="backend"} 0' \
            in metrics
        for worker_id in range(3):
            assert (f'rl_scheduler_extender_pool_worker_up{{worker='
                    f'"{worker_id}"}} 1') in metrics

        # reset fans out: every worker's percentile ring clears, the
        # lifetime histogram stays (Prometheus monotonicity)
        reset = _post(cport, "/stats/reset", {})
        assert reset == {"status": "reset", "workers": 3}
        for snap in pool.scrape():
            assert snap["stats"]["latency"]["count"] == 0
        stats_after = _get(cport, "/stats")
        assert stats_after["latency"]["count"] == n_requests  # lifetime
        assert sum(stats_after["decisions"].values()) == n_requests

        # a junk hello on the control listener (out-of-range worker_id,
        # then raw garbage) must not kill the accept thread — the pool
        # keeps scraping all workers afterwards
        from rl_scheduler_tpu.scheduler.pool import _control_connect

        for payload in (b'{"worker_id": 99}\n', b'not json\n'):
            rogue = _control_connect(pool._control_spec)
            rogue.sendall(payload)
            rogue.close()
        time.sleep(0.2)
        assert len(pool.scrape()) == 3
    finally:
        pool.shutdown()


def _slo_factory(worker_id, shared):
    """Greedy policy with graftlens armed: spans (the default) plus an
    SLO tracker with unburnable thresholds — the aggregation test wants
    counters, not a degrade."""
    from rl_scheduler_tpu.scheduler.slo import SloConfig, SloTracker

    policy = _greedy_factory(worker_id, shared)
    policy.slo = SloTracker(SloConfig(p99_ms=1000.0, availability=0.999))
    return policy


def test_merge_phase_histograms_and_slo_from_real_snapshots():
    """Pure-function pin, mirroring the LatencyStats.merged_histogram
    one: per-phase pool histograms == bucket-wise union of per-worker
    snapshots, and merge_worker_slo sums window counts."""
    from rl_scheduler_tpu.scheduler.extender import PHASES
    from rl_scheduler_tpu.scheduler.pool import (
        merge_phase_histograms,
        merge_worker_slo,
    )
    from rl_scheduler_tpu.scheduler.slo import SloConfig, SloTracker

    shared = PoolShared()
    snapshots = []
    per_worker = (3, 5, 7)
    for worker_id, n in enumerate(per_worker):
        policy = _greedy_factory(worker_id, shared)
        policy.slo = SloTracker(SloConfig(p99_ms=1000.0))
        for i in range(n):
            policy.filter(_filter_args(i))
        snapshots.append(worker_snapshot(policy, worker_id))
    merged = merge_phase_histograms(snapshots)
    assert set(merged) == set(PHASES)
    for phase, (cumulative, total_sum, count) in merged.items():
        assert count == sum(per_worker)
        assert cumulative[-1] == sum(per_worker)
        assert total_sum == pytest.approx(sum(
            s["phases"][phase]["sum"] for s in snapshots))
    slo = merge_worker_slo(snapshots)
    assert slo["lifetime"]["requests_total"] == sum(per_worker)
    assert not slo["degraded"]
    # Workers without spans/slo (pre-graftlens snapshots) merge cleanly.
    bare = dict(snapshots[0])
    bare["phases"] = None
    bare["slo"] = None
    assert merge_phase_histograms([bare]) == {}
    assert merge_worker_slo([bare]) is None


def test_pool_phase_aggregation_reset_and_slo_e2e():
    """The satellite pin, pool-wide: merged /metrics phase histograms ==
    union of per-worker scrapes, /stats/reset never rewinds the phase
    lifetime counters, phase sums reconcile with the end-to-end decide
    latency, and the merged SLO section rides /stats."""
    from rl_scheduler_tpu.scheduler.extender import PHASES
    from rl_scheduler_tpu.scheduler.pool import merge_phase_histograms

    pool = ServingPool(_slo_factory, workers=2, host="127.0.0.1",
                       port=0, control_port=0,
                       restart_policy=FAST_RESTARTS,
                       stable_after_s=60.0, poll_interval_s=0.05,
                       slo_enabled=True)
    pool.start(ready_timeout_s=60.0)
    try:
        cport = pool.control_address[1]
        n_requests = 30
        for i in range(n_requests):
            _post(pool.port, "/filter", _filter_args(i))

        snapshots = pool.scrape()
        ref = merge_phase_histograms(snapshots)
        assert {phase: c for phase, (_, _, c) in ref.items()} == {
            phase: n_requests for phase in PHASES}

        stats = _get(cport, "/stats")
        assert set(stats["phases"]) == set(PHASES)
        for phase in PHASES:
            assert stats["phases"][phase]["lifetime_count"] == n_requests
        # Reconciliation: observe+forward >= 90% of the e2e decide mean.
        e2e = stats["latency"]["lifetime_mean_ms"]
        inner = (stats["phases"]["observe"]["lifetime_mean_ms"]
                 + stats["phases"]["forward"]["lifetime_mean_ms"])
        assert inner >= 0.9 * e2e
        # Merged SLO: counts summed across workers, nothing burning.
        assert stats["slo"]["lifetime"]["requests_total"] == n_requests
        assert not stats["slo"]["degraded"]

        metrics = _get(cport, "/metrics")
        for phase, (cumulative, _, count) in ref.items():
            got = [
                int(line.rsplit(" ", 1)[1])
                for line in metrics.splitlines()
                if line.startswith(
                    f'rl_scheduler_extender_phase_latency_seconds_bucket'
                    f'{{phase="{phase}"')
            ]
            assert got == cumulative, f"phase {phase} bucket drift"
            assert (f'rl_scheduler_extender_phase_latency_seconds_count'
                    f'{{phase="{phase}"}} {count}') in metrics
        assert ('rl_scheduler_extender_slo_requests_total '
                f'{n_requests}') in metrics
        assert "rl_scheduler_extender_slo_degraded 0" in metrics

        # /healthz folds the merged SLO state in (still ok here).
        health = _get(cport, "/healthz")
        assert health["status"] == "ok"
        assert health["slo"] == {"degraded": False, "burning": []}

        # Reset fans out: phase rings clear, lifetime histograms do not.
        _post(cport, "/stats/reset", {})
        stats_after = _get(cport, "/stats")
        for phase in PHASES:
            entry = stats_after["phases"][phase]
            assert entry["lifetime_count"] == n_requests
        assert stats_after["slo"]["lifetime"]["requests_total"] \
            == n_requests
        after = pool.scrape()
        for snap in after:
            for phase in PHASES:
                assert snap["stats"]["phases"][phase]["count"] == 0
        for phase in PHASES:  # per-worker lifetime shares still sum
            assert sum(s["phases"][phase]["count"] for s in after) \
                == n_requests
    finally:
        pool.shutdown()


def test_rollout_slo_canary_gate_judgement():
    """graftlens canary gate unit: a canary burning the latency SLO
    while incumbents keep it fails the hold; a pool-wide slowdown (both
    sides over) passes — not the canary's fault."""
    from rl_scheduler_tpu.scheduler.slo import SloConfig

    def hist_snap(worker_id, latencies_s):
        stats = LatencyStats()
        for v in latencies_s:
            stats.record(v)
        cumulative, total_sum, count = stats.histogram()
        return {"worker_id": worker_id,
                "histogram": {"cumulative": cumulative, "sum": total_sum,
                              "count": count}}

    controller = RolloutController.__new__(RolloutController)
    controller.slo = SloConfig(p99_ms=100.0, fast_burn=14.4)
    controller.min_compare_requests = 20
    empty = hist_snap(0, [])
    # Canary: 50% of 40 requests over 100 ms (budget x fast-burn allows
    # 14.4%); incumbents: all fast -> gate failure.
    canary_end = hist_snap(0, [0.2] * 20 + [0.001] * 20)
    inc_start, inc_end = [hist_snap(1, [])], [hist_snap(1, [0.001] * 40)]
    ok, why = controller._slo_gate(empty, canary_end, inc_start, inc_end)
    assert not ok and "burns the SLO" in why
    # Pool-wide slowdown: incumbents over the limit too -> pass.
    slow_inc_end = [hist_snap(1, [0.2] * 40)]
    ok, _ = controller._slo_gate(empty, canary_end, inc_start,
                                 slow_inc_end)
    assert ok
    # Too few requests to judge -> pass (the latency-ratio gate and
    # breaker/fail-open deltas still stand guard).
    tiny_end = hist_snap(0, [0.2] * 5)
    ok, _ = controller._slo_gate(empty, tiny_end, inc_start, inc_end)
    assert ok


def test_pool_restarts_dead_worker():
    """The supervisor notices a SIGKILLed worker, restarts it on the
    RetryPolicy backoff, and the control plane heals: /healthz reports
    full strength again and the new worker answers scrapes."""
    pool = _make_pool(workers=2)
    try:
        cport = pool.control_address[1]
        pids = {s["pid"] for s in pool.scrape()}
        assert len(pids) == 2
        victim = sorted(pids)[0]
        os.kill(victim, signal.SIGKILL)

        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            try:
                health = _get(cport, "/healthz")
            except urllib.error.HTTPError:
                health = None  # 503: degraded while the worker is down
            if health is not None and health["alive"] == 2 \
                    and health["restarts_total"] >= 1 \
                    and len(pool.scrape()) == 2:
                break
            time.sleep(0.1)
        else:
            pytest.fail(f"pool did not heal: {pool.status()}")

        new_pids = {s["pid"] for s in pool.scrape()}
        assert victim not in new_pids and len(new_pids) == 2

        # the healed pool still serves (retry a few times: connections
        # hashed to the dying socket during the window may be refused)
        for attempt in range(20):
            try:
                result = _post(pool.port, "/filter", _filter_args(attempt))
                break
            except OSError:
                time.sleep(0.1)
        assert len(result["nodenames"]) == 1
    finally:
        pool.shutdown()


def test_pool_inherit_fallback_mode():
    """Where SO_REUSEPORT is unavailable the pool binds once and forks:
    workers accept() on the inherited listener — same endpoints, same
    aggregation, no kernel balancing required."""
    pool = _make_pool(workers=2, mode="inherit")
    try:
        assert pool.status()["mode"] == "inherit"
        for i in range(10):
            result = _post(pool.port, "/filter", _filter_args(i))
            assert len(result["nodenames"]) == 1
        stats = _get(pool.control_address[1], "/stats")
        assert sum(stats["decisions"].values()) == 10
        assert stats["latency"]["count"] == 10
    finally:
        pool.shutdown()


def _free_port():
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


def test_pool_cli_workers_flag_and_sigterm():
    """``--workers 2`` through the real CLI: the supervisor forks the
    pool, both planes answer, and SIGTERM shuts the whole tree down
    cleanly (exit 0, port released)."""
    import multiprocessing

    from rl_scheduler_tpu.scheduler import extender as ext

    ctx = multiprocessing.get_context("fork")
    port, cport = _free_port(), _free_port()
    proc = ctx.Process(target=ext.main, args=(
        ["--workers", "2", "--backend", "greedy", "--host", "127.0.0.1",
         "--port", str(port), "--control-port", str(cport)],))
    proc.start()
    try:
        deadline = time.monotonic() + 60.0
        health = None
        while time.monotonic() < deadline:
            try:
                health = _get(cport, "/healthz", timeout=2)
                if health["alive"] == 2:
                    break
            except OSError:
                pass
            time.sleep(0.1)
        assert health is not None and health["alive"] == 2, health
        result = _post(port, "/filter", _filter_args())
        assert len(result["nodenames"]) == 1
        assert _get(port, "/healthz")["workers"] == 2

        os.kill(proc.pid, signal.SIGTERM)
        proc.join(timeout=30)
        assert proc.exitcode == 0
    finally:
        if proc.is_alive():
            proc.kill()
            proc.join(timeout=10)


def test_pool_cli_flag_validation():
    from rl_scheduler_tpu.scheduler import extender as ext

    with pytest.raises(SystemExit, match="at least 1"):
        ext.main(["--workers", "0"])
    with pytest.raises(SystemExit, match="pool mode"):
        ext.main(["--control-port", "9999"])
    with pytest.raises(SystemExit, match="pool mode"):
        ext.main(["--blas-threads", "1"])
    with pytest.raises(SystemExit, match="pool mode"):
        ext.main(["--control-host", "0.0.0.0"])
    with pytest.raises(SystemExit, match="positive"):
        ext.main(["--workers", "2", "--blas-threads", "-1"])
    with pytest.raises(ValueError, match="blas_threads"):
        ServingPool(_greedy_factory, workers=2, blas_threads=-1)
    # the heuristic splits cores across workers, never below 1
    pool = ServingPool(_greedy_factory, workers=64)
    assert pool.blas_threads == 1


def test_make_server_reuse_port_two_listeners():
    """Two make_server(reuse_port=True) servers share one port — the
    primitive each pool worker uses to join the kernel's balancing
    group."""
    if not hasattr(socket, "SO_REUSEPORT"):
        pytest.skip("no SO_REUSEPORT on this platform")
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))
    policy_a = ExtenderPolicy(GreedyBackend(), telemetry)
    policy_b = ExtenderPolicy(GreedyBackend(), telemetry)
    srv_a = make_server(policy_a, "127.0.0.1", 0, reuse_port=True)
    port = srv_a.server_address[1]
    srv_b = make_server(policy_b, "127.0.0.1", port, reuse_port=True)
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in (srv_a, srv_b)]
    for t in threads:
        t.start()
    try:
        for i in range(12):
            assert len(_post(port, "/filter", _filter_args(i))["nodenames"]) == 1
        total = policy_a.stats.histogram()[2] + policy_b.stats.histogram()[2]
        assert total == 12
    finally:
        srv_a.shutdown()
        srv_b.shutdown()


# -------------------------------------------------- graftroll: rollout


def _make_verified_checkpoint(root, name="ckpt-good"):
    """A minimal run dir that passes graftroll's manifest verification:
    one step, one file, a graftguard-shaped sha256+size manifest —
    exactly what `verify_candidate` trusts, no orbax involved."""
    run = Path(root) / name
    step = run / "checkpoints" / "1"
    step.mkdir(parents=True)
    payload = (name.encode() + b"-weights") * 64
    (step / "state.bin").write_bytes(payload)
    mdir = run / "checkpoint_manifests"
    mdir.mkdir()
    (mdir / "1.json").write_text(json.dumps({
        "step": 1,
        "files": {"state.bin": {
            "sha256": hashlib.sha256(payload).hexdigest(),
            "size": len(payload),
        }},
    }))
    return run


class _PoisonedBackend:
    """Stands in for a verifies-clean-but-regressing checkpoint: every
    decision raises, so the canary's warm-up probes fail open and the
    gate must roll back."""

    name = "poisoned"

    def decide(self, obs):
        raise RuntimeError("regressing checkpoint")


def _rollout_factory(trace_dir=None):
    """Spec-aware greedy factory: a promoted spec whose checkpoint name
    contains 'regress' builds a poisoned backend (the forced-bad promote
    of the drill); any other spec serves greedy. Optionally attaches a
    per-worker trace stream."""

    def factory(worker_id, shared, spec):
        telemetry = TableTelemetry.from_table(
            cpu_source=RandomCpu(seed=0), counter=shared.table_counter
        )
        backend = (_PoisonedBackend()
                   if spec.checkpoint and "regress" in Path(spec.checkpoint).name
                   else GreedyBackend())
        policy = ExtenderPolicy(backend, telemetry)
        if trace_dir is not None:
            from rl_scheduler_tpu.scheduler.tracelog import TraceLog

            policy.trace = TraceLog(trace_dir, prefix=f"w{worker_id}-")
        return policy

    return factory


def _make_rollout_pool(workers=2, trace_dir=None, fault_plan=None,
                       restart_policy=None, front="threading",
                       **rollout_opts):
    opts = {"canary_hold_s": 0.2, "probe_count": 2, "ready_timeout_s": 60.0}
    opts.update(rollout_opts)
    pool = ServingPool(
        _rollout_factory(trace_dir), workers=workers, host="127.0.0.1",
        port=0, control_port=0,
        restart_policy=restart_policy or FAST_RESTARTS,
        stable_after_s=60.0, poll_interval_s=0.05,
        fault_plan=fault_plan, rollout_opts=opts, front=front,
    )
    pool.start(ready_timeout_s=60.0)
    return pool


def _post_code(port, path, payload, timeout=10):
    """Like _post but 4xx/5xx return ``(code, body)`` instead of
    raising — promote refusals are answers, not errors."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _wait_rollout_idle(cport, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status = _get(cport, "/rollout")
        if not status["active"]:
            return status
        time.sleep(0.05)
    pytest.fail(f"rollout still in flight after {timeout}s: {status}")


def test_verify_candidate_manifest_semantics(tmp_path):
    """The promote-side verification: digests pass a clean step, refuse
    truncation/corruption/unfinalized saves, and accept a fully legacy
    run with a warning — no fallback to an older step (the operator
    promoted THIS checkpoint)."""
    run = _make_verified_checkpoint(tmp_path, "ckpt")
    step, reason = verify_candidate(run)
    assert (step, reason) == (1, "verified")

    truncated = Path(shutil.copytree(run, tmp_path / "ckpt-trunc"))
    state = truncated / "checkpoints" / "1" / "state.bin"
    state.write_bytes(state.read_bytes()[: state.stat().st_size // 2])
    step, reason = verify_candidate(truncated)
    assert step is None and "truncated" in reason

    garbage = Path(shutil.copytree(run, tmp_path / "ckpt-garbage"))
    state = garbage / "checkpoints" / "1" / "state.bin"
    data = bytearray(state.read_bytes())
    data[:4] = b"\xde\xad\xbe\xef"
    state.write_bytes(bytes(data))
    step, reason = verify_candidate(garbage)
    assert step is None and "sha256" in reason

    # newest step manifest-less in a manifested run = unfinalized: refuse
    unfinalized = Path(shutil.copytree(run, tmp_path / "ckpt-unfin"))
    (unfinalized / "checkpoints" / "2").mkdir()
    (unfinalized / "checkpoints" / "2" / "state.bin").write_bytes(b"x")
    step, reason = verify_candidate(unfinalized)
    assert step is None and "unfinalized" in reason

    # fully legacy run (no manifest dir): accepted, flagged
    legacy = Path(shutil.copytree(run, tmp_path / "ckpt-legacy"))
    shutil.rmtree(legacy / "checkpoint_manifests")
    assert verify_candidate(legacy) == (1, "legacy")

    assert verify_candidate(tmp_path / "nope")[0] is None


@pytest.mark.parametrize("front", ["threading", "asyncio"])
def test_rollout_drill(tmp_path, front):
    """`make rollout-drill`: (a) a good promote lands generation 1 on
    every worker with serving uninterrupted; (b) a corrupted copy is
    refused before any worker is touched; (c) a verifies-clean-but-
    regressing promote fails the canary's warm-up probes and rolls the
    pool back to the incumbent generation; the trace log replays every
    decision and /stats/reset never rewinds the lifetime counters.
    Parameterized over BOTH data-plane fronts (graftfront): promote,
    canary and rollback must behave identically on asyncio workers."""
    good = _make_verified_checkpoint(tmp_path, "ckpt-good")
    corrupt = Path(shutil.copytree(good, tmp_path / "ckpt-corrupt"))
    state = corrupt / "checkpoints" / "1" / "state.bin"
    state.write_bytes(state.read_bytes() + b"JUNK")
    regress = _make_verified_checkpoint(tmp_path, "ckpt-regress")
    trace_dir = tmp_path / "trace"
    pool = _make_rollout_pool(trace_dir=str(trace_dir), front=front)
    requests = 0
    try:
        cport = pool.control_address[1]
        for i in range(10):
            assert len(_post(pool.port, "/filter",
                             _filter_args(i))["nodenames"]) == 1
            requests += 1

        # (a) good promote: canary + roll, all workers on generation 1
        code, body = _post_code(cport, "/promote",
                                {"checkpoint": str(good)})
        assert code == 202 and body["target_generation"] == 1
        assert body["verification"] == "verified"
        status = _wait_rollout_idle(cport)
        assert status["generation"] == 1
        assert status["promotions_total"] == 1
        assert status["rollbacks_total"] == 0
        assert status["checkpoint"] == str(good)
        snapshots = pool.scrape()
        assert len(snapshots) == 2
        assert all(s["generation"] == 1 for s in snapshots)
        assert len(_post(pool.port, "/filter",
                         _filter_args(100))["nodenames"]) == 1
        requests += 1

        # (b) corrupt promote: refused at verification, nothing rolled
        code, body = _post_code(cport, "/promote",
                                {"checkpoint": str(corrupt)})
        assert code == 422 and "refused" in body["error"]
        status = _get(cport, "/rollout")
        assert status["generation"] == 1 and not status["active"]
        assert status["refusals_total"] == 1
        assert all(s["generation"] == 1 for s in pool.scrape())

        # (c) regressing promote: verifies clean, canary probes fail
        # open, automatic rollback restores the incumbent generation
        code, body = _post_code(cport, "/promote",
                                {"checkpoint": str(regress)})
        assert code == 202 and body["verification"] == "verified"
        status = _wait_rollout_idle(cport)
        assert status["generation"] == 1
        assert status["rollbacks_total"] == 1
        assert "fail" in status["last_error"]
        assert all(s["generation"] == 1 for s in pool.scrape())
        assert len(_post(pool.port, "/filter",
                         _filter_args(101))["nodenames"]) == 1
        requests += 1

        # the gauge transitions the drill doc promises, on one scrape
        metrics = _get(cport, "/metrics")
        assert "rl_scheduler_extender_pool_generation 1" in metrics
        assert "rl_scheduler_extender_pool_promotions_total 1" in metrics
        assert "rl_scheduler_extender_pool_rollbacks_total 1" in metrics
        assert "rl_scheduler_extender_pool_promote_refusals_total 1" in metrics
        assert "rl_scheduler_extender_pool_rollout_state 0" in metrics
        assert 'rl_scheduler_extender_pool_worker_generation{worker="0"} 1' \
            in metrics
        assert "rl_scheduler_extender_trace_records_total" in metrics
        assert "rl_scheduler_extender_trace_dropped_total 0" in metrics
        assert "rl_scheduler_extender_trace_segments_total" in metrics

        # satellite small fix: /stats/reset clears rings ONLY — the
        # promotion/rollback and trace counters stay monotonic
        trace_before = _get(cport, "/stats")["trace"]
        _post(cport, "/stats/reset", {})
        stats = _get(cport, "/stats")
        assert stats["trace"]["records_total"] \
            == trace_before["records_total"]
        metrics = _get(cport, "/metrics")
        assert "rl_scheduler_extender_pool_promotions_total 1" in metrics
        assert "rl_scheduler_extender_pool_rollbacks_total 1" in metrics

        probes = _get(cport, "/rollout")["probes_total"]
    finally:
        pool.shutdown()

    # the durable trace replays every decision made during the drill:
    # our client requests plus the gates' warm-up probes, across BOTH
    # generations and every worker incarnation
    records = list(iter_trace(trace_dir))
    assert len(records) == requests + probes
    # generations 0 (pre-promote) and 1 (promoted) served traffic; the
    # rolled-back attempt at generation 2 left only its fail-open probe
    # record — the trace faithfully records the attempt
    assert {r["generation"] for r in records} == {0, 1, 2}
    failed = [r for r in records if r["fail_open"]]
    assert failed and all(r["generation"] == 2 for r in failed)
    # synthetic gate traffic is TAGGED: a trace consumer can exclude it
    assert sum(1 for r in records if r["endpoint"] == "probe") == probes
    # schema 2 (graftloop): every record carries the replay fields era.
    from rl_scheduler_tpu.scheduler.tracelog import TRACE_SCHEMA

    assert all(r["schema"] == TRACE_SCHEMA for r in records)


def test_healthz_rolling_and_sigkill_mid_rollout_rolls_back(tmp_path):
    """During a rollout the pool reports 200 with `rolling: true` even
    while below strength (a rolling restart must not trip k8s
    liveness); a second promote mid-flight is refused 409; and a canary
    SIGKILLed during its hold triggers automatic rollback onto the
    incumbent generation."""
    good = _make_verified_checkpoint(tmp_path, "ckpt-good")
    slow_restarts = RetryPolicy(max_attempts=5, base_delay_s=2.0,
                                max_delay_s=4.0, jitter=0.0)
    pool = _make_rollout_pool(canary_hold_s=30.0,
                              restart_policy=slow_restarts)
    try:
        cport = pool.control_address[1]
        for i in range(5):
            _post(pool.port, "/filter", _filter_args(i))
        code, _ = _post_code(cport, "/promote", {"checkpoint": str(good)})
        assert code == 202

        # wait for the canary hold (worker 0 on generation 1, held)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            status = _get(cport, "/rollout")
            if status["phase"] == "canary_hold":
                break
            time.sleep(0.02)
        else:
            pytest.fail(f"never reached canary_hold: {status}")

        # single-writer: a second promote during the rollout is refused
        code, body = _post_code(cport, "/promote",
                                {"checkpoint": str(good)})
        assert code == 409 and "in flight" in body["error"]

        # kill an INCUMBENT: the pool is now degraded AND rolling — the
        # health contract is 200 + rolling:true (not 503), and the
        # supervisor's monitor owns the respawn (its backoff is slow
        # here, so the window is deterministic)
        snapshots = pool.scrape()
        by_gen = {s["generation"]: s for s in snapshots}
        assert set(by_gen) == {0, 1}
        os.kill(by_gen[0]["pid"], signal.SIGKILL)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            health = _get(cport, "/healthz")  # must NOT raise 503
            if health["alive"] < health["workers"]:
                break
            time.sleep(0.02)
        else:
            pytest.fail("never observed the degraded window")
        assert health["rolling"] is True
        assert health["status"] == "rolling"

        # SIGKILL the canary mid-hold: the gate sees the death and rolls
        # back; the incumbent generation is restored everywhere
        os.kill(by_gen[1]["pid"], signal.SIGKILL)
        status = _wait_rollout_idle(cport, timeout=60.0)
        assert status["rollbacks_total"] == 1
        assert status["promotions_total"] == 0
        assert status["generation"] == 0
        assert "died" in status["last_error"]
        assert status["conflicts_total"] == 1

        # the pool heals to full strength on generation 0 and serves
        # (once the rollout is idle a still-down incumbent is an honest
        # 503 "degraded" again until its monitor backoff respawns it)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            try:
                health = _get(cport, "/healthz")
            except urllib.error.HTTPError:
                health = None
            if (health is not None and health["status"] == "ok"
                    and health["rolling"] is False):
                break
            time.sleep(0.1)
        else:
            pytest.fail(f"pool never healed: {health}")
        assert all(s["generation"] == 0 for s in pool.scrape())
        for attempt in range(20):
            try:
                result = _post(pool.port, "/filter", _filter_args(attempt))
                break
            except OSError:
                time.sleep(0.1)
        assert len(result["nodenames"]) == 1
    finally:
        pool.shutdown()


def test_legacy_two_arg_factory_still_promotes_generation_label(tmp_path):
    """Backward compatibility: a pre-graftroll (worker_id, shared)
    factory keeps working — a promote still executes the rolling
    restart and bumps the generation label (the factory just serves
    what it always served)."""
    good = _make_verified_checkpoint(tmp_path, "ckpt-good")
    pool = ServingPool(_greedy_factory, workers=2, host="127.0.0.1",
                       port=0, control_port=0,
                       restart_policy=FAST_RESTARTS, stable_after_s=60.0,
                       poll_interval_s=0.05,
                       rollout_opts={"canary_hold_s": 0.1,
                                     "probe_count": 1,
                                     "ready_timeout_s": 60.0})
    pool.start(ready_timeout_s=60.0)
    try:
        cport = pool.control_address[1]
        code, _ = _post_code(cport, "/promote", {"checkpoint": str(good)})
        assert code == 202
        status = _wait_rollout_idle(cport)
        assert status["generation"] == 1
        assert all(s["generation"] == 1 for s in pool.scrape())
        assert len(_post(pool.port, "/filter",
                         _filter_args(0))["nodenames"]) == 1
    finally:
        pool.shutdown()


def test_run_pool_direct_entry_serves_and_traces(tmp_path):
    """run_pool — the CLI's --workers path — wires the spec-aware
    factory and the per-worker trace streams: the pool serves, SIGTERM
    shuts it down cleanly, and --trace-dir holds one record per
    decision tagged with the serving worker."""
    import multiprocessing

    ctx = multiprocessing.get_context("fork")
    port, cport = _free_port(), _free_port()
    trace_dir = tmp_path / "trace"
    proc = ctx.Process(target=run_pool, kwargs=dict(
        build_kwargs={"backend": "greedy", "trace_dir": str(trace_dir)},
        workers=2, host="127.0.0.1", port=port, control_port=cport,
        control_host="127.0.0.1"))
    proc.start()
    try:
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            try:
                if _get(cport, "/healthz", timeout=2)["alive"] == 2:
                    break
            except OSError:
                pass
            time.sleep(0.1)
        else:
            pytest.fail("run_pool never came up")
        for i in range(4):
            assert len(_post(port, "/filter", _filter_args(i))["nodenames"]) == 1
        os.kill(proc.pid, signal.SIGTERM)
        proc.join(timeout=30)
        assert proc.exitcode == 0
    finally:
        if proc.is_alive():
            proc.kill()
            proc.join(timeout=10)
    records = list(iter_trace(trace_dir))
    assert len(records) == 4
    assert {r["worker"] for r in records} <= {0, 1}
    assert all(r["generation"] == 0 for r in records)


def test_rollout_lock_file_o_excl_discipline(tmp_path):
    """The on-disk single-writer lock (graftstudy's runner-lock
    discipline): a live holder refuses the promote, a stale lock from a
    dead pid is cleared and retried."""
    pool = ServingPool(_rollout_factory(), workers=1, host="127.0.0.1",
                       port=0, control_port=0)
    controller = RolloutController(pool, lock_dir=tmp_path)
    lock = controller._acquire_lock_file()
    assert lock is not None and lock.read_text() == str(os.getpid())
    # same-pid holder counts as live: a second acquisition refuses
    with pytest.raises(RuntimeError, match="already in flight"):
        controller._acquire_lock_file()
    controller._release_lock_file(lock)
    # stale lock (dead pid): cleared and re-acquired
    lock.write_text("999999999")
    lock2 = controller._acquire_lock_file()
    assert lock2.read_text() == str(os.getpid())
    controller._release_lock_file(lock2)
    assert WorkerSpec().generation == 0  # frozen default spec


# ------------------------------------------------------------------- soak


def _load_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "extender_bench",
        Path(__file__).resolve().parents[1] / "loadgen" / "extender_bench.py",
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


@pytest.mark.slow
def test_pool_soak_via_bench():
    """``make serve-soak``: the bench's --duration mode against a live
    2-worker pool, pool-wide reset/stats via --control-port, zero
    failures, schema-tagged result line."""
    bench = _load_bench()

    pool = _make_pool(workers=2)
    try:
        out = bench.main([
            "--port", str(pool.port), "--duration", "3", "--threads", "4",
            "--warmup", "5", "--control-port",
            str(pool.control_address[1]),
        ])
    finally:
        pool.shutdown()
    assert out["schema_version"] == 1
    assert out["mode"] == "soak"
    assert out["workers"] == 2
    assert out["concurrency"] == 4
    assert out["failures"] == 0
    assert out["requests"] > 0 and out["req_per_sec"] > 0
    assert out["server_p50_ms"] is not None


@pytest.mark.slow
def test_rollout_drill_soak(tmp_path):
    """The acceptance soak (`make rollout-drill` runs this alongside the
    fast drill): a 2-worker pool serves continuously while (a) a good
    promote lands mid-soak with ZERO failed requests in both phases and
    every worker reporting the new generation, then (b) a regressing
    promote auto-rolls-back mid-soak — also zero failed requests, the
    incumbent generation restored — with the durable trace replaying
    every decision made during both drills."""
    bench = _load_bench()
    good = _make_verified_checkpoint(tmp_path, "ckpt-good")
    regress = _make_verified_checkpoint(tmp_path, "ckpt-regress")
    trace_dir = tmp_path / "trace"
    pool = _make_rollout_pool(trace_dir=str(trace_dir), canary_hold_s=0.5)
    warmup = 5
    try:
        cport = pool.control_address[1]
        common = ["--port", str(pool.port), "--threads", "4",
                  "--warmup", str(warmup), "--control-port", str(cport),
                  "--duration", "6", "--promote-at", "2"]

        # drill (a): good promote under load
        out_good = bench.main(common + ["--promote-checkpoint", str(good)])
        assert out_good["failures"] == 0
        assert out_good["phases"]["pre_promote"]["failures"] == 0
        assert out_good["phases"]["post_promote"]["failures"] == 0
        assert out_good["phases"]["post_promote"]["requests"] > 0
        assert out_good["promote"]["response_code"] == 202
        rollout = out_good["promote"]["rollout"]
        assert rollout["generation"] == 1
        assert rollout["promotions_total"] == 1
        assert rollout["rollbacks_total"] == 0
        snapshots = pool.scrape()
        assert len(snapshots) == 2
        assert all(s["generation"] == 1 for s in snapshots)

        # drill (b): regressing promote rolls back under load
        out_bad = bench.main(common + ["--promote-checkpoint", str(regress)])
        assert out_bad["failures"] == 0
        assert out_bad["phases"]["pre_promote"]["failures"] == 0
        assert out_bad["phases"]["post_promote"]["failures"] == 0
        rollout = out_bad["promote"]["rollout"]
        assert rollout["generation"] == 1       # incumbent restored
        assert rollout["rollbacks_total"] == 1
        assert all(s["generation"] == 1 for s in pool.scrape())

        status = _get(cport, "/rollout")
        probes = status["probes_total"]
        retries = sum(out["phases"][ph]["retries"]
                      for out in (out_good, out_bad)
                      for ph in ("pre_promote", "post_promote"))
        metrics = _get(cport, "/metrics")
        assert "rl_scheduler_extender_pool_rollbacks_total 1" in metrics
        assert "rl_scheduler_extender_trace_segments_total" in metrics
        assert "rl_scheduler_extender_trace_dropped_total 0" in metrics
    finally:
        pool.shutdown()

    # every decision of both drills is in the trace: the bench's
    # successful requests + warmups + the gates' warm-up probes; a
    # connection-level retry MAY have reached a worker before the reset,
    # so retries bound the slack from above
    records = list(iter_trace(trace_dir))
    expected = (out_good["requests"] + out_bad["requests"]
                + 2 * warmup + probes)
    assert expected <= len(records) <= expected + retries
    assert {r["generation"] for r in records} >= {0, 1}
