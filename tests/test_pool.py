"""graftserve (scheduler/pool.py): the multi-worker serving plane.

Aggregation semantics are pinned at two levels: pure-function tests feed
synthetic per-worker snapshots to ``aggregate_stats``/``aggregate_metrics``
(breaker max-merge, request-weighted fractions, merged-histogram
quantiles), and end-to-end tests fork a real pool — SO_REUSEPORT workers
plus the inherit fallback — and check the supervisor's ``/stats``,
``/metrics``, ``/stats/reset`` fan-out, dead-worker restart, and the
shared price-replay/table counters against single-process ground truth.
Multi-process tests keep worker counts small and backoffs short so they
stay inside the tier-1 budget; the bench-driven soak is marked ``slow``
(``make serve-soak``).
"""

import json
import os
import signal
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from rl_scheduler_tpu.scheduler.extender import (
    ExtenderPolicy,
    LatencyStats,
    make_server,
)
from rl_scheduler_tpu.scheduler.policy_backend import GreedyBackend
from rl_scheduler_tpu.scheduler.pool import (
    PoolShared,
    ServingPool,
    SharedCounter,
    _HistogramView,
    aggregate_metrics,
    aggregate_stats,
    quantiles_from_histogram,
    worker_snapshot,
)
from rl_scheduler_tpu.scheduler.telemetry import RandomCpu, TableTelemetry
from rl_scheduler_tpu.utils.retry import CircuitBreaker, RetryPolicy

pytestmark = pytest.mark.skipif(
    not hasattr(os, "fork"), reason="graftserve pools require fork"
)

FAST_RESTARTS = RetryPolicy(max_attempts=5, base_delay_s=0.05,
                            max_delay_s=0.2, jitter=0.0)


def _greedy_factory(worker_id, shared):
    """The cheapest real policy: no checkpoint, no jax — safe to build
    inside a forked test worker."""
    telemetry = TableTelemetry.from_table(
        cpu_source=RandomCpu(seed=0), counter=shared.table_counter
    )
    return ExtenderPolicy(GreedyBackend(), telemetry)


def _post(port, path, payload, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


def _get(port, path, timeout=10):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=timeout) as resp:
        body = resp.read()
    if resp.headers.get("Content-Type", "").startswith("application/json"):
        return json.loads(body)
    return body.decode()


def _filter_args(i=0):
    return {"nodenames": [f"aws-w{i}", f"azure-w{i}"], "pod": {}}


def _make_pool(workers, **kwargs):
    kwargs.setdefault("restart_policy", FAST_RESTARTS)
    kwargs.setdefault("stable_after_s", 60.0)
    kwargs.setdefault("poll_interval_s", 0.05)
    pool = ServingPool(_greedy_factory, workers=workers, host="127.0.0.1",
                       port=0, control_port=0, **kwargs)
    pool.start(ready_timeout_s=60.0)
    return pool


# ------------------------------------------------------------ pure helpers


def test_quantiles_from_histogram_bucket_semantics():
    """histogram_quantile-style estimates: monotone, inside the winning
    bucket's bounds, +Inf reports the highest finite bound, empty is
    count 0."""
    stats = LatencyStats()
    for _ in range(100):
        stats.record(0.0003)  # lands in the (0.25 ms, 0.5 ms] bucket
    cumulative, _, _ = stats.histogram()
    q = quantiles_from_histogram(cumulative)
    assert q["count"] == 100
    for key in ("p50_ms", "p90_ms", "p99_ms"):
        assert 0.25 <= q[key] <= 0.5

    stats = LatencyStats()
    for v in (0.0002,) * 50 + (0.002,) * 40 + (5.0,) * 10:
        stats.record(v)
    cumulative, _, _ = stats.histogram()
    q = quantiles_from_histogram(cumulative)
    assert q["p50_ms"] <= q["p90_ms"] <= q["p99_ms"]
    # 5 s sits beyond the last finite bound (1 s): the histogram carries
    # no information above it, so p99 caps there — exactly
    # histogram_quantile's behavior.
    assert q["p99_ms"] == pytest.approx(1000.0)

    assert quantiles_from_histogram([0] * (len(LatencyStats.BUCKETS) + 1)) \
        == {"count": 0}


def test_breaker_merge_snapshots_max_state_summed_counters():
    """'A dependency is down ANYWHERE' is one gauge: merged state is the
    max by STATE_CODES; lifetime counters sum; the dict keeps
    snapshot()'s exact shape."""
    healthy = CircuitBreaker(name="backend", failure_threshold=2)
    healthy.record_success()
    tripped = CircuitBreaker(name="backend", failure_threshold=2)
    tripped.record_failure()
    tripped.record_failure()  # trips open
    assert tripped.state == CircuitBreaker.OPEN

    merged = CircuitBreaker.merge_snapshots(
        [healthy.snapshot(), tripped.snapshot()]
    )
    assert merged["state"] == CircuitBreaker.OPEN
    assert merged["failures_total"] == 2
    assert merged["opens_total"] == 1
    assert set(merged) == set(healthy.snapshot())

    # half_open outranks closed but not open
    assert CircuitBreaker.merge_snapshots(
        [{"state": "closed", "consecutive_failures": 0, "failures_total": 0,
          "refusals_total": 0, "opens_total": 0},
         {"state": "half_open", "consecutive_failures": 1,
          "failures_total": 3, "refusals_total": 2, "opens_total": 1}]
    )["state"] == "half_open"

    assert CircuitBreaker.merge_snapshots([])["state"] == "closed"


def _synthetic_snapshot(worker_id, decisions, latencies_s, shed=None,
                        breakers=None):
    stats = LatencyStats()
    for v in latencies_s:
        stats.record(v)
    cumulative, total_sum, count = stats.histogram()
    body = {
        "backend": "cpu", "family": "set", "decisions": decisions,
        "choice_fractions": {}, "latency": stats.percentiles_ms(),
        "breakers": breakers or {},
    }
    if shed is not None:
        body["shed_fraction"] = shed
    return {
        "schema": 1, "worker_id": worker_id, "pid": 1000 + worker_id,
        "stats": body,
        "histogram": {"cumulative": cumulative, "sum": total_sum,
                      "count": count},
    }, stats


def test_aggregate_stats_merges_three_workers():
    """Pool /stats over a 3-worker pool: decision counts sum, the latency
    histogram equals ``LatencyStats.merged_histogram`` of the per-worker
    records, shed fractions are request-weighted, and one worker's open
    breaker dominates the pool view."""
    open_breaker = {"state": "open", "consecutive_failures": 0,
                    "failures_total": 5, "refusals_total": 7,
                    "opens_total": 1}
    closed_breaker = {"state": "closed", "consecutive_failures": 1,
                      "failures_total": 1, "refusals_total": 0,
                      "opens_total": 0}
    snap_a, stats_a = _synthetic_snapshot(
        0, {"aws": 8, "azure": 2}, [0.0002] * 10, shed=0.5,
        breakers={"backend": closed_breaker})
    snap_b, stats_b = _synthetic_snapshot(
        1, {"aws": 5, "azure": 25}, [0.002] * 30, shed=0.0,
        breakers={"backend": open_breaker})
    snap_c, stats_c = _synthetic_snapshot(
        2, {"aws": 0, "azure": 0}, [], breakers={"backend": closed_breaker})

    out = aggregate_stats([snap_a, snap_b, snap_c],
                          {"workers": 3, "alive": 3, "restarts_total": 0})
    assert out["decisions"] == {"aws": 13, "azure": 27}
    assert out["choice_fractions"]["aws"] == pytest.approx(13 / 40)

    # merged histogram == union of the per-worker records (ground truth
    # from the same per-worker scrapes, merged by the pinned method)
    ref_cum, ref_sum, ref_count = LatencyStats.merged_histogram(
        [stats_a, stats_b, stats_c])
    assert out["latency"]["count"] == ref_count == 40
    assert out["latency"]["source"] == "merged_histogram"
    assert out["latency"]["sum_seconds"] == pytest.approx(ref_sum)

    # request-weighted shed: (0.5*10 + 0.0*30) / 40
    assert out["shed_fraction"] == pytest.approx(0.125)

    # breaker max-merge: open anywhere -> open pool-wide, counters summed
    assert out["breakers"]["backend"]["state"] == "open"
    assert out["breakers"]["backend"]["failures_total"] == 7
    assert out["breakers"]["backend"]["refusals_total"] == 7

    assert [w["worker_id"] for w in out["workers"]] == [0, 1, 2]
    assert out["backend"] == "cpu" and out["family"] == "set"


def test_aggregate_metrics_exposition():
    """Pool /metrics: ONE histogram whose buckets are the bucket-wise
    sums of the per-worker cumulative counts, summed decision counters,
    max-merged breaker gauge, and per-worker liveness/decision labels."""
    snap_a, stats_a = _synthetic_snapshot(0, {"aws": 3}, [0.0002] * 3)
    snap_b, stats_b = _synthetic_snapshot(1, {"azure": 4}, [0.02] * 4)
    pool = {"workers": 3, "alive": 2, "restarts_total": 1}
    text = aggregate_metrics([snap_a, snap_b], pool)

    ref_cum, ref_sum, ref_count = LatencyStats.merged_histogram(
        [stats_a, stats_b])
    got_buckets = [
        int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
        if line.startswith("rl_scheduler_extender_decision_latency_seconds_bucket")
    ]
    assert got_buckets == ref_cum
    assert f"rl_scheduler_extender_decision_latency_seconds_count {ref_count}" in text
    assert 'rl_scheduler_extender_decisions_total{cloud="aws"} 3' in text
    assert 'rl_scheduler_extender_decisions_total{cloud="azure"} 4' in text
    assert "rl_scheduler_extender_pool_workers 3" in text
    assert "rl_scheduler_extender_pool_workers_alive 2" in text
    assert "rl_scheduler_extender_pool_restarts_total 1" in text
    # worker 2 never answered the scrape: visible, not silently absent
    assert 'rl_scheduler_extender_pool_worker_up{worker="0"} 1' in text
    assert 'rl_scheduler_extender_pool_worker_up{worker="2"} 0' in text
    assert 'rl_scheduler_extender_pool_worker_decisions_total{worker="1"} 4' in text


def test_worker_snapshot_round_trips_histogram():
    """The control-plane snapshot carries exactly the worker's lifetime
    histogram, and _HistogramView feeds it back to merged_histogram
    unchanged — the pool aggregation literally reuses the pinned
    method."""
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))
    policy = ExtenderPolicy(GreedyBackend(), telemetry)
    for i in range(7):
        policy.filter(_filter_args(i))
    snap = worker_snapshot(policy, worker_id=4)
    assert snap["worker_id"] == 4 and snap["pid"] == os.getpid()
    assert _HistogramView(snap["histogram"]).histogram() == \
        policy.stats.histogram()
    merged = LatencyStats.merged_histogram(
        [_HistogramView(snap["histogram"]), policy.stats])
    assert merged[2] == 2 * snap["histogram"]["count"]


# ----------------------------------------------------------- shared state


def test_shared_counter_is_cross_process_atomic():
    """Every index is handed out exactly once across processes."""
    import multiprocessing

    ctx = multiprocessing.get_context("fork")
    counter = SharedCounter(ctx)
    queue = ctx.Queue()

    def worker():
        queue.put([counter.next_index() for _ in range(200)])

    procs = [ctx.Process(target=worker) for _ in range(3)]
    for p in procs:
        p.start()
    seen = [i for _ in procs for i in queue.get(timeout=30)]
    for p in procs:
        p.join(timeout=30)
    assert sorted(seen) == list(range(600))
    assert counter.value == 600


def _constant_cpu():
    return RandomCpu(low=0.4, high=0.4, seed=0)  # uniform(0.4, 0.4) == 0.4


def test_pool_price_counter_score_parity_graph_family():
    """Satellite: all workers of one pool walk the SAME price trajectory
    under ``--price-replay counter``. Two policies sharing the pool's
    counter, serving an identical request stream interleaved, produce
    exactly the score sequence one single-process policy produces —
    request k scores identically no matter which worker serves it."""
    import jax
    import jax.numpy as jnp

    from rl_scheduler_tpu.env.cluster_graph import build_topology
    from rl_scheduler_tpu.models import GNNPolicy
    from rl_scheduler_tpu.scheduler.graph_backend import NumpyGNNBackend

    _, adj, _ = build_topology(8)
    net = GNNPolicy.from_adjacency(adj, dim=64, depth=3)
    tree = net.init(jax.random.PRNGKey(4), jnp.zeros((8, 7), jnp.float32))

    shared = PoolShared()
    clouds = ["aws", "aws", "azure", "azure"]
    display = ["aws-a", "aws-b", "azure-a", "azure-b"]

    def graph_policy(counter):
        return ExtenderPolicy(
            NumpyGNNBackend(tree),
            TableTelemetry.from_table(cpu_source=_constant_cpu()),
            price_replay="counter", price_counter=counter,
        )

    worker_a, worker_b = (graph_policy(shared.price_counter)
                          for _ in range(2))
    reference = graph_policy(None)  # process-local counter, same stream

    pool_probs = [
        (worker_a if k % 2 == 0 else worker_b)
        .decide_graph(clouds, display, None, 0.25)[1]
        for k in range(12)
    ]
    ref_probs = [reference.decide_graph(clouds, display, None, 0.25)[1]
                 for _ in range(12)]
    for pooled, ref in zip(pool_probs, ref_probs):
        np.testing.assert_array_equal(pooled, ref)
    # The trajectory genuinely advanced — the pool consumed one shared
    # position per request, and the price rows moved the distribution
    # (otherwise the parity above would be vacuous).
    assert shared.price_counter.value == 12
    assert any(not np.array_equal(ref_probs[0], p) for p in ref_probs[1:])


def test_pool_table_counter_score_parity_set_family():
    """The normalized-table replay has the same pool seam: set-family
    workers sharing the table counter reproduce the single-process
    score sequence for an identical request stream."""
    import jax
    import jax.numpy as jnp

    from rl_scheduler_tpu.models.transformer import SetTransformerPolicy
    from rl_scheduler_tpu.scheduler.set_backend import NumpySetBackend

    net = SetTransformerPolicy(dim=64, depth=2)
    tree = net.init(jax.random.PRNGKey(3), jnp.zeros((8, 6), jnp.float32))

    shared = PoolShared()
    clouds = ["aws", "aws", "azure"]

    def set_policy(counter):
        return ExtenderPolicy(
            NumpySetBackend(tree),
            TableTelemetry.from_table(cpu_source=_constant_cpu(),
                                      counter=counter),
        )

    worker_a = set_policy(shared.table_counter)
    worker_b = set_policy(shared.table_counter)
    reference = set_policy(None)

    pool_probs = [
        (worker_a if k % 2 == 0 else worker_b).decide_set(clouds, 0.25)[1]
        for k in range(12)
    ]
    ref_probs = [reference.decide_set(clouds, 0.25)[1] for _ in range(12)]
    for pooled, ref in zip(pool_probs, ref_probs):
        np.testing.assert_array_equal(pooled, ref)
    assert shared.table_counter.value == 12
    assert any(not np.array_equal(ref_probs[0], p) for p in ref_probs[1:])


def test_raw_price_replay_refuses_counter_with_wallclock():
    from rl_scheduler_tpu.scheduler.graph_backend import RawPriceReplay

    with pytest.raises(ValueError, match="counter"):
        RawPriceReplay(np.ones((4, 2), np.float32), mode="wallclock",
                       counter=SharedCounter())


# ------------------------------------------------------------- end to end


def test_pool_end_to_end_aggregation_reset_and_health():
    """A real 3-worker pool: traffic through the shared data port, then
    the supervisor's aggregated endpoints against per-worker-scrape
    ground truth, /stats/reset fan-out (rings clear everywhere, lifetime
    histograms don't), and /healthz live-worker reporting."""
    pool = _make_pool(workers=3)
    try:
        cport = pool.control_address[1]
        n_requests = 45
        for i in range(n_requests):
            result = _post(pool.port, "/filter", _filter_args(i))
            assert len(result["nodenames"]) == 1

        health = _get(cport, "/healthz")
        assert health["status"] == "ok"
        assert health["workers"] == 3 and health["alive"] == 3

        # a pool worker's own /healthz names its pool membership
        worker_health = _get(pool.port, "/healthz")
        assert worker_health["workers"] == 3
        assert worker_health["worker_id"] in (0, 1, 2)

        # ground truth: per-worker scrapes, merged by the pinned method
        snapshots = pool.scrape()
        assert len(snapshots) == 3
        ref_cum, ref_sum, ref_count = LatencyStats.merged_histogram(
            [_HistogramView(s["histogram"]) for s in snapshots])
        assert ref_count == n_requests

        stats = _get(cport, "/stats")
        assert sum(stats["decisions"].values()) == n_requests
        assert stats["latency"]["count"] == n_requests
        assert stats["latency"]["source"] == "merged_histogram"
        assert stats["backend"] == "greedy" and stats["family"] == "cloud"
        assert sum(w["decisions_total"] for w in stats["workers"]) \
            == n_requests
        assert "backend" in stats["breakers"]

        metrics = _get(cport, "/metrics")
        got_buckets = [
            int(line.rsplit(" ", 1)[1]) for line in metrics.splitlines()
            if line.startswith(
                "rl_scheduler_extender_decision_latency_seconds_bucket")
        ]
        assert got_buckets == ref_cum
        assert (f"rl_scheduler_extender_decision_latency_seconds_count "
                f"{n_requests}") in metrics
        assert 'rl_scheduler_extender_circuit_state{breaker="backend"} 0' \
            in metrics
        for worker_id in range(3):
            assert (f'rl_scheduler_extender_pool_worker_up{{worker='
                    f'"{worker_id}"}} 1') in metrics

        # reset fans out: every worker's percentile ring clears, the
        # lifetime histogram stays (Prometheus monotonicity)
        reset = _post(cport, "/stats/reset", {})
        assert reset == {"status": "reset", "workers": 3}
        for snap in pool.scrape():
            assert snap["stats"]["latency"]["count"] == 0
        stats_after = _get(cport, "/stats")
        assert stats_after["latency"]["count"] == n_requests  # lifetime
        assert sum(stats_after["decisions"].values()) == n_requests

        # a junk hello on the control listener (out-of-range worker_id,
        # then raw garbage) must not kill the accept thread — the pool
        # keeps scraping all workers afterwards
        from rl_scheduler_tpu.scheduler.pool import _control_connect

        for payload in (b'{"worker_id": 99}\n', b'not json\n'):
            rogue = _control_connect(pool._control_spec)
            rogue.sendall(payload)
            rogue.close()
        time.sleep(0.2)
        assert len(pool.scrape()) == 3
    finally:
        pool.shutdown()


def test_pool_restarts_dead_worker():
    """The supervisor notices a SIGKILLed worker, restarts it on the
    RetryPolicy backoff, and the control plane heals: /healthz reports
    full strength again and the new worker answers scrapes."""
    pool = _make_pool(workers=2)
    try:
        cport = pool.control_address[1]
        pids = {s["pid"] for s in pool.scrape()}
        assert len(pids) == 2
        victim = sorted(pids)[0]
        os.kill(victim, signal.SIGKILL)

        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            try:
                health = _get(cport, "/healthz")
            except urllib.error.HTTPError:
                health = None  # 503: degraded while the worker is down
            if health is not None and health["alive"] == 2 \
                    and health["restarts_total"] >= 1 \
                    and len(pool.scrape()) == 2:
                break
            time.sleep(0.1)
        else:
            pytest.fail(f"pool did not heal: {pool.status()}")

        new_pids = {s["pid"] for s in pool.scrape()}
        assert victim not in new_pids and len(new_pids) == 2

        # the healed pool still serves (retry a few times: connections
        # hashed to the dying socket during the window may be refused)
        for attempt in range(20):
            try:
                result = _post(pool.port, "/filter", _filter_args(attempt))
                break
            except OSError:
                time.sleep(0.1)
        assert len(result["nodenames"]) == 1
    finally:
        pool.shutdown()


def test_pool_inherit_fallback_mode():
    """Where SO_REUSEPORT is unavailable the pool binds once and forks:
    workers accept() on the inherited listener — same endpoints, same
    aggregation, no kernel balancing required."""
    pool = _make_pool(workers=2, mode="inherit")
    try:
        assert pool.status()["mode"] == "inherit"
        for i in range(10):
            result = _post(pool.port, "/filter", _filter_args(i))
            assert len(result["nodenames"]) == 1
        stats = _get(pool.control_address[1], "/stats")
        assert sum(stats["decisions"].values()) == 10
        assert stats["latency"]["count"] == 10
    finally:
        pool.shutdown()


def _free_port():
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


def test_pool_cli_workers_flag_and_sigterm():
    """``--workers 2`` through the real CLI: the supervisor forks the
    pool, both planes answer, and SIGTERM shuts the whole tree down
    cleanly (exit 0, port released)."""
    import multiprocessing

    from rl_scheduler_tpu.scheduler import extender as ext

    ctx = multiprocessing.get_context("fork")
    port, cport = _free_port(), _free_port()
    proc = ctx.Process(target=ext.main, args=(
        ["--workers", "2", "--backend", "greedy", "--host", "127.0.0.1",
         "--port", str(port), "--control-port", str(cport)],))
    proc.start()
    try:
        deadline = time.monotonic() + 60.0
        health = None
        while time.monotonic() < deadline:
            try:
                health = _get(cport, "/healthz", timeout=2)
                if health["alive"] == 2:
                    break
            except OSError:
                pass
            time.sleep(0.1)
        assert health is not None and health["alive"] == 2, health
        result = _post(port, "/filter", _filter_args())
        assert len(result["nodenames"]) == 1
        assert _get(port, "/healthz")["workers"] == 2

        os.kill(proc.pid, signal.SIGTERM)
        proc.join(timeout=30)
        assert proc.exitcode == 0
    finally:
        if proc.is_alive():
            proc.kill()
            proc.join(timeout=10)


def test_pool_cli_flag_validation():
    from rl_scheduler_tpu.scheduler import extender as ext

    with pytest.raises(SystemExit, match="at least 1"):
        ext.main(["--workers", "0"])
    with pytest.raises(SystemExit, match="pool mode"):
        ext.main(["--control-port", "9999"])
    with pytest.raises(SystemExit, match="pool mode"):
        ext.main(["--blas-threads", "1"])
    with pytest.raises(SystemExit, match="pool mode"):
        ext.main(["--control-host", "0.0.0.0"])
    with pytest.raises(SystemExit, match="positive"):
        ext.main(["--workers", "2", "--blas-threads", "-1"])
    with pytest.raises(ValueError, match="blas_threads"):
        ServingPool(_greedy_factory, workers=2, blas_threads=-1)
    # the heuristic splits cores across workers, never below 1
    pool = ServingPool(_greedy_factory, workers=64)
    assert pool.blas_threads == 1


def test_make_server_reuse_port_two_listeners():
    """Two make_server(reuse_port=True) servers share one port — the
    primitive each pool worker uses to join the kernel's balancing
    group."""
    if not hasattr(socket, "SO_REUSEPORT"):
        pytest.skip("no SO_REUSEPORT on this platform")
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))
    policy_a = ExtenderPolicy(GreedyBackend(), telemetry)
    policy_b = ExtenderPolicy(GreedyBackend(), telemetry)
    srv_a = make_server(policy_a, "127.0.0.1", 0, reuse_port=True)
    port = srv_a.server_address[1]
    srv_b = make_server(policy_b, "127.0.0.1", port, reuse_port=True)
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in (srv_a, srv_b)]
    for t in threads:
        t.start()
    try:
        for i in range(12):
            assert len(_post(port, "/filter", _filter_args(i))["nodenames"]) == 1
        total = policy_a.stats.histogram()[2] + policy_b.stats.histogram()[2]
        assert total == 12
    finally:
        srv_a.shutdown()
        srv_b.shutdown()


# ------------------------------------------------------------------- soak


@pytest.mark.slow
def test_pool_soak_via_bench():
    """``make serve-soak``: the bench's --duration mode against a live
    2-worker pool, pool-wide reset/stats via --control-port, zero
    failures, schema-tagged result line."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "extender_bench",
        Path(__file__).resolve().parents[1] / "loadgen" / "extender_bench.py",
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    pool = _make_pool(workers=2)
    try:
        out = bench.main([
            "--port", str(pool.port), "--duration", "3", "--threads", "4",
            "--warmup", "5", "--control-port",
            str(pool.control_address[1]),
        ])
    finally:
        pool.shutdown()
    assert out["schema_version"] == 1
    assert out["mode"] == "soak"
    assert out["workers"] == 2
    assert out["concurrency"] == 4
    assert out["failures"] == 0
    assert out["requests"] > 0 and out["req_per_sec"] > 0
    assert out["server_p50_ms"] is not None
