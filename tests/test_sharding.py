"""Multi-device tests on the virtual 8-CPU mesh: mesh building, dp-PPO."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.agent.ppo import PPOTrainConfig
from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.parallel import make_mesh, make_data_parallel_ppo
from rl_scheduler_tpu.parallel.sharding import dp_ppo_train

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

DP_CFG = PPOTrainConfig(
    num_envs=64,
    rollout_steps=32,
    minibatch_size=256,
    num_epochs=2,
    lr=1e-3,
    hidden=(32, 32),
)


@pytest.fixture(scope="module")
def env_params():
    return env_core.make_params(EnvConfig())


def test_make_mesh_shapes():
    m = make_mesh()
    assert m.shape == {"dp": 8}
    m2 = make_mesh({"dp": 4, "tp": 2})
    assert m2.shape == {"dp": 4, "tp": 2}
    m3 = make_mesh({"dp": -1})
    assert m3.shape == {"dp": 8}
    with pytest.raises(ValueError):
        make_mesh({"dp": 16})


def test_dp_ppo_runs_and_syncs(env_params):
    mesh = make_mesh({"dp": 8})
    init_fn, update_fn, _ = make_data_parallel_ppo(env_params, DP_CFG, mesh)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    # batch leaves sharded over dp, params replicated
    assert runner.obs.shape == (DP_CFG.num_envs, env_core.OBS_DIM)
    assert runner.key.shape[0] == 8  # one key row per device

    update = jax.jit(update_fn)
    runner, metrics = update(runner)
    runner, metrics = update(runner)
    for k in ("episode_reward_mean", "policy_loss", "value_loss"):
        assert np.isfinite(float(metrics[k])), k
    assert int(runner.update_idx) == 2
    # params replicated: every leaf finite, single logical copy
    for leaf in jax.tree.leaves(runner.params):
        assert bool(jnp.all(jnp.isfinite(leaf)))


def test_dp_keys_differ_per_device(env_params):
    mesh = make_mesh({"dp": 8})
    init_fn, _, _ = make_data_parallel_ppo(env_params, DP_CFG, mesh)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    keys = np.asarray(runner.key)
    assert len({tuple(k) for k in keys}) == 8  # all distinct


def test_dp_validation_errors(env_params):
    mesh = make_mesh({"dp": 8})
    with pytest.raises(ValueError, match="not divisible"):
        make_data_parallel_ppo(
            env_params, dataclasses.replace(DP_CFG, num_envs=63), mesh
        )


def test_dp_learning_progress(env_params):
    """The dp path must actually learn (reward improves over iterations)."""
    _, history = dp_ppo_train(env_params, DP_CFG, 12, seed=1)
    first = np.mean([h["reward_mean"] for h in history[:3]])
    last = np.mean([h["reward_mean"] for h in history[-3:]])
    assert last > first


def test_train_cli_dp(tmp_path):
    """--dp shards the CLI training run over the virtual mesh, composing
    with in-training eval, fused dispatch, checkpointing, and resume."""
    import json

    from rl_scheduler_tpu.agent import train_ppo as cli
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    run_dir = cli.main([
        "--preset", "quick", "--dp", "4", "--num-envs", "8",
        "--rollout-steps", "16", "--minibatch-size", "32", "--hidden", "8,8",
        "--iterations", "4", "--checkpoint-every", "2",
        "--eval-every", "2", "--eval-episodes", "4",
        "--updates-per-dispatch", "2", "--sync-every", "2",
        "--run-root", str(tmp_path), "--run-name", "dp_cli",
    ])
    mgr = CheckpointManager(run_dir)
    assert mgr.latest_step() == 4
    mgr.close()
    records = [json.loads(l) for l in (run_dir / "metrics.jsonl").open()]
    trains = [r for r in records if not r.get("eval")
              and "resumed_from_iteration" not in r]
    evals = [r for r in records if r.get("eval")]
    assert [r["iteration"] for r in trains] == [1, 2, 3, 4]
    assert [r["iteration"] for r in evals] == [2, 4]
    # resume continues the sharded run
    cli.main([
        "--preset", "quick", "--dp", "4", "--num-envs", "8",
        "--rollout-steps", "16", "--minibatch-size", "32", "--hidden", "8,8",
        "--iterations", "6", "--checkpoint-every", "2", "--resume",
        "--run-root", str(tmp_path), "--run-name", "dp_cli",
    ])
    mgr = CheckpointManager(run_dir)
    assert mgr.latest_step() == 6
    mgr.close()


def test_train_cli_dp_sp(tmp_path):
    """VERDICT r2 item 2: --sp composes with --dp from the command line —
    cluster_set trains on a dp x sp mesh (ring attention over the node
    axis) with checkpointing, in-training eval, and resume."""
    import json

    from rl_scheduler_tpu.agent import train_ppo as cli
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    argv = [
        "--preset", "quick", "--env", "cluster_set", "--dp", "2", "--sp", "2",
        "--num-envs", "8", "--rollout-steps", "16", "--minibatch-size", "32",
        "--eval-every", "2", "--eval-episodes", "2",
        "--checkpoint-every", "2", "--run-root", str(tmp_path),
        "--run-name", "sp_cli",
    ]
    run_dir = cli.main(argv + ["--iterations", "2"])
    mgr = CheckpointManager(run_dir)
    meta = mgr.restore_meta(2)
    mgr.close()
    assert meta["sp"] == 2 and meta["env"] == "cluster_set"
    records = [json.loads(l) for l in (run_dir / "metrics.jsonl").open()]
    trains = [r for r in records if not r.get("eval")
              and "resumed_from_iteration" not in r]
    evals = [r for r in records if r.get("eval")]
    assert all(np.isfinite(r["reward_mean"]) for r in trains)
    assert evals and np.isfinite(evals[0]["eval_episode_reward_mean"])

    # resume continues (param shapes are sp-invariant; the abstract tree
    # comes from the unsharded twin)
    cli.main(argv + ["--iterations", "4", "--resume"])
    mgr = CheckpointManager(run_dir)
    assert mgr.latest_step() == 4
    mgr.close()

    # sp mismatch on resume is refused
    with pytest.raises(SystemExit, match="--sp"):
        cli.main([
            "--preset", "quick", "--env", "cluster_set", "--dp", "2",
            "--num-envs", "8", "--rollout-steps", "16",
            "--minibatch-size", "32", "--iterations", "6", "--resume",
            "--run-root", str(tmp_path), "--run-name", "sp_cli",
        ])


def test_sp_tp_flag_validation(tmp_path):
    from rl_scheduler_tpu.agent import train_ppo as cli

    root = ["--run-root", str(tmp_path)]
    with pytest.raises(SystemExit, match="cannot combine"):
        cli.main(["--sp", "2", "--tp", "2", "--env", "cluster_set"] + root)
    with pytest.raises(SystemExit, match="node axis"):
        cli.main(["--sp", "2", "--env", "multi_cloud"] + root)
    with pytest.raises(SystemExit, match="structured policy"):
        cli.main(["--tp", "2", "--env", "cluster_graph"] + root)
    with pytest.raises(SystemExit, match="divide by sp"):
        cli.main(["--sp", "3", "--env", "cluster_set"] + root)
    with pytest.raises(SystemExit, match="column widths"):
        cli.main(["--tp", "2", "--hidden", "15,16",
                  "--env", "multi_cloud"] + root)
    with pytest.raises(SystemExit, match="ring attention"):
        cli.main(["--sp", "2", "--fused-set", "--env", "cluster_set"] + root)


def test_train_cli_dp_fused_set(tmp_path):
    """VERDICT r3 item 2: the batch-minor set policy (--fused-set) trains
    under --dp — the production config-4 fast path has multi-device
    evidence, not just a silent untested composition."""
    import json

    from rl_scheduler_tpu.agent import train_ppo as cli
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    run_dir = cli.main([
        "--preset", "quick", "--env", "cluster_set", "--fused-set",
        "--dp", "4", "--num-envs", "8", "--rollout-steps", "16",
        "--minibatch-size", "32", "--num-epochs", "2",
        "--iterations", "2", "--checkpoint-every", "2",
        "--run-root", str(tmp_path), "--run-name", "dp_fused_set",
    ])
    mgr = CheckpointManager(run_dir)
    meta = mgr.restore_meta(2)
    mgr.close()
    assert meta["fused_set"] is True and meta["env"] == "cluster_set"
    records = [json.loads(l) for l in (run_dir / "metrics.jsonl").open()]
    assert all(np.isfinite(r["reward_mean"]) for r in records
               if "reward_mean" in r)


def test_train_cli_dp_fused_gnn(tmp_path):
    """Same for the Pallas GNN kernel (--fused-gnn) under --dp: the
    shard_map'd pallas_call (interpret mode on CPU) compiles and trains."""
    import json

    from rl_scheduler_tpu.agent import train_ppo as cli
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    run_dir = cli.main([
        "--preset", "quick", "--env", "cluster_graph", "--fused-gnn",
        "--dp", "4", "--num-envs", "8", "--rollout-steps", "16",
        "--minibatch-size", "32", "--num-epochs", "2",
        "--iterations", "2", "--checkpoint-every", "2",
        "--run-root", str(tmp_path), "--run-name", "dp_fused_gnn",
    ])
    mgr = CheckpointManager(run_dir)
    meta = mgr.restore_meta(2)
    mgr.close()
    assert meta["fused_gnn"] is True and meta["env"] == "cluster_graph"
    records = [json.loads(l) for l in (run_dir / "metrics.jsonl").open()]
    assert all(np.isfinite(r["reward_mean"]) for r in records
               if "reward_mean" in r)
