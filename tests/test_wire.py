"""graftfront wire codec: round-trip properties and the strictness
contract.

The compact wire format (``scheduler/wire.py``) is the data-plane
front's parse budget: one char per candidate, lazy display names, and a
decoder that is STRICT where the trace reader is lenient. These tests
pin (a) bitwise encode/decode round-trips across the edge cases —
unicode names, empty candidate lists, maximal-N tokens, unknown-cloud
candidates — (b) every malformation class raising :class:`WireError`,
and (c) the HTTP contract that a malformed body answers 400 WITHOUT
dropping the connection (a kube-scheduler keeps its keep-alive pool)."""

from __future__ import annotations

import http.client
import json
import threading

import pytest

from rl_scheduler_tpu.scheduler.extender import ExtenderPolicy, make_server
from rl_scheduler_tpu.scheduler.policy_backend import GreedyBackend
from rl_scheduler_tpu.scheduler.telemetry import RandomCpu, TableTelemetry
from rl_scheduler_tpu.scheduler.wire import (
    WIRE_CONTENT_TYPE,
    SynthNames,
    WireError,
    WireRequest,
    decode_filter_response,
    decode_prioritize_response,
    decode_request,
    encode_filter_response,
    encode_prioritize_response,
    encode_request,
    serve_wire,
)


def _policy():
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))
    return ExtenderPolicy(GreedyBackend(), telemetry)


def _clouds(n):
    return ["aws" if i % 2 == 0 else "azure" for i in range(n)]


# ------------------------------------------------------------ round trips


@pytest.mark.parametrize("clouds", [
    ["aws"],
    ["aws", "azure", None],
    _clouds(7),
    [None] * 3,
    _clouds(4096),                      # maximal-N token
], ids=["one", "mixed", "seven", "all-unknown", "maximal"])
def test_request_roundtrip_without_names(clouds):
    body = encode_request(clouds, 500)
    req = decode_request(body)
    assert req.clouds == list(clouds)
    assert req.pod_millicores == 500
    assert len(req) == len(clouds)
    # Bitwise: re-encoding the decoded request reproduces the body.
    assert encode_request(req.clouds, req.pod_millicores) == body


def test_request_roundtrip_with_names_is_bitwise():
    clouds = ["aws", "azure", None]
    names = ["wéb-0", "ノード-1", "node.x"]  # unicode survives utf-8
    body = encode_request(clouds, 250, names=names)
    req = decode_request(body)
    assert list(req.names) == names
    assert encode_request(req.clouds, req.pod_millicores,
                          names=list(req.names)) == body


def test_request_roundtrip_empty():
    req = decode_request(encode_request([], 0))
    assert len(req) == 0 and req.clouds == []


def test_pod_cpu_fraction_matches_json_normalization():
    req = WireRequest(["aws"], 500)
    assert req.pod_cpu_fraction(4.0) == pytest.approx(0.125)


def test_synth_names_are_lazy_and_sliceable():
    names = SynthNames(["aws", "azure", None])
    assert names[0] == "aws-0"
    assert names[1] == "azure-1"
    assert names[2] == "node-2"
    assert list(names[1:]) == ["azure-1", "node-2"]
    assert len(names) == 3


# ------------------------------------------------------------- strictness


def test_encode_refuses_delimiter_names_and_bad_inputs():
    for bad in ("a;b", "a,b", "a\nb", "a\rb"):
        with pytest.raises(WireError):
            encode_request(["aws"], 100, names=[bad])
    with pytest.raises(WireError):
        encode_request(["aws"], 100, names=["x", "y"])  # count mismatch
    with pytest.raises(WireError):
        encode_request(["aws"], -1)
    with pytest.raises(WireError):
        encode_request(["gcp"], 100)  # cloud outside the v1 alphabet


@pytest.mark.parametrize("body", [
    b"\xff\xfe",            # not utf-8
    b"1;100",               # too few fields
    b"1;100;aa;x;y",        # too many fields
    b"2;100;aa",            # unsupported version
    b"1;abc;aa",            # malformed millicores
    b"1;-5;aa",             # negative millicores
    b"1;100;ab",            # unknown cloud char
    b"1;100;aa;only-one",   # name count mismatch
    b"1;100;aa;x,",         # empty name
], ids=["utf8", "short", "long", "version", "millis", "negative",
        "cloudchar", "namecount", "emptyname"])
def test_decode_refuses_malformed_bodies(body):
    with pytest.raises(WireError):
        decode_request(body)


def test_filter_response_roundtrip():
    assert encode_filter_response(None) == b"1;*"
    assert decode_filter_response(b"1;*", 5) is None
    assert decode_filter_response(encode_filter_response([0, 3, 4]),
                                  5) == [0, 3, 4]
    assert decode_filter_response(encode_filter_response([]), 5) == []
    with pytest.raises(WireError):
        decode_filter_response(b"1;9", 5)  # index out of range
    with pytest.raises(WireError):
        decode_filter_response(b"1;x", 5)
    with pytest.raises(WireError):
        decode_filter_response(b"0;1", 5)


def test_prioritize_response_roundtrip():
    assert decode_prioritize_response(
        encode_prioritize_response([0, 100, 42])) == [0, 100, 42]
    assert decode_prioritize_response(
        encode_prioritize_response([])) == []
    with pytest.raises(WireError):
        decode_prioritize_response(b"1;a,b")


# ------------------------------------------------- policy-level agreement


def test_serve_wire_agrees_with_json_filter_and_prioritize():
    """The wire path must reproduce the JSON path's decisions: two
    fresh policies (identical seeded telemetry) serve the SAME candidate
    set, one per encoding; kept names and scores must match."""
    n = 6
    clouds = _clouds(n)
    names = [f"{c}-n{i}" for i, c in enumerate(clouds)]

    wire_policy, json_policy = _policy(), _policy()
    kept = decode_filter_response(
        serve_wire(wire_policy, "/filter",
                   encode_request(clouds, 0, names=names)), n)
    json_out = json_policy.filter({"nodenames": list(names), "pod": {}})
    assert [names[i] for i in (kept if kept is not None else range(n))] \
        == json_out["nodenames"]

    scores = decode_prioritize_response(
        serve_wire(wire_policy, "/prioritize",
                   encode_request(clouds, 0, names=names)))
    json_scores = json_policy.prioritize({"nodenames": list(names),
                                          "pod": {}})
    assert scores == [entry["score"] for entry in json_scores]


def test_serve_wire_unknown_path_is_value_error():
    with pytest.raises(ValueError):
        serve_wire(_policy(), "/stats", encode_request(["aws"], 0))


# ----------------------------------------------------------- HTTP contract


@pytest.mark.parametrize("front", ["threading", "asyncio"])
def test_bad_wire_answers_400(front):
    """Both fronts refuse a malformed wire body with HTTP 400 and a
    JSON error body — never a dropped connection or a 500."""
    srv = make_server(_policy(), host="127.0.0.1", port=0, front=front)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1",
                                          srv.server_address[1], timeout=5)
        conn.request("POST", "/filter", b"1;100;ab",
                     {"Content-Type": WIRE_CONTENT_TYPE})
        resp = conn.getresponse()
        body = resp.read()
        assert resp.status == 400
        assert "bad wire" in json.loads(body)["error"]
        conn.close()
    finally:
        srv.shutdown()
        srv.server_close()


def test_bad_wire_keeps_the_asyncio_connection_alive():
    """The strictness contract end to end: on the keep-alive front a
    malformed body 400s and the SAME connection then serves a good
    request — a client's connection pool survives its own bad input."""
    srv = make_server(_policy(), host="127.0.0.1", port=0, front="asyncio")
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1",
                                          srv.server_address[1], timeout=5)
        conn.request("POST", "/prioritize", b"1;100;!!",
                     {"Content-Type": WIRE_CONTENT_TYPE})
        resp = conn.getresponse()
        resp.read()
        assert resp.status == 400 and not resp.will_close

        conn.request("POST", "/prioritize", encode_request(_clouds(4), 250),
                     {"Content-Type": WIRE_CONTENT_TYPE})
        resp = conn.getresponse()
        scores = decode_prioritize_response(resp.read())
        assert resp.status == 200 and len(scores) == 4
        conn.close()
    finally:
        srv.shutdown()
        srv.server_close()
