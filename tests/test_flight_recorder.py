"""Flight recorder: device ring semantics, anomaly triggers, dump artifact
schema, and the end-to-end path through the training loop."""

import json
import math

import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.utils.flight_recorder import (
    FlightRecorder,
    build_manifest,
)
from rl_scheduler_tpu.utils.metrics import TrainObserver


def _read(path):
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    manifests = [ln for ln in lines if ln["kind"] == "manifest"]
    rings = [ln for ln in lines if ln["kind"] == "ring"]
    return manifests, rings


def _record_rows(rec, values):
    for i, v in enumerate(values):
        rec.record(i, {"loss": jnp.float32(v), "grad_norm": jnp.float32(1.0)})


def test_dump_fires_on_injected_nan(tmp_path):
    """The acceptance path: a NaN in a watched row dumps ring + manifest."""
    rec = FlightRecorder(
        path=tmp_path / "fr.jsonl",
        manifest=build_manifest(config={"preset": "quick", "seed": 3}),
    )
    _record_rows(rec, [0.5, 0.4, 0.3])
    rec.check_row(0, {"loss": 0.5, "grad_norm": 1.0})
    assert rec.dump_count == 0
    rec.check_row(2, {"loss": float("nan"), "grad_norm": 1.0})
    assert rec.dump_count == 1
    manifests, rings = _read(rec.path)
    (m,) = manifests
    assert m["reason"] == "nan_inf" and "loss" in m["detail"]
    assert m["iteration"] == 2
    # The manifest is self-describing run provenance.
    assert m["config"] == {"preset": "quick", "seed": 3}
    for key in ("jax_version", "backend", "device_kind", "precision",
                "git_sha"):
        assert key in m, key
    # Ring rows: every recorded step, chronological, with the metrics.
    assert [r["step"] for r in rings] == [0, 1, 2]
    assert [r["loss"] for r in rings] == pytest.approx([0.5, 0.4, 0.3])


def test_ring_wraparound_keeps_last_k(tmp_path):
    rec = FlightRecorder(path=tmp_path / "fr.jsonl", capacity=4)
    _record_rows(rec, np.arange(7, dtype=np.float32))
    rec.dump("manual", 6)
    _, rings = _read(rec.path)
    assert [r["step"] for r in rings] == [3, 4, 5, 6]
    assert [r["loss"] for r in rings] == [3.0, 4.0, 5.0, 6.0]


def test_stacked_record_fused_dispatch(tmp_path):
    """updates_per_dispatch=k hands [k]-stacked metrics; the ring writes
    k rows in one scatter."""
    rec = FlightRecorder(path=tmp_path / "fr.jsonl", capacity=8)
    rec.record(0, {"loss": jnp.asarray([1.0, 2.0, 3.0])}, k=3)
    rec.record(3, {"loss": jnp.asarray([4.0, 5.0, 6.0])}, k=3)
    rec.dump("manual", 5)
    _, rings = _read(rec.path)
    assert [r["step"] for r in rings] == [0, 1, 2, 3, 4, 5]
    assert [r["loss"] for r in rings] == [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]


def test_ring_grows_to_hold_one_dispatch(tmp_path):
    """updates_per_dispatch > capacity would scatter duplicate indices in
    one .at[].set (undefined winner per XLA scatter semantics); the ring
    grows to hold a full dispatch instead."""
    rec = FlightRecorder(path=tmp_path / "fr.jsonl", capacity=4)
    rec.record(0, {"loss": jnp.arange(6, dtype=jnp.float32)}, k=6)
    rec.dump("manual", 5)
    _, rings = _read(rec.path)
    assert [r["step"] for r in rings] == [0, 1, 2, 3, 4, 5]
    assert [r["loss"] for r in rings] == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]


def test_zscore_spike_trigger(tmp_path):
    rec = FlightRecorder(path=tmp_path / "fr.jsonl", zscore_threshold=8.0,
                         min_count=20)
    rng = np.random.RandomState(0)
    for i in range(30):
        rec.check_row(i, {"grad_norm": 1.0 + 0.05 * float(rng.randn())})
    assert rec.dump_count == 0
    rec.check_row(30, {"grad_norm": 100.0})
    assert rec.dump_count == 1
    manifests, _ = _read(rec.path)
    assert manifests[0]["reason"] == "zscore_spike"
    assert "sigma" in manifests[0]["detail"]
    # The spike stayed out of the running baseline: a second spike right
    # after still triggers (rate-limit permitting).
    rec.check_row(31, {"grad_norm": 100.0})
    assert rec.dump_count == 2


def test_eval_collapse_wrap(tmp_path):
    rec = FlightRecorder(path=tmp_path / "fr.jsonl")
    seen = []
    wrapped = rec.wrap_eval_log(lambda i, m: seen.append(i), threshold=-50.0)
    wrapped(4, {"eval_episode_reward_mean": -20.0,
                "eval_episodes_completed": 8.0})
    assert rec.dump_count == 0
    wrapped(9, {"eval_episode_reward_mean": -80.0,
                "eval_episodes_completed": 8.0})
    assert rec.dump_count == 1
    manifests, _ = _read(rec.path)
    assert manifests[0]["reason"] == "eval_collapse"
    assert seen == [4, 9], "inner sink must still run after the dump"
    # And the wrap composes with a raising inner sink (the reseed guard):
    def raising(i, m):
        raise RuntimeError("stall")

    wrapped = rec.wrap_eval_log(raising, threshold=-50.0)
    with pytest.raises(RuntimeError):
        wrapped(12, {"eval_episode_reward_mean": -90.0})
    assert rec.dump_count == 2, "dump lands BEFORE the guard raises"


def test_reset_clears_ring_and_tags_manifest(tmp_path):
    """The --reseed-on-stall contract: a reset between attempts drops the
    abandoned attempt's ring rows (same step numbers, different seed) and
    stamps the manifest so later dumps are attributable."""
    rec = FlightRecorder(path=tmp_path / "fr.jsonl", manifest={"seed": 0})
    _record_rows(rec, [0.5, 0.4])
    # A healthy baseline accumulates, then the attempt is abandoned.
    for i in range(25):
        rec.check_row(i, {"grad_norm": 1.0})
    rec.reset(reseed_attempt=1, seed=1)
    rec.record(0, {"loss": jnp.float32(9.0), "grad_norm": jnp.float32(1.0)})
    rec.check_row(0, {"loss": float("nan")})
    manifests, rings = _read(rec.path)
    assert manifests[0]["reseed_attempt"] == 1 and manifests[0]["seed"] == 1
    # Only the replacement attempt's row survives — step 0 appears once.
    assert [(r["step"], r["loss"]) for r in rings] == [(0, 9.0)]
    # The z-score baseline restarted too (below min_count again).
    assert rec._welford.get("grad_norm", (0,))[0] <= 1


def test_dump_exception_unwind(tmp_path):
    """The CLIs' shared unwind hook: reason tags the exception type and
    the detail is bounded, with the ring preserved."""
    rec = FlightRecorder(path=tmp_path / "fr.jsonl")
    _record_rows(rec, [0.5])
    try:
        raise ValueError("boom " + "x" * 600)
    except ValueError as e:
        assert rec.dump_exception(e)
    manifests, rings = _read(rec.path)
    assert manifests[0]["reason"] == "exception:ValueError"
    assert len(manifests[0]["detail"]) == 500
    assert [r["step"] for r in rings] == [0]


def test_dump_rate_limit(tmp_path):
    rec = FlightRecorder(path=tmp_path / "fr.jsonl", max_dumps=2)
    for i in range(5):
        assert rec.dump("manual", i) == (i < 2)
    manifests, _ = _read(rec.path)
    assert len(manifests) == 2


def test_end_to_end_through_train_loop(tmp_path):
    """run_train_loop + TrainObserver: an update that goes NaN mid-run
    triggers the dump with no CLI involvement, and the ring holds the
    healthy steps leading up to it."""
    import jax

    from rl_scheduler_tpu.agent.loop import run_train_loop

    rec = FlightRecorder(path=tmp_path / "fr.jsonl", capacity=16)

    @jax.jit
    def update(state):
        i = state["i"]
        loss = jnp.where(i >= 5, jnp.float32(jnp.nan), 1.0 / (1.0 + i))
        return {"i": i + 1}, {"loss": loss, "grad_norm": jnp.float32(1.0)}

    run_train_loop(update, {"i": jnp.float32(0)}, 0, 8,
                   observer=TrainObserver(recorder=rec))
    manifests, rings = _read(rec.path)
    assert manifests[0]["reason"] == "nan_inf"
    assert manifests[0]["iteration"] == 5
    # Healthy prefix preserved; the poisoned step itself is in the ring
    # too (it was dispatched before detection).
    by_step = {r["step"]: r for r in rings}
    assert by_step[4]["loss"] == pytest.approx(0.2)
    assert isinstance(by_step[5]["loss"], str) and \
        math.isnan(float(by_step[5]["loss"]))
