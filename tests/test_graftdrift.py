"""graftdrift (scheduler/drift.py): online distribution-shift
observability.

Pinned at three levels: pure-function tests for the sketch/score math
(bucket edges, PSI/KS semantics, the ``compute_burn`` delegation that
makes ``drifting`` a two-window verdict), in-process policy tests for
the serving-path wiring (one observation per served decision recorded
in ``_record_trace``, synthetic traffic excluded everywhere, shadow
scoring with bitwise-zero effect on served decisions), and a forked
2-worker pool drill (``make drift-drill``): a price-replay regime flip
mid-soak flips ``*_drifting`` within the short window while the
stationary control soak never alarms. Merge discipline follows the
repo rule — counts sum, distances recompute — and is pinned
fleet-merged == union-of-workers through PR 17's pseudo-worker
machinery.
"""

import json
import math
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import os
import pytest

from rl_scheduler_tpu.scheduler import drift as drift_mod
from rl_scheduler_tpu.scheduler.drift import (
    ACTION_CATEGORIES,
    STREAMS,
    UNIT_EDGES,
    DriftConfig,
    DriftTracker,
    ShadowScorer,
    bucket_index,
    build_reference,
    compute_scores,
    config_from_snapshot,
    drift_metric_lines,
    ks,
    load_reference,
    merge_snapshots,
    psi,
    reference_fingerprint,
    reference_from_trace,
    save_reference,
    shadow_metric_lines,
    stream_size,
    sum_shadow,
)
from rl_scheduler_tpu.scheduler.extender import ExtenderPolicy
from rl_scheduler_tpu.scheduler.fleet import (
    aggregate_fleet_metrics,
    aggregate_fleet_stats,
)
from rl_scheduler_tpu.scheduler.policy_backend import GreedyBackend
from rl_scheduler_tpu.scheduler.pool import (
    PoolShared,
    ServingPool,
    aggregate_metrics,
    aggregate_stats,
    merge_worker_drift,
    sum_worker_shadow,
    worker_snapshot,
)
from rl_scheduler_tpu.scheduler.slo import SloConfig, SloTracker
from rl_scheduler_tpu.scheduler.telemetry import RandomCpu, TableTelemetry
from rl_scheduler_tpu.scheduler.tracelog import (
    SYNTHETIC_ENDPOINTS,
    TraceLog,
    decision_record,
    is_synthetic_endpoint,
)
from rl_scheduler_tpu.utils.retry import RetryPolicy

needs_fork = pytest.mark.skipif(
    not hasattr(os, "fork"), reason="graftserve pools require fork"
)

FAST_RESTARTS = RetryPolicy(max_attempts=5, base_delay_s=0.05,
                            max_delay_s=0.2, jitter=0.0)


class _Clock:
    """Injectable monotonic clock for the ring-window tests."""

    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _tracker(clock=None, **overrides):
    cfg = dict(threshold=0.2, fast_window_s=1.0, slow_window_s=4.0,
               min_window_count=1, bucket_s=0.5)
    cfg.update(overrides)
    return DriftTracker(DriftConfig(**cfg), clock=clock or _Clock())


def _filter_args(i=0):
    return {"nodenames": [f"aws-w{i}", f"azure-w{i}"], "pod": {}}


def _policy(drift=True, shadow_fn=None):
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))
    policy = ExtenderPolicy(GreedyBackend(), telemetry)
    if drift:
        policy.drift = DriftTracker(DriftConfig())
    if shadow_fn is not None:
        policy.shadow = ShadowScorer(shadow_fn)
    return policy


# ---------------------------------------------------------- sketch math


def test_bucket_index_edges_and_stream_size():
    """Numeric streams clamp into [0, NUM_BINS-1] over the unit edges;
    non-finite values land nowhere (None, never a silent zero bucket);
    the categorical stream maps unknown clouds to its 'unknown' tail."""
    assert stream_size("score") == len(UNIT_EDGES) + 1
    assert stream_size("action") == len(ACTION_CATEGORIES)
    assert bucket_index("score", -5.0) == 0
    assert bucket_index("score", 0.0) == 0
    assert bucket_index("score", 5.0) == len(UNIT_EDGES)
    # interior edges are half-open on the left (bisect_right)
    assert bucket_index("cost", UNIT_EDGES[0]) == 1
    assert bucket_index("latency", float("nan")) is None
    assert bucket_index("score", float("inf")) is None
    assert bucket_index("score", "not-a-number") is None
    assert bucket_index("action", "aws") == 0
    assert bucket_index("action", "azure") == 1
    assert bucket_index("action", "gcp") == ACTION_CATEGORIES.index(
        "unknown")


def test_psi_ks_distance_semantics():
    """PSI/KS contract: None with an empty reference (no basis to
    grade), 0.0 with an empty live side (no evidence of movement), ~0
    for identical distributions, large for disjoint ones."""
    same = [10, 20, 30, 40]
    assert psi(same, same) == pytest.approx(0.0, abs=1e-9)
    assert ks(same, same) == pytest.approx(0.0, abs=1e-9)
    assert psi(same, [0, 0, 0, 0]) is None
    assert ks(same, [0, 0, 0, 0]) is None
    assert psi([0, 0, 0, 0], same) == 0.0
    assert ks([0, 0, 0, 0], same) == 0.0
    disjoint = psi([100, 0, 0, 0], [0, 0, 0, 100])
    assert disjoint > 10.0
    assert ks([100, 0, 0, 0], [0, 0, 0, 100]) == pytest.approx(1.0)
    # scale-invariant: x10 the counts on either side, same distances
    assert psi([1, 3], [3, 1]) == pytest.approx(psi([10, 30], [30, 10]))
    assert ks([1, 3], [3, 1]) == pytest.approx(ks([10, 30], [30, 10]))


def test_drift_config_validation_and_bucket_default():
    with pytest.raises(ValueError):
        DriftConfig(threshold=0.0)
    with pytest.raises(ValueError):
        DriftConfig(fast_window_s=600.0, slow_window_s=60.0)
    with pytest.raises(ValueError):
        DriftConfig(min_window_count=0)
    with pytest.raises(ValueError):
        DriftConfig(bucket_s=120.0)  # longer than the fast window
    cfg = DriftConfig(fast_window_s=60.0, slow_window_s=600.0)
    assert cfg.ring_bucket_s == pytest.approx(1.0)  # clamped to 1 s
    assert DriftConfig(fast_window_s=1.0, slow_window_s=3.0) \
        .ring_bucket_s == pytest.approx(0.125)
    rt = config_from_snapshot({"config": cfg.to_dict()})
    assert rt.threshold == cfg.threshold
    assert rt.bucket_s == cfg.ring_bucket_s


def _stream_entry(fast_counts, slow_counts, fast_s=60.0, slow_s=600.0):
    return {
        "windows_raw": {
            "fast": {"seconds": fast_s, "counts": list(fast_counts)},
            "slow": {"seconds": slow_s, "counts": list(slow_counts)},
        },
        "lifetime": {"count": sum(slow_counts),
                     "counts": list(slow_counts)},
        "edges": list(UNIT_EDGES),
    }


def test_compute_scores_two_window_burn_delegation():
    """The drifting verdict IS slo.compute_burn's: burn_rate per window
    equals min(psi/threshold, 8.0) and ``drifting`` requires BOTH
    windows over the threshold — a fast-window blip with a clean slow
    window never alarms, and a near-empty window (< min_window_count)
    contributes zero burn regardless of its PSI."""
    size = stream_size("cost")
    ref_counts = [0] * size
    ref_counts[2] = 400
    reference = {"schema": 1, "generation": 0,
                 "streams": {"cost": {"counts": ref_counts,
                                      "count": 400}}}
    cfg = DriftConfig(threshold=0.2, min_window_count=20)
    shifted = [0] * size
    shifted[10] = 100
    matching = [0] * size
    matching[2] = 100

    both = compute_scores(cfg, {"cost": _stream_entry(shifted, shifted)},
                          reference, generation=0)["cost"]
    assert both["status"] == "ok"
    assert both["drifting"] is True
    for w in ("fast", "slow"):
        assert both["psi"][w] > cfg.threshold
        assert both["burn"][w] == pytest.approx(
            min(both["psi"][w] / cfg.threshold, 8.0), rel=1e-3)
        assert both["windows"][w]["sufficient"]

    blip = compute_scores(cfg, {"cost": _stream_entry(shifted, matching)},
                          reference, generation=0)["cost"]
    assert blip["psi"]["fast"] > cfg.threshold
    assert blip["psi"]["slow"] == pytest.approx(0.0, abs=1e-6)
    assert blip["drifting"] is False

    thin = [0] * size
    thin[10] = 5  # fully shifted but under min_window_count
    starved = compute_scores(cfg, {"cost": _stream_entry(thin, thin)},
                             reference, generation=0)["cost"]
    assert starved["windows"]["fast"]["sufficient"] is False
    assert starved["burn"]["fast"] == 0.0
    assert starved["drifting"] is False

    no_ref = compute_scores(cfg, {"cost": _stream_entry(shifted, shifted)},
                            None, generation=0)["cost"]
    assert no_ref["status"] == "no_reference"
    assert no_ref["psi"]["fast"] is None
    assert no_ref["drifting"] is False

    skew = compute_scores(cfg, {"cost": _stream_entry(shifted, shifted)},
                          reference, generation=3)["cost"]
    assert skew["status"] == "generation_mismatch"
    assert skew["psi"]["fast"] is None


# ------------------------------------------------------------ the tracker


def test_tracker_ring_windows_expire_lifetime_monotonic():
    clock = _Clock()
    tracker = _tracker(clock)
    for _ in range(5):
        tracker.observe_decision("aws", 0.5, cost=0.2, latency=0.3)
    snap = tracker.snapshot()
    for name in STREAMS:
        raw = snap["streams"][name]["windows_raw"]
        assert sum(raw["fast"]["counts"]) == 5
        assert sum(raw["slow"]["counts"]) == 5
        assert snap["streams"][name]["lifetime"]["count"] == 5

    clock.advance(2.0)  # past the 1 s fast window, inside the slow
    snap = tracker.snapshot()
    raw = snap["streams"]["score"]["windows_raw"]
    assert sum(raw["fast"]["counts"]) == 0
    assert sum(raw["slow"]["counts"]) == 5

    clock.advance(10.0)  # past the slow window: ring empty, lifetime not
    snap = tracker.snapshot()
    raw = snap["streams"]["score"]["windows_raw"]
    assert sum(raw["slow"]["counts"]) == 0
    assert snap["streams"]["score"]["lifetime"]["count"] == 5


def test_tracker_welford_moments_and_optional_features():
    """Numeric lifetime carries Welford mean/std/min/max; a None
    feature (a family whose observation has no such column) skips that
    stream entirely — never a zero-fill; an unknown cloud lands in the
    categorical 'unknown' tail."""
    tracker = _tracker()
    for v in (0.2, 0.4, 0.6):
        tracker.observe_decision("gcp-onprem-3", v, cost=None, latency=v)
    snap = tracker.snapshot()
    life = snap["streams"]["score"]["lifetime"]
    assert life["mean"] == pytest.approx(0.4)
    assert life["min"] == 0.2 and life["max"] == 0.6
    assert life["std"] == pytest.approx(math.sqrt(0.08 / 3), rel=1e-4)
    assert snap["streams"]["cost"]["lifetime"]["count"] == 0
    assert snap["streams"]["latency"]["lifetime"]["count"] == 3
    action = snap["streams"]["action"]
    unknown = ACTION_CATEGORIES.index("unknown")
    assert action["lifetime"]["counts"][unknown] == 3


def test_merge_snapshots_counts_sum_closed_under_merge():
    """The repo's merge discipline: bucket counts and lifetime counters
    sum, Welford moments merge with Chan's formula, distances recompute
    from the sums. The output is snapshot-shaped, so the fleet re-merge
    of pool sections equals one flat merge over every worker (closed
    under merge); absent sections contribute nothing."""
    clock = _Clock()
    a, b, c = (_tracker(clock) for _ in range(3))
    for _ in range(3):
        a.observe_decision("aws", 0.1, cost=0.2, latency=0.2)
    for _ in range(5):
        b.observe_decision("azure", 0.9, cost=0.8, latency=0.8)
    c.observe_decision("aws", 0.5, cost=0.5, latency=0.5)

    merged = merge_snapshots([a.snapshot(), b.snapshot()])
    act = merged["streams"]["action"]["lifetime"]
    assert act["counts"][:2] == [3, 5]
    cost = merged["streams"]["cost"]["lifetime"]
    assert cost["count"] == 8
    assert cost["mean"] == pytest.approx((3 * 0.2 + 5 * 0.8) / 8)
    assert cost["min"] == 0.2 and cost["max"] == 0.8

    flat = merge_snapshots([a.snapshot(), b.snapshot(), c.snapshot()])
    nested = merge_snapshots([merged, c.snapshot()])
    for name in STREAMS:
        assert nested["streams"][name]["lifetime"]["counts"] \
            == flat["streams"][name]["lifetime"]["counts"]
        assert nested["streams"][name]["windows_raw"]["fast"]["counts"] \
            == flat["streams"][name]["windows_raw"]["fast"]["counts"]
    assert nested["streams"]["cost"]["lifetime"]["mean"] \
        == pytest.approx(flat["streams"]["cost"]["lifetime"]["mean"])

    assert merge_snapshots([None, {}, None]) is None
    # a worker without a drift section contributes NOTHING
    solo = merge_snapshots([a.snapshot(), None])
    assert solo["streams"]["cost"]["lifetime"]["count"] == 3


def test_merge_snapshots_mixed_references_visible():
    clock = _Clock()
    a, b = _tracker(clock), _tracker(clock)
    a.observe_decision("aws", 0.5, cost=0.5, latency=0.5)
    b.observe_decision("aws", 0.5, cost=0.5, latency=0.5)
    ref_a = build_reference(a.snapshot(), source="a")
    a.set_reference(ref_a)
    b.observe_decision("azure", 0.9, cost=0.9, latency=0.9)
    b.set_reference(build_reference(b.snapshot(), source="b"))
    merged = merge_snapshots([a.snapshot(), b.snapshot()])
    # a mid-roll reference swap must be VISIBLE, never averaged away
    assert merged["reference_mixed"] is True
    same = merge_snapshots([a.snapshot(), a.snapshot()])
    assert "reference_mixed" not in same
    assert same["reference"]["fingerprint"] == ref_a["fingerprint"]


# ------------------------------------------------------------- references


def test_reference_roundtrip_fingerprint_and_tamper(tmp_path):
    tracker = _tracker()
    for _ in range(10):
        tracker.observe_decision("aws", 0.3, cost=0.3, latency=0.3)
    ref = build_reference(tracker.snapshot(), source="test")
    assert ref["fingerprint"] == reference_fingerprint(ref)
    # content-addressed: re-capturing identical counts => identical
    # fingerprint, provenance fields don't participate
    again = build_reference(tracker.snapshot(), source="elsewhere")
    assert again["fingerprint"] == ref["fingerprint"]

    path = tmp_path / "reference.json"
    save_reference(str(path), ref)
    loaded = load_reference(str(path))
    assert loaded == ref

    tampered = dict(ref)
    tampered["streams"] = dict(ref["streams"])
    score = dict(ref["streams"]["score"])
    score["counts"] = [c + 1 for c in score["counts"]]
    tampered["streams"]["score"] = score
    bad = tmp_path / "tampered.json"
    bad.write_text(json.dumps(tampered))
    with pytest.raises(ValueError, match="fingerprint"):
        load_reference(str(bad))
    notref = tmp_path / "notref.json"
    notref.write_text(json.dumps({"schema": 99}))
    with pytest.raises(ValueError, match="schema"):
        load_reference(str(notref))


def _write_trace(trace_dir, records):
    log = TraceLog(trace_dir, prefix="w0-")
    for record in records:
        assert log.append(record)
    log.close()


def _trace_record(endpoint="extender", score=0.4, chosen="aws",
                  generation=0, fail_open=False):
    return decision_record(
        endpoint=endpoint, family="cloud", backend="greedy", candidates=2,
        chosen=chosen, score=score, latency_ms=1.0,
        generation=generation, fail_open=fail_open)


def test_reference_from_trace_newest_generation_excludes_synthetic(
        tmp_path):
    """The eval-corpus path: only the NEWEST generation with scorable
    records is frozen, probe/shadow records and fail-opens are excluded,
    and a trace with nothing scorable refuses loudly."""
    trace = tmp_path / "trace"
    _write_trace(trace, [
        _trace_record(generation=0, score=0.2),
        _trace_record(generation=1, score=0.4),
        _trace_record(generation=1, score=0.4, chosen="azure"),
        _trace_record(generation=1, endpoint="probe", score=0.9),
        _trace_record(generation=1, endpoint="shadow", score=0.9),
        _trace_record(generation=1, fail_open=True, score=None,
                      chosen=None),
    ])
    ref = reference_from_trace(str(trace))
    assert ref["generation"] == 1
    assert ref["streams"]["score"]["count"] == 2  # synthetic excluded
    assert ref["streams"]["action"]["counts"][:2] == [1, 1]
    assert ref["fingerprint"] == reference_fingerprint(ref)

    empty = tmp_path / "empty"
    _write_trace(empty, [_trace_record(endpoint="probe"),
                         _trace_record(fail_open=True, score=None,
                                       chosen=None)])
    with pytest.raises(ValueError, match="no scorable"):
        reference_from_trace(str(empty))


def test_drift_snapshot_cli(tmp_path, capsys):
    """``python -m rl_scheduler_tpu.scheduler.drift snapshot``: freezes
    a fingerprint-verified reference from a /stats body (file or URL)
    or a trace dir; refuses a statsless/empty server with exit 2."""
    tracker = _tracker()
    for _ in range(5):
        tracker.observe_decision("aws", 0.3, cost=0.3, latency=0.3)
    stats = tmp_path / "stats.json"
    stats.write_text(json.dumps({"backend": "greedy",
                                 "drift": tracker.snapshot()}))
    out = tmp_path / "ref.json"
    assert drift_mod.main(["snapshot", "--stats", str(stats),
                           "--out", str(out)]) == 0
    ref = load_reference(str(out))
    assert ref["streams"]["score"]["count"] == 5
    assert ref["source"] == f"stats:{stats}"

    nodrift = tmp_path / "nodrift.json"
    nodrift.write_text(json.dumps({"backend": "greedy"}))
    assert drift_mod.main(["snapshot", "--stats", str(nodrift),
                           "--out", str(out)]) == 2
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"drift": _tracker().snapshot()}))
    assert drift_mod.main(["snapshot", "--stats", str(empty),
                           "--out", str(out)]) == 2

    trace = tmp_path / "trace"
    _write_trace(trace, [_trace_record()])
    out2 = tmp_path / "ref2.json"
    assert drift_mod.main(["snapshot", "--trace", str(trace),
                           "--out", str(out2)]) == 0
    assert load_reference(str(out2))["source"] == f"trace:{trace}"
    capsys.readouterr()


# --------------------------------------------------------- shadow scoring


def test_shadow_scorer_agreement_errors_and_drops():
    seen = []
    scorer = ShadowScorer(lambda obs: (1, 0.9),
                          record_fn=lambda a, s, lat, obs:
                          seen.append((a, s, obs)))
    scorer.submit([0.0], 1, 0.5)   # agrees, delta +0.4
    scorer.submit([0.0], 0, 0.7)   # disagrees, delta +0.2
    assert scorer.drain()
    time.sleep(0.05)
    snap = scorer.snapshot()
    assert snap["submitted_total"] == 2
    assert snap["scored_total"] == 2
    assert snap["agreements_total"] == 1
    assert snap["agreement_rate"] == pytest.approx(0.5)
    assert snap["score_delta"]["mean"] == pytest.approx(0.3)
    assert len(seen) == 2 and seen[0][0] == 1
    scorer.close()

    broken = ShadowScorer(lambda obs: 1 / 0)
    broken.submit([0.0], 0, 0.5)
    broken.drain()
    time.sleep(0.05)
    snap = broken.snapshot()
    assert snap["errors_total"] == 1 and snap["scored_total"] == 0
    assert snap["agreement_rate"] is None
    broken.close()

    gate = threading.Event()

    def _blocked(obs):
        gate.wait(5.0)
        return 0, 0.5

    slow = ShadowScorer(_blocked, queue_size=1)
    for _ in range(4):  # worker holds one, queue holds one, rest drop
        slow.submit([0.0], 0, 0.5)
    dropped = slow.snapshot()["dropped_total"]
    assert dropped >= 2  # the serving side NEVER blocked
    gate.set()
    slow.drain()
    slow.close()
    assert slow.snapshot()["submitted_total"] == 4


def test_sum_shadow_counters_sum_rate_recomputes():
    a = {"submitted_total": 10, "scored_total": 8, "dropped_total": 2,
         "errors_total": 0, "agreements_total": 8,
         "score_delta": {"counts": [8, 0, 0], "sum": 0.8}}
    b = {"submitted_total": 4, "scored_total": 2, "dropped_total": 0,
         "errors_total": 1, "agreements_total": 0,
         "score_delta": {"counts": [0, 2, 0], "sum": -0.2}}
    merged = sum_shadow([a, b, None])
    assert merged["scored_total"] == 10
    assert merged["agreements_total"] == 8
    assert merged["agreement_rate"] == pytest.approx(0.8)
    assert merged["score_delta"]["counts"][:3] == [8, 2, 0]
    assert merged["score_delta"]["mean"] == pytest.approx(0.06)
    assert sum_shadow([None, {}]) is None


# ----------------------------------------------- serving-path wiring


def test_policy_records_drift_in_record_trace_reset_never_rewinds():
    """One drift observation per served decision — recorded in
    ``_record_trace`` so every exclusion (probes, shadow, fail-opens)
    happens in the ONE place the histograms already use — and
    ``/stats/reset`` never rewinds the lifetime sketches (the same
    monotonicity contract as the latency histograms)."""
    policy = _policy()
    n = 12
    for i in range(n):
        policy.filter(_filter_args(i))
    policy.warmup_probe()  # synthetic: must not land in any sketch
    stats = policy.statistics()
    snap = stats["drift"]
    for name in STREAMS:
        assert snap["streams"][name]["lifetime"]["count"] == n
    # flat-family features: cost/latency column means land in [0, 1]
    assert 0.0 <= snap["streams"]["cost"]["lifetime"]["mean"] <= 1.0
    aws, azure = (snap["streams"]["action"]["lifetime"]["counts"][i]
                  for i in range(2))
    assert aws + azure == n

    policy.reset_stats()
    after = policy.statistics()["drift"]
    for name in STREAMS:
        assert after["streams"][name]["lifetime"]["count"] == n

    health = policy.health()
    assert health["status"] == "ok"  # drift is body-only, never liveness
    assert health["drift"]["reference"] is False
    assert set(health["drift"]["statuses"]) == set(STREAMS)
    text = policy.metrics_text()
    assert ('rl_scheduler_extender_drift_observations_total'
            '{stream="score"}') in text
    assert "rl_scheduler_extender_drift_reference 0" in text


def test_synthetic_exclusion_audited_in_one_place(tmp_path):
    """The pinned invariant: every histogram family — e2e latency,
    per-phase spans, SLO counters, drift sketches — excludes
    ``endpoint in SYNTHETIC_ENDPOINTS`` ({probe, shadow}) at record
    time via the shared ``is_synthetic_endpoint`` predicate, so
    count-uniformity closes at exactly the served-request count."""
    assert SYNTHETIC_ENDPOINTS == frozenset({"probe", "shadow"})
    assert is_synthetic_endpoint("probe")
    assert is_synthetic_endpoint("shadow")
    assert not is_synthetic_endpoint("extender")
    assert not is_synthetic_endpoint(None)

    policy = _policy()
    policy.slo = SloTracker(SloConfig(p99_ms=1000.0, availability=0.999))
    policy.trace = TraceLog(tmp_path / "trace", prefix="w0-")
    n = 10
    for i in range(n):
        policy.filter(_filter_args(i))
    for _ in range(3):
        policy.warmup_probe()
    policy.trace.close()
    stats = policy.statistics()
    assert stats["latency"]["lifetime_count"] == n
    for phase, entry in stats["phases"].items():
        assert entry["lifetime_count"] == n, phase
    assert stats["slo"]["lifetime"]["requests_total"] == n
    for name in STREAMS:
        assert stats["drift"]["streams"][name]["lifetime"]["count"] == n

    # trace consumers route through the same predicate: the probes are
    # on disk (tagged) but never replayed/compiled/frozen
    from rl_scheduler_tpu.loopback.compile import usable_records
    from tools.decisionview import load_trace_records
    records, cstats = usable_records(str(tmp_path / "trace"))
    assert cstats["probes_excluded"] == 3
    assert all(not is_synthetic_endpoint(r.get("endpoint"))
               for r in records)
    served = load_trace_records(str(tmp_path / "trace"))
    assert len(served) == n
    both = load_trace_records(str(tmp_path / "trace"), include_probes=True)
    assert len(both) == n + 3


def _shadow_greedy(obs):
    import numpy as np

    action, logits = GreedyBackend().decide(obs)
    z = logits - logits.max()
    probs = np.exp(z) / np.exp(z).sum()
    return int(action), float(probs[action])


def test_shadow_scoring_zero_effect_on_serving():
    """The acceptance pin: shadow scoring has ZERO effect on served
    decisions, SLO counters, and phase count-uniformity — a shadowed
    policy and a shadow-off twin fed the identical request sequence
    produce bitwise-identical decisions and counters, while the shadow
    side actually scored (agreement 1.0: greedy judging greedy)."""
    shadowed = _policy(drift=False, shadow_fn=_shadow_greedy)
    plain = _policy(drift=False)
    shadowed.slo = SloTracker(SloConfig(p99_ms=1000.0))
    plain.slo = SloTracker(SloConfig(p99_ms=1000.0))
    n = 16
    results = [(shadowed.filter(_filter_args(i)),
                plain.filter(_filter_args(i))) for i in range(n)]
    for with_shadow, without in results:
        assert with_shadow == without
    s_stats, p_stats = shadowed.statistics(), plain.statistics()
    assert s_stats["decisions"] == p_stats["decisions"]
    assert s_stats["choice_fractions"] == p_stats["choice_fractions"]
    assert s_stats["fail_open_total"] == p_stats["fail_open_total"]
    assert s_stats["latency"]["lifetime_count"] \
        == p_stats["latency"]["lifetime_count"] == n
    for phase in s_stats["phases"]:
        assert s_stats["phases"][phase]["lifetime_count"] \
            == p_stats["phases"][phase]["lifetime_count"] == n
    assert s_stats["slo"]["lifetime"] == p_stats["slo"]["lifetime"]
    assert "shadow" not in p_stats

    assert shadowed.shadow.drain()
    time.sleep(0.05)
    shadow = shadowed.statistics()["shadow"]
    assert shadow["submitted_total"] == n
    assert shadow["scored_total"] == n
    assert shadow["agreement_rate"] == pytest.approx(1.0)
    assert shadow["score_delta"]["mean"] == pytest.approx(0.0, abs=1e-9)
    text = shadowed.metrics_text()
    assert f"rl_scheduler_extender_shadow_scored_total {n}" in text
    assert "rl_scheduler_extender_shadow_agreement 1.0" in text
    shadowed.shadow.close()


# ----------------------------------------------------------- expositions


def test_metric_lines_exposition():
    tracker = _tracker()
    tracker.observe_decision("aws", 0.5, cost=0.5, latency=0.5)
    no_ref = "\n".join(drift_metric_lines("rl", tracker.snapshot()))
    assert "rl_drift_reference 0" in no_ref
    assert 'rl_drifting{stream="score"} 0' in no_ref
    assert 'rl_drift_observations_total{stream="cost"} 1' in no_ref

    ref = build_reference(tracker.snapshot())
    tracker.set_reference(ref)
    text = "\n".join(drift_metric_lines("rl", tracker.snapshot()))
    fp = ref["fingerprint"][:12]
    assert f'rl_drift_reference{{fingerprint="{fp}",generation="0"}} 1' \
        in text
    assert 'stream="score",window="fast",kind="psi"' in text

    shadow = "\n".join(shadow_metric_lines(
        "rl", {"scored_total": 4, "agreements_total": 3,
               "agreement_rate": 0.75, "score_delta": {"mean": -0.01}}))
    assert "rl_shadow_scored_total 4" in shadow
    assert "rl_shadow_agreement 0.75" in shadow
    assert "rl_shadow_score_delta_mean -0.01" in shadow
    idle = "\n".join(shadow_metric_lines("rl", {}))
    assert "rl_shadow_agreement -1" in idle


# -------------------------------------------------- pool + fleet merges


def _drift_worker(worker_id, clouds_costs, reference=None, shadow=None):
    """A real policy snapshot with a drift section fed a known mix."""
    shared = PoolShared()
    telemetry = TableTelemetry.from_table(
        cpu_source=RandomCpu(seed=0), counter=shared.table_counter)
    policy = ExtenderPolicy(GreedyBackend(), telemetry)
    policy.drift = DriftTracker(DriftConfig())
    for cloud, cost in clouds_costs:
        policy.drift.observe_decision(cloud, 0.5, cost=cost, latency=cost)
    if reference is not None:
        policy.drift.set_reference(reference)
    if shadow is not None:
        policy.shadow = shadow
    return worker_snapshot(policy, worker_id)


def test_pool_merge_worker_drift_and_shadow_sections():
    """merge_worker_drift/sum_worker_shadow are drift's merges lifted
    over worker snapshots; aggregate_stats carries the sections and
    aggregate_metrics exports them; workers (or whole pools) without
    the sections contribute nothing — never a zero-fill."""
    snap_a = _drift_worker(0, [("aws", 0.2)] * 3)
    snap_b = _drift_worker(1, [("azure", 0.8)] * 5)
    merged = merge_worker_drift([snap_a, snap_b])
    assert merged["streams"]["cost"]["lifetime"]["count"] == 8
    assert merged["streams"]["action"]["lifetime"]["counts"][:2] == [3, 5]

    plain = {"schema": 1, "worker_id": 2, "pid": 3,
             "stats": {"decisions": {}},
             "histogram": {"cumulative": [], "sum": 0.0, "count": 0}}
    assert merge_worker_drift([plain]) is None
    degraded = merge_worker_drift([snap_a, plain])
    assert degraded["streams"]["cost"]["lifetime"]["count"] == 3

    body = aggregate_stats([snap_a, snap_b], pool={"workers": 2})
    assert body["drift"]["streams"]["cost"]["lifetime"]["count"] == 8
    assert "shadow" not in body
    text = aggregate_metrics([snap_a, snap_b], pool={"workers": 2,
                                                     "alive": 2})
    assert 'drift_observations_total{stream="cost"} 8' in text

    shadow = {"submitted_total": 6, "scored_total": 6, "dropped_total": 0,
              "errors_total": 0, "agreements_total": 6,
              "score_delta": {"counts": [6], "sum": 0.0}}
    snap_c = dict(snap_a)
    snap_c["stats"] = dict(snap_a["stats"])
    snap_c["stats"]["shadow"] = shadow
    assert sum_worker_shadow([snap_a, snap_b]) is None
    pooled = sum_worker_shadow([snap_c, snap_b])
    assert pooled["scored_total"] == 6
    assert pooled["agreement_rate"] == pytest.approx(1.0)


def test_fleet_drift_merge_equals_union_of_workers():
    """Satellite (c): fleet-merged drift over 3 pools x 2 workers ==
    one flat merge over all six worker sections (counts exactly,
    moments to rounding), via PR 17's pseudo-worker machinery; a
    version-skewed pool without a drift section degrades the merge to
    the pools that have one — never zero-fills; the fleet exposition
    carries the drifting gauge."""
    mixes = [[("aws", 0.1)] * 2, [("azure", 0.9)] * 3,
             [("aws", 0.3)] * 4, [("azure", 0.7)] * 1,
             [("aws", 0.5)] * 5, [("azure", 0.5)] * 2]
    all_snaps = [_drift_worker(i % 2, mix) for i, mix in enumerate(mixes)]
    bodies = {
        f"pool{p}": aggregate_stats(all_snaps[2 * p:2 * p + 2],
                                    pool={"workers": 2, "alive": 2})
        for p in range(3)
    }
    fleet_body = aggregate_fleet_stats(bodies, fleet={"generation": 0})
    union = merge_snapshots(
        [s["stats"]["drift"] for s in all_snaps])
    for name in STREAMS:
        assert fleet_body["drift"]["streams"][name]["lifetime"] \
            == union["streams"][name]["lifetime"]
        assert fleet_body["drift"]["streams"][name]["windows_raw"] \
            == union["streams"][name]["windows_raw"]
    assert fleet_body["drift"]["drifting"] == union["drifting"]

    skewed = {k: v for k, v in bodies["pool0"].items() if k != "drift"}
    partial = aggregate_fleet_stats(
        {"old": skewed, "pool1": bodies["pool1"],
         "pool2": bodies["pool2"]}, fleet={})
    expect = merge_snapshots([s["stats"]["drift"] for s in all_snaps[2:]])
    assert partial["drift"]["streams"]["cost"]["lifetime"]["count"] \
        == expect["streams"]["cost"]["lifetime"]["count"]

    text = aggregate_fleet_metrics(bodies, fleet={"pools": 3})
    assert 'drifting{stream="cost"} 0' in text
    assert 'drift_observations_total{stream="action"} 17' in text


# ------------------------------------------------------ the drill (E2E)


_DRILL_TABLES: dict = {}  # set before pool start; forked workers inherit

_DRILL_CONFIG = DriftConfig(threshold=0.2, fast_window_s=1.0,
                            slow_window_s=3.0, min_window_count=10,
                            bucket_s=0.25)


def _drill_factory(worker_id, shared):
    telemetry = TableTelemetry.from_table(
        data_path=_DRILL_TABLES["base"],
        cpu_source=RandomCpu(seed=0), counter=shared.table_counter)
    policy = ExtenderPolicy(GreedyBackend(), telemetry)
    policy.drift = DriftTracker(_DRILL_CONFIG)
    policy.shadow = ShadowScorer(_shadow_greedy)
    return policy


def _write_table(path, cost_aws, cost_azure, lat_aws, lat_azure,
                 rows=32):
    """A normalized replay table with jitter small enough to stay
    inside one drift bucket (width 1/16), so the stationary soak is
    genuinely stationary."""
    lines = ["cost_aws,cost_azure,latency_aws,latency_azure"]
    for i in range(rows):
        j = (i % 8) * 0.001
        lines.append(f"{cost_aws + j:.4f},{cost_azure + j:.4f},"
                     f"{lat_aws + j:.4f},{lat_azure + j:.4f}")
    path.write_text("\n".join(lines) + "\n")


def _load_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "extender_bench",
        Path(__file__).resolve().parents[1] / "loadgen" /
        "extender_bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def _get(port, path, timeout=10):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=timeout) as resp:
        body = resp.read()
    if resp.headers.get("Content-Type", "").startswith("application/json"):
        return json.loads(body)
    return body.decode()


def _post(port, path, payload, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


@needs_fork
def test_drift_drill(tmp_path):
    """``make drift-drill``: a 2-worker pool soaks under price replay;
    a stationary control soak against a frozen reference never alarms
    (and ``driftview --check`` exits 0); a mid-soak regime flip
    (``extender_bench --flip-at/--flip-tables`` swapping the replay
    table through ``POST /telemetry/flip``) flips ``*_drifting`` in
    BOTH burn windows on the feature and action streams (and
    ``driftview --check`` exits 2). Lifetime sketches survive
    ``/stats/reset``; shadow scoring rode along the whole soak with
    perfect agreement and zero serving failures."""
    from tools.driftview.__main__ import main as driftview_main

    base_csv = tmp_path / "base.csv"
    spike_csv = tmp_path / "spike.csv"
    # base: aws clearly cheapest (greedy serves aws); spike: azure
    # cheapest and every cost/latency column shifted ~10 buckets up
    _write_table(base_csv, 0.10, 0.30, 0.20, 0.24)
    _write_table(spike_csv, 0.95, 0.60, 0.90, 0.85)
    _DRILL_TABLES["base"] = str(base_csv)
    budgets = str(Path(__file__).resolve().parents[1] / "tools" /
                  "driftview" / "budgets.json")

    bench = _load_bench()
    pool = ServingPool(_drill_factory, workers=2, host="127.0.0.1",
                       port=0, control_port=0,
                       restart_policy=FAST_RESTARTS,
                       stable_after_s=60.0, poll_interval_s=0.05)
    pool.start(ready_timeout_s=60.0)
    try:
        cport = pool.control_address[1]
        stats_url = f"http://127.0.0.1:{cport}/stats"
        common = ["--port", str(pool.port), "--threads", "4",
                  "--warmup", "5", "--control-port", str(cport)]

        # phase 1: soak the base regime, then freeze the reference
        out1 = bench.main(common + ["--duration", "1.5"])
        assert out1["failures"] == 0
        ref_path = tmp_path / "reference.json"
        assert drift_mod.main(["snapshot", "--stats", stats_url,
                               "--out", str(ref_path)]) == 0
        ref = load_reference(str(ref_path))
        assert set(ref["streams"]) == set(STREAMS)

        # control-plane refusals: a bad reference path / bad table
        # refuses with 409 + errors, a missing body key with 400
        for path, payload, code in (
                ("/drift/reference", {"path": str(tmp_path / "nope")},
                 409),
                ("/telemetry/flip", {"path": str(tmp_path / "nope")},
                 409),
                ("/drift/reference", {}, 400)):
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(cport, path, payload)
            assert err.value.code == code

        resp = _post(cport, "/drift/reference", {"path": str(ref_path)})
        assert resp["status"] == "loaded" and resp["workers"] == 2

        # phase 2: the stationary control — zero drifting transitions
        out2 = bench.main(common + ["--duration", "1.2"])
        assert out2["failures"] == 0
        stats = _get(cport, "/stats")
        drift = stats["drift"]
        assert drift["drifting"] == []
        assert all(s["status"] == "ok" for s in drift["scores"].values())
        assert drift["reference"]["fingerprint"] == ref["fingerprint"]
        assert driftview_main(["--stats", stats_url, "--reference",
                               str(ref_path), "--check", "--budgets",
                               budgets, "--json"]) == 0

        # phase 3: the regime flip mid-soak — post-flip traffic fills
        # both burn windows (slow = 3 s < the 3.5 s post-flip tail)
        out3 = bench.main(common + ["--duration", "4.0",
                                    "--flip-at", "0.5",
                                    "--flip-tables", str(spike_csv)])
        assert out3["failures"] == 0
        assert out3["flip"]["response_code"] == 200
        assert out3["flip"]["response"]["status"] == "flipped"
        assert out3["flip"]["response"]["workers"] == 2
        assert out3["phases"]["pre_flip"]["requests"] > 0
        assert out3["phases"]["post_flip"]["requests"] > 0
        assert out3["flip_at_s"] == pytest.approx(0.5)

        stats = _get(cport, "/stats")
        drift = stats["drift"]
        # every stream moved: the chosen cloud flipped to azure, the
        # feature means jumped ~10 buckets, and the greedy softmax
        # score crossed a bucket edge with the new cost gap
        assert drift["drifting"] == sorted(STREAMS)
        for name in STREAMS:
            score = drift["scores"][name]
            assert score["drifting"] is True, (name, score)
            for w in ("fast", "slow"):
                assert score["burn"][w] >= 1.0
                assert score["windows"][w]["sufficient"]
        metrics = _get(cport, "/metrics")
        assert 'drifting{stream="cost"} 1' in metrics
        assert 'drifting{stream="action"} 1' in metrics
        health = _get(pool.port, "/healthz")
        assert health["drift"]["drifting"] == drift["drifting"]

        # shadow rode the whole soak: scored plenty, agreed perfectly,
        # and the serving side never failed a request (above)
        shadow = stats["shadow"]
        assert shadow["scored_total"] > 0
        assert shadow["agreement_rate"] == pytest.approx(1.0)

        assert driftview_main(["--stats", stats_url, "--reference",
                               str(ref_path), "--check", "--budgets",
                               budgets, "--json"]) == 2

        # /stats/reset fans out but never rewinds the lifetime sketches
        before = drift["streams"]["score"]["lifetime"]["count"]
        _post(cport, "/stats/reset", {})
        after = _get(cport, "/stats")["drift"]
        assert after["streams"]["score"]["lifetime"]["count"] >= before
    finally:
        pool.shutdown()
        _DRILL_TABLES.clear()
