"""Gymnasium adapter: reference API surface parity."""

import numpy as np
import pytest

gym = pytest.importorskip("gymnasium")

from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env.gym_adapter import K8sMultiCloudEnv


@pytest.fixture(scope="module")
def env():
    return K8sMultiCloudEnv(config=EnvConfig(legacy_reward_sign=True))


def test_spaces(env):
    assert env.action_space.n == 2
    assert env.observation_space.shape == (6,)
    assert env.observation_space.dtype == np.float32


def test_reset_step_api(env):
    obs, info = env.reset(seed=42)
    assert obs.shape == (6,) and isinstance(info, dict)
    obs, reward, done, truncated, info = env.step(0)
    assert isinstance(reward, float)
    assert info["chosen_cloud"] == "aws" and info["step"] == 1
    assert truncated is False and done is False
    obs, reward, done, truncated, info = env.step(1)
    assert info["chosen_cloud"] == "azure" and info["step"] == 2


def test_full_episode(env):
    env.reset(seed=0)
    steps = 0
    done = False
    while not done:
        _, _, done, _, _ = env.step(0)
        steps += 1
    assert steps == 99  # reference episode length


def test_reward_matches_reference_row0(env, reference_table):
    env.reset(seed=1)
    _, reward, _, _, _ = env.step(0)
    row = reference_table.iloc[0]
    assert reward == pytest.approx(100 * (0.6 * row["cost_aws"] + 0.4 * row["latency_aws"]), rel=1e-5)


def test_normal_scheduler_step(env):
    obs, _ = env.reset(seed=2)
    a = env.normal_scheduler_step(obs)
    assert a == (0 if obs[0] <= obs[1] else 1)


def test_env_config_dict_respected():
    e = K8sMultiCloudEnv(env_config={"reward_scale": 1.0, "legacy_reward_sign": True})
    e.reset(seed=3)
    _, reward, _, _, _ = e.step(0)
    assert 0 < reward < 1.1  # scale 1 keeps reward within ~[0, 1]


def test_invalid_action_rejected(env):
    env.reset(seed=4)
    with pytest.raises(AssertionError):
        env.step(2)


def test_time_limit_wrapper_compat():
    """The reference's train_and_compare wraps the env in TimeLimit(100)."""
    from gymnasium.wrappers import TimeLimit

    e = TimeLimit(K8sMultiCloudEnv(), max_episode_steps=100)
    obs, _ = e.reset(seed=5)
    done = truncated = False
    steps = 0
    while not (done or truncated):
        _, _, done, truncated, _ = e.step(steps % 2)
        steps += 1
    assert steps == 99  # natural done fires before the 100-step truncation
