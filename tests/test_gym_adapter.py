"""Gymnasium adapter: reference API surface parity."""

import numpy as np
import pytest

gym = pytest.importorskip("gymnasium")

from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env.gym_adapter import K8sMultiCloudEnv


@pytest.fixture(scope="module")
def env():
    return K8sMultiCloudEnv(config=EnvConfig(legacy_reward_sign=True))


def test_spaces(env):
    assert env.action_space.n == 2
    assert env.observation_space.shape == (6,)
    assert env.observation_space.dtype == np.float32


def test_reset_step_api(env):
    obs, info = env.reset(seed=42)
    assert obs.shape == (6,) and isinstance(info, dict)
    obs, reward, done, truncated, info = env.step(0)
    assert isinstance(reward, float)
    assert info["chosen_cloud"] == "aws" and info["step"] == 1
    assert truncated is False and done is False
    obs, reward, done, truncated, info = env.step(1)
    assert info["chosen_cloud"] == "azure" and info["step"] == 2


def test_full_episode(env):
    env.reset(seed=0)
    steps = 0
    done = False
    while not done:
        _, _, done, _, _ = env.step(0)
        steps += 1
    assert steps == 99  # reference episode length


def test_reward_matches_reference_row0(env, reference_table):
    env.reset(seed=1)
    _, reward, _, _, _ = env.step(0)
    row = reference_table.iloc[0]
    assert reward == pytest.approx(100 * (0.6 * row["cost_aws"] + 0.4 * row["latency_aws"]), rel=1e-5)


def test_normal_scheduler_step(env):
    obs, _ = env.reset(seed=2)
    a = env.normal_scheduler_step(obs)
    assert a == (0 if obs[0] <= obs[1] else 1)


def test_env_config_dict_respected():
    e = K8sMultiCloudEnv(env_config={"reward_scale": 1.0, "legacy_reward_sign": True})
    e.reset(seed=3)
    _, reward, _, _, _ = e.step(0)
    assert 0 < reward < 1.1  # scale 1 keeps reward within ~[0, 1]


def test_invalid_action_rejected(env):
    env.reset(seed=4)
    with pytest.raises(AssertionError):
        env.step(2)


def test_time_limit_wrapper_compat():
    """The reference's train_and_compare wraps the env in TimeLimit(100)."""
    from gymnasium.wrappers import TimeLimit

    e = TimeLimit(K8sMultiCloudEnv(), max_episode_steps=100)
    obs, _ = e.reset(seed=5)
    done = truncated = False
    steps = 0
    while not (done or truncated):
        _, _, done, truncated, _ = e.step(steps % 2)
        steps += 1
    assert steps == 99  # natural done fires before the 100-step truncation


class TestVectorEnv:
    def test_spaces_and_shapes(self):
        from rl_scheduler_tpu.env.gym_adapter import K8sMultiCloudVectorEnv

        env = K8sMultiCloudVectorEnv(num_envs=5)
        obs, info = env.reset(seed=0)
        assert obs.shape == (5, 6) and obs.dtype == np.float32
        assert env.observation_space.shape == (5, 6)
        obs, rewards, terms, truncs, infos = env.step(np.zeros(5, np.int32))
        assert rewards.shape == (5,) and terms.shape == (5,)
        assert not terms.any() and not truncs.any() and infos == {}

    def test_isinstance_of_gym_vector_env(self):
        import gymnasium as gym

        from rl_scheduler_tpu.env.gym_adapter import K8sMultiCloudVectorEnv

        assert isinstance(K8sMultiCloudVectorEnv(num_envs=2), gym.vector.VectorEnv)

    def test_same_step_autoreset_and_final_observation(self):
        from rl_scheduler_tpu.env import core
        from rl_scheduler_tpu.env.gym_adapter import K8sMultiCloudVectorEnv

        env = K8sMultiCloudVectorEnv(num_envs=3)
        env.reset(seed=1)
        ms = int(env.params.max_steps)
        for t in range(ms):
            obs, rewards, terms, truncs, infos = env.step(np.zeros(3, np.int32))
        assert terms.all()
        # terminal obs = table row at index max_steps; next obs = row 0
        costs = np.asarray(env.params.costs)
        assert infos["_final_obs"].all()
        for i in range(3):
            np.testing.assert_allclose(infos["final_obs"][i][:2], costs[ms])
        np.testing.assert_allclose(obs[:, :2], np.tile(costs[0], (3, 1)))
        # episode continues seamlessly after the same-step reset
        obs2, _, terms2, _, _ = env.step(np.ones(3, np.int32))
        assert not terms2.any()
        np.testing.assert_allclose(obs2[:, :2], np.tile(costs[1], (3, 1)))

    def test_reward_matches_single_env(self):
        from rl_scheduler_tpu.env.gym_adapter import (
            K8sMultiCloudEnv,
            K8sMultiCloudVectorEnv,
        )

        single = K8sMultiCloudEnv()
        single.reset(seed=3)
        vec = K8sMultiCloudVectorEnv(num_envs=4)
        vec.reset(seed=3)
        for action in (0, 1, 0, 1):
            _, r1, *_ = single.step(action)
            _, rv, *_ = vec.step(np.full(4, action, np.int32))
            # rewards are table-deterministic (noise only touches obs dims)
            np.testing.assert_allclose(rv, np.full(4, r1), rtol=1e-6)
