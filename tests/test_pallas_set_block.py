"""Fused whole-network set-transformer kernel (``ops/pallas_set_block.py``).

Parity contract: ``FusedBlockSetPolicy`` computes the IDENTICAL function
to ``SetTransformerPolicy(num_heads=1)`` at fleet node counts — float32
forward AND gradients agree with the flax module on the same parameter
tree (interpret mode on CPU covers the exact kernel code path), so a
checkpoint trained on either path serves and evaluates on the other.
Constraint refusals, the CLI round trip with the ``--resume`` meta
guard, and dp / dp x sp gradient equivalence are pinned here too.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.models import SetTransformerPolicy
from rl_scheduler_tpu.models.set_fast import FusedBlockSetPolicy

FLEET_N = 64


@pytest.fixture(scope="module")
def nets_and_params():
    flax_net = SetTransformerPolicy(dim=64, depth=2, num_heads=1)
    fused_net = FusedBlockSetPolicy(num_nodes=FLEET_N, dim=64, depth=2)
    params = flax_net.init(jax.random.PRNGKey(3),
                           jnp.zeros((1, FLEET_N, 6)))
    return flax_net, fused_net, params


def _ppo_style_loss(apply_fn, obs, act):
    def f(p):
        logits, value = apply_fn(p, obs)
        logp = jax.nn.log_softmax(logits)
        return jnp.mean(jnp.take_along_axis(
            logp, act[:, None], axis=1)) + jnp.mean(value ** 2)
    return f


def test_forward_parity_f32(nets_and_params):
    """fwd <= 1e-5 vs the dense flax module at fleet N, with a batch that
    does NOT divide the kernel's row block (exercises the pad path)."""
    flax_net, fused_net, params = nets_and_params
    obs = jax.random.uniform(jax.random.PRNGKey(1), (5, FLEET_N, 6))
    l0, v0 = flax_net.apply(params, obs)
    l1, v1 = jax.jit(fused_net.apply)(params, obs)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v0),
                               rtol=1e-5, atol=1e-5)


def test_gradient_parity_f32(nets_and_params):
    """grads <= 1e-4 vs the flax module through a PPO-shaped loss —
    the custom-VJP remat backward against flax autodiff."""
    flax_net, fused_net, params = nets_and_params
    obs = jax.random.uniform(jax.random.PRNGKey(2), (6, FLEET_N, 6))
    act = jax.random.randint(jax.random.PRNGKey(4), (6,), 0, FLEET_N)
    g0 = jax.grad(_ppo_style_loss(flax_net.apply, obs, act))(params)
    g1 = jax.grad(_ppo_style_loss(fused_net.apply, obs, act))(params)
    for leaf0, leaf1 in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(leaf1), np.asarray(leaf0),
                                   rtol=1e-4, atol=1e-6)


def test_multi_grid_step_parity_f32(nets_and_params):
    """Forward AND gradients with the batch spanning SEVERAL grid steps
    (block_b=2, batch 5 -> 3 steps incl. a padded one): pins the backward
    kernel's accumulator path — zero-init on program_id 0, += on every
    later step, whole-array acc_spec indexing — which the production
    fleet recipes hit with ~800 grid steps per minibatch but single-block
    batches never touch."""
    flax_net, _, params = nets_and_params
    fused_net = FusedBlockSetPolicy(num_nodes=FLEET_N, dim=64, depth=2,
                                    block_b=2)
    obs = jax.random.uniform(jax.random.PRNGKey(11), (5, FLEET_N, 6))
    act = jax.random.randint(jax.random.PRNGKey(12), (5,), 0, FLEET_N)
    l0, v0 = flax_net.apply(params, obs)
    l1, v1 = jax.jit(fused_net.apply)(params, obs)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v0),
                               rtol=1e-5, atol=1e-5)
    g0 = jax.grad(_ppo_style_loss(flax_net.apply, obs, act))(params)
    g1 = jax.grad(_ppo_style_loss(fused_net.apply, obs, act))(params)
    for leaf0, leaf1 in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(leaf1), np.asarray(leaf0),
                                   rtol=1e-4, atol=1e-6)


def test_bf16_close_to_f32(nets_and_params):
    flax_net, _, params = nets_and_params
    fused_bf16 = FusedBlockSetPolicy(num_nodes=FLEET_N, dim=64, depth=2,
                                     dtype=jnp.bfloat16)
    obs = jax.random.uniform(jax.random.PRNGKey(5), (4, FLEET_N, 6))
    l0, v0 = flax_net.apply(params, obs)
    l1, v1 = fused_bf16.apply(params, obs)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                               rtol=0.05, atol=0.05)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v0),
                               rtol=0.05, atol=0.05)


def test_unbatched_matches_flax(nets_and_params):
    flax_net, fused_net, params = nets_and_params
    obs = jax.random.uniform(jax.random.PRNGKey(6), (FLEET_N, 6))
    l0, v0 = flax_net.apply(params, obs)
    l1, v1 = fused_net.apply(params, obs)
    assert l1.shape == (FLEET_N,) and v1.shape == ()
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(v1), float(v0), rtol=1e-5, atol=1e-5)


def test_permutation_equivariance(nets_and_params):
    """The fused path inherits the flax module's contract: logits
    permutation-equivariant, value permutation-invariant."""
    _, fused_net, params = nets_and_params
    obs = jax.random.uniform(jax.random.PRNGKey(7), (3, FLEET_N, 6))
    perm = jax.random.permutation(jax.random.PRNGKey(8), FLEET_N)
    l0, v0 = fused_net.apply(params, obs)
    l1, v1 = fused_net.apply(params, obs[:, perm])
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0)[:, perm],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v0),
                               rtol=1e-5, atol=1e-5)


def test_constraint_refusals():
    """Shape/dtype guards fire at CONSTRUCTION with actionable messages —
    the kernel must never silently re-enter the measured-bad N=8 regime
    or run at an unsupported precision."""
    from rl_scheduler_tpu.ops.pallas_set_block import make_fused_set_apply

    with pytest.raises(ValueError, match="fleet"):
        make_fused_set_apply(num_nodes=8)       # the deleted-design regime
    with pytest.raises(ValueError, match="fleet"):
        make_fused_set_apply(num_nodes=36)      # not a multiple of 8
    with pytest.raises(ValueError, match="multiple of 8"):
        make_fused_set_apply(num_nodes=64, dim=60)
    with pytest.raises(ValueError, match="float32 or bfloat16"):
        make_fused_set_apply(num_nodes=64, compute_dtype=jnp.float16)


def test_node_count_mismatch_refused(nets_and_params):
    """The kernel is shape-specialized: applying a policy built at N=64
    to a 32-node observation is refused, not silently mis-sliced."""
    _, fused_net, params = nets_and_params
    with pytest.raises(ValueError, match="num_nodes"):
        fused_net.apply(params, jnp.zeros((2, 32, 6)))


def test_multihead_tree_rejected():
    multi = SetTransformerPolicy(dim=64, depth=2, num_heads=4)
    params = multi.init(jax.random.PRNGKey(0), jnp.zeros((1, FLEET_N, 6)))
    fused = FusedBlockSetPolicy(num_nodes=FLEET_N)
    with pytest.raises(ValueError, match="num_heads=4"):
        fused.apply(params, jnp.zeros((2, FLEET_N, 6)))


def test_train_cli_fused_set_block_and_resume_guard(tmp_path):
    """--fused-set-block trains cluster_set end to end at fleet N (tiny
    overrides, interpret mode on CPU), meta records the path, the saved
    tree restores onto the FLAX policy with matching outputs, and a
    resume that silently drops the flag is refused."""
    import json

    from rl_scheduler_tpu.agent import train_ppo as cli
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    common = [
        "--preset", "quick", "--env", "cluster_set", "--num-nodes", "32",
        "--num-envs", "4", "--rollout-steps", "8", "--minibatch-size", "16",
        "--num-epochs", "1", "--checkpoint-every", "1",
        "--run-root", str(tmp_path), "--run-name", "fused_block",
    ]
    run_dir = cli.main(common + ["--fused-set-block", "--iterations", "1"])
    mgr = CheckpointManager(run_dir)
    assert mgr.latest_step() == 1
    meta = mgr.restore_meta(1)
    assert meta["fused_set_block"] is True
    assert meta["num_heads"] == 1
    assert meta["num_nodes"] == 32
    tree, _ = mgr.restore(1)
    mgr.close()
    # Serving/evaluation never need to know which path trained the
    # checkpoint: the saved tree is the flax tree.
    params = {"params": tree["params"]["params"]}
    obs = jax.random.uniform(jax.random.PRNGKey(9), (4, 32, 6))
    l_flax, v_flax = SetTransformerPolicy(
        dim=64, depth=2, num_heads=1).apply(params, obs)
    l_fused, v_fused = FusedBlockSetPolicy(num_nodes=32).apply(params, obs)
    np.testing.assert_allclose(np.asarray(l_fused), np.asarray(l_flax),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(v_fused), np.asarray(v_flax),
                               rtol=1e-5, atol=1e-5)
    records = [json.loads(l) for l in (run_dir / "metrics.jsonl").open()]
    assert all(np.isfinite(r["reward_mean"]) for r in records
               if "reward_mean" in r)

    # The recorded recipe identity must not switch silently on resume.
    with pytest.raises(SystemExit, match="fused-set-block"):
        cli.main(common + ["--iterations", "2", "--resume"])


def test_dp_sp_gradient_equivalence_fused_block():
    """The ISSUE's sharded-path check: the PPO-loss gradient through the
    single-chip fused kernel equals the gradient through the dp x sp
    machinery at fleet N — both the node-axis-sharded flax path
    (SeqParallelNet: ring attention + logits all-gather + pmean'd value
    pool, pmean over sp) and the fused kernel itself run data-parallel
    (per-shard grads pmean'd over dp). One parameter tree, three routes,
    one gradient."""
    from jax.sharding import PartitionSpec as P

    from rl_scheduler_tpu.env import cluster_set
    from rl_scheduler_tpu.parallel import make_mesh
    from rl_scheduler_tpu.parallel.mesh import shard_map_compat
    from rl_scheduler_tpu.parallel.sharding import SeqParallelNet

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")

    num_nodes, feat, batch = 32, cluster_set.NODE_FEAT, 16
    key = jax.random.PRNGKey(2)
    k_obs, k_par, k_act = jax.random.split(key, 3)
    obs = jax.random.uniform(k_obs, (batch, num_nodes, feat), jnp.float32)
    act = jax.random.randint(k_act, (batch,), 0, num_nodes, jnp.int32)
    # dim 16: a multiple of 8 (the kernel's sublane constraint) that keeps
    # the interpret-mode backward fast on CPU.
    flax_net = SetTransformerPolicy(dim=16, depth=2)
    params = flax_net.init(k_par, obs)
    fused_net = FusedBlockSetPolicy(num_nodes=num_nodes, dim=16, depth=2)

    g_ref = jax.grad(_ppo_style_loss(flax_net.apply, obs, act))(params)
    g_fused = jax.grad(_ppo_style_loss(fused_net.apply, obs, act))(params)

    # Route 2: node axis sharded over sp (the flax dp x sp machinery).
    sp_mesh = make_mesh({"sp": 4})
    wrapped = SeqParallelNet(
        SetTransformerPolicy(dim=16, depth=2, axis_name="sp"), "sp", 4)

    def sp_grad(p):
        g = jax.grad(_ppo_style_loss(wrapped.apply, obs, act))(p)
        return jax.lax.pmean(g, "sp")

    g_sp = jax.jit(shard_map_compat(
        sp_grad, sp_mesh, in_specs=(P(),), out_specs=P()))(params)

    # Route 3: the fused kernel itself under dp (batch sharded, grads
    # pmean'd — how --preset set_fleet64 trains it when the TPU
    # auto-selection turns the kernel on).
    dp_mesh = make_mesh({"dp": 4})

    def dp_grad(p, local_obs, local_act):
        g = jax.grad(_ppo_style_loss(fused_net.apply, local_obs,
                                     local_act))(p)
        return jax.lax.pmean(g, "dp")

    g_dp = jax.jit(shard_map_compat(
        dp_grad, dp_mesh, in_specs=(P(), P("dp"), P("dp")),
        out_specs=P()))(params, obs, act)

    for ref, fused, sp, dp in zip(
            jax.tree.leaves(g_ref), jax.tree.leaves(g_fused),
            jax.tree.leaves(g_sp), jax.tree.leaves(g_dp)):
        np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(sp), np.asarray(ref),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(dp), np.asarray(ref),
                                   rtol=1e-4, atol=1e-6)


def test_dp_update_fused_block_finite_and_synced():
    """A full dp-sharded PPO update through the fused kernel (the
    dryrun_multichip family 7 path) stays finite and keeps params
    replicated bit-identical across shards."""
    from rl_scheduler_tpu.agent.ppo import PPOTrainConfig
    from rl_scheduler_tpu.env import cluster_set as cs
    from rl_scheduler_tpu.env.bundle import cluster_set_bundle
    from rl_scheduler_tpu.parallel import (
        make_data_parallel_ppo_bundle,
        make_mesh,
    )

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    if not hasattr(jax, "shard_map"):
        # parallel/sharding.py targets the bench env's JAX (>= 0.5,
        # jax.shard_map); older-JAX containers cover the same numerics
        # through test_dp_sp_gradient_equivalence_fused_block above,
        # which shards via the version-compat helper.
        pytest.skip("library sharding paths need jax.shard_map")

    cfg = PPOTrainConfig(num_envs=8, rollout_steps=8, minibatch_size=16,
                         num_epochs=2, lr=1e-3)
    bundle = cluster_set_bundle(cs.make_params(num_nodes=32))
    net = FusedBlockSetPolicy(num_nodes=32, dim=16, depth=1)
    mesh = make_mesh({"dp": 4})
    init_fn, update_fn, _ = make_data_parallel_ppo_bundle(
        bundle, cfg, mesh, net=net)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    runner, metrics = jax.jit(update_fn)(runner)
    assert np.isfinite(float(metrics["policy_loss"]))
    assert np.isfinite(float(metrics["value_loss"]))
    leaf = jax.tree.leaves(runner.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    assert all(np.array_equal(shards[0], s) for s in shards[1:])


def test_is_fleet_node_count_table():
    """The one shape gate shared by the kernel guard, the train CLI's
    auto-selection, and validation — pin its boundary semantics."""
    from rl_scheduler_tpu.ops.pallas_set_block import (
        MIN_FLEET_NODES,
        is_fleet_node_count,
    )

    assert MIN_FLEET_NODES == 32
    for n, ok in [(8, False), (16, False), (31, False), (32, True),
                  (36, False), (40, True), (64, True), (256, True)]:
        assert is_fleet_node_count(n) is ok, n
