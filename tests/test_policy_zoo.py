"""Set/graph envs + transformer/GNN policies (BASELINE configs 4-5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, make_ppo_bundle, ppo_train
from rl_scheduler_tpu.env import cluster_graph, cluster_set
from rl_scheduler_tpu.env.bundle import cluster_graph_bundle, cluster_set_bundle
from rl_scheduler_tpu.models import GNNPolicy, SetTransformerPolicy

NUM_NODES = 6


@pytest.fixture(scope="module")
def set_params():
    return cluster_set.make_params(num_nodes=NUM_NODES)


@pytest.fixture(scope="module")
def graph_params():
    return cluster_graph.make_params(num_nodes=NUM_NODES)


# ------------------------------------------------------------- set env


def test_set_env_shapes_and_reward_sign(set_params):
    state, obs = cluster_set.reset(set_params, jax.random.PRNGKey(0))
    assert obs.shape == (NUM_NODES, cluster_set.NODE_FEAT)
    state, ts = cluster_set.step(set_params, state, jnp.asarray(2))
    assert ts.obs.shape == (NUM_NODES, cluster_set.NODE_FEAT)
    assert float(ts.reward) < 0  # corrected sign: cost is always penalized
    assert int(ts.chosen_cloud) == 0  # node 2 of 6 -> first half -> aws


def test_set_env_overload_penalized(set_params):
    """Hammering one node must eventually cost more than spreading load."""
    key = jax.random.PRNGKey(1)

    def total_reward(policy):
        state, obs = cluster_set.reset(set_params, key)
        total = 0.0
        for t in range(20):
            state, ts = cluster_set.step(set_params, state, policy(t, obs))
            obs = ts.obs
            total += float(ts.reward)
        return total

    hammer = total_reward(lambda t, obs: jnp.asarray(0))
    spread = total_reward(lambda t, obs: jnp.asarray(t % NUM_NODES))
    assert spread > hammer


def test_set_env_cpu_drains(set_params):
    state, _ = cluster_set.reset(set_params, jax.random.PRNGKey(2))
    state, _ = cluster_set.step(set_params, state, jnp.asarray(3))
    used_after_place = float(state.cpu_used[3])
    assert used_after_place > 0
    for _ in range(30):  # place elsewhere; node 3 load must decay toward 0
        state, _ = cluster_set.step(set_params, state, jnp.asarray(0))
    assert float(state.cpu_used[3]) < used_after_place * 0.1


# ------------------------------------------------------------- graph env


def test_topology_is_connected_and_symmetric():
    cloud, adj, hops = cluster_graph.build_topology(8)
    np.testing.assert_array_equal(adj, adj.T)
    assert np.isfinite(hops).all()
    assert (np.diag(adj) == 0).all()
    assert cloud.sum() == 4
    # cross-cloud traffic goes through gateways: strictly positive hops
    assert hops[1, 5] >= 2  # non-gateway aws -> non-gateway azure


def test_graph_env_locality_matters(graph_params):
    """Placing on the affinity node must beat a farther node of the SAME
    cloud — price held constant, only the hop penalty differs."""
    state, _ = cluster_graph.reset(graph_params, jax.random.PRNGKey(0))
    hops = np.asarray(graph_params.hops)
    clouds = np.asarray(graph_params.cloud_of_node)
    for aff in range(NUM_NODES):  # deterministic over every affinity choice
        forced = state._replace(affinity=jnp.asarray(aff, jnp.int32))
        same_cloud = [
            n for n in range(NUM_NODES)
            if clouds[n] == clouds[aff] and hops[n, aff] > 0
        ]
        far = max(same_cloud, key=lambda n: hops[n, aff])
        _, ts_near = cluster_graph.step(graph_params, forced, jnp.asarray(aff))
        _, ts_far = cluster_graph.step(graph_params, forced, jnp.asarray(far))
        assert float(ts_near.reward) > float(ts_far.reward), aff


def test_graph_env_dollar_cost_in_reward(graph_params):
    """Azure nodes cost ~2x aws (raw prices): same-hops placement on azure
    must be penalized more."""
    state, _ = cluster_graph.reset(graph_params, jax.random.PRNGKey(3))
    # force affinity to the aws gateway (node 0) so hops are symmetric
    # between node 0's neighbors; compare gateway aws (0) vs gateway azure
    state = state._replace(affinity=jnp.asarray(0, jnp.int32))
    half = NUM_NODES // 2
    _, ts_aws = cluster_graph.step(graph_params, state, jnp.asarray(1))
    _, ts_azure = cluster_graph.step(graph_params, state, jnp.asarray(half + 1))
    # node 1 (aws, 1 hop from 0) vs half+1 (azure, >=2 hops + higher price)
    assert float(ts_aws.reward) > float(ts_azure.reward)


# ------------------------------------------------------------- policies


def test_set_transformer_permutation_equivariance():
    net = SetTransformerPolicy(dim=32, depth=2)
    obs = jax.random.uniform(jax.random.PRNGKey(0), (NUM_NODES, cluster_set.NODE_FEAT))
    params = net.init(jax.random.PRNGKey(1), obs)
    logits, value = net.apply(params, obs)
    perm = jax.random.permutation(jax.random.PRNGKey(2), NUM_NODES)
    logits_p, value_p = net.apply(params, obs[perm])
    # logits move with their nodes; value is invariant
    np.testing.assert_allclose(np.asarray(logits)[np.asarray(perm)],
                               np.asarray(logits_p), rtol=2e-4, atol=1e-5)
    assert float(value) == pytest.approx(float(value_p), rel=1e-4)


def test_set_transformer_batched_matches_single():
    net = SetTransformerPolicy(dim=32, depth=1)
    obs = jax.random.uniform(jax.random.PRNGKey(0), (3, NUM_NODES, cluster_set.NODE_FEAT))
    params = net.init(jax.random.PRNGKey(1), obs)
    logits_b, value_b = net.apply(params, obs)
    assert logits_b.shape == (3, NUM_NODES)
    assert value_b.shape == (3,)
    logits_0, value_0 = net.apply(params, obs[0])
    np.testing.assert_allclose(np.asarray(logits_b[0]), np.asarray(logits_0),
                               rtol=1e-5, atol=1e-6)


def test_gnn_messages_follow_topology():
    """One conv layer: perturbing a non-neighbor's features must not change
    a node's embedding-derived logit; perturbing a neighbor must."""
    cloud, adj, hops = cluster_graph.build_topology(NUM_NODES)
    net = GNNPolicy.from_adjacency(adj, dim=16, depth=1)
    obs = jax.random.uniform(jax.random.PRNGKey(0), (NUM_NODES, cluster_graph.NODE_FEAT))
    params = net.init(jax.random.PRNGKey(1), obs)
    logits, _ = net.apply(params, obs)

    # pick (target, non_neighbor) with adj == 0
    target, non_nbr = next(
        (i, j)
        for i in range(NUM_NODES)
        for j in range(NUM_NODES)
        if i != j and adj[i, j] == 0
    )
    obs_far = obs.at[non_nbr].add(1.0)
    logits_far, _ = net.apply(params, obs_far)
    assert float(logits[target]) == pytest.approx(float(logits_far[target]), abs=1e-5)

    nbr = int(np.nonzero(adj[target])[0][0])
    obs_near = obs.at[nbr].add(1.0)
    logits_near, _ = net.apply(params, obs_near)
    assert float(logits[target]) != pytest.approx(float(logits_near[target]), abs=1e-5)


# ------------------------------------------------------------- PPO integration

SMOKE = PPOTrainConfig(
    num_envs=8, rollout_steps=32, minibatch_size=64, num_epochs=2,
    lr=1e-3, entropy_coeff=0.01,
)


def test_ppo_trains_set_transformer(set_params):
    bundle = cluster_set_bundle(set_params)
    net = SetTransformerPolicy(dim=32, depth=1)
    init_fn, update_fn, _ = make_ppo_bundle(bundle, SMOKE, net=net)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    runner, metrics = jax.jit(update_fn)(runner)
    for k in ("policy_loss", "value_loss", "entropy"):
        assert np.isfinite(float(metrics[k])), k


def test_ppo_trains_gnn_and_improves(graph_params):
    bundle = cluster_graph_bundle(graph_params)
    net = GNNPolicy.from_adjacency(np.asarray(graph_params.adjacency), dim=32, depth=2)
    cfg = PPOTrainConfig(
        num_envs=16, rollout_steps=64, minibatch_size=256, num_epochs=4,
        lr=3e-3, entropy_coeff=0.01,
    )
    _, history = ppo_train(bundle, cfg, 12, seed=0, net=net)
    first = history[0]["reward_mean"]
    last = history[-1]["reward_mean"]
    assert last > first, f"GNN PPO failed to improve: {first} -> {last}"


def test_set_and_graph_policies_support_bf16_compute():
    """dtype=bfloat16 keeps params f32 and tracks the f32 forward (the
    compute_dtype knob's documented use for the wide policies)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rl_scheduler_tpu.models.gnn import GNNPolicy
    from rl_scheduler_tpu.models.transformer import SetTransformerPolicy

    obs = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 5))
    tr32 = SetTransformerPolicy(dim=32, depth=1)
    tr16 = SetTransformerPolicy(dim=32, depth=1, dtype=jnp.bfloat16)
    params = tr32.init(jax.random.PRNGKey(1), obs)
    l32, v32 = tr32.apply(params, obs)
    l16, v16 = tr16.apply(params, obs)
    assert l16.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(l16), np.asarray(l32), atol=0.1)
    np.testing.assert_allclose(np.asarray(v16), np.asarray(v32), atol=0.1)

    adj = np.eye(8, dtype=np.float32)
    g32 = GNNPolicy.from_adjacency(adj, dim=16, depth=2)
    g16 = GNNPolicy.from_adjacency(adj, dim=16, depth=2, dtype=jnp.bfloat16)
    params = g32.init(jax.random.PRNGKey(2), obs)
    l32, v32 = g32.apply(params, obs)
    l16, v16 = g16.apply(params, obs)
    assert l16.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(l16), np.asarray(l32), atol=0.1)
    np.testing.assert_allclose(np.asarray(v16), np.asarray(v32), atol=0.1)


@pytest.mark.parametrize("env_name", ["single_cluster", "cluster_set", "cluster_graph"])
def test_train_cli_covers_all_env_families(env_name, tmp_path):
    """--env trains configs 1/4/5 end-to-end through the CLI, checkpoint
    included (multi_cloud is covered by the resume round-trip test)."""
    from rl_scheduler_tpu.agent import train_ppo as cli
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    cli.main([
        "--env", env_name, "--preset", "quick", "--num-envs", "4",
        "--rollout-steps", "8", "--minibatch-size", "16",
        "--iterations", "1", "--checkpoint-every", "1",
        "--run-root", str(tmp_path), "--run-name", env_name,
    ])
    mgr = CheckpointManager(tmp_path / env_name)
    assert mgr.latest_step() == 1
    assert mgr.restore_meta(1)["env"] == env_name
    mgr.close()


def test_train_cli_resume_rejects_env_mismatch(tmp_path):
    from rl_scheduler_tpu.agent import train_ppo as cli

    common = ["--preset", "quick", "--num-envs", "4", "--rollout-steps", "8",
              "--minibatch-size", "16", "--checkpoint-every", "1",
              "--run-root", str(tmp_path), "--run-name", "envmix"]
    cli.main(common + ["--env", "single_cluster", "--iterations", "1"])
    with pytest.raises(SystemExit, match="single_cluster"):
        cli.main(common + ["--env", "cluster_set", "--iterations", "2", "--resume"])


def test_train_cli_rejects_inert_flags_for_structured_envs(tmp_path):
    from rl_scheduler_tpu.agent import train_ppo as cli

    with pytest.raises(SystemExit, match="structured policy"):
        cli.main(["--env", "cluster_set", "--hidden", "512,512",
                  "--run-root", str(tmp_path)])
    with pytest.raises(SystemExit, match="legacy-reward-sign"):
        cli.main(["--env", "single_cluster", "--legacy-reward-sign",
                  "--run-root", str(tmp_path)])


def test_set_cli_num_heads_resume_guard(tmp_path):
    """A run checkpointed with one head count refuses to resume under a
    different one with a friendly message (the default changed 4 -> 1)."""
    from rl_scheduler_tpu.agent import train_ppo as cli

    common = [
        "--env", "cluster_set", "--preset", "quick", "--num-envs", "8",
        "--rollout-steps", "16", "--minibatch-size", "64",
        "--run-root", str(tmp_path), "--run-name", "heads_test",
        "--checkpoint-every", "1",
    ]
    cli.main(common + ["--iterations", "1", "--num-heads", "4"])
    with pytest.raises(SystemExit, match="num_heads"):
        cli.main(common + ["--iterations", "2", "--resume"])
    # matching head count resumes fine
    cli.main(common + ["--iterations", "2", "--resume", "--num-heads", "4"])


def test_num_heads_rejected_for_non_set_envs():
    from rl_scheduler_tpu.agent import train_ppo as cli

    with pytest.raises(SystemExit, match="num-heads"):
        cli.main(["--env", "multi_cloud", "--num-heads", "2",
                  "--iterations", "1"])


def test_num_heads_must_divide_dim():
    from rl_scheduler_tpu.agent import train_ppo as cli

    with pytest.raises(SystemExit, match="divisor"):
        cli.main(["--env", "cluster_set", "--num-heads", "3",
                  "--iterations", "1"])
