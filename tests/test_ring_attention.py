"""Ring attention: exact equivalence with dense attention, and the full
sequence-parallel set-transformer forward matching the single-chip one.

Runs on the 8 virtual CPU devices from conftest; the same code rides ICI
on a real TPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from jax import shard_map

from rl_scheduler_tpu.models.transformer import SetTransformerPolicy
from rl_scheduler_tpu.parallel import make_mesh, ring_attention
from rl_scheduler_tpu.parallel.ring_attention import make_flax_attention_fn

B, N, H, D = 2, 32, 4, 16


@pytest.fixture(scope="module")
def qkv():
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(
        jax.random.normal(k, (B, N, H, D), jnp.float32) for k in keys
    )


def test_ring_matches_dense_on_mesh(qkv):
    q, k, v = qkv
    dense = ring_attention(q, k, v, axis_name=None)
    mesh = make_mesh({"sp": 8})
    spec = P(None, "sp", None, None)
    ringed = jax.jit(
        shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis_name="sp"),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(ringed), np.asarray(dense),
                               rtol=1e-5, atol=1e-5)


def test_ring_size_one_is_dense(qkv):
    q, k, v = qkv
    mesh = make_mesh({"sp": 1})
    out = jax.jit(
        shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis_name="sp"),
            mesh=mesh,
            in_specs=(P(), P(), P()),
            out_specs=P(),
        )
    )(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ring_attention(q, k, v)), rtol=1e-6
    )


def test_flax_attention_fn_rejects_mask(qkv):
    q, k, v = qkv
    fn = make_flax_attention_fn(None)
    with pytest.raises(NotImplementedError):
        fn(q, k, v, mask=jnp.ones((B, 1, N, N), bool))
    with pytest.raises(NotImplementedError):
        fn(q, k, v, dropout_rate=0.1)
    np.testing.assert_allclose(
        np.asarray(fn(q, k, v)), np.asarray(ring_attention(q, k, v)), rtol=1e-6
    )


def test_sequence_parallel_policy_matches_single_chip():
    """Full forward: params from the single-chip module drive the sharded
    module bit-compatibly (identical param shapes by construction)."""
    feat, nodes = 6, 16
    obs = jax.random.normal(jax.random.PRNGKey(1), (B, nodes, feat))
    single = SetTransformerPolicy(dim=32, depth=2, num_heads=4)
    params = single.init(jax.random.PRNGKey(2), obs)
    logits_ref, value_ref = single.apply(params, obs)

    mesh = make_mesh({"sp": 8})
    sharded = SetTransformerPolicy(dim=32, depth=2, num_heads=4, axis_name="sp")

    logits_sp, value_sp = jax.jit(
        shard_map(
            lambda p, o: sharded.apply(p, o),
            mesh=mesh,
            in_specs=(P(), P(None, "sp", None)),
            out_specs=(P(None, "sp"), P()),
        )
    )(params, obs)

    np.testing.assert_allclose(np.asarray(logits_sp), np.asarray(logits_ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(value_sp), np.asarray(value_ref),
                               rtol=2e-5, atol=2e-5)


def test_distributed_noop_without_coordinates(monkeypatch):
    from rl_scheduler_tpu.parallel import maybe_initialize_distributed

    for var in ("RL_SCHED_COORDINATOR", "TPU_WORKER_HOSTNAMES",
                "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    assert maybe_initialize_distributed() is False


def test_distributed_incomplete_triple_raises(monkeypatch):
    from rl_scheduler_tpu.parallel import maybe_initialize_distributed

    monkeypatch.setenv("RL_SCHED_COORDINATOR", "localhost:9999")
    monkeypatch.delenv("RL_SCHED_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("RL_SCHED_PROCESS_ID", raising=False)
    with pytest.raises(ValueError, match="RL_SCHED_NUM_PROCESSES"):
        maybe_initialize_distributed()
