"""tools/decisionview: the graftlens serving perf report and its
regression gates, exercised off-network against the checked-in fixture
(a REAL numpy-set policy's /stats body, trace segments, and a 3-round
bench ledger — tests/fixtures/decisionview/)."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from tools.decisionview import (
    MIN_PHASE_COVERAGE,
    build_report,
    check_budgets,
    check_history,
    check_slo,
    format_report,
    load_bench_history,
    load_stats,
    load_trace_records,
)

REPO_ROOT = Path(__file__).resolve().parents[1]
FIXTURE = REPO_ROOT / "tests" / "fixtures" / "decisionview"
BUDGETS = REPO_ROOT / "tools" / "decisionview" / "budgets.json"


@pytest.fixture(scope="module")
def report():
    return build_report(
        stats=load_stats(str(FIXTURE / "stats.json")),
        records=load_trace_records(FIXTURE / "trace"),
        history=load_bench_history(FIXTURE / "bench.jsonl"),
    )


# ------------------------------------------------------------------ report


def test_phase_table_and_reconciliation(report):
    phases = report["phases"]
    assert set(phases) == {"parse", "observe", "batch_wait", "forward",
                           "marshal", "trace"}
    for entry in phases.values():
        assert entry["count"] == 80
        assert entry["mean_ms"] > 0
    rec = report["reconciliation"]
    assert rec["coverage"] >= MIN_PHASE_COVERAGE
    assert rec["phase_sum_ms"] == pytest.approx(
        sum(e["mean_ms"] for e in phases.values()), abs=1e-3)
    # The e2e decide window is explained by the decide-side phases alone
    # (observe + the graftfwd admission window + forward).
    inner = (phases["observe"]["mean_ms"] + phases["batch_wait"]["mean_ms"]
             + phases["forward"]["mean_ms"])
    assert inner >= 0.9 * rec["e2e_mean_ms"]


def test_probe_traffic_excluded_from_report():
    all_records = load_trace_records(FIXTURE / "trace",
                                     include_probes=True)
    client = load_trace_records(FIXTURE / "trace")
    probes = [r for r in all_records if r["endpoint"] == "probe"]
    assert probes, "fixture must contain synthetic probe records"
    assert len(client) == len(all_records) - len(probes)
    # And the per-generation table only counts client traffic.
    report = build_report(records=client)
    assert report["trace_records"] == len(client)
    assert sum(e["count"] for e in report["generations"].values()) \
        == len(client)


def test_per_generation_comparison(report):
    gens = report["generations"]
    assert set(gens) == {"0", "1"}
    for entry in gens.values():
        assert entry["count"] == 40
        assert entry["fail_open_fraction"] == 0.0
        assert entry["mean_ms"] > 0 and entry["p95_ms"] >= entry["mean_ms"]


def test_slo_attainment_section(report):
    slo = report["slo"]
    assert slo["latency"]["attainment"] == 1.0
    assert slo["availability"]["attainment"] == 1.0
    assert not slo["latency"]["burning"]


def test_format_report_renders_every_section(report):
    text = format_report(report)
    for needle in ("Phase decomposition", "SLO attainment",
                   "Per-generation latency", "Bench history", "forward",
                   "coverage"):
        assert needle in text


def test_fleet_merged_stats_body_reports_like_a_pool_body():
    """graftfleet satellite: the fleet controller's merged /stats body
    (aggregate_fleet_stats over pool bodies) reads like any pool body —
    `decisionview --stats http://fleet:8790/stats` renders e2e latency,
    phases, and the SLO section from it without special-casing."""
    from rl_scheduler_tpu.scheduler.extender import ExtenderPolicy
    from rl_scheduler_tpu.scheduler.fleet import aggregate_fleet_stats
    from rl_scheduler_tpu.scheduler.policy_backend import GreedyBackend
    from rl_scheduler_tpu.scheduler.pool import (
        PoolShared,
        aggregate_stats,
        worker_snapshot,
    )
    from rl_scheduler_tpu.scheduler.slo import SloConfig, SloTracker
    from rl_scheduler_tpu.scheduler.telemetry import RandomCpu, TableTelemetry

    bodies = {}
    for p, n in enumerate((3, 5)):
        shared = PoolShared()
        telemetry = TableTelemetry.from_table(
            cpu_source=RandomCpu(seed=0), counter=shared.table_counter)
        policy = ExtenderPolicy(GreedyBackend(), telemetry)
        policy.slo = SloTracker(SloConfig(p99_ms=1000.0))
        for i in range(n):
            policy.filter({"nodenames": [f"aws-w{i}", f"azure-w{i}"],
                           "pod": {}})
        bodies[f"pool{p}"] = aggregate_stats(
            [worker_snapshot(policy, 0)], {"workers": 1, "alive": 1})
    fleet_body = aggregate_fleet_stats(bodies, fleet={"generation": 2})
    fleet_report = build_report(stats=fleet_body)
    assert fleet_report["e2e"]["count"] == 8
    assert fleet_report["e2e"]["mean_ms"] > 0
    assert set(fleet_report["phases"]) == {"parse", "observe", "batch_wait",
                                           "forward", "marshal", "trace"}
    assert fleet_report["slo"]["latency"]["attainment"] == 1.0
    text = format_report(fleet_report)
    assert "Phase decomposition" in text and "SLO attainment" in text


# ------------------------------------------------------------------- gates


def test_checked_in_budgets_pass(report):
    assert check_budgets(report,
                         json.loads(BUDGETS.read_text())) == []


def test_over_budget_and_absent_phase_violate(report):
    tiny = {"tolerance_pct": 0.0,
            "phases": {"forward": 0.0001, "missing_phase": 1.0}}
    violations = check_budgets(report, tiny)
    assert any("forward" in v and "exceeds budget" in v
               for v in violations)
    assert any("missing_phase" in v and "absent" in v for v in violations)


def test_optional_phase_may_be_absent(report):
    """`optional_phases` (graftfwd): a budgeted-but-optional phase may
    be ABSENT without failing (version skew: `--check` against a
    pre-batching pool), while a non-optional absence still violates."""
    pre13 = dict(report)
    pre13["phases"] = {k: v for k, v in report["phases"].items()
                      if k != "batch_wait"}
    budgets = {"tolerance_pct": 50.0,
               "phases": {"batch_wait": 2.0, "forward": 3.0},
               "optional_phases": ["batch_wait"]}
    assert check_budgets(pre13, budgets) == []
    budgets["optional_phases"] = []
    assert any("batch_wait" in v and "absent" in v
               for v in check_budgets(pre13, budgets))


def test_coverage_gap_violates():
    """A report whose spans lost time (sum < 90% of e2e) fails the
    reconciliation gate even with every budgeted phase under budget."""
    stats = load_stats(str(FIXTURE / "stats.json"))
    stats["phases"] = {"forward": stats["phases"]["forward"]}
    broken = build_report(stats=stats)
    violations = check_budgets(broken, {"phases": {}})
    assert any("coverage" in v for v in violations)


def test_history_gate_passes_then_catches_regression():
    history = load_bench_history(FIXTURE / "bench.jsonl")
    assert check_history(history) == []
    regressed = dict(history[-1])
    regressed["req_per_sec"] = history[-1]["req_per_sec"] * 0.5
    regressed["client_p50_ms"] = history[-1]["client_p50_ms"] * 2.0
    violations = check_history(history + [regressed])
    assert len(violations) == 2
    assert any("req_per_sec regressed" in v for v in violations)
    assert any("client_p50_ms regressed" in v for v in violations)
    # A different shape never compares (N=2048 vs the N=64 priors).
    other_shape = dict(regressed, nodes=2048)
    assert check_history(history + [other_shape]) == []
    # A just-starting ledger passes vacuously.
    assert check_history(history[:1]) == []


def test_slo_gate_flags_burning_objective(report):
    assert check_slo(report) == []
    burning = json.loads(json.dumps(report))
    burning["slo"]["latency"]["burning"] = True
    assert len(check_slo(burning)) == 1


# --------------------------------------------------------------------- CLI


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.decisionview", *args],
        capture_output=True, text=True, cwd=REPO_ROOT)


def test_cli_full_report_and_gates_exit_zero():
    proc = _run_cli("--stats", str(FIXTURE / "stats.json"),
                    "--trace", str(FIXTURE / "trace"),
                    "--bench", str(FIXTURE / "bench.jsonl"),
                    "--check", "--budgets", str(BUDGETS),
                    "--check-history", "--slo-check")
    assert proc.returncode == 0, proc.stderr
    line = proc.stdout.strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["schema_version"] == 1
    assert payload["reconciliation"]["coverage"] >= MIN_PHASE_COVERAGE
    assert "all gates OK" in proc.stderr


def test_cli_exits_2_on_injected_over_budget_phase(tmp_path):
    bad = tmp_path / "budgets.json"
    bad.write_text(json.dumps(
        {"tolerance_pct": 0.0, "phases": {"forward": 0.0001}}))
    proc = _run_cli("--stats", str(FIXTURE / "stats.json"),
                    "--check", "--budgets", str(bad), "--json")
    assert proc.returncode == 2
    assert "REGRESSION" in proc.stderr and "forward" in proc.stderr


def test_cli_exits_2_on_history_regression(tmp_path):
    history = load_bench_history(FIXTURE / "bench.jsonl")
    regressed = dict(history[-1], req_per_sec=1.0)
    ledger = tmp_path / "BENCH_serving.jsonl"
    ledger.write_text("".join(json.dumps(r) + "\n"
                              for r in history + [regressed]))
    proc = _run_cli("--bench", str(ledger), "--check-history", "--json")
    assert proc.returncode == 2
    assert "req_per_sec regressed" in proc.stderr


def test_cli_refuses_gate_without_input():
    proc = _run_cli("--check")
    assert proc.returncode == 2  # argparse error
    assert "pass at least one input" in proc.stderr
    proc = _run_cli("--check", "--bench",
                    str(FIXTURE / "bench.jsonl"))
    assert proc.returncode == 2
    assert "--check needs --stats" in proc.stderr


def test_bench_history_flag_appends_ledger(tmp_path):
    """extender_bench --history appends its JSON line (satellite 1) —
    exercised through the arg parser path by reusing a canned line; the
    live-append itself is covered by the slow pool soak."""
    sys.path.insert(0, str(REPO_ROOT / "loadgen"))
    try:
        import importlib

        bench = importlib.import_module("extender_bench")
    finally:
        sys.path.pop(0)
    # The flag exists and the writer tolerates append-after-append.
    ledger = tmp_path / "ledger.jsonl"
    line = {"schema_version": 1, "req_per_sec": 10.0}
    for _ in range(2):
        with open(ledger, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(line) + "\n")
    assert len(load_bench_history(ledger)) == 2
    assert any(a.option_strings == ["--history"]
               for a in _bench_parser_actions(bench))


def _bench_parser_actions(bench):
    import argparse
    import unittest.mock as mock

    captured = {}
    real_parse = argparse.ArgumentParser.parse_args

    def capture(self, argv=None):
        captured["parser"] = self
        raise SystemExit(0)

    with mock.patch.object(argparse.ArgumentParser, "parse_args", capture):
        try:
            bench.main(["--help"])
        except SystemExit:
            pass
    return captured["parser"]._actions
