"""GAE, returns, and loss functions: golden values vs a numpy reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.ops import gae, discounted_returns, ppo_loss, dqn_loss, PPOLossConfig


def numpy_gae(rewards, values, dones, last_value, gamma, lam):
    T, N = rewards.shape
    advs = np.zeros((T, N), np.float32)
    next_adv = np.zeros(N, np.float32)
    next_value = last_value
    for t in reversed(range(T)):
        nd = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nd - values[t]
        next_adv = delta + gamma * lam * nd * next_adv
        advs[t] = next_adv
        next_value = values[t]
    return advs, advs + values


@pytest.fixture
def rollout_arrays(rng):
    T, N = 32, 4
    rewards = rng.randn(T, N).astype(np.float32)
    values = rng.randn(T, N).astype(np.float32)
    dones = (rng.rand(T, N) < 0.1).astype(np.float32)
    last_value = rng.randn(N).astype(np.float32)
    return rewards, values, dones, last_value


def test_gae_matches_numpy(rollout_arrays):
    rewards, values, dones, last_value = rollout_arrays
    adv, tgt = jax.jit(gae, static_argnums=(4, 5))(
        rewards, values, dones, last_value, 0.99, 0.95
    )
    exp_adv, exp_tgt = numpy_gae(rewards, values, dones, last_value, 0.99, 0.95)
    np.testing.assert_allclose(np.asarray(adv), exp_adv, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(tgt), exp_tgt, rtol=1e-4, atol=1e-5)


def test_gae_done_cuts_bootstrap():
    """A done at step t must stop value bootstrapping across the boundary."""
    rewards = jnp.array([[1.0], [1.0]])
    values = jnp.array([[5.0], [7.0]])
    dones = jnp.array([[1.0], [0.0]])
    last_value = jnp.array([100.0])
    adv, _ = gae(rewards, values, dones, last_value, 0.9, 1.0)
    # step 0: delta = 1 - 5 (no bootstrap), no accumulation from step 1
    assert float(adv[0, 0]) == pytest.approx(-4.0)


def test_discounted_returns():
    rewards = jnp.array([[1.0], [2.0], [3.0]])
    dones = jnp.zeros((3, 1))
    last = jnp.array([4.0])
    rets = discounted_returns(rewards, dones, last, 0.5)
    assert float(rets[2, 0]) == pytest.approx(3 + 0.5 * 4)
    assert float(rets[1, 0]) == pytest.approx(2 + 0.5 * 5)
    assert float(rets[0, 0]) == pytest.approx(1 + 0.5 * 4.5)


def test_ppo_loss_zero_when_policy_unchanged(rng):
    """With identical old/new policies and zero advantages, the surrogate is 0
    and gradients w.r.t. the policy are driven only by the value loss."""
    B, A = 64, 2
    logits = jnp.asarray(rng.randn(B, A), jnp.float32)
    actions = jnp.asarray(rng.randint(0, A, B))
    values = jnp.asarray(rng.randn(B), jnp.float32)
    from rl_scheduler_tpu.ops.losses import categorical_log_prob

    old_lp = categorical_log_prob(logits, actions)
    loss, m = ppo_loss(
        logits, values, actions, old_lp, values, jnp.zeros(B), values,
        PPOLossConfig(normalize_advantages=False),
    )
    assert m["policy_loss"] == pytest.approx(0.0, abs=1e-6)
    assert m["approx_kl"] == pytest.approx(0.0, abs=1e-6)
    assert m["value_loss"] == pytest.approx(0.0, abs=1e-6)
    assert float(loss) == pytest.approx(0.0, abs=1e-6)


def test_ppo_loss_clipping_engages(rng):
    B, A = 8, 2
    logits = jnp.asarray(rng.randn(B, A) * 5, jnp.float32)
    actions = jnp.zeros(B, jnp.int32)
    old_lp = jnp.full((B,), -3.0)  # very different behavior policy
    adv = jnp.ones(B)
    values = jnp.zeros(B)
    _, m = ppo_loss(
        logits, values, actions, old_lp, values, adv, values,
        PPOLossConfig(normalize_advantages=False),
    )
    assert float(m["clip_fraction"]) > 0.0


def test_ppo_entropy_bonus_direction(rng):
    """Higher entropy_coeff must lower the total loss for the same inputs."""
    B, A = 32, 2
    logits = jnp.asarray(rng.randn(B, A), jnp.float32)
    actions = jnp.asarray(rng.randint(0, A, B))
    values = jnp.asarray(rng.randn(B), jnp.float32)
    from rl_scheduler_tpu.ops.losses import categorical_log_prob

    old_lp = categorical_log_prob(logits, actions)
    adv = jnp.asarray(rng.randn(B), jnp.float32)
    tgt = jnp.asarray(rng.randn(B), jnp.float32)
    l0, _ = ppo_loss(logits, values, actions, old_lp, values, adv, tgt, PPOLossConfig(entropy_coeff=0.0))
    l1, _ = ppo_loss(logits, values, actions, old_lp, values, adv, tgt, PPOLossConfig(entropy_coeff=0.1))
    assert float(l1) < float(l0)


def test_dqn_loss_zero_at_fixpoint():
    """If Q(s,a) already equals r + gamma*max Q(s',.), the loss is 0."""
    q_next = jnp.array([[1.0, 2.0]])
    rewards = jnp.array([0.5])
    gamma = 0.9
    target = 0.5 + gamma * 2.0
    q = jnp.array([[target, -1.0]])
    actions = jnp.array([0])
    loss, m = dqn_loss(q, q_next, q_next, actions, rewards, jnp.array([0.0]), gamma)
    assert float(loss) == pytest.approx(0.0, abs=1e-6)


def test_dqn_loss_terminal_ignores_bootstrap():
    q = jnp.array([[0.0, 0.0]])
    q_next = jnp.array([[100.0, 100.0]])
    loss, _ = dqn_loss(q, q_next, q_next, jnp.array([0]), jnp.array([1.0]), jnp.array([1.0]), 0.99)
    # target = 1.0; td = -1 -> huber(1) = 0.5
    assert float(loss) == pytest.approx(0.5, abs=1e-6)


def test_dqn_double_q_uses_online_argmax():
    q = jnp.array([[0.0, 0.0]])
    target_q_next = jnp.array([[5.0, 1.0]])
    online_q_next = jnp.array([[0.0, 10.0]])  # online picks action 1
    loss_double, _ = dqn_loss(
        q, target_q_next, online_q_next, jnp.array([0]), jnp.array([0.0]), jnp.array([0.0]), 1.0
    )
    # double-DQN target = target_q_next[online argmax=1] = 1.0 -> huber(1.0)=0.5
    assert float(loss_double) == pytest.approx(0.5, abs=1e-6)


def test_models_forward_shapes(rng):
    from rl_scheduler_tpu.models import ActorCritic, QNetwork

    obs = jnp.asarray(rng.randn(7, 6), jnp.float32)
    ac = ActorCritic(num_actions=2)
    params = ac.init(jax.random.PRNGKey(0), obs)
    logits, value = ac.apply(params, obs)
    assert logits.shape == (7, 2) and value.shape == (7,)
    qn = QNetwork(num_actions=2)
    qp = qn.init(jax.random.PRNGKey(1), obs)
    assert qn.apply(qp, obs).shape == (7, 2)
    # single-obs (unbatched) path used by the serving backend
    logits1, v1 = ac.apply(params, obs[0])
    assert logits1.shape == (2,) and v1.shape == ()


class TestSelectAlongLast:
    def test_matches_take_along_axis(self):
        from rl_scheduler_tpu.ops.indexing import select_along_last

        rng = np.random.default_rng(0)
        vals = jnp.asarray(rng.normal(size=(5, 7, 3)).astype(np.float32))
        idx = jnp.asarray(rng.integers(0, 3, (5, 7)), jnp.int32)
        got = select_along_last(vals, idx)
        expect = jnp.take_along_axis(vals, idx[..., None], axis=-1)[..., 0]
        np.testing.assert_array_equal(np.asarray(got), np.asarray(expect))

    def test_gradient_flows_only_to_selected(self):
        from rl_scheduler_tpu.ops.indexing import select_along_last

        vals = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        idx = jnp.asarray([1, 0], jnp.int32)
        g = jax.grad(lambda v: select_along_last(v, idx).sum())(vals)
        np.testing.assert_array_equal(np.asarray(g), [[0.0, 1.0], [1.0, 0.0]])

    def test_preserves_dtype(self):
        from rl_scheduler_tpu.ops.indexing import select_along_last

        vals = jnp.ones((4, 2), jnp.bfloat16)
        out = select_along_last(vals, jnp.zeros(4, jnp.int32))
        assert out.dtype == jnp.bfloat16

    def test_inf_in_unselected_columns_is_safe(self):
        """Action-masked logits pad with -inf; the select must not turn
        those into NaN via 0 * inf (ADVICE r1)."""
        from rl_scheduler_tpu.ops.indexing import select_along_last

        vals = jnp.asarray([[1.0, -jnp.inf, jnp.inf], [-jnp.inf, 2.0, -jnp.inf]])
        idx = jnp.asarray([0, 1], jnp.int32)
        got = np.asarray(select_along_last(vals, idx))
        np.testing.assert_array_equal(got, [1.0, 2.0])

        g = jax.grad(lambda v: select_along_last(v, idx).sum())(vals)
        assert np.isfinite(np.asarray(g)).all()


# --------------------------------------------- impl resolution + entropy
# (GL007: every public op needs at least one direct test reference)


def test_default_platform_and_resolve_impl():
    """`auto` must resolve per the default device: scan off-TPU, pallas on
    TPU — the dispatch that keeps the Pallas GAE kernel off CPU CI."""
    from rl_scheduler_tpu.ops.gae import default_platform, resolve_impl

    platform = default_platform()
    assert isinstance(platform, str) and platform  # "cpu" under tier-1
    expected_auto = "pallas" if platform == "tpu" else "scan"
    assert resolve_impl("auto") == expected_auto
    assert resolve_impl("scan") == "scan"
    assert resolve_impl("pallas") == "pallas"
    with pytest.raises(ValueError, match="unknown GAE impl"):
        resolve_impl("numpy")


def test_categorical_entropy_golden():
    """Uniform logits -> log(A); a near-deterministic distribution -> ~0."""
    from rl_scheduler_tpu.ops.losses import categorical_entropy

    uniform = jnp.zeros((3, 5))
    np.testing.assert_allclose(
        np.asarray(categorical_entropy(uniform)), np.log(5.0), rtol=1e-6
    )
    peaked = jnp.asarray([[30.0, 0.0, 0.0]])
    assert float(categorical_entropy(peaked)[0]) < 1e-8
    # Shift invariance: logits are unnormalized, entropy must not care.
    shifted = uniform + 7.25
    np.testing.assert_allclose(
        np.asarray(categorical_entropy(shifted)),
        np.asarray(categorical_entropy(uniform)),
        rtol=1e-6,
    )
