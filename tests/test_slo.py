"""graftlens SLO engine (scheduler/slo.py): burn-rate math, multi-window
semantics, pool merging, and the histogram-delta seam the rollout canary
gate uses. Pure-unit — an injectable clock drives the windows."""

import pytest

from rl_scheduler_tpu.scheduler.extender import LatencyStats
from rl_scheduler_tpu.scheduler.slo import (
    SloConfig,
    SloTracker,
    compute_burn,
    config_from_snapshot,
    histogram_bad_fraction,
    merge_snapshots,
)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def make_tracker(clock=None, **kwargs):
    kwargs.setdefault("p99_ms", 10.0)
    kwargs.setdefault("availability", 0.999)
    return SloTracker(SloConfig(**kwargs), clock=clock or FakeClock())


# ------------------------------------------------------------------ config


def test_config_validation():
    with pytest.raises(ValueError):
        SloConfig()  # no objective armed
    with pytest.raises(ValueError):
        SloConfig(p99_ms=-1.0)
    with pytest.raises(ValueError):
        SloConfig(availability=1.5)
    with pytest.raises(ValueError):
        SloConfig(p99_ms=5.0, fast_window_s=600.0, slow_window_s=60.0)
    # Single-objective configs are valid.
    assert SloConfig(p99_ms=5.0).objectives().keys() == {"latency"}
    assert SloConfig(availability=0.99).objectives().keys() == {
        "availability"}


def test_config_round_trips_through_snapshot():
    tracker = make_tracker()
    assert config_from_snapshot(tracker.snapshot()) == tracker.config


# ------------------------------------------------------------- burn rates


def test_latency_burn_rate_math():
    """100 decided requests, 5 over the 10 ms threshold: bad fraction
    5%, latency budget 1% -> burn rate 5.0 in both windows."""
    clock = FakeClock()
    tracker = make_tracker(clock)
    for i in range(100):
        tracker.observe(0.002 if i % 20 else 0.02)  # 5 of 100 over
    snap = tracker.snapshot()
    lat = snap["objectives"]["latency"]
    assert lat["windows"]["fast"]["total"] == 100
    assert lat["windows"]["fast"]["bad"] == 5
    assert lat["windows"]["fast"]["burn_rate"] == pytest.approx(5.0)
    assert lat["windows"]["slow"]["burn_rate"] == pytest.approx(5.0)
    # 5x burn is below the 14.4x fast threshold: not burning.
    assert not lat["burning"]
    assert not snap["degraded"]


def test_total_outage_burns_and_degrades():
    """All requests failing open: availability bad fraction 1.0 against
    a 0.1% budget -> burn ~1000x, far over both thresholds."""
    tracker = make_tracker()
    for _ in range(50):
        tracker.observe_failure()
    snap = tracker.snapshot()
    avail = snap["objectives"]["availability"]
    assert avail["windows"]["fast"]["bad_fraction"] == 1.0
    assert avail["burning"]
    assert snap["degraded"]
    # Fail-opens are excluded from the latency objective's denominator.
    assert snap["objectives"]["latency"]["windows"]["fast"]["total"] == 0


def test_window_expiry_forgives_old_badness():
    """Bad events older than the window stop burning it: the fast
    window recovers first (multi-window = fast detection AND fast
    recovery), the slow window still remembers."""
    clock = FakeClock()
    tracker = make_tracker(clock, fast_window_s=10.0, slow_window_s=100.0,
                           fast_burn=2.0, slow_burn=1.0)
    for _ in range(20):
        tracker.observe(0.5)  # all over threshold: burn 100x
    assert tracker.snapshot()["degraded"]
    clock.t += 30.0  # past fast window, inside slow
    snap = tracker.snapshot()
    assert snap["objectives"]["latency"]["windows"]["fast"]["total"] == 0
    assert snap["objectives"]["latency"]["windows"]["slow"]["bad"] == 20
    # Fast window clean -> the AND rule stops paging (degraded clears).
    assert not snap["degraded"]


def test_lifetime_counters_are_monotonic():
    clock = FakeClock()
    tracker = make_tracker(clock)
    for _ in range(10):
        tracker.observe(0.5)
    tracker.observe_failure()
    life = tracker.snapshot()["lifetime"]
    assert life == {"requests_total": 11, "latency_bad_total": 10,
                    "fail_open_total": 1}
    clock.t += 10_000.0  # windows all expire; lifetime never does
    life2 = tracker.snapshot()["lifetime"]
    assert life2 == life


def test_ring_reuses_slots_across_wraps():
    """A bucket slot reused after the ring wraps must forget its old
    epoch's counts (stale counts would resurrect expired badness)."""
    clock = FakeClock()
    tracker = make_tracker(clock, fast_window_s=2.0, slow_window_s=5.0)
    tracker.observe(0.5)
    clock.t += 8.0  # beyond slow window: the ring index wraps onto the
    tracker.observe(0.001)  # same arithmetic slots
    snap = tracker.snapshot()
    assert snap["objectives"]["latency"]["windows"]["slow"]["bad"] == 0
    assert snap["objectives"]["latency"]["windows"]["slow"]["total"] == 1


# ---------------------------------------------------------------- merging


def test_merge_snapshots_sums_counts_and_recomputes_burn():
    """Counts are linear, rates are not: two workers each at 5% bad
    merge to 5% pool-wide, not to an average of per-worker burns."""
    a, b = make_tracker(), make_tracker()
    for i in range(100):
        a.observe(0.02 if i < 5 else 0.001)
    for i in range(300):
        b.observe(0.02 if i < 15 else 0.001)
    merged = merge_snapshots([a.snapshot(), b.snapshot()])
    fast = merged["objectives"]["latency"]["windows"]["fast"]
    assert fast["total"] == 400
    assert fast["bad"] == 20
    assert fast["burn_rate"] == pytest.approx(5.0)
    assert merged["lifetime"]["requests_total"] == 400
    assert merge_snapshots([]) is None
    # One-sided: a worker without a tracker contributes nothing.
    assert merge_snapshots([a.snapshot(), None])["lifetime"][
        "requests_total"] == 100


def test_compute_burn_is_the_shared_math():
    """compute_burn over hand-built window counts equals the tracker's
    own snapshot — per-worker and pool-wide snapshots share ONE
    implementation."""
    tracker = make_tracker()
    for _ in range(10):
        tracker.observe(0.02)
    snap = tracker.snapshot()
    rebuilt = compute_burn(
        tracker.config,
        {k: tuple(v) for k, v in snap["windows_raw"].items()},
        snap["lifetime"])
    assert rebuilt["objectives"] == snap["objectives"]


# --------------------------------------------- histogram seam (canary gate)


def _hist_snapshot(latencies_s):
    stats = LatencyStats()
    for v in latencies_s:
        stats.record(v)
    cumulative, total_sum, count = stats.histogram()
    return {"cumulative": cumulative, "sum": total_sum, "count": count}


def test_histogram_bad_fraction_from_deltas():
    """Over-threshold fraction from lifetime-histogram deltas: exact at
    bucket bounds, conservative (threshold rounds UP to a bound)."""
    start = _hist_snapshot([])
    end = _hist_snapshot([0.001] * 90 + [0.2] * 10)  # 10% over 100 ms
    frac, count = histogram_bad_fraction(start, end, 100.0,
                                         LatencyStats.BUCKETS)
    assert count == 100
    assert frac == pytest.approx(0.10)
    # A threshold between bounds rounds up (conservative: 30 ms uses the
    # 50 ms bucket boundary, so 40 ms samples do NOT count as bad).
    end2 = _hist_snapshot([0.04] * 10 + [0.001] * 90)
    frac2, _ = histogram_bad_fraction(_hist_snapshot([]), end2, 30.0,
                                      LatencyStats.BUCKETS)
    assert frac2 == 0.0
    # Empty window: no signal, no division.
    assert histogram_bad_fraction(end, end, 100.0,
                                  LatencyStats.BUCKETS) == (0.0, 0)
