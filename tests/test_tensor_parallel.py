"""Tensor parallelism: the tp axis sharding policy weights for real.

Because the tp-sharded param leaves use PartitionSpecs like
``P(None, "tp")``, the GLOBAL arrays of a sharded run ARE the assembled
full matrices — so the unsharded twin module (``tp_axis=None``) applied
to the same param tree is the exact reference for both forward and
gradient equivalence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from rl_scheduler_tpu.agent.ppo import PPOTrainConfig
from rl_scheduler_tpu.env.bundle import multi_cloud_bundle
from rl_scheduler_tpu.parallel import make_mesh, make_tensor_parallel_ppo
from rl_scheduler_tpu.parallel.tensor_parallel import (
    TPActorCritic,
    _spec_tree,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

HIDDEN = (64, 64)
CFG = PPOTrainConfig(
    num_envs=8,
    rollout_steps=8,
    minibatch_size=32,
    num_epochs=2,
    lr=1e-3,
    hidden=HIDDEN,
)


def _init_sharded(dp=2, tp=4):
    mesh = make_mesh({"dp": dp, "tp": tp})
    bundle = multi_cloud_bundle()
    init_fn, update_fn, net = make_tensor_parallel_ppo(bundle, CFG, mesh)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    return mesh, bundle, runner, update_fn, net


def test_tp_global_params_are_full_matrices():
    _, bundle, runner, _, _ = _init_sharded()
    p = runner.params["params"]
    assert p["actor_torso"]["col0"]["kernel"].shape == (6, HIDDEN[0])
    assert p["actor_torso"]["row0"]["kernel"].shape == (HIDDEN[0], HIDDEN[1])
    assert p["actor_torso"]["row_bias0"].shape == (HIDDEN[1],)
    # shards are DISTINCT slices (the tp-folded init), not tp copies
    k = np.asarray(p["actor_torso"]["col0"]["kernel"])
    quarter = HIDDEN[0] // 4
    assert not np.array_equal(k[:, :quarter], k[:, quarter: 2 * quarter])
    # replicated leaves really are replicated (sync step): every physical
    # shard of the actor head holds the same values
    head = p["actor_head"]["kernel"]
    shards = [np.asarray(s.data) for s in head.addressable_shards]
    assert all(np.array_equal(shards[0], s) for s in shards[1:])


def test_tp_forward_matches_unsharded_twin():
    mesh, bundle, runner, _, net = _init_sharded()
    params = jax.device_get(runner.params)
    obs = np.random.default_rng(0).normal(size=(16, 6)).astype(np.float32)

    twin = TPActorCritic(
        num_actions=bundle.num_actions, hidden=HIDDEN, tp_axis=None, tp_size=1
    )
    logits_ref, value_ref = twin.apply(params, jnp.asarray(obs))

    from rl_scheduler_tpu.parallel.tensor_parallel import tp_param_spec_fn

    param_specs = jax.tree_util.tree_map_with_path(
        tp_param_spec_fn("tp"), params
    )
    logits_tp, value_tp = jax.jit(
        shard_map(
            lambda p, o: net.apply(p, o),
            mesh=mesh,
            in_specs=(param_specs, P()),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )(params, jnp.asarray(obs))

    np.testing.assert_allclose(
        np.asarray(logits_tp), np.asarray(logits_ref), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(value_tp), np.asarray(value_ref), rtol=1e-5, atol=1e-5
    )


def test_tp_gradients_match_unsharded_twin():
    """The Megatron f/g custom-vjp boundary ops must make the tp-sharded
    backward produce the exact global gradient — compared leaf-for-leaf
    against the unsharded twin on assembled weights."""
    mesh, bundle, runner, _, net = _init_sharded()
    params = jax.device_get(runner.params)
    rng = np.random.default_rng(1)
    obs = jnp.asarray(rng.normal(size=(16, 6)).astype(np.float32))
    tgt_logits = jnp.asarray(rng.normal(size=(16, 2)).astype(np.float32))
    tgt_value = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))

    def loss_with(apply_fn):
        def loss(p):
            logits, value = apply_fn(p, obs)
            return (
                jnp.mean((logits - tgt_logits) ** 2)
                + jnp.mean((value - tgt_value) ** 2)
            )

        return loss

    twin = TPActorCritic(
        num_actions=bundle.num_actions, hidden=HIDDEN, tp_axis=None, tp_size=1
    )
    g_ref = jax.grad(loss_with(twin.apply))(params)

    from rl_scheduler_tpu.parallel.tensor_parallel import tp_param_spec_fn

    param_specs = jax.tree_util.tree_map_with_path(
        tp_param_spec_fn("tp"), params
    )
    g_tp = jax.jit(
        shard_map(
            jax.grad(loss_with(net.apply)),
            mesh=mesh,
            in_specs=(param_specs,),
            out_specs=param_specs,
            check_vma=False,
        )
    )(params)

    flat_ref = jax.tree_util.tree_leaves_with_path(g_ref)
    flat_tp = jax.tree.leaves(g_tp)
    for (path, ref), tp_leaf in zip(flat_ref, flat_tp):
        np.testing.assert_allclose(
            np.asarray(tp_leaf), np.asarray(ref), rtol=2e-5, atol=1e-6,
            err_msg=jax.tree_util.keystr(path),
        )


def test_tp_ppo_trains_and_stays_finite():
    _, _, runner, update_fn, _ = _init_sharded()
    update = jax.jit(update_fn)
    for _ in range(2):
        runner, metrics = update(runner)
    for k in ("policy_loss", "value_loss", "entropy"):
        assert np.isfinite(float(metrics[k])), k
    assert int(runner.update_idx) == 2


def test_tp_learning_progress():
    mesh = make_mesh({"dp": 4, "tp": 2})
    init_fn, update_fn, _ = make_tensor_parallel_ppo(
        multi_cloud_bundle(),
        PPOTrainConfig(
            num_envs=32, rollout_steps=32, minibatch_size=256,
            num_epochs=2, lr=1e-3, hidden=HIDDEN,
        ),
        mesh,
    )
    runner = jax.jit(init_fn)(jax.random.PRNGKey(1))
    update = jax.jit(update_fn)
    rewards = []
    for _ in range(12):
        runner, metrics = update(runner)
        rewards.append(float(metrics["reward_mean"]))
    assert np.mean(rewards[-3:]) > np.mean(rewards[:3]), rewards


def test_tp_validation_errors():
    mesh = make_mesh({"dp": 2, "tp": 4})
    with pytest.raises(ValueError, match="not divisible"):
        make_tensor_parallel_ppo(
            multi_cloud_bundle(),
            PPOTrainConfig(num_envs=7, hidden=HIDDEN),
            mesh,
        )
    from rl_scheduler_tpu.parallel.tensor_parallel import TPMLPTorso

    with pytest.raises(ValueError, match="pairs"):
        TPMLPTorso(hidden=(64, 64, 64)).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 6))
        )
    # grad clipping would compute per-shard norms and desync replicated
    # leaves across tp — refused, not corrupted
    with pytest.raises(ValueError, match="max_grad_norm"):
        make_tensor_parallel_ppo(
            multi_cloud_bundle(),
            PPOTrainConfig(num_envs=8, hidden=HIDDEN, max_grad_norm=0.5),
            mesh,
        )


def test_tp_honors_compute_dtype():
    mesh = make_mesh({"dp": 2, "tp": 2})
    cfg = PPOTrainConfig(
        num_envs=8, rollout_steps=8, minibatch_size=32, num_epochs=1,
        hidden=HIDDEN, compute_dtype="bfloat16",
    )
    _, _, net = make_tensor_parallel_ppo(multi_cloud_bundle(), cfg, mesh)
    assert net.dtype == jnp.bfloat16
