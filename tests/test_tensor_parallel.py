"""Tensor parallelism: the tp axis sharding policy weights for real.

Because the tp-sharded param leaves use PartitionSpecs like
``P(None, "tp")``, the GLOBAL arrays of a sharded run ARE the assembled
full matrices — so the unsharded twin module (``tp_axis=None``) applied
to the same param tree is the exact reference for both forward and
gradient equivalence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from rl_scheduler_tpu.agent.ppo import PPOTrainConfig
from rl_scheduler_tpu.env.bundle import multi_cloud_bundle
from rl_scheduler_tpu.parallel import make_mesh, make_tensor_parallel_ppo
from rl_scheduler_tpu.parallel.tensor_parallel import (
    TPActorCritic,
    _spec_tree,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

HIDDEN = (64, 64)
CFG = PPOTrainConfig(
    num_envs=8,
    rollout_steps=8,
    minibatch_size=32,
    num_epochs=2,
    lr=1e-3,
    hidden=HIDDEN,
)


def _init_sharded(dp=2, tp=4):
    mesh = make_mesh({"dp": dp, "tp": tp})
    bundle = multi_cloud_bundle()
    init_fn, update_fn, net = make_tensor_parallel_ppo(bundle, CFG, mesh)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    return mesh, bundle, runner, update_fn, net


def test_tp_global_params_are_full_matrices():
    _, bundle, runner, _, _ = _init_sharded()
    p = runner.params["params"]
    assert p["actor_torso"]["col0"]["kernel"].shape == (6, HIDDEN[0])
    assert p["actor_torso"]["row0"]["kernel"].shape == (HIDDEN[0], HIDDEN[1])
    assert p["actor_torso"]["row_bias0"].shape == (HIDDEN[1],)
    # shards are DISTINCT slices (the tp-folded init), not tp copies
    k = np.asarray(p["actor_torso"]["col0"]["kernel"])
    quarter = HIDDEN[0] // 4
    assert not np.array_equal(k[:, :quarter], k[:, quarter: 2 * quarter])
    # replicated leaves really are replicated (sync step): every physical
    # shard of the actor head holds the same values
    head = p["actor_head"]["kernel"]
    shards = [np.asarray(s.data) for s in head.addressable_shards]
    assert all(np.array_equal(shards[0], s) for s in shards[1:])


def test_tp_forward_matches_unsharded_twin():
    mesh, bundle, runner, _, net = _init_sharded()
    params = jax.device_get(runner.params)
    obs = np.random.default_rng(0).normal(size=(16, 6)).astype(np.float32)

    twin = TPActorCritic(
        num_actions=bundle.num_actions, hidden=HIDDEN, tp_axis=None, tp_size=1
    )
    logits_ref, value_ref = twin.apply(params, jnp.asarray(obs))

    from rl_scheduler_tpu.parallel.tensor_parallel import tp_param_spec_fn

    param_specs = jax.tree_util.tree_map_with_path(
        tp_param_spec_fn("tp"), params
    )
    logits_tp, value_tp = jax.jit(
        shard_map(
            lambda p, o: net.apply(p, o),
            mesh=mesh,
            in_specs=(param_specs, P()),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )(params, jnp.asarray(obs))

    np.testing.assert_allclose(
        np.asarray(logits_tp), np.asarray(logits_ref), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(value_tp), np.asarray(value_ref), rtol=1e-5, atol=1e-5
    )


def test_tp_gradients_match_unsharded_twin():
    """The Megatron f/g custom-vjp boundary ops must make the tp-sharded
    backward produce the exact global gradient — compared leaf-for-leaf
    against the unsharded twin on assembled weights."""
    mesh, bundle, runner, _, net = _init_sharded()
    params = jax.device_get(runner.params)
    rng = np.random.default_rng(1)
    obs = jnp.asarray(rng.normal(size=(16, 6)).astype(np.float32))
    tgt_logits = jnp.asarray(rng.normal(size=(16, 2)).astype(np.float32))
    tgt_value = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))

    def loss_with(apply_fn):
        def loss(p):
            logits, value = apply_fn(p, obs)
            return (
                jnp.mean((logits - tgt_logits) ** 2)
                + jnp.mean((value - tgt_value) ** 2)
            )

        return loss

    twin = TPActorCritic(
        num_actions=bundle.num_actions, hidden=HIDDEN, tp_axis=None, tp_size=1
    )
    g_ref = jax.grad(loss_with(twin.apply))(params)

    from rl_scheduler_tpu.parallel.tensor_parallel import tp_param_spec_fn

    param_specs = jax.tree_util.tree_map_with_path(
        tp_param_spec_fn("tp"), params
    )
    g_tp = jax.jit(
        shard_map(
            jax.grad(loss_with(net.apply)),
            mesh=mesh,
            in_specs=(param_specs,),
            out_specs=param_specs,
            check_vma=False,
        )
    )(params)

    flat_ref = jax.tree_util.tree_leaves_with_path(g_ref)
    flat_tp = jax.tree.leaves(g_tp)
    for (path, ref), tp_leaf in zip(flat_ref, flat_tp):
        np.testing.assert_allclose(
            np.asarray(tp_leaf), np.asarray(ref), rtol=2e-5, atol=1e-6,
            err_msg=jax.tree_util.keystr(path),
        )


def test_tp_ppo_trains_and_stays_finite():
    _, _, runner, update_fn, _ = _init_sharded()
    update = jax.jit(update_fn)
    for _ in range(2):
        runner, metrics = update(runner)
    for k in ("policy_loss", "value_loss", "entropy"):
        assert np.isfinite(float(metrics[k])), k
    assert int(runner.update_idx) == 2


def test_tp_learning_progress():
    mesh = make_mesh({"dp": 4, "tp": 2})
    init_fn, update_fn, _ = make_tensor_parallel_ppo(
        multi_cloud_bundle(),
        PPOTrainConfig(
            num_envs=32, rollout_steps=32, minibatch_size=256,
            num_epochs=2, lr=1e-3, hidden=HIDDEN,
        ),
        mesh,
    )
    runner = jax.jit(init_fn)(jax.random.PRNGKey(1))
    update = jax.jit(update_fn)
    rewards = []
    for _ in range(12):
        runner, metrics = update(runner)
        rewards.append(float(metrics["reward_mean"]))
    assert np.mean(rewards[-3:]) > np.mean(rewards[:3]), rewards


def test_tp_validation_errors():
    mesh = make_mesh({"dp": 2, "tp": 4})
    with pytest.raises(ValueError, match="not divisible"):
        make_tensor_parallel_ppo(
            multi_cloud_bundle(),
            PPOTrainConfig(num_envs=7, hidden=HIDDEN),
            mesh,
        )
    from rl_scheduler_tpu.parallel.tensor_parallel import TPMLPTorso

    with pytest.raises(ValueError, match="pairs"):
        TPMLPTorso(hidden=(64, 64, 64)).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 6))
        )


def _twin_grads_and_specs():
    """Shared scaffolding: (mesh, params, twin grads, tp grads specs)."""
    mesh, bundle, runner, _, net = _init_sharded()
    params = jax.device_get(runner.params)
    rng = np.random.default_rng(1)
    obs = jnp.asarray(rng.normal(size=(16, 6)).astype(np.float32))
    tgt_logits = jnp.asarray(rng.normal(size=(16, 2)).astype(np.float32))
    tgt_value = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))

    twin = TPActorCritic(
        num_actions=bundle.num_actions, hidden=HIDDEN, tp_axis=None, tp_size=1
    )

    def loss(p):
        logits, value = twin.apply(p, obs)
        return (jnp.mean((logits - tgt_logits) ** 2)
                + jnp.mean((value - tgt_value) ** 2))

    g_ref = jax.grad(loss)(params)
    from rl_scheduler_tpu.parallel.tensor_parallel import tp_param_spec_fn

    param_specs = jax.tree_util.tree_map_with_path(
        tp_param_spec_fn("tp"), params
    )
    return mesh, params, g_ref, param_specs


def test_tp_grad_clip_matches_unsharded_twin():
    """tp_clip_by_global_norm + adam inside shard_map lands on the SAME
    updated params as optax.clip_by_global_norm + adam on the assembled
    matrices — replicated leaves stay in lockstep (round 2 refused this
    combination; now it is exact)."""
    import dataclasses

    import optax

    from rl_scheduler_tpu.agent.ppo import make_optimizer
    from rl_scheduler_tpu.parallel.tensor_parallel import make_tp_optimizer

    mesh, params, g_ref, param_specs = _twin_grads_and_specs()
    cfg = dataclasses.replace(CFG, max_grad_norm=1e-3)

    # The clip must actually engage, or this test would pass vacuously.
    gnorm = optax.global_norm(g_ref)
    assert float(gnorm) > cfg.max_grad_norm

    tx_ref = make_optimizer(cfg)
    u_ref, _ = tx_ref.update(g_ref, tx_ref.init(params), params)
    p_ref = optax.apply_updates(params, u_ref)

    is_replicated = jax.tree.map(lambda s: s == P(), param_specs)
    tx_tp = make_tp_optimizer(cfg, "tp", is_replicated)

    def step(g, p):
        u, _ = tx_tp.update(g, tx_tp.init(p), p)
        return optax.apply_updates(p, u)

    # in_specs shard the global grads/params exactly as training does:
    # sharded leaves arrive as local slices, replicated leaves whole.
    p_tp = jax.jit(
        shard_map(step, mesh=mesh, in_specs=(param_specs, param_specs),
                  out_specs=param_specs, check_vma=False)
    )(g_ref, params)

    for (path, ref), tp_leaf in zip(
        jax.tree_util.tree_leaves_with_path(p_ref), jax.tree.leaves(p_tp)
    ):
        np.testing.assert_allclose(
            np.asarray(tp_leaf), np.asarray(ref), rtol=2e-5, atol=1e-7,
            err_msg=jax.tree_util.keystr(path),
        )


def test_tp_trains_with_grad_clip():
    mesh = make_mesh({"dp": 2, "tp": 4})
    import dataclasses

    cfg = dataclasses.replace(CFG, max_grad_norm=0.5)
    init_fn, update_fn, _ = make_tensor_parallel_ppo(
        multi_cloud_bundle(), cfg, mesh
    )
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
    update = jax.jit(update_fn)
    for _ in range(2):
        runner, metrics = update(runner)
    for k in ("policy_loss", "value_loss", "entropy"):
        assert np.isfinite(float(metrics[k])), k
    # replicated leaves stay bit-identical across physical shards after
    # clipped updates (the exact desync the r2 refusal guarded against)
    head = runner.params["params"]["actor_head"]["kernel"]
    shards = [np.asarray(s.data) for s in head.addressable_shards]
    assert all(np.array_equal(shards[0], s) for s in shards[1:])


def test_tp_tree_to_actor_critic_parity():
    """The converted tree computes the identical function through the
    plain ActorCritic module — the serving/eval contract."""
    from rl_scheduler_tpu.models import ActorCritic
    from rl_scheduler_tpu.parallel.tensor_parallel import (
        tp_tree_to_actor_critic,
    )

    twin = TPActorCritic(num_actions=2, hidden=HIDDEN, tp_axis=None, tp_size=1)
    params = twin.init(jax.random.PRNGKey(2), jnp.zeros((1, 6)))
    obs = jnp.asarray(
        np.random.default_rng(3).normal(size=(32, 6)).astype(np.float32)
    )
    l_ref, v_ref = twin.apply(params, obs)
    ac = ActorCritic(num_actions=2, hidden=HIDDEN)
    l_ac, v_ac = ac.apply(
        {"params": tp_tree_to_actor_critic(params["params"])}, obs
    )
    np.testing.assert_allclose(np.asarray(l_ac), np.asarray(l_ref),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(v_ac), np.asarray(v_ref),
                               rtol=1e-6, atol=1e-6)


def test_train_cli_tp_roundtrip(tmp_path):
    """VERDICT r2 items 2+3: --tp from the command line composing with
    --dp, then the full tp train -> resume -> evaluate -> serve chain on
    one checkpoint."""
    import json

    from rl_scheduler_tpu.agent import train_ppo as cli
    from rl_scheduler_tpu.agent.evaluate import main as eval_main
    from rl_scheduler_tpu.scheduler.extender import build_policy
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    argv = [
        "--preset", "quick", "--dp", "2", "--tp", "2", "--num-envs", "8",
        "--rollout-steps", "16", "--minibatch-size", "32",
        "--hidden", "16,16", "--eval-every", "2", "--eval-episodes", "2",
        "--checkpoint-every", "2", "--run-root", str(tmp_path),
        "--run-name", "tp_cli",
    ]
    run_dir = cli.main(argv + ["--iterations", "2"])
    mgr = CheckpointManager(run_dir)
    meta = mgr.restore_meta(2)
    mgr.close()
    assert meta["tp"] == 2 and meta["hidden"] == [16, 16]
    records = [json.loads(l) for l in (run_dir / "metrics.jsonl").open()]
    evals = [r for r in records if r.get("eval")]
    assert evals and np.isfinite(evals[0]["eval_episode_reward_mean"])

    # resume extends the run (tp_abstract_state restore target)
    cli.main(argv + ["--iterations", "4", "--resume"])
    mgr = CheckpointManager(run_dir)
    assert mgr.latest_step() == 4
    mgr.close()

    # resuming with a different tp layout is refused, not corrupted
    with pytest.raises(SystemExit, match="--tp 2"):
        cli.main([
            "--preset", "quick", "--dp", "2", "--num-envs", "8",
            "--rollout-steps", "16", "--minibatch-size", "32",
            "--hidden", "16,16", "--iterations", "6", "--resume",
            "--run-root", str(tmp_path), "--run-name", "tp_cli",
        ])

    # evaluate the tp checkpoint through the standard evaluator
    report = eval_main([
        "--run", str(run_dir), "--episodes", "4",
        "--results-dir", str(tmp_path / "results"),
    ])
    assert np.isfinite(report.avg_episode_cost)

    # and serve it: the converted tree loads as a REAL policy backend
    # (a conversion failure would silently fall back to greedy)
    policy = build_policy("cpu", run=str(run_dir))
    assert policy.backend.name == "cpu"
    action, logits = policy.backend.decide(np.zeros(6, np.float32))
    assert action in (0, 1) and np.isfinite(logits).all()


def test_tp_honors_compute_dtype():
    mesh = make_mesh({"dp": 2, "tp": 2})
    cfg = PPOTrainConfig(
        num_envs=8, rollout_steps=8, minibatch_size=32, num_epochs=1,
        hidden=HIDDEN, compute_dtype="bfloat16",
    )
    _, _, net = make_tensor_parallel_ppo(multi_cloud_bundle(), cfg, mesh)
    assert net.dtype == jnp.bfloat16
