"""Scheduler extender: backends, protocol handlers, HTTP server, latency."""

import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.models import ActorCritic
from rl_scheduler_tpu.scheduler.extender import (
    MAX_EXTENDER_SCORE,
    ExtenderPolicy,
    LatencyStats,
    build_policy,
    make_server,
    node_cloud,
)
from rl_scheduler_tpu.scheduler.policy_backend import (
    GreedyBackend,
    JaxAOTBackend,
    NumpyMLPBackend,
    TorchMLPBackend,
    make_backend,
)
from rl_scheduler_tpu.scheduler.telemetry import RandomCpu, TableTelemetry

HIDDEN = (32, 32)


@pytest.fixture(scope="module")
def params_tree():
    net = ActorCritic(num_actions=env_core.NUM_ACTIONS, hidden=HIDDEN)
    return net.init(
        jax.random.PRNGKey(7), jnp.zeros((1, env_core.OBS_DIM), jnp.float32)
    )


@pytest.fixture()
def telemetry():
    return TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))


def _node(name, cloud=None):
    labels = {"cloud": cloud} if cloud else {}
    return {"metadata": {"name": name, "labels": labels}}


# ---------------------------------------------------------------- backends


def test_backends_agree_on_decisions(params_tree):
    """numpy, torch, and jax AOT backends are the same function."""
    numpy_b = NumpyMLPBackend(params_tree)
    torch_b = TorchMLPBackend(params_tree)
    jax_b = JaxAOTBackend(params_tree, hidden=HIDDEN)
    rng = np.random.RandomState(0)
    for _ in range(20):
        obs = rng.uniform(0, 1, env_core.OBS_DIM).astype(np.float32)
        a_np, l_np = numpy_b.decide(obs)
        a_t, l_t = torch_b.decide(obs)
        a_j, l_j = jax_b.decide(obs)
        assert a_np == a_t == a_j
        np.testing.assert_allclose(l_np, l_t, rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(l_np, l_j, rtol=1e-4, atol=1e-5)


def test_greedy_backend_matches_reference_rule():
    b = GreedyBackend()
    # cheaper aws -> 0; cheaper azure -> 1; tie -> aws (obs[0] <= obs[1])
    assert b.decide(np.array([0.1, 0.9, 0, 0, 0, 0], np.float32))[0] == 0
    assert b.decide(np.array([0.9, 0.1, 0, 0, 0, 0], np.float32))[0] == 1
    assert b.decide(np.array([0.5, 0.5, 0, 0, 0, 0], np.float32))[0] == 0


def test_make_backend_falls_back_to_greedy_without_params():
    backend, fell_back = make_backend("jax", params_tree=None)
    assert isinstance(backend, GreedyBackend)
    assert fell_back


def test_make_backend_falls_back_on_garbage_params():
    backend, fell_back = make_backend("cpu", params_tree={"params": {"bogus": {}}})
    assert isinstance(backend, GreedyBackend)
    assert fell_back


# ---------------------------------------------------------------- protocol


def test_filter_keeps_only_chosen_cloud(telemetry, params_tree):
    policy = ExtenderPolicy(NumpyMLPBackend(params_tree), telemetry)
    nodes = [_node("n-aws", "aws"), _node("n-azure", "azure"), _node("mystery")]
    result = policy.filter({"nodes": {"items": nodes}, "pod": {}})
    kept_names = [n["metadata"]["name"] for n in result["nodes"]["items"]]
    # exactly one cloud filtered out; unknown-cloud node passes (fail-open)
    assert "mystery" in kept_names
    assert len(kept_names) == 2
    assert len(result["failedNodes"]) == 1
    assert result["error"] == ""


def test_filter_nodenames_variant(telemetry):
    policy = ExtenderPolicy(GreedyBackend(), telemetry)
    result = policy.filter({"nodenames": ["aws-worker", "azure-worker"], "pod": {}})
    assert len(result["nodenames"]) == 1
    assert len(result["failedNodes"]) == 1


def test_filter_fails_open_when_backend_raises(telemetry):
    class Exploding:
        name = "boom"

        def decide(self, obs):
            raise RuntimeError("kaboom")

    policy = ExtenderPolicy(Exploding(), telemetry)
    nodes = {"items": [_node("a", "aws"), _node("b", "azure")]}
    result = policy.filter({"nodes": nodes, "pod": {}})
    assert len(result["nodes"]["items"]) == 2  # nothing filtered
    # error must stay empty: kube-scheduler hard-fails the scheduling cycle
    # on a non-empty Error unless ignorable=true
    assert result["error"] == ""


def test_prioritize_scores_follow_policy_probs(telemetry, params_tree):
    policy = ExtenderPolicy(NumpyMLPBackend(params_tree), telemetry)
    nodes = [_node("n-aws", "aws"), _node("n-azure", "azure"), _node("mystery")]
    scores = policy.prioritize({"nodes": {"items": nodes}})
    by_host = {s["host"]: s["score"] for s in scores}
    assert set(by_host) == {"n-aws", "n-azure", "mystery"}
    assert all(0 <= s <= 100 for s in by_host.values())
    # probs sum to 1 -> cloud scores sum to ~100; unknown node gets midpoint
    assert by_host["n-aws"] + by_host["n-azure"] == pytest.approx(100, abs=1)
    assert by_host["mystery"] == 50


def test_node_cloud_label_beats_name():
    assert node_cloud(_node("azure-ish-name", "aws")) == "aws"
    assert node_cloud(_node("worker-azure")) == "azure"
    assert node_cloud("kind-aws-worker") == "aws"
    assert node_cloud(_node("plain")) is None
    # whole-token matching: names merely containing 'aws' are NOT classified
    assert node_cloud(_node("gateways-1")) is None
    assert node_cloud("k8s-gateways-worker") is None


def test_make_backend_unknown_name_raises():
    with pytest.raises(ValueError):
        make_backend("cuda")


def test_build_policy_survives_corrupt_checkpoint(tmp_path):
    run = tmp_path / "run"
    (run / "checkpoints" / "5").mkdir(parents=True)
    (run / "checkpoints" / "5" / "garbage").write_text("not a checkpoint")
    policy = build_policy("cpu", run=str(run))
    assert policy.backend.name == "greedy"


def test_stats_accumulate(telemetry):
    policy = ExtenderPolicy(GreedyBackend(), telemetry)
    for _ in range(10):
        policy.filter({"nodenames": ["aws-w", "azure-w"], "pod": {}})
    stats = policy.statistics()
    assert stats["latency"]["count"] == 10
    assert sum(stats["decisions"].values()) == 10
    assert stats["backend"] == "greedy"


def test_latency_stats_merge_for_shared_scrape():
    """Multi-worker serving: one LatencyStats per worker process, and a
    shared scrape sums them — cumulative Prometheus histograms are linear,
    so the bucket-wise merge of two workers must equal one stats instance
    that saw the union of both latency streams."""
    rng = np.random.RandomState(3)
    streams = [rng.exponential(0.002, 200), rng.exponential(0.01, 50)]
    workers = [LatencyStats(), LatencyStats()]
    union = LatencyStats()
    for worker, stream in zip(workers, streams):
        for v in stream:
            worker.record(float(v))
            union.record(float(v))
    merged_counts, merged_sum, merged_count = \
        LatencyStats.merged_histogram(workers)
    union_counts, union_sum, union_count = union.histogram()
    assert merged_counts == union_counts
    assert merged_sum == pytest.approx(union_sum)
    assert merged_count == union_count == 250
    # Prometheus histogram invariants of the merged result: cumulative
    # counts are monotone and the +Inf bucket equals the total count.
    assert merged_counts == sorted(merged_counts)
    assert merged_counts[-1] == merged_count


def test_latency_stats_merge_survives_worker_reset():
    """/stats/reset clears a worker's percentile ring, never its lifetime
    histogram — the merged scrape must not go backwards (Prometheus
    counters treat decreases as counter resets)."""
    workers = [LatencyStats(), LatencyStats()]
    for w in workers:
        for v in (0.0002, 0.003, 0.04):
            w.record(v)
    before = LatencyStats.merged_histogram(workers)
    workers[0].reset()
    assert workers[0].percentiles_ms() == {"count": 0}  # window cleared
    assert LatencyStats.merged_histogram(workers) == before


def test_build_policy_greedy_without_checkpoint(tmp_path):
    policy = build_policy("jax", run_root=str(tmp_path / "empty"))
    assert policy.backend.name == "greedy"


# ---------------------------------------------------------------- HTTP


@pytest.fixture()
def server(telemetry, params_tree):
    policy = ExtenderPolicy(NumpyMLPBackend(params_tree), telemetry)
    srv = make_server(policy, host="127.0.0.1", port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield srv, policy
    srv.shutdown()


def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.load(resp)


def test_http_filter_prioritize_health_stats(server):
    srv, _ = server
    port = srv.server_address[1]
    # Go-style capitalized field names must be accepted
    args = {
        "Pod": {"metadata": {"name": "p"}},
        "Nodes": {"items": [_node("n-aws", "aws"), _node("n-azure", "azure")]},
    }
    filt = _post(port, "/filter", args)
    assert len(filt["nodes"]["items"]) == 1
    prio = _post(port, "/prioritize", args)
    assert len(prio) == 2
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
        assert json.load(r)["status"] == "ok"
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/stats", timeout=5) as r:
        assert json.load(r)["latency"]["count"] >= 2


def test_http_metrics_prometheus_format(server):
    """VERDICT r4 item 7: GET /metrics speaks Prometheus text format —
    decision counters, a LIFETIME latency histogram (cumulative
    le-buckets, monotonic across /stats/reset), and an info gauge."""
    srv, policy = server
    port = srv.server_address[1]
    args = {"nodenames": ["aws-w", "azure-w"], "pod": {}}
    for _ in range(5):
        _post(port, "/filter", args)
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=5) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        text = r.read().decode()

    # decision counters match /stats
    decisions = policy.statistics()["decisions"]
    for cloud, n in decisions.items():
        assert (f'rl_scheduler_extender_decisions_total{{cloud="{cloud}"}} '
                f"{n}") in text

    # histogram: cumulative buckets, +Inf == count, sum present
    bucket_counts = [
        int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
        if line.startswith("rl_scheduler_extender_decision_latency_seconds_bucket")
    ]
    assert bucket_counts == sorted(bucket_counts)  # cumulative
    count_line = [l for l in text.splitlines()
                  if l.startswith("rl_scheduler_extender_decision_latency_seconds_count")][0]
    count = int(count_line.rsplit(" ", 1)[1])
    assert bucket_counts[-1] == count >= 5
    assert "rl_scheduler_extender_decision_latency_seconds_sum" in text
    assert 'rl_scheduler_extender_info{backend=' in text

    # /stats/reset clears the percentile window but NOT the histogram
    _post(port, "/stats/reset", {})
    _post(port, "/filter", args)
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=5) as r:
        text2 = r.read().decode()
    count2 = int([l for l in text2.splitlines()
                  if l.startswith("rl_scheduler_extender_decision_latency_seconds_count")][0]
                 .rsplit(" ", 1)[1])
    assert count2 >= count + 1  # monotonic (>= because other tests share the server)


def test_http_bad_json_is_400(server):
    srv, _ = server
    port = srv.server_address[1]
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/filter", data=b"{not json",
        headers={"Content-Type": "application/json"},
    )
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(req, timeout=5)
    assert exc_info.value.code == 400


def test_decision_latency_under_1ms_p50(server):
    """The serving target: <1 ms p50 per decision (SURVEY.md §6)."""
    srv, policy = server
    port = srv.server_address[1]
    args = {"nodenames": ["aws-w", "azure-w"], "pod": {}}
    for _ in range(200):
        _post(port, "/filter", args)
    lat = policy.statistics()["latency"]
    assert lat["count"] >= 200
    assert lat["p50_ms"] < 1.0, f"decision p50 {lat['p50_ms']}ms exceeds 1ms"


def test_async_placer_never_blocks_and_bounds_queue():
    """A hung kube API must not block filter responses or grow unbounded
    state: placements drain through one worker over a bounded queue."""
    import threading
    import time

    from rl_scheduler_tpu.scheduler.extender import AsyncPlacer

    release = threading.Event()
    placed = []

    class StuckPlacer:
        def place(self, cloud):
            release.wait(timeout=10)
            placed.append(cloud)

    ap = AsyncPlacer(StuckPlacer(), maxsize=4)
    t0 = time.perf_counter()
    for i in range(100):  # far more than maxsize while the worker is stuck
        ap.submit("aws" if i % 2 else "azure")
    assert time.perf_counter() - t0 < 1.0, "submit must never block"
    assert ap.dropped >= 100 - 4 - 1  # all but queue capacity (+in-flight) drop
    release.set()
    deadline = time.time() + 5
    while len(placed) < 4 and time.time() < deadline:
        time.sleep(0.01)
    assert placed, "worker must drain queued placements once unblocked"


# ------------------------------------------------------------ DQN serving


@pytest.fixture(scope="module")
def dqn_params_tree():
    from rl_scheduler_tpu.models import QNetwork

    net = QNetwork(num_actions=env_core.NUM_ACTIONS, hidden=HIDDEN)
    return net.init(
        jax.random.PRNGKey(9), jnp.zeros((1, env_core.OBS_DIM), jnp.float32)
    )


def test_dqn_backends_agree_on_decisions(dqn_params_tree):
    """All host backends serve the same greedy-Q function for a DQN tree."""
    numpy_b = NumpyMLPBackend(dqn_params_tree, algo="dqn")
    torch_b = TorchMLPBackend(dqn_params_tree, algo="dqn")
    jax_b = JaxAOTBackend(dqn_params_tree, hidden=HIDDEN, algo="dqn")
    rng = np.random.RandomState(3)
    for _ in range(20):
        obs = rng.uniform(0, 1, env_core.OBS_DIM).astype(np.float32)
        a_np, q_np = numpy_b.decide(obs)
        a_t, q_t = torch_b.decide(obs)
        a_j, q_j = jax_b.decide(obs)
        assert a_np == a_t == a_j
        np.testing.assert_allclose(q_np, q_t, rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(q_np, q_j, rtol=1e-4, atol=1e-5)


def test_ppo_tree_with_dqn_layout_falls_back(params_tree):
    """Mismatched algo layout (PPO tree read as DQN) must degrade to greedy,
    not crash the server."""
    backend, fell_back = make_backend("cpu", params_tree, algo="dqn")
    assert fell_back and backend.name == "greedy"


def test_make_backend_unknown_algo_raises(params_tree):
    with pytest.raises(ValueError, match="algo"):
        make_backend("cpu", params_tree, algo="sarsa")


def test_build_policy_serves_dqn_checkpoint(tmp_path):
    """End-to-end: the newest run being a DQN one serves its Q-network."""
    from rl_scheduler_tpu.agent import train_dqn as dqn_cli
    from rl_scheduler_tpu.scheduler.extender import build_policy

    run_dir = dqn_cli.main([
        "--env", "multi_cloud", "--preset", "config1", "--iterations", "4",
        "--run-root", str(tmp_path), "--run-name", "dqn_serve_test",
        "--checkpoint-every", "4", "--hidden", "32,32",
    ])
    policy = build_policy(backend="cpu", run=str(run_dir))
    assert policy.backend.name == "cpu"  # not the greedy fallback
    result = policy.filter({
        "pod": {"metadata": {"name": "p"}},
        "nodes": {"items": [_node("n1", "aws"), _node("n2", "azure")]},
    })
    assert len(result["nodes"]["items"]) == 1


def test_build_policy_rejects_wrong_env_checkpoint(tmp_path):
    """A newest run from a different env family (different obs dim) must
    degrade to greedy at startup, not fail-open on every request."""
    from rl_scheduler_tpu.agent import train_dqn as dqn_cli
    from rl_scheduler_tpu.scheduler.extender import build_policy

    dqn_cli.main([
        "--env", "single_cluster", "--preset", "config1", "--iterations", "4",
        "--run-root", str(tmp_path), "--run-name", "sc_run",
        "--checkpoint-every", "4", "--hidden", "16,16",
    ])
    policy = build_policy(backend="cpu", run_root=str(tmp_path))
    assert policy.backend.name == "greedy"


def test_extender_bench_tool(server):
    """The loadgen benchmark drives a live server and reports percentiles."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "extender_bench",
        Path(__file__).resolve().parents[1] / "loadgen" / "extender_bench.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    srv, _ = server
    port = srv.server_address[1]
    out = mod.main(["--port", str(port), "--requests", "40",
                    "--threads", "4", "--warmup", "5"])
    assert out["requests"] == 40
    assert out["client_p50_ms"] > 0 and out["server_p50_ms"] > 0
    assert out["backend"] == "cpu"


def test_load_aware_jax_sheds_overflow_decisions_agree(params_tree):
    """The serving 'jax' flag (LoadAwareJaxBackend): at low concurrency it
    runs the AOT dispatcher; past max_concurrent_jax it routes to the
    native/numpy forward — and every routed decision agrees with the
    reference forward (argmax level; logits match to ~1e-4, not bitwise),
    so shedding is invisible to the scheduler."""
    import threading

    from rl_scheduler_tpu.scheduler.policy_backend import (
        LoadAwareJaxBackend,
    )

    backend = LoadAwareJaxBackend(params_tree, hidden=HIDDEN,
                                  max_concurrent_jax=1)
    # Pin the adaptive router healthy (host reading slow) so this test
    # isolates the ADMISSION routing deterministically — on a real host
    # the router may legitimately prefer the faster native forward
    # single-stream (covered by test_load_aware_mlp_adaptive_demotion).
    backend._adaptive.lat["host"][backend._KEY] = (10.0, 100)
    ref = NumpyMLPBackend(params_tree)
    rng = np.random.default_rng(5)
    obs_batch = rng.uniform(0, 1, size=(64, env_core.OBS_DIM)).astype(np.float32)

    # single-stream: all jax, nothing shed
    for obs in obs_batch[:8]:
        action, _ = backend.decide(obs)
        assert action == ref.decide(obs)[0]
    assert backend.shed_fraction == 0.0

    # 8 threads hammering max_concurrent_jax=1 MUST shed some requests,
    # and every decision still matches the reference forward.
    mismatches = []
    def worker(rows):
        for obs in rows:
            action, _ = backend.decide(obs)
            if action != ref.decide(obs)[0]:
                mismatches.append(obs)

    threads = [threading.Thread(target=worker, args=(obs_batch,))
               for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not mismatches
    assert backend.shed_fraction > 0.0
    assert backend.name == "jax"


def test_load_aware_mlp_adaptive_demotion(params_tree):
    """The MLP jax flag shares the set family's latency-aware router:
    once the AOT dispatch measures ADAPTIVE margin x worse than the
    host forward (a degraded tunnel/pool), single-stream traffic serves
    host-side with recovery probes that promote AOT back."""
    import time as _time

    from rl_scheduler_tpu.scheduler.policy_backend import (
        AdaptiveLatencyRouter,
        LoadAwareJaxBackend,
    )

    backend = LoadAwareJaxBackend(params_tree, hidden=HIDDEN)
    key = backend._KEY
    calls = []
    real_jax = backend._jax.decide
    real_host = backend._overflow.decide
    slow = [True]
    slow_host = [False]

    def jax_decide(o):
        calls.append("jax")
        if slow[0]:
            _time.sleep(0.01)           # a degraded 10 ms dispatch
        return real_jax(o)

    def host_decide(o):
        if slow_host[0]:
            _time.sleep(0.002)          # deterministic recovery margin
        return real_host(o)

    backend._jax.decide = jax_decide
    backend._overflow.decide = host_decide
    # Deterministic baselines: host fast, AOT unmeasured.
    backend._adaptive = AdaptiveLatencyRouter(label="AOT MLP dispatch")
    backend._adaptive.lat["host"][key] = (0.1, 3)

    rng = np.random.default_rng(8)
    obs = rng.uniform(0, 1, env_core.OBS_DIM).astype(np.float32)
    for _ in range(10):                  # accumulate >= min_samples
        backend.decide(obs)
    calls.clear()
    backend.decide(obs)
    assert calls == []                     # demoted: served host-side
    assert backend.reroute_fraction > 0.0  # counted as latency rerouting
    assert backend.shed_fraction == 0.0    # ...NOT as overload shedding

    # Recovery: the dispatch is fast again and the host path reads
    # slower (deterministic margin — on a real host the native forward
    # may legitimately stay the faster path, which is routing working,
    # not a recovery failure). Probes must promote AOT back.
    slow[0] = False
    slow_host[0] = True
    promoted = False
    for _ in range(40 * 32):
        calls.clear()
        backend.decide(obs)
        if (calls == ["jax"]
                and backend._adaptive.route_aot(key) == (True, False)
                and backend._adaptive.route_aot(key) == (True, False)):
            promoted = True
            break
    assert promoted, "recovered AOT dispatch was never promoted back"


def test_make_backend_jax_is_load_aware(params_tree):
    from rl_scheduler_tpu.scheduler.policy_backend import (
        LoadAwareJaxBackend,
    )

    backend, fell_back = make_backend("jax", params_tree, hidden=HIDDEN)
    assert isinstance(backend, LoadAwareJaxBackend) and not fell_back


# ------------------------------------------------ set-family (cluster_set)


@pytest.fixture(scope="module")
def set_params_tree():
    from rl_scheduler_tpu.models.transformer import SetTransformerPolicy

    net = SetTransformerPolicy(dim=64, depth=2)
    return net.init(jax.random.PRNGKey(3), jnp.zeros((8, 6), jnp.float32))


def _set_request(num_nodes=6, pod=None):
    nodes = [
        _node(f"n{i}", ("aws", "azure", None)[i % 3]) for i in range(num_nodes)
    ]
    args = {"nodes": {"items": nodes}}
    if pod is not None:
        args["pod"] = pod
    return args


def test_numpy_set_backend_matches_flax(set_params_tree):
    """The serving-side numpy set-transformer forward is the training-time
    flax function (XLA-CPU reference): logits to 1e-5, same argmax, and
    variable node counts with no per-shape compile."""
    from rl_scheduler_tpu.models.transformer import SetTransformerPolicy
    from rl_scheduler_tpu.scheduler.set_backend import NumpySetBackend

    net = SetTransformerPolicy(dim=64, depth=2)
    backend = NumpySetBackend(set_params_tree)
    cpu = jax.devices("cpu")[0]
    params_cpu = jax.device_put(set_params_tree, cpu)
    rng = np.random.default_rng(0)
    for n in (3, 8, 40):
        obs = rng.uniform(0, 1, size=(n, 6)).astype(np.float32)
        with jax.default_device(cpu):
            ref_logits, _ = jax.jit(net.apply)(params_cpu, jnp.asarray(obs))
        ref = np.asarray(ref_logits)
        action, logits = backend.decide_nodes(obs)
        np.testing.assert_allclose(logits, ref, atol=1e-5)
        assert action == int(np.argmax(ref))


def test_numpy_set_backend_multihead(set_params_tree):
    """Multi-head checkpoints (--num-heads 4) serve through the same numpy
    forward — the head split is shape-driven from the param tree."""
    from rl_scheduler_tpu.models.transformer import SetTransformerPolicy
    from rl_scheduler_tpu.scheduler.set_backend import NumpySetBackend

    net = SetTransformerPolicy(dim=64, depth=2, num_heads=4)
    tree = net.init(jax.random.PRNGKey(5), jnp.zeros((8, 6), jnp.float32))
    backend = NumpySetBackend(tree, num_heads=4)
    cpu = jax.devices("cpu")[0]
    obs = np.random.default_rng(1).uniform(0, 1, (10, 6)).astype(np.float32)
    with jax.default_device(cpu):
        ref_logits, _ = jax.jit(net.apply)(jax.device_put(tree, cpu),
                                           jnp.asarray(obs))
    _, logits = backend.decide_nodes(obs)
    np.testing.assert_allclose(logits, np.asarray(ref_logits), atol=1e-5)


def test_torch_set_backend_matches_numpy(set_params_tree):
    """VERDICT r4 item 5: --backend torch is a real set-policy forward
    (torch CPU mirror), agreeing with the numpy/flax function across
    node counts and head counts — no silent degrade to cpu."""
    from rl_scheduler_tpu.models.transformer import SetTransformerPolicy
    from rl_scheduler_tpu.scheduler.set_backend import (
        NumpySetBackend,
        TorchSetBackend,
        make_set_backend,
    )

    np_b = NumpySetBackend(set_params_tree)
    t_b = TorchSetBackend(set_params_tree)
    rng = np.random.default_rng(7)
    for n in (3, 8, 40):
        obs = rng.uniform(0, 1, size=(n, 6)).astype(np.float32)
        a_np, l_np = np_b.decide_nodes(obs)
        a_t, l_t = t_b.decide_nodes(obs)
        np.testing.assert_allclose(l_t, l_np, atol=1e-5)
        assert a_t == a_np

    # Multi-head checkpoints serve too (head split is shape-driven).
    net4 = SetTransformerPolicy(dim=64, depth=2, num_heads=4)
    tree4 = net4.init(jax.random.PRNGKey(6), jnp.zeros((8, 6), jnp.float32))
    obs = rng.uniform(0, 1, (10, 6)).astype(np.float32)
    _, l_np = NumpySetBackend(tree4, num_heads=4).decide_nodes(obs)
    _, l_t = TorchSetBackend(tree4, num_heads=4).decide_nodes(obs)
    np.testing.assert_allclose(l_t, l_np, atol=1e-5)

    # The --backend torch flag maps to the torch mirror, no fallback.
    backend, fell_back = make_set_backend("torch", set_params_tree)
    assert backend.name == "torch" and not fell_back


def test_jax_set_backend_agrees_and_caches_per_n(set_params_tree):
    """Warm node counts answer from the AOT executable; an unseen N is
    answered immediately by the numpy forward while the executable
    compiles in the background (compiles never block a request), then
    served AOT once it lands."""
    from rl_scheduler_tpu.scheduler.set_backend import (
        JaxSetAOTBackend,
        NumpySetBackend,
    )

    jax_b = JaxSetAOTBackend(set_params_tree, warm_counts=(4,))
    np_b = NumpySetBackend(set_params_tree)
    assert set(jax_b._compiled) == {4}
    rng = np.random.default_rng(2)
    for n in (4, 9, 4, 9):
        obs = rng.uniform(0, 1, size=(n, 6)).astype(np.float32)
        a_jax, l_jax = jax_b.decide_nodes(obs)  # never blocks on a compile
        a_np, l_np = np_b.decide_nodes(obs)
        np.testing.assert_allclose(l_jax, l_np, atol=1e-4)
        assert a_jax == a_np
    deadline = time.monotonic() + 60
    while set(jax_b._compiled) != {4, 9} and time.monotonic() < deadline:
        time.sleep(0.05)
    assert set(jax_b._compiled) == {4, 9}  # background compile landed
    obs = rng.uniform(0, 1, size=(9, 6)).astype(np.float32)
    a_jax, l_jax = jax_b.decide_nodes(obs)  # now AOT-served
    np.testing.assert_allclose(l_jax, np_b.decide_nodes(obs)[1], atol=1e-4)


def test_jax_set_backend_cache_is_bounded(set_params_tree):
    from rl_scheduler_tpu.scheduler.set_backend import JaxSetAOTBackend

    jax_b = JaxSetAOTBackend(set_params_tree, warm_counts=(3, 4), max_cached=2)
    rng = np.random.default_rng(3)
    for n in (5, 6, 7):
        jax_b.decide_nodes(rng.uniform(0, 1, size=(n, 6)).astype(np.float32))
    deadline = time.monotonic() + 60
    while (len(jax_b._compiled) != 2 or jax_b._compiling) and \
            time.monotonic() < deadline:
        time.sleep(0.05)
    assert len(jax_b._compiled) == 2  # LRU evicted down to the cap


def test_load_aware_set_routes_large_n_under_concurrency(set_params_tree):
    """VERDICT r4 item 2: a large-N request (N > NATIVE_OVERFLOW_MAX_N)
    arriving while another decision is in flight serves the uniform numpy
    path DIRECTLY — mixed AOT+overflow traffic GIL-churns at sustained
    saturation (measured 7.4 ms p50 at N=100 @8-way vs 1.4 ms uniform) —
    while single-stream large-N and all small-N requests keep the AOT
    primary."""
    from rl_scheduler_tpu.scheduler.set_backend import LoadAwareSetBackend

    b = LoadAwareSetBackend(set_params_tree)
    calls = []
    real_jax = b._jax.decide_nodes
    real_np = b._overflow_numpy.decide_nodes
    b._jax.decide_nodes = lambda o: (calls.append("jax"), real_jax(o))[1]
    b._overflow_numpy.decide_nodes = (
        lambda o: (calls.append("numpy"), real_np(o))[1])
    # Pre-seed the adaptive-latency EWMAs so this test isolates the
    # concurrency routing (the one-time host seed per N is covered by
    # test_load_aware_set_adaptive_demotion).
    b._lat["host"][40] = (0.5, 1)
    b._lat["host"][8] = (0.5, 1)
    rng = np.random.default_rng(4)
    big = rng.uniform(0, 1, (40, 6)).astype(np.float32)

    b.decide_nodes(big)                 # single-stream: AOT primary
    assert calls == ["jax"]

    calls.clear()
    b._tracker.enter()                  # deterministic in-flight decision
    try:
        b.decide_nodes(big)             # concurrent large-N: uniform numpy
    finally:
        b._tracker.exit()
    assert calls == ["numpy"]
    assert b.shed_fraction > 0.0        # the reroute counts as shed traffic

    # Cooldown: right after concurrency, a momentarily-single-stream
    # large-N request stays on the uniform path (arrival gaps in a
    # sustained load must not re-mix AOT traffic)...
    calls.clear()
    b.decide_nodes(big)
    assert calls == ["numpy"]
    # ...and once the cooldown expires, the AOT primary returns.
    calls.clear()
    b._tracker.force_quiet()
    b.decide_nodes(big)
    assert calls == ["jax"]

    calls.clear()
    b._tracker.enter()
    try:
        b.decide_nodes(big[:8])         # concurrent small-N: gate admits AOT
    finally:
        b._tracker.exit()
    assert calls == ["jax"]


def test_load_aware_set_routes_fleet_giant_n_to_torch(set_params_tree):
    """The host path routes by node count at the measured three-way
    crossover: native C++ to N=20, numpy through the mid range, torch's
    fused CPU kernels from TORCH_OVERFLOW_MIN_N up (3.6x numpy at
    N >= 1024)."""
    from rl_scheduler_tpu.scheduler.set_backend import LoadAwareSetBackend

    b = LoadAwareSetBackend(set_params_tree)
    mid = b._overflow_for(100)
    giant = b._overflow_for(LoadAwareSetBackend.TORCH_OVERFLOW_MIN_N)
    assert mid is b._overflow_numpy
    if b._overflow_torch is not None:
        assert giant is b._overflow_torch
    if b._overflow_native is not None:
        assert b._overflow_for(8) is b._overflow_native

    # Decisions agree across the three host paths (same function).
    rng = np.random.default_rng(11)
    obs = rng.uniform(0, 1, (256, 6)).astype(np.float32)
    actions = {b._overflow_numpy.decide_nodes(obs)[0]}
    if b._overflow_torch is not None:
        actions.add(b._overflow_torch.decide_nodes(obs)[0])
    assert len(actions) == 1


def test_load_aware_set_adaptive_demotion(set_params_tree):
    """Latency-aware routing: once the AOT dispatch measures
    ADAPTIVE_MARGIN x worse than the host path at a node count (a
    degraded tunnel/pool), single-stream traffic at that N serves
    host-side, with 1-in-ADAPTIVE_PROBE_EVERY recovery probes that
    promote AOT back when it recovers."""
    import time as _time

    from rl_scheduler_tpu.scheduler.set_backend import LoadAwareSetBackend

    # N=40 must be warm: timings only attribute to the AOT path when the
    # executable actually serves (the compiling-window numpy fallback
    # must not read as tunnel degradation).
    b = LoadAwareSetBackend(set_params_tree, warm_counts=(40,))
    calls = []
    real_jax = b._jax.decide_nodes
    real_overflow_for = b._overflow_for
    slow = [True]
    slow_host = [False]

    def jax_decide(o):
        calls.append("jax")
        if slow[0]:
            _time.sleep(0.01)           # a degraded 10 ms dispatch
        return real_jax(o)

    class SlowHost:
        def decide_nodes(self, o):
            if slow_host[0]:
                _time.sleep(0.002)      # deterministic recovery margin
            return real_overflow_for(len(o)).decide_nodes(o)

    b._jax.decide_nodes = jax_decide
    b._overflow_for = lambda n: SlowHost()
    rng = np.random.default_rng(5)
    obs = rng.uniform(0, 1, (40, 6)).astype(np.float32)

    # First request seeds the host EWMA (one extra host forward, once).
    b.decide_nodes(obs)
    assert b._lat["host"].get(40) is not None

    # Degraded phase: AOT keeps serving until it has MIN_SAMPLES, then
    # the EWMA comparison demotes it.
    for _ in range(LoadAwareSetBackend.ADAPTIVE_MIN_SAMPLES + 2):
        b.decide_nodes(obs)
    calls.clear()
    b.decide_nodes(obs)
    assert calls == []                  # served host-side, AOT demoted
    assert b.reroute_fraction > 0.0     # counted as latency rerouting...
    assert b.shed_fraction == 0.0       # ...NOT as overload shedding

    # Recovery: the dispatch is fast again and the host path reads
    # slower (deterministic margin — on a real host the numpy forward
    # may legitimately stay the faster path, which is routing working,
    # not a recovery failure). Probes must promote AOT back.
    slow[0] = False
    slow_host[0] = True
    promoted = False
    for _ in range(40 * LoadAwareSetBackend.ADAPTIVE_PROBE_EVERY):
        calls.clear()
        b.decide_nodes(obs)
        if (calls == ["jax"]
                and b._aot_route(40) == (True, False)
                and b._aot_route(40) == (True, False)):
            promoted = True
            break
    assert promoted, "recovered AOT path was never promoted back"


def test_adaptive_ignores_compiling_fallback(set_params_tree):
    """While an uncached N compiles in the background, decisions are
    served by the numpy fallback — those timings must NOT feed the AOT
    latency EWMA (they would false-demote a healthy AOT path at exactly
    the Ns that compile on demand, re-triggering on every LRU evict)."""
    from rl_scheduler_tpu.scheduler.set_backend import LoadAwareSetBackend

    b = LoadAwareSetBackend(set_params_tree)
    b._jax.has_executable = lambda n: False   # pin the compiling window
    rng = np.random.default_rng(6)
    obs = rng.uniform(0, 1, (24, 6)).astype(np.float32)
    for _ in range(LoadAwareSetBackend.ADAPTIVE_MIN_SAMPLES + 4):
        b.decide_nodes(obs)
    assert b._lat["aot"].get(24) is None      # nothing attributed to AOT
    assert b._aot_route(24) == (True, False)  # and no demotion possible


def test_max_score_nodes_caps_structured_scoring(set_params_tree):
    """--max-score-nodes K (kube's percentageOfNodesToScore idea): the
    per-node forward sees at most K candidates per request; unsampled
    nodes score 0; /filter still keeps exactly one (sampled) node."""
    from rl_scheduler_tpu.scheduler.set_backend import NumpySetBackend

    backend = NumpySetBackend(set_params_tree)
    seen_shapes = []
    real = backend.decide_nodes
    backend.decide_nodes = (
        lambda o: (seen_shapes.append(np.asarray(o).shape), real(o))[1])
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=3))
    policy = ExtenderPolicy(backend, telemetry, max_score_nodes=8)

    args = _set_request(num_nodes=30)
    scores = policy.prioritize(args)
    assert len(scores) == 30                     # every candidate answered
    assert seen_shapes[-1][0] == 8               # forward saw the cap only
    positive = [s for s in scores if s["score"] > 0]
    assert 1 <= len(positive) <= 8               # unsampled nodes score 0
    assert max(s["score"] for s in scores) == MAX_EXTENDER_SCORE

    out = policy.filter(args)
    kept = out["nodes"]["items"]
    assert len(kept) == 1 and len(out["failedNodes"]) == 29
    assert seen_shapes[-1][0] == 8

    # Below the cap nothing changes: the forward sees the full list.
    policy.prioritize(_set_request(num_nodes=5))
    assert seen_shapes[-1][0] == 5

    # Successive requests sample independently (no node is permanently
    # unscoreable): over a few requests the union of scored nodes grows
    # past one sample's worth.
    scored = set()
    for _ in range(6):
        for s in policy.prioritize(args):
            if s["score"] > 0:
                scored.add(s["host"])
    assert len(scored) > 8


def test_max_score_nodes_flat_family_refused():
    """The cap bounds the structured families' per-node forward; a flat
    (cloud-decision) serving stack refuses it before traffic."""
    from rl_scheduler_tpu.scheduler.extender import build_policy

    with pytest.raises(ValueError, match="candidate cap"):
        build_policy("greedy", max_score_nodes=4)
    with pytest.raises(SystemExit, match="cap >= 2"):
        from rl_scheduler_tpu.scheduler import extender as cli

        cli.main(["--max-score-nodes", "1"])
    # Programmatic entry points refuse bad ranges too (a negative cap
    # would make random.sample raise inside the fail-open handlers —
    # every request would silently passthrough).
    with pytest.raises(ValueError, match="cap >= 2"):
        build_policy("greedy", max_score_nodes=-4)
    with pytest.raises(ValueError, match="cap >= 2"):
        ExtenderPolicy(GreedyBackend(),
                       TableTelemetry.from_table(), max_score_nodes=1)


def test_set_filter_keeps_argmax_node(set_params_tree):
    """/filter with a set backend keeps exactly the policy's argmax node
    (including unknown-cloud candidates, which score from neutral
    features)."""
    from rl_scheduler_tpu.scheduler.set_backend import NumpySetBackend

    backend = NumpySetBackend(set_params_tree)
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=9))
    policy = ExtenderPolicy(backend, telemetry)
    assert policy.family == "set"

    # Twin telemetry (same seed) reproduces the observation the policy
    # will build, giving the expected decision independently.
    twin = TableTelemetry.from_table(cpu_source=RandomCpu(seed=9))
    args = _set_request(num_nodes=6)
    clouds = [node_cloud(n) for n in args["nodes"]["items"]]
    from rl_scheduler_tpu.scheduler.extender import DEFAULT_POD_CPU

    expected, _ = backend.decide_nodes(twin.observe_nodes(clouds, DEFAULT_POD_CPU))

    result = policy.filter(args)
    kept = result["nodes"]["items"]
    assert len(kept) == 1
    assert kept[0]["metadata"]["name"] == f"n{expected}"
    assert len(result["failedNodes"]) == 5
    assert result["error"] == ""


def test_set_prioritize_scores_follow_logits(set_params_tree):
    from rl_scheduler_tpu.scheduler.extender import DEFAULT_POD_CPU
    from rl_scheduler_tpu.scheduler.set_backend import NumpySetBackend

    backend = NumpySetBackend(set_params_tree)
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=11))
    policy = ExtenderPolicy(backend, telemetry)
    twin = TableTelemetry.from_table(cpu_source=RandomCpu(seed=11))

    args = _set_request(num_nodes=8)
    clouds = [node_cloud(n) for n in args["nodes"]["items"]]
    _, logits = backend.decide_nodes(twin.observe_nodes(clouds, DEFAULT_POD_CPU))

    out = policy.prioritize(args)
    scores = np.array([entry["score"] for entry in out])
    assert scores.max() == 100  # argmax node always gets the full score
    assert scores[np.argmax(logits)] == 100
    # Rank-preserving (monotone in the logits; integer rounding may tie).
    for i in range(len(logits)):
        for j in range(len(logits)):
            if logits[i] > logits[j]:
                assert scores[i] >= scores[j]
    assert all(0 <= s <= 100 for s in scores)


def test_set_filter_fails_open(set_params_tree):
    class ExplodingSet:
        name = "cpu"
        family = "set"

        def decide_nodes(self, obs):
            raise RuntimeError("boom")

    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))
    policy = ExtenderPolicy(ExplodingSet(), telemetry)
    args = _set_request(num_nodes=4)
    result = policy.filter(args)
    assert len(result["nodes"]["items"]) == 4  # all passed through
    assert result["error"] == ""
    out = policy.prioritize(args)
    assert [e["score"] for e in out] == [50, 50, 50, 50]


def test_set_stats_track_unknown_cloud(set_params_tree):
    from rl_scheduler_tpu.scheduler.set_backend import NumpySetBackend

    backend = NumpySetBackend(set_params_tree)
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=1))
    policy = ExtenderPolicy(backend, telemetry)
    for _ in range(5):
        policy.filter(_set_request(num_nodes=6))
    stats = policy.statistics()
    assert stats["family"] == "set"
    assert set(stats["decisions"]) == {"aws", "azure", "unknown"}
    assert sum(stats["decisions"].values()) == 5
    assert stats["latency"]["count"] == 5


def test_observe_nodes_features():
    """Node features line up with training columns (env/cluster_set.py):
    known clouds take their table column, unknown nodes the cross-cloud
    mean with cloud_id 0.5; pod_cpu/step_frac broadcast."""
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=4))
    obs = telemetry.observe_nodes(["aws", "azure", None], pod_cpu=0.3)
    assert obs.shape == (3, 6) and obs.dtype == np.float32
    costs, lats = telemetry.costs[0], telemetry.latencies[0]
    np.testing.assert_allclose(obs[0, 0], costs[0])
    np.testing.assert_allclose(obs[1, 0], costs[1])
    np.testing.assert_allclose(obs[2, 0], costs.mean())
    np.testing.assert_allclose(obs[:, 1], [lats[0], lats[1], lats.mean()])
    assert list(obs[:, 3]) == [0.0, 1.0, 0.5]
    np.testing.assert_allclose(obs[:, 4], 0.3)
    np.testing.assert_allclose(obs[:, 5], 0.0)  # step 0
    # cpu column: unknown = mean of the two cloud readings
    np.testing.assert_allclose(obs[2, 2], obs[:2, 2].mean())


def test_pod_cpu_fraction():
    from rl_scheduler_tpu.scheduler.extender import (
        DEFAULT_POD_CPU,
        pod_cpu_fraction,
    )

    def pod(*cpus):
        return {"spec": {"containers": [
            {"resources": {"requests": {"cpu": c}}} for c in cpus
        ]}}

    assert pod_cpu_fraction(pod("500m", "500m")) == 0.25  # 1 core / 4
    assert pod_cpu_fraction(pod("2")) == 0.5
    assert pod_cpu_fraction(pod("16")) == 1.0  # clipped
    assert pod_cpu_fraction(pod("1"), capacity_cores=8.0) == 0.125
    assert pod_cpu_fraction(None) == DEFAULT_POD_CPU
    assert pod_cpu_fraction({}) == DEFAULT_POD_CPU
    assert pod_cpu_fraction(pod("weird")) == DEFAULT_POD_CPU
    assert pod_cpu_fraction({"spec": {"containers": "nonsense"}}) == DEFAULT_POD_CPU


def test_build_policy_serves_cluster_set_checkpoint(tmp_path):
    """End-to-end: train a tiny cluster_set run through the CLI, then serve
    it — the round-3 refusal (structured policies unservable) is closed."""
    from rl_scheduler_tpu.agent import train_ppo as ppo_cli

    run_dir = ppo_cli.main([
        "--env", "cluster_set", "--preset", "quick", "--iterations", "2",
        "--num-envs", "8", "--rollout-steps", "20", "--minibatch-size", "40",
        "--num-epochs", "2", "--run-root", str(tmp_path),
        "--run-name", "set_serve_test", "--checkpoint-every", "2",
    ])
    policy = build_policy(backend="cpu", run=str(run_dir))
    assert policy.family == "set"
    assert policy.backend.name == "cpu"
    result = policy.filter(_set_request(num_nodes=5))
    assert len(result["nodes"]["items"]) == 1
    out = policy.prioritize(_set_request(num_nodes=5))
    assert len(out) == 5 and max(e["score"] for e in out) == 100

    # jax flag: the AOT warm list defaults to the checkpoint's own
    # training N (this run trained at the default 8), and --warm-nodes
    # overrides it (round 5: fleet checkpoints warm their fleet size).
    policy = build_policy(backend="jax", run=str(run_dir))
    assert set(policy.backend._jax._compiled) == {8}
    policy = build_policy(backend="jax", run=str(run_dir),
                          warm_nodes=(5, 12))
    assert set(policy.backend._jax._compiled) == {5, 12}


def test_http_set_roundtrip(set_params_tree):
    """Full HTTP round-trip with a set backend: filter keeps one node,
    prioritize scores every node, stats report the set family."""
    from rl_scheduler_tpu.scheduler.set_backend import NumpySetBackend

    backend = NumpySetBackend(set_params_tree)
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=2))
    policy = ExtenderPolicy(backend, telemetry)
    srv = make_server(policy, host="127.0.0.1", port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        port = srv.server_address[1]
        payload = _set_request(num_nodes=7)
        result = _post(port, "/filter", payload)
        assert len(result["nodes"]["items"]) == 1
        out = _post(port, "/prioritize", payload)
        assert len(out) == 7
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5
        ) as resp:
            health = json.loads(resp.read())
        assert health == {"status": "ok", "backend": "cpu", "family": "set"}
    finally:
        srv.shutdown()


def test_set_jax_flag_is_load_aware(set_params_tree):
    """The set family's 'jax' serving flag sheds overflow concurrency to
    the numpy forward with agreeing decisions (same contract as the MLP
    family's LoadAwareJaxBackend)."""
    from rl_scheduler_tpu.scheduler.set_backend import (
        LoadAwareSetBackend,
        NumpySetBackend,
        make_set_backend,
    )

    backend, fell_back = make_set_backend("jax", set_params_tree)
    assert isinstance(backend, LoadAwareSetBackend) and not fell_back

    shed = LoadAwareSetBackend(set_params_tree, max_concurrent_jax=1)
    ref = NumpySetBackend(set_params_tree)
    rng = np.random.default_rng(7)
    obs_batch = rng.uniform(0, 1, size=(32, 8, 6)).astype(np.float32)
    for obs in obs_batch[:4]:
        assert shed.decide_nodes(obs)[0] == ref.decide_nodes(obs)[0]
    assert shed.shed_fraction == 0.0

    mismatches = []

    def worker():
        for obs in obs_batch:
            if shed.decide_nodes(obs)[0] != ref.decide_nodes(obs)[0]:
                mismatches.append(obs)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not mismatches
    assert shed.shed_fraction > 0.0


def test_make_set_backend_flag_mapping(set_params_tree):
    """torch serves the torch CPU mirror (round 5; it degraded to numpy
    before); native serves the C++ set core when the toolchain can build
    it (else numpy)."""
    from rl_scheduler_tpu.native import ensure_built_set
    from rl_scheduler_tpu.scheduler.set_backend import (
        NativeSetBackend,
        NumpySetBackend,
        TorchSetBackend,
        make_set_backend,
    )

    backend, fell_back = make_set_backend("torch", set_params_tree)
    assert isinstance(backend, TorchSetBackend) and not fell_back

    backend, fell_back = make_set_backend("native", set_params_tree)
    expected = NativeSetBackend if ensure_built_set() else NumpySetBackend
    assert isinstance(backend, expected) and not fell_back


def test_native_set_backend_matches_numpy(set_params_tree):
    """The C++ set-transformer forward (native/set_infer.cpp) is the same
    function as the numpy/flax forwards — logits to 2e-5 across node and
    head counts — and agrees under concurrent callers (it is the
    load-aware overflow path, running GIL-free)."""
    from rl_scheduler_tpu.models.transformer import SetTransformerPolicy
    from rl_scheduler_tpu.native import ensure_built_set
    from rl_scheduler_tpu.scheduler.set_backend import (
        NativeSetBackend,
        NumpySetBackend,
    )

    if ensure_built_set() is None:
        pytest.skip("no C++ toolchain on this machine")

    rng = np.random.default_rng(8)
    for heads in (1, 4):
        net = SetTransformerPolicy(dim=64, depth=2, num_heads=heads)
        tree = net.init(jax.random.PRNGKey(heads), jnp.zeros((8, 6)))
        native = NativeSetBackend(tree)
        ref = NumpySetBackend(tree)
        for n in (3, 8, 40):
            obs = rng.uniform(0, 1, size=(n, 6)).astype(np.float32)
            a_nat, l_nat = native.decide_nodes(obs)
            a_ref, l_ref = ref.decide_nodes(obs)
            np.testing.assert_allclose(l_nat, l_ref, atol=2e-5)
            assert a_nat == a_ref

    # Concurrency: 8 threads, one shared handle, all decisions agree.
    net = SetTransformerPolicy(dim=64, depth=2)
    tree = net.init(jax.random.PRNGKey(0), jnp.zeros((8, 6)))
    native, ref = NativeSetBackend(tree), NumpySetBackend(tree)
    batch = rng.uniform(0, 1, size=(32, 8, 6)).astype(np.float32)
    expected = [ref.decide_nodes(o)[0] for o in batch]
    mismatches = []

    def worker():
        for o, e in zip(batch, expected):
            if native.decide_nodes(o)[0] != e:
                mismatches.append(o)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not mismatches


def test_make_set_backend_garbage_params_falls_back_to_greedy():
    from rl_scheduler_tpu.scheduler.set_backend import make_set_backend

    backend, fell_back = make_set_backend("cpu", {"params": {"bogus": {}}})
    assert backend.name == "greedy" and fell_back


# ---------------------------------------------- graph-family (cluster_graph)


@pytest.fixture(scope="module")
def gnn_fixture():
    """(params_tree, net, adjacency) for an 8-node training topology."""
    import numpy as _np

    from rl_scheduler_tpu.env.cluster_graph import build_topology
    from rl_scheduler_tpu.models import GNNPolicy

    _, adj, _ = build_topology(8)
    net = GNNPolicy.from_adjacency(adj, dim=64, depth=3)
    tree = net.init(jax.random.PRNGKey(4), jnp.zeros((8, 7), jnp.float32))
    return tree, net, _np.asarray(adj)


def test_numpy_gnn_backend_matches_flax(gnn_fixture):
    """The serving-side numpy GCN forward is the training-time flax
    function, on the training topology AND an arbitrary other one."""
    import numpy as _np

    from rl_scheduler_tpu.models import GNNPolicy
    from rl_scheduler_tpu.scheduler.graph_backend import (
        NumpyGNNBackend,
        topology_for_clouds,
    )

    tree, net, adj = gnn_fixture
    backend = NumpyGNNBackend(tree)
    cpu = jax.devices("cpu")[0]
    rng = np.random.default_rng(0)

    for test_adj, n in ((adj, 8), (topology_for_clouds(
            ["aws"] * 3 + ["azure"] * 2 + [None])[0], 6)):
        obs = rng.uniform(0, 1, size=(n, 7)).astype(np.float32)
        ref_net = GNNPolicy.from_adjacency(test_adj, dim=64, depth=3)
        with jax.default_device(cpu):
            ref_logits, _ = jax.jit(ref_net.apply)(
                jax.device_put(tree, cpu), jnp.asarray(obs))
        action, logits = backend.decide_nodes(obs, _np.asarray(test_adj))
        np.testing.assert_allclose(logits, np.asarray(ref_logits), atol=1e-5)
        assert action == int(np.argmax(np.asarray(ref_logits)))


def test_topology_for_clouds_matches_training_topology():
    """For the canonical first-half-aws ordering, the serving topology
    reproduces env/cluster_graph.py::build_topology bit-for-bit."""
    from rl_scheduler_tpu.env.cluster_graph import build_topology
    from rl_scheduler_tpu.scheduler.graph_backend import topology_for_clouds

    for n in (4, 8):
        _, env_adj, env_hops = build_topology(n)
        adj, hops = topology_for_clouds(
            ["aws"] * (n // 2) + ["azure"] * (n - n // 2))
        np.testing.assert_array_equal(adj, np.asarray(env_adj))
        np.testing.assert_array_equal(hops, np.asarray(env_hops))
    # Unknown-cloud nodes form their own connected group.
    adj, hops = topology_for_clouds(["aws", "aws", None, "azure"])
    assert np.isfinite(hops).all()  # connected
    # Single-cloud requests are just that cloud's ring.
    adj, hops = topology_for_clouds(["aws"] * 5)
    assert np.isfinite(hops).all() and adj.sum() > 0


def test_graph_filter_prioritize_and_affinity(gnn_fixture):
    from rl_scheduler_tpu.scheduler.graph_backend import (
        AFFINITY_ANNOTATION,
        NumpyGNNBackend,
    )

    tree, _, _ = gnn_fixture
    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=21))
    policy = ExtenderPolicy(NumpyGNNBackend(tree), telemetry)
    assert policy.family == "graph"

    args = _set_request(num_nodes=6)
    result = policy.filter(args)
    assert len(result["nodes"]["items"]) == 1
    assert result["error"] == ""
    out = policy.prioritize(_set_request(num_nodes=6))
    scores = [e["score"] for e in out]
    assert len(scores) == 6 and max(scores) == 100

    # The affinity annotation changes the hops feature (and is honored
    # when it names a candidate node): decisions may differ.
    pod = {"metadata": {"name": "p",
                        "annotations": {AFFINITY_ANNOTATION: "n3"}}}
    result = policy.filter(_set_request(num_nodes=6, pod=pod))
    assert len(result["nodes"]["items"]) == 1  # still a single argmax node

    stats = policy.statistics()
    assert stats["family"] == "graph"
    assert stats["latency"]["count"] == 3


def test_graph_filter_fails_open(gnn_fixture):
    class ExplodingGraph:
        name = "cpu"
        family = "graph"

        def decide_nodes(self, obs, adj):
            raise RuntimeError("boom")

    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))
    policy = ExtenderPolicy(ExplodingGraph(), telemetry)
    args = _set_request(num_nodes=4)
    assert len(policy.filter(args)["nodes"]["items"]) == 4
    assert [e["score"] for e in policy.prioritize(args)] == [50] * 4


def test_stats_exposes_shed_fraction(set_params_tree, telemetry):
    """/stats carries the load-aware backends' off-primary fraction —
    the same signal /metrics exports — so operators see routing without
    a Prometheus stack."""
    from rl_scheduler_tpu.scheduler.set_backend import LoadAwareSetBackend

    policy = ExtenderPolicy(LoadAwareSetBackend(set_params_tree), telemetry)
    assert policy.statistics()["shed_fraction"] == 0.0
    # Greedy has no shed_fraction: the key is absent, not zero.
    assert "shed_fraction" not in ExtenderPolicy(
        GreedyBackend(), telemetry).statistics()


def test_warm_nodes_flag_validation(monkeypatch):
    from rl_scheduler_tpu.scheduler import extender as ext

    with pytest.raises(SystemExit, match="comma-separated"):
        ext.main(["--warm-nodes", "8,x"])
    with pytest.raises(SystemExit, match="positive"):
        ext.main(["--warm-nodes", "0"])

    # No-op refusal: a non-set family (or a warm-compile failure that
    # degraded to greedy) must not boot as if the fleet sizes were warm.
    class StubGraphPolicy:
        family = "graph"
        backend = GreedyBackend()

    monkeypatch.setattr(ext, "build_policy", lambda *a, **k: StubGraphPolicy())
    with pytest.raises(SystemExit, match="warm-nodes applies"):
        ext.main(["--warm-nodes", "64", "--port", "0"])


def test_price_replay_period_flag_validation():
    from rl_scheduler_tpu.scheduler import extender as ext

    with pytest.raises(SystemExit, match="positive"):
        ext.main(["--price-replay-period", "0"])
    # a non-default period with counter mode is a no-op: refuse loudly
    with pytest.raises(SystemExit, match="wallclock"):
        ext.main(["--price-replay-period", "60"])


def test_price_replay_period_reaches_replay(monkeypatch):
    """--price-replay-period threads through build_policy into the
    wallclock RawPriceReplay."""
    from rl_scheduler_tpu.scheduler import extender as ext

    captured = {}

    class StubGraphPolicy:
        family = "graph"
        backend = GreedyBackend()

        def __init__(self, backend, telemetry, placer=None,
                     node_capacity_cores=4.0, price_replay="counter",
                     price_replay_period_s=300.0, max_score_nodes=0,
                     price_counter=None):
            captured["mode"] = price_replay
            captured["period"] = price_replay_period_s

    monkeypatch.setattr(ext, "ExtenderPolicy", StubGraphPolicy)
    ext.build_policy(backend="greedy", price_replay="wallclock",
                     price_replay_period_s=60.0)
    assert captured == {"mode": "wallclock", "period": 60.0}


def test_price_replay_refused_for_non_graph_family(monkeypatch):
    """price_replay='wallclock' on a non-graph policy refuses loudly at
    EVERY entry point — build_policy raises ValueError (embeddings,
    tests), and the CLI converts build_policy refusals to a clean
    SystemExit — instead of silently doing nothing (the flag drives the
    graph family's raw-dollar replay only)."""
    from rl_scheduler_tpu.scheduler import extender as ext

    class StubSetPolicy:
        family = "set"
        backend = GreedyBackend()

        def __init__(self, *a, **k):
            pass

    monkeypatch.setattr(ext, "ExtenderPolicy", StubSetPolicy)
    with pytest.raises(ValueError, match="cluster_graph"):
        ext.build_policy(backend="greedy", price_replay="wallclock")

    def raising_build_policy(*a, **k):
        raise ValueError("price replay drives the cluster_graph family")

    monkeypatch.setattr(ext, "build_policy", raising_build_policy)
    with pytest.raises(SystemExit, match="cluster_graph"):
        ext.main(["--price-replay", "wallclock", "--port", "0"])


def test_raw_price_replay_semantics():
    """VERDICT r4 item 6: pin the replay-position semantics. 'counter'
    is process-local — a restart (fresh instance) reproduces the SAME
    row sequence from 0, and two replicas walk identical but independent
    trajectories. 'wallclock' derives the row from wall time, so
    replicas and restarts agree with no coordination and the row
    advances with time, not traffic."""
    from rl_scheduler_tpu.scheduler.graph_backend import RawPriceReplay

    prices = np.arange(10, dtype=np.float32).reshape(5, 2)

    # counter: deterministic sequence, restart starts over
    a = RawPriceReplay(prices)
    seq_a = [a.next_row()[0][0] for _ in range(7)]  # wraps at T=5
    restarted = RawPriceReplay(prices)
    seq_b = [restarted.next_row()[0][0] for _ in range(7)]
    assert seq_a == seq_b                   # restart = same trajectory
    assert seq_a[:5] == [0.0, 2.0, 4.0, 6.0, 8.0] and seq_a[5] == 0.0

    # wallclock: all instances agree at the same instant; the row
    # advances with time and survives restarts
    t = [1000.0]
    mk = lambda: RawPriceReplay(prices, mode="wallclock", period_s=300.0,
                                now_fn=lambda: t[0])
    r1, r2 = mk(), mk()
    row1, frac1 = r1.next_row()
    row2, frac2 = r2.next_row()
    assert row1[0] == row2[0] and frac1 == frac2    # replicas agree
    assert r1.next_row()[0][0] == row1[0]           # traffic doesn't advance
    t[0] += 300.0
    assert r1.next_row()[0][0] != row1[0]           # time does
    t[0] -= 300.0
    assert mk().next_row()[0][0] == row1[0]         # restart agrees

    with pytest.raises(ValueError, match="replay mode"):
        RawPriceReplay(prices, mode="bogus")
    with pytest.raises(ValueError, match="positive"):
        RawPriceReplay(prices, mode="wallclock", period_s=0.0)


def test_build_policy_serves_cluster_graph_checkpoint(tmp_path):
    """End-to-end: train a tiny cluster_graph run through the CLI on the
    FUSED kernel path (--fused-gnn; interpret mode on CPU) and serve it —
    covering the 'fused_gnn checkpoints are the same tree' serving
    claim, not just the flax path."""
    from rl_scheduler_tpu.agent import train_ppo as ppo_cli

    run_dir = ppo_cli.main([
        "--env", "cluster_graph", "--preset", "quick", "--fused-gnn",
        "--iterations", "2",
        "--num-envs", "8", "--rollout-steps", "20", "--minibatch-size", "40",
        "--num-epochs", "2", "--run-root", str(tmp_path),
        "--run-name", "graph_serve_test", "--checkpoint-every", "2",
    ])
    policy = build_policy(backend="jax", run=str(run_dir))
    assert policy.family == "graph"
    assert policy.backend.name == "cpu"  # all flags map to the numpy GCN
    result = policy.filter(_set_request(num_nodes=5))
    assert len(result["nodes"]["items"]) == 1
    out = policy.prioritize(_set_request(num_nodes=5))
    assert len(out) == 5 and max(e["score"] for e in out) == 100


def test_stats_reset_scopes_measurement_window(telemetry):
    """POST /stats/reset clears the latency ring (decision counters stay)
    so consecutive bench runs don't contaminate each other's percentiles
    (the ring holds 4096 entries — ~3 bench runs)."""
    policy = ExtenderPolicy(GreedyBackend(), telemetry)
    for _ in range(5):
        policy.filter({"nodenames": ["aws-w", "azure-w"], "pod": {}})
    assert policy.statistics()["latency"]["count"] == 5
    out = policy.reset_stats()
    assert out == {"status": "reset"}
    stats = policy.statistics()
    assert stats["latency"]["count"] == 0  # ring cleared
    # graftlens: the lifetime histogram numbers survive the reset (the
    # merge-safe decisionview inputs must stay monotonic).
    assert stats["latency"]["lifetime_count"] == 5
    assert sum(stats["decisions"].values()) == 5  # counters survive

    srv = make_server(policy, host="127.0.0.1", port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        port = srv.server_address[1]
        _post(port, "/filter", {"nodenames": ["aws-w"], "pod": {}})
        assert _post(port, "/stats/reset", {}) == {"status": "reset"}
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=5
        ) as resp:
            assert json.loads(resp.read())["latency"]["count"] == 0
    finally:
        srv.shutdown()


def test_malformed_payloads_fail_open_not_closed(server):
    """Structurally malformed (but valid-JSON) payloads must never drop
    the connection OR silently answer "zero feasible nodes": whole-field
    junk echoes the request through (passthrough), junk ITEMS are dropped
    while the real nodes still get scored. Round-4 fix: these shapes
    previously raised inside the handler and closed the socket with no
    response."""
    srv, _ = server
    port = srv.server_address[1]
    # Whole-field junk: passthrough — the request's fields echo back.
    for payload in ({"nodes": "garbage"}, {"nodes": {"items": "nope"}}):
        result = _post(port, "/filter", payload)
        assert result["nodes"] == payload["nodes"]  # echoed, not emptied
        assert result["error"] == ""
        assert _post(port, "/prioritize", payload) == []
    result = _post(port, "/filter", {"nodenames": 42})
    assert result["nodenames"] == 42 and result["error"] == ""

    # Junk items dropped; REAL nodes still scored (never rejected in
    # favor of a junk candidate): kept + failed must cover exactly n1/n2.
    payload = {
        "nodes": {"items": [None, 7,
                            {"metadata": {"name": "n1",
                                          "labels": {"cloud": "aws"}}},
                            {"metadata": {"name": "n2",
                                          "labels": {"cloud": "azure"}}}]},
        "pod": "not-a-pod",
    }
    result = _post(port, "/filter", payload)
    kept = {n["metadata"]["name"] for n in result["nodes"]["items"]}
    assert kept | set(result["failedNodes"]) == {"n1", "n2"}
    assert len(kept) == 1  # the cloud decision still fired
    prio = _post(port, "/prioritize", payload)
    assert {e["host"] for e in prio} == {"n1", "n2"}


def test_malformed_payloads_structured_family(set_params_tree):
    """Same contract for the set family: junk items can never win the
    pointer argmax (they are dropped before scoring), and whole-field
    junk passes through."""
    from rl_scheduler_tpu.scheduler.set_backend import NumpySetBackend

    telemetry = TableTelemetry.from_table(cpu_source=RandomCpu(seed=3))
    policy = ExtenderPolicy(NumpySetBackend(set_params_tree), telemetry)
    junk_items = {"nodes": {"items": [7, None, _node("real-1", "aws"),
                                      _node("real-2", "azure")]}}
    for _ in range(4):  # across table rows: winner is always a real node
        result = policy.filter(junk_items)
        assert len(result["nodes"]["items"]) == 1
        assert result["nodes"]["items"][0]["metadata"]["name"] in (
            "real-1", "real-2")
    out = policy.prioritize(junk_items)
    assert {e["host"] for e in out} == {"real-1", "real-2"}
    result = policy.filter({"nodes": "garbage"})
    assert result["nodes"] == "garbage" and result["error"] == ""


def test_request_nodes_drops_junk():
    """_request_nodes never raises on junk field types; junk items are
    EXCLUDED from the candidate set (not scored as neutral unknowns)."""
    fn = ExtenderPolicy._request_nodes
    assert fn({"nodes": "garbage"}) == (False, [], [], [])
    assert fn({"nodes": {"items": "nope"}}) == (False, [], [], [])
    assert fn({"nodenames": 42}) == (False, [], [], [])
    use_names, sources, display, clouds = fn(
        {"nodes": {"items": [None, {"metadata": {"name": "aws-1"}}, 7]}}
    )
    assert not use_names and len(sources) == 1
    assert display == ["aws-1"] and clouds == ["aws"]
    use_names, sources, display, clouds = fn({"nodenames": ["a-aws", 9, None]})
    assert use_names and sources == ["a-aws"] and clouds == ["aws"]


# ------------------------------------------- serving-surface coverage
# GL007 extended OP_DIRS over scheduler/ with graftroll: every public
# op of the serving plane needs a test reference; these pin behavior
# for names the protocol/e2e suites exercised only indirectly.


def test_native_mlp_backend_matches_numpy_or_degrades(params_tree):
    """The C++ core serves the identical decision as numpy where the
    toolchain/.so exists; where it doesn't, construction raises and
    make_backend's documented degradation hands out the numpy path."""
    from rl_scheduler_tpu.scheduler.policy_backend import NativeMLPBackend

    numpy_b = NumpyMLPBackend(params_tree)
    try:
        native_b = NativeMLPBackend(params_tree)
    except Exception:
        backend, fell_back = make_backend("native", params_tree, HIDDEN)
        assert backend.name in ("cpu", "greedy") and not isinstance(
            backend, NativeMLPBackend)
        return
    for seed in range(20):
        obs = np.random.default_rng(seed).uniform(
            0, 1, env_core.OBS_DIM).astype(np.float32)
        action_np, logits_np = numpy_b.decide(obs)
        action_nat, logits_nat = native_b.decide(obs)
        assert action_nat == action_np
        np.testing.assert_allclose(logits_nat, logits_np, atol=2e-5)


def test_concurrency_tracker_counts_and_forces_quiet():
    """ConcurrencyTracker backs the load-aware admission decisions:
    enter() reports whether another decision is in flight, clean_since
    observes a quiet window, force_quiet resets the high-water mark."""
    from rl_scheduler_tpu.scheduler.policy_backend import ConcurrencyTracker

    tracker = ConcurrencyTracker()
    t0 = time.monotonic()
    assert tracker.enter() is False          # first in-flight: alone
    assert tracker.enter() is True           # second: concurrent
    assert tracker.last_concurrent >= t0     # the join stamped the clock
    tracker.exit()
    tracker.exit()
    assert tracker.clean_since(time.monotonic()) is True
    assert tracker.clean_since(t0) is False  # the burst happened after t0
    tracker.force_quiet()
    assert tracker.clean_since(t0) is True


def test_shed_gate_admits_bounded_inflight_and_tracks_fraction():
    """ShedGate bounds in-flight primary-path decisions; overflow is
    shed and counted into shed_fraction."""
    from rl_scheduler_tpu.scheduler.policy_backend import ShedGate

    gate = ShedGate(max_inflight=1)
    ok, reason = gate.admit()
    assert ok and reason is None
    ok, reason = gate.admit()
    assert not ok and "saturated" in reason  # overflow: shed, logged once
    gate.record_shed("large-N reroute")      # caller-side off-primary
    gate.release()
    assert gate.shed_fraction == pytest.approx(2 / 3)


def test_make_graph_backend_and_build_graph_obs(params_tree):
    """The graph family's public constructors: make_graph_backend maps
    every flag onto the numpy GCN forward, and build_graph_obs emits the
    [N, 7] training column order with unknown-cloud nodes on neutral
    features."""
    from rl_scheduler_tpu.env.cluster_graph import build_topology
    from rl_scheduler_tpu.models import GNNPolicy
    from rl_scheduler_tpu.scheduler.graph_backend import (
        build_graph_obs,
        make_graph_backend,
        topology_for_clouds,
    )

    _, adj0, _ = build_topology(8)
    net = GNNPolicy.from_adjacency(adj0, dim=32, depth=3)
    tree = net.init(jax.random.PRNGKey(0), jnp.zeros((8, 7), jnp.float32))
    backend, fell_back = make_graph_backend("jax", tree)
    assert not fell_back and backend.family == "graph"

    clouds = ["aws", "aws", "azure", None]
    adj, hops = topology_for_clouds(clouds)
    obs = build_graph_obs(clouds, np.array([0.10, 0.20], np.float32),
                          np.array([0.4, 0.6], np.float32), hops, adj,
                          affinity=None, pod_cpu=0.25, step_frac=0.5)
    assert obs.shape == (4, 7) and obs.dtype == np.float32
    assert obs[3, 2] == 0.5                       # unknown cloud: neutral id
    assert obs[3, 1] == pytest.approx(0.5)        # cross-cloud mean cpu
    np.testing.assert_array_equal(obs[:, 5], 0.25)
    action, logits = backend.decide_nodes(obs, adj)
    assert logits.shape == (4,) and 0 <= action < 4


def test_check_warm_nodes_served_refuses_unhonored_request(telemetry):
    """check_warm_nodes_served (run post-build in the CLI AND inside
    every pool worker): a --warm-nodes demand a greedy/cloud-family
    policy cannot honor refuses to boot instead of serving half-warmed;
    no demand, no refusal."""
    from rl_scheduler_tpu.scheduler.extender import check_warm_nodes_served

    policy = ExtenderPolicy(GreedyBackend(), telemetry)
    check_warm_nodes_served(policy, None)
    with pytest.raises(SystemExit, match="warm-nodes"):
        check_warm_nodes_served(policy, (8, 64))
