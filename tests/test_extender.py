"""Scheduler extender: backends, protocol handlers, HTTP server, latency."""

import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.models import ActorCritic
from rl_scheduler_tpu.scheduler.extender import (
    ExtenderPolicy,
    build_policy,
    make_server,
    node_cloud,
)
from rl_scheduler_tpu.scheduler.policy_backend import (
    GreedyBackend,
    JaxAOTBackend,
    NumpyMLPBackend,
    TorchMLPBackend,
    make_backend,
)
from rl_scheduler_tpu.scheduler.telemetry import RandomCpu, TableTelemetry

HIDDEN = (32, 32)


@pytest.fixture(scope="module")
def params_tree():
    net = ActorCritic(num_actions=env_core.NUM_ACTIONS, hidden=HIDDEN)
    return net.init(
        jax.random.PRNGKey(7), jnp.zeros((1, env_core.OBS_DIM), jnp.float32)
    )


@pytest.fixture()
def telemetry():
    return TableTelemetry.from_table(cpu_source=RandomCpu(seed=0))


def _node(name, cloud=None):
    labels = {"cloud": cloud} if cloud else {}
    return {"metadata": {"name": name, "labels": labels}}


# ---------------------------------------------------------------- backends


def test_backends_agree_on_decisions(params_tree):
    """numpy, torch, and jax AOT backends are the same function."""
    numpy_b = NumpyMLPBackend(params_tree)
    torch_b = TorchMLPBackend(params_tree)
    jax_b = JaxAOTBackend(params_tree, hidden=HIDDEN)
    rng = np.random.RandomState(0)
    for _ in range(20):
        obs = rng.uniform(0, 1, env_core.OBS_DIM).astype(np.float32)
        a_np, l_np = numpy_b.decide(obs)
        a_t, l_t = torch_b.decide(obs)
        a_j, l_j = jax_b.decide(obs)
        assert a_np == a_t == a_j
        np.testing.assert_allclose(l_np, l_t, rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(l_np, l_j, rtol=1e-4, atol=1e-5)


def test_greedy_backend_matches_reference_rule():
    b = GreedyBackend()
    # cheaper aws -> 0; cheaper azure -> 1; tie -> aws (obs[0] <= obs[1])
    assert b.decide(np.array([0.1, 0.9, 0, 0, 0, 0], np.float32))[0] == 0
    assert b.decide(np.array([0.9, 0.1, 0, 0, 0, 0], np.float32))[0] == 1
    assert b.decide(np.array([0.5, 0.5, 0, 0, 0, 0], np.float32))[0] == 0


def test_make_backend_falls_back_to_greedy_without_params():
    backend, fell_back = make_backend("jax", params_tree=None)
    assert isinstance(backend, GreedyBackend)
    assert fell_back


def test_make_backend_falls_back_on_garbage_params():
    backend, fell_back = make_backend("cpu", params_tree={"params": {"bogus": {}}})
    assert isinstance(backend, GreedyBackend)
    assert fell_back


# ---------------------------------------------------------------- protocol


def test_filter_keeps_only_chosen_cloud(telemetry, params_tree):
    policy = ExtenderPolicy(NumpyMLPBackend(params_tree), telemetry)
    nodes = [_node("n-aws", "aws"), _node("n-azure", "azure"), _node("mystery")]
    result = policy.filter({"nodes": {"items": nodes}, "pod": {}})
    kept_names = [n["metadata"]["name"] for n in result["nodes"]["items"]]
    # exactly one cloud filtered out; unknown-cloud node passes (fail-open)
    assert "mystery" in kept_names
    assert len(kept_names) == 2
    assert len(result["failedNodes"]) == 1
    assert result["error"] == ""


def test_filter_nodenames_variant(telemetry):
    policy = ExtenderPolicy(GreedyBackend(), telemetry)
    result = policy.filter({"nodenames": ["aws-worker", "azure-worker"], "pod": {}})
    assert len(result["nodenames"]) == 1
    assert len(result["failedNodes"]) == 1


def test_filter_fails_open_when_backend_raises(telemetry):
    class Exploding:
        name = "boom"

        def decide(self, obs):
            raise RuntimeError("kaboom")

    policy = ExtenderPolicy(Exploding(), telemetry)
    nodes = {"items": [_node("a", "aws"), _node("b", "azure")]}
    result = policy.filter({"nodes": nodes, "pod": {}})
    assert len(result["nodes"]["items"]) == 2  # nothing filtered
    # error must stay empty: kube-scheduler hard-fails the scheduling cycle
    # on a non-empty Error unless ignorable=true
    assert result["error"] == ""


def test_prioritize_scores_follow_policy_probs(telemetry, params_tree):
    policy = ExtenderPolicy(NumpyMLPBackend(params_tree), telemetry)
    nodes = [_node("n-aws", "aws"), _node("n-azure", "azure"), _node("mystery")]
    scores = policy.prioritize({"nodes": {"items": nodes}})
    by_host = {s["host"]: s["score"] for s in scores}
    assert set(by_host) == {"n-aws", "n-azure", "mystery"}
    assert all(0 <= s <= 100 for s in by_host.values())
    # probs sum to 1 -> cloud scores sum to ~100; unknown node gets midpoint
    assert by_host["n-aws"] + by_host["n-azure"] == pytest.approx(100, abs=1)
    assert by_host["mystery"] == 50


def test_node_cloud_label_beats_name():
    assert node_cloud(_node("azure-ish-name", "aws")) == "aws"
    assert node_cloud(_node("worker-azure")) == "azure"
    assert node_cloud("kind-aws-worker") == "aws"
    assert node_cloud(_node("plain")) is None
    # whole-token matching: names merely containing 'aws' are NOT classified
    assert node_cloud(_node("gateways-1")) is None
    assert node_cloud("k8s-gateways-worker") is None


def test_make_backend_unknown_name_raises():
    with pytest.raises(ValueError):
        make_backend("cuda")


def test_build_policy_survives_corrupt_checkpoint(tmp_path):
    run = tmp_path / "run"
    (run / "checkpoints" / "5").mkdir(parents=True)
    (run / "checkpoints" / "5" / "garbage").write_text("not a checkpoint")
    policy = build_policy("cpu", run=str(run))
    assert policy.backend.name == "greedy"


def test_stats_accumulate(telemetry):
    policy = ExtenderPolicy(GreedyBackend(), telemetry)
    for _ in range(10):
        policy.filter({"nodenames": ["aws-w", "azure-w"], "pod": {}})
    stats = policy.statistics()
    assert stats["latency"]["count"] == 10
    assert sum(stats["decisions"].values()) == 10
    assert stats["backend"] == "greedy"


def test_build_policy_greedy_without_checkpoint(tmp_path):
    policy = build_policy("jax", run_root=str(tmp_path / "empty"))
    assert policy.backend.name == "greedy"


# ---------------------------------------------------------------- HTTP


@pytest.fixture()
def server(telemetry, params_tree):
    policy = ExtenderPolicy(NumpyMLPBackend(params_tree), telemetry)
    srv = make_server(policy, host="127.0.0.1", port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield srv, policy
    srv.shutdown()


def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.load(resp)


def test_http_filter_prioritize_health_stats(server):
    srv, _ = server
    port = srv.server_address[1]
    # Go-style capitalized field names must be accepted
    args = {
        "Pod": {"metadata": {"name": "p"}},
        "Nodes": {"items": [_node("n-aws", "aws"), _node("n-azure", "azure")]},
    }
    filt = _post(port, "/filter", args)
    assert len(filt["nodes"]["items"]) == 1
    prio = _post(port, "/prioritize", args)
    assert len(prio) == 2
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
        assert json.load(r)["status"] == "ok"
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/stats", timeout=5) as r:
        assert json.load(r)["latency"]["count"] >= 2


def test_http_bad_json_is_400(server):
    srv, _ = server
    port = srv.server_address[1]
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/filter", data=b"{not json",
        headers={"Content-Type": "application/json"},
    )
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(req, timeout=5)
    assert exc_info.value.code == 400


def test_decision_latency_under_1ms_p50(server):
    """The serving target: <1 ms p50 per decision (SURVEY.md §6)."""
    srv, policy = server
    port = srv.server_address[1]
    args = {"nodenames": ["aws-w", "azure-w"], "pod": {}}
    for _ in range(200):
        _post(port, "/filter", args)
    lat = policy.statistics()["latency"]
    assert lat["count"] >= 200
    assert lat["p50_ms"] < 1.0, f"decision p50 {lat['p50_ms']}ms exceeds 1ms"


def test_async_placer_never_blocks_and_bounds_queue():
    """A hung kube API must not block filter responses or grow unbounded
    state: placements drain through one worker over a bounded queue."""
    import threading
    import time

    from rl_scheduler_tpu.scheduler.extender import AsyncPlacer

    release = threading.Event()
    placed = []

    class StuckPlacer:
        def place(self, cloud):
            release.wait(timeout=10)
            placed.append(cloud)

    ap = AsyncPlacer(StuckPlacer(), maxsize=4)
    t0 = time.perf_counter()
    for i in range(100):  # far more than maxsize while the worker is stuck
        ap.submit("aws" if i % 2 else "azure")
    assert time.perf_counter() - t0 < 1.0, "submit must never block"
    assert ap.dropped >= 100 - 4 - 1  # all but queue capacity (+in-flight) drop
    release.set()
    deadline = time.time() + 5
    while len(placed) < 4 and time.time() < deadline:
        time.sleep(0.01)
    assert placed, "worker must drain queued placements once unblocked"


# ------------------------------------------------------------ DQN serving


@pytest.fixture(scope="module")
def dqn_params_tree():
    from rl_scheduler_tpu.models import QNetwork

    net = QNetwork(num_actions=env_core.NUM_ACTIONS, hidden=HIDDEN)
    return net.init(
        jax.random.PRNGKey(9), jnp.zeros((1, env_core.OBS_DIM), jnp.float32)
    )


def test_dqn_backends_agree_on_decisions(dqn_params_tree):
    """All host backends serve the same greedy-Q function for a DQN tree."""
    numpy_b = NumpyMLPBackend(dqn_params_tree, algo="dqn")
    torch_b = TorchMLPBackend(dqn_params_tree, algo="dqn")
    jax_b = JaxAOTBackend(dqn_params_tree, hidden=HIDDEN, algo="dqn")
    rng = np.random.RandomState(3)
    for _ in range(20):
        obs = rng.uniform(0, 1, env_core.OBS_DIM).astype(np.float32)
        a_np, q_np = numpy_b.decide(obs)
        a_t, q_t = torch_b.decide(obs)
        a_j, q_j = jax_b.decide(obs)
        assert a_np == a_t == a_j
        np.testing.assert_allclose(q_np, q_t, rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(q_np, q_j, rtol=1e-4, atol=1e-5)


def test_ppo_tree_with_dqn_layout_falls_back(params_tree):
    """Mismatched algo layout (PPO tree read as DQN) must degrade to greedy,
    not crash the server."""
    backend, fell_back = make_backend("cpu", params_tree, algo="dqn")
    assert fell_back and backend.name == "greedy"


def test_make_backend_unknown_algo_raises(params_tree):
    with pytest.raises(ValueError, match="algo"):
        make_backend("cpu", params_tree, algo="sarsa")


def test_build_policy_serves_dqn_checkpoint(tmp_path):
    """End-to-end: the newest run being a DQN one serves its Q-network."""
    from rl_scheduler_tpu.agent import train_dqn as dqn_cli
    from rl_scheduler_tpu.scheduler.extender import build_policy

    run_dir = dqn_cli.main([
        "--env", "multi_cloud", "--preset", "config1", "--iterations", "4",
        "--run-root", str(tmp_path), "--run-name", "dqn_serve_test",
        "--checkpoint-every", "4", "--hidden", "32,32",
    ])
    policy = build_policy(backend="cpu", run=str(run_dir))
    assert policy.backend.name == "cpu"  # not the greedy fallback
    result = policy.filter({
        "pod": {"metadata": {"name": "p"}},
        "nodes": {"items": [_node("n1", "aws"), _node("n2", "azure")]},
    })
    assert len(result["nodes"]["items"]) == 1


def test_build_policy_rejects_wrong_env_checkpoint(tmp_path):
    """A newest run from a different env family (different obs dim) must
    degrade to greedy at startup, not fail-open on every request."""
    from rl_scheduler_tpu.agent import train_dqn as dqn_cli
    from rl_scheduler_tpu.scheduler.extender import build_policy

    dqn_cli.main([
        "--env", "single_cluster", "--preset", "config1", "--iterations", "4",
        "--run-root", str(tmp_path), "--run-name", "sc_run",
        "--checkpoint-every", "4", "--hidden", "16,16",
    ])
    policy = build_policy(backend="cpu", run_root=str(tmp_path))
    assert policy.backend.name == "greedy"


def test_extender_bench_tool(server):
    """The loadgen benchmark drives a live server and reports percentiles."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "extender_bench",
        Path(__file__).resolve().parents[1] / "loadgen" / "extender_bench.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    srv, _ = server
    port = srv.server_address[1]
    out = mod.main(["--port", str(port), "--requests", "40",
                    "--threads", "4", "--warmup", "5"])
    assert out["requests"] == 40
    assert out["client_p50_ms"] > 0 and out["server_p50_ms"] > 0
    assert out["backend"] == "cpu"


def test_load_aware_jax_sheds_overflow_decisions_agree(params_tree):
    """The serving 'jax' flag (LoadAwareJaxBackend): at low concurrency it
    runs the AOT dispatcher; past max_concurrent_jax it routes to the
    native/numpy forward — and every routed decision agrees with the
    reference forward (argmax level; logits match to ~1e-4, not bitwise),
    so shedding is invisible to the scheduler."""
    import threading

    from rl_scheduler_tpu.scheduler.policy_backend import (
        LoadAwareJaxBackend,
    )

    backend = LoadAwareJaxBackend(params_tree, hidden=HIDDEN,
                                  max_concurrent_jax=1)
    ref = NumpyMLPBackend(params_tree)
    rng = np.random.default_rng(5)
    obs_batch = rng.uniform(0, 1, size=(64, env_core.OBS_DIM)).astype(np.float32)

    # single-stream: all jax, nothing shed
    for obs in obs_batch[:8]:
        action, _ = backend.decide(obs)
        assert action == ref.decide(obs)[0]
    assert backend.shed_fraction == 0.0

    # 8 threads hammering max_concurrent_jax=1 MUST shed some requests,
    # and every decision still matches the reference forward.
    mismatches = []
    def worker(rows):
        for obs in rows:
            action, _ = backend.decide(obs)
            if action != ref.decide(obs)[0]:
                mismatches.append(obs)

    threads = [threading.Thread(target=worker, args=(obs_batch,))
               for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not mismatches
    assert backend.shed_fraction > 0.0
    assert backend.name == "jax"


def test_make_backend_jax_is_load_aware(params_tree):
    from rl_scheduler_tpu.scheduler.policy_backend import (
        LoadAwareJaxBackend,
    )

    backend, fell_back = make_backend("jax", params_tree, hidden=HIDDEN)
    assert isinstance(backend, LoadAwareJaxBackend) and not fell_back
