# Convenience entry points. The pytest gate (tests/test_graftlint.py) is
# the source of truth for lint; `make lint` is the same check, standalone.

PY ?= python

.PHONY: lint lint-json test tier1

lint:
	$(PY) -m tools.graftlint --check

lint-json:
	$(PY) -m tools.graftlint --check --json

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

tier1: test
